#!/usr/bin/env python
"""MNIST LeNet-5 training demo (reference: v1_api_demo/mnist/api_train.py —
the canonical v2-API walkthrough: layers -> trainer.SGD -> events).

Run: python demos/mnist/api_train.py [--passes N] [--batch-size B]
Uses cached real MNIST when present, else the labelled synthetic fallback.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle
from paddle_tpu import layer


def lenet5(img):
    """(reference: the api_train.py conv topology)"""
    conv1 = layer.img_conv(img, filter_size=5, num_filters=20,
                           num_channels=1, padding=0,
                           act=paddle.activation.Relu(), name="conv1")
    pool1 = layer.img_pool(conv1, pool_size=2, stride=2, name="pool1")
    conv2 = layer.img_conv(pool1, filter_size=5, num_filters=50, padding=0,
                           act=paddle.activation.Relu(), name="conv2")
    pool2 = layer.img_pool(conv2, pool_size=2, stride=2, name="pool2")
    return layer.fc(pool2, 10, act=paddle.activation.Softmax(), name="fc")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--checkpoint-dir", default=None)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    paddle.init(seed=42, platform=args.platform)
    img = paddle.layer.data("pixel", paddle.data_type.dense_vector(784))
    lbl = paddle.layer.data("label", paddle.data_type.integer_value(10))
    out = lenet5(img)
    cost = layer.classification_cost(out, lbl, name="cost")
    err = paddle.evaluator.classification_error(out, lbl, name="err")

    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params, extra_layers=[err],
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9,
            learning_rate_schedule="poly", learning_rate_args="0.001,0.75"))

    def handler(event):
        if isinstance(event, paddle.event.EndPass):
            result = trainer.test(
                reader=paddle.batch(paddle.dataset.mnist.test(), 256))
            print(f"pass complete: test cost {result.cost:.4f} "
                  f"{trainer.evaluators.result()}")

    trainer.train(
        reader=paddle.reader.decorator.shuffle(
            paddle.batch(paddle.dataset.mnist.train(), args.batch_size),
            buf_size=50),
        num_passes=args.passes, event_handler=handler,
        checkpoint_dir=args.checkpoint_dir)

    # inference on a few test images
    import numpy as np
    samples = [s for s, _ in zip(paddle.dataset.mnist.test()(), range(8))]
    probs = paddle.infer(output_layer=out, parameters=params,
                         input=[[s[0]] for s in samples])
    pred = np.argmax(np.asarray(probs), axis=-1)
    print("labels:", [s[1] for s in samples])
    print("preds: ", pred.tolist())


if __name__ == "__main__":
    main()
