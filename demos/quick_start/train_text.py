#!/usr/bin/env python
"""quick_start text classification — the sparse-SEQUENCE configs
(reference: v1_api_demo/quick_start/trainer_config.bow.py /
.emb.py / .cnn.py: sentence sentiment over per-timestep sparse word
vectors, the path that exercised sparse_binary_vector_sequence,
python/paddle/trainer/PyDataProvider2.py:202).

Three selectable pipelines over the imdb reader (synthetic-fallback
aware):
- ``bow``: sparse_binary_vector_sequence → shared fc (sparse weighted
  row-gather) → sequence sum-pool → softmax — the sparse showcase.
- ``emb``: integer_value_sequence → embedding → pool → softmax.
- ``cnn``: embedding → sequence_conv_pool (the .cnn.py topology).

Run: python demos/quick_start/train_text.py [--net bow|emb|cnn]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle
from paddle_tpu import layer, networks

VOCAB = 2000


def build(net):
    lbl = layer.data("label", paddle.data_type.integer_value(2))
    if net == "bow":
        # one sparse row per TIMESTEP (word n-hots) — the reference's
        # sparse-sequence data path through the feeder
        words = layer.data(
            "words", paddle.data_type.sparse_binary_vector_sequence(VOCAB))
        h = layer.fc(words, 64, act=paddle.activation.Relu(), name="qs_fc")
        pooled = layer.pool(h, pooling_type=paddle.pooling.Sum())
    elif net == "emb":
        words = layer.data(
            "words", paddle.data_type.integer_value_sequence(VOCAB))
        emb = layer.embedding(words, 64, name="qs_emb")
        pooled = layer.pool(emb, pooling_type=paddle.pooling.Avg())
    else:                                   # cnn
        words = layer.data(
            "words", paddle.data_type.integer_value_sequence(VOCAB))
        emb = layer.embedding(words, 64, name="qs_emb")
        pooled = networks.sequence_conv_pool(
            emb, context_len=3, hidden_size=64, name="qs_cnn")
    out = layer.fc(pooled, 2, act=paddle.activation.Softmax(), name="qs_out")
    return words, layer.classification_cost(out, lbl, name="qs_cost")


def to_sparse_seq(reader):
    """integer_value_sequence sample → per-timestep singleton index
    lists (each word is a 1-hot row; n-gram feeds would emit several
    indices per step)."""
    def gen():
        for words, label in reader():
            yield [[w] for w in words], label
    return gen


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--net", choices=("bow", "emb", "cnn"), default="bow")
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    paddle.init(seed=9, platform=args.platform)
    word_idx = {f"w{i}": i for i in range(VOCAB - 1)}
    word_idx["<unk>"] = VOCAB - 1
    reader = paddle.dataset.imdb.train(word_idx)
    if args.net == "bow":
        reader = to_sparse_seq(reader)
    _, cost = build(args.net)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=2e-3))
    losses = []
    trainer.train(
        reader=paddle.batch(paddle.reader.firstn(reader, 1024),
                            args.batch_size),
        num_passes=args.passes,
        event_handler=lambda e: losses.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    print(f"net={args.net}: first loss {losses[0]:.4f} -> "
          f"last {losses[-1]:.4f}")
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
