#!/usr/bin/env python
"""CTR wide&deep quick-start (reference: v1_api_demo/quick_start/
trainer_config.lr.py — the high-dimensional sparse logistic-regression
showcase that exercised the sparse-remote-update pserver path; here the
embedding shards ride in-graph collectives).

Run: python demos/quick_start/train_ctr.py [--passes N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle
from paddle_tpu.models import ctr


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--wide-dim", type=int, default=10000)
    ap.add_argument("--vocab", type=int, default=10000)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    paddle.init(seed=7, platform=args.platform)
    out, cost = ctr.ctr_wide_deep(args.wide_dim, args.vocab)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(learning_rate=1e-3))

    reader = ctr.synthetic_reader(args.wide_dim, args.vocab, n=2048)
    losses = []
    trainer.train(
        reader=paddle.batch(reader, args.batch_size),
        num_passes=args.passes,
        event_handler=lambda e: losses.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    print(f"first loss {losses[0]:.4f} -> last loss {losses[-1]:.4f}")


if __name__ == "__main__":
    main()
