#!/usr/bin/env python
"""VAE demo (reference: v1_api_demo/vae/vae_train.py — MLP VAE on MNIST
with reparameterised sampling and an ELBO objective).

Run: python demos/vae/vae_train.py [--batches N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import vae


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    paddle.init(seed=13, platform=args.platform)
    trainer = vae.VAETrainer(vae.VAEConfig(), jax.random.PRNGKey(0))
    reader = paddle.batch(paddle.dataset.mnist.train(), args.batch_size)
    key = jax.random.PRNGKey(1)
    i, first = 0, None
    for pass_id in range(100):
        for batch in reader():
            # mnist is [-1, 1]; bernoulli VAE wants [0, 1]
            x = (np.stack([b[0] for b in batch]).astype(np.float32)
                 + 1.0) / 2.0
            key, sub = jax.random.split(key)
            loss = trainer.train_batch(sub, x)
            first = first if first is not None else loss
            if i % 50 == 0:
                print(f"batch {i}: -ELBO {loss:.2f}")
            i += 1
            if i >= args.batches:
                print(f"-ELBO {first:.2f} -> {loss:.2f}")
                return


if __name__ == "__main__":
    main()
