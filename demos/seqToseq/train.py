#!/usr/bin/env python
"""Seq2seq with attention on WMT14 (reference: demo/seqToseq +
python/paddle/v2/dataset/wmt14.py consumers — encoder-decoder NMT with
the recurrent-group attention decoder).

Run: python demos/seqToseq/train.py [--passes N] [--dict-size V]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import paddle_tpu as paddle
from paddle_tpu.models import seq2seq


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--dict-size", type=int, default=1000)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    paddle.init(seed=17, platform=args.platform)
    cost = seq2seq.seq2seq_train(args.dict_size, args.dict_size)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Adam(
            learning_rate=5e-3, gradient_clipping_threshold=5.0))

    losses = []
    trainer.train(
        reader=paddle.batch(paddle.dataset.wmt14.train(args.dict_size),
                            args.batch_size),
        num_passes=args.passes,
        feeding={"source_language_word": 0, "target_language_word": 1,
                 "target_language_next_word": 2},
        event_handler=lambda e: losses.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    print(f"loss {losses[0]:.3f} -> {losses[-1]:.3f}")


if __name__ == "__main__":
    main()
