#!/usr/bin/env python
"""Traffic-speed prediction demo (reference:
v1_api_demo/traffic_prediction/trainer_config.py — 24 past terms of link
speeds -> 24 forecast horizons, one shared-weight classifier head per
horizon over 5 speed classes).

Run: python demos/traffic_prediction/train.py [--passes N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import layer

TERM_NUM = 24
FORECASTING_NUM = 24
SPEED_CLASSES = 5


def synthetic_traffic(n=2048, seed=0):
    """Sinusoidal daily pattern + noise, discretised into speed classes —
    learnable structure standing in for the sensor CSVs."""
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            phase = rng.rand() * 2 * np.pi
            t = np.arange(TERM_NUM + FORECASTING_NUM)
            speed = 2.0 + 2.0 * np.sin(2 * np.pi * t / 24 + phase) \
                + 0.3 * rng.randn(len(t))
            cls = np.clip(np.round(speed), 0, SPEED_CLASSES - 1)
            yield tuple([speed[:TERM_NUM].astype(np.float32)] +
                        [int(c) for c in cls[TERM_NUM:]])
    return reader


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    paddle.init(seed=23, platform=args.platform)
    encode = layer.data("link_encode",
                        paddle.data_type.dense_vector(TERM_NUM))
    hidden = layer.fc(encode, 16, act=paddle.activation.Relu(),
                      name="tp_hidden")
    costs = []
    feeding = {"link_encode": 0}
    for i in range(FORECASTING_NUM):
        lbl = layer.data(f"label_{i}",
                         paddle.data_type.integer_value(SPEED_CLASSES))
        feeding[f"label_{i}"] = i + 1
        # shared-weight heads across horizons (the reference's _link_vec.w)
        out = layer.fc(hidden, SPEED_CLASSES,
                       act=paddle.activation.Softmax(),
                       name=f"tp_out_{i}",
                       param_attr=layer.ParamAttr(name="tp_link_vec.w"))
        costs.append(layer.classification_cost(out, lbl,
                                               name=f"tp_cost_{i}"))
    total = layer.addto(costs, name="tp_cost")

    params = paddle.parameters.create(total)
    trainer = paddle.trainer.SGD(
        cost=total, parameters=params,
        update_equation=paddle.optimizer.RMSProp(learning_rate=1e-3))
    seen = []
    trainer.train(reader=paddle.batch(synthetic_traffic(), args.batch_size),
                  num_passes=args.passes, feeding=feeding,
                  event_handler=lambda e: seen.append(e.cost)
                  if isinstance(e, paddle.event.EndIteration) else None)
    print(f"summed 24-horizon cost {seen[0]:.2f} -> {seen[-1]:.2f}")


if __name__ == "__main__":
    main()
