"""A deterministic elastic training worker — the gang member script the
chaos tests (tests/test_elastic_chaos.py), the recovery benchmark
(benchmarks/elastic_bench.py), and docs/howto_elastic.md all run under
``runtime/supervisor.py``.

Each worker is a single-process JAX runtime over
``PADDLE_LOCAL_CPU_DEVICES`` virtual CPU devices that trains the SAME
deterministic stream on a ``data`` mesh of size PADDLE_NUM_PROCESSES —
the CPU simulation of one host in a data-parallel gang (jaxlib cannot
run cross-process CPU collectives: replicated identical compute stands
in for the all-reduce, which keeps every trajectory bit-deterministic
and therefore comparable across kill/restart/shrink scenarios).

The elastic contract is exercised for real: SGD.train heartbeats to
the supervisor, checkpoints through the fenced crash-consistent commit
protocol into a per-rank dir, resumes from the latest INTACT
checkpoint with the input pipeline's stream position (exact next
batch), and reshards the ZeRO layout when PADDLE_NUM_PROCESSES changed
across a restart (meta-driven reshard, io/checkpoint.py).

Env knobs (beyond the supervisor's PADDLE_* contract):
  ELASTIC_OUT        output dir (losses/params per rank+epoch; ckpts)
  ELASTIC_NB         batches per pass              (default 8)
  ELASTIC_BS         batch size                    (default 8)
  ELASTIC_HIDDEN     hidden width (default 16; the observability A/B
                     widens it so step wall is measurable, not noise)
  ELASTIC_ZERO       ZeRO stage for the data mesh  (default 1)
  ELASTIC_STEP_SLEEP extra seconds per step (lets the supervisor catch
                     a gang mid-run instead of racing it to the finish)
  PADDLE_TPU_CHECKPOINT_PERIOD  flag: batches between async saves
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# single-process virtual-device runtime (conftest.py technique); must
# happen before the backend initialises. No distributed.init(): the
# gang members are independent runtimes in the CPU simulation.
_NDEV = int(os.environ.get("PADDLE_LOCAL_CPU_DEVICES", "4"))
os.environ.setdefault("PADDLE_TPU_SEED", "42")
os.environ.setdefault("PADDLE_TPU_COMPUTE_DTYPE", "float32")
from paddle_tpu.utils.flags import set_xla_host_device_count  # noqa: E402

set_xla_host_device_count(_NDEV)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", _NDEV)
except AttributeError:
    pass

import numpy as np  # noqa: E402

import paddle_tpu as paddle  # noqa: E402
from paddle_tpu import layer, parallel  # noqa: E402
from paddle_tpu.core import place  # noqa: E402
from paddle_tpu.pipeline import Pipeline  # noqa: E402
from paddle_tpu.utils.rng import KeySource  # noqa: E402


def main():
    rank = int(os.environ.get("PADDLE_PROCESS_ID", "0"))
    nprocs = int(os.environ.get("PADDLE_NUM_PROCESSES", "1"))
    epoch = int(os.environ.get("PADDLE_ELASTIC_EPOCH", "0"))
    nb = int(os.environ.get("ELASTIC_NB", "8"))
    bs = int(os.environ.get("ELASTIC_BS", "8"))
    hidden = int(os.environ.get("ELASTIC_HIDDEN", "16"))
    zero = int(os.environ.get("ELASTIC_ZERO", "1"))
    sleep_s = float(os.environ.get("ELASTIC_STEP_SLEEP", "0"))
    out = os.environ.get("ELASTIC_OUT", ".")
    os.makedirs(out, exist_ok=True)
    ckdir = os.path.join(out, f"ckpt_rank{rank}")

    x = layer.data("ew_x", paddle.data_type.dense_vector(8))
    lbl = layer.data("ew_l", paddle.data_type.integer_value(2))
    h = layer.fc(x, hidden, act=paddle.activation.Relu(), name="ew_h")
    o = layer.fc(h, 2, act=paddle.activation.Softmax(), name="ew_o")
    cost = layer.classification_cost(o, lbl, name="ew_cost")
    params = paddle.parameters.create(cost, KeySource(5))
    mesh = place.make_mesh((nprocs,), (place.AXIS_DATA,))
    tr = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(learning_rate=0.1),
        parallel=parallel.data_parallel(mesh, zero=zero))

    def reader():
        # batch b is a pure function of b: every pass, every rank, and
        # every incarnation sees the identical stream — resume
        # correctness shows up as exact trajectory equality
        for b in range(nb):
            rs = np.random.RandomState(1000 + b)
            for _ in range(bs):
                y = int(rs.randint(2))
                yield ((rs.randn(8) + 2.0 * y).astype(np.float32), y)

    pipe = Pipeline(reader, batch_size=bs, prefetch=2, track_state=True)

    losses = []

    def handler(e):
        if isinstance(e, paddle.event.EndIteration):
            losses.append({"step": tr._step - 1, "loss": float(e.cost),
                           "wall_s": float(e.wall_time_s or 0.0)})
            if sleep_s:
                import time
                time.sleep(sleep_s)

    try:
        tr.train(reader=pipe, num_passes=1, event_handler=handler,
                 checkpoint_dir=ckdir)
    finally:
        pipe.close()

    with open(os.path.join(out, f"losses_rank{rank}_epoch{epoch}.jsonl"),
              "w") as f:
        for rec in losses:
            f.write(json.dumps(rec) + "\n")
    from paddle_tpu.io.checkpoint import _flatten
    np.savez(os.path.join(out, f"final_rank{rank}_epoch{epoch}.npz"),
             **_flatten(tr.parameters.values))
    with open(os.path.join(out, f"done_rank{rank}_epoch{epoch}.json"),
              "w") as f:
        json.dump({"step": tr._step, "nprocs": nprocs}, f)
    print(f"elastic worker rank {rank} epoch {epoch}: done at step "
          f"{tr._step}", flush=True)


if __name__ == "__main__":
    main()
