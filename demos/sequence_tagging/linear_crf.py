#!/usr/bin/env python
"""Sequence tagging with a linear-chain CRF (reference:
v1_api_demo/sequence_tagging/linear_crf.py — CoNLL-style SRL/NER tagging
with crf_layer cost and crf_decoding at test time).

Run: python demos/sequence_tagging/linear_crf.py [--passes N]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import layer


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--vocab", type=int, default=500)
    ap.add_argument("--tags", type=int, default=7)
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    paddle.init(seed=11, platform=args.platform)
    words = layer.data("words", paddle.data_type.integer_value_sequence(
        args.vocab))
    tags = layer.data("tags", paddle.data_type.integer_value_sequence(
        args.tags))
    emb = layer.embedding(words, 64, name="crf_emb")
    feat = layer.fc(emb, args.tags, act=None, name="crf_feat")
    crf = layer.crf_layer(feat, tags, size=args.tags, name="crf_cost")
    # decoding shares the training CRF's transition matrix by name
    decode = layer.crf_decoding_layer(
        feat, size=args.tags, name="crf_decode",
        param_attr=layer.ParamAttr(name="crf_cost.w"))
    chunk = paddle.evaluator.chunk(decode, tags, num_chunk_types=3,
                                   chunk_scheme="IOB", name="chunk_f1")

    params = paddle.parameters.create(crf)
    trainer = paddle.trainer.SGD(
        cost=crf, parameters=params, extra_layers=[decode, chunk],
        update_equation=paddle.optimizer.Adam(learning_rate=5e-3))

    reader = paddle.dataset.synthetic.sequence_tagging(
        1024, args.vocab, args.tags, seed=5)
    losses = []
    trainer.train(
        reader=paddle.batch(reader, args.batch_size),
        num_passes=args.passes,
        event_handler=lambda e: losses.append(e.cost)
        if isinstance(e, paddle.event.EndIteration) else None)
    print(f"first loss {losses[0]:.3f} -> last {losses[-1]:.3f}  "
          f"{trainer.evaluators.result()}")


if __name__ == "__main__":
    main()
