#!/usr/bin/env python
"""Model-zoo workflows: feature extraction at a named layer + parameter
dump (reference: v1_api_demo/model_zoo/resnet/classify.py extracts
activations of a chosen layer from a trained model;
model_zoo/embedding/extract_para.py dumps an embedding matrix to text).

Loads the checked-in PRETRAINED zoo artifact (demos/model_zoo/
pretrained/resnet_cifar8.tar.gz, held-out accuracy recorded in
PRETRAINED.md — produced by train_pretrained.py; the reference shipped
downloadable trained models the same way), then: (1) re-saves/reloads
through the tar round-trip, (2) runs inference pruned to an
INTERMEDIATE layer (feature extraction — any layer's output is
addressable by name), (3) dumps a parameter matrix to a text file in
the extract_para format (rows of space-separated floats).
``--retrain`` ignores the artifact and trains from scratch instead.

Run: python demos/model_zoo/extract.py [--retrain] [--out-dir DIR]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np

import paddle_tpu as paddle
from paddle_tpu import layer
from paddle_tpu.models import resnet


def build(recipe=False):
    """recipe: resnet fused_bn mode (False dense; "1"->True streaming-BN;
    "int8"/"full"/"q8"/"defer"/"q8sr") — parameter names interchange
    across modes, so artifacts stay loadable either way."""
    if recipe == "1":
        recipe = True
    img = layer.data("image", paddle.data_type.dense_vector(3 * 32 * 32))
    lbl = layer.data("label", paddle.data_type.integer_value(10))
    out = resnet.resnet_cifar10(img, depth=8, class_num=10,
                                fused_bn=recipe)
    cost = layer.classification_cost(out, lbl, name="cost")
    return img, out, cost


PRETRAINED = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "pretrained", "resnet_cifar8.tar.gz")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--retrain", action="store_true",
                    help="train from scratch instead of loading the "
                         "checked-in pretrained artifact")
    ap.add_argument("--passes", type=int, default=1)
    ap.add_argument("--batches", type=int, default=4)
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_model_zoo")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()
    os.makedirs(args.out_dir, exist_ok=True)

    paddle.init(seed=5, platform=args.platform)
    img, out, cost = build()
    if not args.retrain and os.path.exists(PRETRAINED):
        import gzip
        import io
        with gzip.open(PRETRAINED, "rb") as f:
            params = paddle.parameters.Parameters.from_tar(
                io.BytesIO(f.read()))
        print(f"loaded pretrained zoo artifact {PRETRAINED}")
    else:
        params = paddle.parameters.create(cost)
        trainer = paddle.trainer.SGD(
            cost=cost, parameters=params,
            update_equation=paddle.optimizer.Momentum(learning_rate=0.01,
                                                      momentum=0.9))
        reader = paddle.reader.firstn(paddle.dataset.cifar.train10(),
                                      32 * args.batches)
        trainer.train(reader=paddle.batch(reader, 32),
                      num_passes=args.passes)
        params = trainer.parameters

    model_path = os.path.join(args.out_dir, "resnet_cifar.tar")
    with open(model_path, "wb") as f:
        params.to_tar(f)
    print(f"saved {model_path}")

    # (1) reload into a fresh Parameters object
    with open(model_path, "rb") as f:
        loaded = paddle.parameters.Parameters.from_tar(f)

    # (2) feature extraction: prune the program to the global-average-pool
    # layer (the penultimate feature vector, as classify.py's
    # --job=extract does for resnet features)
    from paddle_tpu.topology import Topology
    gap = Topology(cost).find("rc_gap")
    feats = paddle.infer(
        output_layer=gap,
        parameters=loaded,
        input=[(np.random.RandomState(0).rand(3 * 32 * 32)
                .astype(np.float32),)],
        feeding={"image": 0})
    print(f"extracted features: shape {np.asarray(feats).shape}")

    # (3) dump a parameter matrix as text (extract_para.py format)
    wname = sorted(loaded.names())[0]
    mat = loaded[wname]
    txt_path = os.path.join(args.out_dir, f"{wname.replace('/', '_')}.txt")
    with open(txt_path, "w") as f:
        for row in mat.reshape(mat.shape[0], -1):
            f.write(" ".join(f"{x:.6f}" for x in row) + "\n")
    print(f"dumped {wname} {mat.shape} -> {txt_path}")


if __name__ == "__main__":
    main()
