#!/usr/bin/env python
"""Produce the checked-in model-zoo artifact (reference slot:
v1_api_demo/model_zoo/resnet/ ships downloadable TRAINED models; this
repo has no network, so the zoo artifact is trained here on the
deterministic synthetic-CIFAR world and committed).

Trains the demo ResNet-8 on paddle.dataset.cifar.train10 (the labelled
synthetic fallback — same distribution every run), evaluates held-out
accuracy on test10, and writes demos/model_zoo/pretrained/
resnet_cifar8.tar.gz plus a provenance note. extract.py loads this
artifact by default, so the extract/infer demo runs against a genuinely
trained model.

Run: python demos/model_zoo/train_pretrained.py [--passes N]
"""

import argparse
import gzip
import io
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import paddle_tpu as paddle


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--passes", type=int, default=6)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--recipe", default=False,
                    help="fused_bn recipe: 1/int8/full/q8/defer/q8sr "
                    "(default dense)")
    args = ap.parse_args()
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                           "pretrained")
    os.makedirs(out_dir, exist_ok=True)

    paddle.init(seed=5, platform=args.platform)
    from extract import build                   # same topology as the demo
    img, out, cost = build(recipe=args.recipe)
    params = paddle.parameters.create(cost)
    trainer = paddle.trainer.SGD(
        cost=cost, parameters=params,
        update_equation=paddle.optimizer.Momentum(
            learning_rate=0.05, momentum=0.9,
            learning_rate_schedule="discexp", learning_rate_args="0.5,400"))
    trainer.train(
        reader=paddle.batch(
            paddle.reader.shuffle(paddle.dataset.cifar.train10(),
                                  buf_size=2048, seed=7), 64),
        num_passes=args.passes)

    # held-out evaluation: the artifact must beat chance by a wide margin
    test = list(paddle.dataset.cifar.test10()())
    xs = np.asarray([t[0] for t in test], np.float32)
    ys = np.asarray([t[1] for t in test], np.int32)
    probs = paddle.infer(output_layer=out, parameters=trainer.parameters,
                         input=[(x,) for x in xs], feeding={"image": 0})
    acc = float((np.asarray(probs).argmax(-1) == ys).mean())
    print(f"held-out accuracy: {acc:.3f} (chance 0.100)")
    assert acc > 0.5, f"artifact not trained enough: acc {acc}"

    buf = io.BytesIO()
    trainer.parameters.to_tar(buf)
    path = os.path.join(out_dir, "resnet_cifar8.tar.gz")
    with gzip.open(path, "wb", compresslevel=9) as f:
        f.write(buf.getvalue())
    with open(os.path.join(out_dir, "PRETRAINED.md"), "w") as f:
        f.write(
            "# Model-zoo artifact: resnet_cifar8.tar.gz\n\n"
            f"ResNet-8 (cifar variant), trained by train_pretrained.py on\n"
            f"the deterministic synthetic-CIFAR world "
            f"(dataset/cifar.py train10 fallback,\n"
            f"seed-stable across machines), {args.passes} passes.\n\n"
            f"Held-out accuracy on test10: **{acc:.3f}** "
            f"(chance 0.100).\n\n"
            "Loaded by default in extract.py — the feature-extraction/\n"
            "parameter-dump demo runs against a genuinely trained model\n"
            "(reference slot: v1_api_demo/model_zoo/resnet pretrained "
            "weights).\n")
    print(f"wrote {path} ({os.path.getsize(path)} bytes)")


if __name__ == "__main__":
    main()
