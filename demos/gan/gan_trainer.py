#!/usr/bin/env python
"""GAN demo (reference: v1_api_demo/gan/gan_trainer.py — alternating
generator/discriminator training on uniform data / MNIST).

Run: python demos/gan/gan_trainer.py [--batches N] [--conv]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import jax
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import gan


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--conv", action="store_true",
                    help="DCGAN-style conv G/D (28x28 images)")
    ap.add_argument("--platform", default=None,
                    help="force a JAX platform (e.g. cpu)")
    args = ap.parse_args()

    paddle.init(seed=99, platform=args.platform)
    cfg = gan.GANConfig(conv=args.conv)
    trainer = gan.GANTrainer(cfg, jax.random.PRNGKey(0))

    reader = paddle.batch(paddle.dataset.mnist.train(), args.batch_size)
    key = jax.random.PRNGKey(1)
    i = 0
    for pass_id in range(100):
        for batch in reader():
            real = np.stack([b[0] for b in batch]).astype(np.float32)
            key, sub = jax.random.split(key)
            d_loss, g_loss = trainer.train_batch(sub, real)
            if i % 50 == 0:
                print(f"batch {i}: d_loss {d_loss:.4f} g_loss {g_loss:.4f}")
            i += 1
            if i >= args.batches:
                samples = trainer.sample(jax.random.PRNGKey(2), 4)
                print("sample stats: mean %.3f std %.3f" %
                      (float(np.mean(samples)), float(np.std(samples))))
                return


if __name__ == "__main__":
    main()
