#!/usr/bin/env python
"""Train a tiny character LM and generate with greedy / sampling / beam
search over the KV cache (reference workflow slot: seqToseq generation +
trainer/tests/test_recurrent_machine_generation.cpp — the transformer
flagship's serving loop).

Run: python demos/text_generation/generate.py [--steps N] [--platform cpu]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--platform", default=None)
    args = ap.parse_args()

    import paddle_tpu as paddle
    paddle.init(seed=3, platform=args.platform)
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import transformer as tfm

    # toy corpus: repeated pangram — enough structure for greedy decode
    # to reproduce it after a few hundred steps
    text = "the quick brown fox jumps over the lazy dog. " * 40
    chars = sorted(set(text))
    stoi = {c: i for i, c in enumerate(chars)}
    data = np.array([stoi[c] for c in text], np.int32)

    cfg = tfm.TransformerConfig(vocab=len(chars), d_model=64, n_layers=2,
                                n_heads=2, d_ff=128, max_len=128,
                                dtype=jnp.float32)
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    adam = paddle.optimizer.Adam(learning_rate=3e-3)
    opt = adam.tree_init_state(params)

    T, B = 64, 8
    rng = np.random.RandomState(0)

    @jax.jit
    def step(p, o, toks, tgts, i):
        loss, g = jax.value_and_grad(tfm.lm_loss)(p, toks, tgts, cfg)
        p, o = adam.tree_update(i, g, p, o)
        return loss, p, o

    for i in range(args.steps):
        starts = rng.randint(0, len(data) - T - 1, B)
        toks = jnp.asarray(np.stack([data[s:s + T] for s in starts]))
        tgts = jnp.asarray(np.stack([data[s + 1:s + T + 1] for s in starts]))
        loss, params, opt = step(params, opt, toks, tgts,
                                 jnp.asarray(i, jnp.int32))
        if i % 50 == 0:
            print(f"step {i} loss {float(loss):.3f}")

    prompt_txt = "the quick "
    prompt = jnp.asarray([[stoi[c] for c in prompt_txt]], jnp.int32)

    def decode(ids):
        return "".join(chars[int(i)] for i in np.asarray(ids))

    greedy = tfm.generate(params, prompt, cfg, max_new=40)
    print("greedy :", repr(decode(greedy[0])))
    sampled = tfm.generate(params, prompt, cfg, max_new=40, temperature=0.8,
                           key=jax.random.PRNGKey(7))
    print("sampled:", repr(decode(sampled[0])))
    beams, scores = tfm.beam_search(params, prompt, cfg, max_new=40,
                                    beam_size=3)
    for j in range(3):
        print(f"beam[{j}] ({float(scores[0, j]):.2f}):",
              repr(decode(beams[0, j])))


if __name__ == "__main__":
    main()
