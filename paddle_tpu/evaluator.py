"""Evaluators — training/test metrics.

Reference: paddle/gserver/evaluators/Evaluator.h:42 hierarchy (classification
error, precision/recall, AUC, chunk-F1, CTC error, ...) wrapped by
python/paddle/v2/evaluator.py. Design here: an evaluator is a LayerOutput
emitting a small vector of *accumulables* per batch (device-side, inside the
jitted step), plus a host-side finalize() that turns summed accumulables into
the metric — so metric math rides the same traced program and only a few
scalars cross the host boundary each batch.
"""

from typing import Optional

import jax
import jax.numpy as jnp

from paddle_tpu.topology import LayerOutput, Value, auto_name


class MetricAccumulator:
    """Host-side accumulator over batch accumulable vectors."""

    def __init__(self, name, finalize_fn, width):
        self.name = name
        self.finalize_fn = finalize_fn
        self.width = width
        self.total = None

    def reset(self):
        self.total = None

    def add(self, vec):
        import numpy as np
        vec = np.asarray(vec, np.float64)
        self.total = vec if self.total is None else self.total + vec

    def value(self):
        if self.total is None:
            return float("nan")
        return self.finalize_fn(self.total)


def _evaluator_layer(name, etype, inputs, accum_fn, finalize_fn, width):
    def fwd(params, parents, ctx):
        return Value(accum_fn(params, parents, ctx))
    lo = LayerOutput(name, etype, inputs, fwd, [], size=width)
    lo.metric_finalize = finalize_fn
    lo.metric_width = width
    return lo


def classification_error(input, label, name: Optional[str] = None, top_k=1):
    """Error rate (reference: ClassificationErrorEvaluator, Evaluator.cpp).
    Accumulables: [#wrong, #examples]. Sequence inputs count per-token."""
    name = name or auto_name("classification_error_evaluator")

    def accum(params, parents, ctx):
        pv, lv = parents
        pred, lab = pv.array, lv.array
        if pv.is_sequence:
            mask = (jnp.arange(pred.shape[1])[None, :] <
                    pv.lengths[:, None]).astype(jnp.float32)
            lab2 = lab if lab.ndim == 2 else lab.reshape(lab.shape[0], -1)
            wrong = (jnp.argmax(pred, -1) != lab2).astype(jnp.float32) * mask
            return jnp.stack([wrong.sum(), mask.sum()])
        lab1 = lab.reshape(-1)
        if top_k == 1:
            wrong = (jnp.argmax(pred, -1) != lab1).astype(jnp.float32)
        else:
            topi = jnp.argsort(-pred, axis=-1)[:, :top_k]
            wrong = 1.0 - jnp.any(topi == lab1[:, None], axis=-1
                                  ).astype(jnp.float32)
        return jnp.stack([wrong.sum(), jnp.full((), wrong.shape[0],
                                                jnp.float32)])

    return _evaluator_layer(name, "classification_error", [input, label],
                            accum, lambda t: t[0] / max(t[1], 1), 2)


def precision_recall(input, label, name: Optional[str] = None,
                     positive_label=1):
    """Binary precision/recall/F1 (reference: PrecisionRecallEvaluator).
    Accumulables: [tp, fp, fn]."""
    name = name or auto_name("precision_recall_evaluator")

    def accum(params, parents, ctx):
        pred = jnp.argmax(parents[0].array, -1)
        lab = parents[1].array.reshape(-1)
        pos = pred == positive_label
        truth = lab == positive_label
        tp = jnp.sum(pos & truth).astype(jnp.float32)
        fp = jnp.sum(pos & ~truth).astype(jnp.float32)
        fn = jnp.sum(~pos & truth).astype(jnp.float32)
        return jnp.stack([tp, fp, fn])

    def fin(t):
        tp, fp, fn = t
        p = tp / max(tp + fp, 1e-12)
        r = tp / max(tp + fn, 1e-12)
        return {"precision": p, "recall": r,
                "f1": 2 * p * r / max(p + r, 1e-12)}

    return _evaluator_layer(name, "precision_recall", [input, label],
                            accum, fin, 3)


def auc(input, label, name: Optional[str] = None, num_thresholds=200):
    """Binned AUC (reference: AucEvaluator — bucketed ROC like the original;
    operators/auc_op.cc). Accumulables: [pos_hist..., neg_hist...]."""
    name = name or auto_name("auc_evaluator")

    def accum(params, parents, ctx):
        probs = parents[0].array
        # positive-class probability: column 1 of softmax output, or the
        # single sigmoid output
        p = probs[:, 1] if probs.shape[-1] >= 2 else probs[:, 0]
        lab = parents[1].array.reshape(-1).astype(jnp.float32)
        bins = jnp.clip((p * num_thresholds).astype(jnp.int32), 0,
                        num_thresholds - 1)
        pos = jnp.zeros(num_thresholds).at[bins].add(lab)
        neg = jnp.zeros(num_thresholds).at[bins].add(1.0 - lab)
        return jnp.concatenate([pos, neg])

    def fin(t):
        import numpy as np
        pos, neg = t[:num_thresholds], t[num_thresholds:]
        # sweep thresholds high->low accumulating TPR/FPR, trapezoid rule
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        tot_p, tot_n = max(tp[-1], 1e-12), max(fp[-1], 1e-12)
        tpr = np.concatenate([[0.0], tp / tot_p])
        fpr = np.concatenate([[0.0], fp / tot_n])
        return float(np.trapezoid(tpr, fpr))

    return _evaluator_layer(name, "auc", [input, label], accum, fin,
                            2 * num_thresholds)


def sum_cost(input, name: Optional[str] = None):
    """(reference: SumCostEvaluator) Accumulables: [sum, count]."""
    name = name or auto_name("sum_evaluator")

    def accum(params, parents, ctx):
        v = parents[0].array.astype(jnp.float32)
        return jnp.stack([v.sum(), jnp.full((), v.shape[0], jnp.float32)])

    return _evaluator_layer(name, "sum_cost", [input], accum,
                            lambda t: t[0] / max(t[1], 1), 2)


def positive_negative_pair(input, label, query_id,
                           name: Optional[str] = None, weight=None):
    """Pos/neg pair ordering ratio for ranking (reference: PnpairEvaluator,
    Evaluator.cpp:932-960 — within each query, pairs with differing labels
    count pos if the higher-labelled sample scores strictly higher, neg if
    strictly lower, spe on score ties; pair weight = mean of sample
    weights). Accumulables: [pos, neg, spe]. Pairs are counted within each
    minibatch, so keep a query's samples in one batch (the reference
    accumulated the whole pass on host — an O(N^2) host sort; in-graph
    batch-local counting is the TPU-friendly form)."""
    name = name or auto_name("pnpair_evaluator")
    inputs = [input, label, query_id] + ([weight] if weight else [])

    def accum(params, parents, ctx):
        score = parents[0].array.reshape(-1).astype(jnp.float32)
        lab = parents[1].array.reshape(-1).astype(jnp.int32)
        qid = parents[2].array.reshape(-1).astype(jnp.int32)
        w = (parents[3].array.reshape(-1).astype(jnp.float32)
             if len(parents) > 3 else jnp.ones_like(score))
        same_q = qid[:, None] == qid[None, :]
        diff_lab = lab[:, None] != lab[None, :]
        upper = (jnp.arange(score.shape[0])[:, None] <
                 jnp.arange(score.shape[0])[None, :])
        pair_w = (w[:, None] + w[None, :]) * 0.5
        consider = same_q & diff_lab & upper
        hi = (score[:, None] > score[None, :]) & (lab[:, None] > lab[None, :])
        lo = (score[:, None] < score[None, :]) & (lab[:, None] < lab[None, :])
        correct = hi | lo
        wrong = ((score[:, None] > score[None, :]) &
                 (lab[:, None] < lab[None, :])) | \
                ((score[:, None] < score[None, :]) &
                 (lab[:, None] > lab[None, :]))
        tie = ~(correct | wrong)
        pos = jnp.sum(jnp.where(consider & correct, pair_w, 0.0))
        neg = jnp.sum(jnp.where(consider & wrong, pair_w, 0.0))
        spe = jnp.sum(jnp.where(consider & tie, pair_w, 0.0))
        return jnp.stack([pos, neg, spe])

    def fin(t):
        pos, neg, spe = t
        return {"pos": pos, "neg": neg, "spe": spe,
                "ratio": pos / max(neg, 1e-12)}

    return _evaluator_layer(name, "pnpair", inputs, accum, fin, 3)


class EvaluatorSet:
    """Host-side bundle the trainer drives (reset per pass / per test)."""

    def __init__(self, layers):
        self.layers = [l for l in layers if hasattr(l, "metric_finalize")]
        self.accs = {l.name: MetricAccumulator(l.name, l.metric_finalize,
                                               l.metric_width)
                     for l in self.layers}

    def reset(self):
        for a in self.accs.values():
            a.reset()

    def add_batch(self, outputs):
        for l in self.layers:
            if l.name in outputs:
                self.accs[l.name].add(outputs[l.name].array)

    def result(self):
        return {name: acc.value() for name, acc in self.accs.items()}


def chunk(input, label, num_chunk_types: int, chunk_scheme: str = "IOB",
          name: Optional[str] = None):
    """Chunking F1 (NER-style) over predicted vs gold tag sequences
    (reference: ChunkEvaluator.cpp — IOB/IOE/IOBES/plain schemes; tag
    layout tag = chunk_type * num_tag_types + tag_type, O = the last id).

    Accumulables: [#correct_chunks, #pred_chunks, #label_chunks].
    TPU design: chunk extraction and matching are vectorized boundary
    masks + a segmented all-equal scan — no host-side segment lists.
    """
    name = name or auto_name("chunk_evaluator")
    schemes = {"IOB": 2, "IOE": 2, "IOBES": 4, "plain": 1}
    if chunk_scheme not in schemes:
        raise ValueError(f"unknown chunk scheme {chunk_scheme}")
    num_tag_types = schemes[chunk_scheme]
    other_tag = num_chunk_types * num_tag_types      # the "O" id

    def boundaries(tags):
        """begin/end/type masks [B, T] for one scheme."""
        inside = tags < other_tag
        ttype = jnp.where(inside, tags % num_tag_types, -1)
        ctype = jnp.where(inside, tags // num_tag_types, -1)
        prev_in = jnp.pad(inside, ((0, 0), (1, 0)))[:, :-1]
        prev_ct = jnp.pad(ctype, ((0, 0), (1, 0)),
                          constant_values=-1)[:, :-1]
        nxt_in = jnp.pad(inside, ((0, 0), (0, 1)))[:, 1:]
        nxt_ct = jnp.pad(ctype, ((0, 0), (0, 1)),
                         constant_values=-1)[:, 1:]
        nxt_tt = jnp.pad(ttype, ((0, 0), (0, 1)),
                         constant_values=-1)[:, 1:]
        if chunk_scheme == "IOB":          # B=0, I=1
            begin = inside & ((ttype == 0) | ~prev_in | (prev_ct != ctype))
            end = inside & (~nxt_in | (nxt_ct != ctype) | (nxt_tt == 0))
        elif chunk_scheme == "IOE":        # I=0, E=1
            prev_tt = jnp.pad(ttype, ((0, 0), (1, 0)),
                              constant_values=-1)[:, :-1]
            begin = inside & (~prev_in | (prev_ct != ctype) |
                              (prev_tt == 1))
            end = inside & ((ttype == 1) | ~nxt_in | (nxt_ct != ctype))
        elif chunk_scheme == "IOBES":      # B=0, I=1, E=2, S=3
            prev_tt = jnp.pad(ttype, ((0, 0), (1, 0)),
                              constant_values=-1)[:, :-1]
            begin = inside & ((ttype == 0) | (ttype == 3) | ~prev_in |
                              (prev_ct != ctype) | (prev_tt == 2) |
                              (prev_tt == 3))
            end = inside & ((ttype == 2) | (ttype == 3) | ~nxt_in |
                            (nxt_ct != ctype) | (nxt_tt == 0) |
                            (nxt_tt == 3))
        else:                              # plain: every tag its own chunk run
            begin = inside & (~prev_in | (prev_ct != ctype))
            end = inside & (~nxt_in | (nxt_ct != ctype))
        return begin, end, ctype

    def accum(params, parents, ctx):
        pv, lv = parents
        pred = pv.array
        if pred.ndim == 3:                 # scores -> tag ids
            pred = jnp.argmax(pred, axis=-1)
        pred = pred.astype(jnp.int32)
        lab = lv.array.astype(jnp.int32)
        if lab.ndim == 3:
            lab = lab[..., 0]
        T = pred.shape[1]
        valid = jnp.arange(T)[None, :] < pv.lengths[:, None]
        pred = jnp.where(valid, pred, other_tag)
        lab = jnp.where(valid, lab, other_tag)
        pb, pe, pc = boundaries(pred)
        lb, le, lc = boundaries(lab)
        align = (pb == lb) & (pe == le) & (pc == lc)

        # segmented "all aligned since the label-chunk start" scan
        def scan_t(run, xs):
            a_t, b_t = xs
            run = a_t & jnp.where(b_t, True, run)
            return run, run

        _, run_ok = jax.lax.scan(
            scan_t, jnp.zeros(pred.shape[0], bool),
            (align.swapaxes(0, 1), lb.swapaxes(0, 1)))
        run_ok = run_ok.swapaxes(0, 1)
        correct = le & pe & run_ok
        return jnp.stack([jnp.sum(correct).astype(jnp.float32),
                          jnp.sum(pb).astype(jnp.float32),
                          jnp.sum(lb).astype(jnp.float32)])

    def fin(t):
        c, p, l = t
        prec = c / max(p, 1e-12)
        rec = c / max(l, 1e-12)
        return {"precision": prec, "recall": rec,
                "f1": 2 * prec * rec / max(prec + rec, 1e-12)}

    return _evaluator_layer(name, "chunk", [input, label], accum, fin, 3)


def ctc_error(input, label, blank: Optional[int] = None,
              name: Optional[str] = None):
    """Sequence error: edit distance between the greedy CTC decode of
    ``input`` and the label, normalized by label length (reference:
    CTCErrorEvaluator.cpp). Accumulables: [total_edit, total_label_len]."""
    from paddle_tpu.ops import ctc as ops_ctc
    name = name or auto_name("ctc_error_evaluator")

    def edit_distance(a, a_len, b, b_len):
        """Levenshtein via scan over rows of the DP table.
        a [La], b [Lb] padded int arrays."""
        La, Lb = a.shape[0], b.shape[0]
        row0 = jnp.arange(Lb + 1, dtype=jnp.float32)

        def step(row, xs):
            ai, i = xs

            def inner(left, xs2):
                bj, up, diag = xs2
                cost = jnp.where(ai == bj, 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(left + 1, up + 1), diag + cost)
                return val, val

            _, vals = jax.lax.scan(inner, i + 1.0, (b, row[1:], row[:-1]))
            new_row = jnp.concatenate([jnp.array([i + 1.0]), vals])
            # beyond a_len keep previous row (no-op)
            return jnp.where(i < a_len, new_row, row), None

        final, _ = jax.lax.scan(step, row0,
                                (a, jnp.arange(La, dtype=jnp.float32)))
        return final[b_len.astype(jnp.int32)]

    def accum(params, parents, ctx):
        pv, lv = parents
        n_cls = pv.array.shape[-1]
        blk = (n_cls - 1) if blank is None else blank
        logp = jnp.log(jnp.maximum(pv.array.astype(jnp.float32), 1e-30)) \
            if input.activation == "softmax" else \
            jax.nn.log_softmax(pv.array.astype(jnp.float32), -1)
        dec, dec_len = ops_ctc.ctc_greedy_decode(logp, pv.lengths, blank=blk)
        lab = lv.array.astype(jnp.int32)
        if lab.ndim == 3:
            lab = lab[..., 0]
        dists = jax.vmap(edit_distance)(dec, dec_len, lab, lv.lengths)
        return jnp.stack([jnp.sum(dists),
                          jnp.sum(lv.lengths).astype(jnp.float32)])

    return _evaluator_layer(name, "ctc_error", [input, label], accum,
                            lambda t: t[0] / max(t[1], 1e-12), 2)


def detection_map(detections, label, num_classes: int,
                  overlap_threshold: float = 0.5, background_id: int = 0,
                  score_bins: int = 100, name: Optional[str] = None):
    """Detection mAP over detection_output results (reference:
    DetectionMAPEvaluator.cpp — 11-point / integral AP).

    TPU design: instead of host-side per-detection score lists, TP/FP are
    histogrammed into ``score_bins`` confidence bins per class on device;
    AP integrates the binned precision/recall curve on the host.
    Accumulables per class: [tp_hist, fp_hist, #gt].
    """
    name = name or auto_name("detection_map_evaluator")
    C, BINS = num_classes, score_bins

    def accum(params, parents, ctx):
        dv, lv = parents
        det = dv.array                                   # [B, K, 6]
        gt = lv.array                                    # [B, G, 5]
        gt_valid = (jnp.arange(gt.shape[1])[None, :] <
                    lv.lengths[:, None])

        def one(det_b, gt_b, valid_b):
            from paddle_tpu.ops import detection as ops_det
            iou = ops_det.iou_matrix(det_b[:, 2:6], gt_b[:, 1:5])  # [K,G]
            cls_match = (det_b[:, 0:1] == gt_b[None, :, 0]) & valid_b[None]
            iou = jnp.where(cls_match, iou, 0.0)
            K, G = iou.shape
            # greedy: detections are score-sorted (detection_output output);
            # each claims its best unclaimed gt above threshold
            def body(i, carry):
                claimed, tp = carry
                row = jnp.where(claimed, 0.0, iou[i])
                j = jnp.argmax(row)
                hit = (row[j] >= overlap_threshold) & (det_b[i, 0] >= 0)
                claimed = claimed.at[j].set(claimed[j] | hit)
                tp = tp.at[i].set(hit)
                return claimed, tp

            _, tp = jax.lax.fori_loop(
                0, K, body, (jnp.zeros(G, bool), jnp.zeros(K, bool)))
            valid_det = det_b[:, 0] >= 0
            fp = valid_det & ~tp
            bins = jnp.clip((det_b[:, 1] * BINS).astype(jnp.int32), 0,
                            BINS - 1)
            cls = jnp.maximum(det_b[:, 0].astype(jnp.int32), 0)
            flat = cls * BINS + bins
            tp_h = jnp.zeros(C * BINS).at[flat].add(
                tp.astype(jnp.float32) * valid_det)
            fp_h = jnp.zeros(C * BINS).at[flat].add(
                fp.astype(jnp.float32))
            gt_h = jnp.zeros(C).at[gt_b[:, 0].astype(jnp.int32)].add(
                valid_b.astype(jnp.float32))
            return jnp.concatenate([tp_h, fp_h, gt_h])

        per = jax.vmap(one)(det, gt, gt_valid)
        return jnp.sum(per, axis=0)

    def fin(t):
        import numpy as np
        tp_h = t[:C * BINS].reshape(C, BINS)
        fp_h = t[C * BINS:2 * C * BINS].reshape(C, BINS)
        ngt = t[2 * C * BINS:]
        aps = []
        for c in range(C):
            if c == background_id or ngt[c] <= 0:
                continue
            # sweep score bins high -> low
            tp = np.cumsum(tp_h[c][::-1])
            fp = np.cumsum(fp_h[c][::-1])
            rec = tp / ngt[c]
            prec = tp / np.maximum(tp + fp, 1e-12)
            # integral AP with monotone precision envelope
            prec = np.maximum.accumulate(prec[::-1])[::-1]
            ap = 0.0
            prev_r = 0.0
            for r, p in zip(rec, prec):
                ap += (r - prev_r) * p
                prev_r = r
            aps.append(ap)
        return float(np.mean(aps)) if aps else 0.0

    return _evaluator_layer(name, "detection_map", [detections, label],
                            accum, fin, 2 * C * BINS + C)
