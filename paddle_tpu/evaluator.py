"""Evaluators — training/test metrics.

Reference: paddle/gserver/evaluators/Evaluator.h:42 hierarchy (classification
error, precision/recall, AUC, chunk-F1, CTC error, ...) wrapped by
python/paddle/v2/evaluator.py. Design here: an evaluator is a LayerOutput
emitting a small vector of *accumulables* per batch (device-side, inside the
jitted step), plus a host-side finalize() that turns summed accumulables into
the metric — so metric math rides the same traced program and only a few
scalars cross the host boundary each batch.
"""

from typing import Optional

import jax.numpy as jnp

from paddle_tpu.topology import LayerOutput, Value, auto_name


class MetricAccumulator:
    """Host-side accumulator over batch accumulable vectors."""

    def __init__(self, name, finalize_fn, width):
        self.name = name
        self.finalize_fn = finalize_fn
        self.width = width
        self.total = None

    def reset(self):
        self.total = None

    def add(self, vec):
        import numpy as np
        vec = np.asarray(vec, np.float64)
        self.total = vec if self.total is None else self.total + vec

    def value(self):
        if self.total is None:
            return float("nan")
        return self.finalize_fn(self.total)


def _evaluator_layer(name, etype, inputs, accum_fn, finalize_fn, width):
    def fwd(params, parents, ctx):
        return Value(accum_fn(params, parents, ctx))
    lo = LayerOutput(name, etype, inputs, fwd, [], size=width)
    lo.metric_finalize = finalize_fn
    lo.metric_width = width
    return lo


def classification_error(input, label, name: Optional[str] = None, top_k=1):
    """Error rate (reference: ClassificationErrorEvaluator, Evaluator.cpp).
    Accumulables: [#wrong, #examples]. Sequence inputs count per-token."""
    name = name or auto_name("classification_error_evaluator")

    def accum(params, parents, ctx):
        pv, lv = parents
        pred, lab = pv.array, lv.array
        if pv.is_sequence:
            mask = (jnp.arange(pred.shape[1])[None, :] <
                    pv.lengths[:, None]).astype(jnp.float32)
            lab2 = lab if lab.ndim == 2 else lab.reshape(lab.shape[0], -1)
            wrong = (jnp.argmax(pred, -1) != lab2).astype(jnp.float32) * mask
            return jnp.stack([wrong.sum(), mask.sum()])
        lab1 = lab.reshape(-1)
        if top_k == 1:
            wrong = (jnp.argmax(pred, -1) != lab1).astype(jnp.float32)
        else:
            topi = jnp.argsort(-pred, axis=-1)[:, :top_k]
            wrong = 1.0 - jnp.any(topi == lab1[:, None], axis=-1
                                  ).astype(jnp.float32)
        return jnp.stack([wrong.sum(), jnp.full((), wrong.shape[0],
                                                jnp.float32)])

    return _evaluator_layer(name, "classification_error", [input, label],
                            accum, lambda t: t[0] / max(t[1], 1), 2)


def precision_recall(input, label, name: Optional[str] = None,
                     positive_label=1):
    """Binary precision/recall/F1 (reference: PrecisionRecallEvaluator).
    Accumulables: [tp, fp, fn]."""
    name = name or auto_name("precision_recall_evaluator")

    def accum(params, parents, ctx):
        pred = jnp.argmax(parents[0].array, -1)
        lab = parents[1].array.reshape(-1)
        pos = pred == positive_label
        truth = lab == positive_label
        tp = jnp.sum(pos & truth).astype(jnp.float32)
        fp = jnp.sum(pos & ~truth).astype(jnp.float32)
        fn = jnp.sum(~pos & truth).astype(jnp.float32)
        return jnp.stack([tp, fp, fn])

    def fin(t):
        tp, fp, fn = t
        p = tp / max(tp + fp, 1e-12)
        r = tp / max(tp + fn, 1e-12)
        return {"precision": p, "recall": r,
                "f1": 2 * p * r / max(p + r, 1e-12)}

    return _evaluator_layer(name, "precision_recall", [input, label],
                            accum, fin, 3)


def auc(input, label, name: Optional[str] = None, num_thresholds=200):
    """Binned AUC (reference: AucEvaluator — bucketed ROC like the original;
    operators/auc_op.cc). Accumulables: [pos_hist..., neg_hist...]."""
    name = name or auto_name("auc_evaluator")

    def accum(params, parents, ctx):
        probs = parents[0].array
        # positive-class probability: column 1 of softmax output, or the
        # single sigmoid output
        p = probs[:, 1] if probs.shape[-1] >= 2 else probs[:, 0]
        lab = parents[1].array.reshape(-1).astype(jnp.float32)
        bins = jnp.clip((p * num_thresholds).astype(jnp.int32), 0,
                        num_thresholds - 1)
        pos = jnp.zeros(num_thresholds).at[bins].add(lab)
        neg = jnp.zeros(num_thresholds).at[bins].add(1.0 - lab)
        return jnp.concatenate([pos, neg])

    def fin(t):
        import numpy as np
        pos, neg = t[:num_thresholds], t[num_thresholds:]
        # sweep thresholds high->low accumulating TPR/FPR, trapezoid rule
        tp = np.cumsum(pos[::-1])
        fp = np.cumsum(neg[::-1])
        tot_p, tot_n = max(tp[-1], 1e-12), max(fp[-1], 1e-12)
        tpr = np.concatenate([[0.0], tp / tot_p])
        fpr = np.concatenate([[0.0], fp / tot_n])
        return float(np.trapezoid(tpr, fpr))

    return _evaluator_layer(name, "auc", [input, label], accum, fin,
                            2 * num_thresholds)


def sum_cost(input, name: Optional[str] = None):
    """(reference: SumCostEvaluator) Accumulables: [sum, count]."""
    name = name or auto_name("sum_evaluator")

    def accum(params, parents, ctx):
        v = parents[0].array.astype(jnp.float32)
        return jnp.stack([v.sum(), jnp.full((), v.shape[0], jnp.float32)])

    return _evaluator_layer(name, "sum_cost", [input], accum,
                            lambda t: t[0] / max(t[1], 1), 2)


class EvaluatorSet:
    """Host-side bundle the trainer drives (reset per pass / per test)."""

    def __init__(self, layers):
        self.layers = [l for l in layers if hasattr(l, "metric_finalize")]
        self.accs = {l.name: MetricAccumulator(l.name, l.metric_finalize,
                                               l.metric_width)
                     for l in self.layers}

    def reset(self):
        for a in self.accs.values():
            a.reset()

    def add_batch(self, outputs):
        for l in self.layers:
            if l.name in outputs:
                self.accs[l.name].add(outputs[l.name].array)

    def result(self):
        return {name: acc.value() for name, acc in self.accs.items()}
