"""MQ2007 learning-to-rank (reference: python/paddle/v2/dataset/mq2007.py —
LETOR text format ``rel qid:N 1:v1 ... 46:v46``, grouped by query, emitted
pointwise / pairwise / listwise).

Formats (mq2007.py gen_point/gen_pair/gen_list):
- pointwise: (relevance_score, 46-vector)
- pairwise:  (np.ones(1), better_vector, worse_vector)
- listwise:  (scores_array, vectors_array) per query

Offline fallback: a linear relevance model over synthetic feature vectors so
rank costs train.
"""

import os

import numpy as np

from paddle_tpu.dataset import common

FEATURE_DIM = 46
_FOLD_FILES = {"train": "train.txt", "test": "test.txt", "vali": "vali.txt"}


class QueryList:
    def __init__(self, query_id):
        self.query_id = query_id
        self.relevance_score = []
        self.feature_vector = []

    def append(self, rel, vec):
        self.relevance_score.append(rel)
        self.feature_vector.append(vec)


def _parse_letor(path):
    """Stream QueryList groups from a LETOR file (mq2007.py load_from_text)."""
    current = None
    with open(path) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            parts = line.split()
            rel = int(parts[0])
            qid = int(parts[1].split(":")[1])
            vec = np.zeros(FEATURE_DIM, np.float32)
            for tok in parts[2:]:
                k, v = tok.split(":")
                k = int(k)
                if 1 <= k <= FEATURE_DIM:
                    vec[k - 1] = float(v)
            if current is None or current.query_id != qid:
                if current is not None:
                    yield current
                current = QueryList(qid)
            current.append(rel, vec)
    if current is not None:
        yield current


def _synthetic_queries(num_queries, seed):
    w = np.random.RandomState(1234).randn(FEATURE_DIM).astype(np.float32)

    def gen():
        r = np.random.RandomState(seed)
        for qid in range(num_queries):
            q = QueryList(qid)
            for _ in range(int(r.randint(4, 16))):
                vec = r.randn(FEATURE_DIM).astype(np.float32)
                score = float(vec @ w) + 0.3 * float(r.randn())
                q.append(int(np.clip(round(score / 2 + 1), 0, 2)), vec)
            yield q
    return gen


def _emit(querylists, fmt):
    for q in querylists:
        scores = np.asarray(q.relevance_score, np.float32)
        vecs = np.asarray(q.feature_vector, np.float32)
        if fmt == "pointwise":
            for s, v in zip(scores, vecs):
                yield float(s), v
        elif fmt == "pairwise":
            n = len(scores)
            for i in range(n):
                for j in range(n):
                    if scores[i] > scores[j]:
                        yield np.ones(1, np.float32), vecs[i], vecs[j]
        elif fmt == "listwise":
            yield scores, vecs
        else:
            raise ValueError(f"unknown format {fmt!r}")


def _reader_creator(split, fmt):
    fold = os.path.join(common.DATA_HOME, "mq2007", "MQ2007", "Fold1",
                        _FOLD_FILES.get(split, split))
    if os.path.exists(fold):
        def reader():
            yield from _emit(_parse_letor(fold), fmt)
        return common.real_data(reader)
    seed = {"train": 91, "test": 911, "vali": 9111}.get(split, 99)
    nq = 256 if split == "train" else 64

    def reader():
        yield from _emit(_synthetic_queries(nq, seed)(), fmt)
    return common.synthetic_fallback("mq2007", split, reader)


def train(format="pairwise"):
    return _reader_creator("train", format)


def test(format="pairwise"):
    return _reader_creator("test", format)
