"""MovieLens 1-M (reference: python/paddle/v2/dataset/movielens.py — user
metadata + movie metadata + rating samples parsed from ml-1m.zip).

Sample schema (movielens.py __reader__): ``[user_id, gender(0/1), age_idx,
job_id, movie_id, [category_ids], [title_word_ids], [rating*2-5]]`` — the
recommender-system wide&deep input. Real path parses the cached zip; offline
fallback synthesises a latent-factor world with the same schema so the
recommender demo converges.
"""

import re
import zipfile

import numpy as np

from paddle_tpu.dataset import common

ARCHIVE = "ml-1m.zip"
age_table = [1, 18, 25, 35, 45, 50, 56]

# synthetic-world sizes (used when no cache is present)
_SYN_USERS, _SYN_MOVIES, _SYN_JOBS = 600, 400, 21
_SYN_CATEGORIES, _SYN_TITLE_WORDS = 18, 1000

_meta = None


class MovieInfo:
    def __init__(self, index, categories, title):
        self.index = int(index)
        self.categories = categories
        self.title = title

    def value(self, categories_dict, title_dict):
        return [self.index,
                [categories_dict[c] for c in self.categories],
                [title_dict[w.lower()] for w in self.title.split()]]


class UserInfo:
    def __init__(self, index, gender, age, job_id):
        self.index = int(index)
        self.is_male = gender == "M"
        self.age = age_table.index(int(age))
        self.job_id = int(job_id)

    def value(self):
        return [self.index, 0 if self.is_male else 1, self.age, self.job_id]


def _load_meta():
    """Parse movies.dat/users.dat once (movielens.py __initialize_meta_info__)."""
    global _meta
    if _meta is not None:
        return _meta
    path = common.cached_file("movielens", ARCHIVE)
    if not path:
        _meta = _synthetic_meta()
        return _meta
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    movies, title_words, categories = {}, set(), set()
    users = {}
    with zipfile.ZipFile(path) as z:
        with z.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = line.decode("latin1").strip().split("::")
                cats = cats.split("|")
                categories.update(cats)
                m = pattern.match(title)
                title = m.group(1).strip() if m else title
                movies[int(mid)] = MovieInfo(mid, cats, title)
                title_words.update(w.lower() for w in title.split())
        with z.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job, _zip = \
                    line.decode("latin1").strip().split("::")
                users[int(uid)] = UserInfo(uid, gender, age, job)
    _meta = {
        "movies": movies, "users": users, "real": True,
        "categories": {c: i for i, c in enumerate(sorted(categories))},
        "title_words": {w: i for i, w in enumerate(sorted(title_words))},
    }
    return _meta


def _synthetic_meta():
    rng = np.random.RandomState(1234)
    movies = {
        mid: MovieInfo(mid,
                       [f"c{int(c)}" for c in
                        rng.choice(_SYN_CATEGORIES, 1 + int(rng.randint(3)),
                                   replace=False)],
                       " ".join(f"t{int(w)}" for w in
                                rng.randint(0, _SYN_TITLE_WORDS,
                                            2 + int(rng.randint(4)))))
        for mid in range(1, _SYN_MOVIES + 1)}
    users = {
        uid: UserInfo(uid, "M" if rng.rand() < 0.5 else "F",
                      age_table[int(rng.randint(len(age_table)))],
                      int(rng.randint(_SYN_JOBS)))
        for uid in range(1, _SYN_USERS + 1)}
    cats = sorted({c for m in movies.values() for c in m.categories})
    words = sorted({w.lower() for m in movies.values()
                    for w in m.title.split()})
    return {"movies": movies, "users": users, "real": False,
            "categories": {c: i for i, c in enumerate(cats)},
            "title_words": {w: i for i, w in enumerate(words)}}


def _synthetic_ratings(meta, seed, test_ratio, is_test):
    """Latent-factor ratings: user and movie embeddings drawn from the task
    seed; rating = clipped dot product — learnable structure, not noise."""
    rng = np.random.RandomState(4321)
    uvec = rng.randn(_SYN_USERS + 1, 6)
    mvec = rng.randn(_SYN_MOVIES + 1, 6)
    r = np.random.RandomState(seed)
    for _ in range(16384):
        uid = int(r.randint(1, _SYN_USERS + 1))
        mid = int(r.randint(1, _SYN_MOVIES + 1))
        if (r.rand() < test_ratio) != is_test:
            continue
        raw = float(uvec[uid] @ mvec[mid]) / 2.5 + 0.2 * float(r.randn())
        rating = float(np.clip(np.round(raw + 3.0), 1, 5))
        yield uid, mid, rating


def _reader_creator(rand_seed=0, test_ratio=0.1, is_test=False):
    def reader():
        meta = _load_meta()
        cats, words = meta["categories"], meta["title_words"]
        if meta["real"]:
            path = common.cached_file("movielens", ARCHIVE)
            rand = np.random.RandomState(rand_seed)
            with zipfile.ZipFile(path) as z:
                with z.open("ml-1m/ratings.dat") as f:
                    for line in f:
                        if (rand.rand() < test_ratio) != is_test:
                            continue
                        uid, mid, rating, _ts = \
                            line.decode("latin1").strip().split("::")
                        usr = meta["users"][int(uid)]
                        mov = meta["movies"][int(mid)]
                        yield (usr.value() + mov.value(cats, words) +
                               [[float(rating) * 2 - 5.0]])
        else:
            for uid, mid, rating in _synthetic_ratings(
                    meta, 7 + rand_seed, test_ratio, is_test):
                usr, mov = meta["users"][uid], meta["movies"][mid]
                yield (usr.value() + mov.value(cats, words) +
                       [[rating * 2 - 5.0]])

    meta = _load_meta()
    return (common.real_data(reader) if meta["real"] else
            common.synthetic_fallback(
                "movielens", "test" if is_test else "train", reader))


def train():
    return _reader_creator(is_test=False)


def test():
    return _reader_creator(is_test=True)


def get_movie_title_dict():
    return _load_meta()["title_words"]


def movie_categories():
    return _load_meta()["categories"]


def max_movie_id():
    return max(_load_meta()["movies"])


def max_user_id():
    return max(_load_meta()["users"])


def max_job_id():
    return max(u.job_id for u in _load_meta()["users"].values())


def user_info():
    return _load_meta()["users"]


def movie_info():
    return _load_meta()["movies"]


def convert(path, line_count=1024):
    """Write the dataset as recordio chunks (reference: the
    per-module convert() feeding cloud training)."""
    out = []
    out += common.convert(path, train(), line_count, 'movielens_train')
    out += common.convert(path, test(), line_count, 'movielens_test')
    return out
