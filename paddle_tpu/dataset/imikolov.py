"""PTB language-model n-grams (reference: python/paddle/v2/dataset/imikolov.py
— n-gram windows of word ids for word2vec-style training)."""

import numpy as np

from paddle_tpu.dataset import synthetic

VOCAB_SIZE = 2000


def build_dict():
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def train(word_idx=None, n=5):
    vocab = len(word_idx) if word_idx else VOCAB_SIZE
    seq = synthetic.sequence_classification(2048, vocab, 2, seed=31,
                                            min_len=n + 2, max_len=40)

    def reader():
        for toks, _ in seq():
            for i in range(len(toks) - n + 1):
                yield tuple(toks[i:i + n])
    return reader


def test(word_idx=None, n=5):
    vocab = len(word_idx) if word_idx else VOCAB_SIZE
    seq = synthetic.sequence_classification(256, vocab, 2, seed=311,
                                            min_len=n + 2, max_len=40)

    def reader():
        for toks, _ in seq():
            for i in range(len(toks) - n + 1):
                yield tuple(toks[i:i + n])
    return reader
