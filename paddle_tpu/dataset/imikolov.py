"""PTB language-model dataset (reference: python/paddle/v2/dataset/imikolov.py
— n-gram windows or (src, trg) sequences of word ids from the Mikolov
simple-examples PTB text).

Real path: parse ptb.train.txt / ptb.valid.txt out of the cached
simple-examples.tgz; offline fallback: synthetic n-grams, loudly labelled.
"""

import collections
import tarfile

from paddle_tpu.dataset import common, synthetic

ARCHIVE = "simple-examples.tgz"
TRAIN_FILE = "./simple-examples/data/ptb.train.txt"
VALID_FILE = "./simple-examples/data/ptb.valid.txt"
VOCAB_SIZE = 2000


class DataType:
    NGRAM = 1
    SEQ = 2


def _lines(member):
    path = common.cached_file("imikolov", ARCHIVE)
    with tarfile.open(path) as tf:
        for raw in tf.extractfile(member):
            yield raw.decode("utf-8", errors="ignore")


_dict_cache = {}


def build_dict(min_word_freq=50):
    """Word -> id by descending frequency over train+valid, '<s>'/'<e>'
    counted per line, '<unk>' last (imikolov.py:48-73). Memoized — the
    tarball scan is expensive and train()/test() both need it."""
    if min_word_freq in _dict_cache:
        return _dict_cache[min_word_freq]
    if not common.cached_file("imikolov", ARCHIVE):
        d = {f"w{i}": i for i in range(VOCAB_SIZE)}
        d.setdefault("<unk>", len(d))
        d.setdefault("<s>", len(d))
        d.setdefault("<e>", len(d))
        return d
    freq = collections.defaultdict(int)
    for member in (TRAIN_FILE, VALID_FILE):
        for line in _lines(member):
            for w in line.strip().split():
                freq[w] += 1
            freq["<s>"] += 1
            freq["<e>"] += 1
    freq.pop("<unk>", None)
    kept = sorted(((w, c) for w, c in freq.items() if c > min_word_freq),
                  key=lambda x: (-x[1], x[0]))
    word_idx = {w: i for i, (w, _) in enumerate(kept)}
    word_idx["<unk>"] = len(word_idx)
    _dict_cache[min_word_freq] = word_idx
    return word_idx


def _real_reader(member, word_idx, n, data_type):
    unk = word_idx["<unk>"]

    def reader():
        for line in _lines(member):
            if data_type == DataType.NGRAM:
                toks = ["<s>"] + line.strip().split() + ["<e>"]
                if len(toks) >= n:
                    ids = [word_idx.get(w, unk) for w in toks]
                    for i in range(n, len(ids) + 1):
                        yield tuple(ids[i - n:i])
            else:
                ids = [word_idx.get(w, unk) for w in line.strip().split()]
                src = [word_idx["<s>"]] + ids
                trg = ids + [word_idx["<e>"]]
                if n > 0 and len(src) > n:
                    continue
                yield src, trg
    return reader


def _synthetic(split, num, vocab, n, seed, data_type):
    if data_type == DataType.NGRAM:
        seq = synthetic.sequence_classification(
            num, vocab, 2, seed=seed, min_len=n + 2, max_len=40)

        def reader():
            for toks, _ in seq():
                for i in range(len(toks) - n + 1):
                    yield tuple(toks[i:i + n])
    else:
        # SEQ mode: n is a max src length cutoff (n<=0 = unlimited), so
        # generate sequences that fit under it
        max_len = min(n - 1, 40) if n > 0 else 40
        seq = synthetic.sequence_classification(
            num, vocab, 2, seed=seed, min_len=min(3, max_len),
            max_len=max_len)

        def reader():
            bos, eos = vocab - 2, vocab - 1
            for toks, _ in seq():
                yield [bos] + toks, toks + [eos]
    return common.synthetic_fallback("imikolov", split, reader)


def train(word_idx=None, n=5, data_type=DataType.NGRAM):
    if common.cached_file("imikolov", ARCHIVE):
        wi = word_idx or build_dict()
        return common.real_data(_real_reader(TRAIN_FILE, wi, n, data_type))
    vocab = len(word_idx) if word_idx else VOCAB_SIZE
    return _synthetic("train", 2048, vocab, n, 31, data_type)


def test(word_idx=None, n=5, data_type=DataType.NGRAM):
    if common.cached_file("imikolov", ARCHIVE):
        wi = word_idx or build_dict()
        return common.real_data(_real_reader(VALID_FILE, wi, n, data_type))
    vocab = len(word_idx) if word_idx else VOCAB_SIZE
    return _synthetic("test", 256, vocab, n, 311, data_type)


def convert(path, line_count=1024):
    """Write the dataset as recordio chunks (reference: the
    per-module convert() feeding cloud training)."""
    out = []
    out += common.convert(path, train(), line_count, 'imikolov_train')
    out += common.convert(path, test(), line_count, 'imikolov_test')
    return out
