"""WMT14 FR→EN translation (reference: python/paddle/v2/dataset/wmt14.py —
the shrunk wmt14.tgz with src.dict/trg.dict and tab-separated parallel text).

Sample schema (wmt14.py reader_creator): ``(src_ids, trg_ids, trg_ids_next)``
where src has <s>/<e> wrappers, trg starts with <s>, trg_next ends with <e>,
OOV -> <unk> (id 2), pairs longer than 80 tokens dropped. Offline fallback:
a deterministic token-mapping translation task (trg = permuted src vocab) so
seq2seq demonstrably learns.
"""

import tarfile

import numpy as np

from paddle_tpu.dataset import common

ARCHIVE = "wmt14.tgz"
START, END, UNK = "<s>", "<e>", "<unk>"
UNK_IDX = 2
_SYN_SRC_VOCAB = _SYN_TRG_VOCAB = 1000

_dict_cache = {}


def _read_dicts(dict_size):
    if dict_size in _dict_cache:
        return _dict_cache[dict_size]
    path = common.cached_file("wmt14", ARCHIVE)
    src_dict, trg_dict = {}, {}
    with tarfile.open(path) as tf:
        for member in tf:
            if member.name.endswith("src.dict"):
                target = src_dict
            elif member.name.endswith("trg.dict"):
                target = trg_dict
            else:
                continue
            for i, line in enumerate(tf.extractfile(member)):
                if i >= dict_size:
                    break
                target[line.decode("utf-8", errors="ignore").strip()] = i
    _dict_cache[dict_size] = (src_dict, trg_dict)
    return src_dict, trg_dict


def _real_reader(file_name, dict_size):
    def reader():
        src_dict, trg_dict = _read_dicts(dict_size)
        path = common.cached_file("wmt14", ARCHIVE)
        with tarfile.open(path) as tf:
            names = [m.name for m in tf if m.name.endswith(file_name)]
            for name in names:
                for line in tf.extractfile(name):
                    parts = line.decode("utf-8",
                                        errors="ignore").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [src_dict.get(w, UNK_IDX)
                               for w in [START] + parts[0].split() + [END]]
                    trg_words = parts[1].split()
                    trg_ids = [trg_dict.get(w, UNK_IDX) for w in trg_words]
                    if len(src_ids) > 80 or len(trg_ids) > 80:
                        continue
                    yield (src_ids, [trg_dict[START]] + trg_ids,
                           trg_ids + [trg_dict[END]])
    return reader


def _synthetic_reader(split, dict_size, num, seed):
    """Permutation-translation: target token = fixed permutation of source
    token — a seq2seq task a model can actually drive to zero loss."""
    vs = min(dict_size, _SYN_SRC_VOCAB)
    perm = np.random.RandomState(1234).permutation(vs)
    s_bos, s_eos = 0, 1
    t_bos, t_eos = 0, 1

    def reader():
        r = np.random.RandomState(seed)
        for _ in range(num):
            n = int(r.randint(4, 20))
            src = r.randint(3, vs, n)
            trg = perm[src] % vs
            trg = np.where(trg < 3, 3, trg)
            yield ([s_bos] + src.tolist() + [s_eos],
                   [t_bos] + trg.tolist(),
                   trg.tolist() + [t_eos])
    return common.synthetic_fallback("wmt14", split, reader)


def train(dict_size=30000):
    if common.cached_file("wmt14", ARCHIVE):
        return common.real_data(_real_reader("train/train", dict_size))
    return _synthetic_reader("train", dict_size, 4096, seed=51)


def test(dict_size=30000):
    if common.cached_file("wmt14", ARCHIVE):
        return common.real_data(_real_reader("test/test", dict_size))
    return _synthetic_reader("test", dict_size, 512, seed=511)


def gen(dict_size=30000):
    if common.cached_file("wmt14", ARCHIVE):
        return common.real_data(_real_reader("gen/gen", dict_size))
    return _synthetic_reader("gen", dict_size, 64, seed=5111)


def get_dict(dict_size=30000, reverse=True):
    """id->word maps when reverse (wmt14.py get_dict)."""
    if common.cached_file("wmt14", ARCHIVE):
        src_dict, trg_dict = _read_dicts(dict_size)
    else:
        vs = min(dict_size, _SYN_SRC_VOCAB)
        src_dict = {f"s{i}": i for i in range(vs)}
        trg_dict = {f"t{i}": i for i in range(vs)}
    if reverse:
        src_dict = {v: k for k, v in src_dict.items()}
        trg_dict = {v: k for k, v in trg_dict.items()}
    return src_dict, trg_dict
