"""IMDB sentiment (reference: python/paddle/v2/dataset/imdb.py — tokenized
reviews as word-id sequences + binary label)."""

from paddle_tpu.dataset import synthetic

VOCAB_SIZE = 5000


def word_dict():
    return {f"w{i}": i for i in range(VOCAB_SIZE)}


def train(word_idx=None):
    n = len(word_idx) if word_idx else VOCAB_SIZE
    return synthetic.sequence_classification(4096, n, 2, seed=21,
                                             min_len=8, max_len=60)


def test(word_idx=None):
    n = len(word_idx) if word_idx else VOCAB_SIZE
    return synthetic.sequence_classification(512, n, 2, seed=211,
                                             min_len=8, max_len=60)
