"""IMDB sentiment (reference: python/paddle/v2/dataset/imdb.py — tokenized
reviews as word-id sequences + binary label, parsed from aclImdb_v1.tar.gz).

Real path: sequential scan of the cached tarball (the reference deliberately
used tarfile.next() streaming, imdb.py:40); offline fallback: synthetic
sequences with the same (list[int], int) schema, loudly labelled.
"""

import collections
import re
import string
import tarfile

from paddle_tpu.dataset import common, synthetic

ARCHIVE = "aclImdb_v1.tar.gz"
VOCAB_SIZE = 5000

_TRAIN_POS = re.compile(r"aclImdb/train/pos/.*\.txt$")
_TRAIN_NEG = re.compile(r"aclImdb/train/neg/.*\.txt$")
_TEST_POS = re.compile(r"aclImdb/test/pos/.*\.txt$")
_TEST_NEG = re.compile(r"aclImdb/test/neg/.*\.txt$")
_PUNCT = str.maketrans("", "", string.punctuation)


def tokenize(pattern):
    """Stream tokenized docs whose member name matches ``pattern``."""
    path = common.cached_file("imdb", ARCHIVE)
    if not path:
        return
    with tarfile.open(path) as tarf:
        tf = tarf.next()
        while tf is not None:
            if pattern.match(tf.name):
                text = tarf.extractfile(tf).read().decode(
                    "utf-8", errors="ignore")
                yield text.rstrip("\n\r").translate(_PUNCT).lower().split()
            tf = tarf.next()


_TRAIN_ANY = re.compile(r"aclImdb/train/(pos|neg)/.*\.txt$")
_dict_cache = {}


def build_dict(pattern=None, cutoff=150):
    """Word -> id by descending frequency, '<unk>' last (imdb.py:57).
    Memoized — the tarball scan is expensive and train()/test() both need
    it; the default pattern covers pos+neg in ONE sequential pass."""
    key = (pattern.pattern if pattern else None, cutoff)
    if key in _dict_cache:
        return _dict_cache[key]
    if common.cached_file("imdb", ARCHIVE):
        freq = collections.defaultdict(int)
        for doc in tokenize(pattern or _TRAIN_ANY):
            for w in doc:
                freq[w] += 1
        kept = sorted(((w, c) for w, c in freq.items() if c > cutoff),
                      key=lambda x: (-x[1], x[0]))
        word_idx = {w: i for i, (w, _) in enumerate(kept)}
        word_idx["<unk>"] = len(word_idx)
    else:
        word_idx = {f"w{i}": i for i in range(VOCAB_SIZE)}
        word_idx["<unk>"] = len(word_idx)
    _dict_cache[key] = word_idx
    return word_idx


# back-compat alias used by models/tests
def word_dict():
    return build_dict()


def _real_reader(pos_pat, neg_pat, word_idx):
    unk = word_idx["<unk>"]

    def reader():
        # alternate pos/neg so minibatches stay balanced (imdb.py:78)
        pos = ((doc, 1) for doc in tokenize(pos_pat))
        neg = ((doc, 0) for doc in tokenize(neg_pat))
        iters, i = [pos, neg], 0
        exhausted = [False, False]
        while not all(exhausted):
            if not exhausted[i % 2]:
                try:
                    doc, lbl = next(iters[i % 2])
                    yield [word_idx.get(w, unk) for w in doc], lbl
                except StopIteration:
                    exhausted[i % 2] = True
            i += 1
    return reader


def train(word_idx=None):
    if common.cached_file("imdb", ARCHIVE):
        wi = word_idx or build_dict()
        return common.real_data(_real_reader(_TRAIN_POS, _TRAIN_NEG, wi))
    n = len(word_idx) if word_idx else VOCAB_SIZE
    return common.synthetic_fallback(
        "imdb", "train", synthetic.sequence_classification(
            4096, n, 2, seed=21, min_len=8, max_len=60))


def test(word_idx=None):
    if common.cached_file("imdb", ARCHIVE):
        wi = word_idx or build_dict()
        return common.real_data(_real_reader(_TEST_POS, _TEST_NEG, wi))
    n = len(word_idx) if word_idx else VOCAB_SIZE
    return common.synthetic_fallback(
        "imdb", "test", synthetic.sequence_classification(
            512, n, 2, seed=211, min_len=8, max_len=60))


def convert(path, line_count=1024):
    """Write the dataset as recordio chunks (reference: the
    per-module convert() feeding cloud training)."""
    out = []
    out += common.convert(path, train(), line_count, 'imdb_train')
    out += common.convert(path, test(), line_count, 'imdb_test')
    return out
