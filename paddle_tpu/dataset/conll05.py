"""CoNLL-2005 semantic role labeling (reference:
python/paddle/v2/dataset/conll05.py — 9-feature SRL samples built from the
public test split of conll05st plus word/verb/label dictionaries).

Sample schema (conll05.py reader_creator): ``(word_idx, ctx_n2, ctx_n1,
ctx_0, ctx_p1, ctx_p2, pred_idx, mark, label_idx)`` — all sequences of
sentence length; the five ctx features broadcast the predicate window and
``mark`` flags the window positions. Real path parses the cached tarball;
offline fallback synthesises tagged sentences with the same 9-slot schema.
"""

import gzip
import itertools
import tarfile

import numpy as np

from paddle_tpu.dataset import common

ARCHIVE = "conll05st-tests.tar.gz"
WORDDICT = "wordDict.txt"
VERBDICT = "verbDict.txt"
TRGDICT = "targetDict.txt"
WORDS_NAME = "conll05st-release/test.wsj/words/test.wsj.words.gz"
PROPS_NAME = "conll05st-release/test.wsj/props/test.wsj.props.gz"
UNK_IDX = 0

_SYN_VOCAB, _SYN_VERBS, _SYN_LABELS = 800, 60, 21


def load_dict(path):
    with open(path) as f:
        return {line.strip(): i for i, line in enumerate(f)}


def _have_cache():
    return all(common.cached_file("conll05st", f)
               for f in (ARCHIVE, WORDDICT, VERBDICT, TRGDICT))


def get_dict():
    """(word_dict, verb_dict, label_dict) (conll05.py get_dict)."""
    if _have_cache():
        return (load_dict(common.cached_file("conll05st", WORDDICT)),
                load_dict(common.cached_file("conll05st", VERBDICT)),
                load_dict(common.cached_file("conll05st", TRGDICT)))
    word = {f"w{i}": i for i in range(_SYN_VOCAB)}
    verb = {f"v{i}": i for i in range(_SYN_VERBS)}
    label = {lbl: i for i, lbl in enumerate(
        ["O"] + [f"{b}-A{k}" for k in range(10) for b in ("B", "I")])}
    label["B-V"] = len(label)
    return word, verb, label


def corpus_reader(data_path, words_name=WORDS_NAME, props_name=PROPS_NAME):
    """Stream (sentence_words, predicate, iobes_labels) triples from the
    conll05st props format (conll05.py corpus_reader — '*'/'(A0*'/'*)'
    bracket runs converted to B-/I-/O tags)."""

    def reader():
        with tarfile.open(data_path) as tf:
            wf = tf.extractfile(words_name)
            pf = tf.extractfile(props_name)
            with gzip.GzipFile(fileobj=wf) as words_file, \
                    gzip.GzipFile(fileobj=pf) as props_file:
                sentence, one_seg = [], []
                for word, label in itertools.zip_longest(words_file,
                                                         props_file):
                    word = word.decode().strip()
                    label = label.decode().strip().split()
                    if label:
                        sentence.append(word)
                        one_seg.append(label)
                        continue
                    # end of sentence: column 0 is the verb column, columns
                    # 1.. are per-predicate bracket tag runs
                    columns = list(zip(*one_seg)) if one_seg else []
                    if columns:
                        verbs = [v for v in columns[0] if v != "-"]
                        for vi, col in enumerate(columns[1:]):
                            tags, cur, inside = [], "O", False
                            ok = True
                            for tok in col:
                                if tok == "*":
                                    tags.append(f"I-{cur}" if inside
                                                else "O")
                                elif tok == "*)":
                                    tags.append(f"I-{cur}")
                                    inside = False
                                elif "(" in tok and ")" in tok:
                                    cur = tok[1:tok.find("*")]
                                    tags.append(f"B-{cur}")
                                    inside = False
                                elif "(" in tok:
                                    cur = tok[1:tok.find("*")]
                                    tags.append(f"B-{cur}")
                                    inside = True
                                else:
                                    ok = False
                                    break
                            if ok and vi < len(verbs):
                                yield sentence, verbs[vi], tags
                    sentence, one_seg = [], []
    return reader


def reader_creator(corpus, word_dict, predicate_dict, label_dict):
    """9-feature SRL construction (conll05.py reader_creator)."""

    def reader():
        for sentence, predicate, labels in corpus():
            if "B-V" not in labels:
                continue
            n = len(sentence)
            vi = labels.index("B-V")
            mark = [0] * n
            ctx = {}
            for off, key in ((-2, "n2"), (-1, "n1"), (0, "0"),
                             (1, "p1"), (2, "p2")):
                j = vi + off
                if 0 <= j < n:
                    mark[j] = 1
                    ctx[key] = sentence[j]
                else:
                    ctx[key] = "bos" if off < 0 else "eos"
            word_idx = [word_dict.get(w, UNK_IDX) for w in sentence]
            ctx_feats = [[word_dict.get(ctx[k], UNK_IDX)] * n
                         for k in ("n2", "n1", "0", "p1", "p2")]
            pred_idx = [predicate_dict.get(predicate, 0)] * n
            label_idx = [label_dict.get(t, label_dict.get("O", 0))
                         for t in labels]
            yield tuple([word_idx] + ctx_feats + [pred_idx, mark, label_idx])
    return reader


def _synthetic_corpus(split, seed, num):
    """Tagged sentences from a deterministic tag table (same learnable
    structure as synthetic.sequence_tagging), with one synthetic verb."""
    word_dict, verb_dict, label_dict = get_dict()
    labels = [lbl for lbl in label_dict if lbl != "B-V"]
    tag_of = np.random.RandomState(99).randint(0, len(labels), _SYN_VOCAB)

    def corpus():
        r = np.random.RandomState(seed)
        for _ in range(num):
            n = int(r.randint(6, 25))
            toks = r.randint(0, _SYN_VOCAB, n)
            vi = int(r.randint(n))
            words = [f"w{t}" for t in toks]
            tags = [labels[tag_of[t]] for t in toks]
            tags[vi] = "B-V"
            verb = f"v{toks[vi] % _SYN_VERBS}"
            yield words, verb, tags
    return common.synthetic_fallback(
        "conll05", split,
        reader_creator(corpus, word_dict, verb_dict, label_dict))


def test():
    """The public split (training data is licensed; the reference trains on
    the test split too, conll05.py test())."""
    if _have_cache():
        word_dict, verb_dict, label_dict = get_dict()
        corpus = corpus_reader(common.cached_file("conll05st", ARCHIVE))
        return common.real_data(
            reader_creator(corpus, word_dict, verb_dict, label_dict))
    return _synthetic_corpus("test", seed=41, num=2048)


def train():
    return test() if _have_cache() else _synthetic_corpus(
        "train", seed=40, num=4096)
