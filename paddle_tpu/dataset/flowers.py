"""Oxford 102 Flowers (reference: python/paddle/v2/dataset/flowers.py —
102-class classification; images from 102flowers.tgz, labels/setid from
imagelabels.mat/setid.mat; train=tstid, test=trnid split swap as in the
reference; samples are (flattened 3x224x224 float32 CHW, label)).

Offline fallback keeps the (150528-float, int) schema with class-prototype
structure.
"""

import io
import tarfile

import numpy as np

from paddle_tpu.dataset import common, synthetic

DATA_ARCHIVE = "102flowers.tgz"
LABEL_FILE = "imagelabels.mat"
SETID_FILE = "setid.mat"
# the official trnid is smaller than tstid; the reference trains on tstid
TRAIN_FLAG, TEST_FLAG, VALID_FLAG = "tstid", "trnid", "valid"
IMG_DIM = 3 * 224 * 224


def _have_cache():
    return all(common.cached_file("flowers", f)
               for f in (DATA_ARCHIVE, LABEL_FILE, SETID_FILE))


def _transform(img_bytes, is_train):
    """Resize short side to 256, center-crop 224, CHW float32 with the
    reference's mean subtraction (flowers.py default_mapper)."""
    from PIL import Image
    img = Image.open(io.BytesIO(img_bytes)).convert("RGB")
    w, h = img.size
    scale = 256.0 / min(w, h)
    img = img.resize((int(w * scale + 0.5), int(h * scale + 0.5)))
    w, h = img.size
    x0, y0 = (w - 224) // 2, (h - 224) // 2
    arr = np.asarray(img.crop((x0, y0, x0 + 224, y0 + 224)),
                     np.float32)              # HWC RGB
    mean = np.array([123.68, 116.78, 103.94], np.float32)
    arr = (arr - mean).transpose(2, 0, 1)     # CHW
    return arr.reshape(-1)


def _real_reader(flag, is_train):
    def reader():
        import scipy.io as scio
        labels = scio.loadmat(
            common.cached_file("flowers", LABEL_FILE))["labels"][0]
        indexes = scio.loadmat(
            common.cached_file("flowers", SETID_FILE))[flag][0]
        wanted = {int(i) for i in indexes}
        with tarfile.open(common.cached_file("flowers", DATA_ARCHIVE)) as tar:
            for m in tar:
                if not m.name.endswith(".jpg"):
                    continue
                idx = int(m.name[-9:-4])       # image_#####.jpg
                if idx not in wanted:
                    continue
                img = tar.extractfile(m).read()
                yield _transform(img, is_train), int(labels[idx - 1]) - 1
    return reader


def train():
    if _have_cache():
        return common.real_data(_real_reader(TRAIN_FLAG, True))
    return common.synthetic_fallback(
        "flowers", "train",
        synthetic.classification(2048, IMG_DIM, 102, seed=81, noise=0.5))


def test():
    if _have_cache():
        return common.real_data(_real_reader(TEST_FLAG, False))
    return common.synthetic_fallback(
        "flowers", "test",
        synthetic.classification(256, IMG_DIM, 102, seed=811, noise=0.5))


def valid():
    if _have_cache():
        return common.real_data(_real_reader(VALID_FLAG, False))
    return common.synthetic_fallback(
        "flowers", "valid",
        synthetic.classification(256, IMG_DIM, 102, seed=8111, noise=0.5))
