"""Datasets (reference: python/paddle/v2/dataset/ — mnist, cifar, imdb,
imikolov, movielens, conll05, uci_housing, wmt14, ...).

This environment has no network egress, so each dataset module follows the
reference's download-cache protocol (common.py:62) but falls back to a
deterministic synthetic generator with identical sample schema when no cache
is present — training plumbing, shapes, and convergence behaviour stay
testable offline; drop real files into ~/.cache/paddle_tpu/dataset to use
real data.
"""

from paddle_tpu.dataset import common
from paddle_tpu.dataset import mnist
from paddle_tpu.dataset import cifar
from paddle_tpu.dataset import uci_housing
from paddle_tpu.dataset import imdb
from paddle_tpu.dataset import imikolov
from paddle_tpu.dataset import movielens
from paddle_tpu.dataset import conll05
from paddle_tpu.dataset import wmt14
from paddle_tpu.dataset import sentiment
from paddle_tpu.dataset import voc2012
from paddle_tpu.dataset import flowers
from paddle_tpu.dataset import mq2007
from paddle_tpu.dataset import synthetic
