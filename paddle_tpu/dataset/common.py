"""Dataset cache helpers (reference: python/paddle/v2/dataset/common.py:62 —
download cache under ~/.cache/paddle/dataset, md5 check, cluster file split)."""

import hashlib
import os

from paddle_tpu.utils.logger import get_logger

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))

log = get_logger("dataset")
_warned = set()


def real_data(reader_fn):
    """Mark a reader as backed by real cached files."""
    reader_fn.provenance = "real"
    return reader_fn


def synthetic_fallback(module: str, split: str, reader_fn):
    """Mark a reader as synthetic and warn LOUDLY, once per module/split.

    A run that silently trains on noise believing it trained the real
    dataset is worse than a crash — the provenance attribute lets callers
    (and tests) assert what they actually consumed."""
    key = (module, split)
    if key not in _warned:
        _warned.add(key)
        log.warning(
            "dataset %s.%s: no cached real data under %s — using SYNTHETIC "
            "schema-compatible data. Results do NOT reflect the real "
            "dataset; drop the reference files into the cache dir to fix.",
            module, split, os.path.join(DATA_HOME, module))
    reader_fn.provenance = "synthetic"
    return reader_fn


def cache_path(module: str, filename: str) -> str:
    return os.path.join(DATA_HOME, module, filename)


def cached_file(module: str, filename: str, md5=None):
    """Return the cached path if present (and md5-valid), else None.
    (No download: this environment has no egress; the reference's download()
    lives here in spirit.)"""
    path = cache_path(module, filename)
    if not os.path.exists(path):
        return None
    if md5:
        h = hashlib.md5()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != md5:
            return None
    return path


def split(reader_fn, line_count, suffix_formatter=None):
    """Cluster split helper (reference: common.py split/cluster_files) —
    partition a reader into chunks for the task-dispatch data service.
    Streams: yields one chunk at a time, holding only ``line_count`` samples
    in memory (the recordio/task design it feeds is streaming too)."""
    current = []
    for sample in reader_fn():
        current.append(sample)
        if len(current) >= line_count:
            yield current
            current = []
    if current:
        yield current


def split_to_recordio(reader_fn, path_pattern, line_count=1024):
    """Materialise a reader into recordio files of ``line_count`` records
    each — the cluster_files path (reference: common.py convert-to-recordio
    for cloud training). path_pattern must contain one ``%d``/``{}`` slot;
    returns the written paths."""
    import re as _re

    from paddle_tpu.runtime import recordio

    has_pct_slot = _re.search(r"%[0-9]*[ds]", path_pattern) is not None

    def render(i):
        return path_pattern % i if has_pct_slot else path_pattern.format(i)

    if render(0) == render(1):
        raise ValueError(
            f"path_pattern {path_pattern!r} has no %d/{{}} slot — every "
            f"chunk would overwrite the previous one")
    paths = []
    for i, chunk in enumerate(split(reader_fn, line_count)):
        path = render(i)
        recordio.write_records(path, chunk)
        paths.append(path)
    return paths


def convert(output_path, reader_fn, line_count, name_prefix):
    """Convert a reader to recordio chunk files named
    ``<name_prefix>-%05d`` under output_path (reference: common.py:194 —
    the cloud-training preprocessing step feeding the master's task
    dispatch). Returns the written paths."""
    os.makedirs(output_path, exist_ok=True)
    pattern = os.path.join(output_path, f"{name_prefix}-%05d")
    return split_to_recordio(reader_fn, pattern, line_count)
