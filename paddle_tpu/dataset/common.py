"""Dataset cache helpers (reference: python/paddle/v2/dataset/common.py:62 —
download cache under ~/.cache/paddle/dataset, md5 check, cluster file split)."""

import hashlib
import os

DATA_HOME = os.path.expanduser(
    os.environ.get("PADDLE_TPU_DATA_HOME", "~/.cache/paddle_tpu/dataset"))


def cache_path(module: str, filename: str) -> str:
    return os.path.join(DATA_HOME, module, filename)


def cached_file(module: str, filename: str, md5=None):
    """Return the cached path if present (and md5-valid), else None.
    (No download: this environment has no egress; the reference's download()
    lives here in spirit.)"""
    path = cache_path(module, filename)
    if not os.path.exists(path):
        return None
    if md5:
        h = hashlib.md5()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest() != md5:
            return None
    return path


def split(reader_fn, line_count, suffix_formatter=None):
    """Cluster file split helper (reference: common.py split/cluster_files) —
    partition a reader into chunks for the task-dispatch data service."""
    chunks, current = [], []
    for sample in reader_fn():
        current.append(sample)
        if len(current) >= line_count:
            chunks.append(current)
            current = []
    if current:
        chunks.append(current)
    return chunks
