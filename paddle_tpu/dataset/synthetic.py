"""Deterministic synthetic data generators with learnable structure.

Used as offline fallbacks: samples are class-prototype + noise so models
demonstrably converge, matching each real dataset's schema. The task
structure (prototypes / weights / tag tables) is derived from ``task_seed``
and the sampling stream from ``seed`` — train/test splits share the task
by sharing task_seed while differing in seed.
"""

import numpy as np


def classification(num_samples, feature_dim, num_classes, seed=0, noise=0.3,
                   task_seed=1234):
    """Gaussian class prototypes + noise."""
    protos = np.random.RandomState(task_seed).randn(
        num_classes, feature_dim).astype(np.float32)

    def reader():
        r = np.random.RandomState(seed + 1)
        for i in range(num_samples):
            y = int(r.randint(num_classes))
            x = protos[y] + noise * r.randn(feature_dim).astype(np.float32)
            yield x.astype(np.float32), y
    return reader


def regression(num_samples, feature_dim, seed=0, noise=0.1, task_seed=1234):
    rng = np.random.RandomState(task_seed)
    w = rng.randn(feature_dim).astype(np.float32)
    b = float(rng.randn())

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(num_samples):
            x = r.randn(feature_dim).astype(np.float32)
            y = float(x @ w + b + noise * r.randn())
            yield x, np.array([y], np.float32)
    return reader


def sequence_classification(num_samples, vocab_size, num_classes, seed=0,
                            min_len=5, max_len=30, task_seed=1234):
    """Integer sequences whose class is signalled by token distribution —
    an IMDB-like schema (list[int], int)."""
    rng = np.random.RandomState(task_seed)
    # each class prefers a distinct slice of the vocabulary
    prefs = [rng.permutation(vocab_size)[: vocab_size // 2]
             for _ in range(num_classes)]

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(num_samples):
            y = int(r.randint(num_classes))
            n = int(r.randint(min_len, max_len + 1))
            mix = r.rand(n) < 0.75
            toks = np.where(mix, r.choice(prefs[y], n),
                            r.randint(0, vocab_size, n))
            yield toks.astype(np.int64).tolist(), y
    return reader


def sequence_tagging(num_samples, vocab_size, num_tags, seed=0,
                     min_len=5, max_len=20, task_seed=1234):
    """Token-level tags correlated with token ids (CoNLL-like schema:
    (list[int] words, list[int] tags))."""
    tag_of = np.random.RandomState(task_seed).randint(0, num_tags, vocab_size)

    def reader():
        r = np.random.RandomState(seed + 1)
        for _ in range(num_samples):
            n = int(r.randint(min_len, max_len + 1))
            toks = r.randint(0, vocab_size, n)
            tags = tag_of[toks].copy()
            flip = r.rand(n) < 0.1
            tags[flip] = r.randint(0, num_tags, int(flip.sum()))
            yield toks.astype(np.int64).tolist(), tags.astype(np.int64).tolist()
    return reader
