"""Pascal VOC2012 segmentation (reference:
python/paddle/v2/dataset/voc2012.py — (HWC uint8 image, HW class-index mask)
pairs from VOCtrainval_11-May-2012.tar; splits trainval/train/val).

Offline fallback: synthetic images with rectangular class blobs so a
segmentation head can overfit (same (image, mask) schema, 21 classes).
"""

import io
import tarfile

import numpy as np

from paddle_tpu.dataset import common

ARCHIVE = "VOCtrainval_11-May-2012.tar"
SET_FILE = "VOCdevkit/VOC2012/ImageSets/Segmentation/{}.txt"
DATA_FILE = "VOCdevkit/VOC2012/JPEGImages/{}.jpg"
LABEL_FILE = "VOCdevkit/VOC2012/SegmentationClass/{}.png"
NUM_CLASSES = 21


def _real_reader(sub_name):
    def reader():
        from PIL import Image
        path = common.cached_file("voc2012", ARCHIVE)
        with tarfile.open(path) as tar:
            members = {m.name: m for m in tar.getmembers()}
            sets = tar.extractfile(members[SET_FILE.format(sub_name)])
            for line in sets:
                key = line.decode().strip()
                img = Image.open(io.BytesIO(
                    tar.extractfile(members[DATA_FILE.format(key)]).read()))
                lbl = Image.open(io.BytesIO(
                    tar.extractfile(members[LABEL_FILE.format(key)]).read()))
                yield np.array(img), np.array(lbl)
    return reader


def _synthetic_reader(split, num, seed, hw=96):
    def reader():
        r = np.random.RandomState(seed)
        for _ in range(num):
            img = r.randint(0, 255, (hw, hw, 3), np.uint8)
            mask = np.zeros((hw, hw), np.uint8)
            for _ in range(int(r.randint(1, 4))):
                cls = int(r.randint(1, NUM_CLASSES))
                y0, x0 = r.randint(0, hw - 16, 2)
                h, w = r.randint(8, 32, 2)
                mask[y0:y0 + h, x0:x0 + w] = cls
                # blob colour correlates with class so it is learnable
                img[y0:y0 + h, x0:x0 + w, cls % 3] = 200 + cls
            yield img, mask
    return common.synthetic_fallback("voc2012", split, reader)


def train():
    if common.cached_file("voc2012", ARCHIVE):
        return common.real_data(_real_reader("trainval"))
    return _synthetic_reader("train", 512, seed=71)


def test():
    if common.cached_file("voc2012", ARCHIVE):
        return common.real_data(_real_reader("train"))
    return _synthetic_reader("test", 128, seed=711)


def val():
    if common.cached_file("voc2012", ARCHIVE):
        return common.real_data(_real_reader("val"))
    return _synthetic_reader("val", 128, seed=7111)
