"""NLTK movie_reviews sentiment (reference:
python/paddle/v2/dataset/sentiment.py — 2000 polar reviews, pos/neg
interleaved, word ids by corpus frequency; first 1600 train, rest test).

Real path reads the extracted ``movie_reviews`` corpus directory (neg/ and
pos/ subdirs of .txt files) from the dataset cache; offline fallback keeps
the (list[int], 0/1) schema.
"""

import collections
import os
import re

from paddle_tpu.dataset import common, synthetic

CORPUS_DIR = "movie_reviews"
NUM_TRAINING_INSTANCES = 1600
VOCAB_SIZE = 3000

_cache = None


def _corpus_path():
    p = os.path.join(common.DATA_HOME, "sentiment", CORPUS_DIR)
    return p if os.path.isdir(p) else None


def _tokenize(text):
    return re.findall(r"[a-z0-9']+|[.,!?;]", text.lower())


def _load():
    """(word_dict, samples) — samples interleave neg/pos for balanced
    minibatches (sentiment.py sort_files)."""
    global _cache
    if _cache is not None:
        return _cache
    root = _corpus_path()
    docs = {"neg": [], "pos": []}
    for cat in ("neg", "pos"):
        d = os.path.join(root, cat)
        for fn in sorted(os.listdir(d)):
            with open(os.path.join(d, fn), errors="ignore") as f:
                docs[cat].append(_tokenize(f.read()))
    freq = collections.defaultdict(int)
    for cat in docs:
        for doc in docs[cat]:
            for w in doc:
                freq[w] += 1
    ranked = sorted(freq.items(), key=lambda x: (-x[1], x[0]))
    word_dict = {w: i for i, (w, _) in enumerate(ranked)}
    samples = []
    for neg, pos in zip(docs["neg"], docs["pos"]):
        samples.append(([word_dict[w] for w in neg], 0))
        samples.append(([word_dict[w] for w in pos], 1))
    _cache = (word_dict, samples)
    return _cache


def get_word_dict():
    if _corpus_path():
        word_dict, _ = _load()
        return sorted(word_dict.items(), key=lambda kv: kv[1])
    return [(f"w{i}", i) for i in range(VOCAB_SIZE)]


def _make_reader(lo, hi):
    def reader():
        _, samples = _load()
        for sample in samples[lo:hi]:
            yield sample
    return reader


def train():
    if _corpus_path():
        return common.real_data(_make_reader(0, NUM_TRAINING_INSTANCES))
    return common.synthetic_fallback(
        "sentiment", "train", synthetic.sequence_classification(
            1600, VOCAB_SIZE, 2, seed=61, min_len=20, max_len=200))


def test():
    if _corpus_path():
        return common.real_data(_make_reader(NUM_TRAINING_INSTANCES, None))
    return common.synthetic_fallback(
        "sentiment", "test", synthetic.sequence_classification(
            400, VOCAB_SIZE, 2, seed=611, min_len=20, max_len=200))


def convert(path, line_count=1024):
    """Write the dataset as recordio chunks (reference: the
    per-module convert() feeding cloud training)."""
    out = []
    out += common.convert(path, train(), line_count, 'sentiment_train')
    out += common.convert(path, test(), line_count, 'sentiment_test')
    return out
