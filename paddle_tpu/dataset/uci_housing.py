"""UCI housing regression (reference: python/paddle/v2/dataset/uci_housing.py
— 13 features normalized, float target)."""

import numpy as np

from paddle_tpu.dataset import common, synthetic

FEATURE_DIM = 13


def _file_reader(path, start, end):
    def reader():
        data = np.loadtxt(path).astype(np.float32)
        feats = data[:, :-1]
        feats = (feats - feats.mean(0)) / np.maximum(feats.std(0), 1e-6)
        for row, target in zip(feats[start:end], data[start:end, -1]):
            yield row, np.array([target], np.float32)
    return reader


def train():
    p = common.cached_file("uci_housing", "housing.data")
    if p:
        return common.real_data(_file_reader(p, 0, 404))
    return common.synthetic_fallback(
        "uci_housing", "train", synthetic.regression(404, FEATURE_DIM,
                                                     seed=3))


def test():
    p = common.cached_file("uci_housing", "housing.data")
    if p:
        return common.real_data(_file_reader(p, 404, 506))
    return common.synthetic_fallback(
        "uci_housing", "test", synthetic.regression(102, FEATURE_DIM,
                                                    seed=33))


def convert(path, line_count=1024):
    """Write the dataset as recordio chunks (reference: the
    per-module convert() feeding cloud training)."""
    out = []
    out += common.convert(path, train(), line_count, 'uci_housing_train')
    out += common.convert(path, test(), line_count, 'uci_housing_test')
    return out
