"""MNIST (reference: python/paddle/v2/dataset/mnist.py — idx-format parser,
(784-float normalized to [-1,1], int label) samples)."""

import gzip
import struct

import numpy as np

from paddle_tpu.dataset import common, synthetic

TRAIN_IMAGES = "train-images-idx3-ubyte.gz"
TRAIN_LABELS = "train-labels-idx1-ubyte.gz"
TEST_IMAGES = "t10k-images-idx3-ubyte.gz"
TEST_LABELS = "t10k-labels-idx1-ubyte.gz"


def _idx_reader(images_path, labels_path):
    def reader():
        with gzip.open(labels_path, "rb") as lf:
            magic, n = struct.unpack(">II", lf.read(8))
            labels = np.frombuffer(lf.read(n), np.uint8)
        with gzip.open(images_path, "rb") as imf:
            magic, n, rows, cols = struct.unpack(">IIII", imf.read(16))
            images = np.frombuffer(imf.read(n * rows * cols), np.uint8)
            images = images.reshape(n, rows * cols).astype(np.float32)
        images = images / 255.0 * 2.0 - 1.0   # reference normalisation
        for x, y in zip(images, labels):
            yield x, int(y)
    return reader


def _synthetic(split, n, seed):
    return common.synthetic_fallback(
        "mnist", split, synthetic.classification(n, 784, 10, seed=seed,
                                                 noise=0.4))


def train():
    imgs = common.cached_file("mnist", TRAIN_IMAGES)
    labs = common.cached_file("mnist", TRAIN_LABELS)
    if imgs and labs:
        return common.real_data(_idx_reader(imgs, labs))
    return _synthetic("train", 8192, seed=7)


def test():
    imgs = common.cached_file("mnist", TEST_IMAGES)
    labs = common.cached_file("mnist", TEST_LABELS)
    if imgs and labs:
        return common.real_data(_idx_reader(imgs, labs))
    return _synthetic("test", 1024, seed=77)


def convert(path, line_count=1024):
    """Write the dataset as recordio chunks (reference: the
    per-module convert() feeding cloud training)."""
    out = []
    out += common.convert(path, train(), line_count, 'mnist_train')
    out += common.convert(path, test(), line_count, 'mnist_test')
    return out
