"""CIFAR-10/100 (reference: python/paddle/v2/dataset/cifar.py — pickled
batches of (3072-float [0,1] CHW, int label))."""

import pickle
import tarfile

import numpy as np

from paddle_tpu.dataset import common, synthetic

CIFAR10 = "cifar-10-python.tar.gz"
CIFAR100 = "cifar-100-python.tar.gz"


def _tar_reader(path, sub_name, label_key):
    def reader():
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                if sub_name not in member.name:
                    continue
                batch = pickle.load(tf.extractfile(member), encoding="latin1")
                for x, y in zip(batch["data"], batch[label_key]):
                    yield (x / 255.0).astype(np.float32), int(y)
    return reader


def train10():
    p = common.cached_file("cifar", CIFAR10)
    if p:
        return common.real_data(_tar_reader(p, "data_batch", "labels"))
    return common.synthetic_fallback(
        "cifar", "train10",
        synthetic.classification(8192, 3072, 10, seed=11, noise=0.5))


def test10():
    p = common.cached_file("cifar", CIFAR10)
    if p:
        return common.real_data(_tar_reader(p, "test_batch", "labels"))
    return common.synthetic_fallback(
        "cifar", "test10",
        synthetic.classification(1024, 3072, 10, seed=111, noise=0.5))


def train100():
    p = common.cached_file("cifar", CIFAR100)
    if p:
        return common.real_data(_tar_reader(p, "train", "fine_labels"))
    return common.synthetic_fallback(
        "cifar", "train100",
        synthetic.classification(8192, 3072, 100, seed=13, noise=0.5))


def test100():
    p = common.cached_file("cifar", CIFAR100)
    if p:
        return common.real_data(_tar_reader(p, "test", "fine_labels"))
    return common.synthetic_fallback(
        "cifar", "test100",
        synthetic.classification(1024, 3072, 100, seed=131, noise=0.5))


def convert(path, line_count=1024):
    """Write the dataset as recordio chunks (reference: the
    per-module convert() feeding cloud training)."""
    out = []
    out += common.convert(path, train10(), line_count, 'cifar_train10')
    out += common.convert(path, test10(), line_count, 'cifar_test10')
    out += common.convert(path, train100(), line_count, 'cifar_train100')
    out += common.convert(path, test100(), line_count, 'cifar_test100')
    return out
