"""On-device token sampling for the continuous-batching decode engine.

The legacy LMServer samples host-side: every decoded token ships the
full ``[B, vocab]`` logits to numpy and loops ``RandomState.choice`` per
row — exactly the host/device sync PAPERS' non-GPU-inference field study
(arxiv 2607.08215) names as the decode-loop throughput killer. Here the
sampler is a pure jnp function that runs INSIDE the compiled decode
step, so only the sampled ids ``[B] int32`` ever cross to the host.

Per-slot controls are runtime vectors (static shapes, one compile):

- ``temperature`` [B] float32 — ``<= 0`` means greedy argmax for that
  row; the categorical draw still happens but is discarded by a
  ``where``, keeping the program shape-identical for any mix.
- ``top_k`` [B] int32 — ``<= 0`` (or ``>= vocab``) disables filtering.
  A runtime k can't use ``lax.top_k`` (static k), so the row is sorted
  once and everything below the k-th value is masked to ``-inf``; ties
  at the threshold survive, matching the usual top-k convention.
"""

import jax
import jax.numpy as jnp


def sample_tokens(logits: jax.Array, key: jax.Array,
                  temperature: jax.Array, top_k: jax.Array) -> jax.Array:
    """logits [B, V] fp32, per-slot temperature [B] / top_k [B] →
    sampled ids [B] int32 (greedy rows use argmax, first-index ties —
    the same convention as the host-side legacy path)."""
    V = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    k = jnp.clip(top_k.astype(jnp.int32), 0, V)
    srt = jnp.sort(logits, axis=-1)[:, ::-1]          # descending
    kth = jnp.take_along_axis(srt, jnp.maximum(k - 1, 0)[:, None],
                              axis=-1)                # [B, 1]
    keep = (k[:, None] <= 0) | (logits >= kth)
    z = jnp.where(keep, logits, -jnp.inf)
    t = jnp.where(temperature > 0, temperature, 1.0)  # div-safe for
    z = z / t[:, None].astype(jnp.float32)            # greedy rows
    sampled = jax.random.categorical(key, z, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def spec_accept(sampled: jax.Array, draft: jax.Array,
                valid: jax.Array) -> jax.Array:
    """Accept/reject fold of one speculative verify window: ``sampled``
    [B, W] are the TARGET's own tokens at each window position (greedy
    argmax or a categorical draw, per row), ``draft`` [B, W-1] the
    proposals those positions were conditioned on, ``valid`` [B] the
    usable window rows. Returns ``n`` [B]: how many leading sampled
    tokens are emitted — position j+1's sample only counts if every
    draft token before it matched (``cumprod`` of the leading run), so
    ``n = 1 + run`` emits the accepted drafts plus exactly one
    correction/bonus token. Because an accepted draft token EQUALS the
    target's sample at its position, the emitted tokens are always
    ``sampled[:, :n]`` — distribution-exact for sampled rows, bitwise
    the target-only sequence for greedy rows."""
    B, W = sampled.shape
    if W == 1:
        return jnp.minimum(jnp.ones((B,), jnp.int32),
                           valid.astype(jnp.int32))
    m = ((sampled[:, :W - 1] == draft)
         & (jnp.arange(1, W, dtype=jnp.int32)[None, :]
            < valid[:, None]))
    run = jnp.cumprod(m.astype(jnp.int32), axis=1).sum(axis=1)
    return jnp.minimum(1 + run, valid).astype(jnp.int32)


def spec_verify_tokens(logits: jax.Array, draft: jax.Array,
                       key: jax.Array, temperature: jax.Array,
                       top_k: jax.Array, valid: jax.Array):
    """Verify-window sampling + accept/reject: logits [B, W, V] from
    ``transformer.verify_step_paged``, draft [B, W-1] proposals,
    per-slot temperature/top_k [B] (broadcast over the window), valid
    [B] usable rows → (sampled [B, W] int32, n_emitted [B] int32).
    Each window row samples through :func:`sample_tokens` — the same
    greedy/top-k/categorical conventions as the decode step, over the
    same vocab axis length, so greedy rows are bitwise the target-only
    engine's argmax."""
    B, W, V = logits.shape
    X = sample_tokens(logits.reshape(B * W, V), key,
                      jnp.repeat(temperature, W),
                      jnp.repeat(top_k, W)).reshape(B, W)
    return X, spec_accept(X, draft, valid)


def _prefill_live(dequant):
    """Prefill-side weight resolution: an explicit ``dequant`` wins;
    otherwise {"q8","scale"} trees dequantize wholesale (prefill is
    compute-bound — one fp32 materialization amortizes over the whole
    chunk, unlike the weight-read-bound decode step, which handles q8
    natively inside its layer scan)."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops import q8 as ops_q8

    def _live(params):
        if dequant is not None:
            return dequant(params)
        if transformer._blocks_quantized(params):
            return ops_q8.dequantize_tree(params)
        return params

    return _live


def _decode_live(dequant):
    """Decode-side weight resolution: {"q8","scale"} trees pass through
    UNTOUCHED (the decode steps dequantize in-scan — pre-dequantizing
    here would rebuild the fp32 stack per token, the 4-byte-read
    regression this path exists to kill); a custom ``dequant`` still
    applies to non-quantized trees."""
    from paddle_tpu.models import transformer

    def _live(params):
        if transformer._blocks_quantized(params):
            return params
        return dequant(params) if dequant is not None else params

    return _live


def _epilogue(mode):
    """The sampling tail of a decode program under the resolved
    ``PADDLE_TPU_PALLAS`` mode: the Pallas ``fused_sample`` kernel
    (greedy/top-k set exact, categorical matching in distribution) when
    the kernels are dispatchable on this backend
    (``decode.kernels_dispatchable`` — "on" off-TPU falls back to
    ``sample_tokens`` with a once-per-mode warning) AND, for ``on``,
    when the cached Mosaic lowering probe
    (``decode.sample_lowering_ok``) accepts the logits shape;
    ``sample_tokens`` otherwise."""
    from paddle_tpu.ops.pallas import decode as _pallas_decode
    if not _pallas_decode.kernels_dispatchable(mode):
        def tail(logits, seed, temperature, top_k):
            key = jax.random.PRNGKey(seed)
            return sample_tokens(logits, key, temperature, top_k)
    else:
        def tail(logits, seed, temperature, top_k):
            if mode == "on" and not _pallas_decode.sample_lowering_ok(
                    logits.shape[0], logits.shape[1]):
                key = jax.random.PRNGKey(seed)
                return sample_tokens(logits, key, temperature, top_k)
            return _pallas_decode.fused_sample(
                logits, seed, temperature, top_k,
                interpret=(mode == "interpret"))
    return tail


def engine_step_fns(cfg, dequant=None, pallas=None):
    """(prefill_fn, decode_fn) closures over a TransformerConfig — the
    two programs the engine compiles (once per prefill bucket, once for
    decode) and ``save_lm_artifact`` exports as the format-v3 modules.

    ``dequant`` optionally maps the stored param tree to live weights
    for PREFILL (the weights_int8 artifact path); the decode step
    consumes {"q8","scale"} trees natively (in-scan dequant — 1-byte
    weight reads per token) and needs no dequant either way.
    ``pallas`` resolves the package-wide ``PADDLE_TPU_PALLAS`` policy
    (explicit arg > env > auto): when the kernels are on, the decode
    sampling tail runs the Pallas ``fused_sample`` epilogue. The slot
    arena's attention itself stays XLA — the flash-decode kernel
    targets the paged pool layout (``paged_step_fns``).

    prefill_fn(params, cache, tokens [1, Tb], length (), slot (),
               temperature (), top_k (), seed ()) → (token (), cache)
    decode_fn(params, cache, tokens [B], pos [B], active [B] bool,
              temperature [B], top_k [B], seed ()) → (tokens [B], cache)

    Sampling happens inside both programs, so each call returns int32
    ids only — no logits cross the host boundary. ``seed`` is a fresh
    per-call int32; any randomness derives inside the program, keeping
    the exported signature plain-integer.
    """
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import policy as _pallas_policy

    mode = _pallas_policy.pallas_mode(pallas)
    _live = _prefill_live(dequant)
    _live_d = _decode_live(dequant)
    tail = _epilogue(mode)

    def prefill_fn(params, cache, tokens, length, slot, temperature,
                   top_k, seed):
        logits, cache = transformer.prefill_into_slot(
            _live(params), cache, tokens, length, slot, cfg)
        key = jax.random.PRNGKey(seed)
        tok = sample_tokens(logits, key, jnp.reshape(temperature, (1,)),
                            jnp.reshape(top_k, (1,)))
        return tok[0], cache

    def decode_fn(params, cache, tokens, pos, active, temperature,
                  top_k, seed):
        logits, cache = transformer.decode_step_slots(
            _live_d(params), cache, tokens, pos, active, cfg)
        return tail(logits, seed, temperature, top_k), cache

    return prefill_fn, decode_fn


def paged_step_fns(cfg, block_size: int, dequant=None, pallas=None):
    """(prefill_chunk_fn, decode_fn) for the PAGED block-pool engine —
    compiled once per chunk bucket / once for decode, and exported by
    ``save_lm_artifact`` as the format-v4 modules.

    prefill_fn(params, pool, tokens [1, C], length (), pages [P],
               temperature (), top_k (), seed ()) → (token (), pool)
    decode_fn(params, pool, tokens [B], pos [B], active [B] bool,
              pages [B, P], temperature [B], top_k [B], seed ())
              → (tokens [B], pool)

    The chunk's context length is implied by the SHAPES: the pages
    vector covers context + chunk, so each (chunk bucket, context
    pages) pair is its own compiled program. Sampling runs inside both:
    the prefill token only matters on a prompt's FINAL chunk (the
    engine discards the others), but sampling unconditionally keeps the
    exported signature uniform.

    ``pallas`` resolves the ``PADDLE_TPU_PALLAS`` policy (explicit arg
    > env > auto): when on, the decode step's attention runs the
    flash-decode kernel over the pool, the chunk prefill runs the
    ``ops/pallas/prefill.py`` pair (chunk attention off the pool +
    span-write kernel), and the sampling tail the fused epilogue; the
    pure-XLA path stays the always-available fallback. ``dequant``
    applies to PREFILL only — decode consumes {"q8","scale"} trees
    natively (in-scan dequant, 1-byte weight reads per token). The
    pool may be QUANTIZED (``init_block_pool(kv_dtype=...)``): both
    step programs detect the layout from the pytree and carry the
    write-time KV quantization + dequantizing reads on every path.
    """
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import policy as _pallas_policy

    mode = _pallas_policy.pallas_mode(pallas)
    _live = _prefill_live(dequant)
    _live_d = _decode_live(dequant)
    tail = _epilogue(mode)

    def prefill_fn(params, pool, tokens, length, pages,
                   temperature, top_k, seed):
        logits, pool = transformer.prefill_into_blocks(
            _live(params), pool, tokens, length, pages, cfg,
            block_size=block_size, pallas=mode)
        key = jax.random.PRNGKey(seed)
        tok = sample_tokens(logits, key, jnp.reshape(temperature, (1,)),
                            jnp.reshape(top_k, (1,)))
        return tok[0], pool

    def decode_fn(params, pool, tokens, pos, active, pages, temperature,
                  top_k, seed):
        logits, pool = transformer.decode_step_paged(
            _live_d(params), pool, tokens, pos, active, pages, cfg,
            block_size=block_size, pallas=mode)
        return tail(logits, seed, temperature, top_k), pool

    return prefill_fn, decode_fn


def _spec_epilogue(mode):
    """The accept/reject sampling tail of a verify program under the
    resolved ``PADDLE_TPU_PALLAS`` mode: the Pallas ``fused_sample``
    kernel per window row + the accept fold
    (``ops.pallas.decode.fused_spec_verify``) when the kernels are
    dispatchable, :func:`spec_verify_tokens` otherwise. Both emit the
    same greedy tokens exactly (the PR-9 fused_sample contract), so the
    spec engine's bitwise-greedy promise holds on either path."""
    from paddle_tpu.ops.pallas import decode as _pallas_decode
    if not _pallas_decode.kernels_dispatchable(mode):
        def tail(logits, draft, seed, temperature, top_k, valid):
            key = jax.random.PRNGKey(seed)
            return spec_verify_tokens(logits, draft, key, temperature,
                                      top_k, valid)
    else:
        def tail(logits, draft, seed, temperature, top_k, valid):
            B, W, V = logits.shape
            if mode == "on" and not _pallas_decode.sample_lowering_ok(
                    B * W, V):
                key = jax.random.PRNGKey(seed)
                return spec_verify_tokens(logits, draft, key,
                                          temperature, top_k, valid)
            return _pallas_decode.fused_spec_verify(
                logits, draft, seed, temperature, top_k, valid,
                interpret=(mode == "interpret"))
    return tail


def paged_spec_fns(cfg, draft_cfg, block_size: int, spec_k: int,
                   dequant=None, pallas=None):
    """The speculative-decoding program set for the paged spec engine —
    the three DRAFT-side programs plus the target VERIFY, compiled next
    to (never instead of) the ``paged_step_fns`` pair. ``spec_k`` fixes
    the proposal depth; the verify window is ``W = spec_k + 1`` rows
    (last accepted token + the k proposals).

    Returns a dict of closures:

    - ``propose(draft_params, draft_pool, last [B], pos [B],
      active [B], valid [B], pages [B, P])`` → (proposals [B, k]
      int32, draft_pool) — k GREEDY draft decode steps fused into one
      program via ``lax.scan`` (one dispatch per engine step, the
      host-overhead half of the spec win; the draft's small weights
      are re-read per scan step, which is what makes a small draft
      the right draft). Scan step j's pool write is masked to
      ``j < valid``: the engine allocates pages only through
      ``pos + valid - 1``, and an unmasked write past that would land
      through the zeroed page-table tail in ANOTHER slot's physical
      block 0 rows of the draft pool. Proposals past the mask are
      garbage and unused (the verify window masks the same rows).
    - ``verify(params, pool, window [B, W], pos [B], valid [B],
      active [B], pages, temperature [B], top_k [B], seed)`` →
      (sampled [B, W], n_emitted [B], pool) — ONE batched W-token pass
      (``transformer.verify_step_paged``) with the accept/reject
      sampling tail fused in; only the small int outputs cross to host.
    - ``draft_verify(draft_params, draft_pool, window [B, W], pos,
      valid, active, pages)`` → draft_pool — the draft-side forced
      window write (no sampling, logits dead-coded): keeps the draft
      pool position-faithful when a preempted request replays known
      tokens, where the propose program's own proposals would diverge
      from the forced history.
    - ``draft_prefill(draft_params, draft_pool, tokens [1, C], length,
      pages [P])`` → draft_pool — the draft's chunk prefill on the SAME
      chunk grid/page vectors as the target's (one draft program per
      (bucket, span) the target compiles — the draft's own program
      set), logits discarded (the sampled first token is the target
      prefill's).

    ``dequant``/``pallas`` follow ``paged_step_fns`` semantics and
    apply to the TARGET side; the draft runs its params as given (pass
    a quantized draft tree for int8 draft weights — decode-side
    consumption is native)."""
    from paddle_tpu.models import transformer
    from paddle_tpu.ops.pallas import policy as _pallas_policy

    mode = _pallas_policy.pallas_mode(pallas)
    _live_d = _decode_live(dequant)
    spec_tail = _spec_epilogue(mode)
    k = int(spec_k)
    if k < 1:
        raise ValueError(f"spec_k must be >= 1, got {spec_k}")

    def propose_fn(draft_params, draft_pool, last, pos, active, valid,
                   pages):
        valid = jnp.asarray(valid, jnp.int32)

        def body(carry, j):
            pool, toks, p = carry
            lg, pool = transformer.decode_step_paged(
                draft_params, pool, toks, p, active & (j < valid),
                pages, draft_cfg, block_size=block_size, pallas=mode)
            nxt = jnp.argmax(lg, -1).astype(jnp.int32)
            return (pool, nxt, p + 1), nxt

        (draft_pool, _, _), props = jax.lax.scan(
            body, (draft_pool, jnp.asarray(last, jnp.int32),
                   jnp.asarray(pos, jnp.int32)),
            jnp.arange(k, dtype=jnp.int32))
        return jnp.transpose(props), draft_pool        # [k, B] -> [B, k]

    def verify_fn(params, pool, window, pos, valid, active, pages,
                  temperature, top_k, seed):
        logits, pool = transformer.verify_step_paged(
            _live_d(params), pool, window, pos, valid, active, pages,
            cfg, block_size=block_size)
        sampled, n = spec_tail(logits, window[:, 1:], seed, temperature,
                               top_k, valid)
        return sampled, n, pool

    def draft_verify_fn(draft_params, draft_pool, window, pos, valid,
                        active, pages):
        _, draft_pool = transformer.verify_step_paged(
            draft_params, draft_pool, window, pos, valid, active,
            pages, draft_cfg, block_size=block_size)
        return draft_pool

    def draft_prefill_fn(draft_params, draft_pool, tokens, length,
                         pages):
        _, draft_pool = transformer.prefill_into_blocks(
            draft_params, draft_pool, tokens, length, pages, draft_cfg,
            block_size=block_size, pallas=mode)
        return draft_pool

    return {"propose": propose_fn, "verify": verify_fn,
            "draft_verify": draft_verify_fn,
            "draft_prefill": draft_prefill_fn}
