"""On-device token sampling for the continuous-batching decode engine.

The legacy LMServer samples host-side: every decoded token ships the
full ``[B, vocab]`` logits to numpy and loops ``RandomState.choice`` per
row — exactly the host/device sync PAPERS' non-GPU-inference field study
(arxiv 2607.08215) names as the decode-loop throughput killer. Here the
sampler is a pure jnp function that runs INSIDE the compiled decode
step, so only the sampled ids ``[B] int32`` ever cross to the host.

Per-slot controls are runtime vectors (static shapes, one compile):

- ``temperature`` [B] float32 — ``<= 0`` means greedy argmax for that
  row; the categorical draw still happens but is discarded by a
  ``where``, keeping the program shape-identical for any mix.
- ``top_k`` [B] int32 — ``<= 0`` (or ``>= vocab``) disables filtering.
  A runtime k can't use ``lax.top_k`` (static k), so the row is sorted
  once and everything below the k-th value is masked to ``-inf``; ties
  at the threshold survive, matching the usual top-k convention.
"""

import jax
import jax.numpy as jnp


def sample_tokens(logits: jax.Array, key: jax.Array,
                  temperature: jax.Array, top_k: jax.Array) -> jax.Array:
    """logits [B, V] fp32, per-slot temperature [B] / top_k [B] →
    sampled ids [B] int32 (greedy rows use argmax, first-index ties —
    the same convention as the host-side legacy path)."""
    V = logits.shape[-1]
    logits = logits.astype(jnp.float32)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    k = jnp.clip(top_k.astype(jnp.int32), 0, V)
    srt = jnp.sort(logits, axis=-1)[:, ::-1]          # descending
    kth = jnp.take_along_axis(srt, jnp.maximum(k - 1, 0)[:, None],
                              axis=-1)                # [B, 1]
    keep = (k[:, None] <= 0) | (logits >= kth)
    z = jnp.where(keep, logits, -jnp.inf)
    t = jnp.where(temperature > 0, temperature, 1.0)  # div-safe for
    z = z / t[:, None].astype(jnp.float32)            # greedy rows
    sampled = jax.random.categorical(key, z, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy)


def engine_step_fns(cfg, dequant=None):
    """(prefill_fn, decode_fn) closures over a TransformerConfig — the
    two programs the engine compiles (once per prefill bucket, once for
    decode) and ``save_lm_artifact`` exports as the format-v3 modules.

    ``dequant`` optionally maps the stored param tree to live weights
    (the weights_int8 artifact path); identity when None.

    prefill_fn(params, cache, tokens [1, Tb], length (), slot (),
               temperature (), top_k (), seed ()) → (token (), cache)
    decode_fn(params, cache, tokens [B], pos [B], active [B] bool,
              temperature [B], top_k [B], seed ()) → (tokens [B], cache)

    Sampling happens inside both programs (``sample_tokens``), so each
    call returns int32 ids only — no logits cross the host boundary.
    ``seed`` is a fresh per-call int32; the key derives inside the
    program, keeping the exported signature plain-integer.
    """
    from paddle_tpu.models import transformer

    def _live(params):
        return dequant(params) if dequant is not None else params

    def prefill_fn(params, cache, tokens, length, slot, temperature,
                   top_k, seed):
        logits, cache = transformer.prefill_into_slot(
            _live(params), cache, tokens, length, slot, cfg)
        key = jax.random.PRNGKey(seed)
        tok = sample_tokens(logits, key, jnp.reshape(temperature, (1,)),
                            jnp.reshape(top_k, (1,)))
        return tok[0], cache

    def decode_fn(params, cache, tokens, pos, active, temperature,
                  top_k, seed):
        logits, cache = transformer.decode_step_slots(
            _live(params), cache, tokens, pos, active, cfg)
        key = jax.random.PRNGKey(seed)
        return sample_tokens(logits, key, temperature, top_k), cache

    return prefill_fn, decode_fn


def paged_step_fns(cfg, block_size: int, dequant=None):
    """(prefill_chunk_fn, decode_fn) for the PAGED block-pool engine —
    compiled once per chunk bucket / once for decode, and exported by
    ``save_lm_artifact`` as the format-v4 modules.

    prefill_fn(params, pool, tokens [1, C], length (), pages [P],
               temperature (), top_k (), seed ()) → (token (), pool)
    decode_fn(params, pool, tokens [B], pos [B], active [B] bool,
              pages [B, P], temperature [B], top_k [B], seed ())
              → (tokens [B], pool)

    The chunk's context length is implied by the SHAPES: the pages
    vector covers context + chunk, so each (chunk bucket, context
    pages) pair is its own compiled program. Sampling runs inside both:
    the prefill token only matters on a prompt's FINAL chunk (the
    engine discards the others), but sampling unconditionally keeps the
    exported signature uniform.
    """
    from paddle_tpu.models import transformer

    def _live(params):
        return dequant(params) if dequant is not None else params

    def prefill_fn(params, pool, tokens, length, pages,
                   temperature, top_k, seed):
        logits, pool = transformer.prefill_into_blocks(
            _live(params), pool, tokens, length, pages, cfg,
            block_size=block_size)
        key = jax.random.PRNGKey(seed)
        tok = sample_tokens(logits, key, jnp.reshape(temperature, (1,)),
                            jnp.reshape(top_k, (1,)))
        return tok[0], pool

    def decode_fn(params, pool, tokens, pos, active, pages, temperature,
                  top_k, seed):
        logits, pool = transformer.decode_step_paged(
            _live(params), pool, tokens, pos, active, pages, cfg,
            block_size=block_size)
        key = jax.random.PRNGKey(seed)
        return sample_tokens(logits, key, temperature, top_k), pool

    return prefill_fn, decode_fn
