"""Continuous-batching LM serving (slot-based KV arena + scheduler).

Public surface:

- :class:`~paddle_tpu.serving.engine.DecodeEngine` — the scheduler
  (FIFO admission, slot recycling, bucketed prefill, on-device
  sampling); build via ``DecodeEngine.from_params`` or a format-v3
  artifact's ``LMServer.engine()``.
- :class:`~paddle_tpu.serving.engine.EngineRequest` — per-request
  lifecycle record (tokens, TTFT, latency, finish reason).
- :func:`~paddle_tpu.serving.sampling.sample_tokens` /
  :func:`~paddle_tpu.serving.sampling.engine_step_fns` — the pure step
  programs (greedy / temperature / top-k inside the compiled step).
"""

from paddle_tpu.serving.engine import (  # noqa: F401
    DEFAULT_PREFILL_BUCKETS, DecodeEngine, EngineRequest)
from paddle_tpu.serving.sampling import (  # noqa: F401
    engine_step_fns, sample_tokens)
