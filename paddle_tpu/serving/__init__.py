"""Continuous-batching LM serving (paged block-table KV + scheduler).

Public surface:

- :class:`~paddle_tpu.serving.engine.PagedDecodeEngine` — the paged
  engine (block-pool KV, chunked prefill interleaved with decode,
  content-hash prefix cache with refcounted blocks + LRU eviction);
  build via ``PagedDecodeEngine.from_params`` or a format-v4
  artifact's ``LMServer.engine()``.
- :class:`~paddle_tpu.serving.engine.DecodeEngine` — the legacy
  row-per-request arena engine (FIFO admission, slot recycling,
  bucketed whole-prompt prefill); format-v3 artifacts load here.
- :class:`~paddle_tpu.serving.engine.EngineRequest` — per-request
  lifecycle record (tokens, TTFT, latency, finish reason,
  prefix_hit_tokens).
- :class:`~paddle_tpu.serving.blocks.BlockPool` — host-side block
  allocator / prefix cache the paged engine schedules over.
- :class:`~paddle_tpu.serving.tiers.TieredStore` — the host-side
  spill tiers behind the HBM block pool (bounded DRAM arena over a
  bounded, checksummed disk directory); LRU-evicted prefix blocks
  demote into it and re-admit bitwise through the import path.
- :class:`~paddle_tpu.serving.router.Router` — the serving-fleet tier:
  prefix-aware placement over N replicas (content-chain block hashes
  as the routing key), three-state-health-driven drain with
  dead-replica requeue, and prefill/decode disaggregation over the
  ``serving/transfer.py`` KV-block wire.
- :class:`~paddle_tpu.serving.replica.EngineReplica` /
  :class:`~paddle_tpu.serving.replica.SocketReplica` /
  :class:`~paddle_tpu.serving.replica.ReplicaServer` /
  :func:`~paddle_tpu.serving.replica.serve_stdio` — the replica
  handles and JSONL transports (stdio with graceful SIGTERM drain,
  TCP for multi-process fleets) the router fronts.
- :func:`~paddle_tpu.serving.sampling.sample_tokens` /
  :func:`~paddle_tpu.serving.sampling.engine_step_fns` /
  :func:`~paddle_tpu.serving.sampling.paged_step_fns` — the pure step
  programs (greedy / temperature / top-k inside the compiled step).
"""

from paddle_tpu.serving.blocks import (  # noqa: F401
    BlockPool, chain_hash, prompt_block_hashes)
from paddle_tpu.serving.engine import (  # noqa: F401
    DEFAULT_PREFILL_BUCKETS, VALID_TIERS, DecodeEngine, EngineRequest,
    PagedDecodeEngine, SpecDecodeEngine, default_chunk_buckets)
from paddle_tpu.serving.replica import (  # noqa: F401
    EngineLoop, EngineReplica, ReplicaServer, SocketReplica,
    serve_stdio)
from paddle_tpu.serving.router import (  # noqa: F401
    Router, RouterRequest)
from paddle_tpu.serving.tiers import (  # noqa: F401
    TieredStore)
from paddle_tpu.serving.sampling import (  # noqa: F401
    engine_step_fns, paged_spec_fns, paged_step_fns, sample_tokens,
    spec_accept, spec_verify_tokens)
