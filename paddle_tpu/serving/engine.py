"""Continuous-batching LM decode engine: slot scheduler over a KV arena.

The lockstep serving surface (``io/lm_serving.LMServer.generate``)
forces every request into one fixed-shape batch: shared prompt length,
shared step count, host-side sampling. This engine replaces batch
formation with SLOTS: the KV cache is a ``[L, B, cache_len, Hkv, Dh]``
arena whose B rows are leased to requests independently. A request

1. queues (FIFO) until a slot frees,
2. prefills into its slot via ``transformer.prefill_into_slot`` — the
   prompt is right-padded to a bucket length (``core/ragged`` buckets),
   so the engine compiles at most once per bucket,
3. decodes in the shared per-slot-position step
   (``transformer.decode_step_slots`` + on-device sampling) alongside
   whatever else is in flight, each row at its own position,
4. terminates on EOS / max_new and releases the slot to the next
   queued request — mid-flight, no other row perturbed.

Every shape is static: one compile per prefill bucket + ONE for decode,
verified by the observe compile tracker under the names
``serving_engine.prefill`` / ``serving_engine.decode``.

The host loop only ever moves ``[B] int32`` token ids off device (the
sampler runs inside the step); scheduling state (positions, active
mask, per-slot temperature/top_k) lives in numpy and is re-uploaded as
tiny vectors per step.

Observability: each engine carries its own metrics ``Registry`` —
queue-wait and time-to-first-token histograms, slot-occupancy and
queue-depth gauges, token/step counters, per-request goodput — and
``serve()`` exposes them on the standard ``/metrics`` + ``/healthz``
endpoints (``observe/health.py``).
"""

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.observe import compile_tracker as _ct
from paddle_tpu.observe import metrics as _metrics

# prefill buckets: small powers of two keep compile count tiny while
# wasting at most ~2x padded prefill compute on a mixed workload
DEFAULT_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512)

# decode steps run single-digit ms; prefill tens-to-hundreds (matches
# io/lm_serving's serving-latency resolution)
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
_GOODPUT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    500.0, 1000.0, 2500.0, 5000.0, 10000.0)


@dataclasses.dataclass
class EngineRequest:
    """One generation request and its lifecycle record."""
    rid: int
    prompt: np.ndarray                  # [Tp] int32
    max_new: int
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    # -- lifecycle (filled by the engine) --------------------------------
    bucket: int = 0
    slot: int = -1
    tokens: List[int] = dataclasses.field(default_factory=list)
    status: str = "queued"              # queued | running | done
    finish_reason: Optional[str] = None  # eos | max_tokens
    submit_t: float = 0.0
    prefill_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None

    @property
    def output(self) -> np.ndarray:
        """prompt + generated ids, the ``generate()``-shaped result."""
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def latency_s(self) -> Optional[float]:
        if self.finish_t is None:
            return None
        return self.finish_t - self.submit_t


class DecodeEngine:
    """Slot-based continuous-batching scheduler over compiled step fns.

    ``prefill`` / ``decode`` follow the ``sampling.engine_step_fns``
    signatures (params threaded explicitly, cache functional). Build one
    with :meth:`from_params` (in-process jit) or
    :meth:`io.lm_serving.LMServer.engine` (format-v3 AOT artifact).
    """

    def __init__(self, prefill: Callable, decode: Callable, params, cache,
                 *, batch: int, cache_len: int,
                 buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
                 seed: Optional[int] = None,
                 registry: Optional[_metrics.Registry] = None,
                 tracker: Optional[_ct.CompileTracker] = None):
        import jax.numpy as jnp
        self._jnp = jnp
        self._prefill_fn = prefill
        self._decode_fn = decode
        self.params = params
        self.cache = cache
        self.batch = int(batch)
        self.cache_len = int(cache_len)
        self.buckets = tuple(sorted({int(b) for b in buckets
                                     if int(b) <= cache_len}))
        if not self.buckets:
            raise ValueError(f"no prefill bucket fits cache_len="
                             f"{cache_len} (buckets={tuple(buckets)})")
        # engine-level "unseeded must not repeat": like the LMServer fix,
        # None draws fresh OS entropy instead of collapsing to a constant
        self._rng = np.random.RandomState(seed)
        # per-engine tracker by default: a shared (global) tracker would
        # have seen another engine's signatures already and mis-credit /
        # swallow this engine's real compiles in compile_counts()
        self._tracker = tracker or _ct.CompileTracker()
        # -- host-side slot state (uploaded as [B] vectors per step) -----
        B = self.batch
        self._pos = np.zeros(B, np.int32)
        self._active = np.zeros(B, bool)
        self._last = np.zeros(B, np.int32)
        self._temp = np.zeros(B, np.float32)
        self._topk = np.zeros(B, np.int32)
        self._slot_req: List[Optional[EngineRequest]] = [None] * B
        self._free = deque(range(B))
        self._queue: deque = deque()
        self._ids = itertools.count()
        # -- metrics ------------------------------------------------------
        reg = self.metrics = registry or _metrics.Registry()
        self._m_requests = reg.counter(
            "engine_requests_total", "requests submitted")
        self._m_completed = reg.counter(
            "engine_requests_completed_total",
            "requests finished, by termination reason")
        self._m_tokens = reg.counter(
            "engine_tokens_total", "tokens emitted across all requests")
        self._m_steps = reg.counter(
            "engine_decode_steps_total", "batched decode steps executed")
        self._m_prefills = reg.counter(
            "engine_prefill_calls_total", "slot prefills executed")
        self._m_queue = reg.gauge(
            "engine_queue_depth", "requests waiting for a slot")
        self._m_occupancy = reg.gauge(
            "engine_slots_active", "arena slots currently decoding")
        self._m_wait_s = reg.histogram(
            "engine_queue_wait_seconds", "submit -> prefill-start wait",
            buckets=_LATENCY_BUCKETS)
        self._m_ttft_s = reg.histogram(
            "engine_ttft_seconds", "submit -> first token (queue wait + "
            "prefill)", buckets=_LATENCY_BUCKETS)
        self._m_prefill_s = reg.histogram(
            "engine_prefill_seconds", "slot-prefill device latency",
            buckets=_LATENCY_BUCKETS)
        self._m_step_s = reg.histogram(
            "engine_decode_step_seconds", "batched decode-step latency "
            "(device call + [B]-ids host sync)", buckets=_LATENCY_BUCKETS)
        self._m_goodput = reg.histogram(
            "engine_request_tokens_per_sec", "per-request goodput: "
            "tokens emitted / (finish - submit)",
            buckets=_GOODPUT_BUCKETS)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_params(cls, params, cfg, *, batch: int, cache_len: int,
                    buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
                    seed: Optional[int] = None, **kw):
        """In-process engine: jit the step fns against live params (the
        no-artifact path tests and benchmarks drive)."""
        import jax
        from paddle_tpu.models import transformer
        from paddle_tpu.serving import sampling
        if cache_len > cfg.max_len:
            raise ValueError(f"cache_len {cache_len} exceeds cfg.max_len "
                             f"{cfg.max_len}")
        prefill_fn, decode_fn = sampling.engine_step_fns(cfg)
        cache = transformer.init_cache(cfg, batch, cache_len)
        return cls(jax.jit(prefill_fn), jax.jit(decode_fn), params, cache,
                   batch=batch, cache_len=cache_len, buckets=buckets,
                   seed=seed, **kw)

    # -- request API -------------------------------------------------------
    def submit(self, prompt, max_new: int, *, temperature: float = 0.0,
               top_k: int = 0, eos_id: Optional[int] = None
               ) -> EngineRequest:
        """Queue one request; returns its (live) EngineRequest record."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size < 1:
            raise ValueError("submit: empty prompt")
        if max_new < 1:
            raise ValueError(f"submit: max_new must be >= 1, "
                             f"got {max_new}")
        from paddle_tpu.core import ragged
        if prompt.size > self.buckets[-1]:
            # beyond the largest bucket there is no compiled prefill
            # program (AOT artifacts ship exactly one per bucket)
            raise ValueError(
                f"submit: prompt length {prompt.size} exceeds the "
                f"largest prefill bucket {self.buckets[-1]}")
        bucket = ragged.bucket_length(prompt.size, self.buckets)
        if prompt.size + max_new > self.cache_len:
            raise ValueError(
                f"submit: {prompt.size} prompt + {max_new} new tokens "
                f"exceed cache_len {self.cache_len}")
        req = EngineRequest(
            rid=next(self._ids), prompt=prompt, max_new=int(max_new),
            temperature=float(temperature), top_k=int(top_k),
            eos_id=eos_id, bucket=bucket, submit_t=time.perf_counter())
        self._queue.append(req)
        self._m_requests.inc()
        self._m_queue.set(len(self._queue))
        return req

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def idle(self) -> bool:
        return not self._queue and not self._active.any()

    # -- scheduler ---------------------------------------------------------
    def _seed(self) -> np.int32:
        return np.int32(self._rng.randint(0, 2 ** 31 - 1))

    def _finish(self, req: EngineRequest, reason: str, now: float):
        req.status, req.finish_reason, req.finish_t = "done", reason, now
        self._m_completed.inc(reason=reason)
        if req.latency_s and req.latency_s > 0:
            self._m_goodput.observe(len(req.tokens) / req.latency_s)
        slot = req.slot
        if slot >= 0:
            self._active[slot] = False
            self._slot_req[slot] = None
            self._free.append(slot)

    def _emit(self, req: EngineRequest, tok: int, now: float) -> bool:
        """Record one emitted token; True when the request finished."""
        req.tokens.append(int(tok))
        self._m_tokens.inc()
        if req.first_token_t is None:
            req.first_token_t = now
            self._m_ttft_s.observe(now - req.submit_t)
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(req, "eos", now)
            return True
        if len(req.tokens) >= req.max_new:
            self._finish(req, "max_tokens", now)
            return True
        return False

    def _admit(self, finished: List[EngineRequest]):
        jnp = self._jnp
        while self._queue and self._free:
            req = self._queue.popleft()
            slot = self._free.popleft()
            now = time.perf_counter()
            req.prefill_t = now
            self._m_wait_s.observe(now - req.submit_t)
            padded = np.zeros((1, req.bucket), np.int32)
            padded[0, :req.prompt.size] = req.prompt
            t0 = time.perf_counter()
            tok, self.cache = self._tracker.track_call(
                "serving_engine.prefill", self._prefill_fn,
                self.params, self.cache, jnp.asarray(padded),
                np.int32(req.prompt.size), np.int32(slot),
                np.float32(req.temperature), np.int32(req.top_k),
                self._seed())
            tok = int(np.asarray(tok))
            now = time.perf_counter()
            self._m_prefill_s.observe(now - t0)
            self._m_prefills.inc()
            req.slot, req.status = slot, "running"
            self._slot_req[slot] = req
            if self._emit(req, tok, now):
                finished.append(req)    # one-token request: slot already
                continue                # recycled by _finish
            self._active[slot] = True
            self._pos[slot] = req.prompt.size
            self._last[slot] = tok
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
        self._m_queue.set(len(self._queue))

    def step(self) -> List[EngineRequest]:
        """One scheduler iteration: admit waiting requests into free
        slots, run one batched decode step for everything in flight.
        Returns the requests that finished during this step."""
        finished: List[EngineRequest] = []
        self._admit(finished)
        if self._active.any():
            jnp = self._jnp
            t0 = time.perf_counter()
            nxt, self.cache = self._tracker.track_call(
                "serving_engine.decode", self._decode_fn,
                self.params, self.cache, jnp.asarray(self._last),
                jnp.asarray(self._pos), jnp.asarray(self._active),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                self._seed())
            nxt = np.asarray(nxt)       # the only device->host transfer:
            now = time.perf_counter()   # [B] int32 ids
            self._m_step_s.observe(now - t0)
            self._m_steps.inc()
            for slot in np.flatnonzero(self._active):
                req = self._slot_req[slot]
                tok = int(nxt[slot])
                self._pos[slot] += 1
                self._last[slot] = tok
                if self._emit(req, tok, now):
                    finished.append(req)
        self._m_occupancy.set(self.active_count)
        return finished

    def run_until_idle(self, max_steps: int = 100_000
                       ) -> List[EngineRequest]:
        """Drive ``step()`` until queue and arena drain; returns every
        request finished along the way (submission order not guaranteed
        — requests terminate independently)."""
        done: List[EngineRequest] = []
        for _ in range(max_steps):
            if self.idle:
                return done
            done.extend(self.step())
        raise RuntimeError(f"engine did not drain in {max_steps} steps "
                           f"({self.queue_depth} queued, "
                           f"{self.active_count} active)")

    # -- observability -----------------------------------------------------
    def health(self) -> dict:
        return {"requests": int(self._m_requests.value()),
                "completed": sum(
                    int(self._m_completed.value(reason=r))
                    for r in ("eos", "max_tokens")),
                "tokens": int(self._m_tokens.value()),
                "decode_steps": int(self._m_steps.value()),
                "queue_depth": self.queue_depth,
                "slots_active": self.active_count,
                "slots_total": self.batch,
                "cache_len": self.cache_len,
                "prefill_buckets": list(self.buckets)}

    def metrics_text(self) -> str:
        return self.metrics.render_prometheus()

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """/metrics + /healthz over this engine's registry; caller owns
        ``close()``."""
        from paddle_tpu.observe.health import HealthServer
        return HealthServer(registry=self.metrics, health_fn=self.health,
                            host=host, port=port)

    def compile_counts(self) -> Dict[str, int]:
        """Compilations the tracker charged to this engine's two
        programs — the "one per bucket + one for decode" invariant."""
        return {"prefill": self._tracker.count("serving_engine.prefill"),
                "decode": self._tracker.count("serving_engine.decode")}
