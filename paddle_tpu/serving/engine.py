"""Continuous-batching LM decode engine: slot scheduler over a KV arena.

The lockstep serving surface (``io/lm_serving.LMServer.generate``)
forces every request into one fixed-shape batch: shared prompt length,
shared step count, host-side sampling. This engine replaces batch
formation with SLOTS: the KV cache is a ``[L, B, cache_len, Hkv, Dh]``
arena whose B rows are leased to requests independently. A request

1. queues (FIFO) until a slot frees,
2. prefills into its slot via ``transformer.prefill_into_slot`` — the
   prompt is right-padded to a bucket length (``core/ragged`` buckets),
   so the engine compiles at most once per bucket,
3. decodes in the shared per-slot-position step
   (``transformer.decode_step_slots`` + on-device sampling) alongside
   whatever else is in flight, each row at its own position,
4. terminates on EOS / max_new and releases the slot to the next
   queued request — mid-flight, no other row perturbed.

Every shape is static: one compile per prefill bucket + ONE for decode,
verified by the observe compile tracker under the names
``serving_engine.prefill`` / ``serving_engine.decode``.

The host loop only ever moves ``[B] int32`` token ids off device (the
sampler runs inside the step); scheduling state (positions, active
mask, per-slot temperature/top_k) lives in numpy and is re-uploaded as
tiny vectors per step.

Observability: each engine carries its own metrics ``Registry`` —
queue-wait and time-to-first-token histograms, slot-occupancy and
queue-depth gauges, token/step counters, per-request goodput — and
``serve()`` exposes them on the standard ``/metrics`` + ``/healthz``
endpoints (``observe/health.py``).

:class:`PagedDecodeEngine` supersedes the row-per-request arena with a
block-table KV layout (paged pool + per-slot page vectors, chunked
prefill interleaved with decode, content-hash prefix cache with
refcounted blocks and LRU eviction) and carries the multi-tenant
scheduler: latency/batch tiers with strict-priority admission,
per-tenant token budgets (exhaustion queues, never rejects), and
preempt-to-blocks — a batch-tier victim's pages re-publish into the
prefix cache so resume is either a pure host re-mapping or a
cache-hit chunked prefill, bitwise either way.
:class:`SpecDecodeEngine` adds speculative decoding on top (draft
model sharing the block table, fused k-step propose, batched-window
verify bitwise the decode step). :class:`DecodeEngine` remains the
legacy whole-row engine that format-v3 artifacts load into.
"""

import dataclasses
import itertools
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from paddle_tpu.observe import chrome_trace as _chrome
from paddle_tpu.observe import compile_tracker as _ct
from paddle_tpu.observe import costs as _costs
from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.observe import requests as _requests
from paddle_tpu.observe.window import SloConfig, WindowedQuantiles

# per-process engine instance counter: bakes into request trace ids
# (``eng<N>.r<rid>``) so several engines' lifecycle events never
# collide in one exported timeline
_ENGINE_IDS = itertools.count()

# prefill buckets: small powers of two keep compile count tiny while
# wasting at most ~2x padded prefill compute on a mixed workload
DEFAULT_PREFILL_BUCKETS = (16, 32, 64, 128, 256, 512)

# decode steps run single-digit ms; prefill tens-to-hundreds (matches
# io/lm_serving's serving-latency resolution)
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0)
_GOODPUT_BUCKETS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                    500.0, 1000.0, 2500.0, 5000.0, 10000.0)


# the two scheduling tiers: "latency" admits ahead of "batch" and may
# preempt a batch-tier victim's blocks; "batch" fills whatever capacity
# latency traffic leaves (and is the only tier preemption may evict)
VALID_TIERS = ("latency", "batch")


@dataclasses.dataclass
class EngineRequest:
    """One generation request and its lifecycle record."""
    rid: int
    prompt: np.ndarray                  # [Tp] int32
    max_new: int
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    tenant: str = "default"             # token-budget accounting key
    tier: str = "batch"                 # latency | batch (VALID_TIERS)
    # -- lifecycle (filled by the engine) --------------------------------
    bucket: int = 0
    slot: int = -1
    prefix_hit_tokens: int = 0          # prompt tokens served from the
    #                                     prefix cache (paged engine)
    block_hashes: Optional[List[bytes]] = None  # prompt block digests,
    #                                     memoized at first admission try
    tier_promote_done: bool = False     # spill-tier promotion attempted
    #                                     (once per request: a blocked
    #                                     queue head re-enters admission
    #                                     every step)
    tier_promoted_blocks: int = 0       # blocks that promotion just
    #                                     re-adopted for this request —
    #                                     admission labels them dram/
    #                                     disk hits, not hbm
    tokens: List[int] = dataclasses.field(default_factory=list)
    status: str = "queued"              # queued | prefilling (paged,
    #                                     mid-chunk) | running | done
    finish_reason: Optional[str] = None  # eos | max_tokens
    submit_t: float = 0.0
    prefill_t: Optional[float] = None
    first_token_t: Optional[float] = None
    finish_t: Optional[float] = None
    prefill_own_s: float = 0.0          # device time of this request's
    #                                     OWN prefill chunk(s)
    trace_id: str = ""                  # eng<N>.r<rid>: joins this
    #                                     request's lifecycle events
    decode_open: bool = False           # a "decode" trace slice is open
    preemptions: int = 0                # times preempted to blocks
    # preempt-to-blocks resume state (paged engine): the host snapshot
    # taken at preemption (block-chain digests + decode cursor), and —
    # on the eviction-fallback path — the already-emitted tokens the
    # replay force-feeds through the decode program without re-emitting
    snapshot: Optional[dict] = dataclasses.field(
        default=None, repr=False)
    replay: Optional[List[int]] = dataclasses.field(
        default=None, repr=False)

    @property
    def output(self) -> np.ndarray:
        """prompt + generated ids, the ``generate()``-shaped result."""
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])

    @property
    def ttft_s(self) -> Optional[float]:
        if self.first_token_t is None:
            return None
        return self.first_token_t - self.submit_t

    @property
    def latency_s(self) -> Optional[float]:
        if self.finish_t is None:
            return None
        return self.finish_t - self.submit_t

    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.prefill_t is None:
            return None
        return self.prefill_t - self.submit_t

    @property
    def prefill_stall_s(self) -> Optional[float]:
        """Admitted -> first token, minus own prefill device time:
        time parked behind OTHER requests' chunks and the decode steps
        interleaved between them (near 0 on the row-arena engine,
        whose prefill is monolithic)."""
        if self.first_token_t is None or self.prefill_t is None:
            return None
        return max(self.first_token_t - self.prefill_t
                   - self.prefill_own_s, 0.0)

    @property
    def decode_s(self) -> Optional[float]:
        if self.finish_t is None or self.first_token_t is None:
            return None
        return self.finish_t - self.first_token_t

    @property
    def cache_hit_frac(self) -> float:
        """Fraction of the prompt served from the prefix cache."""
        return self.prefix_hit_tokens / max(int(self.prompt.size), 1)


class DecodeEngine:
    """Slot-based continuous-batching scheduler over compiled step fns.

    ``prefill`` / ``decode`` follow the ``sampling.engine_step_fns``
    signatures (params threaded explicitly, cache functional). Build one
    with :meth:`from_params` (in-process jit) or
    :meth:`io.lm_serving.LMServer.engine` (format-v3 AOT artifact).
    """

    def __init__(self, prefill: Callable, decode: Callable, params, cache,
                 *, batch: int, cache_len: int,
                 buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
                 seed: Optional[int] = None,
                 registry: Optional[_metrics.Registry] = None,
                 tracker: Optional[_ct.CompileTracker] = None,
                 slo: Optional[SloConfig] = None,
                 decode_flops: Optional[float] = None,
                 pallas_mode: Optional[str] = None):
        import jax.numpy as jnp
        self._jnp = jnp
        self._prefill_fn = prefill
        self._decode_fn = decode
        self.params = params
        self.cache = cache
        self.batch = int(batch)
        self.cache_len = int(cache_len)
        # decode-MFU accounting (the PR-2 scoreboard): model FLOPs of
        # one compiled decode step (from lowered cost analysis or the
        # artifact's cost stamp) against the declared chip peak
        self.decode_flops = decode_flops
        self._peak_flops = _costs.device_peak_flops()
        # which attention/sampling path the decode program compiled
        # (resolved PADDLE_TPU_PALLAS policy; None = unknown/legacy)
        self.pallas_mode = pallas_mode
        self.buckets = tuple(sorted({int(b) for b in buckets
                                     if int(b) <= cache_len}))
        if not self.buckets:
            raise ValueError(f"no prefill bucket fits cache_len="
                             f"{cache_len} (buckets={tuple(buckets)})")
        # engine-level "unseeded must not repeat": like the LMServer fix,
        # None draws fresh OS entropy instead of collapsing to a constant
        self._rng = np.random.RandomState(seed)
        # per-engine tracker by default: a shared (global) tracker would
        # have seen another engine's signatures already and mis-credit /
        # swallow this engine's real compiles in compile_counts()
        self._tracker = tracker or _ct.CompileTracker()
        # -- host-side slot state (uploaded as [B] vectors per step) -----
        B = self.batch
        self._pos = np.zeros(B, np.int32)
        self._active = np.zeros(B, bool)
        self._last = np.zeros(B, np.int32)
        self._temp = np.zeros(B, np.float32)
        self._topk = np.zeros(B, np.int32)
        self._slot_req: List[Optional[EngineRequest]] = [None] * B
        self._free = deque(range(B))
        self._queue: deque = deque()
        self._ids = itertools.count()
        # -- request-scoped observability --------------------------------
        self._engine_id = next(_ENGINE_IDS)
        # perf_counter -> wall-clock anchor: lifecycle events must land
        # on the same epoch timeline as the trace-scope spans, but the
        # engine's internal timestamps stay monotonic perf_counter
        self._wall_anchor = time.time() - time.perf_counter()
        self.request_log = _requests.RequestLog()
        self.slo: Optional[SloConfig] = None
        self._win_ttft: WindowedQuantiles = None  # set by configure_slo
        self._win_tps: WindowedQuantiles = None
        self.configure_slo(slo)
        # -- metrics ------------------------------------------------------
        reg = self.metrics = registry or _metrics.Registry()
        self._m_requests = reg.counter(
            "engine_requests_total", "requests submitted")
        self._m_completed = reg.counter(
            "engine_requests_completed_total",
            "requests finished, by termination reason")
        self._m_tokens = reg.counter(
            "engine_tokens_total", "tokens emitted across all requests")
        self._m_steps = reg.counter(
            "engine_decode_steps_total", "batched decode steps executed")
        self._m_prefills = reg.counter(
            "engine_prefill_calls_total", "slot prefills executed")
        self._m_queue = reg.gauge(
            "engine_queue_depth", "requests waiting for a slot")
        self._m_occupancy = reg.gauge(
            "engine_slots_active", "arena slots currently decoding")
        self._m_wait_s = reg.histogram(
            "engine_queue_wait_seconds", "submit -> prefill-start wait",
            buckets=_LATENCY_BUCKETS)
        self._m_ttft_s = reg.histogram(
            "engine_ttft_seconds", "submit -> first token (queue wait + "
            "prefill)", buckets=_LATENCY_BUCKETS)
        self._m_prefill_s = reg.histogram(
            "engine_prefill_seconds", "slot-prefill device latency",
            buckets=_LATENCY_BUCKETS)
        self._m_step_s = reg.histogram(
            "engine_decode_step_seconds", "batched decode-step latency "
            "(device call + [B]-ids host sync)", buckets=_LATENCY_BUCKETS)
        self._m_goodput = reg.histogram(
            "engine_request_tokens_per_sec", "per-request goodput: "
            "tokens emitted / (finish - submit)",
            buckets=_GOODPUT_BUCKETS)
        self._m_win_ttft = reg.gauge(
            "engine_ttft_window_seconds", "rolling TTFT quantile over "
            "the SLO window (label q = p50|p95|p99) — the cumulative "
            "histogram cannot answer this once traffic has history")
        self._m_win_tps = reg.gauge(
            "engine_tokens_per_sec_window", "rolling per-request "
            "goodput quantile over the SLO window (label q)")
        self._m_burn = reg.gauge(
            "engine_slo_burn_rate", "TTFT SLO burn rate: windowed "
            "violation fraction / error budget (0 without a "
            "configured SLO)")
        self._m_rejected = reg.counter(
            "engine_requests_rejected_total",
            "submissions rejected at validation, by reason")
        self._m_decode_mfu = reg.gauge(
            "engine_decode_mfu", "model-FLOPs utilisation of the last "
            "batched decode step (0 until decode FLOPs and a chip peak "
            "are known; CPU peaks are nominal — see core/place.py)")

    # -- construction ------------------------------------------------------
    @classmethod
    def from_params(cls, params, cfg, *, batch: int, cache_len: int,
                    buckets: Sequence[int] = DEFAULT_PREFILL_BUCKETS,
                    seed: Optional[int] = None, pallas: Optional[str] = None,
                    **kw):
        """In-process engine: jit the step fns against live params (the
        no-artifact path tests and benchmarks drive). ``pallas``
        overrides the ``PADDLE_TPU_PALLAS`` policy for the step
        programs (fused sampling epilogue on the slot engine)."""
        import jax
        from paddle_tpu.models import transformer
        from paddle_tpu.ops.pallas import policy as _pallas_policy
        from paddle_tpu.serving import sampling
        if cache_len > cfg.max_len:
            raise ValueError(f"cache_len {cache_len} exceeds cfg.max_len "
                             f"{cfg.max_len}")
        prefill_fn, decode_fn = sampling.engine_step_fns(cfg, pallas=pallas)
        cache = transformer.init_cache(cfg, batch, cache_len)
        jdf = jax.jit(decode_fn)
        if "decode_flops" not in kw:    # the trace is not free — skip
            kw["decode_flops"] = _decode_step_flops(  # it when supplied
                jdf, params, cache, batch)
        return cls(jax.jit(prefill_fn), jdf, params, cache,
                   batch=batch, cache_len=cache_len, buckets=buckets,
                   seed=seed, pallas_mode=_pallas_policy.pallas_mode(pallas),
                   **kw)

    # -- request-scoped observability --------------------------------------
    def configure_slo(self, slo: Optional[SloConfig]):
        """Install (or with ``None`` clear) the TTFT SLO this engine's
        `/healthz` evaluates over its rolling window. Resets the window
        estimators to the new window length — callable after
        construction (the ``paddle_tpu serve --ttft_slo_ms`` path)."""
        self.slo = slo
        win = slo.window_s if slo is not None else 60.0
        self._win_ttft = WindowedQuantiles(window_s=win)
        self._win_tps = WindowedQuantiles(window_s=win)
        # per-tier TTFT windows (created lazily as tiers appear) feed
        # the {q, tier}-labelled gauge samples: the scheduler's whole
        # point is per-tier p99 separation, which the aggregate window
        # cannot show
        self._win_ttft_tier: Dict[str, WindowedQuantiles] = {}
        self._tier_window_s = win

    def _tier_window(self, tier: str) -> WindowedQuantiles:
        win = self._win_ttft_tier.get(tier)
        if win is None:
            win = self._win_ttft_tier[tier] = WindowedQuantiles(
                window_s=self._tier_window_s)
        return win

    def _wall(self, perf_t: float) -> float:
        return self._wall_anchor + perf_t

    def _ev(self, req: EngineRequest, name: str, ph: str, perf_t: float,
            **args):
        """One lifecycle event on this request's async trace track."""
        _chrome.record_event(name, self._wall(perf_t), ph, req.trace_id,
                             args=args or None)

    def _reject(self, rid: int, reason: str, msg: str) -> ValueError:
        """Account + trace a rejected submission; returns (does not
        raise) the ValueError so call sites read ``raise self._reject``."""
        now = time.perf_counter()
        self._m_rejected.inc(reason=reason)
        _chrome.record_event(
            "request_rejected", self._wall(now), "n",
            f"eng{self._engine_id}.r{rid}",
            args={"rid": rid, "reason": reason})
        # a rejection leaves a record too (observe/requests.py promises
        # one per finished OR rejected request): no measured components,
        # so attribute() reports dominance "none" and slowest(by latency)
        # skips it, but a rejection storm shows in summary()'s by_reason
        rec = {"rid": rid, "engine": self._engine_id,
               "trace_id": f"eng{self._engine_id}.r{rid}",
               "submit_ts": round(self._wall(now), 6),
               "finish_reason": f"rejected:{reason}",
               "prompt_tokens": None, "tokens": 0,
               "queue_wait_s": None, "prefill_own_s": None,
               "prefill_stall_s": None, "decode_s": None,
               "ttft_s": None, "latency_s": None, "cache_hit_frac": 0.0}
        self.request_log.add(rec)
        _requests.default_request_log().add(rec)
        return ValueError(msg)

    def _enqueue(self, req: EngineRequest) -> EngineRequest:
        """Shared submit tail: queue the request and open its trace
        track (async ``request`` slice + nested ``queued`` slice).
        A caller-supplied trace id (``submit(trace=...)`` — the fleet
        router propagating its fleet-unique context over the serve
        wire) is adopted verbatim so the engine's lifecycle events join
        the router's ``route``/``place`` spans in one merged timeline;
        otherwise the engine mints its own per-process id."""
        if not req.trace_id:
            req.trace_id = f"eng{self._engine_id}.r{req.rid}"
        self._queue.append(req)
        self._m_requests.inc()
        self._m_queue.set(len(self._queue))
        self._ev(req, "request", "b", req.submit_t, rid=req.rid,
                 prompt_tokens=int(req.prompt.size), max_new=req.max_new,
                 tenant=req.tenant, tier=req.tier)
        self._ev(req, "queued", "b", req.submit_t)
        return req

    def _record_request(self, req: EngineRequest):
        """One flat record into the engine's bounded request ring AND
        the process default (``observe.default_request_log()``)."""
        def r6(v):
            return round(v, 6) if v is not None else None

        rec = {"rid": req.rid, "engine": self._engine_id,
               "trace_id": req.trace_id,
               "submit_ts": round(self._wall(req.submit_t), 6),
               "finish_reason": req.finish_reason,
               "tenant": req.tenant, "tier": req.tier,
               "preemptions": req.preemptions,
               "prompt_tokens": int(req.prompt.size),
               "tokens": len(req.tokens),
               "queue_wait_s": r6(req.queue_wait_s),
               "prefill_own_s": r6(req.prefill_own_s),
               "prefill_stall_s": r6(req.prefill_stall_s),
               "decode_s": r6(req.decode_s),
               "ttft_s": r6(req.ttft_s),
               "latency_s": r6(req.latency_s),
               "cache_hit_frac": round(req.cache_hit_frac, 4)}
        self.request_log.add(rec)
        _requests.default_request_log().add(rec)

    def _slo_burn_rate(self) -> float:
        if self.slo is None:
            return 0.0
        return self.slo.burn_rate(
            self._win_ttft.fraction_over(self.slo.ttft_s))

    def _update_window_gauges(self):
        """Refresh the rolling-quantile gauges + burn rate. Called when
        requests finish (request-grain, not step-grain, so the sort
        stays off the per-token path) AND on every read of the gauges
        (``health()`` / ``metrics_text()``): window samples expire with
        time, so a gauge last written mid-breach would otherwise report
        that breach forever once traffic stops, contradicting the
        live-computed `/healthz`."""
        ttft = self._win_ttft.quantiles((0.5, 0.95, 0.99))
        tps = self._win_tps.quantiles((0.5, 0.95, 0.99))
        for lbl, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            self._m_win_ttft.set(ttft[q], q=lbl)
            self._m_win_tps.set(tps[q], q=lbl)
        # per-tier split of the same gauge ({q, tier} samples): the
        # scheduler's effect IS the separation between these series
        for tier, win in self._win_ttft_tier.items():
            tq = win.quantiles((0.5, 0.95, 0.99))
            for lbl, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
                self._m_win_ttft.set(tq[q], q=lbl, tier=tier)
        self._m_burn.set(self._slo_burn_rate())

    # -- request API -------------------------------------------------------
    def _validate_submit(self, rid: int, prompt, max_new: int,
                         tier: str):
        """Shared submit validation (both engines): counted rejections,
        never tracebacks, for the malformed-request classes a JSONL
        wire can deliver."""
        if prompt.size < 1:
            raise self._reject(rid, "empty_prompt", "submit: empty prompt")
        if max_new < 1:
            raise self._reject(rid, "bad_max_new",
                               f"submit: max_new must be >= 1, "
                               f"got {max_new}")
        if tier not in VALID_TIERS:
            raise self._reject(rid, "bad_tier",
                               f"submit: tier must be one of "
                               f"{VALID_TIERS}, got {tier!r}")

    def submit(self, prompt, max_new: int, *, temperature: float = 0.0,
               top_k: int = 0, eos_id: Optional[int] = None,
               tenant: str = "default", tier: str = "batch",
               trace: Optional[str] = None) -> EngineRequest:
        """Queue one request; returns its (live) EngineRequest record.
        ``tenant``/``tier`` ride into the request log and trace events;
        ``trace`` adopts a caller-provided trace id (fleet propagation)
        instead of minting ``eng<N>.r<rid>``. The row-arena engine
        schedules FIFO regardless (tiered admission and preemption live
        in :class:`PagedDecodeEngine`)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        rid = next(self._ids)
        self._validate_submit(rid, prompt, max_new, tier)
        from paddle_tpu.core import ragged
        if prompt.size > self.buckets[-1]:
            # beyond the largest bucket there is no compiled prefill
            # program (AOT artifacts ship exactly one per bucket)
            raise self._reject(
                rid, "prompt_too_long",
                f"submit: prompt length {prompt.size} exceeds the "
                f"largest prefill bucket {self.buckets[-1]}")
        bucket = ragged.bucket_length(prompt.size, self.buckets)
        if prompt.size + max_new > self.cache_len:
            raise self._reject(
                rid, "exceeds_cache",
                f"submit: {prompt.size} prompt + {max_new} new tokens "
                f"exceed cache_len {self.cache_len}")
        req = EngineRequest(
            rid=rid, prompt=prompt, max_new=int(max_new),
            temperature=float(temperature), top_k=int(top_k),
            eos_id=eos_id, tenant=str(tenant), tier=str(tier),
            bucket=bucket, submit_t=time.perf_counter(),
            trace_id=str(trace) if trace else "")
        return self._enqueue(req)

    def abort_requests(self, reason: str = "replica_killed") -> int:
        """Close every live request's open trace slices (``queued`` /
        ``prefill`` / ``decode`` / ``request``) with an ``aborted``
        marker and drop the work. This is the IN-PROCESS analogue of
        the replica process dying: a real SIGKILL takes its span buffer
        with it (the merged fleet trace simply never sees the dead
        attempt), but an in-process fleet shares one buffer, so a kill
        simulation must close what the dead attempt opened or the
        joined trace shows unbalanced slices. Trace-level only — block
        /slot accounting is abandoned, not released, exactly like a
        dead process; do not reuse the engine afterwards."""
        now = time.perf_counter()
        aborted: List[EngineRequest] = []
        for req in list(self._queue):
            self._ev(req, "queued", "e", now)
            aborted.append(req)
        # preempted-to-blocks requests (paged engine) already closed
        # their prefill/decode slices at preemption
        aborted.extend(list(getattr(self, "_preempted", ())))
        for slot, req in enumerate(self._slot_req):
            if req is None:
                continue
            if req.first_token_t is None:
                self._ev(req, "prefill", "e", now)
            if req.decode_open:
                self._ev(req, "decode", "e", now)
                req.decode_open = False
            self._active[slot] = False
            self._slot_req[slot] = None
            aborted.append(req)
        for req in aborted:
            req.status, req.finish_reason = "aborted", reason
            self._ev(req, "aborted", "n", now, reason=reason)
            self._ev(req, "request", "e", now)
        self._queue.clear()
        if hasattr(self, "_preempted"):
            self._preempted.clear()
        self._m_queue.set(0)
        return len(aborted)

    @property
    def active_count(self) -> int:
        return int(self._active.sum())

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def free_slots(self) -> int:
        return len(self._free)

    @property
    def idle(self) -> bool:
        return not self._queue and not self._active.any()

    # -- scheduler ---------------------------------------------------------
    def _seed(self) -> np.int32:
        return np.int32(self._rng.randint(0, 2 ** 31 - 1))

    def _finish(self, req: EngineRequest, reason: str, now: float):
        req.status, req.finish_reason, req.finish_t = "done", reason, now
        self._m_completed.inc(reason=reason)
        if req.latency_s and req.latency_s > 0:
            goodput = len(req.tokens) / req.latency_s
            self._m_goodput.observe(goodput)
            self._win_tps.observe(goodput)
        slot = req.slot
        if slot >= 0:
            self._active[slot] = False
            self._slot_req[slot] = None
            self._free.append(slot)
        if req.decode_open:
            self._ev(req, "decode", "e", now)
            req.decode_open = False
        self._ev(req, "finished", "n", now, reason=reason,
                 tokens=len(req.tokens))
        self._ev(req, "request", "e", now)
        self._record_request(req)
        self._update_window_gauges()

    def _emit(self, req: EngineRequest, tok: int, now: float) -> bool:
        """Record one emitted token; True when the request finished."""
        req.tokens.append(int(tok))
        self._m_tokens.inc()
        finishing = ((req.eos_id is not None and tok == req.eos_id)
                     or len(req.tokens) >= req.max_new)
        if req.first_token_t is None:
            req.first_token_t = now
            ttft = now - req.submit_t
            self._m_ttft_s.observe(ttft)
            self._win_ttft.observe(ttft)
            self._tier_window(req.tier).observe(ttft)
            self._ev(req, "prefill", "e", now)
            self._ev(req, "first_token", "n", now,
                     ttft_ms=round(1000 * ttft, 3))
            if not finishing:
                self._ev(req, "decode", "b", now)
                req.decode_open = True
        if req.eos_id is not None and tok == req.eos_id:
            self._finish(req, "eos", now)
            return True
        if len(req.tokens) >= req.max_new:
            self._finish(req, "max_tokens", now)
            return True
        return False

    def _admit(self, finished: List[EngineRequest]):
        jnp = self._jnp
        while self._queue and self._free:
            req = self._queue.popleft()
            slot = self._free.popleft()
            now = time.perf_counter()
            req.prefill_t = now
            self._m_wait_s.observe(now - req.submit_t)
            self._ev(req, "queued", "e", now)
            self._ev(req, "admitted", "n", now, slot=slot,
                     queue_wait_ms=round(1000 * (now - req.submit_t), 3))
            self._ev(req, "prefill", "b", now)
            padded = np.zeros((1, req.bucket), np.int32)
            padded[0, :req.prompt.size] = req.prompt
            t0 = time.perf_counter()
            tok, self.cache = self._tracker.track_call(
                "serving_engine.prefill", self._prefill_fn,
                self.params, self.cache, jnp.asarray(padded),
                np.int32(req.prompt.size), np.int32(slot),
                np.float32(req.temperature), np.int32(req.top_k),
                self._seed())
            tok = int(np.asarray(tok))
            now = time.perf_counter()
            req.prefill_own_s = now - t0
            self._m_prefill_s.observe(now - t0)
            self._m_prefills.inc()
            self._ev(req, "prefill_chunk", "n", now,
                     tokens=int(req.prompt.size), bucket=req.bucket)
            req.slot, req.status = slot, "running"
            self._slot_req[slot] = req
            if self._emit(req, tok, now):
                finished.append(req)    # one-token request: slot already
                continue                # recycled by _finish
            self._active[slot] = True
            self._pos[slot] = req.prompt.size
            self._last[slot] = tok
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
        self._m_queue.set(len(self._queue))

    # hooks the paged subclass specializes -------------------------------
    def _schedule(self, finished: List[EngineRequest]):
        """Admission (and, for the paged engine, prefill-chunk) work
        that runs before the decode step."""
        self._admit(finished)

    def _pre_decode(self):
        """Host bookkeeping needed before a decode step may run (the
        paged engine allocates write pages here)."""

    def _decode_extra(self):
        """Extra decode-program args inserted after ``active`` (the
        paged engine's page table)."""
        return ()

    def _consume_forced(self, slot: int) -> bool:
        """True when this slot is replaying already-emitted history
        after a preempt-to-blocks resume (paged engine): the decode
        step's sampled id is discarded, the known token advances the
        cursor, and nothing re-emits. The row-arena engine never
        preempts."""
        return False

    def _update_gauges(self):
        self._m_occupancy.set(self.active_count)

    def step(self) -> List[EngineRequest]:
        """One scheduler iteration: admit waiting requests into free
        slots, run one batched decode step for everything in flight.
        Returns the requests that finished during this step."""
        finished: List[EngineRequest] = []
        self._schedule(finished)
        if self._active.any():
            jnp = self._jnp
            self._pre_decode()
            t0 = time.perf_counter()
            nxt, self.cache = self._tracker.track_call(
                "serving_engine.decode", self._decode_fn,
                self.params, self.cache, jnp.asarray(self._last),
                jnp.asarray(self._pos), jnp.asarray(self._active),
                *self._decode_extra(),
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                self._seed())
            nxt = np.asarray(nxt)       # the only device->host transfer:
            now = time.perf_counter()   # [B] int32 ids
            self._m_step_s.observe(now - t0)
            self._m_steps.inc()
            mfu = _costs.mfu(self.decode_flops, now - t0,
                             self._peak_flops)
            if mfu is not None:
                self._m_decode_mfu.set(mfu)
            for slot in np.flatnonzero(self._active):
                if self._consume_forced(slot):
                    continue
                req = self._slot_req[slot]
                tok = int(nxt[slot])
                self._pos[slot] += 1
                self._last[slot] = tok
                if self._emit(req, tok, now):
                    finished.append(req)
        self._update_gauges()
        return finished

    def run_until_idle(self, max_steps: int = 100_000
                       ) -> List[EngineRequest]:
        """Drive ``step()`` until queue and arena drain; returns every
        request finished along the way (submission order not guaranteed
        — requests terminate independently)."""
        done: List[EngineRequest] = []
        for _ in range(max_steps):
            if self.idle:
                return done
            done.extend(self.step())
        raise RuntimeError(f"engine did not drain in {max_steps} steps "
                           f"({self.queue_depth} queued, "
                           f"{self.active_count} active)")

    # -- observability -----------------------------------------------------
    def decode_mfu(self) -> Optional[float]:
        """Mean decode-step MFU over this engine's lifetime: decode
        FLOPs / (mean step seconds × chip peak). None until a step ran
        or when FLOPs/peak are unknown. Noise-robust against the
        last-step gauge (``engine_decode_mfu``) — the figure
        ``serving_bench`` reports."""
        cell = self._m_step_s._peek({})
        if cell is None or not cell.count:
            return None
        return _costs.mfu(self.decode_flops, cell.sum / cell.count,
                          self._peak_flops)

    def health(self) -> dict:
        doc = {"requests": int(self._m_requests.value()),
               "completed": sum(
                   int(self._m_completed.value(reason=r))
                   for r in ("eos", "max_tokens")),
               "tokens": int(self._m_tokens.value()),
               "decode_steps": int(self._m_steps.value()),
               "queue_depth": self.queue_depth,
               "slots_active": self.active_count,
               "slots_total": self.batch,
               "cache_len": self.cache_len,
               "pallas": self.pallas_mode,
               "prefill_buckets": list(self.buckets)}
        mfu = self.decode_mfu()
        if mfu is not None:
            doc["decode_mfu"] = round(mfu, 9)
        self._update_window_gauges()
        ttft = self._win_ttft.quantiles((0.5, 0.95, 0.99))
        doc["window"] = {
            "window_s": self._win_ttft.window_s,
            "requests": self._win_ttft.count(),
            "ttft_p50_s": round(ttft[0.5], 6),
            "ttft_p95_s": round(ttft[0.95], 6),
            "ttft_p99_s": round(ttft[0.99], 6),
            "tokens_per_sec_p50": round(self._win_tps.quantile(0.5), 3),
            # raw windowed TTFT samples in clock-free [age_s, value]
            # form (newest 512): the fleet aggregator POOLS these for
            # its fleet quantiles — per-replica quantiles cannot be
            # averaged (see WindowedQuantiles.samples)
            "ttft_samples": [[round(a, 4), round(v, 6)] for a, v in
                             self._win_ttft.export_samples()[-512:]]}
        if self._win_ttft_tier:
            doc["window"]["tiers"] = {
                tier: {"requests": win.count(),
                       "ttft_p50_s": round(win.quantile(0.5), 6),
                       "ttft_p99_s": round(win.quantile(0.99), 6)}
                for tier, win in sorted(self._win_ttft_tier.items())}
        if self.slo is not None:
            burn = self._slo_burn_rate()
            doc["slo"] = {"ttft_s": self.slo.ttft_s,
                          "target": self.slo.target,
                          "window_s": self.slo.window_s,
                          "burn_threshold": self.slo.burn_threshold,
                          "ttft_burn_rate": round(burn, 4)}
            if burn > self.slo.burn_threshold:
                # degraded, NOT unhealthy: /healthz stays 200 (load
                # balancers keep routing) while the reason is machine-
                # readable — the hook the SLO-aware scheduler steers on
                doc["status"] = "degraded"
                doc["degraded_reason"] = (
                    f"ttft_slo_burn_rate {burn:.2f} > "
                    f"{self.slo.burn_threshold} (p99 "
                    f"{ttft[0.99]:.4f}s vs slo {self.slo.ttft_s}s over "
                    f"{self._win_ttft.count()} requests)")
        return doc

    def requests_doc(self, k: int = 10) -> dict:
        """The `/requests` section: aggregate summary + top-k slowest
        with attributed latency components."""
        doc = self.request_log.summary()
        doc["slowest_by_ttft"] = self.request_log.slowest(k, by="ttft_s")
        return doc

    def metrics_text(self) -> str:
        self._update_window_gauges()   # expire-on-read: see the docstring
        return self.metrics.render_prometheus()

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """/metrics + /healthz + /requests over this engine's registry;
        caller owns ``close()``."""
        from paddle_tpu.observe.health import HealthServer
        return HealthServer(registry=self.metrics, health_fn=self.health,
                            host=host, port=port,
                            requests_fn=self.requests_doc,
                            metrics_fn=self.metrics_text)

    def compile_counts(self) -> Dict[str, int]:
        """Compilations the tracker charged to this engine's two
        programs — the "one per bucket + one for decode" invariant."""
        return {"prefill": self._tracker.count("serving_engine.prefill"),
                "decode": self._tracker.count("serving_engine.decode")}


def _decode_step_flops(decode_fn, params, cache, batch, *extra):
    """Model FLOPs of one compiled decode step from the lowered HLO
    cost model (None when unavailable) — the ``engine_decode_mfu``
    numerator the in-process engines derive themselves; AOT artifacts
    carry it stamped in ``meta.cost_analysis`` instead."""
    vec_i = np.zeros(batch, np.int32)
    vec_f = np.zeros(batch, np.float32)
    vec_b = np.zeros(batch, bool)
    cost = _costs.lowered_cost(
        decode_fn, params, cache, vec_i, vec_i, vec_b, *extra,
        vec_f, vec_i, np.int32(0))
    return (cost or {}).get("flops")


def default_chunk_buckets(chunk_tokens: int) -> tuple:
    """Power-of-two chunk buckets up to ``chunk_tokens`` (which is
    always included): a prompt's tail chunk pads to the smallest
    covering bucket instead of the full chunk size."""
    out, b = {int(chunk_tokens)}, 8
    while b < chunk_tokens:
        out.add(b)
        b *= 2
    return tuple(sorted(out))


class PagedDecodeEngine(DecodeEngine):
    """Block-table continuous batching: paged KV, chunked prefill,
    prefix cache.

    Replaces the row-per-request arena with a block POOL
    (``models/transformer.init_block_pool``): HBM is committed per
    ``block_size``-token block actually written — a request holds
    ``ceil((Tp + max_new)/block_size)`` blocks instead of a whole
    ``cache_len`` row — and the pool can be sized independently of
    ``batch``. On top of the pool:

    - **chunked prefill** — prompts are admitted in ``chunk_tokens``
      chunks (``transformer.prefill_into_blocks``), ONE chunk per
      ``step()`` interleaved with the batched decode step, so a long
      prompt no longer stalls in-flight decoders for its full duration,
      and any prompt with ``Tp + max_new <= cache_len`` is accepted (no
      largest-bucket rejection);
    - **prefix cache** — full prompt blocks are published under
      content-chain hashes (``serving/blocks``); a later prompt sharing
      the prefix maps the cached blocks into its page table with a
      refcount bump and skips their prefill compute. Refcount-0 cached
      blocks park in an LRU and are evicted oldest-first under
      allocation pressure. Hit decoding is bitwise the cold-prefill
      decoding (the gathered KV values are identical).

    Admission reserves a request's worst-case block count up front and
    allocates lazily, so decode never stalls mid-flight on an empty
    pool; a request that cannot reserve waits FIFO at the queue head.
    Compile discipline: at most one compile per (chunk bucket, context
    span) pair — the chunk grid is fixed at ``chunk_tokens``, so the
    reachable spans are the multiples of ``chunk_tokens`` below
    ``cache_len`` — plus ONE decode (same tracker names,
    ``compile_counts()``). Span specialization is what keeps a COLD
    chunk's attention at ``C x C`` instead of ``C x cache_len``.
    """

    def __init__(self, prefill: Callable, decode: Callable, params,
                 cache, *, batch: int, cache_len: int, block_size: int,
                 num_blocks: Optional[int] = None, chunk_tokens: int = 64,
                 chunk_buckets: Optional[Sequence[int]] = None,
                 seed: Optional[int] = None,
                 registry: Optional[_metrics.Registry] = None,
                 tracker: Optional[_ct.CompileTracker] = None,
                 slo: Optional[SloConfig] = None,
                 decode_flops: Optional[float] = None,
                 pallas_mode: Optional[str] = None,
                 kv_dtype: Optional[str] = None,
                 tenant_budgets: Optional[Dict[str, int]] = None,
                 tiers=None):
        from paddle_tpu.serving import blocks as _blocks
        bs = int(block_size)
        if bs < 1 or cache_len % bs:
            raise ValueError(f"cache_len {cache_len} must be a positive "
                             f"multiple of block_size {bs}")
        chunk_tokens = min(int(chunk_tokens), int(cache_len))
        if chunk_tokens < 1:
            raise ValueError(f"chunk_tokens must be >= 1, "
                             f"got {chunk_tokens}")
        # the chunk grid anchors the static context spans: chunk
        # boundaries (and therefore prefix-hit cutoffs) must land on
        # block edges, and the grid must tile cache_len so every page
        # vector a chunk needs fits in pages_per_slot
        if chunk_tokens % bs:
            raise ValueError(f"chunk_tokens {chunk_tokens} must be a "
                             f"multiple of block_size {bs}")
        if cache_len % chunk_tokens:
            raise ValueError(f"cache_len {cache_len} must be a multiple "
                             f"of chunk_tokens {chunk_tokens}")
        if chunk_buckets is None:
            chunk_buckets = default_chunk_buckets(chunk_tokens)
        if tracker is None:
            # the paged engine LEGITIMATELY compiles one prefill program
            # per reachable (chunk bucket, context span) pair — raise
            # the default tracker's storm threshold past that ceiling so
            # normal chunk-grid traffic doesn't read as a recompile
            # storm (a caller-supplied tracker keeps its own threshold)
            spans = max(1, int(cache_len) // chunk_tokens)
            tracker = _ct.CompileTracker(
                storm_threshold=spans * len(tuple(chunk_buckets)) + 2)
        super().__init__(prefill, decode, params, cache, batch=batch,
                         cache_len=cache_len, buckets=chunk_buckets,
                         seed=seed, registry=registry, tracker=tracker,
                         slo=slo, decode_flops=decode_flops,
                         pallas_mode=pallas_mode)
        self.block_size = bs
        self.pages_per_slot = cache_len // bs
        self.num_blocks = int(num_blocks if num_blocks is not None
                              else batch * self.pages_per_slot)
        self.chunk_tokens = chunk_tokens
        self.pool = _blocks.BlockPool(self.num_blocks, bs)
        # KV storage width of the device pool ("none" = model dtype;
        # "int8"/"int4" pools carry per-(position, head) scale tables
        # the page table indexes alongside the values). Derived HBM
        # arithmetic uses the pool SHAPES, so it needs no model config.
        self.kv_dtype = kv_dtype or "none"
        kshape = cache["k"].shape          # [L, Hkv, M, Dh-stored]
        L, Hkv, _, Dh_st = kshape
        per_tok = 2 * Hkv * Dh_st * cache["k"].dtype.itemsize
        if "k_scale" in cache:
            per_tok += 2 * Hkv * 4         # fp32 scale rows (k + v)
        self.kv_bytes_per_token = int(L) * per_tok
        self.pool_bytes = self.kv_bytes_per_token * self.num_blocks * bs
        B = self.batch
        # page table uploaded on change (most decode steps reuse the
        # cached device copy); unallocated entries stay 0 and are only
        # ever read under the attend mask
        self._pages = np.zeros((B, self.pages_per_slot), np.int32)
        self._pages_dev = None
        self._nalloc = [0] * B              # pages allocated per slot
        self._slot_blocks: List[List[int]] = [[] for _ in range(B)]
        self._slot_hashes: List[List[bytes]] = [[] for _ in range(B)]
        self._slot_off = [0] * B            # next prompt token to prefill
        self._slot_reserved = [0] * B       # unallocated reservation left
        self._slot_prefill_s = [0.0] * B    # device seconds across chunks
        self._prefilling: deque = deque()   # slots mid-prompt, round-robin
        self._evictions_seen = 0
        # -- multi-tenant scheduling state -------------------------------
        # budgets cap a tenant's RESERVED tokens in flight (admitted,
        # unfinished requests' prompt+max_new); exhaustion queues the
        # tenant's requests — other tenants admit past them
        self.tenant_budgets: Dict[str, int] = dict(tenant_budgets or {})
        self._tenant_used: Dict[str, int] = {}
        self._preempted: deque = deque()    # preempted reqs awaiting resume
        self._slot_forced: List[deque] = [deque() for _ in range(B)]
        reg = self.metrics
        self._m_preempts = reg.counter(
            "engine_preemptions_total", "batch-tier victims preempted "
            "to blocks (pages re-published to the prefix cache) so a "
            "latency-tier request could reserve")
        self._m_resumes = reg.counter(
            "engine_resumes_total", "preempted requests resumed, by "
            "mode: remap = every snapshot block still cached (pure "
            "host re-mapping), replay = eviction fallback (cache-hit "
            "chunked prefill + forced decode replay)")
        self._m_tenant_tokens = reg.gauge(
            "engine_tenant_tokens_in_flight", "reserved tokens "
            "(prompt + max_new of live requests) per tenant — what the "
            "token budget caps")
        self._m_blocks_in_use = reg.gauge(
            "engine_blocks_in_use", "pool blocks referenced by live "
            "requests")
        self._m_blocks_free = reg.gauge(
            "engine_blocks_free", "pool blocks holding nothing (not "
            "even evictable cached content)")
        self._m_blocks_cached = reg.gauge(
            "engine_blocks_cached", "refcount-0 prefix-cache blocks "
            "parked in the LRU (evictable)")
        self._m_prefix_hits = reg.counter(
            "engine_prefix_cache_hit_blocks_total",
            "prompt blocks served from the prefix cache (prefill "
            "compute skipped)")
        self._m_prefix_miss = reg.counter(
            "engine_prefix_cache_miss_blocks_total",
            "full prompt blocks that had to be prefilled")
        self._m_evictions = reg.counter(
            "engine_prefix_cache_evictions_total",
            "cached blocks evicted LRU-oldest-first under allocation "
            "pressure")
        self._m_chunks = reg.counter(
            "engine_prefill_chunks_total", "prefill chunk programs "
            "executed (several per long prompt)")
        self._m_stall = reg.histogram(
            "engine_prefill_stall_seconds", "time in-flight decoders "
            "were stalled by one prefill chunk (observed per chunk run "
            "while any slot was decoding)", buckets=_LATENCY_BUCKETS)
        self._m_kv_bytes = reg.gauge(
            "engine_kv_bytes_per_token", "pool HBM bytes one resident "
            "token costs across all layers (k + v + scale rows at the "
            "pool's kv_dtype) — the per-token decode-read traffic and "
            "the slots-at-equal-HBM denominator")
        self._m_kv_bytes.set(self.kv_bytes_per_token)
        self._m_kv_exported = reg.counter(
            "engine_kv_blocks_exported_total", "prefix-cache blocks "
            "serialized out over the P/D transfer wire "
            "(export_prefix — the prefill half of disaggregation)")
        self._m_kv_imported = reg.counter(
            "engine_kv_blocks_imported_total", "transferred blocks "
            "adopted into the pool via the prefix-cache publish path "
            "(import_prefix — the decode half of disaggregation)")
        self._m_tier_hits = reg.counter(
            "engine_prefix_tier_hit_blocks_total", "prompt blocks "
            "served per tier (label tier): hbm = ordinary prefix-cache "
            "hit, dram/disk = spilled block re-adopted at admission")
        self._m_tier_miss = reg.counter(
            "engine_prefix_tier_miss_blocks_total", "prefix lookups "
            "that missed a tier (label tier), counted once per "
            "request's promotion walk — a cold block misses hbm, dram "
            "AND disk; a disk re-adopt misses hbm and dram")
        # -- tiered spill store (HBM -> host DRAM -> disk) ---------------
        # `tiers` is a serving.tiers.TieredStore (tests that want
        # direct store access) or a kwargs dict for one ({"dram_bytes":
        # ..., "disk_bytes": ..., "disk_dir": ...}); None (the default)
        # disables spill entirely — eviction behaves exactly as before.
        self.tiers = None
        if tiers is not None:
            from paddle_tpu.serving import tiers as _tiers
            self.tiers = (tiers if isinstance(tiers, _tiers.TieredStore)
                          else _tiers.TieredStore(registry=reg,
                                                  **dict(tiers)))
            self.pool.on_evict = self._demote_block

    # -- construction ------------------------------------------------------
    @classmethod
    def from_params(cls, params, cfg, *, batch: int, cache_len: int,
                    block_size: int = 16,
                    num_blocks: Optional[int] = None,
                    chunk_tokens: int = 64,
                    chunk_buckets: Optional[Sequence[int]] = None,
                    seed: Optional[int] = None,
                    pallas: Optional[str] = None,
                    kv_dtype: Optional[str] = None, **kw):
        """In-process paged engine: jit the chunk-prefill/paged-decode
        programs against live params (the no-artifact path tests and
        benchmarks drive). ``pallas`` overrides the
        ``PADDLE_TPU_PALLAS`` policy for the step programs (flash-decode
        attention + chunk-prefill kernel + fused sampling epilogue);
        ``params`` may be the ``quantize_lm_params`` int8 tree — the
        decode step then reads weights at 1 byte/elt (in-scan dequant).
        ``kv_dtype`` ("int8"/"int4") quantizes the KV pool itself
        (``transformer.init_block_pool``): history streams at 1 or 1/2
        byte/elt and the same HBM budget holds 4-8x the blocks — the
        step programs detect the pool layout from the pytree, so no
        other wiring changes."""
        import jax
        from paddle_tpu.models import transformer
        from paddle_tpu.ops.pallas import policy as _pallas_policy
        from paddle_tpu.serving import sampling
        if cache_len > cfg.max_len:
            raise ValueError(f"cache_len {cache_len} exceeds cfg.max_len "
                             f"{cfg.max_len}")
        if block_size < 1 or cache_len % block_size:
            raise ValueError(f"cache_len {cache_len} must be a positive "
                             f"multiple of block_size {block_size}")
        nb = int(num_blocks if num_blocks is not None
                 else batch * (cache_len // block_size))
        prefill_fn, decode_fn = sampling.paged_step_fns(
            cfg, block_size, pallas=pallas)
        pool = transformer.init_block_pool(cfg, nb, block_size,
                                           kv_dtype=kv_dtype)
        jdf = jax.jit(decode_fn)
        if "decode_flops" not in kw:    # the trace is not free — skip
            pages = np.zeros((batch, cache_len // block_size), np.int32)
            kw["decode_flops"] = _decode_step_flops(
                jdf, params, pool, batch, pages)
        return cls(jax.jit(prefill_fn), jdf, params, pool,
                   batch=batch, cache_len=cache_len,
                   block_size=block_size, num_blocks=nb,
                   chunk_tokens=chunk_tokens, chunk_buckets=chunk_buckets,
                   seed=seed, kv_dtype=kv_dtype,
                   pallas_mode=_pallas_policy.pallas_mode(pallas), **kw)

    # -- request API -------------------------------------------------------
    def set_tenant_budget(self, tenant: str, tokens: Optional[int]):
        """Cap (or with ``None`` uncap) ``tenant``'s reserved tokens in
        flight. Takes effect at the next admission — live requests are
        never evicted by a budget change (budgets queue, they do not
        kill). Submissions whose own prompt+max_new exceeds the cap are
        REJECTED (reason ``exceeds_budget``) — they could never admit;
        note that shrinking a budget below an already-QUEUED request's
        charge parks that request until the budget is raised again.
        Per-tenant gauge samples exist only for budgeted tenants;
        uncapping drops the sample (it would otherwise freeze at its
        last value)."""
        if tokens is None:
            self.tenant_budgets.pop(tenant, None)
            self._m_tenant_tokens.remove(tenant=tenant)
        else:
            self.tenant_budgets[str(tenant)] = int(tokens)

    def submit(self, prompt, max_new: int, *, temperature: float = 0.0,
               top_k: int = 0, eos_id: Optional[int] = None,
               tenant: str = "default", tier: str = "batch",
               trace: Optional[str] = None) -> EngineRequest:
        """Queue one request. Unlike the row-arena engine there is no
        largest-bucket rejection: any prompt with
        ``len(prompt) + max_new <= cache_len`` is accepted and prefilled
        in chunks. ``tier="latency"`` admits ahead of batch-tier work
        and may preempt a batch victim's blocks under pool pressure;
        ``tenant`` charges the request's worst-case tokens against that
        tenant's budget (exhaustion queues, never rejects)."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        rid = next(self._ids)
        self._validate_submit(rid, prompt, max_new, tier)
        if prompt.size + max_new > self.cache_len:
            raise self._reject(
                rid, "exceeds_cache",
                f"submit: {prompt.size} prompt + {max_new} new tokens "
                f"exceed cache_len {self.cache_len}")
        need = -(-(prompt.size + max_new) // self.block_size)
        if need > self.num_blocks:
            # _admit reserves the worst-case block count up front; a
            # request needing more blocks than the pool HAS could never
            # reserve and would livelock the FIFO queue head forever
            raise self._reject(
                rid, "exceeds_pool",
                f"submit: {prompt.size} prompt + {max_new} new tokens "
                f"need {need} blocks, exceeding the pool's "
                f"{self.num_blocks}")
        budget = self.tenant_budgets.get(str(tenant))
        if budget is not None and prompt.size + max_new > budget:
            # same never-admittable class for budgets: a request whose
            # OWN charge exceeds its tenant's cap could never pass
            # _budget_ok even with nothing in flight — it would queue
            # forever (budget exhaustion queues; impossibility rejects)
            raise self._reject(
                rid, "exceeds_budget",
                f"submit: {prompt.size} prompt + {max_new} new tokens "
                f"exceed tenant {tenant!r}'s budget of {budget}")
        req = EngineRequest(
            rid=rid, prompt=prompt, max_new=int(max_new),
            temperature=float(temperature), top_k=int(top_k),
            eos_id=eos_id, tenant=str(tenant), tier=str(tier),
            bucket=0, submit_t=time.perf_counter(),
            trace_id=str(trace) if trace else "")
        return self._enqueue(req)

    # -- P/D disaggregation (KV transfer over the fleet wire) -------------
    def prefix_digests(self, prompt) -> List[bytes]:
        """Content-chain digests of ``prompt``'s TRANSFERABLE prefix:
        the chunk-aligned full blocks admission can serve as cache hits
        (the final chunk always recomputes locally — it must produce
        logits to sample from). This is the P/D transfer unit and the
        router's placement key."""
        from paddle_tpu.serving import blocks as _blocks
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        per = self.chunk_tokens // self.block_size
        usable = ((int(prompt.size) - 1) // self.chunk_tokens) * per
        if usable <= 0:
            return []
        return _blocks.prompt_block_hashes(prompt,
                                           self.block_size)[:usable]

    def export_prefix(self, prompt, trace: Optional[str] = None,
                      partial: bool = False) -> Optional[bytes]:
        """Serialize ``prompt``'s transferable prefix out of this pool
        — the prefill half of P/D disaggregation. Every prefix block
        must already be published (run the prompt through the scheduler
        first, e.g. ``submit(prompt, max_new=1)`` + drain: chunked
        prefill publishes the blocks as each chunk lands). Returns
        ``None`` when the prompt has no transferable prefix or any
        block was evicted before serialization — the receiver then
        falls back to a cold prefill, which is slower but identical.

        ``partial=True`` is the fleet cache-fetch mode: serve the
        LEADING chunk-aligned run from wherever it lives — HBM pool
        rows AND spilled DRAM/disk-tier payloads mixed in one chain —
        stopping at the first miss instead of returning None. The
        receiver cannot tell the sources apart (the spill format is
        the wire format), and a partial chain still serves hits there
        because admission stops at its first miss anyway. None only
        when the leading run is empty."""
        from paddle_tpu.serving import transfer as _transfer
        digests = self.prefix_digests(prompt)
        if not digests:
            return None
        names = None
        items = []
        for h in digests:
            b = self.pool.lookup(h)
            if b is not None:
                if names is None:
                    names = [n for n in _transfer.ARRAY_ORDER
                             if n in self.cache]
                items.append((h, {
                    n: np.asarray(_transfer._block_slab(
                        self.cache[n], int(b), self.block_size))
                    for n in names}))
                continue
            if not partial:
                return None
            got = self.tiers.get(h) if self.tiers is not None else None
            if got is None:
                break
            try:
                meta, sub = _transfer.deserialize_blocks(got[1])
                _transfer.check_pool_match(meta, self.cache,
                                           self.block_size,
                                           self.kv_dtype)
                if len(sub) != 1 or sub[0][0] != h:
                    raise ValueError("spill payload digest mismatch")
            except (ValueError, KeyError):
                self.tiers.quarantine(h)
                break
            items.append(sub[0])
        if not items:
            return None
        payload = _transfer.serialize_raw_blocks(
            _transfer.pool_meta(self.cache, self.block_size,
                                self.kv_dtype),
            items, trace=trace)
        self._m_kv_exported.inc(len(items))
        return payload

    def import_prefix(self, payload: bytes) -> int:
        """Adopt serialized prefix blocks into this pool via the
        ordinary prefix-cache publish path — the decode half of P/D
        disaggregation. Stamp-checked (pool layout / kv_dtype / slab
        shape must match this pool). Walks the chain in order, skipping
        digests already cached; stops early when the pool cannot
        reserve another block (a partial prefix still serves hits —
        admission stops at the first miss anyway). Returns the blocks
        newly adopted; they park refcount-0 in the LRU, hit-ready.
        Generation over adopted blocks is bitwise the colocated run
        (the PR-6 hit-vs-cold guarantee: identical KV bytes, identical
        chunk grid for the locally-computed tail)."""
        from paddle_tpu.serving import transfer as _transfer
        meta, blocks = _transfer.deserialize_blocks(payload)
        _transfer.check_pool_match(meta, self.cache, self.block_size,
                                   self.kv_dtype)
        n = 0
        chain_blocks = set()    # pool blocks holding EARLIER digests
        #                         of this chain — cached before the
        #                         call or adopted by it
        pending = []
        for digest, arrays in blocks:
            existing = self.pool.lookup(digest)
            if existing is not None:
                chain_blocks.add(existing)
                continue
            if not self.pool.can_reserve(1):
                break
            if (self.pool.free_count == 0
                    and self.pool.lru_oldest() in chain_blocks):
                # the next alloc would evict one of THIS chain's own
                # leading blocks (already-cached head included): a
                # full-pool import must keep the leading run — a chain
                # with its head evicted serves zero hits (admission
                # stops at the first miss)
                break
            self.pool.reserve(1)
            b = self.pool.alloc()
            pending.append((b, arrays))
            self.pool.publish(digest, b)
            self.pool.release(b)    # refcount 0 + published: parks in
            chain_blocks.add(b)     # the LRU, served as a hit from here
            n += 1
        # value writes batched: one scatter per pool leaf for the whole
        # chain (nothing reads the pool between publish and here — the
        # engine is single-threaded)
        self.cache = _transfer.write_blocks(self.cache, pending,
                                            self.block_size)
        if n:
            self._m_kv_imported.inc(n)
        if meta.get("trace"):
            # the payload header carried the fleet trace context across
            # the P/D hop: mark the adoption on that track, so the
            # disaggregated prefill→decode handoff is one connected
            # timeline (the request's prefix_adopt hit follows at
            # admission)
            _chrome.record_event(
                "prefix_import", self._wall(time.perf_counter()), "n",
                str(meta["trace"]),
                args={"blocks": n, "chain": len(blocks)})
        return n

    # -- tiered spill (HBM -> host DRAM -> disk) ---------------------------
    def _demote_block(self, block: int, digest: bytes):
        """``pool.on_evict`` hook: serialize the LRU-evicted cached
        block with the transfer wire (the spill format IS the wire
        format) and park it in the DRAM/disk tiers. Fires inside
        ``alloc()`` BEFORE the new holder scatters over the rows, so
        the bytes still match the digest. Never raises into the
        allocation path — a failed spill is just a lost cache entry,
        exactly what eviction meant before tiers existed."""
        from paddle_tpu.serving import transfer as _transfer
        try:
            payload = _transfer.serialize_blocks(
                self.cache, [block], [digest], self.block_size,
                self.kv_dtype)
            self.tiers.put(digest, payload)
        except Exception:
            pass

    def _promote_for(self, req: EngineRequest):
        """Re-adopt ``req``'s spilled prefix from the DRAM/disk tiers
        into the pool at the moment admission is guaranteed, so the
        re-plan sees the promoted blocks as ordinary prefix-cache hits
        and the PR-6 bitwise hit-vs-cold contract carries across tiers
        unchanged.
        Walks the chain to the chunk-aligned hit cap and stops at the
        first full miss (a chain with a hole serves no hits past it).
        Runs ONCE per request (``tier_promote_done``); a corrupt or
        stamp-mismatched payload is quarantined and treated as the
        miss it is — never an exception on the admission path."""
        from paddle_tpu.serving import blocks as _blocks
        from paddle_tpu.serving import transfer as _transfer
        req.tier_promote_done = True
        bs = self.block_size
        hashes = req.block_hashes
        if hashes is None:
            hashes = _blocks.prompt_block_hashes(req.prompt, bs)
            req.block_hashes = hashes
        per = self.chunk_tokens // bs
        usable = ((int(req.prompt.size) - 1) // self.chunk_tokens) * per
        chain_blocks = set()
        pending = []
        promoted = 0
        for h in hashes[:usable]:
            existing = self.pool.lookup(h)
            if existing is not None:
                chain_blocks.add(existing)
                continue
            self._m_tier_miss.inc(tier="hbm")
            got = self.tiers.get(h)
            if got is None:
                self._m_tier_miss.inc(tier="dram")
                self._m_tier_miss.inc(tier="disk")
                break
            tier, payload = got
            if tier == "disk":
                self._m_tier_miss.inc(tier="dram")
            try:
                meta, items = _transfer.deserialize_blocks(payload)
                _transfer.check_pool_match(meta, self.cache, bs,
                                           self.kv_dtype)
                if len(items) != 1 or items[0][0] != h:
                    raise ValueError("spill payload digest mismatch")
            except (ValueError, KeyError):
                self.tiers.quarantine(h)
                break
            if not self.pool.can_reserve(1):
                break
            if (self.pool.free_count == 0
                    and self.pool.lru_oldest() in chain_blocks):
                # same guard as import_prefix: adopting one more block
                # must not evict this chain's own head
                break
            self.pool.reserve(1)
            b = self.pool.alloc()
            pending.append((b, items[0][1]))
            self.pool.publish(h, b)
            self.pool.release(b)        # refcount 0 + published: parks
            chain_blocks.add(b)         # in the LRU, hit-ready
            self._m_tier_hits.inc(tier=tier)
            promoted += 1
        self.cache = _transfer.write_blocks(self.cache, pending, bs)
        req.tier_promoted_blocks = promoted
        if promoted:
            self._m_kv_imported.inc(promoted)
            self._ev(req, "tier_promote", "n", time.perf_counter(),
                     blocks=promoted)

    @property
    def preempted_count(self) -> int:
        """Preempted requests parked awaiting resume."""
        return len(self._preempted)

    @property
    def idle(self) -> bool:
        return (not self._queue and not self._preempted
                and not self._prefilling and not self._active.any())

    # -- scheduler ---------------------------------------------------------
    def _alloc_page(self, slot: int):
        b = self.pool.alloc()
        self._pages[slot, self._nalloc[slot]] = b
        self._pages_dev = None
        self._nalloc[slot] += 1
        self._slot_blocks[slot].append(b)
        self._slot_reserved[slot] -= 1

    # -- multi-tenant admission / preemption -------------------------------
    def _charge(self, req: EngineRequest) -> int:
        """Worst-case tokens a live request holds against its tenant's
        budget — the same prompt+max_new the block reservation backs."""
        return int(req.prompt.size) + int(req.max_new)

    def _budget_ok(self, req: EngineRequest) -> bool:
        budget = self.tenant_budgets.get(req.tenant)
        if budget is None:
            return True
        return self._tenant_used.get(req.tenant, 0) \
            + self._charge(req) <= budget

    def _track_tenant(self, req: EngineRequest, delta: int):
        used = max(self._tenant_used.get(req.tenant, 0) + delta, 0)
        if used:
            self._tenant_used[req.tenant] = used
        else:
            # prune at zero: tenant names arrive unvalidated off the
            # JSONL wire, so keeping dead entries (or per-tenant gauge
            # samples) would grow host state one permanent row per
            # tenant name ever seen
            self._tenant_used.pop(req.tenant, None)
        if req.tenant in self.tenant_budgets:
            # gauge cardinality bounded by the CONFIGURED budget set,
            # not by whatever tenant strings clients invent
            self._m_tenant_tokens.set(used, tenant=req.tenant)

    def _charge_tenant(self, req: EngineRequest):
        self._track_tenant(req, self._charge(req))

    def _uncharge_tenant(self, req: EngineRequest):
        self._track_tenant(req, -self._charge(req))

    def _admission_plan(self, req: EngineRequest):
        """(hashes, hits, need, revive) for admitting ``req`` now."""
        from paddle_tpu.serving import blocks as _blocks
        bs = self.block_size
        Tp = req.prompt.size
        hashes = req.block_hashes
        if hashes is None:      # computed once per request: the digests
            #                     are a pure function of the prompt, and
            #                     a reservation-blocked head re-enters
            #                     here every step
            hashes = _blocks.prompt_block_hashes(req.prompt, bs)
            req.block_hashes = hashes
        # cap hits CHUNK-aligned (not merely block-aligned): the
        # post-hit chunks must replay the cold prefill's exact chunk
        # grid for the bitwise hit-vs-cold guarantee, and at least the
        # last prompt token is always recomputed — the final chunk must
        # produce logits to sample from
        per = self.chunk_tokens // bs
        usable = ((Tp - 1) // self.chunk_tokens) * per
        hits: List[int] = []
        for h in hashes[:usable]:
            b = self.pool.lookup(h)
            if b is None:
                break
            hits.append(b)
        # a PARTIAL-chunk hit run must round DOWN to the chunk grid:
        # starting prefill mid-chunk would reach (bucket, span) shapes
        # off the exported grid — KeyError on v4 artifacts, extra
        # compiles in-process
        hits = hits[:len(hits) // per * per]
        need = -(-(Tp + req.max_new) // bs) - len(hits)
        # hits parked refcount-0 in the LRU are about to be revived by
        # share(): they leave the allocatable set, so the reservation
        # must clear them TOO or a later lazy alloc() could find the
        # pool exhausted despite its reservation
        revive = sum(1 for b in hits if self.pool.refcount(b) == 0)
        return hashes, hits, need, revive

    def _try_admit(self, req: EngineRequest,
                   finished: List[EngineRequest]) -> bool:
        """Admit ``req`` if a slot is free and its reservation fits;
        the plan is computed ONCE and handed to the admission body."""
        if not self._free:
            return False
        plan = self._admission_plan(req)
        _, _, need, revive = plan
        if not self.pool.can_reserve(need + revive):
            return False
        # promote ONLY once admission is certain: a promoted block
        # parks refcount-0 in the LRU, and a queued request's wait can
        # outlive that parking (other requests' allocs would evict the
        # promotion before it ever served a hit). Promotion keeps the
        # reservation check's ground truth intact — each promoted
        # block moves free -> LRU (allocatable unchanged) and its
        # digest moves need -> revive (the sum unchanged) — so the
        # can_reserve verdict above still stands; only the hit list
        # needs recomputing.
        if self.tiers is not None and not req.tier_promote_done:
            self._promote_for(req)
            if req.tier_promoted_blocks:
                plan = self._admission_plan(req)
        self._admit_request(req, finished, plan)
        return True

    def _admit_request(self, req: EngineRequest,
                       finished: List[EngineRequest], plan):
        """Place one admissible request into a slot (the PR-6 admission
        body). ``plan`` is the caller's ``_admission_plan`` result."""
        hashes, hits, need, revive = plan
        slot = self._free.popleft()
        self.pool.reserve(need)
        for b in hits:
            self.pool.share(b)
        self._pages[slot, :] = 0
        self._pages[slot, :len(hits)] = hits
        self._pages_dev = None
        self._nalloc[slot] = len(hits)
        self._slot_blocks[slot] = list(hits)
        self._slot_hashes[slot] = hashes
        self._slot_off[slot] = len(hits) * self.block_size
        self._slot_reserved[slot] = need
        self._slot_prefill_s[slot] = 0.0
        req.prefix_hit_tokens = len(hits) * self.block_size
        self._m_prefix_hits.inc(len(hits))
        # tier-labeled hit split: blocks _promote_for just re-adopted
        # were dram/disk hits (counted there); the rest were warm in
        # HBM all along
        hbm_hits = len(hits) - req.tier_promoted_blocks
        req.tier_promoted_blocks = 0
        if hbm_hits > 0:
            self._m_tier_hits.inc(hbm_hits, tier="hbm")
        # misses are counted as chunks actually run cold
        # (_prefill_chunk): a block published by a CONCURRENT
        # same-prefix request mid-prefill is adopted, not missed
        now = time.perf_counter()
        req.prefill_t = now
        if req.preemptions == 0:
            # re-admissions after a preemption would re-observe the
            # whole submit->now span on top of the first observation —
            # the histogram records each request's ORIGINAL queue wait
            self._m_wait_s.observe(now - req.submit_t)
        self._ev(req, "queued", "e", now)
        self._ev(req, "admitted", "n", now, slot=slot,
                 queue_wait_ms=round(1000 * (now - req.submit_t), 3),
                 hit_blocks=len(hits), reserved_blocks=need)
        self._ev(req, "prefill", "b", now)
        req.slot, req.status = slot, "prefilling"
        self._slot_req[slot] = req
        self._charge_tenant(req)
        if req.replay is not None:
            # preempt-resume eviction fallback: the prompt re-prefills
            # on its exact cold chunk grid (cache hits make surviving
            # chunks free), then the already-emitted history replays
            # through the decode program without re-emitting
            self._slot_forced[slot] = deque(req.replay)
            req.replay = None
        self._prefilling.append(slot)

    def _admit(self, finished: List[EngineRequest]):
        """Tiered, budget-aware admission. Priority classes, scanned in
        order each scheduler step:

        1. **latency-tier queue** (FIFO) — a reservation-blocked head
           may preempt batch-tier victims; while it stays blocked,
           nothing below it admits (strict priority).
        2. **preempted resumes** (oldest first) — ahead of fresh
           batch admissions so preemption is a delay, not a demotion.
        3. **batch-tier queue** (FIFO) — head-of-line on reservation,
           like the single-tenant engine.

        In every class a request whose TENANT budget is exhausted is
        SKIPPED, not blocked on: token budgets isolate tenants from
        each other, so one tenant's burst must not head-of-line-block
        the rest of the fleet. Budget exhaustion therefore queues
        (the request stays, admitted when its tenant's tokens free) —
        it never rejects."""
        blocked = False
        for req in [r for r in self._queue if r.tier == "latency"]:
            if not self._budget_ok(req):
                continue
            admitted = self._try_admit(req, finished)
            if not admitted and self._preemption_feasible(req):
                while not admitted and self._preempt_victim():
                    admitted = self._try_admit(req, finished)
            if not admitted:
                blocked = True
                break
            self._queue.remove(req)
        if not blocked:
            for req in list(self._preempted):
                if not self._budget_ok(req):
                    continue
                if self._try_resume(req, finished) is None:
                    blocked = True
                    break
                self._preempted.remove(req)
            if not blocked:
                for req in [r for r in self._queue
                            if r.tier == "batch"]:
                    if not self._budget_ok(req):
                        continue
                    if not self._try_admit(req, finished):
                        break
                    self._queue.remove(req)
        self._m_queue.set(len(self._queue) + len(self._preempted))

    def _preemption_feasible(self, req: EngineRequest) -> bool:
        """Could evicting batch-tier work EVER free enough for ``req``?
        Worst-case need vs everything not pinned by latency-tier
        holders. False stops a blocked latency request from pointlessly
        draining every batch victim it can never benefit from."""
        held_lat = sum(self._nalloc[s] + self._slot_reserved[s]
                       for s, r in enumerate(self._slot_req)
                       if r is not None and r.tier != "batch")
        need = -(-(req.prompt.size + req.max_new) // self.block_size)
        return need <= self.num_blocks - held_lat

    def _preempt_victim(self) -> bool:
        """Preempt ONE batch-tier victim to free blocks (and its slot)
        for a blocked latency-tier admission. Victim choice: the
        batch request holding the most pool resources (allocated +
        still-reserved blocks — what preemption actually frees); ties
        break toward the most recently admitted (least sunk prefill
        work). Returns False when no batch-tier work is preemptable."""
        best, best_key = -1, None
        for slot, req in enumerate(self._slot_req):
            if req is None or req.tier != "batch":
                continue
            if req.status not in ("prefilling", "running"):
                continue
            key = (self._nalloc[slot] + self._slot_reserved[slot],
                   req.prefill_t or 0.0)
            if best_key is None or key > best_key:
                best, best_key = slot, key
        if best < 0:
            return False
        self._preempt(best)
        return True

    def _preempt(self, slot: int):
        """Preempt-to-blocks: snapshot the slot's decode cursor, publish
        every fully-written block (prompt chain continued over the
        generated tokens, plus the partial tail block under its own
        chain digest) into the prefix cache, release the pages and the
        reservation. The pool makes this a pure host operation — no
        device copy moves — and resume is either a straight re-mapping
        (blocks survived in the LRU) or a cache-hit chunked prefill
        plus forced decode replay (blocks evicted). A victim still
        PREFILLING simply re-queues: its published chunks already sit
        in the prefix cache, so re-admission hits them."""
        from paddle_tpu.serving import blocks as _blocks
        req = self._slot_req[slot]
        now = time.perf_counter()
        bs = self.block_size
        blocks = list(self._slot_blocks[slot])
        if req.status == "running":
            if req.decode_open:
                self._ev(req, "decode", "e", now)
                req.decode_open = False
            pos = int(self._pos[slot])
            seq = np.concatenate(
                [req.prompt, np.asarray(req.tokens, np.int32)])
            nfull = pos // bs
            hashes = _blocks.prompt_block_hashes(seq[:nfull * bs], bs)
            tail_len = pos % bs
            tail_hash = None
            if tail_len:
                parent = hashes[-1] if hashes else _blocks.ROOT_HASH
                tail_hash = _blocks.chain_hash(
                    parent, seq[nfull * bs:pos])
            for j, h in enumerate(hashes):
                self.pool.publish(h, blocks[j])
            if tail_hash is not None:
                self.pool.publish(tail_hash, blocks[nfull])
            req.snapshot = {
                "hashes": hashes, "tail_hash": tail_hash,
                "tail_len": tail_len, "pos": pos,
                "last": int(self._last[slot]),
                "forced": list(self._slot_forced[slot])}
            published = nfull + (1 if tail_len else 0)
            self._active[slot] = False
        else:                       # mid-prefill: published chunk
            published = 0           # blocks already carry their hashes
            self._prefilling.remove(slot)
            self._ev(req, "prefill", "e", now)   # close the open slice
            if self._slot_forced[slot]:
                # a replay-resuming victim preempted AGAIN mid-prefill:
                # its un-replayed history must survive the re-queue or
                # the next admission would RE-EMIT already-delivered
                # tokens (replay restarts from the full emitted list —
                # the prompt prefill re-derives the earlier part)
                req.replay = list(req.tokens)
        for b in blocks:
            self.pool.release(b)
        self.pool.unreserve(self._slot_reserved[slot])
        self._slot_blocks[slot] = []
        self._slot_hashes[slot] = []
        self._slot_reserved[slot] = 0
        self._nalloc[slot] = 0
        self._slot_off[slot] = 0
        self._slot_forced[slot] = deque()
        self._pages[slot, :] = 0
        self._pages_dev = None
        self._slot_req[slot] = None
        self._free.append(slot)
        self._uncharge_tenant(req)
        req.slot = -1
        req.preemptions += 1
        self._m_preempts.inc()
        self._ev(req, "preempted", "n", now, tokens=len(req.tokens),
                 blocks_published=published, was=req.status)
        # the request is queued again (the resume line or the arrival
        # queue): open a fresh "queued" slice so the re-admission's
        # (or remap-resume's) "queued e" stays balanced
        self._ev(req, "queued", "b", now)
        if req.status == "running":
            req.status = "preempted"
            self._preempted.append(req)
        else:
            req.status = "queued"
            self._queue.appendleft(req)

    def _try_resume(self, req: EngineRequest,
                    finished: List[EngineRequest]) -> Optional[str]:
        """Resume one preempted request. Fast path (``"remap"``): every
        snapshot digest still resolves in the prefix cache — share the
        blocks back into a fresh page table, un-publish the partial
        tail (decode writes into it again), restore the cursor; no
        device work at all, and generation continues bitwise as if
        never preempted. Eviction fallback (``"replay"``): re-admit
        through the normal chunked prefill (the prompt's surviving
        chunks are cache hits on the exact cold grid) and force-feed
        the already-emitted tokens through the decode program — same
        program shapes as the original run, so the continuation stays
        bitwise too. ``None``: blocked on a slot or reservation."""
        from paddle_tpu.serving import blocks as _blocks
        if not self._free:
            return None
        snap = req.snapshot
        bs = self.block_size
        blocks: List[int] = []
        ok = True
        for h in snap["hashes"]:
            b = self.pool.lookup(h)
            if b is None:
                ok = False
                break
            blocks.append(b)
        tail_b = None
        if ok and snap["tail_hash"] is not None:
            tail_b = self.pool.lookup(snap["tail_hash"])
            # the tail block gets WRITTEN into: it must be exclusively
            # ours (refcount-0, LRU-parked); anything else falls back
            # to replay rather than corrupting a shared block
            if tail_b is None or self.pool.refcount(tail_b) != 0:
                ok = False
            else:
                blocks.append(tail_b)
        if ok:
            need = -(-(req.prompt.size + req.max_new) // bs) \
                - len(blocks)
            revive = sum(1 for b in blocks
                         if self.pool.refcount(b) == 0)
            if not self.pool.can_reserve(need + revive):
                return None
            now = time.perf_counter()
            slot = self._free.popleft()
            self.pool.reserve(need)
            for b in blocks:
                self.pool.share(b)
            if tail_b is not None:
                self.pool.unpublish(tail_b)
            self._pages[slot, :] = 0
            self._pages[slot, :len(blocks)] = blocks
            self._pages_dev = None
            self._nalloc[slot] = len(blocks)
            self._slot_blocks[slot] = list(blocks)
            self._slot_hashes[slot] = req.block_hashes or \
                _blocks.prompt_block_hashes(req.prompt, bs)
            self._slot_off[slot] = req.prompt.size
            self._slot_reserved[slot] = need
            self._slot_forced[slot] = deque(snap.get("forced", ()))
            req.slot, req.status = slot, "running"
            self._slot_req[slot] = req
            self._active[slot] = True
            self._pos[slot] = snap["pos"]
            self._last[slot] = snap["last"]
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            self._charge_tenant(req)
            req.snapshot = None
            self._ev(req, "queued", "e", now)
            if not req.decode_open:
                self._ev(req, "decode", "b", now)
                req.decode_open = True
            self._m_resumes.inc(mode="remap")
            self._ev(req, "resumed", "n", now, mode="remap",
                     blocks=len(blocks))
            return "remap"
        # eviction fallback: forced replay through normal admission
        req.replay = list(req.tokens)
        if not self._try_admit(req, finished):
            req.replay = None           # still parked: keep the
            return None                 # snapshot for the next attempt
        req.snapshot = None
        self._m_resumes.inc(mode="replay")
        self._ev(req, "resumed", "n", time.perf_counter(),
                 mode="replay", replay_tokens=len(req.tokens))
        return "replay"

    def _draft_chunk_hook(self, slot: int, padded, c: int, npages: int):
        """No-op on the plain paged engine; the spec engine mirrors the
        chunk into the draft pool here."""

    def _try_adopt(self, slot: int) -> bool:
        """Map the slot's NEXT chunk straight onto cached blocks when
        every block of it is already published — a CONCURRENT
        same-prefix request cold-prefilled it after this one was
        admitted. Shares the blocks, returns the reservation, skips the
        chunk program entirely. Only whole chunk-aligned chunks below
        the hit cap qualify, so the hit-vs-cold bitwise guarantee's
        chunk grid is preserved."""
        req = self._slot_req[slot]
        off = self._slot_off[slot]
        bs, K = self.block_size, self.chunk_tokens
        cap = ((req.prompt.size - 1) // K) * K
        if off % K or off >= cap:
            return False
        hashes = self._slot_hashes[slot]
        first = off // bs
        blocks = []
        for j in range(first, first + K // bs):
            b = self.pool.lookup(hashes[j])
            if b is None:
                return False
            blocks.append(b)
        for b in blocks:
            self.pool.share(b)
            self._pages[slot, self._nalloc[slot]] = b
            self._nalloc[slot] += 1
            self._slot_blocks[slot].append(b)
        self._pages_dev = None
        self.pool.unreserve(len(blocks))
        self._slot_reserved[slot] -= len(blocks)
        self._slot_off[slot] = off + K
        req.prefix_hit_tokens += K
        self._m_prefix_hits.inc(len(blocks))
        self._m_tier_hits.inc(len(blocks), tier="hbm")
        self._ev(req, "prefix_adopt", "n", time.perf_counter(),
                 hit_blocks=len(blocks), tokens=K)
        return True

    def _prefill_chunk(self, finished: List[EngineRequest]):
        from paddle_tpu.core import ragged
        jnp = self._jnp
        slot = self._prefilling.popleft()
        req = self._slot_req[slot]
        while self._try_adopt(slot):
            pass
        off = self._slot_off[slot]
        c = min(req.prompt.size - off, self.chunk_tokens)
        bucket = ragged.bucket_length(c, self.buckets)
        end_page = -(-(off + c) // self.block_size)
        while self._nalloc[slot] < end_page:
            self._alloc_page(slot)
        padded = np.zeros((1, bucket), np.int32)
        padded[0, :c] = req.prompt[off:off + c]
        # the page-vector PREFIX covering context + chunk: its length
        # (off/bs context pages + the bucket's own span) is what makes
        # the chunk program span-specialized — a cold chunk attends
        # over C tokens, not cache_len. Entries past the allocated
        # count back only padding positions, whose writes drop.
        npages = off // self.block_size + -(-bucket // self.block_size)
        stalled = bool(self._active.any())
        t0 = time.perf_counter()
        tok, self.cache = self._tracker.track_call(
            "serving_engine.prefill", self._prefill_fn,
            self.params, self.cache, jnp.asarray(padded),
            np.int32(c), jnp.asarray(self._pages[slot, :npages]),
            np.float32(req.temperature), np.int32(req.top_k),
            self._seed())
        tok = int(np.asarray(tok))
        # the spec engine's draft model prefills the SAME chunk into
        # its own pool here (same page vector — one block table maps
        # both pools, so hits/preemption/eviction stay in lockstep)
        self._draft_chunk_hook(slot, padded, c, npages)
        now = time.perf_counter()
        # accumulate per-chunk device time; the histogram observes one
        # per-request total at the final chunk so its semantics match
        # the row-arena engine's (chunk-grain timing lives in the stall
        # histogram and engine_prefill_chunks_total)
        self._slot_prefill_s[slot] += now - t0
        self._m_chunks.inc()
        if stalled:
            self._m_stall.observe(now - t0)
        # publish the chunk's fully-written prompt blocks NOW (not at
        # prompt completion): a concurrent same-prefix request adopts
        # them instead of re-prefilling — a burst of shared-prefix
        # arrivals cold-prefills the prefix exactly once
        cold = 0
        for j in range(off // self.block_size,
                       (off + c) // self.block_size):
            self.pool.publish(self._slot_hashes[slot][j],
                              int(self._pages[slot, j]))
            self._m_prefix_miss.inc()
            cold += 1
        self._ev(req, "prefill_chunk", "n", now, tokens=int(c),
                 cold_blocks=cold,
                 hit_blocks=req.prefix_hit_tokens // self.block_size,
                 stalled_decoders=int(self._active.sum()) if stalled
                 else 0)
        self._slot_off[slot] = off + c
        if off + c < req.prompt.size:
            self._prefilling.append(slot)   # round-robin: one chunk per
            return                          # step, decode in between
        # final chunk: emit the sampled first token
        req.prefill_own_s = self._slot_prefill_s[slot]
        self._m_prefill_s.observe(self._slot_prefill_s[slot])
        self._m_prefills.inc()
        req.status = "running"
        if self._slot_forced[slot]:
            # preempt-resume replay: this prompt's first token was
            # emitted before the preemption — the chunk grid just
            # re-derived it (bitwise under greedy; forced regardless,
            # so sampled histories replay exactly too). Restore the
            # decode cursor, re-emit nothing; the lifecycle slices
            # still transition (prefill closes, decode reopens) so the
            # trace stays b/e-balanced through a replay.
            self._ev(req, "prefill", "e", now)
            if not req.decode_open:
                self._ev(req, "decode", "b", now)
                req.decode_open = True
            self._active[slot] = True
            self._pos[slot] = req.prompt.size
            self._last[slot] = self._slot_forced[slot].popleft()
            self._temp[slot] = req.temperature
            self._topk[slot] = req.top_k
            return
        if self._emit(req, tok, now):
            finished.append(req)            # blocks released by _finish;
            return                          # published ones park in LRU
        self._active[slot] = True
        self._pos[slot] = req.prompt.size
        self._last[slot] = tok
        self._temp[slot] = req.temperature
        self._topk[slot] = req.top_k

    def _consume_forced(self, slot: int) -> bool:
        forced = self._slot_forced[slot]
        if not forced:
            return False
        # replay: the decode step ran at the right (pos, last) and its
        # pool write is what matters; the sampled id re-derives the
        # known next token (bitwise under greedy), which advances the
        # cursor WITHOUT re-emitting — the caller already holds it
        self._pos[slot] += 1
        self._last[slot] = forced.popleft()
        return True

    def _finish(self, req: EngineRequest, reason: str, now: float):
        slot = req.slot
        if slot >= 0:
            for b in self._slot_blocks[slot]:
                self.pool.release(b)
            self.pool.unreserve(self._slot_reserved[slot])
            self._slot_blocks[slot] = []
            self._slot_hashes[slot] = []
            self._slot_reserved[slot] = 0
            self._nalloc[slot] = 0
            self._pages[slot, :] = 0
            self._pages_dev = None
            self._slot_forced[slot] = deque()
            self._uncharge_tenant(req)
        super()._finish(req, reason, now)

    def _schedule(self, finished: List[EngineRequest]):
        self._admit(finished)
        # With decoders in flight, at most ONE chunk runs per step —
        # the stall a prefill inflicts on them is bounded by a single
        # chunk program. With NOTHING decoding there is nobody to
        # stall: drain chunks back-to-back (a burst of arrivals reaches
        # its first tokens as fast as the row engine's monolithic
        # prefill would) until a finished prompt activates a decoder.
        while self._prefilling:
            self._prefill_chunk(finished)
            if finished:
                self._admit(finished)   # a one-token request freed its
                #                         slot mid-schedule
            if self._active.any():
                break

    def _pre_decode(self):
        # lazily allocate the page each active row is about to write
        # (reservation at admission guarantees this never fails)
        for slot in np.flatnonzero(self._active):
            if self._pos[slot] // self.block_size >= self._nalloc[slot]:
                self._alloc_page(slot)

    def _decode_extra(self):
        if self._pages_dev is None:
            self._pages_dev = self._jnp.asarray(self._pages)
        return (self._pages_dev,)

    def _update_gauges(self):
        super()._update_gauges()
        pool = self.pool
        self._m_blocks_in_use.set(pool.in_use)
        self._m_blocks_free.set(pool.free_count)
        self._m_blocks_cached.set(pool.cached_free_count)
        if pool.evictions > self._evictions_seen:
            self._m_evictions.inc(pool.evictions - self._evictions_seen)
            self._evictions_seen = pool.evictions

    # -- observability -----------------------------------------------------
    def health(self) -> dict:
        doc = super().health()
        doc.update({"block_size": self.block_size,
                    "blocks_total": self.num_blocks,
                    "blocks_in_use": self.pool.in_use,
                    "blocks_cached": self.pool.cached_free_count,
                    "prefix_cache_entries": self.pool.cached_count,
                    "chunk_tokens": self.chunk_tokens,
                    "kv_dtype": self.kv_dtype,
                    "kv_bytes_per_token": self.kv_bytes_per_token,
                    "pool_bytes": self.pool_bytes,
                    "preempted_queued": len(self._preempted),
                    "preemptions": int(self._m_preempts.value())})
        # per-token decode FLOPs: the recompute cost the fleet router's
        # fetch-vs-recompute crossover weighs against kv_bytes_per_token
        if self.decode_flops:
            doc["flops_per_token"] = float(self.decode_flops) \
                / max(self.batch, 1)
        # tier section: occupancy + a capped newest-first digest listing
        # per tier (hbm included) — what the router scrapes into its
        # fleet-global cache directory. Present even without a spill
        # store so an HBM-only replica still advertises its warm set.
        tiers_doc = (self.tiers.health() if self.tiers is not None
                     else {"digests": {}})
        tiers_doc["digests"]["hbm"] = [
            d.hex() for d in self.pool.cached_digests(512)]
        doc["tiers"] = tiers_doc
        tenants = sorted(set(self._tenant_used)
                         | set(self.tenant_budgets))
        if tenants:
            doc["tenants"] = {
                t: {"tokens_in_flight": self._tenant_used.get(t, 0),
                    "budget": self.tenant_budgets.get(t)}
                for t in tenants}
        return doc


class SpecDecodeEngine(PagedDecodeEngine):
    """Speculative decoding over the paged pool: a small DRAFT model
    proposes ``spec_k`` tokens per scheduler step, the TARGET model
    verifies the whole window in ONE batched pass, and an on-device
    accept/reject epilogue emits every accepted draft token plus one
    correction/bonus token — up to ``spec_k + 1`` tokens per step at
    one verify dispatch instead of ``spec_k + 1`` decode dispatches.

    **Shared pool.** The draft keeps its own device pool (its layer
    count / head geometry differ) but with the SAME (num_blocks,
    block_size) grid, indexed through the SAME page table and host
    :class:`~paddle_tpu.serving.blocks.BlockPool`: every writer (chunk
    prefill, verify, propose) writes both pools at the same physical
    rows, so a content-hash that certifies a target block certifies
    the draft rows beside it — prefix-cache hits, preemption and
    resume need no draft-side bookkeeping at all.

    **The step.** ``propose`` runs the k draft decode steps as one
    ``lax.scan``-fused program (greedy argmax between iterations — one
    dispatch, not k); ``verify`` runs the ``W = k+1`` window through
    ``transformer.verify_step_paged`` (every reduction keeps the
    decode step's axis lengths, so each window row is BITWISE the
    decode step it replaces) with the accept/reject sampling tail
    fused in. Greedy output is therefore bitwise-identical to the
    target-only engine — acceptance changes HOW FAST tokens emit,
    never WHICH tokens (pinned in tests/test_spec_decode.py).

    Rejected rows' KV stays in the pool above the rewound cursor where
    nothing reads it; the next window overwrites it. The multi-tenant
    scheduler (tiers, budgets, preempt-to-blocks) is inherited
    unchanged — on the eviction-fallback resume the forced history
    replays through verify windows, with ``draft_verify`` keeping the
    draft pool position-faithful where propose's own proposals would
    diverge from the forced tokens.
    """

    def __init__(self, prefill: Callable, decode: Callable, params,
                 cache, *, draft_params, draft_cache,
                 draft_prefill: Callable, propose: Callable,
                 verify: Callable, draft_verify: Callable, spec_k: int,
                 tracker: Optional[_ct.CompileTracker] = None,
                 **kw):
        if kw.get("tiers") is not None:
            # a spilled payload carries only TARGET pool rows; adopting
            # one would leave the draft pool's rows beside it stale —
            # the same desync import_prefix refuses below
            raise ValueError("SpecDecodeEngine does not support tiered "
                             "spill (draft pool rows cannot ride the "
                             "single-pool payload)")
        if tracker is None and "chunk_tokens" in kw:
            # the spec engine legitimately compiles roughly TWICE the
            # paged chunk-grid set (target + draft prefill programs)
            # plus propose/verify/draft_verify — keep the default
            # tracker's storm threshold above that
            chunk = min(int(kw.get("chunk_tokens", 64)),
                        int(kw["cache_len"]))
            spans = max(1, int(kw["cache_len"]) // max(chunk, 1))
            cb = kw.get("chunk_buckets")
            nb = len(tuple(cb)) if cb else len(
                default_chunk_buckets(chunk))
            tracker = _ct.CompileTracker(
                storm_threshold=2 * spans * nb + 8)
        super().__init__(prefill, decode, params, cache,
                         tracker=tracker, **kw)
        self.spec_k = int(spec_k)
        if self.spec_k < 1:
            raise ValueError(f"spec_k must be >= 1, got {spec_k}")
        self.draft_params = draft_params
        self.draft_cache = draft_cache
        self._draft_prefill_fn = draft_prefill
        self._propose_fn = propose
        self._verify_fn = verify
        self._draft_verify_fn = draft_verify
        self._valid = np.ones(self.batch, np.int32)
        reg = self.metrics
        self._m_spec_rounds = reg.counter(
            "engine_spec_rounds_total",
            "propose+verify rounds executed")
        self._m_spec_proposed = reg.counter(
            "engine_spec_proposed_tokens_total",
            "draft tokens proposed for verification")
        self._m_spec_accepted = reg.counter(
            "engine_spec_accepted_tokens_total",
            "proposed draft tokens the target accepted (the emitted "
            "correction/bonus token is not counted — acceptance "
            "measures the draft's hit rate, not throughput)")

    # -- construction ------------------------------------------------------
    @classmethod
    def from_params(cls, params, cfg, draft_params, draft_cfg, *,
                    spec_k: int = 4, batch: int, cache_len: int,
                    block_size: int = 16,
                    num_blocks: Optional[int] = None,
                    chunk_tokens: int = 64,
                    chunk_buckets: Optional[Sequence[int]] = None,
                    seed: Optional[int] = None,
                    pallas: Optional[str] = None,
                    kv_dtype: Optional[str] = None, **kw):
        """In-process spec engine: jit the target paged pair plus the
        draft program set against live params. The draft must share
        the target's vocab (its proposals are target tokens) and cover
        ``cache_len`` positions; everything else about it may differ —
        smaller is the point."""
        import jax
        from paddle_tpu.models import transformer
        from paddle_tpu.ops.pallas import policy as _pallas_policy
        from paddle_tpu.serving import sampling
        if draft_cfg.vocab != cfg.vocab:
            raise ValueError(
                f"draft vocab {draft_cfg.vocab} != target vocab "
                f"{cfg.vocab}: proposals must be target token ids")
        if cache_len > cfg.max_len or cache_len > draft_cfg.max_len:
            raise ValueError(
                f"cache_len {cache_len} exceeds max_len (target "
                f"{cfg.max_len}, draft {draft_cfg.max_len})")
        nb = int(num_blocks if num_blocks is not None
                 else batch * (cache_len // block_size))
        prefill_fn, decode_fn = sampling.paged_step_fns(
            cfg, block_size, pallas=pallas)
        spec = sampling.paged_spec_fns(cfg, draft_cfg, block_size,
                                       spec_k, pallas=pallas)
        pool = transformer.init_block_pool(cfg, nb, block_size,
                                           kv_dtype=kv_dtype)
        draft_pool = transformer.init_block_pool(draft_cfg, nb,
                                                 block_size)
        jdf = jax.jit(decode_fn)
        jvf = jax.jit(spec["verify"])
        if "decode_flops" not in kw:
            # MFU accounting numerator = ONE VERIFY ROUND's model FLOPs
            # (the program this engine actually dispatches per step)
            pages = np.zeros((batch, cache_len // block_size), np.int32)
            W = int(spec_k) + 1
            cost = _costs.lowered_cost(
                jvf, params, pool, np.zeros((batch, W), np.int32),
                np.zeros(batch, np.int32), np.ones(batch, np.int32),
                np.zeros(batch, bool), pages,
                np.zeros(batch, np.float32), np.zeros(batch, np.int32),
                np.int32(0))
            kw["decode_flops"] = (cost or {}).get("flops")
        return cls(jax.jit(prefill_fn), jdf, params, pool,
                   draft_params=draft_params, draft_cache=draft_pool,
                   draft_prefill=jax.jit(spec["draft_prefill"]),
                   propose=jax.jit(spec["propose"]), verify=jvf,
                   draft_verify=jax.jit(spec["draft_verify"]),
                   spec_k=spec_k, batch=batch, cache_len=cache_len,
                   block_size=block_size, num_blocks=nb,
                   chunk_tokens=chunk_tokens,
                   chunk_buckets=chunk_buckets, seed=seed,
                   kv_dtype=kv_dtype,
                   pallas_mode=_pallas_policy.pallas_mode(pallas), **kw)

    # -- scheduler ---------------------------------------------------------
    def _draft_chunk_hook(self, slot: int, padded, c: int, npages: int):
        jnp = self._jnp
        self.draft_cache = self._tracker.track_call(
            "serving_engine.draft_prefill", self._draft_prefill_fn,
            self.draft_params, self.draft_cache, jnp.asarray(padded),
            np.int32(c), jnp.asarray(self._pages[slot, :npages]))

    def _pre_decode(self):
        # a verify round writes up to `valid` rows per slot — allocate
        # every page the window touches (the admission reservation
        # covers them: pos + valid - 1 <= Tp + max_new - 1)
        for slot in np.flatnonzero(self._active):
            end = int(self._pos[slot]) + int(self._valid[slot]) - 1
            while end // self.block_size >= self._nalloc[slot]:
                self._alloc_page(slot)

    def step(self) -> List[EngineRequest]:
        """One scheduler iteration: admission + chunk prefill as the
        paged engine, then ONE propose+verify round for everything in
        flight (instead of one decode step)."""
        finished: List[EngineRequest] = []
        self._schedule(finished)
        if self._active.any():
            jnp = self._jnp
            B, W = self.batch, self.spec_k + 1
            valid = np.ones(B, np.int32)
            forced = np.zeros(B, bool)
            for slot in np.flatnonzero(self._active):
                req = self._slot_req[slot]
                if self._slot_forced[slot]:
                    forced[slot] = True
                    valid[slot] = min(W, 1 + len(self._slot_forced[slot]))
                else:
                    cap = (req.prompt.size + req.max_new
                           - int(self._pos[slot]) - 1)
                    valid[slot] = max(min(W, cap), 1)
            self._valid = valid
            self._pre_decode()
            t0 = time.perf_counter()
            pages_dev = self._decode_extra()[0]
            window = np.zeros((B, W), np.int32)
            window[:, 0] = self._last
            act_prop = self._active & ~forced
            if act_prop.any():
                props, self.draft_cache = self._tracker.track_call(
                    "serving_engine.propose", self._propose_fn,
                    self.draft_params, self.draft_cache,
                    jnp.asarray(self._last), jnp.asarray(self._pos),
                    jnp.asarray(act_prop), jnp.asarray(valid),
                    pages_dev)
                window[:, 1:] = np.asarray(props)
            for slot in np.flatnonzero(forced):
                # replay window: the known history IS the proposal set
                f = list(self._slot_forced[slot])[:W - 1]
                window[slot, 1:1 + len(f)] = f
            win_dev = jnp.asarray(window)
            if forced.any():
                # keep the draft pool position-faithful on replay rows
                # (propose writes were masked off for these slots)
                self.draft_cache = self._tracker.track_call(
                    "serving_engine.draft_verify",
                    self._draft_verify_fn, self.draft_params,
                    self.draft_cache, win_dev, jnp.asarray(self._pos),
                    jnp.asarray(valid),
                    jnp.asarray(forced & self._active), pages_dev)
            X, n, self.cache = self._tracker.track_call(
                "serving_engine.verify", self._verify_fn,
                self.params, self.cache, win_dev,
                jnp.asarray(self._pos), jnp.asarray(valid),
                jnp.asarray(self._active), pages_dev,
                jnp.asarray(self._temp), jnp.asarray(self._topk),
                self._seed())
            X, n = np.asarray(X), np.asarray(n)
            now = time.perf_counter()
            self._m_step_s.observe(now - t0)
            self._m_steps.inc()
            self._m_spec_rounds.inc()
            mfu = _costs.mfu(self.decode_flops, now - t0,
                             self._peak_flops)
            if mfu is not None:
                self._m_decode_mfu.set(mfu)
            for slot in np.flatnonzero(self._active):
                req = self._slot_req[slot]
                if forced[slot]:
                    f = self._slot_forced[slot]
                    m = min(int(valid[slot]), len(f))
                    for _ in range(m):
                        tok = f.popleft()
                    self._pos[slot] += m
                    self._last[slot] = tok
                    continue
                nprop = max(int(valid[slot]) - 1, 0)
                m = int(n[slot])
                self._m_spec_proposed.inc(nprop)
                self._m_spec_accepted.inc(max(m - 1, 0))
                fin, used = False, 0
                for j in range(m):
                    used += 1
                    if self._emit(req, int(X[slot, j]), now):
                        fin = True
                        break
                if fin:
                    finished.append(req)
                else:
                    self._pos[slot] += used
                    self._last[slot] = int(X[slot, used - 1])
        self._update_gauges()
        return finished

    def import_prefix(self, payload: bytes) -> int:
        """Refused on the spec engine: the transfer wire ships TARGET
        pool blocks only, and adopting them would break the shared-pool
        invariant (every content hash certifies the draft rows beside
        it — imported blocks have no draft rows, so propose would read
        garbage KV). Route disaggregated decode at target-only
        replicas; a spec replica still serves as a prefill exporter."""
        raise ValueError("import_prefix: a SpecDecodeEngine cannot "
                         "adopt transferred blocks (no draft-pool rows "
                         "travel on the wire) — use a target-only "
                         "decode replica for P/D disaggregation")

    # -- observability -----------------------------------------------------
    def acceptance_rate(self) -> Optional[float]:
        """Lifetime draft acceptance: accepted / proposed (None before
        the first proposal). 1.0 means every draft token survived
        verification — e.g. a draft identical to the target under
        greedy sampling."""
        prop = self._m_spec_proposed.value()
        if not prop:
            return None
        return self._m_spec_accepted.value() / prop

    def compile_counts(self) -> Dict[str, int]:
        c = super().compile_counts()
        c.update({
            "draft_prefill": self._tracker.count(
                "serving_engine.draft_prefill"),
            "propose": self._tracker.count("serving_engine.propose"),
            "verify": self._tracker.count("serving_engine.verify"),
            "draft_verify": self._tracker.count(
                "serving_engine.draft_verify")})
        return c

    def health(self) -> dict:
        doc = super().health()
        acc = self.acceptance_rate()
        doc["spec"] = {
            "k": self.spec_k,
            "rounds": int(self._m_spec_rounds.value()),
            "proposed": int(self._m_spec_proposed.value()),
            "accepted": int(self._m_spec_accepted.value()),
            "acceptance_rate": round(acc, 4) if acc is not None
            else None}
        return doc
