"""Tiered prefix-cache spill store: host DRAM → disk, below the HBM pool.

The block pool's LRU eviction used to mean a cached prefix block was
GONE — the next request paid a full cold prefill even when the same
system prompt had been warm seconds earlier. :class:`TieredStore` turns
eviction into demotion: the engine serializes each evicted refcount-0
cached block with the PR-15 transfer wire (``serving/transfer.
serialize_blocks`` — values + scales + layout/kv_dtype stamps, so the
WIRE format is the SPILL format and re-adoption reuses the ordinary
``import_prefix`` machinery verbatim) and parks the payload here:

- **DRAM tier** — a bytes-bounded in-memory LRU of per-digest payloads.
  Pressure demotes oldest-first to the disk tier (or drops, when no
  disk tier is configured).
- **Disk tier** — a bytes-bounded directory of checksummed files, one
  per digest, published ATOMICALLY (write to a dot-prefixed temp name,
  ``os.replace`` — the io/checkpoint publish discipline, minus the
  fsync: a cache needs torn-file DETECTION, not durability, and the
  checksum provides it), so a crashed writer leaves either a whole
  file or an invisible temp, and an OS-crash-torn file reads back as
  a quarantined miss. Pressure deletes oldest-first.

Per-digest (not per-chain) granularity is sound because a content-chain
digest certifies its WHOLE prefix: re-adoption walks the chain in order
and stops at the first tier miss, exactly like engine admission walks
the HBM prefix cache.

Robustness is a first-class contract: a corrupt or truncated disk file
(bad magic, checksum mismatch, short read) is a MISS, never an
exception on the admission path — the file is quarantined (renamed
``*.corrupt``) and counted (``engine_tier_corrupt_total``). Same for a
payload whose stamp no longer matches the pool: the engine calls
:meth:`quarantine` and moves on.

Capacity arithmetic rides the kv_dtype for free: an int8 pool's block
payloads are ~4x smaller than fp32's (int4 ~6x with scale rows), so the
same DRAM/disk budgets hold proportionally deeper prefix history —
every tier inherits PR-12's quantization win.

Pure host state (numpy + stdlib; jax never touched) — unit-testable
without a device, like ``serving/blocks.py``.
"""

import hashlib
import os
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from paddle_tpu.observe import metrics as _metrics

TIERS = ("dram", "disk")

# disk-tier file framing: magic + 16-byte blake2b of the payload, then
# the payload itself (which carries its own PTKV stamp inside)
_FILE_MAGIC = b"PTT1"
_SUM_BYTES = 16


def _payload_sum(payload: bytes) -> bytes:
    return hashlib.blake2b(payload, digest_size=_SUM_BYTES).digest()


class TieredStore:
    """Bounded DRAM→disk spill store for serialized prefix blocks.

    ``dram_bytes`` caps the in-memory tier (0 disables it — demotions
    go straight to disk); ``disk_bytes`` caps the disk tier (0 or a
    missing ``disk_dir`` disables it — DRAM pressure then drops
    oldest-first). ``registry`` receives the tier gauges/counters under
    the ``engine_tier_*`` names so one engine ``/metrics`` scrape (and,
    through the fleet aggregator, one router scrape) answers for the
    whole hierarchy.

    An existing ``disk_dir`` is re-adopted on construction: published
    ``*.kv`` files are re-indexed oldest-mtime-first (the post-restart
    warm start), temp and quarantined files are ignored. Integrity is
    verified lazily at :meth:`get` — a torn or bit-flipped file from a
    killed process is caught by the checksum then, quarantined, and
    served as a miss.
    """

    def __init__(self, *, dram_bytes: int = 0, disk_bytes: int = 0,
                 disk_dir: Optional[str] = None,
                 registry: Optional[_metrics.Registry] = None):
        self.dram_bytes = max(int(dram_bytes), 0)
        self.disk_bytes = max(int(disk_bytes), 0)
        self.disk_dir = disk_dir if (disk_dir and self.disk_bytes) \
            else None
        # digest -> payload bytes (DRAM) / file size (disk); both LRU:
        # oldest first, move_to_end on hit
        self._dram: "OrderedDict[bytes, bytes]" = OrderedDict()
        self._disk: "OrderedDict[bytes, int]" = OrderedDict()
        self._dram_used = 0
        self._disk_used = 0
        # bumped every time a digest leaves the store ENTIRELY (budget
        # eviction, quarantine, unreadable file) — the fleet cache
        # directory's invalidation fence. A router that advertised this
        # replica's digests compares the epoch stamped on health docs
        # AND on every op result: a bump between health scrapes tells
        # it the advertisement is stale NOW, not at the next cadence.
        # Demotions (dram -> disk) do not bump: the digest still serves.
        self.eviction_epoch = 0
        reg = registry if registry is not None else _metrics.Registry()
        self.metrics = reg
        self._m_bytes = reg.gauge(
            "engine_tier_bytes", "spill bytes resident per tier "
            "(label tier) — HBM occupancy lives in the block gauges")
        self._m_entries = reg.gauge(
            "engine_tier_entries", "spilled block payloads resident "
            "per tier (label tier)")
        self._m_demotions = reg.counter(
            "engine_tier_demotions_total", "block payloads written "
            "INTO a tier (label tier): hbm->dram evictions land in "
            "dram, dram pressure cascades into disk")
        self._m_promotions = reg.counter(
            "engine_tier_promotions_total", "block payloads served "
            "OUT of a tier back toward HBM (label tier); a disk hit "
            "also refills dram")
        self._m_evictions = reg.counter(
            "engine_tier_evictions_total", "payloads dropped off a "
            "tier's cold end (label tier) — the working set outran "
            "the tier budget")
        self._m_corrupt = reg.counter(
            "engine_tier_corrupt_total", "disk-tier files quarantined "
            "(bad magic, checksum mismatch, short read, stamp "
            "mismatch at adoption) — each one served as a miss, "
            "never an exception")
        for t in TIERS:
            self._m_bytes.set(0, tier=t)
            self._m_entries.set(0, tier=t)
        if self.disk_dir:
            os.makedirs(self.disk_dir, exist_ok=True)
            self._scan_disk()

    # -- introspection -----------------------------------------------------
    @property
    def dram_used(self) -> int:
        return self._dram_used

    @property
    def disk_used(self) -> int:
        return self._disk_used

    def tier_of(self, digest: bytes) -> Optional[str]:
        if digest in self._dram:
            return "dram"
        if digest in self._disk:
            return "disk"
        return None

    def __contains__(self, digest) -> bool:
        return self.tier_of(digest) is not None

    def digests(self, limit: Optional[int] = None) -> Dict[str, List[str]]:
        """Hex digests resident per tier, NEWEST first (the warm end is
        what a fleet directory wants when the listing is capped)."""
        out = {}
        for t, d in (("dram", self._dram), ("disk", self._disk)):
            hexes = [k.hex() for k in reversed(d)]
            out[t] = hexes[:limit] if limit else hexes
        return out

    def health(self, digest_limit: int = 512) -> dict:
        """The ``/healthz`` ``tiers`` section: occupancy + a capped
        newest-first digest listing per tier — what the router scrapes
        into its fleet-global cache directory."""
        return {
            "dram": {"bytes": self._dram_used,
                     "capacity_bytes": self.dram_bytes,
                     "entries": len(self._dram)},
            "disk": {"bytes": self._disk_used,
                     "capacity_bytes": self.disk_bytes,
                     "entries": len(self._disk)},
            "eviction_epoch": self.eviction_epoch,
            "digests": self.digests(digest_limit)}

    # -- demotion ----------------------------------------------------------
    def put(self, digest: bytes, payload: bytes):
        """Demote one block payload into the hierarchy (DRAM first).
        A payload larger than every tier budget is dropped outright; a
        digest already resident just refreshes its recency."""
        digest = bytes(digest)
        if digest in self._dram:
            self._dram.move_to_end(digest)
            return
        if digest in self._disk:
            self._disk.move_to_end(digest)
            return
        if self.dram_bytes >= len(payload):
            self._dram[digest] = payload
            self._dram_used += len(payload)
            self._m_demotions.inc(tier="dram")
            while self._dram_used > self.dram_bytes:
                old, old_payload = self._dram.popitem(last=False)
                self._dram_used -= len(old_payload)
                self._spill_to_disk(old, old_payload)
        else:
            self._spill_to_disk(digest, payload, direct=True)
        self._sync_gauges()

    def _spill_to_disk(self, digest: bytes, payload: bytes,
                       direct: bool = False):
        if self.disk_dir is None or self.disk_bytes < len(payload):
            self._m_evictions.inc(tier="dram" if not direct else "disk")
            self.eviction_epoch += 1
            return
        path = self._path(digest)
        tmp = os.path.join(self.disk_dir,
                           f".tmp-{digest.hex()}.{os.getpid()}")
        blob = _FILE_MAGIC + _payload_sum(payload) + payload
        try:
            # no fsync: this is a CACHE, not a checkpoint — a torn
            # file after an OS crash reads back as a checksum miss
            # (quarantined, recomputed), so durability buys nothing
            # and the spill sits on the alloc critical path
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)      # atomic publish: a reader (or a
            #                            restart scan) sees the whole
            #                            file or nothing
        except OSError:
            # a full/readonly disk degrades the tier to a drop, never
            # an exception on the eviction path
            try:
                os.unlink(tmp)
            except OSError:
                pass
            self._m_evictions.inc(tier="disk")
            self.eviction_epoch += 1
            return
        if digest in self._disk:       # republish refreshed the bytes
            self._disk_used -= self._disk.pop(digest)
        self._disk[digest] = len(blob)
        self._disk_used += len(blob)
        self._m_demotions.inc(tier="disk")
        while self._disk_used > self.disk_bytes:
            old, size = self._disk.popitem(last=False)
            self._disk_used -= size
            try:
                os.unlink(self._path(old))
            except OSError:
                pass
            self._m_evictions.inc(tier="disk")
            if old not in self._dram:
                self.eviction_epoch += 1

    # -- promotion ---------------------------------------------------------
    def get(self, digest: bytes) -> Optional[Tuple[str, bytes]]:
        """``(tier, payload)`` for a resident digest, else None. A disk
        hit verifies the checksum (corrupt/truncated → quarantined,
        counted, miss) and refills the DRAM tier so a hot chain climbs
        back up the hierarchy."""
        digest = bytes(digest)
        payload = self._dram.get(digest)
        if payload is not None:
            self._dram.move_to_end(digest)
            self._m_promotions.inc(tier="dram")
            return "dram", payload
        if digest not in self._disk:
            return None
        payload = self._read_disk(digest)
        if payload is None:
            return None
        self._m_promotions.inc(tier="disk")
        if self.dram_bytes >= len(payload):
            # refill DRAM WITHOUT re-demoting the cascade back onto
            # this same digest's disk slot (it stays resident on disk;
            # double-residency is fine — tier_of reports the fast one)
            self._dram[digest] = payload
            self._dram_used += len(payload)
            while self._dram_used > self.dram_bytes:
                old, old_payload = self._dram.popitem(last=False)
                self._dram_used -= len(old_payload)
                if old != digest:
                    self._spill_to_disk(old, old_payload)
            self._sync_gauges()
        return "disk", payload

    def _read_disk(self, digest: bytes) -> Optional[bytes]:
        path = self._path(digest)
        try:
            with open(path, "rb") as f:
                blob = f.read()
        except OSError:
            self._drop_disk(digest)
            return None
        head = len(_FILE_MAGIC) + _SUM_BYTES
        if (len(blob) < head or blob[:len(_FILE_MAGIC)] != _FILE_MAGIC
                or _payload_sum(blob[head:])
                != blob[len(_FILE_MAGIC):head]):
            self.quarantine(digest)
            return None
        return blob[head:]

    def _drop_disk(self, digest: bytes):
        size = self._disk.pop(digest, None)
        if size is not None:
            self._disk_used -= size
            if digest not in self._dram:
                self.eviction_epoch += 1
            self._sync_gauges()

    def quarantine(self, digest: bytes):
        """Remove ``digest`` from the store and park its disk file (if
        any) under ``*.corrupt`` — called on checksum failure here and
        by the engine on a stamp mismatch at adoption. Counted; never
        raises."""
        digest = bytes(digest)
        payload = self._dram.pop(digest, None)
        if payload is not None:
            self._dram_used -= len(payload)
            if digest not in self._disk:
                self.eviction_epoch += 1
        if digest in self._disk:
            self._drop_disk(digest)
            path = self._path(digest)
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
        elif payload is None:
            return                     # nothing resident: nothing to count
        self._m_corrupt.inc()
        self._sync_gauges()

    # -- disk scan / bookkeeping -------------------------------------------
    def _path(self, digest: bytes) -> str:
        return os.path.join(self.disk_dir, digest.hex() + ".kv")

    def _scan_disk(self):
        """Re-adopt a previous process's published files (oldest first
        so LRU age survives the restart); temp files from a killed
        writer are deleted, quarantined files ignored. Content is NOT
        verified here — the checksum runs at get(), so a torn file
        costs nothing until (and unless) its digest is asked for."""
        entries = []
        for fn in os.listdir(self.disk_dir):
            path = os.path.join(self.disk_dir, fn)
            if fn.startswith(".tmp-"):
                try:
                    os.unlink(path)    # a writer died mid-publish; the
                except OSError:        # temp was never visible to get()
                    pass
                continue
            if not fn.endswith(".kv"):
                continue
            try:
                digest = bytes.fromhex(fn[:-3])
                st = os.stat(path)
            except (ValueError, OSError):
                continue
            entries.append((st.st_mtime, digest, st.st_size))
        budget_ok = []
        total = 0
        for mtime, digest, size in sorted(entries, reverse=True):
            # newest first under the budget; anything past it is stale
            # spill from a larger previous configuration
            if total + size > self.disk_bytes:
                try:
                    os.unlink(self._path(digest))
                except OSError:
                    pass
                continue
            total += size
            budget_ok.append((mtime, digest, size))
        for _, digest, size in sorted(budget_ok):
            self._disk[digest] = size
            self._disk_used += size
        self._sync_gauges()

    def _sync_gauges(self):
        self._m_bytes.set(self._dram_used, tier="dram")
        self._m_bytes.set(self._disk_used, tier="disk")
        self._m_entries.set(len(self._dram), tier="dram")
        self._m_entries.set(len(self._disk), tier="disk")
