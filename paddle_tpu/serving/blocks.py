"""Host-side block allocator + prefix cache for the paged KV arena.

The device side is a flat pool (``models/transformer.init_block_pool``,
[L, num_blocks·block_size, Hkv, Dh]); this module owns the HOST
bookkeeping that decides which aligned ``block_size`` span backs which
logical positions of which request:

- **free list** — blocks never touched or fully released;
- **refcounts** — a block holding a shared prompt prefix is referenced
  by every slot whose page table maps it (prefix hits call
  :meth:`share`); it frees only when the LAST holder releases;
- **prefix cache** — full PROMPT blocks are published under a
  content-chain hash (:func:`chain_hash` over the parent digest + the
  block's token ids, so a hit certifies the whole prefix, not one
  block); a later request whose prompt starts with the same token
  blocks maps them straight into its page table and skips their
  prefill compute entirely;
- **LRU** — a cached block whose refcount drops to 0 parks in an LRU
  instead of the free list: it still serves future hits for free, and
  allocation pressure evicts oldest-first (eviction un-publishes the
  hash — the KV bytes are about to be overwritten).

Everything here is pure-python/numpy host state — no jax — so block
lifecycle is unit-testable without a device
(tests/test_paged_engine.py).
"""

import hashlib
from collections import OrderedDict, deque
from typing import Dict, List, Optional

import numpy as np

# chain root: the hash of "no prefix" (any constant salt works; a named
# one keeps digests stable across processes for debugging)
ROOT_HASH = b"paddle-tpu-paged-kv-root"


def chain_hash(parent: bytes, tokens) -> bytes:
    """Digest of one full prompt block GIVEN its prefix digest — equal
    digests certify equal (prefix + block) token content, which is what
    makes a cached block's KV reusable verbatim."""
    h = hashlib.blake2b(parent, digest_size=16)
    h.update(np.asarray(tokens, np.int32).tobytes())
    return h.digest()


def prompt_block_hashes(prompt: np.ndarray, block_size: int
                        ) -> List[bytes]:
    """Chain digests of every FULL block of ``prompt`` (the tail partial
    block is never cached — decode keeps writing into it)."""
    out, h = [], ROOT_HASH
    for i in range(len(prompt) // block_size):
        h = chain_hash(h, prompt[i * block_size:(i + 1) * block_size])
        out.append(h)
    return out


class BlockPool:
    """Refcounted allocator over ``num_blocks`` KV blocks with a
    content-addressed prefix cache and LRU eviction of refcount-0
    cached blocks.

    Reservation protocol: the engine reserves a request's worst-case
    block count (prompt + max_new, minus prefix hits) at ADMISSION via
    :meth:`reserve`, then allocates lazily as positions are actually
    written (:meth:`alloc` consumes one reservation). Decode therefore
    never stalls mid-flight on an empty pool — admission is the only
    backpressure point."""

    def __init__(self, num_blocks: int, block_size: int):
        if num_blocks < 1 or block_size < 1:
            raise ValueError(f"need >=1 blocks of >=1 tokens, got "
                             f"{num_blocks}x{block_size}")
        self.num_blocks = int(num_blocks)
        self.block_size = int(block_size)
        self._free = deque(range(self.num_blocks))
        self._ref = np.zeros(self.num_blocks, np.int64)
        self._hash: Dict[int, bytes] = {}       # cached block -> digest
        self._index: Dict[bytes, int] = {}      # digest -> cached block
        self._lru: "OrderedDict[int, None]" = OrderedDict()
        self._reserved = 0
        self.evictions = 0                      # lifetime LRU evictions
        # demotion hook: called as on_evict(block, digest) when alloc()
        # evicts a refcount-0 cached block, BEFORE the new holder's
        # refcount is set — the KV bytes still match the digest at that
        # instant (nothing has scattered over them yet), which is what
        # lets a tiered store serialize the block on its way out.
        # unpublish() does NOT fire it: there the bytes are about to
        # stop matching the digest, so there is nothing worth spilling.
        self.on_evict = None

    # -- occupancy ---------------------------------------------------------
    @property
    def free_count(self) -> int:
        """Blocks holding nothing at all (not even cached content)."""
        return len(self._free)

    @property
    def cached_free_count(self) -> int:
        """Refcount-0 blocks parked in the LRU (evictable cache)."""
        return len(self._lru)

    @property
    def allocatable(self) -> int:
        """Blocks an alloc() could return right now (free + evictable)."""
        return len(self._free) + len(self._lru)

    @property
    def in_use(self) -> int:
        """Blocks referenced by at least one live slot."""
        return self.num_blocks - self.allocatable

    @property
    def reserved(self) -> int:
        return self._reserved

    @property
    def cached_count(self) -> int:
        """Blocks published in the prefix cache (any refcount)."""
        return len(self._index)

    def refcount(self, block: int) -> int:
        return int(self._ref[block])

    def lru_oldest(self) -> Optional[int]:
        """The refcount-0 cached block ``alloc()`` would evict next
        (None when the LRU is empty) — lets a bulk adopter
        (``import_prefix``) stop before eating its own chain head."""
        return next(iter(self._lru), None)

    @property
    def idle(self) -> bool:
        """True when no slot holds a block and nothing is reserved —
        the no-leak invariant a drained engine must restore."""
        return self._reserved == 0 and self.in_use == 0

    # -- reservation -------------------------------------------------------
    def can_reserve(self, n: int) -> bool:
        return self._reserved + n <= self.allocatable

    def reserve(self, n: int):
        if not self.can_reserve(n):
            raise RuntimeError(
                f"reserve({n}): only {self.allocatable - self._reserved} "
                f"unreserved blocks left of {self.num_blocks}")
        self._reserved += n

    def unreserve(self, n: int):
        if n > self._reserved:
            raise RuntimeError(f"unreserve({n}) exceeds reservation "
                               f"{self._reserved}")
        self._reserved -= n

    # -- lifecycle ---------------------------------------------------------
    def alloc(self) -> int:
        """One private block (refcount 1), consuming one reservation.
        Prefers never-cached free blocks; under pressure evicts the
        LRU-oldest refcount-0 cached block (un-publishing its hash)."""
        if self._reserved < 1:
            raise RuntimeError("alloc() without a reservation")
        self._reserved -= 1
        if self._free:
            b = self._free.popleft()
        elif self._lru:
            b, _ = self._lru.popitem(last=False)      # oldest first
            h = self._hash.pop(b)
            del self._index[h]
            self.evictions += 1
            if self.on_evict is not None:
                self.on_evict(b, h)
        else:
            raise RuntimeError("block pool exhausted despite reservation")
        self._ref[b] = 1
        return b

    def share(self, block: int):
        """One more holder of ``block`` (a prefix-cache hit). Revives a
        refcount-0 cached block out of the LRU."""
        if self._ref[block] == 0:
            if block not in self._lru:
                raise RuntimeError(f"share({block}): block is free, "
                                   f"not cached")
            del self._lru[block]
        self._ref[block] += 1

    def release(self, block: int):
        """Drop one holder. At refcount 0 a cache-published block parks
        in the LRU (MRU end); a private one returns to the free list."""
        if self._ref[block] < 1:
            raise RuntimeError(f"release({block}): refcount already 0")
        self._ref[block] -= 1
        if self._ref[block] == 0:
            if block in self._hash:
                self._lru[block] = None
            else:
                self._free.append(block)

    # -- prefix cache ------------------------------------------------------
    def cached_digests(self, limit: Optional[int] = None) -> List[bytes]:
        """Digests currently published in the prefix cache, hottest
        first (refcount>0 carriers, then LRU newest-to-oldest) — the
        HBM rows of a fleet cache directory's per-replica listing."""
        hot = [self._hash[b] for b in self._hash if self._ref[b] > 0]
        cold = [self._hash[b] for b in reversed(self._lru)]
        out = hot + cold
        return out[:limit] if limit else out

    def lookup(self, digest: bytes) -> Optional[int]:
        """Cached block for ``digest`` (LRU-parked ones included), or
        None."""
        return self._index.get(digest)

    def publish(self, digest: bytes, block: int):
        """Register ``block`` as the cached carrier of ``digest``.
        No-op when the digest is already cached (first writer wins) or
        the block already carries another digest."""
        if digest in self._index or block in self._hash:
            return
        self._index[digest] = block
        self._hash[block] = digest

    def unpublish(self, block: int):
        """Drop ``block``'s prefix-cache entry, if any. The
        preempt-to-blocks resume path calls this on the revived PARTIAL
        tail block right before decoding writes into it again — its
        bytes are about to stop matching the published digest. A
        refcount-0 LRU-parked block loses its cache-worthiness too and
        returns to the plain free list."""
        h = self._hash.pop(block, None)
        if h is None:
            return
        del self._index[h]
        if block in self._lru:
            del self._lru[block]
            self._free.append(block)
