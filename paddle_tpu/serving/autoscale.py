"""Fleet control plane: self-healing, wedge-kills, and SLO-driven
elastic capacity over a serving fleet.

The :class:`FleetController` is the first component that COMMANDS the
fleet rather than observing it. It closes three loops no component
closes alone, with no human in any of them:

**Self-healing.** A replica the router marks ``dead`` (SIGKILL, OOM,
wedge hammer below) is respawned under its OWN name by the fleet
backend — so a ``{name}``-templated spill directory carries over and
the replacement's disk tier re-adopts the dead incarnation's published
prefixes on startup — then re-registered with the router
(:meth:`~paddle_tpu.serving.router.Router.replace_replica`) and
re-warmed: the prefixes the router recently placed there are
re-imported over the KV transfer wire from warm survivors
(:meth:`~paddle_tpu.serving.router.Router.rewarm_replica`). Restart
policy is the training supervisor's, verbatim — the extracted
:class:`~paddle_tpu.runtime.supervisor.RestartBudget` gives each
replica a consecutive-unstable budget with decorrelated-jitter backoff
and stable-incarnation refill; an exhausted budget retires the name
(``fleet_heal_abandoned_total``) instead of crash-looping.

**Wedge detection.** A replica that is transport-alive but has made no
progress on a non-empty outstanding set for ``wedge_timeout_s`` is
SIGKILLed (``fleet_wedge_kills_total``) — the dead transport then
routes through the ordinary requeue + healing path. Liveness is the
transport's verdict; PROGRESS is the controller's.

**Elastic capacity.** Queue depth and the router's TTFT SLO burn rate,
sustained past a hysteresis window, spawn replicas up to
``max_replicas`` — bounded by a spawn token budget
(``spawn_budget`` per ``spawn_budget_window_s``) so flapping load
cannot thrash the fleet. A sustained idle fleet drains its newest
surplus replica through the graceful SIGTERM path (admissions stop,
in-flight finishes, then the process exits 0) down to
``min_replicas``.

Every decision lands in the flight recorder ring (dumped with any
post-mortem) and as ``fleet_*`` metrics in the ROUTER registry, so the
one ``/metrics`` + ``/healthz`` scrape that answers for the fleet
answers for its control plane too.

The controller is single-threaded and steppable like everything else
in the serving stack: drive :meth:`step` alongside ``router.step()``
(the ``route`` CLI loop does both). The fleet backend is anything with
the :class:`ServingFleet` named-lifecycle surface — ``spawn(name)`` /
``handle(name)`` / ``stop(name)`` / ``kill_name(name)`` —
:class:`InProcessFleet` provides it over in-process engines for tests
and the chaos bench's equal-chip A/B.
"""

import logging
import time
from collections import deque
from typing import Dict, Optional

from paddle_tpu.observe import flight as _flight
from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.runtime.supervisor import RestartBudget

logger = logging.getLogger(__name__)


class InProcessFleet:
    """The ServingFleet named-lifecycle surface over IN-PROCESS
    engines: ``engine_factory(name)`` builds the engine a spawned
    replica wraps (an ``EngineReplica`` handle). Used by the fast
    controller tests and the chaos bench, where process/socket
    overhead would drown the signal being measured."""

    def __init__(self, engine_factory):
        self._factory = engine_factory
        self._handles: Dict[str, object] = {}

    def spawn(self, name: Optional[str] = None) -> dict:
        from paddle_tpu.serving.replica import EngineReplica
        if name is None:
            k = 0
            while f"replica{k}" in self._handles:
                k += 1
            name = f"replica{k}"
        cur = self._handles.get(name)
        if cur is not None and cur.alive():
            raise RuntimeError(f"replica {name!r} is still running")
        self._handles[name] = EngineReplica(self._factory(name),
                                            name=name)
        return {"name": name}

    def handle(self, name: str):
        return self._handles[name]

    def stop(self, name: str):
        h = self._handles.get(name)
        if h is not None:
            h.close()

    def kill_name(self, name: str):
        h = self._handles.get(name)
        if h is not None:
            h.kill()


class _HealState:
    """Per-name healing ledger."""

    def __init__(self, budget: RestartBudget, now: float):
        self.budget = budget
        self.launched_t = now       # current incarnation's birth
        self.next_attempt_t = 0.0   # backoff gate
        self.dead_seen = False      # this death already debited
        self.abandoned = False


class FleetController:
    """One control loop over (router, fleet). See module docstring.

    ``scale_up_queue``/``scale_up_burn``: either signal sustained for
    ``hysteresis_s`` triggers a scale-up (0 disables that signal).
    ``scale_down_idle_s``: a fully idle fleet sustained this long
    drains one surplus replica. ``wedge_timeout_s``: 0 disables the
    wedge hammer. ``clock`` is injectable for tests."""

    def __init__(self, router, fleet, *,
                 min_replicas: int = 1,
                 max_replicas: int = 8,
                 heal: bool = True,
                 max_restarts: int = 3,
                 stable_window: float = 60.0,
                 backoff_base: float = 0.5,
                 backoff_cap: float = 15.0,
                 rewarm: bool = True,
                 rewarm_limit: int = 8,
                 scale_up_queue: int = 8,
                 scale_up_burn: float = 0.0,
                 scale_down_idle_s: float = 10.0,
                 hysteresis_s: float = 5.0,
                 spawn_budget: int = 6,
                 spawn_budget_window_s: float = 300.0,
                 wedge_timeout_s: float = 0.0,
                 clock=time.monotonic):
        self.router = router
        self.fleet = fleet
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.heal = bool(heal)
        self.rewarm = bool(rewarm)
        self.rewarm_limit = int(rewarm_limit)
        self.scale_up_queue = int(scale_up_queue)
        self.scale_up_burn = float(scale_up_burn)
        self.scale_down_idle_s = float(scale_down_idle_s)
        self.hysteresis_s = float(hysteresis_s)
        self.spawn_budget = int(spawn_budget)
        self.spawn_budget_window_s = float(spawn_budget_window_s)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self._clock = clock
        self._budget_kw = dict(
            max_restarts=int(max_restarts),
            stable_window=float(stable_window),
            backoff_base=float(backoff_base),
            backoff_cap=float(backoff_cap))
        now = self._clock()
        self._heal: Dict[str, _HealState] = {
            st.name: _HealState(RestartBudget(**self._budget_kw), now)
            for st in router._all}
        # wedge ledger: name -> (outstanding-ids snapshot, t of last
        # observed change)
        self._progress: Dict[str, tuple] = {}
        self._spawn_times: deque = deque()
        self._up_since: Optional[float] = None
        self._idle_since: Optional[float] = None
        self._draining: set = set()
        # -- metrics: the ROUTER registry, prefixed fleet_* alongside
        # the aggregator's series (those are fleet_<engine metric>;
        # these controller names cannot collide)
        reg = router.metrics
        self._m_heals = reg.counter(
            "fleet_heal_total", "replica heal attempts, by result "
            "(healed = respawned + re-registered; failed = the spawn "
            "itself died, retried under backoff)")
        self._m_abandoned = reg.counter(
            "fleet_heal_abandoned_total", "replicas retired after "
            "their restart budget was exhausted (crash loop)")
        self._m_wedge = reg.counter(
            "fleet_wedge_kills_total", "alive-but-stuck replicas "
            "SIGKILLed by the wedge detector (healing follows)")
        self._m_scale = reg.counter(
            "fleet_scale_events_total", "autoscale decisions, by "
            "direction (up = replica spawned; down = drain begun)")
        self._m_scale_blocked = reg.counter(
            "fleet_scale_blocked_total", "scale-ups suppressed, by "
            "reason (budget = spawn tokens exhausted; max = at "
            "max_replicas)")
        self._m_target = reg.gauge(
            "fleet_target_replicas", "replicas the controller is "
            "steering toward (live + spawning - draining)")
        self._m_tokens = reg.gauge(
            "fleet_spawn_budget_remaining", "spawn tokens left in the "
            "current anti-flap window")
        self._m_target.set(len(router._all))
        self._m_tokens.set(self.spawn_budget)
        # the router /healthz grows a controller section
        router._controller_summary = self.summary

    # -- decision journal --------------------------------------------------
    def _decide(self, action: str, **detail):
        rec = {"t": time.time(), "actor": "fleet_controller",
               "action": action}
        rec.update(detail)
        _flight.default_flight_recorder().record(rec)
        logger.info("fleet_controller: %s %s", action, detail)

    # -- the loop ----------------------------------------------------------
    def step(self, now: Optional[float] = None):
        """One control iteration; drive alongside ``router.step()``."""
        now = self._clock() if now is None else now
        self._wedge_pass(now)
        self._heal_pass(now)
        self._scale_pass(now)
        self._drain_pass(now)
        live = sum(1 for st in self.router._all
                   if st.state != "dead"
                   and st.name not in self._draining)
        self._m_target.set(live)
        self._m_tokens.set(self._spawn_tokens_left(now))

    # -- wedge detection ---------------------------------------------------
    def _wedge_pass(self, now: float):
        if self.wedge_timeout_s <= 0:
            return
        for st in self.router._all:
            if st.state == "dead":
                self._progress.pop(st.name, None)
                continue
            ids = frozenset(st.outstanding.keys())
            prev = self._progress.get(st.name)
            if prev is None or prev[0] != ids:
                self._progress[st.name] = (ids, now)
                continue
            if ids and st.in_flight > 0 and \
                    now - prev[1] >= self.wedge_timeout_s:
                # alive but frozen: no result, ack, or error for the
                # whole window while holding work. Kill it — the dead
                # transport requeues its work and healing respawns it.
                self._m_wedge.inc()
                self._decide("wedge_kill", replica=st.name,
                             stuck_ops=len(ids),
                             stuck_s=round(now - prev[1], 3))
                try:
                    self.fleet.kill_name(st.name)
                except Exception:
                    pass
                try:
                    st.handle.close()
                except Exception:
                    pass
                self._progress.pop(st.name, None)

    # -- healing -----------------------------------------------------------
    def _heal_pass(self, now: float):
        if not self.heal:
            return
        for st in list(self.router._all):
            hs = self._heal.get(st.name)
            if hs is None:
                hs = self._heal[st.name] = _HealState(
                    RestartBudget(**self._budget_kw), now)
            if st.state != "dead":
                hs.dead_seen = False
                continue
            if hs.abandoned or st.name in self._draining:
                continue
            if not hs.dead_seen:
                # first sight of this death: debit the budget (a
                # long-stable incarnation refills it) and arm backoff
                hs.dead_seen = True
                hs.budget.note_failure(
                    stepped=True, uptime_s=now - hs.launched_t)
                if hs.budget.exhausted:
                    hs.abandoned = True
                    self._m_abandoned.inc()
                    self._decide("heal_abandoned", replica=st.name,
                                 restarts=hs.budget.restarts)
                    try:
                        self.router.remove_replica(st.name)
                    except RuntimeError:
                        # last decode replica: keep the corpse
                        # registered; a later manual heal can still
                        # replace it
                        hs.abandoned = False
                        hs.budget.reset()
                    continue
                hs.next_attempt_t = now + hs.budget.delay()
                self._decide(
                    "heal_scheduled", replica=st.name,
                    restarts=hs.budget.restarts,
                    delay_s=round(hs.next_attempt_t - now, 3))
                continue
            if now < hs.next_attempt_t:
                continue
            # attempt the respawn under the SAME name: the spill dir
            # hands over, the router keeps the slot
            try:
                self.fleet.spawn(st.name)
                handle = self.fleet.handle(st.name)
            except Exception as e:  # noqa: BLE001 — spawn died: retry
                self._m_heals.inc(result="failed")
                hs.budget.note_failure(stepped=False, uptime_s=0.0)
                if hs.budget.exhausted:
                    hs.abandoned = True
                    self._m_abandoned.inc()
                    self._decide("heal_abandoned", replica=st.name,
                                 restarts=hs.budget.restarts)
                    try:
                        self.router.remove_replica(st.name)
                    except RuntimeError:
                        hs.abandoned = False
                        hs.budget.reset()
                    continue
                hs.next_attempt_t = now + hs.budget.delay()
                self._decide("heal_failed", replica=st.name,
                             error=str(e)[:200],
                             retry_in_s=round(
                                 hs.next_attempt_t - now, 3))
                continue
            self.router.replace_replica(st.name, handle)
            hs.launched_t = now
            self._m_heals.inc(result="healed")
            rewarmed = 0
            if self.rewarm:
                try:
                    rewarmed = self.router.rewarm_replica(
                        st.name, limit=self.rewarm_limit)
                except Exception:  # noqa: BLE001 — rewarm is advisory
                    rewarmed = 0
            self._decide("healed", replica=st.name,
                         restarts=hs.budget.restarts,
                         rewarm_exports=rewarmed)

    # -- elastic capacity --------------------------------------------------
    def _spawn_tokens_left(self, now: float) -> int:
        while self._spawn_times and \
                now - self._spawn_times[0] > self.spawn_budget_window_s:
            self._spawn_times.popleft()
        return max(0, self.spawn_budget - len(self._spawn_times))

    def _live_decode(self):
        return [st for st in self.router._decode
                if st.state != "dead"
                and st.name not in self._draining]

    def _scale_pass(self, now: float):
        r = self.router
        want_up = ((self.scale_up_queue
                    and r.queue_depth >= self.scale_up_queue)
                   or (self.scale_up_burn
                       and r._slo_burn_rate() > self.scale_up_burn))
        if not want_up:
            self._up_since = None
        else:
            if self._up_since is None:
                self._up_since = now
            if now - self._up_since >= self.hysteresis_s:
                self._try_scale_up(now)
        # idle = nothing queued, nothing in flight anywhere
        idle = (r.queue_depth == 0
                and all(st.in_flight == 0 for st in r._all))
        if not idle:
            self._idle_since = None
        else:
            if self._idle_since is None:
                self._idle_since = now
            if (now - self._idle_since >= self.scale_down_idle_s
                    and len(self._live_decode()) > self.min_replicas):
                self._begin_scale_down(now)

    def _try_scale_up(self, now: float):
        live = self._live_decode()
        if len(live) >= self.max_replicas:
            self._m_scale_blocked.inc(reason="max")
            self._up_since = now    # re-arm, don't spam
            return
        if self._spawn_tokens_left(now) <= 0:
            self._m_scale_blocked.inc(reason="budget")
            self._up_since = now
            return
        try:
            name = (self.fleet.allocate_name()
                    if hasattr(self.fleet, "allocate_name") else None)
            ep = self.fleet.spawn(name)
            name = ep["name"] if isinstance(ep, dict) else name
            handle = self.fleet.handle(name)
        except Exception as e:  # noqa: BLE001 — spawn died: not fatal
            self._decide("scale_up_failed", error=str(e)[:200])
            self._up_since = now
            return
        self.router.add_replica(handle)
        self._heal[name] = _HealState(
            RestartBudget(**self._budget_kw), now)
        self._spawn_times.append(now)
        self._up_since = now        # hysteresis restarts per replica
        self._m_scale.inc(direction="up")
        self._decide("scale_up", replica=name,
                     queue_depth=self.router.queue_depth,
                     burn=round(self.router._slo_burn_rate(), 3),
                     live=len(self._live_decode()))

    def _begin_scale_down(self, now: float):
        live = self._live_decode()
        # newest first: scale-down unwinds scale-up, and the seed
        # replicas keep the warmest caches
        victim = live[-1]
        self._draining.add(victim.name)
        self._idle_since = now
        self.router.begin_drain(victim.name)
        self._m_scale.inc(direction="down")
        self._decide("scale_down", replica=victim.name,
                     live=len(live) - 1)

    def _drain_pass(self, now: float):
        for name in list(self._draining):
            st = next((s for s in self.router._all
                       if s.name == name), None)
            if st is None:
                self._draining.discard(name)
                continue
            if st.state == "dead" or st.in_flight == 0:
                self._draining.discard(name)
                self._heal.pop(name, None)
                try:
                    self.router.remove_replica(name)
                except (KeyError, RuntimeError):
                    pass
                try:
                    self.fleet.stop(name)
                except Exception:
                    pass
                self._decide("drained", replica=name)

    # -- observability -----------------------------------------------------
    @staticmethod
    def _csum(metric) -> int:
        return int(sum(c.value for c in metric.series().values()))

    def summary(self) -> dict:
        now = self._clock()
        states = self.router.replica_states()
        return {
            "live": sum(1 for s in states.values() if s != "dead"),
            "min": self.min_replicas, "max": self.max_replicas,
            "draining": sorted(self._draining),
            "abandoned": sorted(n for n, h in self._heal.items()
                                if h.abandoned),
            "heals": self._csum(self._m_heals),
            "wedge_kills": self._csum(self._m_wedge),
            "scale_events": self._csum(self._m_scale),
            "spawn_tokens": self._spawn_tokens_left(now)}

    def health(self) -> dict:
        doc = dict(self.summary())
        doc["healthy"] = doc["live"] > 0
        return doc

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """The controller's own ``/healthz`` (+ the shared router
        registry's ``/metrics``); caller owns ``close()``."""
        from paddle_tpu.observe.health import HealthServer
        return HealthServer(registry=self.router.metrics,
                            health_fn=self.health,
                            host=host, port=port)
