"""Serving-fleet router: prefix-aware placement over N engine replicas.

One engine serves one host's worth of traffic; the fleet tier is this
router fronting N replicas over the JSONL serve wire (in-process
:class:`~paddle_tpu.serving.replica.EngineReplica` handles, or
:class:`~paddle_tpu.serving.replica.SocketReplica` handles to
``paddle_tpu serve --port`` processes). Three responsibilities:

**Placement.** Admission is prefix-cache-aware: the prompt's
content-chain block hashes (``serving/blocks.prompt_block_hashes`` —
the same digests the replicas' prefix caches key on) are the routing
key. The router remembers which digests it placed on which replica (a
bounded per-replica hot set); a new request scores each replica by its
hot leading-digest run and lands where its prefix is hot, so
shared-prefix tenants converge onto warm pools and the fleet
cold-prefills a shared system prompt once, not N times. Fallback is
least-loaded among healthy replicas, under a per-replica in-flight cap.

**Health-driven drain.** Each replica's three-state ``/healthz``
(PR-7: ok | degraded | unhealthy, plus SLO burn gauges behind it)
drives admission: ``degraded`` replicas are DEPRIORITIZED (placed only
when no ok replica has room), ``unhealthy`` replicas stop admitting
while their in-flight work finishes (drain), and a DEAD replica
(transport gone) has its in-flight requests re-queued onto survivors —
every accepted request completes; a re-queued request simply re-runs
its full prompt (deterministic decoding makes the output identical).

**P/D disaggregation.** With a prefill tier configured, a request
whose transferable prefix is not hot on any decode replica first runs
chunked prefill on a PREFILL replica (``export_prefix``); the finished
KV blocks come back serialized (values + scale tables, layout/kv_dtype
stamped — ``serving/transfer``) and are shipped to the chosen decode
replica (``import_prefix``, the prefix-cache publish path) ahead of
the generate op on the same ordered connection. The decode replica
admits the request as a prefix-cache hit and recomputes only the final
chunk — generation is bitwise the colocated run. If the prefill tier
is busy or dies, the router falls back to a plain colocated placement:
disaggregation is a throughput optimization, never a correctness
dependency.

The router is steppable like the engines (``submit`` / ``step`` /
``run_until_idle`` / ``idle``) and single-threaded: one ``step()``
pumps in-process replicas, collects results, polls health, and places
queued work. Observability mirrors the engine surface: a router
registry (placement/requeue/drain counters, per-replica state and
in-flight gauges, fleet TTFT windows + SLO burn), a request log, and
``serve()`` exposing ``/metrics`` + ``/healthz`` + ``/requests``.
"""

import dataclasses
import itertools
import logging
import os
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from paddle_tpu.observe import alerts as _alerts
from paddle_tpu.observe import chrome_trace as _chrome
from paddle_tpu.observe import fleet as _fleet
from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.observe import requests as _requests
from paddle_tpu.observe.window import SloConfig, WindowedQuantiles
from paddle_tpu.serving import blocks as _blocks

logger = logging.getLogger(__name__)

# routers minted per process: the trace-id prefix bakes in pid +
# instance so every fleet request id is unique across the whole
# multi-process trace merge (two routers can NEVER collide)
_ROUTER_IDS = itertools.count()

_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0)

# load-shed reasons the door can refuse with (each a counted
# rejection, never a timeout):
#   queue_full    — the router backlog crossed shed_queue_max (the
#                   latency tier gets 2x headroom before it sheds)
#   burn_rate     — the fleet TTFT SLO burn rate crossed shed_burn_max;
#                   batch-tier arrivals shed first, latency keeps
#                   flowing (the SLO the burn measures IS latency-tier
#                   experience)
#   tenant_budget — the request's own reserved-token charge exceeds
#                   the tenant's FLEET budget: it could never place
SHED_REASONS = ("queue_full", "burn_rate", "tenant_budget")


class AdmissionError(RuntimeError):
    """The router refused a request at the door (load shed). Carries
    the machine-readable ``reason`` (one of :data:`SHED_REASONS`) so
    callers can distinguish back-off-and-retry (``queue_full``,
    ``burn_rate``) from never-admissible (``tenant_budget``)."""

    def __init__(self, reason: str, msg: str):
        super().__init__(msg)
        self.reason = reason


def fleet_keying(handles, default_block_size: int = 16,
                 default_chunk_tokens: int = 64) -> Tuple[int, int]:
    """Placement keying (block size / chunk grid) read off the first
    replica ``/healthz`` that reports it — the one way the router's
    digest notion is derived from the engines' own prefix caches
    (``ServingFleet.router`` and the ``route`` CLI both key through
    here, so they can never drift apart)."""
    bs, chunk = int(default_block_size), int(default_chunk_tokens)
    for h in handles:
        doc = h.health()
        if doc and doc.get("block_size"):
            return int(doc["block_size"]), int(
                doc.get("chunk_tokens", chunk))
    logger.warning(
        "fleet_keying: no replica /healthz reported block_size — "
        "falling back to block_size=%d chunk_tokens=%d; if the engines "
        "use a different grid, placement digests will never match and "
        "the prefix-aware path is dead (pass health ports, or "
        "block_size=/chunk_tokens= explicitly)", bs, chunk)
    return bs, chunk

# replica states, best-first; the gauge encodes the rank so dashboards
# can alert on `router_replica_state < 3`
REPLICA_STATES = ("ok", "degraded", "unhealthy", "dead")
_STATE_RANK = {"ok": 3, "degraded": 2, "unhealthy": 1, "dead": 0}

# cache tiers, fastest first — directory entries prefer the fastest
# replica holding a digest; a fetch is priced off the SLOWEST tier in
# the source's leading run
_TIER_RANK = {"hbm": 0, "dram": 1, "disk": 2}


@dataclasses.dataclass
class RouterRequest:
    """One fleet request and its routing lifecycle."""
    xid: int
    prompt: np.ndarray
    max_new: int
    temperature: float = 0.0
    top_k: int = 0
    eos_id: Optional[int] = None
    tenant: str = "default"
    tier: str = "batch"
    # -- routing lifecycle (filled by the router) ------------------------
    status: str = "queued"      # queued | prefill | placed | done | failed
    replica: Optional[str] = None           # decode placement
    prefill_replica: Optional[str] = None   # P/D export source
    digests: List[bytes] = dataclasses.field(
        default_factory=list, repr=False)   # full-block chain hashes
    usable: int = 0             # leading digests admission can hit
    #                             (chunk-aligned — the placement key)
    payload: Optional[str] = None           # b64 KV payload awaiting a
    payload_blocks: int = 0                 # decode slot (P/D flow)
    prefix_score: int = 0       # hot digests at the chosen replica
    tokens: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None
    error: Optional[str] = None
    requeues: int = 0           # dead-replica recoveries
    placements: int = 0
    trace_id: str = ""          # fleet-unique; replicas adopt it
    submit_t: float = 0.0
    placed_t: Optional[float] = None
    finish_t: Optional[float] = None
    replica_ttft_ms: Optional[float] = None
    replica_latency_ms: Optional[float] = None

    @property
    def output(self) -> np.ndarray:
        return np.concatenate([self.prompt,
                               np.asarray(self.tokens, np.int32)])

    @property
    def latency_s(self) -> Optional[float]:
        if self.finish_t is None:
            return None
        return self.finish_t - self.submit_t

    @property
    def ttft_s(self) -> Optional[float]:
        """Fleet TTFT: router queueing + the replica-reported TTFT."""
        if self.replica_ttft_ms is None or self.placed_t is None:
            return None
        return (self.placed_t - self.submit_t
                + self.replica_ttft_ms / 1000.0)


@dataclasses.dataclass
class _RewarmTicket:
    """Outstanding-table entry for a rewarm export/import relay — NOT
    a request (never requeued, never finished; its loss is a cache
    miss for the replacement replica, nothing more)."""
    rid: str
    target: str                 # replica name the payload ships to
    digests: List[bytes]


class _Replica:
    """Router-side state for one replica handle."""

    def __init__(self, handle, cap: int, hot_cap: int):
        self.handle = handle
        self.name = handle.name
        self.state = "ok"
        self.last_health: dict = {}
        self.health_t = -1e9
        # xid -> (req, kind); kind: generate | export | import | rewarm
        self.outstanding: "OrderedDict" = OrderedDict()
        self.cap = int(cap)
        self.hot: "OrderedDict" = OrderedDict()
        self.hot_cap = int(hot_cap)
        # administrative drain hold (scale-down): while set, the health
        # poll must NOT re-promote this replica to ok — it stays
        # unhealthy (no new admissions) until removed or released
        self.draining = False
        # the replica's tier eviction epoch as last seen (health doc or
        # any op result): a bump between health scrapes means the warm
        # advertisement is stale NOW — see _note_epoch
        self.tier_epoch = -1
        # most recent placement prompts with a usable prefix, keyed by
        # their leading digest chain — the rewarm seed list a
        # replacement replica's prefixes are re-imported from
        self.recent: "OrderedDict" = OrderedDict()
        # digest -> tier ("hbm" | "dram" | "disk"): the replica's OWN
        # advertisement of what it holds warm at any cache tier, rebuilt
        # from each /healthz scrape's `tiers.digests` listing. `hot` is
        # the router's placement-side guess; `warm` is ground truth on
        # the scrape cadence — prefix scoring unions both.
        self.warm: Dict[bytes, str] = {}

    @property
    def in_flight(self) -> int:
        """Work that occupies the replica (import acks don't)."""
        return sum(1 for _, kind in self.outstanding.values()
                   if kind != "import")

    def note_recent(self, digests: tuple, prompt, cap: int = 16):
        if digests in self.recent:
            self.recent.move_to_end(digests)
        self.recent[digests] = prompt
        while len(self.recent) > cap:
            self.recent.popitem(last=False)

    def mark_hot(self, digests):
        for d in digests:
            if d in self.hot:
                self.hot.move_to_end(d)
            else:
                self.hot[d] = None
        while len(self.hot) > self.hot_cap:
            self.hot.popitem(last=False)

    def warm_tier(self, digest) -> Optional[str]:
        """The fastest tier this replica holds ``digest`` at, or None.
        A placement-marked hot digest counts as HBM (the engine will
        promote from its own DRAM/disk on admission anyway, so any
        local tier serves hits without router help)."""
        if digest in self.hot:
            return "hbm"
        return self.warm.get(digest)

    def prefix_run(self, digests) -> Tuple[int, Optional[str]]:
        """(length, deepest tier) of the LEADING digest run warm at
        ANY local tier — the same stop-at-first-miss walk engine
        admission does. The deepest tier prices a remote fetch."""
        n, deepest = 0, None
        for d in digests:
            t = self.warm_tier(d)
            if t is None:
                break
            n += 1
            if deepest is None or _TIER_RANK[t] > _TIER_RANK[deepest]:
                deepest = t
        return n, deepest

    def prefix_score(self, digests) -> int:
        return self.prefix_run(digests)[0]


class Router:
    """Prefix-aware fleet router over replica handles (see module
    docstring). ``replicas`` are handles implementing the protocol in
    ``serving/replica.py``; ``prefill`` names the subset serving as
    the disaggregated prefill tier (those receive only
    ``export_prefix`` work — P/D mode is off when empty).
    ``block_size``/``chunk_tokens`` must match the replicas' engines:
    they derive the placement digests and the transferable-prefix cap
    exactly as engine admission does."""

    def __init__(self, replicas: Sequence, *, block_size: int = 16,
                 chunk_tokens: int = 64, prefill: Sequence[str] = (),
                 max_in_flight: int = 8, health_poll_s: float = 0.25,
                 hot_digests: int = 4096,
                 registry: Optional[_metrics.Registry] = None,
                 slo: Optional[SloConfig] = None,
                 trace: bool = True, aggregate: bool = True,
                 fleet_jsonl: Optional[str] = None,
                 alert_rules: Optional[Sequence] = None,
                 fetch_flops_per_byte: float = 8.0,
                 shed_queue_max: int = 0,
                 shed_burn_max: float = 0.0,
                 tenant_budgets: Optional[Dict[str, int]] = None):
        if not replicas:
            raise ValueError("router needs at least one replica")
        bs, chunk = int(block_size), int(chunk_tokens)
        if bs < 1 or chunk < 1 or chunk % bs:
            raise ValueError(f"chunk_tokens {chunk} must be a positive "
                             f"multiple of block_size {bs}")
        self.block_size, self.chunk_tokens = bs, chunk
        self._replica_cap = int(max_in_flight)
        self._hot_cap = int(hot_digests)
        self._all: List[_Replica] = [
            _Replica(h, max_in_flight, hot_digests) for h in replicas]
        names = [st.name for st in self._all]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        prefill = set(prefill)
        unknown = prefill - set(names)
        if unknown:
            raise ValueError(f"prefill names {sorted(unknown)} not in "
                             f"replicas {names}")
        self._prefill = [st for st in self._all if st.name in prefill]
        self._decode = [st for st in self._all
                        if st.name not in prefill]
        if not self._decode:
            raise ValueError("every replica is prefill-tier: nothing "
                             "left to decode")
        self._health_poll_s = float(health_poll_s)
        # -- admission control (the door) ---------------------------------
        # 0 disables each shed axis; see SHED_REASONS for semantics
        self.shed_queue_max = int(shed_queue_max)
        self.shed_burn_max = float(shed_burn_max)
        # fleet-wide tenant budgets: tenant -> reserved-token cap
        # (prompt + max_new summed over the tenant's PLACED work across
        # every replica). Over-budget tenants QUEUE (skipped by
        # placement, no head-of-line blocking) — the one rejection is a
        # single request whose own charge exceeds the budget, mirroring
        # the engine-level contract.
        self._tenant_budgets: Dict[str, int] = dict(tenant_budgets or {})
        self._tenant_used: Dict[str, int] = {}
        self._charged: set = set()
        # rewarm state: dead replica name -> its recent prefix prompts
        self._rewarm_stash: Dict[str, list] = {}
        self._rewarm_ids = itertools.count()
        # a FleetController registers its summary callable here so one
        # router /healthz answers for the control plane too
        self._controller_summary = None
        self._queue: deque = deque()
        self._requests: Dict[int, RouterRequest] = {}
        self._ids = itertools.count()
        self.request_log = _requests.RequestLog()
        self._n_completed = 0
        self.slo = slo
        win = slo.window_s if slo is not None else 60.0
        self._win_ttft = WindowedQuantiles(window_s=win)
        self._win_tps = WindowedQuantiles(window_s=win)
        # -- metrics ------------------------------------------------------
        reg = self.metrics = registry or _metrics.Registry()
        self._m_requests = reg.counter(
            "router_requests_total", "requests submitted to the fleet")
        self._m_completed = reg.counter(
            "router_requests_completed_total",
            "fleet requests finished, by finish reason (error = the "
            "replica rejected the request — malformed, too long)")
        self._m_tokens = reg.counter(
            "router_tokens_total", "tokens emitted across the fleet")
        self._m_placements = reg.counter(
            "router_placements_total", "generate placements onto "
            "replicas (a requeued request places again)")
        self._m_place_hits = reg.counter(
            "router_placement_prefix_hits_total",
            "placements that landed where a leading run of the "
            "prompt's block digests was already hot — the prefix-aware "
            "hit rate's numerator")
        self._m_requeued = reg.counter(
            "router_requeued_total", "in-flight requests re-queued off "
            "a dead replica onto survivors")
        self._m_drains = reg.counter(
            "router_drains_total", "replica drains begun, by reason "
            "(unhealthy = stop admitting, in-flight finishes; dead = "
            "transport lost, in-flight re-queued)")
        self._m_queue = reg.gauge(
            "router_queue_depth", "requests waiting for a placement")
        self._m_in_flight = reg.gauge(
            "router_replica_in_flight", "outstanding work per replica "
            "(router view: generate + export ops awaiting results)")
        self._m_replica_queue = reg.gauge(
            "router_replica_queue_depth", "queue depth each replica "
            "last reported on /healthz")
        self._m_state = reg.gauge(
            "router_replica_state", "replica admission state: 3=ok "
            "2=degraded 1=unhealthy 0=dead")
        self._m_ttft = reg.histogram(
            "router_ttft_seconds", "fleet TTFT: submit -> first token "
            "(router queueing + replica-reported TTFT)",
            buckets=_LATENCY_BUCKETS)
        self._m_win_ttft = reg.gauge(
            "router_ttft_window_seconds", "rolling fleet TTFT quantile "
            "over the SLO window (label q)")
        self._m_win_tps = reg.gauge(
            "router_tokens_per_sec_window", "rolling per-request "
            "decode tokens/sec quantile over the SLO window (label q)")
        self._m_burn = reg.gauge(
            "router_slo_burn_rate", "fleet TTFT SLO burn rate (0 "
            "without a configured SLO)")
        self._m_pd_exports = reg.counter(
            "router_pd_exports_total", "prefill-tier export_prefix "
            "ops completed (P/D disaggregation)")
        self._m_pd_blocks = reg.counter(
            "router_pd_blocks_shipped_total", "KV blocks shipped over "
            "the P/D transfer path and adopted by decode replicas")
        self._m_pd_errors = reg.counter(
            "router_pd_errors_total", "P/D transfer ops a replica "
            "refused, by op (export = colocated fallback; import = "
            "cold prefill on the decode replica — same bits, slower)")
        self._m_hit_rate = reg.gauge(
            "router_placement_hit_rate", "fraction of generate "
            "placements that landed on a replica with a hot "
            "leading-digest run — the prefix-hit-rate alert's input")
        self._m_kv_fetches = reg.counter(
            "router_kv_fetches_total", "remote prefix fetches placed "
            "through the fleet cache directory, labeled by the "
            "DEEPEST tier in the source's leading run (the tier that "
            "priced the fetch)")
        self._m_dir_size = reg.gauge(
            "router_directory_size", "distinct digests the fleet "
            "cache directory currently maps to a live replica+tier")
        self._m_shed = reg.counter(
            "router_shed_total", "requests refused at the door, by "
            "reason (queue_full | burn_rate | tenant_budget) — counted "
            "rejections, never timeouts")
        self._m_tenant_flight = reg.gauge(
            "router_tenant_tokens_in_flight", "reserved tokens "
            "(prompt + max_new) each tenant has placed fleet-wide — "
            "the charge the fleet tenant budget caps")
        self._m_rewarm = reg.counter(
            "router_rewarm_total", "prefix re-imports attempted for a "
            "replacement replica, by result (shipped = KV relayed "
            "from a warm survivor; miss = no warm source / payload "
            "gone — the replacement cold-prefills that prefix)")
        self._m_dir_invalidations = reg.counter(
            "router_directory_invalidations_total", "warm-set "
            "invalidations forced by a tier eviction-epoch bump seen "
            "on an op result between health scrapes (the stale-fetch "
            "prevention path)")
        # fetch-vs-recompute crossover: ship the prefix's KV bytes when
        # recomputing a token costs more than `fetch_flops_per_byte`
        # device FLOPs per wire byte shipped (both sides linear in
        # prefix tokens, so the tokens cancel). 0 fetches whenever a
        # source exists; float("inf") disables fetching entirely.
        self.fetch_flops_per_byte = float(fetch_flops_per_byte)
        for st in self._all:
            self._m_state.set(_STATE_RANK[st.state], replica=st.name)
        # -- fleet observability plane ------------------------------------
        # trace propagation: every accepted request gets a FLEET-unique
        # trace id (pid + router instance + xid) stamped onto the serve
        # wire; replicas adopt it, so their engine lifecycle events join
        # under the router's route/queue/place spans when the per-
        # process exports merge on pid (observe.trace_export)
        self.trace_requests = bool(trace)
        self._trace_prefix = f"fleet{os.getpid()}.{next(_ROUTER_IDS)}"
        self._wall_anchor = time.time() - time.perf_counter()
        # metrics aggregation + alerts: the aggregator writes into THIS
        # registry, so one /metrics scrape answers for the whole fleet;
        # the evaluator runs over the same registry per scrape round
        self.aggregate = bool(aggregate)
        self.fleet = _fleet.FleetAggregator(
            registry=reg, window_s=win, jsonl_path=fleet_jsonl)
        self.alerts = _alerts.AlertEvaluator(
            reg, alert_rules if alert_rules is not None
            else _alerts.default_fleet_rules())
        self._scrape_t = -1e9

    # -- trace propagation -------------------------------------------------
    def _rev(self, req: RouterRequest, name: str, ph: str,
             perf_t: float, **args):
        """One router-side lifecycle event on the request's FLEET
        trace track (same cat/id as the replica engine's events, so
        the merged export renders one connected tree)."""
        if req.trace_id:
            _chrome.record_event(name, self._wall_anchor + perf_t, ph,
                                 req.trace_id, args=args or None)

    # -- request API -------------------------------------------------------
    def submit(self, prompt, max_new: int, *, temperature: float = 0.0,
               top_k: int = 0, eos_id: Optional[int] = None,
               tenant: str = "default", tier: str = "batch"
               ) -> RouterRequest:
        """Queue one fleet request; placement happens in ``step()``.
        The request is stamped with a fleet-unique trace id; its
        ``route`` slice (the router-side root of the whole cross-
        process request tree) opens here and closes at completion.
        Raises :class:`AdmissionError` when the door sheds (see
        :data:`SHED_REASONS`) — shed BEFORE replicas saturate, never a
        timeout after they did."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        tier, tenant = str(tier), str(tenant)
        if self.shed_queue_max:
            # latency-tier traffic gets 2x headroom: the backlog that
            # sheds bulk work early is exactly what keeps the latency
            # tier's TTFT in band
            limit = (2 * self.shed_queue_max if tier == "latency"
                     else self.shed_queue_max)
            if len(self._queue) >= limit:
                self._m_shed.inc(reason="queue_full")
                raise AdmissionError(
                    "queue_full", f"router queue at {len(self._queue)} "
                    f">= {limit} for tier {tier!r}")
        if (self.shed_burn_max and tier != "latency"
                and self._slo_burn_rate() > self.shed_burn_max):
            self._m_shed.inc(reason="burn_rate")
            raise AdmissionError(
                "burn_rate", f"TTFT SLO burn rate "
                f"{self._slo_burn_rate():.2f} > {self.shed_burn_max} "
                f"— batch-tier arrivals shed until it recovers")
        budget = self._tenant_budgets.get(tenant)
        own = int(prompt.size) + int(max_new)
        if budget is not None and own > budget:
            self._m_shed.inc(reason="tenant_budget")
            raise AdmissionError(
                "tenant_budget", f"request reserves {own} tokens > "
                f"tenant {tenant!r} fleet budget {budget} — it could "
                f"never place")
        req = RouterRequest(
            xid=next(self._ids), prompt=prompt, max_new=int(max_new),
            temperature=float(temperature), top_k=int(top_k),
            eos_id=eos_id, tenant=str(tenant), tier=str(tier),
            submit_t=time.perf_counter())
        if self.trace_requests:
            req.trace_id = f"{self._trace_prefix}.r{req.xid}"
        req.digests = _blocks.prompt_block_hashes(prompt,
                                                  self.block_size)
        per = self.chunk_tokens // self.block_size
        req.usable = min(
            len(req.digests),
            ((int(prompt.size) - 1) // self.chunk_tokens) * per)
        self._queue.append(req)
        self._requests[req.xid] = req
        self._m_requests.inc()
        self._m_queue.set(len(self._queue))
        self._rev(req, "route", "b", req.submit_t, xid=req.xid,
                  prompt_tokens=int(prompt.size), max_new=req.max_new,
                  tenant=req.tenant, tier=req.tier)
        self._rev(req, "queue", "b", req.submit_t)
        return req

    # -- fleet-wide tenant accounting -------------------------------------
    def set_tenant_budget(self, tenant: str, tokens: Optional[int]):
        """Set (or with ``None`` clear) a tenant's fleet-wide
        reserved-token budget. Takes effect at the next placement
        round — work already placed is never clawed back."""
        if tokens is None:
            self._tenant_budgets.pop(str(tenant), None)
        else:
            self._tenant_budgets[str(tenant)] = int(tokens)

    @staticmethod
    def _tenant_charge(req: RouterRequest) -> int:
        return int(req.prompt.size) + int(req.max_new)

    def _charge(self, req: RouterRequest):
        if req.xid in self._charged:
            return
        self._charged.add(req.xid)
        used = self._tenant_used.get(req.tenant, 0)
        self._tenant_used[req.tenant] = used + self._tenant_charge(req)
        self._m_tenant_flight.set(self._tenant_used[req.tenant],
                                  tenant=req.tenant)

    def _release(self, req: RouterRequest):
        if req.xid not in self._charged:
            return
        self._charged.discard(req.xid)
        used = self._tenant_used.get(req.tenant, 0)
        self._tenant_used[req.tenant] = max(
            0, used - self._tenant_charge(req))
        self._m_tenant_flight.set(self._tenant_used[req.tenant],
                                  tenant=req.tenant)

    def _tenant_blocked(self, req: RouterRequest) -> bool:
        budget = self._tenant_budgets.get(req.tenant)
        if budget is None:
            return False
        return (self._tenant_used.get(req.tenant, 0)
                + self._tenant_charge(req) > budget)

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def outstanding(self) -> int:
        return sum(len(st.outstanding) for st in self._all)

    @property
    def idle(self) -> bool:
        return not self._queue and not any(
            kind != "import"
            for st in self._all
            for _, kind in st.outstanding.values())

    def replica_states(self) -> Dict[str, str]:
        return {st.name: st.state for st in self._all}

    def placement_hit_rate(self) -> float:
        """Fraction of generate placements that landed on a replica
        with a hot leading-digest run."""
        total = self._m_placements.value()
        if not total:
            return 0.0
        return self._m_place_hits.value() / total

    # -- scheduler ---------------------------------------------------------
    def step(self) -> List[RouterRequest]:
        """One router iteration: pump in-process replicas, collect
        results, poll health (requeueing off dead replicas), place
        queued work. Returns the requests that finished this step."""
        for st in self._all:
            if st.state != "dead":
                st.handle.pump()
        finished = self._collect()
        now = time.perf_counter()
        self._poll_health(now)
        if self.aggregate and now - self._scrape_t >= self._health_poll_s:
            self._scrape_t = now
            self._scrape()
        self._place()
        self._update_gauges()
        return finished

    def run_until_idle(self, max_steps: int = 200_000
                       ) -> List[RouterRequest]:
        done: List[RouterRequest] = []
        for _ in range(max_steps):
            if self.idle:
                return done
            done.extend(self.step())
        raise RuntimeError(
            f"router did not drain in {max_steps} steps "
            f"({self.queue_depth} queued, {self.outstanding} "
            f"outstanding, states {self.replica_states()})")

    # -- results -----------------------------------------------------------
    def _collect(self) -> List[RouterRequest]:
        finished: List[RouterRequest] = []
        for st in self._all:
            for doc in st.handle.poll():
                self._note_epoch(st, doc)
                ent = st.outstanding.pop(doc.get("id"), None)
                if ent is None:
                    # ack for an untracked op, or a late result for a
                    # request already requeued off this replica —
                    # first completion wins
                    continue
                req, kind = ent
                if kind == "rewarm":
                    self._on_rewarm(st, req, doc)
                    continue
                if kind == "import":
                    if "error" in doc:
                        # a refused adoption (stamp mismatch, spec
                        # engine) degrades that request to a cold
                        # prefill — same bits, slower; count + log so
                        # a misconfigured fleet is visible, never
                        # silent
                        self._m_pd_errors.inc(op="import")
                        logger.warning("import_prefix refused by %s: %s",
                                       st.name, doc["error"])
                    else:
                        self._m_pd_blocks.inc(
                            int(doc.get("imported") or 0))
                    continue
                if kind == "export":
                    self._on_export(st, req, doc)
                elif "error" in doc:
                    err = str(doc["error"])
                    if err.startswith("draining"):
                        # the replica sealed for graceful drain after
                        # placement won the race: not a request
                        # failure — place it on a survivor
                        self._requeue(st, req)
                    else:
                        self._finish(req, None, error=err)
                        finished.append(req)
                else:
                    self._finish(req, doc)
                    finished.append(req)
        return finished

    def _note_epoch(self, st, doc: dict):
        """Tier-directory invalidation fence: every replica op result
        carries the spill tiers' eviction epoch. A bump relative to
        what the last health scrape advertised means digests retired
        BETWEEN scrapes — the warm set is stale NOW. Drop it (fetches
        stop routing at ghosts immediately) and force a re-scrape at
        the next poll instead of waiting out the cadence."""
        ep = doc.get("tier_epoch")
        if ep is None:
            return
        ep = int(ep)
        if st.tier_epoch >= 0 and ep > st.tier_epoch:
            if st.warm:
                st.warm = {}
                self._m_dir_invalidations.inc()
            st.health_t = -1e9      # re-scrape on the very next poll
        st.tier_epoch = max(st.tier_epoch, ep)

    def _requeue(self, st, req: RouterRequest):
        """Send ``req`` back to the queue front after ``st`` refused or
        lost it (drain refusal, dead transport)."""
        req.requeues += 1
        req.status = "queued"
        req.replica = None
        req.payload, req.payload_blocks = None, 0
        self._release(req)
        self._m_requeued.inc()
        self._set_state(st, "unhealthy")    # stop placing here; the
        #                                     health poll re-promotes a
        #                                     replica that recovers
        now = time.perf_counter()
        self._rev(req, "requeue", "n", now, reason="drain",
                  replica=st.name, requeues=req.requeues)
        self._rev(req, "queue", "b", now)   # waiting again: the queue
        #                                     slice re-opens on the SAME
        #                                     trace — one connected tree
        self._queue.appendleft(req)

    def _on_export(self, st, req: RouterRequest, doc: dict):
        req.prefill_replica = st.name
        if "error" in doc:
            # a prefill replica that REFUSES the export (non-paged
            # engine, budget rejection, drain) must not fail the
            # request — disaggregation is never a correctness
            # dependency; fall back colocated (prefill_replica is set,
            # so placement won't retry the prefill tier)
            self._m_pd_errors.inc(op="export")
            logger.warning("export_prefix refused by %s (colocated "
                           "fallback): %s", st.name, doc["error"])
            req.status = "queued"
            self._queue.appendleft(req)
            return
        self._m_pd_exports.inc()
        payload = doc.get("payload")
        if payload:
            req.payload = payload
            req.payload_blocks = int(doc.get("blocks", 0))
            st.mark_hot(req.digests[:req.payload_blocks])
        # back to the queue FRONT (it already waited through the
        # prefill stage) awaiting a decode placement; an empty payload
        # (no transferable prefix / evicted) decodes colocated-style
        req.status = "queued"
        self._queue.appendleft(req)

    def _finish(self, req: RouterRequest, doc: Optional[dict],
                error: Optional[str] = None):
        now = time.perf_counter()
        req.finish_t = now
        self._release(req)
        self._n_completed += 1
        if error is not None:
            req.status, req.error = "failed", error
            req.finish_reason = "error"
            self._m_completed.inc(reason="error")
        else:
            req.status = "done"
            req.tokens = [int(t) for t in doc.get("tokens", ())]
            req.finish_reason = doc.get("finish_reason")
            req.replica_ttft_ms = doc.get("ttft_ms")
            req.replica_latency_ms = doc.get("latency_ms")
            self._m_completed.inc(reason=req.finish_reason or "unknown")
            self._m_tokens.inc(len(req.tokens))
            ttft = req.ttft_s
            if ttft is not None:
                self._m_ttft.observe(ttft)
                self._win_ttft.observe(ttft)
            if req.latency_s and req.tokens:
                self._win_tps.observe(len(req.tokens) / req.latency_s)
        self._rev(req, "route", "e", now,
                  reason=req.finish_reason or "error",
                  tokens=len(req.tokens), requeues=req.requeues,
                  replica=req.replica)
        self._record_request(req)

    def _record_request(self, req: RouterRequest):
        def r6(v):
            return round(v, 6) if v is not None else None

        self.request_log.add({
            "rid": req.xid, "engine": "router",
            "trace_id": req.trace_id or f"router.r{req.xid}",
            "finish_reason": req.finish_reason if req.error is None
            else f"rejected:{req.error[:80]}",
            "tenant": req.tenant, "tier": req.tier,
            "replica": req.replica,
            "prefill_replica": req.prefill_replica,
            "requeues": req.requeues,
            "prefix_score": req.prefix_score,
            "prompt_tokens": int(req.prompt.size),
            "tokens": len(req.tokens),
            "queue_wait_s": r6((req.placed_t or req.finish_t)
                               - req.submit_t),
            "prefill_own_s": None, "prefill_stall_s": None,
            "decode_s": None,
            "ttft_s": r6(req.ttft_s),
            "latency_s": r6(req.latency_s),
            "cache_hit_frac": round(
                req.prefix_score / max(len(req.digests), 1), 4)})

    # -- health / drain ----------------------------------------------------
    def _poll_health(self, now: float):
        for st in self._all:
            if st.state == "dead":
                continue
            if not st.handle.alive():
                self._mark_dead(st)
                continue
            if now - st.health_t < self._health_poll_s:
                # throttle applies even while the endpoint is
                # unreachable — health() can block (HTTP timeout) and
                # this loop runs on the single scheduler thread
                continue
            st.health_t = now
            try:
                doc = st.handle.health()
            except Exception:
                doc = None
            if doc is None:
                continue    # endpoint unreachable: state unknown,
            #                 liveness stays the transport's verdict
            st.last_health = doc
            # fleet cache directory feed: the replica's /healthz tiers
            # section lists its warm digests per tier (hbm listing
            # capped at the engine); rebuild — not merge — so entries
            # the replica evicted are pruned on this same cadence
            tiers_doc = (doc.get("tiers") or {})
            tiers = tiers_doc.get("digests") or {}
            ep = tiers_doc.get("eviction_epoch")
            if tiers and not (ep is not None
                              and int(ep) < st.tier_epoch):
                # refuse a warm rebuild whose epoch is OLDER than what
                # op results already proved — its digest list may still
                # name retired entries; wait for a fresh view
                warm: Dict[bytes, str] = {}
                for tname in ("disk", "dram", "hbm"):   # fastest wins
                    for hexd in tiers.get(tname, ()):
                        try:
                            warm[bytes.fromhex(hexd)] = tname
                        except ValueError:
                            pass
                st.warm = warm
            if ep is not None:
                # the scrape and its warm rebuild are one atomic view:
                # record the epoch it was taken at so only LATER bumps
                # (seen on op results) invalidate it
                st.tier_epoch = max(st.tier_epoch, int(ep))
            status = doc.get("status", "ok")
            if not doc.get("healthy", True):
                status = "unhealthy"
            if st.draining:
                # administrative drain hold: never re-promote a
                # replica the controller is scaling down
                status = "unhealthy"
            self._set_state(
                st, status if status in REPLICA_STATES else "ok")

    def _set_state(self, st, new: str):
        if new == st.state:
            return
        if new == "unhealthy":
            self._m_drains.inc(reason="unhealthy")
        st.state = new
        self._m_state.set(_STATE_RANK[new], replica=st.name)

    def _mark_dead(self, st):
        if st.state == "dead":
            return
        st.state = "dead"
        # rewarm seed: remember what was recently placed here (most
        # recent last) BEFORE pruning, so a replacement replica can
        # re-import those prefixes from warm survivors
        if st.recent:
            self._rewarm_stash[st.name] = list(st.recent.values())
            st.recent.clear()
        # prune the dead member's directory entries immediately: a
        # fetch routed at a corpse would just bounce through the
        # requeue path, and `directory()` must never advertise one
        st.warm = {}
        st.hot.clear()
        self._m_state.set(0, replica=st.name)
        self._m_drains.inc(reason="dead")
        now = time.perf_counter()
        requeue: List[RouterRequest] = []
        for xid, (req, kind) in list(st.outstanding.items()):
            st.outstanding.pop(xid)
            if kind == "import":
                continue
            if kind == "rewarm":
                # a rewarm export lost with its source is just a cache
                # miss for the replacement — never requeued work
                self._m_rewarm.inc(result="miss")
                continue
            self._release(req)
            req.requeues += 1
            req.status = "queued"
            req.replica = None
            # a payload produced by (or destined for) the dead replica
            # restarts the whole flow — survivors may have the prefix
            # hot anyway
            req.payload, req.payload_blocks = None, 0
            self._rev(req, "requeue", "n", now, reason="dead",
                      replica=st.name, requeues=req.requeues)
            self._rev(req, "queue", "b", now)
            requeue.append(req)
        if requeue:
            self._m_requeued.inc(len(requeue))
            for req in reversed(requeue):
                self._queue.appendleft(req)
        # the fleet flight hook: bundle the dead member's last-known
        # state with the router's view into one post-mortem artifact
        # (only when a flight dir is configured — tests and notebooks
        # must not litter; same gate as the trainer's crash dumps)
        self.fleet.drop_replica(st.name)
        from paddle_tpu.observe import flight as _flight
        if _flight.configured():
            _fleet.death_postmortem(
                st.name, router_view=self.health(),
                last_health=st.last_health,
                outstanding=[{"xid": r.xid, "requeues": r.requeues,
                              "trace": r.trace_id} for r in requeue],
                alerts=self.alerts.firing())

    # -- fleet aggregation -------------------------------------------------
    def _scrape(self):
        """One aggregation round on the health-poll cadence: ingest
        every live replica's registry snapshot + last health doc into
        the fleet aggregator (it writes into THIS registry), refresh
        the derived fleet gauges, then run the alert rules over the
        result. Dead replicas still report their router-side state so
        ``fleet_replicas{state="dead"}`` counts them."""
        for st in self._all:
            snapshot = None
            if st.state != "dead":
                fn = getattr(st.handle, "metrics_snapshot", None)
                if fn is not None:
                    try:
                        snapshot = fn()
                    except Exception:
                        snapshot = None
            self.fleet.observe_replica(
                st.name, state=st.state,
                health=st.last_health or None, snapshot=snapshot)
        self.fleet.finish_scrape()
        self._m_dir_size.set(len(self.directory()))
        self._update_gauges()
        self._update_window_gauges()    # burn gauge feeds the TTFT rule
        self.alerts.evaluate()

    def remove_replica(self, name: str):
        """Administratively retire a replica: forget its per-replica
        gauge series and aggregator state so fleet counts (and the
        dead-replica alert) reflect the intended fleet, not history.
        The admin surface a future autoscaler's scale-down uses; any
        in-flight work is requeued first via the dead path."""
        st = next((s for s in self._all if s.name == name), None)
        if st is None:
            raise KeyError(f"no replica named {name!r}")
        self._mark_dead(st)
        self._all.remove(st)
        if st in self._decode:
            self._decode.remove(st)
        if st in self._prefill:
            self._prefill.remove(st)
        if not self._decode:
            raise RuntimeError("removed the last decode replica: the "
                               "router can no longer place work")
        self.fleet.drop_replica(name)
        self.fleet.forget_state(name)
        for g in (self._m_state, self._m_in_flight,
                  self._m_replica_queue):
            g.remove(replica=name)
        try:
            st.handle.close()
        except Exception:
            pass

    # -- fleet lifecycle (the controller's command surface) ----------------
    def add_replica(self, handle, *, prefill: bool = False):
        """Register a NEW replica handle (scale-up, or a replacement
        spawned under a fresh name). It admits immediately as ``ok``;
        the next health poll corrects that if the replica disagrees."""
        if any(st.name == handle.name for st in self._all):
            raise ValueError(f"replica name {handle.name!r} already "
                             f"registered")
        st = _Replica(handle, self._replica_cap, self._hot_cap)
        self._all.append(st)
        (self._prefill if prefill else self._decode).append(st)
        self._m_state.set(_STATE_RANK[st.state], replica=st.name)
        return st

    def replace_replica(self, name: str, handle):
        """Swap a DEAD replica's handle for its replacement under the
        SAME name (the healed process inherits the spill dir keyed on
        it). Role and list position carry over; the warm set starts
        empty and refills from the replacement's first health scrape
        (its disk tier re-adopts the spill dir) plus the rewarm path."""
        st = next((s for s in self._all if s.name == name), None)
        if st is None:
            raise KeyError(f"no replica named {name!r}")
        if st.state != "dead":
            raise ValueError(f"replica {name!r} is {st.state}, not "
                             f"dead — drain and remove it instead")
        if handle.name != name:
            raise ValueError(f"replacement handle is named "
                             f"{handle.name!r}, expected {name!r}")
        try:
            st.handle.close()
        except Exception:
            pass
        st.handle = handle
        st.last_health = {}
        st.health_t = -1e9
        st.tier_epoch = -1
        st.draining = False
        st.outstanding.clear()
        st.state = "ok"
        self._m_state.set(_STATE_RANK["ok"], replica=name)
        return st

    def begin_drain(self, name: str):
        """Administrative drain (scale-down): stop admitting onto
        ``name`` and HOLD it unhealthy against health-poll
        re-promotion. In-flight work finishes normally; the caller
        watches ``in_flight`` reach 0 and then removes the replica."""
        st = next((s for s in self._all if s.name == name), None)
        if st is None:
            raise KeyError(f"no replica named {name!r}")
        st.draining = True
        self._set_state(st, "unhealthy")

    def rewarm_replica(self, name: str, limit: int = 8) -> int:
        """Re-warm a replacement replica: for each prefix recently
        placed on the dead incarnation (the stash `_mark_dead` kept),
        ship its KV from the warmest live survivor over the transfer
        wire — a ``warm_only`` export relayed as an import, exactly
        the cache-directory fetch path. Prefixes the replacement
        already holds warm (its disk tier re-adopted the spill dir)
        are skipped. Returns the number of rewarm exports issued."""
        target = next((s for s in self._all if s.name == name), None)
        if target is None:
            raise KeyError(f"no replica named {name!r}")
        stash = self._rewarm_stash.pop(name, [])
        issued = 0
        for prompt in reversed(stash):      # most recent first
            if issued >= int(limit):
                break
            digests = _blocks.prompt_block_hashes(
                np.asarray(prompt, np.int32), self.block_size)
            if not digests:
                continue
            if target.prefix_score(digests) >= len(digests):
                continue    # already warm (spill-dir re-adoption)
            src, run, tier = None, 0, None
            for st in self._all:
                if st is target or st.state not in ("ok", "degraded"):
                    continue
                n, deepest = st.prefix_run(digests)
                if n > run or (n == run and n > 0 and src is not None
                               and st.in_flight < src.in_flight):
                    src, run, tier = st, n, deepest
            if src is None or run <= 0:
                self._m_rewarm.inc(result="miss")
                continue
            rid = f"rw{next(self._rewarm_ids)}"
            spec = {"id": rid, "op": "export_prefix", "warm_only": True,
                    "prompt": [int(t) for t in prompt]}
            src.handle.submit(spec)
            # the ticket rides the ordinary outstanding plumbing (the
            # handle is polled ONLY by _collect); _on_rewarm relays
            # the payload to the target when the export lands
            src.outstanding[rid] = (
                _RewarmTicket(rid, name, list(digests)), "rewarm")
            self._m_kv_fetches.inc(tier=tier or "dram")
            issued += 1
        return issued

    def _on_rewarm(self, src, ticket, doc: dict):
        """A rewarm export landed: relay the payload to the ticket's
        target replica as an ordinary import (the prefix-cache publish
        path), or count the miss if the source had nothing left."""
        target = next((s for s in self._all
                       if s.name == ticket.target), None)
        payload = doc.get("payload") if "error" not in doc else None
        if (target is None or target.state == "dead" or not payload):
            self._m_rewarm.inc(result="miss")
            return
        blocks = int(doc.get("blocks") or 0)
        imp = {"id": f"{ticket.rid}.imp", "op": "import_prefix",
               "payload": payload}
        target.handle.submit(imp)
        target.outstanding[f"{ticket.rid}.imp"] = (ticket, "import")
        target.mark_hot(ticket.digests[:blocks] if blocks
                        else ticket.digests)
        self._m_rewarm.inc(result="shipped")

    # -- placement ---------------------------------------------------------
    def _place(self):
        remaining: deque = deque()
        while self._queue:
            req = self._queue.popleft()
            if not self._place_one(req):
                remaining.append(req)
        self._queue = remaining
        self._m_queue.set(len(self._queue))

    def _place_one(self, req: RouterRequest) -> bool:
        if self._tenant_blocked(req):
            # over its fleet budget: the request WAITS (placement
            # skips it without blocking the tenants behind it) until
            # enough of the tenant's placed work finishes
            return False
        if req.payload is not None:
            return self._place_decode(req)
        if (req.usable and req.prefill_replica is None
                and not self._warm_on_placeable_decode(req)):
            # fleet cache directory: the prefix is cold on every decode
            # replica that could take this request, but may be warm
            # SOMEWHERE — another replica's HBM, DRAM or disk. Fetch it
            # over the transfer wire when shipping bytes beats
            # recomputing FLOPs (the crossover knob); the payload comes
            # back through the ordinary export relay and ships ahead of
            # the generate op like a P/D prefill would.
            src, run, tier = self._pick_fetch_source(req)
            if src is not None and self._fetch_pays(src):
                spec = {"id": req.xid, "op": "export_prefix",
                        "warm_only": True,
                        "prompt": [int(t) for t in req.prompt]}
                if req.trace_id:
                    spec["trace"] = req.trace_id
                src.handle.submit(spec)
                src.outstanding[req.xid] = (req, "export")
                req.status = "prefill"
                req.prefill_replica = src.name
                self._m_kv_fetches.inc(tier=tier)
                self._rev(req, "place", "n", time.perf_counter(),
                          kind="fetch", replica=src.name,
                          blocks=run, tier=tier)
                return True
        if (self._prefill and req.usable
                and req.prefill_replica is None
                and not self._hot_anywhere(req)):
            st = self._pick_prefill()
            if st is not None:
                spec = {"id": req.xid, "op": "export_prefix",
                        "prompt": [int(t) for t in req.prompt]}
                if req.trace_id:
                    # the P/D hop joins the same fleet trace: the
                    # prefill replica's engine spans land on this id
                    spec["trace"] = req.trace_id
                st.handle.submit(spec)
                st.outstanding[req.xid] = (req, "export")
                req.status = "prefill"
                req.prefill_replica = st.name
                self._rev(req, "place", "n", time.perf_counter(),
                          kind="export", replica=st.name)
                return True
            # no prefill capacity: colocated fallback — correctness
            # (and latency) must not wait on the prefill tier
        return self._place_decode(req)

    def _warm_on_placeable_decode(self, req: RouterRequest) -> bool:
        """True when a decode replica that could take this request NOW
        (live, under its cap) holds a leading run warm at any local
        tier — placement lands there and local hits/promotion serve
        it, so a remote fetch would only burn wire bytes."""
        usable = req.digests[:req.usable]
        return any(st.prefix_score(usable) > 0
                   for st in self._decode
                   if st.state in ("ok", "degraded")
                   and st.in_flight < st.cap)

    def _pick_fetch_source(self, req: RouterRequest):
        """Best remote source for ``req``'s prefix: the live replica
        (any role — a capped decode replica or the prefill tier both
        qualify) with the longest leading warm run; ties prefer the
        least loaded. Returns (replica, run_blocks, deepest_tier) or
        (None, 0, None)."""
        usable = req.digests[:req.usable]
        best, best_key, best_run = None, None, (0, None)
        for st in self._all:
            if st.state not in ("ok", "degraded"):
                continue
            n, deepest = st.prefix_run(usable)
            if n <= 0:
                continue
            key = (n, -st.in_flight)
            if best_key is None or key > best_key:
                best, best_key, best_run = st, key, (n, deepest)
        return best, best_run[0], best_run[1]

    def _fetch_pays(self, src) -> bool:
        """The bytes-shipped-vs-FLOPs-recomputed crossover. Both sides
        are linear in prefix tokens (`kv_bytes_per_token` wire bytes
        vs `flops_per_token` recompute), so the prefix length cancels
        and the decision is a per-token rate comparison against the
        ``fetch_flops_per_byte`` knob. Missing health figures (row
        engine, no scrape yet) fail toward recompute — the behavior
        the fleet had before the directory existed."""
        if self.fetch_flops_per_byte == 0:
            return True
        doc = src.last_health or {}
        flops = doc.get("flops_per_token")
        kvb = doc.get("kv_bytes_per_token")
        if not flops or not kvb:
            return False
        return float(flops) >= self.fetch_flops_per_byte * float(kvb)

    def directory(self) -> Dict[str, dict]:
        """The fleet-global cache directory: digest hex -> {replica,
        tier} over every LIVE replica's advertised warm set (hot-set
        entries count as hbm), preferring the fastest tier when a
        digest is warm in several places. Dead replicas never appear —
        their entries are pruned the moment death is detected."""
        out: Dict[str, dict] = {}
        for st in self._all:
            if st.state == "dead":
                continue
            for d in st.hot:
                cur = out.get(d.hex())
                if cur is None or _TIER_RANK[cur["tier"]] > 0:
                    out[d.hex()] = {"replica": st.name, "tier": "hbm"}
            for d, t in st.warm.items():
                cur = out.get(d.hex())
                if cur is None or _TIER_RANK[t] < _TIER_RANK[cur["tier"]]:
                    out[d.hex()] = {"replica": st.name, "tier": t}
        return out

    def _hot_anywhere(self, req: RouterRequest) -> bool:
        """True when some decode replica already holds the whole
        transferable prefix hot — the placement-hit fast path that
        skips the prefill tier entirely."""
        usable = req.digests[:req.usable]
        return any(st.prefix_score(usable) >= req.usable
                   for st in self._decode
                   if st.state in ("ok", "degraded"))

    def _pick_prefill(self):
        best, best_key = None, None
        for st in self._prefill:
            if st.state in ("unhealthy", "dead"):
                continue
            if st.in_flight >= st.cap:
                continue
            key = (1 if st.state == "ok" else 0, -st.in_flight)
            if best_key is None or key > best_key:
                best, best_key = st, key
        return best

    def _pick_decode(self, req: RouterRequest):
        usable = req.digests[:req.usable]
        best, best_key = None, None
        for st in self._decode:
            if st.state in ("unhealthy", "dead"):
                continue
            if st.in_flight >= st.cap:
                continue
            # state dominates (degraded replicas only when no ok one
            # has room), then the hot-prefix run, then load
            key = (1 if st.state == "ok" else 0,
                   st.prefix_score(usable), -st.in_flight)
            if best_key is None or key > best_key:
                best, best_key = st, key
        return best

    def _place_decode(self, req: RouterRequest) -> bool:
        st = self._pick_decode(req)
        if st is None:
            return False
        usable = req.digests[:req.usable]
        score = st.prefix_score(usable)
        if req.payload is not None:
            # ship the KV ahead of the generate op on the same ordered
            # connection: the import lands before admission runs
            iid = f"imp{req.xid}.{req.placements}"
            imp = {"id": iid, "op": "import_prefix",
                   "payload": req.payload}
            if req.trace_id:
                imp["trace"] = req.trace_id
            st.handle.submit(imp)
            st.outstanding[iid] = (req, "import")
            st.mark_hot(req.digests[:req.payload_blocks])
            score = max(score, req.payload_blocks)
            req.payload = None
        spec = {
            "id": req.xid, "prompt": [int(t) for t in req.prompt],
            "max_new": req.max_new, "temperature": req.temperature,
            "top_k": req.top_k, "eos_id": req.eos_id,
            "tenant": req.tenant, "tier": req.tier}
        if req.trace_id:
            # the replica engine ADOPTS this id (its _enqueue only
            # mints one when the wire didn't carry one), so its
            # queued/prefill/decode spans join this very track
            spec["trace"] = req.trace_id
        st.handle.submit(spec)
        st.outstanding[req.xid] = (req, "generate")
        req.status, req.replica = "placed", st.name
        req.placed_t = time.perf_counter()
        req.placements += 1
        req.prefix_score = score
        self._charge(req)
        self._m_placements.inc()
        if score > 0:
            self._m_place_hits.inc()
        st.mark_hot(usable)
        if req.usable:
            # rewarm seed: this prompt's leading chunk-aligned prefix
            # is (about to be) warm here — what a replacement would
            # want re-imported if this replica dies
            st.note_recent(
                tuple(usable),
                req.prompt[:req.usable * self.block_size].copy())
        self._rev(req, "queue", "e", req.placed_t)
        self._rev(req, "place", "n", req.placed_t, kind="generate",
                  replica=st.name, prefix_score=score,
                  placements=req.placements)
        return True

    # -- observability -----------------------------------------------------
    def _slo_burn_rate(self) -> float:
        if self.slo is None:
            return 0.0
        return self.slo.burn_rate(
            self._win_ttft.fraction_over(self.slo.ttft_s))

    def _update_gauges(self):
        """Cheap per-step scalar gauges (the scheduler calls this every
        iteration — window quantiles live in _update_window_gauges,
        computed only at scrape time like the engines')."""
        for st in self._all:
            self._m_in_flight.set(st.in_flight, replica=st.name)
            qd = (st.last_health or {}).get("queue_depth")
            if qd is not None:
                self._m_replica_queue.set(qd, replica=st.name)
        self._m_hit_rate.set(self.placement_hit_rate())

    def _update_window_gauges(self):
        ttft = self._win_ttft.quantiles((0.5, 0.95, 0.99))
        tps = self._win_tps.quantiles((0.5, 0.95, 0.99))
        for lbl, q in (("p50", 0.5), ("p95", 0.95), ("p99", 0.99)):
            self._m_win_ttft.set(ttft[q], q=lbl)
            self._m_win_tps.set(tps[q], q=lbl)
        self._m_burn.set(self._slo_burn_rate())

    def health(self) -> dict:
        self._update_gauges()
        self._update_window_gauges()
        ttft = self._win_ttft.quantiles((0.5, 0.95, 0.99))
        doc = {
            "replicas": {
                st.name: {
                    "state": st.state,
                    "role": "prefill" if st in self._prefill
                    else "decode",
                    "in_flight": st.in_flight,
                    "queue_depth": (st.last_health or {}).get(
                        "queue_depth"),
                    "slots_active": (st.last_health or {}).get(
                        "slots_active"),
                    "blocks_in_use": (st.last_health or {}).get(
                        "blocks_in_use"),
                    "blocks_total": (st.last_health or {}).get(
                        "blocks_total"),
                    "ttft_p99_s": ((st.last_health or {}).get("window")
                                   or {}).get("ttft_p99_s"),
                    "slo_burn": ((st.last_health or {}).get("slo")
                                 or {}).get("ttft_burn_rate"),
                    "tiers": {
                        t: ((st.last_health or {}).get("tiers") or {})
                        .get(t, {}).get("entries")
                        for t in ("dram", "disk")}}
                for st in self._all},
            "directory_size": len(self.directory()),
            "queue_depth": len(self._queue),
            "requests": int(self._m_requests.value()),
            "completed": self._n_completed,
            "requeued": int(self._m_requeued.value()),
            "shed": int(sum(c.value for c
                            in self._m_shed.series().values())),
            "placement_hit_rate": round(self.placement_hit_rate(), 4),
            "alerts_firing": self.alerts.firing(),
            "window": {"ttft_p50_s": round(ttft[0.5], 6),
                       "ttft_p99_s": round(ttft[0.99], 6),
                       "requests": self._win_ttft.count(),
                       "fleet_ttft_p99_s": round(
                           self.fleet.ttft_quantile(0.99), 6)}}
        decode_live = [st for st in self._decode
                       if st.state in ("ok", "degraded")]
        if not decode_live:
            doc["healthy"] = False      # nothing can admit: 503
        elif any(st.state != "ok" for st in self._all):
            doc["status"] = "degraded"
            doc["degraded_reason"] = ", ".join(
                f"{st.name}={st.state}" for st in self._all
                if st.state != "ok")
        if self.slo is not None:
            doc["slo"] = {"ttft_s": self.slo.ttft_s,
                          "target": self.slo.target,
                          "burn_rate": round(self._slo_burn_rate(), 4)}
        if self._tenant_budgets:
            doc["tenants"] = {
                t: {"budget": b, "in_flight": self._tenant_used.get(t, 0)}
                for t, b in sorted(self._tenant_budgets.items())}
        if self._controller_summary is not None:
            try:
                doc["controller"] = self._controller_summary()
            except Exception:
                pass
        return doc

    def requests_doc(self, k: int = 10) -> dict:
        doc = self.request_log.summary()
        doc["slowest_by_ttft"] = self.request_log.slowest(k, by="ttft_s")
        return doc

    def metrics_text(self) -> str:
        self._update_gauges()
        self._update_window_gauges()
        return self.metrics.render_prometheus()

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """/metrics + /healthz + /requests + /alerts over the router
        registry (the aggregator writes fleet series into it, so this
        one scrape answers for the whole fleet); caller owns
        ``close()``."""
        from paddle_tpu.observe.health import HealthServer
        return HealthServer(registry=self.metrics, health_fn=self.health,
                            host=host, port=port,
                            requests_fn=self.requests_doc,
                            metrics_fn=self.metrics_text,
                            alerts_fn=self.alerts.doc)

    def close(self):
        for st in self._all:
            try:
                st.handle.close()
            except Exception:
                pass
        self.fleet.close()
