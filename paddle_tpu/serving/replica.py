"""Serving replica: the JSONL engine loop behind one fleet endpoint.

One replica = one decode engine + one :class:`EngineLoop` pumping the
fleet's JSONL op wire through it. The wire is the ``paddle_tpu serve``
request/result format, extended with two fleet ops:

- ``{"prompt": [...], "max_new": n, ...}`` (op ``generate``, the
  default) → one result line ``{"id", "tokens", "finish_reason",
  "ttft_ms", "latency_ms"}`` when the request completes (NOT in
  submission order — continuous batching);
- ``{"op": "export_prefix", "prompt": [...]}`` → the prompt's
  transferable KV prefix serialized out of the pool (base64; the
  prefill half of P/D disaggregation). A cold prompt rides the
  ordinary scheduler first — its chunks interleave with in-flight
  decode like any admission — and the payload serializes when the
  warm-up request finishes;
- ``{"op": "import_prefix", "payload": b64}`` → adopt transferred
  blocks via the prefix-cache publish path (the decode half); acked
  with ``{"imported": n}``. Ops on one connection are processed in
  arrival order, so an ``import_prefix`` line followed by a
  ``generate`` line is guaranteed to admit AFTER the blocks landed.

Transports around the loop:

- :func:`serve_stdio` — the ``paddle_tpu serve`` stdio loop, now with
  graceful drain: SIGTERM stops ingesting, every in-flight (and
  already-read) request finishes and emits its result, and the loop
  returns 0 — the contract the fleet router's replica drain relies on;
- :class:`ReplicaServer` — the same loop behind a TCP socket
  (``paddle_tpu serve --port``), one reader thread per connection,
  results written back to the submitting connection;
- :class:`EngineReplica` / :class:`SocketReplica` — the Router-facing
  replica HANDLES (``submit / poll / health / alive / pump``): one
  wraps an engine in this process (single-process fleets, tests, the
  bench's equal-chip A/B), the other speaks TCP + HTTP ``/healthz`` to
  a replica process. A dead socket flips ``alive()`` False — the
  router's signal to requeue that replica's in-flight work elsewhere.
"""

import base64
import json
import queue
import signal
import socket
import sys
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np


class _StreamReply:
    """Reply sink over a text stream (stdout): one JSON line per doc."""

    def __init__(self, stream):
        self._stream = stream
        self._lock = threading.Lock()

    def write(self, doc: dict):
        with self._lock:
            print(json.dumps(doc), file=self._stream, flush=True)


class _SocketReply:
    """Reply sink over one TCP connection. A peer that hung up makes
    results undeliverable — swallowed, never a loop crash (the fleet
    router treats the REPLICA dying as the failure mode, not vice
    versa)."""

    def __init__(self, conn: socket.socket):
        self._conn = conn
        self._lock = threading.Lock()

    def write(self, doc: dict):
        data = (json.dumps(doc) + "\n").encode("utf-8")
        with self._lock:
            try:
                self._conn.sendall(data)
            except OSError:
                pass


class ListReply:
    """Collects reply docs in memory — the in-process handle's sink."""

    def __init__(self):
        self.docs: List[dict] = []

    def write(self, doc: dict):
        self.docs.append(doc)


class EngineLoop:
    """Transport-agnostic JSONL op loop around one decode engine.

    Lines (str or pre-parsed dict) arrive via :meth:`feed` from any
    thread, each with the reply sink its results go back to; the loop
    itself runs single-threaded (:meth:`run` on the owner's thread, or
    :meth:`step_once` pumped externally), so the engine never sees
    concurrent calls. Ops are processed in arrival order. Exit
    conditions: EOF (:meth:`feed_eof`) or DRAIN (:meth:`drain`) — both
    finish everything in flight and already queued first, emitting
    every result, which is what makes SIGTERM lossless."""

    def __init__(self, eng, *, default_max_new: int = 64):
        self.eng = eng
        self._inbox: "queue.Queue" = queue.Queue()
        self.draining = threading.Event()
        self._sealed = threading.Event()
        self._eof = False
        self._default_max_new = int(default_max_new)
        self._live: Dict[int, Tuple[object, object]] = {}
        self._exports: Dict[
            int, Tuple[object, object, np.ndarray, object]] = {}

    # -- ingestion (any thread) -------------------------------------------
    def feed(self, line, reply):
        if self._sealed.is_set():
            # draining: lines accepted BEFORE the seal finish and emit;
            # anything arriving after is refused with an error doc (id
            # echoed so a router can requeue it elsewhere) — otherwise
            # a continuously-streaming client would reset ``eng.idle``
            # forever and the drain could never converge
            doc = {"error": "draining: replica not admitting"}
            if isinstance(line, dict):
                if "id" in line:
                    doc["id"] = line["id"]
            else:
                try:
                    doc["id"] = json.loads(line)["id"]
                except (ValueError, KeyError, TypeError):
                    pass
            reply.write(doc)
            return
        self._inbox.put((line, reply))

    def feed_eof(self):
        self._inbox.put(None)

    def drain(self):
        """Graceful-drain trigger (signal-safe: just sets an Event)."""
        self.draining.set()

    @property
    def idle(self) -> bool:
        return self.eng.idle and self._inbox.empty()

    def _stamp(self, doc: dict) -> dict:
        # Every result/ack doc carries the spill tiers' eviction epoch
        # so a router comparing it against the epoch it saw at the last
        # /healthz scrape learns about full-retirement evictions NOW,
        # between health cadences, instead of fetching a stale digest.
        tiers = getattr(self.eng, "tiers", None)
        if tiers is not None:
            doc["tier_epoch"] = int(tiers.eviction_epoch)
        return doc

    # -- op dispatch (loop thread only) -----------------------------------
    def _ingest(self, item):
        if item is None:
            self._eof = True
            return
        line, reply = item
        if isinstance(line, str):
            if not line.strip():
                return
            try:
                r = json.loads(line)
            except json.JSONDecodeError as e:
                reply.write({"error": f"bad json: {e}"})
                return
        else:
            r = dict(line)
        op = r.get("op", "generate")
        try:
            if op == "generate":
                self._op_generate(r, reply)
            elif op == "export_prefix":
                self._op_export(r, reply)
            elif op == "import_prefix":
                self._op_import(r, reply)
            else:
                raise ValueError(f"unknown op {op!r}")
        except (ValueError, KeyError, TypeError) as e:
            err = {"error": str(e)}
            if "id" in r:
                err["id"] = r["id"]
            reply.write(err)

    def _op_generate(self, r: dict, reply):
        req = self.eng.submit(
            np.asarray(r["prompt"], np.int32),
            int(r.get("max_new", self._default_max_new)),
            temperature=float(r.get("temperature", 0.0)),
            top_k=int(r.get("top_k", 0)),
            eos_id=r.get("eos_id"),
            tenant=str(r.get("tenant", "default")),
            tier=str(r.get("tier", "batch")),
            trace=r.get("trace"))
        self._live[req.rid] = (reply, r.get("id", req.rid))

    def _op_export(self, r: dict, reply):
        eng = self.eng
        if not hasattr(eng, "export_prefix"):
            raise ValueError("export_prefix needs a paged engine")
        prompt = np.asarray(r["prompt"], np.int32).reshape(-1)
        xid = r.get("id")
        digests = eng.prefix_digests(prompt)
        if not digests:
            reply.write(self._stamp(
                {"id": xid, "op": "export_prefix",
                 "payload": None, "blocks": 0}))
            return
        if r.get("warm_only"):
            # fleet cache-directory fetch: serve whatever leading run
            # is warm HERE (HBM pool + DRAM/disk spill tiers mixed),
            # never warming the prompt up locally — the requester asked
            # for our cache, not our compute. An empty run is an empty
            # payload; the fetcher falls back to a cold prefill.
            payload = eng.export_prefix(prompt, trace=r.get("trace"),
                                        partial=True)
            if payload is None:
                reply.write(self._stamp(
                    {"id": xid, "op": "export_prefix",
                     "payload": None, "blocks": 0}))
            else:
                from paddle_tpu.serving import transfer as _transfer
                meta, _ = _transfer.deserialize_blocks(payload)
                reply.write(self._stamp(self._export_doc(
                    xid, payload, len(meta["digests"]))))
            return
        payload = eng.export_prefix(prompt, trace=r.get("trace"))
        if payload is not None:      # prefix already hot: serialize now
            reply.write(self._stamp(
                self._export_doc(xid, payload, len(digests))))
            return
        # cold: run the prompt through the ordinary scheduler (its
        # chunks publish into the prefix cache as each one lands, and
        # interleave with in-flight decode like any admission); the
        # payload serializes when the warm-up request finishes. The
        # warm-up request adopts the wire trace id so the prefill half
        # of a disaggregated handoff joins the same fleet timeline as
        # the decode half.
        req = eng.submit(prompt, 1, trace=r.get("trace"))
        self._exports[req.rid] = (reply, xid, prompt, r.get("trace"))

    @staticmethod
    def _export_doc(xid, payload: bytes, blocks: int) -> dict:
        return {"id": xid, "op": "export_prefix",
                "payload": base64.b64encode(payload).decode("ascii"),
                "blocks": int(blocks)}

    def _op_import(self, r: dict, reply):
        eng = self.eng
        if not hasattr(eng, "import_prefix"):
            raise ValueError("import_prefix needs a paged engine")
        n = eng.import_prefix(base64.b64decode(r["payload"]))
        reply.write(self._stamp({"id": r.get("id"), "op": "import_prefix",
                                 "imported": int(n)}))

    def _finish(self, req):
        if req.rid in self._exports:
            reply, xid, prompt, trace = self._exports.pop(req.rid)
            payload = self.eng.export_prefix(prompt, trace=trace)
            if payload is None:
                # evicted under pool pressure before serialization: the
                # requester falls back to a cold prefill (slower, same
                # bits)
                reply.write(self._stamp(
                    {"id": xid, "op": "export_prefix",
                     "payload": None, "blocks": 0}))
            else:
                reply.write(self._stamp(self._export_doc(
                    xid, payload,
                    len(self.eng.prefix_digests(prompt)))))
            return
        reply, xid = self._live.pop(req.rid, (None, None))
        if reply is None:
            return
        reply.write(self._stamp({
            "id": xid, "tokens": [int(t) for t in req.tokens],
            "finish_reason": req.finish_reason,
            "ttft_ms": round(1000 * req.ttft_s, 3)
            if req.ttft_s is not None else None,
            "latency_ms": round(1000 * req.latency_s, 3)
            if req.latency_s is not None else None}))

    # -- pumping -----------------------------------------------------------
    def ingest_all(self):
        while True:
            try:
                self._ingest(self._inbox.get_nowait())
            except queue.Empty:
                return

    def step_once(self):
        """Fleet-handle pump: ingest everything queued, then one engine
        step (results land in their reply sinks)."""
        self.ingest_all()
        if not self.eng.idle:
            for req in self.eng.step():
                self._finish(req)

    def pump(self, block_s: float = 0.05) -> bool:
        """One run-loop iteration. Returns False when the loop should
        exit (EOF or drain, with everything finished and emitted)."""
        if self.draining.is_set():
            # first observation seals the inbox: everything queued up
            # to the seal was accepted and must finish; later feed()
            # calls are refused (see feed) so the drain converges even
            # under a client that never stops streaming
            self.ingest_all()
            self._sealed.set()
            self.ingest_all()   # lines that raced the seal flag
        else:
            try:
                self._ingest(self._inbox.get(
                    timeout=block_s if self.eng.idle else 0.0))
            except queue.Empty:
                pass
        if not self.eng.idle:
            for req in self.eng.step():
                self._finish(req)
        return not ((self._eof or self.draining.is_set())
                    and self.eng.idle and self._inbox.empty())

    def run(self) -> int:
        while self.pump():
            pass
        return 0


def install_drain_handler(loop: EngineLoop,
                          signals_=(signal.SIGTERM,)):
    """SIGTERM → :meth:`EngineLoop.drain`. Returns a ``restore()``
    callable putting the previous handlers back. Signal handlers can
    only be installed from the main thread (the signal-module rule);
    elsewhere this is a documented no-op — embedding callers drive
    ``loop.drain()`` themselves."""
    if (not signals_ or threading.current_thread()
            is not threading.main_thread()):
        return lambda: None
    prev = {}
    for s in signals_:
        prev[s] = signal.signal(s, lambda *_: loop.drain())

    def restore():
        for s, h in prev.items():
            try:
                signal.signal(s, h)
            except (ValueError, OSError):
                pass
    return restore


def serve_stdio(eng, stdin=None, stdout=None, *,
                default_max_new: int = 64,
                drain_signals=(signal.SIGTERM,)) -> int:
    """The ``paddle_tpu serve`` stdio loop: JSONL requests from
    ``stdin`` through ``eng``, one JSONL result per request on
    ``stdout`` as it completes. Exits 0 at stdin EOF once in-flight
    work drains — or on SIGTERM, which stops reading and finishes
    everything already accepted (results emitted, exit 0): the
    graceful replica-drain contract the fleet router relies on."""
    stdin = stdin if stdin is not None else sys.stdin
    stdout = stdout if stdout is not None else sys.stdout
    loop = EngineLoop(eng, default_max_new=default_max_new)
    reply = _StreamReply(stdout)

    def _read():
        try:
            for line in stdin:
                loop.feed(line, reply)
        except ValueError:          # stdin closed under the reader
            pass
        loop.feed_eof()

    threading.Thread(target=_read, daemon=True,
                     name="serve-stdin").start()
    restore = install_drain_handler(loop, drain_signals)
    try:
        return loop.run()
    finally:
        restore()


class ReplicaServer:
    """TCP JSONL replica endpoint around one engine — the fleet-facing
    ``paddle_tpu serve --port`` transport. Connection reader threads
    feed the shared :class:`EngineLoop`; the engine loop runs on the
    caller's thread (:meth:`serve_forever`) and writes each line's
    results back to its originating connection (keep the connection
    open to receive them). Runs until :meth:`drain` (SIGTERM in the
    CLI): in-flight requests finish and emit, then ``serve_forever``
    returns 0. A client disconnecting is NOT a drain — other clients
    (or a reconnecting router) keep the replica serving."""

    def __init__(self, eng, host: str = "127.0.0.1", port: int = 0,
                 *, default_max_new: int = 64):
        self.loop = EngineLoop(eng, default_max_new=default_max_new)
        self._sock = socket.create_server((host, port))
        self._sock.settimeout(0.2)
        self.addr = self._sock.getsockname()
        self._closed = threading.Event()
        threading.Thread(target=self._accept_loop, daemon=True,
                         name="replica-accept").start()

    @property
    def port(self) -> int:
        return self.addr[1]

    def _accept_loop(self):
        while not self._closed.is_set():
            if self.loop.draining.is_set():
                return      # draining: no new connections either
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._reader, args=(conn,),
                             daemon=True, name="replica-conn").start()

    def _reader(self, conn: socket.socket):
        reply = _SocketReply(conn)
        try:
            with conn, conn.makefile("r", encoding="utf-8") as f:
                for line in f:
                    self.loop.feed(line, reply)
        except (OSError, ValueError):
            pass

    def serve_forever(self) -> int:
        try:
            return self.loop.run()
        finally:
            self.close()

    def drain(self):
        self.loop.drain()

    def close(self):
        self._closed.set()
        try:
            self._sock.close()
        except OSError:
            pass


class EngineReplica:
    """In-process fleet handle: the Router-facing replica protocol
    (``submit / poll / health / alive / pump``) over a live engine in
    THIS process — single-process fleets, the fast router tests, and
    the bench's equal-chip A/B (no process/socket overhead in the
    timed path)."""

    def __init__(self, eng, name: str = "replica0", *,
                 default_max_new: int = 64):
        self.eng = eng
        self.name = str(name)
        self._loop = EngineLoop(eng, default_max_new=default_max_new)
        self._reply = ListReply()
        self._killed = False

    def submit(self, spec: dict):
        if self._killed:
            return
        self._loop.feed(dict(spec), self._reply)

    def pump(self):
        """Advance the wrapped engine by one scheduler step."""
        if not self._killed:
            self._loop.step_once()

    def poll(self) -> List[dict]:
        if self._killed:
            return []
        docs, self._reply.docs = self._reply.docs, []
        return docs

    def health(self) -> Optional[dict]:
        return None if self._killed else self.eng.health()

    def alive(self) -> bool:
        return not self._killed

    def kill(self):
        """Simulate process death (chaos tests, the bench's kill
        injection): the handle goes deaf — ``alive()`` False, submits
        dropped, results undeliverable — and the wrapped engine closes
        its live requests' open trace slices (``abort_requests``) the
        way a real SIGKILL loses them with the process's span buffer.
        The router's requeue path sees exactly what a dead socket
        shows it."""
        self._killed = True
        if hasattr(self.eng, "abort_requests"):
            self.eng.abort_requests()

    def metrics_snapshot(self) -> Optional[dict]:
        """The engine registry's snapshot dict — the fleet aggregator's
        in-process scrape source (the TCP handle parses `/metrics`
        text into the same shape)."""
        if self._killed:
            return None
        self.eng._update_window_gauges()
        return self.eng.metrics.snapshot()

    @property
    def idle(self) -> bool:
        return self._loop.idle

    def close(self):
        pass


class SocketReplica:
    """Router-side handle to a replica PROCESS over TCP (the JSONL op
    wire) + its HTTP ``/healthz``. A dead socket (connection EOF,
    refused writes) flips :meth:`alive` False — the router's signal to
    requeue this replica's in-flight work onto survivors. ``health()``
    returns the parsed three-state document, or ``None`` when the
    endpoint is unreachable (state unknown; LIVENESS stays the
    transport's verdict)."""

    def __init__(self, name: str, addr, health_url: Optional[str] = None,
                 *, connect_timeout: float = 10.0):
        self.name = str(name)
        self.addr = tuple(addr)
        self.health_url = health_url
        self._q: "queue.Queue" = queue.Queue()
        self._dead = threading.Event()
        self._wlock = threading.Lock()
        self._sock = socket.create_connection(self.addr,
                                              timeout=connect_timeout)
        self._sock.settimeout(None)
        threading.Thread(target=self._read_loop, daemon=True,
                         name=f"fleet-{self.name}").start()

    def _read_loop(self):
        try:
            with self._sock.makefile("r", encoding="utf-8") as f:
                for line in f:
                    try:
                        self._q.put(json.loads(line))
                    except json.JSONDecodeError:
                        continue
        except (OSError, ValueError):
            pass
        self._dead.set()

    def submit(self, spec: dict):
        data = (json.dumps(spec) + "\n").encode("utf-8")
        try:
            with self._wlock:
                self._sock.sendall(data)
        except OSError:
            self._dead.set()

    def poll(self) -> List[dict]:
        out = []
        while True:
            try:
                out.append(self._q.get_nowait())
            except queue.Empty:
                return out

    def health(self) -> Optional[dict]:
        if self.health_url is None:
            return None
        import urllib.error
        import urllib.request
        url = self.health_url.rstrip("/") + "/healthz"
        try:
            with urllib.request.urlopen(url, timeout=2.0) as resp:
                return json.loads(resp.read())
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read())   # 503 carries the
            except (ValueError, OSError):     # unhealthy doc
                return {"status": "unhealthy"}
        except Exception:
            return None

    def metrics_snapshot(self) -> Optional[dict]:
        """Scrape the replica process's `/metrics` into the registry
        snapshot shape (``observe.metrics.parse_prometheus``) — the
        fleet aggregator's TCP scrape source. ``None`` when the
        endpoint is unreachable (the aggregator keeps the last view)."""
        if self.health_url is None:
            return None
        import urllib.request
        from paddle_tpu.observe.metrics import parse_prometheus
        url = self.health_url.rstrip("/") + "/metrics"
        try:
            with urllib.request.urlopen(url, timeout=2.0) as resp:
                return parse_prometheus(resp.read().decode("utf-8"))
        except Exception:
            return None

    def alive(self) -> bool:
        return not self._dead.is_set()

    def pump(self):
        """No-op: the replica process steps its own engine."""

    def close(self):
        self._dead.set()
        try:
            self._sock.close()
        except OSError:
            pass
