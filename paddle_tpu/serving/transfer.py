"""KV-block transfer wire: serialize pool blocks for P/D disaggregation.

The serving fleet's prefill/decode split ships FINISHED KV blocks from
a prefill replica's pool into a decode replica's pool. This module owns
that wire: one payload is an ordered CHAIN of (content digest, block
rows) pairs sliced out of the head-major pool (k/v ``[L, Hkv, M, Dh]``;
int8/int4 pools add the ``[L, Hkv, M]`` fp32 scale tables — scales
travel WITH their block, the write-local property that makes blocks
relocatable across pools), stamped with the pool layout / kv_dtype /
per-block slab shape so a mismatched receiver refuses loudly instead of
adopting garbage.

Deserialize + write is the receiving side's half: the decode engine
allocates local blocks, writes the payload rows in (functional jnp
updates at block-aligned offsets — ``write_block``), and publishes the
digests through the ordinary prefix-cache publish path
(``PagedDecodeEngine.import_prefix``). Adoption is then a plain prefix
cache hit, so generation downstream is bitwise the colocated run
(the PR-6 hit-vs-cold guarantee).

The wire is explicit binary, not pickle: a fixed magic + version, a
JSON header naming layout/kv_dtype/digests/array specs, then the raw
C-order buffers in documented order. Everything roundtrips BITWISE for
fp32, bf16, int8 and int4 pools (tests/test_fleet.py). Serialization
host-copies only the shipped block slabs (``np.asarray`` per slab, not
per pool leaf); jax is only touched in ``write_block``/``write_blocks``.
"""

import json
import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

MAGIC = b"PTKV"
VERSION = 1

# per-block arrays ride in this order (when present in the pool)
ARRAY_ORDER = ("k", "v", "k_scale", "v_scale")


def _np_dtype(name: str) -> np.dtype:
    """Resolve a dtype name, including the ml_dtypes extras (bfloat16)
    a jax pool may store."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes
        return np.dtype(getattr(ml_dtypes, name))


def _block_slab(leaf: np.ndarray, block: int, block_size: int):
    """One block's rows out of a pool leaf: the position axis is axis 2
    for the 4D value arrays ([L, Hkv, M, Dh]) and the trailing axis for
    the 3D scale tables ([L, Hkv, M])."""
    s = block * block_size
    if leaf.ndim == 4:
        return leaf[:, :, s:s + block_size, :]
    return leaf[:, :, s:s + block_size]


def pool_meta(cache, block_size: int, kv_dtype: str = "none") -> dict:
    """The stamp a payload carries (and ``check_pool_match`` verifies):
    pool layout, KV storage width, block size, and each array's
    per-block slab shape + dtype."""
    from paddle_tpu.models.transformer import POOL_LAYOUT
    arrays = {}
    for name in ARRAY_ORDER:
        if name not in cache:
            continue
        leaf = cache[name]
        shape = list(leaf.shape)
        shape[2] = int(block_size)
        arrays[name] = {"shape": shape, "dtype": str(leaf.dtype)}
    return {"layout": POOL_LAYOUT, "kv_dtype": str(kv_dtype or "none"),
            "block_size": int(block_size), "arrays": arrays}


def serialize_raw_blocks(meta: dict,
                         items: Sequence[Tuple[bytes, Dict[str, np.ndarray]]],
                         trace: Optional[str] = None) -> bytes:
    """Pack already-materialized ``(digest, {name: slab})`` pairs under
    a prebuilt :func:`pool_meta` stamp. This is :func:`serialize_blocks`
    with the pool-slicing step factored out, so a sender can mix slabs
    read from its HBM pool with slabs round-tripped through a spill
    tier (``serving/tiers.py``) in ONE chain-ordered payload — the
    receiving side cannot tell the difference, which is the point."""
    meta = dict(meta)
    meta["digests"] = [bytes(d).hex() for d, _ in items]
    if trace:
        meta["trace"] = str(trace)
    names = [n for n in ARRAY_ORDER if n in meta["arrays"]]
    header = json.dumps(meta).encode("utf-8")
    out = [MAGIC, struct.pack("<II", VERSION, len(header)), header]
    for _, arrays in items:
        for n in names:
            out.append(np.ascontiguousarray(
                np.asarray(arrays[n])).tobytes())
    return b"".join(out)


def serialize_blocks(cache, block_ids: Sequence[int],
                     digests: Sequence[bytes], block_size: int,
                     kv_dtype: str = "none",
                     trace: Optional[str] = None) -> bytes:
    """Pack ``block_ids``'s pool rows (chain order, one digest per
    block) into one stamped payload. ``trace`` rides in the header so
    the fleet trace context survives the P/D hop INSIDE the payload —
    the importing replica emits its adoption event on the same track
    even when the payload is relayed through a router that did not
    stamp the wire op."""
    if len(block_ids) != len(digests):
        raise ValueError(f"{len(block_ids)} blocks vs "
                         f"{len(digests)} digests")
    meta = pool_meta(cache, block_size, kv_dtype)
    meta["digests"] = [bytes(d).hex() for d in digests]
    if trace:
        meta["trace"] = str(trace)
    names = [n for n in ARRAY_ORDER if n in meta["arrays"]]
    header = json.dumps(meta).encode("utf-8")
    out = [MAGIC, struct.pack("<II", VERSION, len(header)), header]
    # slice each block's slab FIRST, then host-copy only the slab — a
    # device pool ships B*block_size rows over the wire, not the whole
    # pool per export
    for b in block_ids:
        for n in names:
            out.append(np.ascontiguousarray(np.asarray(
                _block_slab(cache[n], int(b), block_size))).tobytes())
    return b"".join(out)


def deserialize_blocks(payload: bytes
                       ) -> Tuple[dict, List[Tuple[bytes, Dict[str, np.ndarray]]]]:
    """Unpack a payload into its stamp + the ordered
    ``(digest, {array name: block slab})`` chain."""
    if payload[:4] != MAGIC:
        raise ValueError("not a KV transfer payload (bad magic)")
    version, hlen = struct.unpack_from("<II", payload, 4)
    if version != VERSION:
        raise ValueError(f"KV payload version {version}, expected "
                         f"{VERSION}")
    meta = json.loads(payload[12:12 + hlen].decode("utf-8"))
    names = [n for n in ARRAY_ORDER if n in meta["arrays"]]
    specs = [(n, tuple(meta["arrays"][n]["shape"]),
              _np_dtype(meta["arrays"][n]["dtype"])) for n in names]
    off = 12 + hlen
    blocks = []
    for hexd in meta["digests"]:
        arrays = {}
        for n, shape, dt in specs:
            nbytes = int(np.prod(shape)) * dt.itemsize
            arrays[n] = np.frombuffer(
                payload, dtype=dt, count=int(np.prod(shape)),
                offset=off).reshape(shape)
            off += nbytes
        blocks.append((bytes.fromhex(hexd), arrays))
    if off != len(payload):
        raise ValueError(f"KV payload size mismatch: consumed {off} of "
                         f"{len(payload)} bytes")
    return meta, blocks


def check_pool_match(meta: dict, cache, block_size: int,
                     kv_dtype: str = "none"):
    """Refuse a payload whose stamp does not match the receiving pool —
    adopting bytes across a layout / storage-width / geometry mismatch
    would poison the prefix cache silently."""
    want = pool_meta(cache, block_size, kv_dtype)
    for key in ("layout", "kv_dtype", "block_size", "arrays"):
        if meta.get(key) != want[key]:
            raise ValueError(
                f"KV payload {key} mismatch: payload "
                f"{meta.get(key)!r} vs pool {want[key]!r}")


def write_block(cache, block: int, arrays: Dict[str, np.ndarray],
                block_size: int):
    """Write one deserialized block slab into ``cache`` at ``block``
    (functional update; returns the new pytree). Dtypes already match
    by ``check_pool_match``, so the copy is bitwise."""
    return write_blocks(cache, [(block, arrays)], block_size)


def write_blocks(cache, writes: Sequence[Tuple[int, Dict[str, np.ndarray]]],
                 block_size: int):
    """Batched :func:`write_block`: ONE functional scatter per pool
    leaf for the whole chain (per-block ``.at[].set`` would copy the
    full pool once per adopted block)."""
    if not writes:
        return cache
    import jax.numpy as jnp
    bs = int(block_size)
    idx = jnp.asarray(np.concatenate(
        [np.arange(int(b) * bs, int(b) * bs + bs) for b, _ in writes]))
    out = dict(cache)
    for name in writes[0][1]:
        slab = jnp.asarray(np.concatenate(
            [np.asarray(arrays[name]) for _, arrays in writes], axis=2))
        leaf = out[name]
        if leaf.ndim == 4:
            out[name] = leaf.at[:, :, idx, :].set(slab)
        else:
            out[name] = leaf.at[:, :, idx].set(slab)
    return out
