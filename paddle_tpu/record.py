"""Layer-call recording — the program save format's front half.

The reference persisted models as a ModelConfig protobuf next to the
weights (python/paddle/trainer/config_parser.py; trainer/MergeModel.cpp
packed both into one artifact). Here the Python call graph IS the config,
so the equivalent is to record each public layer-API call (name + JSON-able
kwargs) on the LayerOutput it produces; ``Topology.to_dict`` persists those
records and ``Topology.from_dict`` replays them to rebuild the graph in a
process that never saw the model-building code.

Calls whose arguments cannot be serialized (e.g. ``recurrent_group`` step
closures) simply carry no record — such graphs must be served through the
AOT StableHLO export path instead (paddle_tpu.io.merged).
"""

import dataclasses
import functools
import inspect
import itertools
import threading

_SCALARS = (bool, int, float, str, bytes, type(None))
_call_ids = itertools.count()
_lock = threading.Lock()


class Unserializable(Exception):
    """Argument cannot be represented in the program save format."""


def encode_value(v):
    from paddle_tpu.activation import BaseActivation
    from paddle_tpu.core.param import ParamAttr
    from paddle_tpu.data_type import InputType
    from paddle_tpu.pooling import BasePoolingType
    from paddle_tpu.topology import LayerOutput

    if isinstance(v, _SCALARS):
        return v
    if isinstance(v, LayerOutput):
        return {"$layer": v.name}
    if isinstance(v, (list, tuple)):
        return [encode_value(x) for x in v]
    if isinstance(v, dict):
        if not all(isinstance(k, str) for k in v):
            raise Unserializable(f"non-string dict keys: {v!r}")
        return {"$dict": {k: encode_value(x) for k, x in v.items()}}
    if isinstance(v, ParamAttr):
        return {"$param_attr": dataclasses.asdict(v)}
    if isinstance(v, InputType):
        return {"$input_type": [v.dim, v.kind.value, v.seq.value]}
    if isinstance(v, BaseActivation):
        return {"$act": v.name}
    if isinstance(v, BasePoolingType) or (
            isinstance(v, type) and issubclass(v, BasePoolingType)):
        return {"$pool": v.name}
    raise Unserializable(f"{type(v).__name__}: {v!r}")


def decode_value(v, nodes):
    """Inverse of encode_value; ``nodes`` maps layer name -> LayerOutput."""
    from paddle_tpu import activation as act_mod
    from paddle_tpu.core.param import ParamAttr
    from paddle_tpu.data_type import InputType, Kind, SeqLevel

    if isinstance(v, _SCALARS):
        return v
    if isinstance(v, list):
        return [decode_value(x, nodes) for x in v]
    if isinstance(v, dict):
        if "$layer" in v:
            return nodes[v["$layer"]]
        if "$dict" in v:
            return {k: decode_value(x, nodes) for k, x in v["$dict"].items()}
        if "$param_attr" in v:
            return ParamAttr(**v["$param_attr"])
        if "$input_type" in v:
            dim, kind, seq = v["$input_type"]
            return InputType(dim, Kind(kind), SeqLevel(seq))
        if "$act" in v:
            for cls in vars(act_mod).values():
                if (isinstance(cls, type)
                        and issubclass(cls, act_mod.BaseActivation)
                        and cls.name == v["$act"] ):
                    return cls()
            raise ValueError(f"unknown activation {v['$act']!r}")
        if "$pool" in v:
            return v["$pool"]   # layer APIs accept the string name
    raise ValueError(f"cannot decode {v!r}")


def _outputs_of(result):
    from paddle_tpu.topology import LayerOutput
    if isinstance(result, LayerOutput):
        return [result]
    if isinstance(result, (list, tuple)):
        return [r for r in result if isinstance(r, LayerOutput)]
    return []


def _recorded(api_path, fn):
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return fn

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        result = fn(*args, **kwargs)
        outs = _outputs_of(result)
        if outs and all(getattr(o, "config", None) is None for o in outs):
            # inner (already-recorded) calls win: a composite that merely
            # wraps recorded layer calls needs no record of its own
            try:
                bound = sig.bind(*args, **kwargs)
                enc = {k: encode_value(v) for k, v in bound.arguments.items()}
                with _lock:
                    cid = next(_call_ids)
                cfg = {"api": api_path, "kwargs": enc, "call": cid,
                       "out_names": [o.name for o in outs]}
                for i, o in enumerate(outs):
                    o.config = {**cfg, "out_index": i}
            except Unserializable:
                pass
        return result

    return wrapped


def install(module, public=None):
    """Wrap a module's public layer functions with call recording."""
    names = public if public is not None else [
        n for n in vars(module)
        if not n.startswith("_") and inspect.isfunction(vars(module)[n])
        and vars(module)[n].__module__ == module.__name__]
    for n in names:
        setattr(module, n, _recorded(f"{module.__name__}.{n}",
                                     getattr(module, n)))


def resolve_api(api_path):
    """'paddle_tpu.layer.fc' -> the (recorded) function object."""
    import importlib
    mod_name, _, attr = api_path.rpartition(".")
    return getattr(importlib.import_module(mod_name), attr)
