"""ResNet for ImageNet (reference: benchmark/paddle/image/resnet.py —
layer_num 50/101/152, 1000 classes, 3x224x224; the north-star benchmark
model per BASELINE.md).

Built on the layer API: conv_bn blocks with addto shortcuts; NHWC throughout;
bf16 matmul/conv compute per the global dtype policy.
"""

from paddle_tpu import activation, layer, pooling


def _stash_for(fused):
    """(stash dtype, stochastic rounding) for the deferral recipes;
    None = not a deferral mode. "q8sr" is q8 with unbiased stochastic
    rounding (closes the eval co-adaptation gap, BENCHMARKS.md)."""
    return {"q8": ("int8", False), "defer": ("bf16", False),
            "q8sr": ("int8", True)}.get(fused)


def conv_bn_layer(input, ch_out, filter_size, stride, padding, active_type,
                  ch_in=None, name=None, fused=False):
    """(reference: resnet.py conv_bn_layer). ``fused=True`` runs the
    single-op conv→BN path (ops/conv_bn.py: stats in the conv's fusion
    group, closed-form BN VJP); ``fused="int8"`` additionally stashes
    the backward's saved activations as int8. ``fused="q8"`` runs the
    q8 pipeline (ops/q8.py): activations stored int8 in HBM, BN affine +
    activation deferred into the consumer's conv fusion. ``fused="defer"``
    is the same deferral machinery with a near-lossless bf16 stash (the
    affine-prologue block-remat recipe). The round-3 Pallas conv kernels
    behind the old ``fused="full"`` mode measured 0.43-0.59x of plain
    XLA and were retired in round 5 (see ops/conv_bn.py docstring)."""
    if fused == "full":
        raise ValueError(
            "fused='full' (Pallas conv backward kernels) was retired "
            "after the on-chip A/B measured it at 0.43x of plain XLA "
            "(BENCHMARKS.md); use 'int8' or the q8/defer recipes")
    if _stash_for(fused):
        stash, sr = _stash_for(fused)
        return layer.img_conv_bn_q8(
            input, filter_size=filter_size, num_filters=ch_out,
            num_channels=ch_in, stride=stride, padding=padding,
            act=active_type, name=f"{name}_q8" if name else None,
            conv_name=f"{name}_conv" if name else None,
            bn_name=f"{name}_bn" if name else None,
            stash=stash, stochastic=sr)
    if fused:
        # explicit integer padding (NOT "SAME": XLA pads SAME
        # asymmetrically at stride 2, which would silently change
        # stride-2 numerics vs the unfused path); param names mirror the
        # unfused pair so checkpoints are interchangeable between paths.
        return layer.img_conv_bn(
            input, filter_size=filter_size, num_filters=ch_out,
            num_channels=ch_in, stride=stride, padding=padding,
            act=active_type, name=f"{name}_fused" if name else None,
            conv_name=f"{name}_conv" if name else None,
            bn_name=f"{name}_bn" if name else None,
            save8=(fused == "int8"))
    tmp = layer.img_conv(input, filter_size=filter_size, num_filters=ch_out,
                         num_channels=ch_in, stride=stride, padding=padding,
                         act=None, bias_attr=False,
                         name=f"{name}_conv" if name else None)
    return layer.batch_norm(tmp, act=active_type,
                            name=f"{name}_bn" if name else None)


def shortcut(input, ch_in, ch_out, stride, name=None, fused=False):
    if ch_in != ch_out or stride != 1:
        return conv_bn_layer(input, ch_out, 1, stride, 0, None,
                             name=f"{name}_proj" if name else None,
                             fused=fused)
    return input


def _addto(inputs, act, name, fused):
    if _stash_for(fused):
        stash, sr = _stash_for(fused)
        return layer.addto_q8(inputs, act=act, name=name,
                              stash=stash, stochastic=sr)
    return layer.addto(inputs, act=act, name=name)


def bottleneck_block(input, ch_in, ch_out, stride, name=None, fused=False):
    """1x1 -> 3x3 -> 1x1(x4) with identity/projection shortcut
    (reference: resnet.py bottleneck_block)."""
    short = shortcut(input, ch_in, ch_out * 4, stride, name=name,
                     fused=fused)
    conv1 = conv_bn_layer(input, ch_out, 1, stride, 0, activation.Relu(),
                          name=f"{name}_a" if name else None, fused=fused)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, activation.Relu(),
                          name=f"{name}_b" if name else None, fused=fused)
    conv3 = conv_bn_layer(conv2, ch_out * 4, 1, 1, 0, None,
                          name=f"{name}_c" if name else None, fused=fused)
    return _addto([conv3, short], activation.Relu(),
                  f"{name}_add" if name else None, fused)


def basic_block(input, ch_in, ch_out, stride, name=None, fused=False):
    short = shortcut(input, ch_in, ch_out, stride, name=name, fused=fused)
    conv1 = conv_bn_layer(input, ch_out, 3, stride, 1, activation.Relu(),
                          name=f"{name}_a" if name else None, fused=fused)
    conv2 = conv_bn_layer(conv1, ch_out, 3, 1, 1, None,
                          name=f"{name}_b" if name else None, fused=fused)
    return _addto([conv2, short], activation.Relu(),
                  f"{name}_add" if name else None, fused)


_DEPTH_CFG = {
    18: ("basic", [2, 2, 2, 2]),
    34: ("basic", [3, 4, 6, 3]),
    50: ("bottleneck", [3, 4, 6, 3]),
    101: ("bottleneck", [3, 4, 23, 3]),
    152: ("bottleneck", [3, 8, 36, 3]),
}


def resnet_imagenet(input, depth=50, class_num=1000, img_size=224,
                    stem_space_to_depth=False, fused_bn=False):
    """(reference: resnet.py:6 — 3x224x224, 1000 classes).
    stem_space_to_depth: compute the 7x7/s2 stem as a stride-1 conv over
    space-to-depth input (numerically identical; lane-utilisation lever,
    see layer.space_to_depth_conv).
    fused_bn: single-op conv→BN blocks (ops/conv_bn.py: stats ride the
    conv's fusion group, closed-form BN VJP; "int8" adds the int8
    backward stash; the stem keeps the unfused path). fused_bn="q8"
    instead runs the q8 pipeline (ops/q8.py): the whole residual trunk
    keeps activations in HBM as centered int8 with deferred BN/ReLU; the
    stem and head stay dense."""
    kind, counts = _DEPTH_CFG[depth]
    block = bottleneck_block if kind == "bottleneck" else basic_block
    expansion = 4 if kind == "bottleneck" else 1

    if stem_space_to_depth:
        tmp = layer.space_to_depth_conv(input, 7, 64, num_channels=3,
                                        act=None, img_size=img_size,
                                        name="res_conv1_conv")
        conv1 = layer.batch_norm(tmp, act=activation.Relu(),
                                 name="res_conv1_bn")
    else:
        conv1 = conv_bn_layer(input, 64, 7, 2, 3, activation.Relu(),
                              ch_in=3, name="res_conv1")
    pool1 = layer.img_pool(conv1, pool_size=3, stride=2, padding=1,
                           pool_type=pooling.Max(), name="res_pool1")

    ch_in = 64
    tmp = pool1
    if _stash_for(fused_bn):
        stash, sr = _stash_for(fused_bn)
        tmp = layer.q8_entry(tmp, name="res_q8_entry",
                             stash=stash, stochastic=sr)
    for stage, (n, ch_out) in enumerate(zip(counts, [64, 128, 256, 512])):
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            tmp = block(tmp, ch_in, ch_out, stride,
                        name=f"res{stage+2}_{i}", fused=fused_bn)
            ch_in = ch_out * expansion
    if _stash_for(fused_bn):
        tmp = layer.q8_exit(tmp, name="res_q8_exit")
    pool = layer.img_pool(tmp, pool_size=7, stride=1,
                          pool_type=pooling.Avg(), name="res_gap")
    return layer.fc(pool, class_num, act=activation.Softmax(), name="res_fc")


def resnet_cifar10(input, depth=32, class_num=10, fused_bn=False,
                   width=16):
    """(reference: v1_api_demo/model_zoo resnet cifar variant).
    fused_bn: same recipe surface as resnet_imagenet (False / True /
    "int8" / "q8" / "defer" / "q8sr"); the stem stays dense.
    width: base channel count (stages run width/2·width/4·width;
    width=64 gives the 64–256-channel ladder the q8 quality experiments
    use to probe per-channel scale behavior at ImageNet-class widths)."""
    n = (depth - 2) // 6
    conv1 = conv_bn_layer(input, width, 3, 1, 1, activation.Relu(),
                          ch_in=3, name="rc_conv1")
    tmp = conv1
    if _stash_for(fused_bn):
        stash, sr = _stash_for(fused_bn)
        tmp = layer.q8_entry(tmp, name="rc_q8_entry", stash=stash,
                             stochastic=sr)
    ch_in = width
    for stage, ch_out in enumerate([width, 2 * width, 4 * width]):
        for i in range(n):
            stride = 2 if (i == 0 and stage > 0) else 1
            tmp = basic_block(tmp, ch_in, ch_out, stride,
                              name=f"rc{stage}_{i}", fused=fused_bn)
            ch_in = ch_out
    if _stash_for(fused_bn):
        tmp = layer.q8_exit(tmp, name="rc_q8_exit")
    pool = layer.img_pool(tmp, pool_size=8, stride=1,
                          pool_type=pooling.Avg(), name="rc_gap")
    return layer.fc(pool, class_num, act=activation.Softmax(), name="rc_fc")
