"""GoogleNet / Inception-v1 (reference: benchmark/paddle/image/googlenet.py
— inception blocks via concat of 1x1/3x3/5x5/pool-proj branches)."""

from paddle_tpu import activation, layer, pooling


def inception(input, ch_1x1, ch_3x3r, ch_3x3, ch_5x5r, ch_5x5, pool_proj,
              name):
    b1 = layer.img_conv(input, 1, ch_1x1, padding=0, act=activation.Relu(),
                        name=f"{name}_1x1")
    b2r = layer.img_conv(input, 1, ch_3x3r, padding=0, act=activation.Relu(),
                         name=f"{name}_3x3r")
    b2 = layer.img_conv(b2r, 3, ch_3x3, padding=1, act=activation.Relu(),
                        name=f"{name}_3x3")
    b3r = layer.img_conv(input, 1, ch_5x5r, padding=0, act=activation.Relu(),
                         name=f"{name}_5x5r")
    b3 = layer.img_conv(b3r, 5, ch_5x5, padding=2, act=activation.Relu(),
                        name=f"{name}_5x5")
    bp = layer.img_pool(input, 3, stride=1, padding=1,
                        pool_type=pooling.Max(), name=f"{name}_pool")
    bpp = layer.img_conv(bp, 1, pool_proj, padding=0, act=activation.Relu(),
                         name=f"{name}_poolproj")
    return layer.concat([b1, b2, b3, bpp], name=f"{name}_out")


def googlenet(input, class_num=1000):
    c1 = layer.img_conv(input, 7, 64, num_channels=3, stride=2, padding=3,
                        act=activation.Relu(), name="g_c1", img_size=224)
    p1 = layer.img_pool(c1, 3, stride=2, padding=1, pool_type=pooling.Max(),
                        name="g_p1")
    c2r = layer.img_conv(p1, 1, 64, padding=0, act=activation.Relu(),
                         name="g_c2r")
    c2 = layer.img_conv(c2r, 3, 192, padding=1, act=activation.Relu(),
                        name="g_c2")
    p2 = layer.img_pool(c2, 3, stride=2, padding=1, pool_type=pooling.Max(),
                        name="g_p2")
    i3a = inception(p2, 64, 96, 128, 16, 32, 32, "g_i3a")
    i3b = inception(i3a, 128, 128, 192, 32, 96, 64, "g_i3b")
    p3 = layer.img_pool(i3b, 3, stride=2, padding=1, pool_type=pooling.Max(),
                        name="g_p3")
    i4a = inception(p3, 192, 96, 208, 16, 48, 64, "g_i4a")
    i4b = inception(i4a, 160, 112, 224, 24, 64, 64, "g_i4b")
    i4c = inception(i4b, 128, 128, 256, 24, 64, 64, "g_i4c")
    i4d = inception(i4c, 112, 144, 288, 32, 64, 64, "g_i4d")
    i4e = inception(i4d, 256, 160, 320, 32, 128, 128, "g_i4e")
    p4 = layer.img_pool(i4e, 3, stride=2, padding=1, pool_type=pooling.Max(),
                        name="g_p4")
    i5a = inception(p4, 256, 160, 320, 32, 128, 128, "g_i5a")
    i5b = inception(i5a, 384, 192, 384, 48, 128, 128, "g_i5b")
    gap = layer.img_pool(i5b, 7, stride=1, pool_type=pooling.Avg(),
                         name="g_gap")
    drop = layer.dropout(gap, 0.4, name="g_drop")
    return layer.fc(drop, class_num, act=activation.Softmax(), name="g_out")
