"""SmallNet for MNIST/CIFAR (reference: benchmark/paddle/image/
smallnet_mnist_cifar.py — 2 conv-pool + 2 fc)."""

from paddle_tpu import activation, layer, networks


def smallnet(input, class_num=10, num_channels=3):
    c1 = networks.simple_img_conv_pool(input, filter_size=5, num_filters=32,
                                       pool_size=3, pool_stride=2,
                                       num_channel=num_channels,
                                       act=activation.Relu(), name="s1",
                                       padding=2)
    c2 = networks.simple_img_conv_pool(c1, filter_size=5, num_filters=64,
                                       pool_size=3, pool_stride=2,
                                       act=activation.Relu(), name="s2",
                                       padding=2)
    fc1 = layer.fc(c2, 128, act=activation.Relu(), name="s_fc1")
    return layer.fc(fc1, class_num, act=activation.Softmax(), name="s_out")


def lenet5(input, class_num=10):
    """(reference: v1_api_demo/mnist LeNet-ish conv config)"""
    c1 = networks.simple_img_conv_pool(input, filter_size=5, num_filters=20,
                                       pool_size=2, num_channel=1,
                                       act=activation.Relu(), name="l1")
    c2 = networks.simple_img_conv_pool(c1, filter_size=5, num_filters=50,
                                       pool_size=2, act=activation.Relu(),
                                       name="l2")
    fc1 = layer.fc(c2, 500, act=activation.Relu(), name="l_fc1")
    return layer.fc(fc1, class_num, act=activation.Softmax(), name="l_out")
