"""CTR wide&deep model — the high-dimensional-sparse showcase.

Reference: v1_api_demo/quick_start/trainer_config.lr.py (wide sparse
logistic regression over bag-of-words), trainer_config.emb.py (the deep
embedding variant), and the sparse-remote-update training path those ran
on (trainer/RemoteParameterUpdater.h:265 sharded embedding rows across
pservers; math/SparseRowMatrix.h row-sparse grads). BASELINE config 5.

TPU-native layout: the wide weight [wide_dim, 2] and the embedding table
[vocab, emb_dim] shard across the ``model`` mesh axis (ctr_dist_rules);
lookups are gathers whose collectives XLA places over ICI, and the
row-sparse gradient materialises through the scatter-add in gather's
backward — no pserver, no SelectedRows.
"""

from typing import Sequence, Tuple

import paddle_tpu as paddle
from paddle_tpu import layer


def ctr_wide_deep(wide_dim: int, vocab_size: int, emb_dim: int = 64,
                  hidden: Sequence[int] = (128, 64), name: str = "ctr"):
    """Build the wide&deep click-through model.

    Inputs (feed order): ``wide`` sparse_binary_vector(wide_dim) — the
    cross/id features; ``deep_ids`` integer_value_sequence(vocab_size) —
    the deep-side feature ids; ``label`` integer_value(2).
    Returns (prediction LayerOutput [b, 2] softmax, cost LayerOutput).
    """
    wide_in = layer.data("wide", paddle.data_type.sparse_binary_vector(
        wide_dim))
    ids = layer.data("deep_ids", paddle.data_type.integer_value_sequence(
        vocab_size))
    lbl = layer.data("label", paddle.data_type.integer_value(2))

    emb = layer.embedding(ids, emb_dim, name=f"{name}_emb")
    deep = layer.pool(emb, pooling_type=paddle.pooling.Avg(),
                      name=f"{name}_pool")
    for i, h in enumerate(hidden):
        deep = layer.fc(deep, h, act=paddle.activation.Relu(),
                        name=f"{name}_fc{i}")

    # wide&deep join: one fc summing the sparse wide input and the deep
    # tower (multi-input fc = summed projections, the MixedLayer pattern)
    out = layer.fc([wide_in, deep], 2, act=paddle.activation.Softmax(),
                   name=f"{name}_out")
    cost = layer.classification_cost(out, lbl, name=f"{name}_cost")
    return out, cost


def ctr_dist_rules(name: str = "ctr"):
    """Sharding rules for the high-dim tables (the sparse_remote_update
    slot): embedding over vocab, wide weight over its input dim."""
    from paddle_tpu import parallel
    return [
        parallel.embedding_vocab_rule(rf"^{name}_emb\.w$"),
        parallel.fc_row_rule(rf"^{name}_out\.w0$"),   # wide [wide_dim, 2]
    ]


def synthetic_reader(wide_dim: int, vocab_size: int, n: int = 512,
                     seed: int = 0, nnz: int = 8, seq_len: int = 10):
    """Synthetic CTR samples with learnable structure: the label depends
    on whether feature ids fall in the 'clicky' half of each table."""
    import numpy as np

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            wide = sorted(set(rng.randint(0, wide_dim, nnz).tolist()))
            ids = rng.randint(0, vocab_size, rng.randint(3, seq_len))
            signal = (np.mean([w < wide_dim // 2 for w in wide])
                      + np.mean(ids < vocab_size // 2)) / 2
            label = int(signal > 0.5)
            yield wide, [int(i) for i in ids], label

    return reader
