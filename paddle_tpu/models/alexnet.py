"""AlexNet (reference: benchmark/paddle/image/alexnet.py — 227x227x3 input,
conv widths 96/256/384/384/256, conv1 11x11 s4 p1, LRN size 5 scale 1e-4
power 0.75, 3x3 s2 max pools, 4096-4096-1000 fc head with dropout 0.5)."""

from paddle_tpu import activation, layer, pooling


def alexnet(input, class_num=1000, img_size=227):
    conv1 = layer.img_conv(input, filter_size=11, num_filters=96,
                           num_channels=3, stride=4, padding=1,
                           act=activation.Relu(), name="a_conv1",
                           img_size=img_size)
    norm1 = layer.img_cmrnorm(conv1, size=5, scale=0.0001, power=0.75,
                              name="a_norm1")
    pool1 = layer.img_pool(norm1, 3, stride=2, pool_type=pooling.Max(),
                           name="a_pool1")
    conv2 = layer.img_conv(pool1, filter_size=5, num_filters=256, padding=2,
                           act=activation.Relu(), name="a_conv2")
    norm2 = layer.img_cmrnorm(conv2, size=5, scale=0.0001, power=0.75,
                              name="a_norm2")
    pool2 = layer.img_pool(norm2, 3, stride=2, pool_type=pooling.Max(),
                           name="a_pool2")
    conv3 = layer.img_conv(pool2, filter_size=3, num_filters=384, padding=1,
                           act=activation.Relu(), name="a_conv3")
    conv4 = layer.img_conv(conv3, filter_size=3, num_filters=384, padding=1,
                           act=activation.Relu(), name="a_conv4")
    conv5 = layer.img_conv(conv4, filter_size=3, num_filters=256, padding=1,
                           act=activation.Relu(), name="a_conv5")
    pool3 = layer.img_pool(conv5, 3, stride=2, pool_type=pooling.Max(),
                           name="a_pool3")
    fc1 = layer.fc(pool3, 4096, act=activation.Relu(), name="a_fc1")
    d1 = layer.dropout(fc1, 0.5, name="a_drop1")
    fc2 = layer.fc(d1, 4096, act=activation.Relu(), name="a_fc2")
    d2 = layer.dropout(fc2, 0.5, name="a_drop2")
    return layer.fc(d2, class_num, act=activation.Softmax(), name="a_out")
