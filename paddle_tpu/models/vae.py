"""Variational autoencoder (reference: v1_api_demo/vae/vae_conf.py +
vae_train.py — MLP encoder/decoder on MNIST with the reparameterisation
trick and an ELBO objective).

TPU-native: one jitted train step; the sampling key threads explicitly
(the reference drew noise on the host each batch)."""

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp

from paddle_tpu import optimizer as opt_mod


@dataclasses.dataclass(frozen=True)
class VAEConfig:
    x_dim: int = 784
    hidden_dim: int = 400
    z_dim: int = 20
    lr: float = 1e-3


def init_params(key: jax.Array, cfg: VAEConfig):
    ks = jax.random.split(key, 5)
    X, H, Z = cfg.x_dim, cfg.hidden_dim, cfg.z_dim

    def dense(k, i, o):
        return jax.random.normal(k, (i, o), jnp.float32) / math.sqrt(i)

    return {
        "enc_w": dense(ks[0], X, H), "enc_b": jnp.zeros(H),
        "mu_w": dense(ks[1], H, Z), "mu_b": jnp.zeros(Z),
        "lv_w": dense(ks[2], H, Z), "lv_b": jnp.zeros(Z),
        "dec_w": dense(ks[3], Z, H), "dec_b": jnp.zeros(H),
        "out_w": dense(ks[4], H, X), "out_b": jnp.zeros(X),
    }


def encode(params, x):
    h = jnp.tanh(x @ params["enc_w"] + params["enc_b"])
    mu = h @ params["mu_w"] + params["mu_b"]
    logvar = h @ params["lv_w"] + params["lv_b"]
    return mu, logvar


def decode(params, z):
    h = jnp.tanh(z @ params["dec_w"] + params["dec_b"])
    return h @ params["out_w"] + params["out_b"]     # bernoulli logits


def elbo_loss(params, x, key) -> Tuple[jax.Array, dict]:
    """Negative ELBO = BCE reconstruction + KL(q(z|x) || N(0,1))."""
    mu, logvar = encode(params, x)
    eps = jax.random.normal(key, mu.shape, mu.dtype)
    z = mu + jnp.exp(0.5 * logvar) * eps             # reparameterisation
    logits = decode(params, z)
    bce = jnp.sum(jax.nn.softplus(logits) - x * logits, axis=-1)
    kl = -0.5 * jnp.sum(1 + logvar - jnp.square(mu) - jnp.exp(logvar),
                        axis=-1)
    loss = jnp.mean(bce + kl)
    return loss, {"bce": jnp.mean(bce), "kl": jnp.mean(kl)}


class VAETrainer:
    def __init__(self, cfg: VAEConfig, key: jax.Array):
        self.cfg = cfg
        self.params = init_params(key, cfg)
        self.opt = opt_mod.Adam(learning_rate=cfg.lr).bind([])
        self.opt_state = self.opt.init_state(self.params)
        self._step = 0

        def step(params, opt_state, x, key, i):
            (loss, aux), grads = jax.value_and_grad(
                elbo_loss, has_aux=True)(params, x, key)
            newp, news = self.opt.update(i, grads, params, opt_state)
            return loss, aux, newp, news

        self._train_step = jax.jit(step)

    def train_batch(self, key: jax.Array, x: jax.Array) -> float:
        loss, aux, self.params, self.opt_state = self._train_step(
            self.params, self.opt_state, x,
            key, jnp.asarray(self._step, jnp.int32))
        self._step += 1
        return float(loss)

    def reconstruct(self, key: jax.Array, x: jax.Array) -> jnp.ndarray:
        mu, logvar = encode(self.params, x)
        return jax.nn.sigmoid(decode(self.params, mu))

    def sample(self, key: jax.Array, n: int) -> jnp.ndarray:
        z = jax.random.normal(key, (n, self.cfg.z_dim), jnp.float32)
        return jax.nn.sigmoid(decode(self.params, z))
