"""Text / sequence benchmark models (reference: benchmark/paddle/rnn/rnn.py
LSTM text classification; v1_api_demo/quick_start configs)."""

from paddle_tpu import activation, data_type, layer, networks, pooling


def lstm_text_classification(words, hidden_dim=256, class_num=2,
                             emb_dim=128, stacked_num=1):
    """Embedding -> (stacked) LSTM -> max-pool -> softmax
    (reference: benchmark/paddle/rnn/rnn.py)."""
    emb = layer.embedding(words, emb_dim, name="t_emb")
    tmp = emb
    for i in range(stacked_num):
        tmp = networks.simple_lstm(tmp, hidden_dim, name=f"t_lstm{i}")
    pooled = layer.pool(tmp, pooling_type=pooling.Max(), name="t_pool")
    return layer.fc(pooled, class_num, act=activation.Softmax(), name="t_out")


def text_conv_net(words, hidden_dim=128, class_num=2, emb_dim=128,
                  context_len=3):
    """Text CNN (reference: v1_api_demo/quick_start trainer_config.cnn.py)."""
    emb = layer.embedding(words, emb_dim, name="tc_emb")
    conv = networks.sequence_conv_pool(emb, context_len=context_len,
                                       hidden_size=hidden_dim,
                                       name="tc_conv")
    return layer.fc(conv, class_num, act=activation.Softmax(), name="tc_out")


def stacked_lstm_tagger(words, tag_num, vocab_size=None, emb_dim=64,
                        hidden_dim=128, depth=2):
    """Bidirectional stacked LSTM sequence tagger emitting per-token softmax
    (reference: v1_api_demo/sequence_tagging rnn_crf.py topology minus CRF;
    CRF variant lives with the CRF layer)."""
    emb = layer.embedding(words, emb_dim, name="tag_emb")
    fwd = networks.simple_lstm(emb, hidden_dim, name="tag_l0f")
    bwd = networks.simple_lstm(emb, hidden_dim, reverse=True, name="tag_l0b")
    tmp = layer.concat([fwd, bwd], name="tag_cat0")
    for i in range(1, depth):
        f = networks.simple_lstm(tmp, hidden_dim, name=f"tag_l{i}f")
        b = networks.simple_lstm(tmp, hidden_dim, reverse=True,
                                 name=f"tag_l{i}b")
        tmp = layer.concat([f, b], name=f"tag_cat{i}")
    return layer.fc(tmp, tag_num, act=activation.Softmax(), name="tag_out")
