"""GAN (DCGAN-style) — generator/discriminator with alternating training
(reference: v1_api_demo/gan/gan_conf.py + gan_trainer.py — two
GradientMachines trained alternately on uniform noise vs real samples).

TPU-native: both networks are pure functions over parameter pytrees; the
two alternating updates are TWO jitted train steps (the reference swapped
GradientMachines per batch). MLP variant for vector data (gan_conf.py) and
a conv variant for images (gan_conf_image.py) share the same trainer.
"""

import dataclasses
import math
from typing import Tuple

import jax
import jax.numpy as jnp

from paddle_tpu import optimizer as opt_mod
from paddle_tpu.ops import conv as ops_conv


@dataclasses.dataclass(frozen=True)
class GANConfig:
    noise_dim: int = 10
    sample_dim: int = 784
    hidden_dim: int = 256
    conv: bool = False          # conv G/D for images (28x28 assumed)
    lr: float = 2e-4


def init_params(key: jax.Array, cfg: GANConfig):
    ks = jax.random.split(key, 8)
    H, Z, X = cfg.hidden_dim, cfg.noise_dim, cfg.sample_dim

    def dense(k, i, o):
        return jax.random.normal(k, (i, o), jnp.float32) / math.sqrt(i)

    if not cfg.conv:
        gen = {"w1": dense(ks[0], Z, H), "b1": jnp.zeros(H),
               "w2": dense(ks[1], H, H), "b2": jnp.zeros(H),
               "w3": dense(ks[2], H, X), "b3": jnp.zeros(X)}
        disc = {"w1": dense(ks[3], X, H), "b1": jnp.zeros(H),
                "w2": dense(ks[4], H, H), "b2": jnp.zeros(H),
                "w3": dense(ks[5], H, 1), "b3": jnp.zeros(1)}
        return {"gen": gen, "disc": disc}
    # conv variant: G projects noise to 7x7x32 then 2x transposed convs;
    # D mirrors with strided convs (gan_conf_image.py shape schedule)
    gen = {"proj": dense(ks[0], Z, 7 * 7 * 32),
           "b0": jnp.zeros(7 * 7 * 32),
           "k1": jax.random.normal(ks[1], (4, 4, 32, 16)) * 0.05,
           "k2": jax.random.normal(ks[2], (4, 4, 16, 1)) * 0.05}
    disc = {"k1": jax.random.normal(ks[3], (4, 4, 1, 16)) * 0.05,
            "k2": jax.random.normal(ks[4], (4, 4, 16, 32)) * 0.05,
            "w": dense(ks[5], 7 * 7 * 32, 1), "b": jnp.zeros(1)}
    return {"gen": gen, "disc": disc}


def generator(params, z, cfg: GANConfig):
    g = params["gen"]
    if not cfg.conv:
        h = jax.nn.relu(z @ g["w1"] + g["b1"])
        h = jax.nn.relu(h @ g["w2"] + g["b2"])
        return jnp.tanh(h @ g["w3"] + g["b3"])
    h = jax.nn.relu(z @ g["proj"] + g["b0"]).reshape(-1, 7, 7, 32)
    h = jax.nn.relu(ops_conv.conv2d_transpose(h, g["k1"], stride=2))
    x = jnp.tanh(ops_conv.conv2d_transpose(h, g["k2"], stride=2))
    return x.reshape(x.shape[0], -1).astype(jnp.float32)


def discriminator(params, x, cfg: GANConfig):
    d = params["disc"]
    if not cfg.conv:
        h = jax.nn.leaky_relu(x @ d["w1"] + d["b1"], 0.2)
        h = jax.nn.leaky_relu(h @ d["w2"] + d["b2"], 0.2)
        return (h @ d["w3"] + d["b3"])[:, 0]
    img = x.reshape(-1, 28, 28, 1)
    h = jax.nn.leaky_relu(
        ops_conv.conv2d(img, d["k1"], stride=2).astype(jnp.float32), 0.2)
    h = jax.nn.leaky_relu(
        ops_conv.conv2d(h, d["k2"], stride=2).astype(jnp.float32), 0.2)
    return (h.reshape(h.shape[0], -1) @ d["w"] + d["b"])[:, 0]


def _bce_logits(logits, target):
    # -t*log σ(l) - (1-t)*log(1-σ(l)) in the stable softplus form
    return jnp.mean(jax.nn.softplus(logits) - target * logits)


class GANTrainer:
    """Alternating D/G updates as two jitted steps (the gan_trainer.py
    loop: train D on real+fake, then G through a frozen D)."""

    def __init__(self, cfg: GANConfig, key: jax.Array):
        self.cfg = cfg
        self.params = init_params(key, cfg)
        self.d_opt = opt_mod.Adam(learning_rate=cfg.lr, beta1=0.5).bind([])
        self.g_opt = opt_mod.Adam(learning_rate=cfg.lr, beta1=0.5).bind([])
        self.d_state = self.d_opt.init_state(self.params["disc"])
        self.g_state = self.g_opt.init_state(self.params["gen"])
        self._step = 0
        self._d_step = jax.jit(self._make_d_step())
        self._g_step = jax.jit(self._make_g_step())

    def _make_d_step(self):
        cfg, opt = self.cfg, self.d_opt

        def step(params, d_state, real, z, i):
            def loss(dp):
                p = {"gen": params["gen"], "disc": dp}
                fake = generator(p, z, cfg)
                l_real = _bce_logits(discriminator(p, real, cfg), 1.0)
                l_fake = _bce_logits(
                    discriminator(p, jax.lax.stop_gradient(fake), cfg), 0.0)
                return l_real + l_fake
            lval, grads = jax.value_and_grad(loss)(params["disc"])
            new_d, new_s = opt.update(i, grads, params["disc"], d_state)
            return lval, {"gen": params["gen"], "disc": new_d}, new_s
        return step

    def _make_g_step(self):
        cfg, opt = self.cfg, self.g_opt

        def step(params, g_state, z, i):
            def loss(gp):
                p = {"gen": gp, "disc": params["disc"]}
                fake = generator(p, z, cfg)
                # non-saturating G loss: fool D into predicting real
                return _bce_logits(discriminator(p, fake, cfg), 1.0)
            lval, grads = jax.value_and_grad(loss)(params["gen"])
            new_g, new_s = opt.update(i, grads, params["gen"], g_state)
            return lval, {"gen": new_g, "disc": params["disc"]}, new_s
        return step

    def train_batch(self, key: jax.Array, real: jax.Array
                    ) -> Tuple[float, float]:
        """One D step + one G step; returns (d_loss, g_loss)."""
        kd, kg = jax.random.split(key)
        n = real.shape[0]
        i = jnp.asarray(self._step, jnp.int32)
        z = jax.random.uniform(kd, (n, self.cfg.noise_dim), jnp.float32,
                               -1.0, 1.0)
        d_loss, self.params, self.d_state = self._d_step(
            self.params, self.d_state, real, z, i)
        z2 = jax.random.uniform(kg, (n, self.cfg.noise_dim), jnp.float32,
                                -1.0, 1.0)
        g_loss, self.params, self.g_state = self._g_step(
            self.params, self.g_state, z2, i)
        self._step += 1
        return float(d_loss), float(g_loss)

    def sample(self, key: jax.Array, n: int) -> jnp.ndarray:
        z = jax.random.uniform(key, (n, self.cfg.noise_dim), jnp.float32,
                               -1.0, 1.0)
        return generator(self.params, z, self.cfg)
