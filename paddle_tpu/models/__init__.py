"""Model zoo (reference: benchmark/paddle/image/{alexnet,googlenet,vgg,
resnet,smallnet_mnist_cifar}.py, v1_api_demo/ configs)."""

from paddle_tpu.models import alexnet
from paddle_tpu.models import ctr
from paddle_tpu.models import googlenet
from paddle_tpu.models import resnet
from paddle_tpu.models import smallnet
from paddle_tpu.models import seq2seq
from paddle_tpu.models import text
from paddle_tpu.models import vgg
from paddle_tpu.models import gan
from paddle_tpu.models import vae
