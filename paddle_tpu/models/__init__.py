"""Model zoo (reference: benchmark/paddle/image/{alexnet,googlenet,vgg,
resnet,smallnet_mnist_cifar}.py, v1_api_demo/ configs)."""
