"""Attention seq2seq (the seqToseq encoder-decoder family).

Reference: the seqToseq network used by demo/seqToseq + machine-translation
book test (gru_encoder_decoder with simple_attention; beam-search generation
via RecurrentGradientMachine / SWIG SequenceGenerator, api/PaddleAPI.h:1025).

Builds both graphs from one set of shared parameter names:
- ``seq2seq_train``: teacher-forced training cost via recurrent_group
- ``seq2seq_generate``: beam-search generation reusing the same parameters
"""

from typing import Optional

import paddle_tpu.data_type as data_type
from paddle_tpu import layer, networks


def _encoder(src_word_id, src_dict_dim: int, word_vec_dim: int,
             encoder_size: int):
    src_embedding = layer.embedding(
        src_word_id, size=word_vec_dim,
        param_attr=layer.ParamAttr(name="_source_language_embedding"))
    src_forward = networks.simple_gru(src_embedding, size=encoder_size,
                                      name="src_fwd_gru")
    src_backward = networks.simple_gru(src_embedding, size=encoder_size,
                                       reverse=True, name="src_bwd_gru")
    encoded_vector = layer.concat([src_forward, src_backward])
    with_proj = layer.fc(encoded_vector, size=encoder_size, act="linear",
                         bias_attr=False, name="encoded_proj",
                         param_attr=layer.ParamAttr(name="_encoded_proj.w"))
    return encoded_vector, with_proj, src_backward


def seq2seq_train(src_dict_dim: int, trg_dict_dim: int,
                  word_vec_dim: int = 32, encoder_size: int = 32,
                  decoder_size: int = 32):
    """Teacher-forced training graph → cost layer."""
    src = layer.data("source_language_word",
                     data_type.integer_value_sequence(src_dict_dim))
    trg = layer.data("target_language_word",
                     data_type.integer_value_sequence(trg_dict_dim))
    lbl = layer.data("target_language_next_word",
                     data_type.integer_value_sequence(trg_dict_dim))

    encoded_vector, encoded_proj, src_backward = _encoder(
        src, src_dict_dim, word_vec_dim, encoder_size)
    back_first = layer.first_seq(src_backward, name="enc_last")
    decoder_boot = layer.fc(back_first, size=decoder_size, act="tanh",
                            name="decoder_boot",
                            param_attr=layer.ParamAttr(name="_decoder_boot.w"))

    trg_embedding = layer.embedding(
        trg, size=word_vec_dim,
        param_attr=layer.ParamAttr(name="_target_language_embedding"))

    def step(enc, enc_proj, cur_word):
        gru = networks.gru_decoder_with_attention(
            enc, enc_proj, cur_word, decoder_size, decoder_boot,
            name="decoder_gru")
        return layer.fc(gru, size=trg_dict_dim, act="softmax",
                        name="decoder_out",
                        param_attr=layer.ParamAttr(name="_decoder_out.w"))

    decoded = layer.recurrent_group(
        step,
        input=[layer.StaticInput(encoded_vector, is_seq=True),
               layer.StaticInput(encoded_proj, is_seq=True),
               trg_embedding],
        name="decoder_group")
    return layer.classification_cost(decoded, lbl, name="seq2seq_cost")


def seq2seq_generate(src_dict_dim: int, trg_dict_dim: int,
                     word_vec_dim: int = 32, encoder_size: int = 32,
                     decoder_size: int = 32, beam_size: int = 3,
                     max_length: int = 30, bos_id: int = 0, eos_id: int = 1):
    """Beam-search generation graph sharing the training parameters."""
    src = layer.data("source_language_word",
                     data_type.integer_value_sequence(src_dict_dim))
    encoded_vector, encoded_proj, src_backward = _encoder(
        src, src_dict_dim, word_vec_dim, encoder_size)
    back_first = layer.first_seq(src_backward, name="enc_last")
    decoder_boot = layer.fc(back_first, size=decoder_size, act="tanh",
                            name="decoder_boot",
                            param_attr=layer.ParamAttr(name="_decoder_boot.w"))

    def step(enc, enc_proj, cur_word):
        gru = networks.gru_decoder_with_attention(
            enc, enc_proj, cur_word, decoder_size, decoder_boot,
            name="decoder_gru")
        return layer.fc(gru, size=trg_dict_dim, act="softmax",
                        name="decoder_out",
                        param_attr=layer.ParamAttr(name="_decoder_out.w"))

    return layer.beam_search(
        step,
        input=[layer.StaticInput(encoded_vector, is_seq=True),
               layer.StaticInput(encoded_proj, is_seq=True),
               layer.GeneratedInput(
                   size=trg_dict_dim,
                   embedding_name="_target_language_embedding",
                   embedding_size=word_vec_dim)],
        bos_id=bos_id, eos_id=eos_id, beam_size=beam_size,
        max_length=max_length, name="generated_word")
