"""Decoder-only transformer LM — the long-context / large-scale flagship.

The reference predates transformers; this is the modern capability filling
the "scale sequence length / scale out" slot (SURVEY.md §2.3, §5): causal LM
with ring-attention context parallelism over the ``seq`` mesh axis, tensor
parallelism over ``model`` (heads + MLP), data parallelism over ``data``,
all as one jit-compiled GSPMD program.

Functional design (not the v1 layer DSL): parameters are a pytree with
blocks stacked on a leading axis and the layer loop is a ``lax.scan`` —
one compiled block body regardless of depth, weights ride the MXU in bf16.
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import place
from paddle_tpu.ops import loss as ops_loss
from paddle_tpu.ops import norm as ops_norm
from paddle_tpu.parallel import ring


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int
    d_model: int = 512
    n_heads: int = 8
    n_kv_heads: int = 0                # 0 = MHA; fewer = grouped-query
                                       # attention (smaller KV cache)
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 2048
    dtype: object = jnp.bfloat16
    dropout: float = 0.0               # residual/embedding dropout rate
    use_rope: bool = False             # rotary q/k embeddings instead of
                                       # learned absolute positions
    rope_theta: float = 10000.0
    use_ring_attention: bool = False   # shard_map CP over the seq axis
    cp_mode: str = "ring"              # "ring" (K/V rotate over ICI) or
                                       # "alltoall" (Ulysses head-scatter;
                                       # needs seq-axis | n_heads)
    use_flash_attention: bool = False  # Pallas fused attention (TPU)
    remat: str = "none"                # "none" | "bf16" | "q8": layer-
                                       # granular recompute; autodiff
                                       # saves only one (quantized) copy
                                       # of each block's input instead of
                                       # every intermediate — the
                                       # long-context capacity lever
                                       # (ops/q8.q8_remat)
    moe_experts: int = 0               # >0: the FFN is a top-k MoE over
                                       # this many experts (parallel/moe)
                                       # sharded on the ``expert`` axis;
                                       # 0 = dense mlp
    moe_top_k: int = 1                 # 1 = Switch; 2 = GShard top-2
    moe_capacity_factor: float = 1.25
    moe_aux_weight: float = 0.01       # load-balance loss weight (added
                                       # to lm_loss per layer)

    def __post_init__(self):
        if self.cp_mode not in ("ring", "alltoall"):
            raise ValueError(
                f"cp_mode must be 'ring' or 'alltoall', got "
                f"{self.cp_mode!r}")
        if self.remat not in ("none", "bf16", "q8"):
            raise ValueError(
                f"remat must be 'none', 'bf16' or 'q8', got "
                f"{self.remat!r}")

    def moe_cfg(self):
        """The parallel/moe.MoEConfig this FFN runs under."""
        from paddle_tpu.parallel import moe
        return moe.MoEConfig(
            d_model=self.d_model, d_ff=self.d_ff,
            num_experts=self.moe_experts,
            capacity_factor=self.moe_capacity_factor,
            aux_loss_weight=self.moe_aux_weight, top_k=self.moe_top_k)

    @property
    def head_dim(self):
        return self.d_model // self.n_heads

    @property
    def kv_heads(self):
        """Effective number of key/value heads (GQA groups q heads over
        fewer kv heads; 0 means standard multi-head attention)."""
        h = self.n_kv_heads or self.n_heads
        if h <= 0 or self.n_heads % h:
            raise ValueError(f"n_heads={self.n_heads} must be a multiple "
                             f"of n_kv_heads={h}")
        return h


def init_params(key: jax.Array, cfg: TransformerConfig):
    """Parameter pytree; block weights stacked on axis 0 (scan layout)."""
    k = jax.random.split(key, 8)
    D, F, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab
    kvd = cfg.kv_heads * cfg.head_dim     # == D for MHA; smaller for GQA
    s = 1.0 / math.sqrt(D)

    def nrm(kk, shape, scale):
        return (jax.random.normal(kk, shape, jnp.float32) * scale).astype(
            jnp.float32)

    if cfg.moe_experts:
        E = cfg.moe_experts
        ffn = {
            "gate": nrm(k[4], (L, D, E), s),
            "moe_w_in": nrm(k[5], (L, E, D, F), s),
            "moe_w_out": nrm(k[6], (L, E, F, D), 1.0 / math.sqrt(F) /
                             math.sqrt(2 * L)),
        }
    else:
        ffn = {
            "mlp_in": nrm(k[4], (L, D, F), s),
            "mlp_out": nrm(k[5], (L, F, D), 1.0 / math.sqrt(F) /
                           math.sqrt(2 * L)),
        }
    return {
        "embed": nrm(k[0], (V, D), 1.0 / math.sqrt(D)),
        # rope computes positions analytically; keep a 1-row stub so the
        # pytree structure (and shardings) stay config-independent
        "pos": (nrm(k[1], (cfg.max_len, D), 0.02) if not cfg.use_rope
                else jnp.zeros((1, D), jnp.float32)),
        "blocks": {
            "ln1": jnp.ones((L, D), jnp.float32),
            "ln1_b": jnp.zeros((L, D), jnp.float32),
            "qkv": nrm(k[2], (L, D, D + 2 * kvd), s),
            "attn_out": nrm(k[3], (L, D, D), s / math.sqrt(2 * L)),
            "ln2": jnp.ones((L, D), jnp.float32),
            "ln2_b": jnp.zeros((L, D), jnp.float32),
            **ffn,
        },
        "ln_f": jnp.ones((D,), jnp.float32),
        "ln_f_b": jnp.zeros((D,), jnp.float32),
    }


def param_shardings(cfg: TransformerConfig, mesh: Mesh):
    """TP layout (scaling-book): qkv/mlp_in column-parallel, attn_out/mlp_out
    row-parallel over ``model``; embeddings vocab-sharded over ``model``;
    MoE experts sharded over ``expert``. An axis the mesh doesn't carry
    degrades to replication, so the same layout serves DP-only,
    DPxTP and DPxEP meshes."""
    M = place.AXIS_MODEL if place.AXIS_MODEL in mesh.axis_names else None

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    if cfg.moe_experts:
        # FFN is expert-parallel instead of tensor-parallel: experts
        # shard over the ``expert`` axis, gate replicated
        E = (place.AXIS_EXPERT if place.AXIS_EXPERT in mesh.axis_names
             else None)
        ffn = {"gate": ns(),
               "moe_w_in": ns(None, E, None, None),
               "moe_w_out": ns(None, E, None, None)}
    else:
        ffn = {"mlp_in": ns(None, None, M),
               "mlp_out": ns(None, M, None)}
    return {
        "embed": ns(M, None),
        "pos": ns(),
        "blocks": {
            "ln1": ns(), "ln1_b": ns(), "ln2": ns(), "ln2_b": ns(),
            "qkv": ns(None, None, M),
            "attn_out": ns(None, M, None),
            **ffn,
        },
        "ln_f": ns(), "ln_f_b": ns(),
    }


def _layer_norm(x, g, b):
    return ops_norm.layer_norm(x, g, b).astype(x.dtype)


def _rope_tables(positions, head_dim, theta):
    """cos/sin tables [T, Dh/2] for GLOBAL positions — computed once per
    forward (outside the layer scan) and shared by every layer's q and k."""
    if head_dim % 2:
        raise ValueError(f"RoPE requires an even head_dim, got {head_dim}")
    half = head_dim // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    ang = positions.astype(jnp.float32)[:, None] * freqs[None, :]
    return jnp.cos(ang), jnp.sin(ang)


def _rope_rows(x, tables):
    """Rotary embedding for one token PER ROW: x [B, H, Dh] with per-row
    cos/sin tables [B, Dh/2] (each batch row sits at its own position —
    the continuous-batching decode layout). Elementwise math is identical
    to ``_rope``'s, so a row at position p rotates bitwise the same as a
    lockstep step at scalar position p."""
    cos, sin = tables
    cos = cos[:, None, :]
    sin = sin[:, None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
        axis=-1).astype(x.dtype)


def _rope(x, tables):
    """Rotary position embedding over the head dim of [..., T, H, Dh]
    (pairing halves: (x1, x2) -> (x1·cos − x2·sin, x1·sin + x2·cos)).
    Positions entered the tables as GLOBAL indices, so the rotation is
    correct under ring context parallelism too — it applies to q/k before
    any attention engine (full / flash / ring), no kernel change."""
    cos, sin = tables
    cos = cos[None, :, None, :]
    sin = sin[None, :, None, :]
    half = x.shape[-1] // 2
    x1 = x[..., :half].astype(jnp.float32)
    x2 = x[..., half:].astype(jnp.float32)
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos],
        axis=-1).astype(x.dtype)


def _blocks_quantized(params) -> bool:
    """True when the big matmul weights ride as {"q8","scale"} nodes
    (``io/lm_serving.quantize_lm_params``) — the int8-weight serving
    path the decode steps handle natively (dequant INSIDE the layer
    scan, so weights are read from HBM at 1 byte/elt per token)."""
    from paddle_tpu.ops import q8 as ops_q8
    return any(ops_q8.is_quantized_weight(n) for n in
               jax.tree_util.tree_leaves(
                   params["blocks"], is_leaf=ops_q8.is_quantized_weight))


def _live_layer_weights(w, li):
    """Dequantize ONE layer's {"q8","scale"} weights inside the scan
    body, with the anti-hoist defenses proven in ``generate``: the
    weights arrive as scanned xs (loop-VARIANT by data dependence — a
    dynamic slice of the int8 stack per iteration), sit behind an
    optimization barrier, and the scales fold in a float zero derived
    from the layer counter. XLA therefore cannot rematerialize the full
    fp32 weight stack outside the loop; each layer's dequant multiply
    fuses into its matmul operand reads (asserted on the optimized HLO
    in tests/test_pallas_decode.py)."""
    from paddle_tpu.ops import q8 as ops_q8
    w = jax.lax.optimization_barrier(w)
    eps = li.astype(jnp.float32) * 0.0

    def leaf(n):
        if ops_q8.is_quantized_weight(n):
            return ops_q8.dequantize_weight(
                {"q8": n["q8"], "scale": n["scale"] + eps})
        return n

    return {k: leaf(v) for k, v in w.items()}


def _embed_rows(params, tokens, cfg):
    """Token-embedding gather, q8-aware: quantized embeddings gather
    int8 rows and dequantize per row (the [B, 1] scale broadcast fuses
    into the gather's consumer) — no fp32 [V, D] table materializes."""
    from paddle_tpu.ops import q8 as ops_q8
    emb = params["embed"]
    if ops_q8.is_quantized_weight(emb):
        return (jnp.take(emb["q8"], tokens, axis=0).astype(jnp.float32)
                * jnp.take(emb["scale"], tokens, axis=0)).astype(cfg.dtype)
    return jnp.take(emb, tokens, axis=0).astype(cfg.dtype)


def _vocab_logits(x, params):
    """Final vocab projection [B, D] -> [B, V], q8-aware: the dequant
    multiply is elementwise on the einsum operand, which XLA fuses into
    the dot's weight read (1-byte weight traffic on TPU; CPU may
    materialize — the logits head is one matrix, amortized against the
    L-layer stack the scan protects)."""
    from paddle_tpu.ops import q8 as ops_q8
    emb = params["embed"]
    emb32 = (ops_q8.dequantize_weight(emb)
             if ops_q8.is_quantized_weight(emb)
             else emb.astype(jnp.float32))
    return jnp.einsum("bd,vd->bv", x.astype(jnp.float32), emb32)


def forward(params, tokens: jax.Array, cfg: TransformerConfig, *,
            mesh: Optional[Mesh] = None,
            lengths: Optional[jax.Array] = None,
            return_kv: bool = False, return_aux: bool = False,
            dropout_key: Optional[jax.Array] = None):
    """tokens [B, T] int32 → logits [B, T, vocab] (float32).

    With ``cfg.use_ring_attention`` and a mesh carrying a >1 ``seq`` axis,
    attention runs as ring CP; activations get seq-sharding constraints so
    XLA keeps the [B, T, D] tensors distributed end-to-end.
    ``return_kv=True`` additionally returns the per-layer (k, v)
    projections stacked [L, B, T, kv_heads, Dh] (kv_heads < n_heads
    under GQA) — the prefill path of the KV-cache decoder shares this
    exact block so the two can't drift.
    ``dropout_key`` enables inverted dropout at rate ``cfg.dropout``
    (embedding + both residual branches per block); omit it — as eval
    and serving paths do — for deterministic inference.
    ``return_aux=True`` additionally returns the summed MoE
    load-balance loss (zero for dense configs) — lm_loss adds it.
    """
    return _forward_impl(params, tokens, cfg, mesh, lengths, return_kv,
                         head="all", dropout_key=dropout_key,
                         return_aux=return_aux)


def _forward_impl(params, tokens, cfg, mesh, lengths, return_kv, head,
                  dropout_key=None, return_aux=False, gather_pos=None):
    B, T = tokens.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    if not 0.0 <= cfg.dropout < 1.0:
        raise ValueError(f"cfg.dropout must be in [0, 1), got {cfg.dropout}")
    rate = cfg.dropout if dropout_key is not None else 0.0

    def drop(h, key):
        if rate <= 0.0:
            return h
        keep = jax.random.bernoulli(key, 1.0 - rate, h.shape)
        return jnp.where(keep, h / (1.0 - rate), 0).astype(h.dtype)

    if rate > 0.0:
        emb_key, blk_key = jax.random.split(dropout_key)
    else:
        emb_key = blk_key = jax.random.PRNGKey(0)   # unused (rate is static)
    layer_keys = jax.random.split(blk_key, cfg.n_layers)
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    if not cfg.use_rope:
        x = x + params["pos"][:T].astype(cfg.dtype)[None]
    if rate > 0.0:
        x = drop(x, emb_key)
    rope_tabs = _rope_tables(jnp.arange(T, dtype=jnp.int32), Dh,
                             cfg.rope_theta) if cfg.use_rope else None

    seq_sharded = (mesh is not None and place.AXIS_SEQ in mesh.axis_names
                   and mesh.shape[place.AXIS_SEQ] > 1)

    def constrain(h):
        if mesh is None:
            return h
        spec = P(place.AXIS_DATA,
                 place.AXIS_SEQ if seq_sharded else None, None)
        return jax.lax.with_sharding_constraint(
            h, NamedSharding(mesh, spec))

    x = constrain(x)

    Hkv = cfg.kv_heads
    kvd = Hkv * Dh

    def block(x, scanned):
        w, lkey = scanned
        k1, k2 = jax.random.split(lkey)
        h = _layer_norm(x, w["ln1"], w["ln1_b"])
        qkv = jnp.einsum("btd,de->bte", h, w["qkv"].astype(h.dtype))
        q, k, v = jnp.split(qkv, [H * Dh, H * Dh + kvd], axis=-1)
        q = q.reshape(B, T, H, Dh)
        k = k.reshape(B, T, Hkv, Dh)
        v = v.reshape(B, T, Hkv, Dh)
        if cfg.use_rope:
            q = _rope(q, rope_tabs)
            k = _rope(k, rope_tabs)
        kv = (k.astype(cfg.dtype), v.astype(cfg.dtype)) \
            if return_kv else None
        # GQA: every engine takes Hkv-head k/v directly — the ring path
        # rotates the small tensors over ICI and broadcasts to the q-head
        # layout locally per step; the jnp engines group in the einsum
        if seq_sharded and cfg.use_ring_attention:
            if cfg.cp_mode == "alltoall":
                # Ulysses layout: two all-to-alls reshuffle seq<->heads,
                # attention runs fully local per head group
                attn = ring.alltoall_attention_spmd(
                    q, k, v, mesh, causal=True, lengths=lengths,
                    use_flash=cfg.use_flash_attention and lengths is None)
            else:
                # flash blocks inside the ring when the batch is packed —
                # O(T/P·D) per chip with no score tensor even per ring
                # step
                attn = ring.ring_attention_spmd(
                    q, k, v, mesh, causal=True, lengths=lengths,
                    use_flash=cfg.use_flash_attention and lengths is None)
        elif cfg.use_flash_attention and lengths is None:
            from paddle_tpu.ops.pallas import flash_attention
            attn = flash_attention(q, k, v, causal=True)
        else:
            attn = ring.full_attention(q, k, v, causal=True, lengths=lengths)
        attn = attn.reshape(B, T, cfg.d_model)
        x = x + drop(jnp.einsum("btd,de->bte", attn,
                                w["attn_out"].astype(attn.dtype)), k1)
        x = constrain(x)
        h2 = _layer_norm(x, w["ln2"], w["ln2_b"])
        if cfg.moe_experts:
            from paddle_tpu.parallel import moe
            out, aux = moe.moe_ffn(
                {"gate": w["gate"], "w_in": w["moe_w_in"],
                 "w_out": w["moe_w_out"]},
                h2.reshape(B * T, cfg.d_model), cfg.moe_cfg(), mesh=mesh)
            x = x + drop(out.reshape(B, T, cfg.d_model).astype(x.dtype),
                         k2)
            return constrain(x), (kv, aux)
        ff = jnp.einsum("btd,df->btf", h2, w["mlp_in"].astype(h2.dtype))
        ff = jax.nn.gelu(ff)
        x = x + drop(jnp.einsum("btf,fd->btd", ff,
                                w["mlp_out"].astype(ff.dtype)), k2)
        return constrain(x), (kv, jnp.zeros((), jnp.float32))

    if cfg.remat != "none" and not return_kv:
        # layer-granular recompute: backward rebuilds each block from a
        # (quantized) copy of its input; the scan then saves one stash
        # per layer instead of every intermediate (ops/q8.q8_remat).
        # KV-returning calls are serving-only (no backward) — skip there.
        from paddle_tpu.ops import q8 as ops_q8
        inner = ops_q8.q8_remat(
            block, stash="int8" if cfg.remat == "q8" else "bf16")
        x, (kvs, auxs) = jax.lax.scan(inner, x,
                                      (params["blocks"], layer_keys))
    else:
        x, (kvs, auxs) = jax.lax.scan(block, x,
                                      (params["blocks"], layer_keys))
    aux_total = jnp.sum(auxs)
    if head == "last":
        # serving prefill: only the final position feeds the vocab head —
        # skips the O(T·vocab) logits tensor a full head would materialize
        x = x[:, -1:]
    elif head == "gather":
        # slot prefill: the prompt is right-padded to a bucket length, so
        # the position feeding the vocab head is the TRACED index
        # ``gather_pos`` [B] (the true last prompt token), not -1. Same
        # O(vocab) head as "last"; causality already isolates the real
        # prefix from the padding, so no attention mask is needed and the
        # gathered activations are bitwise the unpadded forward's.
        x = jnp.take_along_axis(x, gather_pos.reshape(-1, 1, 1), axis=1)
    x = _layer_norm(x, params["ln_f"], params["ln_f_b"])
    logits = jnp.einsum("btd,vd->btv", x.astype(jnp.float32),
                        params["embed"].astype(jnp.float32))
    if return_kv and return_aux:
        return logits, kvs, aux_total
    if return_kv:
        return logits, kvs
    if return_aux:
        return logits, aux_total
    return logits


def lm_loss(params, tokens, targets, cfg: TransformerConfig, *,
            mesh: Optional[Mesh] = None,
            lengths: Optional[jax.Array] = None,
            dropout_key: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross-entropy over valid positions (+ the MoE
    load-balance aux loss for moe_experts configs)."""
    logits, aux = forward(params, tokens, cfg, mesh=mesh, lengths=lengths,
                          dropout_key=dropout_key, return_aux=True)
    tok_ce = ops_loss.softmax_cross_entropy(logits, targets)
    if lengths is not None:
        mask = (jnp.arange(tokens.shape[1])[None, :] <
                lengths[:, None]).astype(jnp.float32)
    else:
        mask = jnp.ones_like(tok_ce)
    ce = jnp.sum(tok_ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return ce + aux


def init_cache(cfg: TransformerConfig, batch: int, max_len: int):
    """Per-layer KV cache for incremental decoding:
    [L, B, max_len, kv_heads, Dh] (kv_heads < n_heads under GQA)
    (the serving-side analog of the reference's recurrent generation
    machinery, trainer/tests/test_recurrent_machine_generation.cpp slot)."""
    shape = (cfg.n_layers, batch, max_len, cfg.kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, cfg.dtype), "v": jnp.zeros(shape, cfg.dtype)}


# The pool's array layout generation — stamped into v4/v5 artifacts
# (io/lm_serving) so a loader never schedules programs compiled against
# a different layout, and the key prefix of the Pallas MEASURED_*
# tuning tables. "head_major" is [L, Hkv, M, Dh]: the kv-head axis
# leads so every Pallas grid program's pool block is a Mosaic-legal
# (1, block_size, Dh) slab (the pre-relayout "slot_major"
# [L, M, Hkv, Dh] forced per-head column blocks (M, 1, Dh), which the
# TPU last-two-dims tiling rule rejects). ONE definition — the kernels
# own it (ops/pallas/decode.py); this re-export is what the artifact
# stamping and the engine read, so a future layout bump cannot fence
# artifacts and key the tuning tables with different strings.
from paddle_tpu.ops.pallas.decode import POOL_LAYOUT  # noqa: E402


def init_block_pool(cfg: TransformerConfig, num_blocks: int,
                    block_size: int, kv_dtype: Optional[str] = None):
    """Paged KV pool for the block-table decode engine, HEAD-MAJOR:
    [L, kv_heads, num_blocks * block_size, Dh] per k/v — the standard
    TPU paged-KV layout (kv-head leading). Block ``i`` owns the aligned
    span ``[i*block_size, (i+1)*block_size)`` of the flat position axis
    (now the SECOND-to-last axis); per-slot page tables
    (``serving/blocks.BlockPool``) map logical positions onto blocks,
    so HBM is committed per BLOCK actually written instead of
    ``cache_len`` per arena row. Head-major is what makes every Pallas
    serving kernel's pool block a tiling-legal ``(1, block_size, Dh)``
    slab placeable by scalar-prefetched page indexing — see
    ``POOL_LAYOUT`` and ops/pallas/decode.py.

    ``kv_dtype`` picks the pool storage width. ``None`` keeps the model
    dtype ({"k","v"} only). ``"int8"`` stores k/v as symmetric int8
    with one fp32 scale per (layer, head, position) in
    ``k_scale``/``v_scale`` tables [L, kv_heads, M] that ride beside
    the pool — the page table indexes values and scales alike, so
    scales travel with their block under any paging. ``"int4"`` packs
    two nibbles per byte ([..., Dh//2] storage, same scale layout).
    Scales are per pool ROW (write-local): a decode step writing one
    token never rescales a block's resident neighbours, which is what
    keeps hit-replay bitwise and blocks relocatable."""
    M = int(num_blocks) * int(block_size)
    if kv_dtype in (None, "none"):
        shape = (cfg.n_layers, cfg.kv_heads, M, cfg.head_dim)
        return {"k": jnp.zeros(shape, cfg.dtype),
                "v": jnp.zeros(shape, cfg.dtype)}
    from paddle_tpu.ops import q8 as ops_q8
    if kv_dtype not in ops_q8.KV_DTYPES:
        raise ValueError(f"kv_dtype {kv_dtype!r}: one of "
                         f"{(None,) + ops_q8.KV_DTYPES}")
    Dh = cfg.head_dim
    if kv_dtype == "int4":
        if Dh % 2:
            raise ValueError(f"int4 KV packs nibble pairs: head_dim "
                             f"{Dh} must be even")
        Dh = Dh // 2
    shape = (cfg.n_layers, cfg.kv_heads, M, Dh)
    sshape = (cfg.n_layers, cfg.kv_heads, M)
    return {"k": jnp.zeros(shape, jnp.int8),
            "v": jnp.zeros(shape, jnp.int8),
            "k_scale": jnp.zeros(sshape, jnp.float32),
            "v_scale": jnp.zeros(sshape, jnp.float32)}


def pool_kv_dtype(cache, cfg: TransformerConfig) -> str:
    """The KV storage width a pool pytree carries: ``"none"`` (model
    dtype), ``"int8"``, or ``"int4"`` — inferred from the pytree
    structure so the step functions need no extra argument and jit
    re-specializes automatically when the pool layout changes."""
    if "k_scale" not in cache:
        return "none"
    return "int4" if cache["k"].shape[-1] == cfg.head_dim // 2 \
        and cfg.head_dim > 1 else "int8"


def kv_pool_bytes_per_token(cfg: TransformerConfig,
                            kv_dtype: Optional[str] = None) -> int:
    """HBM bytes ONE resident token costs across all layers (k + v +
    scale rows) — the ``engine_kv_bytes_per_token`` gauge and the
    slots-at-equal-HBM arithmetic in ``serving_bench``."""
    Hkv, Dh = cfg.kv_heads, cfg.head_dim
    if kv_dtype in (None, "none"):
        per = 2 * Hkv * Dh * jnp.dtype(cfg.dtype).itemsize
    elif kv_dtype == "int8":
        per = 2 * Hkv * Dh + 2 * Hkv * 4
    elif kv_dtype == "int4":
        per = 2 * Hkv * (Dh // 2) + 2 * Hkv * 4
    else:
        raise ValueError(f"kv_dtype {kv_dtype!r}")
    return cfg.n_layers * per


def kv_rel_l2_budget(cfg: TransformerConfig, kv_dtype: str) -> float:
    """Global rel-L2 budget for decode logits off a quantized pool vs
    the fp32 pool — the PR-5 tolerance-contract recipe. Symmetric
    rounding injects at most ``0.5/qmax`` relative noise per KV element
    (0.5/127 for int8, 0.5/7 for int4); each layer reads quantized K
    (score perturbation, softmax-damped) and quantized V (weighted-sum
    perturbation) — 2L independent noise injections that compound in
    quadrature through the residual stream, so the noise reaching the
    logits is ~``sqrt(2L) * 0.5/qmax``. Budget = 2x that (slack for
    unlucky alignment and the softmax nonlinearity, never enough to
    excuse a wrong-scale bug, which lands at O(1) — measured on the
    test config: int8 ~0.2% vs budget 1.6%, int4 ~4% vs 29%)."""
    from paddle_tpu.ops import q8 as ops_q8
    half_step = 0.5 / ops_q8.KV_QMAX[kv_dtype]
    return min(0.5, 2.0 * math.sqrt(2 * cfg.n_layers) * half_step)


def prefill(params, tokens: jax.Array, cfg: TransformerConfig,
            cache_len: int, *, mesh: Optional[Mesh] = None):
    """Batched prompt ingestion: the SAME traced block the training path
    runs (flash/ring dispatch included when ``mesh`` is passed) with the
    vocab head applied to the last position only, plus cache padding to
    ``cache_len``. Returns (last-position logits [B, vocab] fp32, cache).
    Packed (equal-length) prompts only — the decode loop's position
    counter is shared across the batch."""
    T = tokens.shape[1]
    logits, (kc, vc) = _forward_impl(params, tokens, cfg, mesh, None,
                                     True, head="last")
    pad = ((0, 0), (0, 0), (0, cache_len - T), (0, 0), (0, 0))
    return logits[:, 0], {"k": jnp.pad(kc, pad), "v": jnp.pad(vc, pad)}


def decode_step(params, cache, tokens: jax.Array, pos: jax.Array,
                cfg: TransformerConfig):
    """One incremental step: tokens [B] at position ``pos`` (scalar int32)
    → (logits [B, vocab] fp32, updated cache). All shapes static; the
    cache updates via dynamic_update_slice so the step compiles once and
    is replayed for every position (lax.scan-friendly)."""
    B = tokens.shape[0]
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    kvd = Hkv * Dh
    max_len = cache["k"].shape[2]
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    if not cfg.use_rope:
        x = x + jax.lax.dynamic_index_in_dim(
            params["pos"], pos, keepdims=False).astype(cfg.dtype)
    rope_tabs = _rope_tables(jnp.asarray(pos, jnp.int32).reshape(1), Dh,
                             cfg.rope_theta) if cfg.use_rope else None

    def block(x, scanned):
        w, kc, vc = scanned                  # kc/vc [B, max_len, Hkv, Dh]
        h = _layer_norm(x, w["ln1"], w["ln1_b"])
        qkv = h @ w["qkv"].astype(h.dtype)   # [B, D + 2*kvd]
        q, k, v = jnp.split(qkv, [H * Dh, H * Dh + kvd], axis=-1)
        if cfg.use_rope:
            q = _rope(q.reshape(B, 1, H, Dh), rope_tabs).reshape(B, H * Dh)
            k = _rope(k.reshape(B, 1, Hkv, Dh), rope_tabs).reshape(B, kvd)
        kc = jax.lax.dynamic_update_slice_in_dim(
            kc, k.reshape(B, 1, Hkv, Dh).astype(kc.dtype), pos, axis=1)
        vc = jax.lax.dynamic_update_slice_in_dim(
            vc, v.reshape(B, 1, Hkv, Dh).astype(vc.dtype), pos, axis=1)
        # grouped attention: q [B, Hkv, G, Dh] against the Hkv-head cache
        g = H // Hkv
        q32 = q.reshape(B, Hkv, g, Dh).astype(jnp.float32)
        s = jnp.einsum("bkgd,btkd->bkgt", q32,
                       kc.astype(jnp.float32)) / math.sqrt(Dh)
        mask = jnp.arange(max_len) <= pos
        s = jnp.where(mask[None, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bkgt,btkd->bkgd", p, vc.astype(jnp.float32))
        attn = attn.reshape(B, cfg.d_model).astype(cfg.dtype)
        x = x + attn @ w["attn_out"].astype(attn.dtype)
        h2 = _layer_norm(x, w["ln2"], w["ln2_b"])
        if cfg.moe_experts:
            import dataclasses as _dc

            from paddle_tpu.parallel import moe
            # decode capacity = full batch (cf = E/k): inference must
            # not drop tokens the way Switch training capacity does
            mc = _dc.replace(cfg.moe_cfg(), capacity_factor=float(
                cfg.moe_experts) / cfg.moe_top_k)
            out, _ = moe.moe_ffn(
                {"gate": w["gate"], "w_in": w["moe_w_in"],
                 "w_out": w["moe_w_out"]}, h2, mc)
            x = x + out.astype(x.dtype)
        else:
            ff = jax.nn.gelu(h2 @ w["mlp_in"].astype(h2.dtype))
            x = x + ff @ w["mlp_out"].astype(ff.dtype)
        return x, (kc, vc)

    x, (kn, vn) = jax.lax.scan(block, x,
                               (params["blocks"], cache["k"], cache["v"]))
    x = _layer_norm(x, params["ln_f"], params["ln_f_b"])
    logits = jnp.einsum("bd,vd->bv", x.astype(jnp.float32),
                        params["embed"].astype(jnp.float32))
    return logits, {"k": kn, "v": vn}


def prefill_into_slot(params, cache, tokens: jax.Array, length: jax.Array,
                      slot: jax.Array, cfg: TransformerConfig, *,
                      mesh: Optional[Mesh] = None):
    """Prefill ONE request into arena row ``slot`` of a shared KV cache.

    tokens [1, Tb] is the prompt right-padded to a bucket length Tb;
    ``length`` (scalar int32, traced) is the true prompt length and
    ``slot`` (scalar int32, traced) the arena row. Returns (logits at the
    last real prompt position [1, vocab] fp32, updated cache). All shapes
    are static, so the engine compiles ONCE per (bucket, arena) pair and
    new requests join mid-flight without retracing.

    Correctness of right-padding without a mask: KV projections are
    per-position, and causal attention means padded positions only feed
    their OWN outputs — the gathered position ``length - 1`` attends to
    real tokens exclusively, so its logits are bitwise the unpadded
    forward's. The padded rows' garbage KV lands at positions
    ``length..Tb-1``, each of which is overwritten by a decode step
    BEFORE any per-slot attention mask (``pos >= position``) can read it.
    Rows other than ``slot`` are untouched (dynamic_update_slice writes a
    1-row slab)."""
    if tokens.shape[0] != 1:
        raise ValueError(f"prefill_into_slot takes one request "
                         f"([1, Tb] tokens), got {tokens.shape}")
    logits, (kc, vc) = _forward_impl(
        params, tokens, cfg, mesh, None, True, head="gather",
        gather_pos=jnp.reshape(length, (1,)) - 1)
    slot = jnp.asarray(slot, jnp.int32)
    zero = jnp.zeros((), jnp.int32)
    idx = (zero, slot, zero, zero, zero)
    return logits[:, 0], {
        "k": jax.lax.dynamic_update_slice(
            cache["k"], kc.astype(cache["k"].dtype), idx),
        "v": jax.lax.dynamic_update_slice(
            cache["v"], vc.astype(cache["v"].dtype), idx)}


def decode_step_slots(params, cache, tokens: jax.Array, pos: jax.Array,
                      active: jax.Array, cfg: TransformerConfig):
    """One incremental step with PER-SLOT positions: tokens [B] int32,
    ``pos`` [B] int32 (each row's write/attend position) and ``active``
    [B] bool → (logits [B, vocab] fp32, updated cache).

    The continuous-batching variant of ``decode_step``: every arena row
    advances independently, so requests of different lengths decode in
    one compiled program. Inactive rows compute (harmlessly) but their
    cache rows are NOT written — admission and recycling can't perturb
    in-flight neighbours. For rows whose pos equals a lockstep call's
    scalar pos, the arithmetic is elementwise identical to
    ``decode_step``'s, so logits match bitwise (tested).

    The block body deliberately mirrors ``decode_step``'s rather than
    sharing it: the lockstep path keeps its cheaper scalar-index
    ``dynamic_update_slice`` (and its exported v1/v2 artifact program),
    while this variant needs per-row where-writes. The bitwise test in
    tests/test_serving_engine.py pins the two against drifting.

    ``params`` may carry int8 weights ({"q8","scale"} nodes from
    ``io/lm_serving.quantize_lm_params``): they ride the layer scan as
    int8 xs and dequantize inside the body (``_live_layer_weights``
    anti-hoist defenses), so serving reads weights at 1 byte/elt."""
    B = tokens.shape[0]
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    kvd = Hkv * Dh
    max_len = cache["k"].shape[2]
    quantized = _blocks_quantized(params)
    pos = jnp.asarray(pos, jnp.int32)
    x = _embed_rows(params, tokens, cfg)
    if not cfg.use_rope:
        x = x + jnp.take(params["pos"], pos, axis=0).astype(cfg.dtype)
    rope_tabs = _rope_tables(pos, Dh, cfg.rope_theta) \
        if cfg.use_rope else None
    # [B, max_len] one-hot write mask: row b writes position pos[b] only
    # when active — a where() against the arena instead of
    # dynamic_update_slice, because each row targets a different index
    write = ((jnp.arange(max_len, dtype=jnp.int32)[None, :]
              == pos[:, None]) & active[:, None])
    attend = (jnp.arange(max_len, dtype=jnp.int32)[None, :]
              <= pos[:, None])                          # [B, max_len]

    def block(x, scanned):
        w, li, kc, vc = scanned              # kc/vc [B, max_len, Hkv, Dh]
        if quantized:
            w = _live_layer_weights(w, li)
        h = _layer_norm(x, w["ln1"], w["ln1_b"])
        qkv = h @ w["qkv"].astype(h.dtype)   # [B, D + 2*kvd]
        q, k, v = jnp.split(qkv, [H * Dh, H * Dh + kvd], axis=-1)
        if cfg.use_rope:
            q = _rope_rows(q.reshape(B, H, Dh), rope_tabs).reshape(
                B, H * Dh)
            k = _rope_rows(k.reshape(B, Hkv, Dh), rope_tabs).reshape(
                B, kvd)
        kc = jnp.where(write[:, :, None, None],
                       k.reshape(B, 1, Hkv, Dh).astype(kc.dtype), kc)
        vc = jnp.where(write[:, :, None, None],
                       v.reshape(B, 1, Hkv, Dh).astype(vc.dtype), vc)
        g = H // Hkv
        q32 = q.reshape(B, Hkv, g, Dh).astype(jnp.float32)
        s = jnp.einsum("bkgd,btkd->bkgt", q32,
                       kc.astype(jnp.float32)) / math.sqrt(Dh)
        s = jnp.where(attend[:, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bkgt,btkd->bkgd", p, vc.astype(jnp.float32))
        attn = attn.reshape(B, cfg.d_model).astype(cfg.dtype)
        x = x + attn @ w["attn_out"].astype(attn.dtype)
        h2 = _layer_norm(x, w["ln2"], w["ln2_b"])
        if cfg.moe_experts:
            import dataclasses as _dc

            from paddle_tpu.parallel import moe
            mc = _dc.replace(cfg.moe_cfg(), capacity_factor=float(
                cfg.moe_experts) / cfg.moe_top_k)
            out, _ = moe.moe_ffn(
                {"gate": w["gate"], "w_in": w["moe_w_in"],
                 "w_out": w["moe_w_out"]}, h2, mc)
            x = x + out.astype(x.dtype)
        else:
            ff = jax.nn.gelu(h2 @ w["mlp_in"].astype(h2.dtype))
            x = x + ff @ w["mlp_out"].astype(ff.dtype)
        return x, (kc, vc)

    li = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    x, (kn, vn) = jax.lax.scan(block, x, (params["blocks"], li,
                                          cache["k"], cache["v"]))
    x = _layer_norm(x, params["ln_f"], params["ln_f_b"])
    logits = _vocab_logits(x, params)
    return logits, {"k": kn, "v": vn}


def decode_step_paged(params, cache, tokens: jax.Array, pos: jax.Array,
                      active: jax.Array, pages: jax.Array,
                      cfg: TransformerConfig, *, block_size: int,
                      pallas: Optional[str] = None):
    """One incremental step over the PAGED block pool: tokens [B] int32,
    ``pos`` [B] int32, ``active`` [B] bool, ``pages`` [B, P] int32 block
    ids → (logits [B, vocab] fp32, updated pool).

    The block-table variant of ``decode_step_slots``: the cache is the
    head-major flat pool ``init_block_pool`` builds ([L, Hkv, M, Dh]
    with M = num_blocks·block_size) and each slot reads its KV through
    a gathered logical view ``[B, T]`` (T = P·block_size) built from
    its page vector — every shape static, so the engine still compiles
    the decode step exactly ONCE for any paging. Row b writes its new
    k/v at the physical index ``pages[b, pos[b]//bs]·bs + pos[b]%bs``
    (the pool's position axis) via a scatter whose inactive rows target
    an out-of-bounds index and are DROPPED (mode="drop") —
    admission/recycling can't perturb in-flight neighbours, matching
    ``decode_step_slots``'s inactive-row contract. The XLA path's
    gathered view transposes back to the [B, T, Hkv, Dh] shape the
    slot-major pool produced, so the attention arithmetic — and its
    bitwise contract against ``decode_step_slots`` — is untouched by
    the relayout.

    For a slot whose pages tile a contiguous span (the identity mapping)
    the gathered view IS the old arena row, T equals the arena's
    cache_len, and every elementwise/reduction shape matches
    ``decode_step_slots`` — logits and written cache values are bitwise
    identical (pinned in tests/test_paged_engine.py), so the two decode
    paths cannot drift.

    ``pallas`` picks the attention engine through the package-wide
    ``PADDLE_TPU_PALLAS`` policy (explicit arg > env > auto): when it
    resolves ``on``/``interpret`` (and the working set passes the VMEM
    budget), the gather + score + softmax + weighted sum above is
    replaced by ``ops.pallas.decode.flash_decode_attention`` — page
    indices resolved inside the kernel, K/V streamed from the pool, no
    gathered ``[B, T, Hkv, Dh]`` view or ``[B, H, T]`` score tensor in
    HBM, bitwise the XLA path's logits on aligned fp32 shapes (pinned
    in tests/test_pallas_decode.py). The pool WRITE of the step's new
    k/v stays the same scatter on either engine. ``params`` may carry
    int8 weights ({"q8","scale"} nodes): they ride the layer scan as
    int8 xs and dequantize inside the body (``_live_layer_weights``
    anti-hoist defenses), so serving reads weights at 1 byte/elt.

    QUANTIZED pools (``init_block_pool(kv_dtype="int8"/"int4")``,
    detected from the pytree): the step quantizes its new k/v row at
    write time (one scale per (row, head) — ``ops/q8.quantize_kv``)
    and scatters values AND scale rows with the same mode="drop"
    isolation; reads gather int8/nibble-packed rows plus their scales
    and widen in the consumer (XLA path) or in-register inside the
    kernel's gather loop (Pallas path) — history crosses HBM at 1 or
    1/2 byte/elt, and the fused-dequant kernel stays bitwise the XLA
    quantized path (tests/test_kv_quant.py)."""
    from paddle_tpu.ops import q8 as ops_q8
    from paddle_tpu.ops.pallas import decode as _pallas_decode
    from paddle_tpu.ops.pallas import policy as _pallas_policy
    B = tokens.shape[0]
    P = pages.shape[1]
    bs = int(block_size)
    T = P * bs
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    kvd = Hkv * Dh
    M = cache["k"].shape[2]
    quantized = _blocks_quantized(params)
    kvq = pool_kv_dtype(cache, cfg)       # "none" | "int8" | "int4"
    mode = _pallas_policy.pallas_mode(pallas)
    # dispatchable (backend), the VMEM budget, AND the per-shape Mosaic
    # lowering probe: each falls back to the pure-XLA path below rather
    # than failing the compile
    use_pallas = _pallas_decode.kernels_dispatchable(mode)
    if use_pallas and mode == "on" and not (
            _pallas_decode.decode_kernel_fits(
                M, P, bs, H // Hkv, Dh, cache["k"].dtype, kv_dtype=kvq)
            and _pallas_decode.decode_lowering_ok(
                M, P, bs, Hkv, H // Hkv, Dh, cache["k"].dtype,
                kv_dtype=kvq, q_dtype=cfg.dtype)):
        use_pallas = False          # pure-XLA fallback rather than an
        #                             opaque Mosaic failure
    pos = jnp.asarray(pos, jnp.int32)
    pages = jnp.asarray(pages, jnp.int32)
    x = _embed_rows(params, tokens, cfg)
    if not cfg.use_rope:
        x = x + jnp.take(params["pos"], pos, axis=0).astype(cfg.dtype)
    rope_tabs = _rope_tables(pos, Dh, cfg.rope_theta) \
        if cfg.use_rope else None
    # logical->physical index map per slot [B, T]: page-strided spans
    gidx = (pages[:, :, None] * bs
            + jnp.arange(bs, dtype=jnp.int32)[None, None, :]
            ).reshape(B, T)
    # physical write index per row; inactive rows aim out of bounds so
    # the scatter drops them (the paged analog of the where()-write)
    wpage = jnp.take_along_axis(pages, (pos // bs)[:, None],
                                axis=1)[:, 0]
    widx = jnp.where(active, wpage * bs + pos % bs, M)
    attend = (jnp.arange(T, dtype=jnp.int32)[None, :]
              <= pos[:, None])                           # [B, T] logical

    def block(x, scanned):
        if kvq != "none":
            w, li, kc, vc, ksc, vsc = scanned  # + scales [Hkv, M]
        else:
            w, li, kc, vc = scanned            # kc/vc [Hkv, M, Dh]
            ksc = vsc = None
        if quantized:
            w = _live_layer_weights(w, li)
        h = _layer_norm(x, w["ln1"], w["ln1_b"])
        qkv = h @ w["qkv"].astype(h.dtype)   # [B, D + 2*kvd]
        q, k, v = jnp.split(qkv, [H * Dh, H * Dh + kvd], axis=-1)
        if cfg.use_rope:
            q = _rope_rows(q.reshape(B, H, Dh), rope_tabs).reshape(
                B, H * Dh)
            k = _rope_rows(k.reshape(B, Hkv, Dh), rope_tabs).reshape(
                B, kvd)
        if kvq != "none":
            # write-time quantization: one scale per (row, head); the
            # same scatter discipline drops inactive rows for values
            # AND scales, so isolation holds for both tables (the
            # head-major pool scatters on its position axis, values
            # transposed to [Hkv, B, ...] — same values, new placement)
            kq, ks_new = ops_q8.quantize_kv(k.reshape(B, Hkv, Dh), kvq)
            vq, vs_new = ops_q8.quantize_kv(v.reshape(B, Hkv, Dh), kvq)
            kc = kc.at[:, widx].set(jnp.swapaxes(kq, 0, 1),
                                    mode="drop")
            vc = vc.at[:, widx].set(jnp.swapaxes(vq, 0, 1),
                                    mode="drop")
            ksc = ksc.at[:, widx].set(jnp.swapaxes(ks_new, 0, 1),
                                      mode="drop")
            vsc = vsc.at[:, widx].set(jnp.swapaxes(vs_new, 0, 1),
                                      mode="drop")
        else:
            kc = kc.at[:, widx].set(
                jnp.swapaxes(k.reshape(B, Hkv, Dh), 0,
                             1).astype(kc.dtype), mode="drop")
            vc = vc.at[:, widx].set(
                jnp.swapaxes(v.reshape(B, Hkv, Dh), 0,
                             1).astype(vc.dtype), mode="drop")
        g = H // Hkv
        if use_pallas:
            # the kernel reads the just-written pool (pos attends to
            # itself) and resolves the page walk via scalar prefetch;
            # for quantized pools the dequant multiply runs in-register
            # on the streamed blocks (int8/int4 HBM reads)
            attn = _pallas_decode.flash_decode_attention(
                q.reshape(B, Hkv, g, Dh), kc, vc, pages, pos,
                block_size=bs, k_scale=ksc, v_scale=vsc, kv_dtype=kvq,
                interpret=(mode == "interpret"))
        else:
            # gather on the pool's position axis, then transpose the
            # logical view back to [B, T, Hkv, ...] — the exact shape
            # (and values) the slot-major pool produced, so everything
            # downstream is bitwise the pre-relayout path
            if kvq != "none":
                kt = ops_q8.dequantize_kv(
                    jnp.transpose(jnp.take(kc, gidx, axis=1),
                                  (1, 2, 0, 3)),
                    jnp.transpose(jnp.take(ksc, gidx, axis=1),
                                  (1, 2, 0)), kvq)
                vt = ops_q8.dequantize_kv(
                    jnp.transpose(jnp.take(vc, gidx, axis=1),
                                  (1, 2, 0, 3)),
                    jnp.transpose(jnp.take(vsc, gidx, axis=1),
                                  (1, 2, 0)), kvq)
            else:
                kt = jnp.transpose(jnp.take(kc, gidx, axis=1),
                                   (1, 2, 0, 3)).astype(jnp.float32)
                vt = jnp.transpose(jnp.take(vc, gidx, axis=1),
                                   (1, 2, 0, 3)).astype(jnp.float32)
            q32 = q.reshape(B, Hkv, g, Dh).astype(jnp.float32)
            s = jnp.einsum("bkgd,btkd->bkgt", q32, kt) / math.sqrt(Dh)
            s = jnp.where(attend[:, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("bkgt,btkd->bkgd", p, vt)
        attn = attn.reshape(B, cfg.d_model).astype(cfg.dtype)
        x = x + attn @ w["attn_out"].astype(attn.dtype)
        h2 = _layer_norm(x, w["ln2"], w["ln2_b"])
        if cfg.moe_experts:
            import dataclasses as _dc

            from paddle_tpu.parallel import moe
            mc = _dc.replace(cfg.moe_cfg(), capacity_factor=float(
                cfg.moe_experts) / cfg.moe_top_k)
            out, _ = moe.moe_ffn(
                {"gate": w["gate"], "w_in": w["moe_w_in"],
                 "w_out": w["moe_w_out"]}, h2, mc)
            x = x + out.astype(x.dtype)
        else:
            ff = jax.nn.gelu(h2 @ w["mlp_in"].astype(h2.dtype))
            x = x + ff @ w["mlp_out"].astype(ff.dtype)
        if kvq != "none":
            return x, (kc, vc, ksc, vsc)
        return x, (kc, vc)

    li = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    if kvq != "none":
        x, (kn, vn, ksn, vsn) = jax.lax.scan(
            block, x, (params["blocks"], li, cache["k"], cache["v"],
                       cache["k_scale"], cache["v_scale"]))
        new_cache = {"k": kn, "v": vn, "k_scale": ksn, "v_scale": vsn}
    else:
        x, (kn, vn) = jax.lax.scan(block, x, (params["blocks"], li,
                                              cache["k"], cache["v"]))
        new_cache = {"k": kn, "v": vn}
    x = _layer_norm(x, params["ln_f"], params["ln_f_b"])
    logits = _vocab_logits(x, params)
    return logits, new_cache


def verify_step_paged(params, cache, tokens: jax.Array, pos: jax.Array,
                      valid: jax.Array, active: jax.Array,
                      pages: jax.Array, cfg: TransformerConfig, *,
                      block_size: int):
    """W tokens of EVERY slot in one pass over the paged pool — the
    speculative-decoding verify step. tokens [B, W] int32 (row b holds
    ``[last_token, draft_1, ..., draft_{W-1}]``), ``pos`` [B] int32 (the
    position row b's FIRST token writes — decode_step_paged's ``pos``
    semantics), ``valid`` [B] int32 (window rows beyond it neither write
    nor matter), ``active`` [B] bool, ``pages`` [B, P] the FULL page
    table → (logits [B, W, vocab] fp32, updated pool).

    This is ``decode_step_paged`` with a W axis, and deliberately
    nothing more: every reduction an output element depends on keeps
    the decode step's axis LENGTH — attention scores/softmax/weighted
    sum run over the same gathered ``T = P·block_size`` logical view
    (full page table, not a trimmed span), layer norms over d_model,
    the vocab head over d_model — and every dense op is row-wise over
    a flattened ``[B·W, ...]`` batch. XLA's CPU/TPU reductions split
    lanes by axis length, so equal lengths (plus row-independent
    matmuls) make window row (b, j) BITWISE the decode step this slot
    would have run at position ``pos+j`` — the property that lets a
    spec-decode engine promise greedy output bitwise-identical to the
    target-only engine (pinned in tests/test_spec_decode.py). A
    chunk-prefill-shaped verify could not promise this: its
    concat(context, chunk) softmax axis changes length with the span.
    One backend caveat: the bitwise claim is the GEMM regime's — a
    one-row decode batch ([1, D] @ W) may lower as a matvec whose
    accumulation differs from the window's multi-row gemm at the ulp
    level, so B >= 2 engines carry the pinned guarantee and B = 1 is
    near-exact (greedy ids still agree except on sub-ulp logit ties).

    Window causality: all W rows' k/v are scattered BEFORE the gather,
    and row j masks the view at ``t <= pos+j`` — so row j attends to
    rows < j of its own window plus itself, exactly the sequential
    decode semantics (row i's activations depend only on positions
    <= i, so recomputing them batched is the chunked-prefill argument).
    Rows >= valid (and inactive slots) scatter to the out-of-bounds
    index and are DROPPED, preserving the inactive-row isolation
    contract. Rejected draft rows' k/v DO land in the pool — the
    engine simply rewinds ``pos``, the attend mask hides them, and the
    next window overwrites them (positions above ``pos`` are never
    read, the same discipline as a freed slot's stale bytes).

    Quantized pools and int8 {"q8","scale"} weight trees ride exactly
    as in ``decode_step_paged`` (write-time KV quantization with
    mode="drop" on values AND scales, in-scan weight dequant)."""
    from paddle_tpu.ops import q8 as ops_q8
    B, W = tokens.shape
    N = B * W
    P = pages.shape[1]
    bs = int(block_size)
    T = P * bs
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    kvd = Hkv * Dh
    M = cache["k"].shape[2]
    quantized = _blocks_quantized(params)
    kvq = pool_kv_dtype(cache, cfg)
    pos = jnp.asarray(pos, jnp.int32)
    valid = jnp.asarray(valid, jnp.int32)
    pages = jnp.asarray(pages, jnp.int32)
    gpos = pos[:, None] + jnp.arange(W, dtype=jnp.int32)[None, :]
    flat = tokens.reshape(N)
    x = _embed_rows(params, flat, cfg)                  # [N, D]
    if not cfg.use_rope:
        # clip keeps rows past `valid` (whose writes drop) in range;
        # valid rows clip to themselves, bitwise the decode-step take
        x = x + jnp.take(params["pos"],
                         jnp.minimum(gpos.reshape(N),
                                     params["pos"].shape[0] - 1),
                         axis=0).astype(cfg.dtype)
    rope_tabs = _rope_tables(gpos.reshape(N), Dh, cfg.rope_theta) \
        if cfg.use_rope else None
    # logical->physical map per slot [B, T] (decode's gidx, unchanged)
    gidx = (pages[:, :, None] * bs
            + jnp.arange(bs, dtype=jnp.int32)[None, None, :]
            ).reshape(B, T)
    # physical write index per window row; rows >= valid and inactive
    # slots aim out of bounds so the scatter drops them
    wpage = jnp.take_along_axis(pages, gpos // bs, axis=1)     # [B, W]
    live = active[:, None] & (jnp.arange(W, dtype=jnp.int32)[None, :]
                              < valid[:, None])
    widx = jnp.where(live, wpage * bs + gpos % bs, M).reshape(N)
    # row (b, j) sees logical positions t <= pos_b + j — the decode
    # mask at that position, so axis length AND boundary match
    attend = (jnp.arange(T, dtype=jnp.int32)[None, None, :]
              <= gpos[:, :, None])                       # [B, W, T]

    def block(x, scanned):
        if kvq != "none":
            w, li, kc, vc, ksc, vsc = scanned
        else:
            w, li, kc, vc = scanned
            ksc = vsc = None
        if quantized:
            w = _live_layer_weights(w, li)
        h = _layer_norm(x, w["ln1"], w["ln1_b"])
        qkv = h @ w["qkv"].astype(h.dtype)              # [N, D + 2*kvd]
        q, k, v = jnp.split(qkv, [H * Dh, H * Dh + kvd], axis=-1)
        if cfg.use_rope:
            q = _rope_rows(q.reshape(N, H, Dh), rope_tabs).reshape(
                N, H * Dh)
            k = _rope_rows(k.reshape(N, Hkv, Dh), rope_tabs).reshape(
                N, kvd)
        if kvq != "none":
            kq, ks_new = ops_q8.quantize_kv(k.reshape(N, Hkv, Dh), kvq)
            vq, vs_new = ops_q8.quantize_kv(v.reshape(N, Hkv, Dh), kvq)
            kc = kc.at[:, widx].set(jnp.swapaxes(kq, 0, 1),
                                    mode="drop")
            vc = vc.at[:, widx].set(jnp.swapaxes(vq, 0, 1),
                                    mode="drop")
            ksc = ksc.at[:, widx].set(jnp.swapaxes(ks_new, 0, 1),
                                      mode="drop")
            vsc = vsc.at[:, widx].set(jnp.swapaxes(vs_new, 0, 1),
                                      mode="drop")
        else:
            kc = kc.at[:, widx].set(
                jnp.swapaxes(k.reshape(N, Hkv, Dh), 0,
                             1).astype(kc.dtype), mode="drop")
            vc = vc.at[:, widx].set(
                jnp.swapaxes(v.reshape(N, Hkv, Dh), 0,
                             1).astype(vc.dtype), mode="drop")
        g = H // Hkv
        # head-major gather transposed back to the [B, T, Hkv, ...]
        # logical view (same values/shape as the slot-major path — the
        # verify rows' bitwise contract vs decode_step_paged rides on
        # the arithmetic downstream being identical)
        if kvq != "none":
            kt = ops_q8.dequantize_kv(
                jnp.transpose(jnp.take(kc, gidx, axis=1),
                              (1, 2, 0, 3)),
                jnp.transpose(jnp.take(ksc, gidx, axis=1),
                              (1, 2, 0)), kvq)
            vt = ops_q8.dequantize_kv(
                jnp.transpose(jnp.take(vc, gidx, axis=1),
                              (1, 2, 0, 3)),
                jnp.transpose(jnp.take(vsc, gidx, axis=1),
                              (1, 2, 0)), kvq)
        else:
            kt = jnp.transpose(jnp.take(kc, gidx, axis=1),
                               (1, 2, 0, 3)).astype(jnp.float32)
            vt = jnp.transpose(jnp.take(vc, gidx, axis=1),
                               (1, 2, 0, 3)).astype(jnp.float32)
        q32 = q.reshape(B, W, Hkv, g, Dh).astype(jnp.float32)
        s = jnp.einsum("bwkgd,btkd->bwkgt", q32, kt) / math.sqrt(Dh)
        s = jnp.where(attend[:, :, None, None, :], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        attn = jnp.einsum("bwkgt,btkd->bwkgd", p, vt)
        attn = attn.reshape(N, cfg.d_model).astype(cfg.dtype)
        x = x + attn @ w["attn_out"].astype(attn.dtype)
        h2 = _layer_norm(x, w["ln2"], w["ln2_b"])
        if cfg.moe_experts:
            import dataclasses as _dc

            from paddle_tpu.parallel import moe
            mc = _dc.replace(cfg.moe_cfg(), capacity_factor=float(
                cfg.moe_experts) / cfg.moe_top_k)
            out, _ = moe.moe_ffn(
                {"gate": w["gate"], "w_in": w["moe_w_in"],
                 "w_out": w["moe_w_out"]}, h2, mc)
            x = x + out.astype(x.dtype)
        else:
            ff = jax.nn.gelu(h2 @ w["mlp_in"].astype(h2.dtype))
            x = x + ff @ w["mlp_out"].astype(ff.dtype)
        if kvq != "none":
            return x, (kc, vc, ksc, vsc)
        return x, (kc, vc)

    li = jnp.arange(cfg.n_layers, dtype=jnp.int32)
    if kvq != "none":
        x, (kn, vn, ksn, vsn) = jax.lax.scan(
            block, x, (params["blocks"], li, cache["k"], cache["v"],
                       cache["k_scale"], cache["v_scale"]))
        new_cache = {"k": kn, "v": vn, "k_scale": ksn, "v_scale": vsn}
    else:
        x, (kn, vn) = jax.lax.scan(block, x, (params["blocks"], li,
                                              cache["k"], cache["v"]))
        new_cache = {"k": kn, "v": vn}
    x = _layer_norm(x, params["ln_f"], params["ln_f_b"])
    logits = _vocab_logits(x, params)
    return logits.reshape(B, W, cfg.vocab), new_cache


def prefill_into_blocks(params, cache, tokens: jax.Array,
                        length: jax.Array, pages: jax.Array,
                        cfg: TransformerConfig, *, block_size: int,
                        pallas: Optional[str] = None):
    """Prefill ONE CHUNK of one request's prompt into its pages of the
    block pool.

    tokens [1, C] is a chunk of the prompt right-padded to a chunk
    bucket C; ``length`` (scalar int32, traced) counts its valid
    tokens; ``pages`` [P] int32 is the PREFIX of the slot's page vector
    covering context + chunk — the chunk occupies the LAST
    ``ceil(C/block_size)`` pages, so the tokens already resident for
    this slot (prefix-cache hits + earlier chunks) number
    ``ctx = (P - ceil(C/block_size)) * block_size``, a STATIC property
    of the argument shapes. The engine keeps ctx block-aligned by
    construction (hits and chunk boundaries are multiples of the chunk
    size). Returns (logits at global position ``ctx + length - 1``
    [1, vocab] fp32, updated pool).

    The layer scan carries NOTHING pool-sized: the context KV is
    gathered ONCE up front ([L, ctx, Hkv, Dh], read-only per-layer
    inputs), each layer attends over ``concat(context, chunk)`` with the
    context fully visible and the chunk causally masked, and the chunk's
    KV lands in the pool post-scan as one masked contiguous-span
    ``dynamic_update_slice`` per chunk page (padded rows write back the
    span's old bytes). Cold prompts (ctx = 0) therefore cost
    the same as a slot prefill of the same bucket instead of dragging
    the whole arena view through every layer, and the per-chunk price
    scales with ``C · (ctx + C)``, not ``C · cache_len``.

    Compile discipline: one compile per (chunk bucket, context pages)
    shape pair — a fixed chunk grid, so a prompt of any length costs
    ``ceil(Tp/chunk)`` compiled calls interleaved with decode steps
    instead of one monolithic stall. Because the engine's chunk grid is
    deterministic and prefix-cache hits are chunk-aligned, a hit replay
    runs bitwise the cold prefill's programs on bitwise the cold
    prefill's values (pinned in tests/test_paged_engine.py).

    Quantized pools (``init_block_pool(kv_dtype=...)``): the context
    gathers int8/int4 rows + their scales (1 byte/elt of history
    through the scan) and dequantizes in the consumer; the chunk's own
    KV is quantized at write time, per (layer, token, head), with the
    same masked-span RMW covering values AND scales. In-chunk attention
    uses the exact (pre-quantization) chunk values — only what decode
    reads LATER is rounded, matching the decode-write discipline.

    ``pallas`` resolves the ``PADDLE_TPU_PALLAS`` policy: when on, each
    layer's chunk attention runs ``ops.pallas.prefill.flash_chunk_prefill``
    (pages resolved inside the kernel, context streamed from the pool
    with the dequant fused, one exact softmax over the concat — no
    gathered context or [C, S+C] score tensor in HBM) and the span
    writes run the ``paged_span_write`` kernel (block-mapped through
    the page vector via scalar prefetch). The XLA path above stays the
    always-available fallback and the numerics reference."""
    from paddle_tpu.ops import q8 as ops_q8
    from paddle_tpu.ops.pallas import policy as _pallas_policy
    if tokens.shape[0] != 1:
        raise ValueError(f"prefill_into_blocks takes one request "
                         f"([1, C] tokens), got {tokens.shape}")
    C = tokens.shape[1]
    bs = int(block_size)
    P = pages.shape[0]
    pc = -(-C // bs)                    # pages the chunk itself spans
    S = (P - pc) * bs                   # static context length
    if S < 0:
        raise ValueError(f"pages vector ({P}) shorter than the chunk's "
                         f"own span ({pc} pages for C={C})")
    H, Dh = cfg.n_heads, cfg.head_dim
    Hkv = cfg.kv_heads
    kvd = Hkv * Dh
    kvq = pool_kv_dtype(cache, cfg)
    M = cache["k"].shape[2]
    mode = _pallas_policy.pallas_mode(pallas)
    from paddle_tpu.ops.pallas import decode as _pallas_decode
    use_pallas = _pallas_decode.kernels_dispatchable(mode)
    if use_pallas:
        from paddle_tpu.ops.pallas import prefill as _pallas_prefill
        if mode == "on" and not (
                _pallas_prefill.prefill_kernel_fits(
                    M, S, C, H // Hkv, Dh, cache["k"].dtype,
                    kv_dtype=kvq, block_size=bs)
                and _pallas_prefill.prefill_lowering_ok(
                    M, S, C, bs, Hkv, H // Hkv, Dh, cache["k"].dtype,
                    kv_dtype=kvq, q_dtype=cfg.dtype)
                and _pallas_prefill.span_write_lowering_ok(
                    M, -(-C // bs), bs, cfg.n_layers, Hkv,
                    Dh, cache["k"].dtype, kv_dtype=kvq)):
            use_pallas = False      # XLA fallback, not a Mosaic OOM
            #                         or an opaque tiling rejection
    length = jnp.asarray(length, jnp.int32)
    pages = jnp.asarray(pages, jnp.int32)
    gpos = S + jnp.arange(C, dtype=jnp.int32)            # [C] global
    x = jnp.take(params["embed"], tokens[0], axis=0).astype(cfg.dtype)
    if not cfg.use_rope:
        # clip keeps padded rows (whose writes drop anyway) in range
        x = x + jnp.take(params["pos"],
                         jnp.minimum(gpos, params["pos"].shape[0] - 1),
                         axis=0).astype(cfg.dtype)
    rope_tabs = _rope_tables(gpos, Dh, cfg.rope_theta) \
        if cfg.use_rope else None
    valid = jnp.arange(C, dtype=jnp.int32) < length
    if use_pallas:
        # the kernel resolves the page walk itself: the pool rides the
        # layer scan as xs (a per-layer view, no gather/copy) and only
        # the slot's MAPPED context blocks ever stream into VMEM
        if kvq != "none":
            ctx_xs = (cache["k"], cache["v"],
                      cache["k_scale"], cache["v_scale"])
        else:
            ctx_xs = (cache["k"], cache["v"])
    else:
        # context gather (once, all layers): every context position is
        # real (ctx tokens were written by hits/earlier chunks), no
        # mask needed. The head-major pool gathers on its position
        # axis, then the view transposes back to the position-leading
        # [L, S, Hkv, ...] shape the slot-major pool produced — same
        # values, so the scan body below is bitwise the old path's
        gidx = (pages[:P - pc, None] * bs
                + jnp.arange(bs, dtype=jnp.int32)[None, :]).reshape(S)

        def _ctx(n):
            g = jnp.take(cache[n], gidx, axis=2)   # [L, Hkv, S, ...]
            perm = (0, 2, 1) + tuple(range(3, g.ndim))
            return jnp.transpose(g, perm)          # [L, S, Hkv, ...]

        ctx_xs = tuple(_ctx(n)
                       for n in (("k", "v", "k_scale", "v_scale")
                                 if kvq != "none" else ("k", "v")))
    # [C, S+C] mask: context fully visible, chunk causally masked
    attend = jnp.concatenate(
        [jnp.ones((C, S), bool),
         jnp.tril(jnp.ones((C, C), bool))], axis=1)

    def block(x, scanned):
        w = scanned[0]
        ctx = scanned[1:]       # per-layer pool view (pallas) or the
        #                         gathered [S, ...] context (XLA)
        h = _layer_norm(x, w["ln1"], w["ln1_b"])
        qkv = h @ w["qkv"].astype(h.dtype)   # [C, D + 2*kvd]
        q, k, v = jnp.split(qkv, [H * Dh, H * Dh + kvd], axis=-1)
        if cfg.use_rope:
            q = _rope_rows(q.reshape(C, H, Dh), rope_tabs).reshape(
                C, H * Dh)
            k = _rope_rows(k.reshape(C, Hkv, Dh), rope_tabs).reshape(
                C, kvd)
        kck = k.reshape(C, Hkv, Dh)
        vck = v.reshape(C, Hkv, Dh)
        g = H // Hkv
        if use_pallas:
            from paddle_tpu.ops.pallas import prefill as _pp
            kc, vc = ctx[0], ctx[1]
            ksc, vsc = (ctx[2], ctx[3]) if kvq != "none" else (None,
                                                               None)
            attn = _pp.flash_chunk_prefill(
                q.reshape(C, Hkv, g, Dh), kck, vck, kc, vc,
                pages[:P - pc], block_size=bs, k_scale=ksc,
                v_scale=vsc, kv_dtype=kvq,
                interpret=(mode == "interpret"))
            attn = attn.reshape(C, Hkv, g, Dh)
        else:
            if kvq != "none":
                ck = ops_q8.dequantize_kv(ctx[0], ctx[2], kvq)
                cv = ops_q8.dequantize_kv(ctx[1], ctx[3], kvq)
            else:
                ck = ctx[0].astype(jnp.float32)
                cv = ctx[1].astype(jnp.float32)
            kall = jnp.concatenate([ck, kck.astype(jnp.float32)],
                                   axis=0)
            vall = jnp.concatenate([cv, vck.astype(jnp.float32)],
                                   axis=0)
            q32 = q.reshape(C, Hkv, g, Dh).astype(jnp.float32)
            s = jnp.einsum("ckgd,tkd->ckgt", q32, kall) / math.sqrt(Dh)
            s = jnp.where(attend[:, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum("ckgt,tkd->ckgd", p, vall)
        attn = attn.reshape(C, cfg.d_model).astype(cfg.dtype)
        x = x + attn @ w["attn_out"].astype(attn.dtype)
        h2 = _layer_norm(x, w["ln2"], w["ln2_b"])
        if cfg.moe_experts:
            import dataclasses as _dc

            from paddle_tpu.parallel import moe
            # inference capacity (cf = E/k): prefill must not drop
            # tokens the way Switch training capacity does
            mc = _dc.replace(cfg.moe_cfg(), capacity_factor=float(
                cfg.moe_experts) / cfg.moe_top_k)
            out, _ = moe.moe_ffn(
                {"gate": w["gate"], "w_in": w["moe_w_in"],
                 "w_out": w["moe_w_out"]}, h2, mc)
            x = x + out.astype(x.dtype)
        else:
            ff = jax.nn.gelu(h2 @ w["mlp_in"].astype(h2.dtype))
            x = x + ff @ w["mlp_out"].astype(ff.dtype)
        if kvq != "none":
            # fp values out of the scan; quantized post-scan in one
            # pass so values and scales stack [L, C, ...] together
            return x, (kck, vck)
        return x, (kck.astype(cache["k"].dtype),
                   vck.astype(cache["v"].dtype))

    x, (ks, vs) = jax.lax.scan(block, x, (params["blocks"],) + ctx_xs)
    # pool write for the whole chunk, all layers: the scan stacks the
    # spans position-major ([L, C, Hkv, Dh]); quantization (per
    # (layer, token, head)) runs on that layout — the same values as
    # ever — and the spans then transpose to the pool's head-major
    # [L, Hkv, C, ...] for one masked read-modify-write of the
    # CONTIGUOUS bs-token span per chunk page — dynamic_update_slice,
    # not a scatter (a [C]-index scatter into the flat pool is several
    # ms slower per call on CPU). Padded rows write back the span's
    # old bytes, the RMW equivalent of the scatter's mode="drop".
    if kvq != "none":
        kq, kscl = ops_q8.quantize_kv(ks, kvq)   # [L,C,Hkv,Dh'], [L,C,Hkv]
        vq, vscl = ops_q8.quantize_kv(vs, kvq)
        spans = {"k": kq, "v": vq, "k_scale": kscl, "v_scale": vscl}
    else:
        spans = {"k": ks, "v": vs}
    spans = {n: jnp.transpose(a, (0, 2, 1) + tuple(range(3, a.ndim)))
             for n, a in spans.items()}          # [L, Hkv, C, ...]
    pad = pc * bs - C
    if pad:
        spans = {n: jnp.pad(a, ((0, 0), (0, 0), (0, pad)) + ((0, 0),)
                            * (a.ndim - 3)) for n, a in spans.items()}
        vfull = jnp.concatenate([valid, jnp.zeros((pad,), bool)])
    else:
        vfull = valid
    new_cache = dict(cache)
    tail_pages = pages[P - pc:]
    if use_pallas:
        from paddle_tpu.ops.pallas import prefill as _pallas_prefill
        new_cache.update(_pallas_prefill.paged_span_write(
            {n: cache[n] for n in spans}, spans, tail_pages, vfull,
            block_size=bs, interpret=(mode == "interpret")))
    else:
        for j in range(pc):
            dst = tail_pages[j] * bs
            for n, a in spans.items():
                vmask = vfull[j * bs:(j + 1) * bs].reshape(
                    (1, 1, bs) + (1,) * (a.ndim - 3))
                aj = a[:, :, j * bs:(j + 1) * bs]
                old = jax.lax.dynamic_slice(
                    new_cache[n], (0, 0, dst) + (0,) * (a.ndim - 3),
                    a.shape[:2] + (bs,) + a.shape[3:])
                new_cache[n] = jax.lax.dynamic_update_slice(
                    new_cache[n], jnp.where(vmask, aj, old),
                    (0, 0, dst) + (0,) * (a.ndim - 3))
    # only the last VALID chunk position feeds the vocab head (the
    # gather-head discipline of prefill_into_slot)
    x = jnp.take(x, jnp.reshape(jnp.maximum(length - 1, 0), (1,)), axis=0)
    x = _layer_norm(x, params["ln_f"], params["ln_f_b"])
    logits = jnp.einsum("td,vd->tv", x.astype(jnp.float32),
                        params["embed"].astype(jnp.float32))
    return logits, new_cache


def generate(params, prompt: jax.Array, cfg: TransformerConfig, *,
             max_new: int, temperature: float = 0.0,
             key: Optional[jax.Array] = None,
             mesh: Optional[Mesh] = None) -> jax.Array:
    """Autoregressive generation: prompt [B, Tp] → [B, Tp + max_new].

    Batched prefill fills the KV cache in one forward pass, then a
    ``lax.scan`` replays the compiled single-token step ``max_new`` times
    — the TPU-idiomatic decode loop (no per-step retracing, no growing
    shapes). temperature=0 is greedy argmax; otherwise categorical
    sampling with ``key``.

    ``params`` may contain int8-quantized weights
    (io/lm_serving.quantize_lm_params {"q8","scale"} nodes): they are
    threaded through the SCAN CARRY and dequantized inside each step, so
    XLA cannot hoist the dequant out of the loop — every decoded token
    reads the weights from HBM at 1 byte/elt with the dequant multiply
    fused into the matmul operand reads (decode is weight-read-bound;
    a loop-invariant dequant would silently restore 4-byte reads)."""
    from paddle_tpu.ops import q8 as ops_q8

    B, Tp = prompt.shape
    if max_new < 1:
        raise ValueError(f"generate: max_new must be >= 1, got {max_new}")
    cache_len = Tp + max_new
    if cache_len > cfg.max_len:
        raise ValueError(f"generate: {cache_len} positions exceed "
                         f"cfg.max_len={cfg.max_len}")
    if temperature > 0 and key is None:
        raise ValueError("generate: sampling (temperature>0) needs a key")
    quantized = any(ops_q8.is_quantized_weight(n) for n in
                    jax.tree_util.tree_leaves(
                        params, is_leaf=ops_q8.is_quantized_weight))
    live = ops_q8.dequantize_tree(params) if quantized else params
    logits, cache = prefill(live, prompt, cfg, cache_len, mesh=mesh)
    del live
    key = key if key is not None else jax.random.PRNGKey(0)

    def sample(logits, k):
        if temperature > 0:
            return jax.random.categorical(k, logits / temperature, axis=-1)
        return jnp.argmax(logits, axis=-1)

    key, k0 = jax.random.split(key)
    first = sample(logits, k0).astype(jnp.int32)

    # one step function serves both paths: quantized weights ride the
    # carry as `extra` and are rebuilt INSIDE the body behind an
    # optimization barrier — XLA's while-loop simplifier + LICM would
    # otherwise hoist the loop-invariant dequant and materialize fp32
    # weights once, silently restoring 4-byte weight reads per token
    extra0 = (params,) if quantized else ()

    def step(carry, i):
        extra, cache, tok, key = carry
        key, ks = jax.random.split(key)
        if quantized:
            # three hoist defenses so the dequant stays inside the loop
            # (int8 weight reads per token, the point of the feature):
            # the weights ride the CARRY, sit behind an optimization
            # BARRIER, and the scales fold in a float zero derived from
            # the loop counter (loop-variant by data dependence). The
            # CPU backend deletes barriers and folds the zero, hoisting
            # anyway (one fp32 materialization per generate call —
            # amortized over max_new tokens, so never WORSE than fp32
            # decode); whether TPU keeps the in-loop int8 reads is an
            # on-chip measurement (queue_r4d [3d]). The exported
            # LMServer path dequantizes per HOST call and cannot be
            # hoisted regardless.
            p8 = jax.lax.optimization_barrier(extra[0])
            i_eps = i.astype(jnp.float32) * 0.0

            def _leaf(n):
                if ops_q8.is_quantized_weight(n):
                    return {"q8": n["q8"], "scale": n["scale"] + i_eps}
                return n

            p = ops_q8.dequantize_tree(jax.tree_util.tree_map(
                _leaf, p8, is_leaf=ops_q8.is_quantized_weight))
        else:
            p = params
        logits, cache = decode_step(p, cache, tok, Tp + i, cfg)
        nxt = sample(logits, ks).astype(jnp.int32)
        return (extra, cache, nxt, key), tok

    (_, _, last, _), toks = jax.lax.scan(
        step, (extra0, cache, first, key),
        jnp.arange(max_new - 1, dtype=jnp.int32))
    generated = jnp.concatenate(
        [jnp.swapaxes(toks, 0, 1), last[:, None]], axis=1) \
        if max_new > 1 else first[:, None]
    return jnp.concatenate([prompt, generated], axis=1)


def beam_search(params, prompt: jax.Array, cfg: TransformerConfig, *,
                max_new: int, beam_size: int = 4,
                mesh: Optional[Mesh] = None) -> tuple:
    """Beam-search decoding over the KV cache: prompt [B, Tp] →
    (tokens [B, beam, Tp + max_new], scores [B, beam], best first.

    The transformer-flagship analog of the recurrent DSL's beam_search
    (recurrent.py; reference: RecurrentGradientMachine generation,
    GradientMachine::eval beam path). The cache carries B·beam hypotheses
    flattened on the batch axis; each step scores beam·vocab expansions,
    keeps the top ``beam_size``, and GATHERS the cache rows of the
    surviving hypotheses — all static shapes under one lax.scan. (No
    length penalty: all hypotheses here have identical length max_new,
    so any GNMT-style α rescales every score equally; EOS-terminated
    variable-length decoding is the recurrent DSL's beam_search domain.)"""
    B, Tp = prompt.shape
    if max_new < 1:
        raise ValueError(f"beam_search: max_new must be >= 1, got {max_new}")
    cache_len = Tp + max_new
    if cache_len > cfg.max_len:
        raise ValueError(f"beam_search: {cache_len} positions exceed "
                         f"cfg.max_len={cfg.max_len}")
    if beam_size < 1 or beam_size > cfg.vocab:
        raise ValueError(f"beam_search: beam_size {beam_size} must be in "
                         f"[1, vocab={cfg.vocab}]")
    K, V = beam_size, cfg.vocab

    logits, cache = prefill(params, prompt, cfg, cache_len, mesh=mesh)
    logp0 = jax.nn.log_softmax(logits, axis=-1)            # [B, V]
    top0, tok0 = jax.lax.top_k(logp0, K)                   # [B, K]
    # replicate the cache per beam: [L, B, T, H, Dh] -> [L, B*K, T, H, Dh]
    cache = jax.tree_util.tree_map(lambda c: jnp.repeat(c, K, axis=1),
                                   cache)
    scores = top0                                          # [B, K]
    toks = tok0.astype(jnp.int32)                          # [B, K] step-0 pick
    batch_base = (jnp.arange(B, dtype=jnp.int32)[:, None] * K)  # [B, 1]

    def step(carry, i):
        cache, toks, scores = carry
        flat = toks.reshape(B * K)
        logits, cache = decode_step(params, cache, flat, Tp + i, cfg)
        logp = jax.nn.log_softmax(logits, axis=-1).reshape(B, K, V)
        total = scores[:, :, None] + logp                  # [B, K, V]
        top, idx = jax.lax.top_k(total.reshape(B, K * V), K)
        beam_src = (idx // V).astype(jnp.int32)            # [B, K]
        nxt = (idx % V).astype(jnp.int32)
        # reindex the cache rows to the surviving hypotheses
        flat_src = (batch_base + beam_src).reshape(B * K)
        cache = jax.tree_util.tree_map(
            lambda c: jnp.take(c, flat_src, axis=1), cache)
        return (cache, nxt, top), (toks, beam_src)

    (cache, last, scores), (hist_toks, hist_src) = jax.lax.scan(
        step, (cache, toks, scores),
        jnp.arange(max_new - 1, dtype=jnp.int32))

    # backtrack: hist_toks[i] holds position-i tokens in the beam order
    # BEFORE step i's reshuffle (O_i) while hist_src[i] maps the
    # post-reshuffle order O_{i+1} back to O_i — so the survivor pointer
    # must step through src FIRST, then gather the token row
    def back(carry, xs):
        ptr = carry                                        # [B, K] in O_{i+1}
        t, src = xs
        ptr = jnp.take_along_axis(src, ptr, axis=1)        # now in O_i
        tok = jnp.take_along_axis(t, ptr, axis=1)
        return ptr, tok

    ptr0 = jnp.tile(jnp.arange(K, dtype=jnp.int32)[None], (B, 1))
    _, rev = jax.lax.scan(back, ptr0, (hist_toks, hist_src), reverse=True)
    seq = jnp.concatenate([jnp.moveaxis(rev, 0, 2), last[:, :, None]],
                          axis=2) if max_new > 1 else toks[:, :, None]
    prompt_rep = jnp.repeat(prompt[:, None, :], K, axis=1)
    out = jnp.concatenate([prompt_rep, seq], axis=2)       # [B, K, Tp+new]
    return out, scores
