"""Decoder-only transformer LM — the long-context / large-scale flagship.

The reference predates transformers; this is the modern capability filling
the "scale sequence length / scale out" slot (SURVEY.md §2.3, §5): causal LM
with ring-attention context parallelism over the ``seq`` mesh axis, tensor
parallelism over ``model`` (heads + MLP), data parallelism over ``data``,
all as one jit-compiled GSPMD program.

Functional design (not the v1 layer DSL): parameters are a pytree with
blocks stacked on a leading axis and the layer loop is a ``lax.scan`` —
one compiled block body regardless of depth, weights ride the MXU in bf16.
"""

import dataclasses
import math
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.core import place
from paddle_tpu.ops import loss as ops_loss
from paddle_tpu.ops import norm as ops_norm
from paddle_tpu.parallel import ring


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    max_len: int = 2048
    dtype: object = jnp.bfloat16
    use_ring_attention: bool = False   # shard_map CP over the seq axis
    use_flash_attention: bool = False  # Pallas fused attention (TPU)

    @property
    def head_dim(self):
        return self.d_model // self.n_heads


def init_params(key: jax.Array, cfg: TransformerConfig):
    """Parameter pytree; block weights stacked on axis 0 (scan layout)."""
    k = jax.random.split(key, 8)
    D, F, L, V = cfg.d_model, cfg.d_ff, cfg.n_layers, cfg.vocab
    s = 1.0 / math.sqrt(D)

    def nrm(kk, shape, scale):
        return (jax.random.normal(kk, shape, jnp.float32) * scale).astype(
            jnp.float32)

    return {
        "embed": nrm(k[0], (V, D), 1.0 / math.sqrt(D)),
        "pos": nrm(k[1], (cfg.max_len, D), 0.02),
        "blocks": {
            "ln1": jnp.ones((L, D), jnp.float32),
            "ln1_b": jnp.zeros((L, D), jnp.float32),
            "qkv": nrm(k[2], (L, D, 3 * D), s),
            "attn_out": nrm(k[3], (L, D, D), s / math.sqrt(2 * L)),
            "ln2": jnp.ones((L, D), jnp.float32),
            "ln2_b": jnp.zeros((L, D), jnp.float32),
            "mlp_in": nrm(k[4], (L, D, F), s),
            "mlp_out": nrm(k[5], (L, F, D), 1.0 / math.sqrt(F) /
                           math.sqrt(2 * L)),
        },
        "ln_f": jnp.ones((D,), jnp.float32),
        "ln_f_b": jnp.zeros((D,), jnp.float32),
    }


def param_shardings(cfg: TransformerConfig, mesh: Mesh):
    """TP layout (scaling-book): qkv/mlp_in column-parallel, attn_out/mlp_out
    row-parallel over ``model``; embeddings vocab-sharded over ``model``."""
    M = place.AXIS_MODEL

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    return {
        "embed": ns(M, None),
        "pos": ns(),
        "blocks": {
            "ln1": ns(), "ln1_b": ns(), "ln2": ns(), "ln2_b": ns(),
            "qkv": ns(None, None, M),
            "attn_out": ns(None, M, None),
            "mlp_in": ns(None, None, M),
            "mlp_out": ns(None, M, None),
        },
        "ln_f": ns(), "ln_f_b": ns(),
    }


def _layer_norm(x, g, b):
    return ops_norm.layer_norm(x, g, b).astype(x.dtype)


def forward(params, tokens: jax.Array, cfg: TransformerConfig, *,
            mesh: Optional[Mesh] = None,
            lengths: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, T] int32 → logits [B, T, vocab] (float32).

    With ``cfg.use_ring_attention`` and a mesh carrying a >1 ``seq`` axis,
    attention runs as ring CP; activations get seq-sharding constraints so
    XLA keeps the [B, T, D] tensors distributed end-to-end.
    """
    B, T = tokens.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    x = jnp.take(params["embed"], tokens, axis=0).astype(cfg.dtype)
    x = x + params["pos"][:T].astype(cfg.dtype)[None]

    seq_sharded = (mesh is not None and place.AXIS_SEQ in mesh.axis_names
                   and mesh.shape[place.AXIS_SEQ] > 1)

    def constrain(h):
        if mesh is None:
            return h
        spec = P(place.AXIS_DATA,
                 place.AXIS_SEQ if seq_sharded else None, None)
        return jax.lax.with_sharding_constraint(
            h, NamedSharding(mesh, spec))

    x = constrain(x)

    def block(x, w):
        h = _layer_norm(x, w["ln1"], w["ln1_b"])
        qkv = jnp.einsum("btd,de->bte", h, w["qkv"].astype(h.dtype))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, Dh)
        k = k.reshape(B, T, H, Dh)
        v = v.reshape(B, T, H, Dh)
        if seq_sharded and cfg.use_ring_attention:
            # flash blocks inside the ring when the batch is packed —
            # O(T/P·D) per chip with no score tensor even per ring step
            attn = ring.ring_attention_spmd(
                q, k, v, mesh, causal=True, lengths=lengths,
                use_flash=cfg.use_flash_attention and lengths is None)
        elif cfg.use_flash_attention and lengths is None:
            from paddle_tpu.ops.pallas import flash_attention
            attn = flash_attention(q, k, v, causal=True)
        else:
            attn = ring.full_attention(q, k, v, causal=True, lengths=lengths)
        attn = attn.reshape(B, T, cfg.d_model)
        x = x + jnp.einsum("btd,de->bte", attn,
                           w["attn_out"].astype(attn.dtype))
        x = constrain(x)
        h2 = _layer_norm(x, w["ln2"], w["ln2_b"])
        ff = jnp.einsum("btd,df->btf", h2, w["mlp_in"].astype(h2.dtype))
        ff = jax.nn.gelu(ff)
        x = x + jnp.einsum("btf,fd->btd", ff,
                           w["mlp_out"].astype(ff.dtype))
        return constrain(x), None

    x, _ = jax.lax.scan(block, x, params["blocks"])
    x = _layer_norm(x, params["ln_f"], params["ln_f_b"])
    logits = jnp.einsum("btd,vd->btv", x.astype(jnp.float32),
                        params["embed"].astype(jnp.float32))
    return logits


def lm_loss(params, tokens, targets, cfg: TransformerConfig, *,
            mesh: Optional[Mesh] = None,
            lengths: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token cross-entropy over valid positions."""
    logits = forward(params, tokens, cfg, mesh=mesh, lengths=lengths)
    tok_ce = ops_loss.softmax_cross_entropy(logits, targets)
    if lengths is not None:
        mask = (jnp.arange(tokens.shape[1])[None, :] <
                lengths[:, None]).astype(jnp.float32)
    else:
        mask = jnp.ones_like(tok_ce)
    return jnp.sum(tok_ce * mask) / jnp.maximum(jnp.sum(mask), 1.0)
