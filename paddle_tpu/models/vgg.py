"""VGG (reference: benchmark/paddle/image/vgg.py — img_conv_group stacks,
VGG-16/19)."""

from paddle_tpu import activation, layer, pooling


def _conv_group(input, num_convs, num_filters, name, num_channels=None):
    tmp = input
    for i in range(num_convs):
        tmp = layer.img_conv(tmp, filter_size=3, num_filters=num_filters,
                             num_channels=num_channels if i == 0 else None,
                             padding=1, act=activation.Relu(),
                             name=f"{name}_c{i}")
    return layer.img_pool(tmp, 2, stride=2, pool_type=pooling.Max(),
                          name=f"{name}_pool")


_CFG = {11: [1, 1, 2, 2, 2], 13: [2, 2, 2, 2, 2],
        16: [2, 2, 3, 3, 3], 19: [2, 2, 4, 4, 4]}


def vgg(input, depth=19, class_num=1000):
    counts = _CFG[depth]
    tmp = input
    chans = 3
    for i, (n, f) in enumerate(zip(counts, [64, 128, 256, 512, 512])):
        tmp = _conv_group(tmp, n, f, name=f"v{i+1}",
                          num_channels=chans if i == 0 else None)
    fc1 = layer.fc(tmp, 4096, act=activation.Relu(), name="v_fc1")
    d1 = layer.dropout(fc1, 0.5, name="v_drop1")
    fc2 = layer.fc(d1, 4096, act=activation.Relu(), name="v_fc2")
    d2 = layer.dropout(fc2, 0.5, name="v_drop2")
    return layer.fc(d2, class_num, act=activation.Softmax(), name="v_out")


def vgg16(input, class_num=1000):
    return vgg(input, 16, class_num)


def vgg19(input, class_num=1000):
    return vgg(input, 19, class_num)
