"""Image preprocessing utilities (reference: python/paddle/v2/image.py —
load/resize/crop/flip/transform helpers feeding the CHW float pipelines).

PIL + numpy replace the reference's cv2 path; same semantics: images are HWC
uint8 in memory, transformed to CHW float32 for the model.
"""

import io
import tarfile

import numpy as np

__all__ = [
    "load_image_bytes", "load_image", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar",
]


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode encoded image bytes to an HWC (or HW) uint8 array."""
    from PIL import Image
    img = Image.open(io.BytesIO(data))
    img = img.convert("RGB" if is_color else "L")
    return np.asarray(img)


def load_image(path: str, is_color: bool = True) -> np.ndarray:
    with open(path, "rb") as f:
        return load_image_bytes(f.read(), is_color)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the shorter edge equals ``size`` (image.py:150)."""
    from PIL import Image
    h, w = im.shape[:2]
    if h > w:
        new_w, new_h = size, int(round(h * size / w))
    else:
        new_w, new_h = int(round(w * size / h)), size
    mode = "RGB" if im.ndim == 3 else "L"
    out = Image.fromarray(im, mode).resize((new_w, new_h))
    return np.asarray(out)


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """HWC -> CHW (image.py:177)."""
    assert len(im.shape) == len(order)
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True):
    h, w = im.shape[:2]
    h0, w0 = (h - size) // 2, (w - size) // 2
    return im[h0:h0 + size, w0:w0 + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng=None):
    rng = rng or np.random
    h, w = im.shape[:2]
    h0 = int(rng.randint(0, h - size + 1))
    w0 = int(rng.randint(0, w - size + 1))
    return im[h0:h0 + size, w0:w0 + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    return im[:, ::-1]


def simple_transform(im: np.ndarray, resize_size: int, crop_size: int,
                     is_train: bool, is_color: bool = True, mean=None,
                     rng=None) -> np.ndarray:
    """The standard train/eval pipeline (image.py:277): resize-short, then
    random-crop+flip (train) or center-crop (eval), CHW float32, optional
    per-channel or per-pixel mean subtraction."""
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, is_color, rng=rng)
        if rng.randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size, is_color)
    if im.ndim == 3:
        im = to_chw(im)
    im = im.astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1 and im.ndim == 3:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(path: str, resize_size: int, crop_size: int,
                       is_train: bool, is_color: bool = True, mean=None):
    return simple_transform(load_image(path, is_color), resize_size,
                            crop_size, is_train, is_color, mean)


def batch_images_from_tar(data_file: str, dataset_name: str,
                          img2label: dict, num_per_batch: int = 1024):
    """Pre-batch raw images from a tar into pickled batch files
    (image.py:35 — the flowers-style preprocessing cache). Returns the
    meta-file path listing the batch files."""
    import os
    import pickle
    out_path = f"{data_file}_batch"
    meta = os.path.join(out_path, "batch_images_meta")
    if os.path.exists(meta):
        return meta
    os.makedirs(out_path, exist_ok=True)
    data, labels, file_id, names = [], [], 0, []
    with tarfile.open(data_file) as tf:
        for m in tf:
            if m.name not in img2label:
                continue
            data.append(tf.extractfile(m).read())
            labels.append(img2label[m.name])
            if len(data) == num_per_batch:
                name = os.path.join(out_path,
                                    f"batch_{dataset_name}_{file_id}")
                with open(name, "wb") as f:
                    pickle.dump({"data": data, "label": labels}, f,
                                protocol=4)
                names.append(name)
                data, labels, file_id = [], [], file_id + 1
    if data:
        name = os.path.join(out_path, f"batch_{dataset_name}_{file_id}")
        with open(name, "wb") as f:
            pickle.dump({"data": data, "label": labels}, f, protocol=4)
        names.append(name)
    with open(meta, "w") as f:
        f.write("\n".join(names))
    return meta
