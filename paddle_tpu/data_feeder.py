"""Host-side batch assembly: python samples → device-ready Values.

Reference: python/paddle/v2/data_feeder.py:28 (DataFeeder → Arguments) and
py_paddle/dataprovider_converter.py — converts per-slot python data
(dense / sparse / index, with optional sequence nesting per
PyDataProvider2.py:109-250) into the engine's input structures.

TPU-native: everything becomes padded/bucketed numpy, so batch shapes come
from a small fixed set and XLA compiles once per bucket:
- DENSE           -> [b, dim] float32
- INDEX           -> [b] int32
- DENSE seq       -> [b, T] + lengths (T bucketed)
- INDEX seq       -> [b, T] int32 + lengths
- SPARSE_*        -> indices [b, K] + weights [b, K] (K bucketed nonzeros)
- SPARSE_* seq    -> indices [b, T, K] + weights [b, T, K] + lengths
  (reference: sparse_binary_vector_sequence / sparse_float_vector_sequence,
  python/paddle/trainer/PyDataProvider2.py:202,324 — per-timestep sparse
  rows; zero-weight entries are padding so downstream weighted gathers
  are exact without a mask)
"""

from typing import Dict, List, Sequence

import jax.numpy as jnp
import numpy as np

from paddle_tpu.core.ragged import (DEFAULT_BUCKETS, SequenceBatch,
                                    bucket_length, sub_lengths_matrix)
from paddle_tpu.data_type import InputType, Kind, SeqLevel
from paddle_tpu.topology import Value
from paddle_tpu.utils import enforce


class DataFeeder:
    def __init__(self, data_types: Dict[str, InputType],
                 feeding: Dict[str, int] = None, buckets=DEFAULT_BUCKETS):
        """data_types: layer name -> InputType; feeding: name -> index in the
        sample tuple (defaults to declaration order)."""
        self.data_types = data_types
        names = list(data_types)
        self.feeding = feeding or {n: i for i, n in enumerate(names)}
        self.buckets = buckets

    def __call__(self, batch: Sequence) -> Dict[str, Value]:
        return self.feed(batch)

    def _is_prebatched(self, batch) -> bool:
        """True for a tuple of whole-column ndarrays (one per slot, same
        leading batch dim, dense slots with an explicit batch axis) —
        distinguishable from a tuple of per-sample arrays, which fails
        the slot-count or ndim conditions."""
        if not (isinstance(batch, tuple) and batch
                and len(batch) == len(self.data_types)
                and all(isinstance(c, np.ndarray) for c in batch)):
            return False
        lead = set()
        for name, itype in self.data_types.items():
            idx = self.feeding[name]
            if idx >= len(batch):
                return False
            c = batch[idx]
            need = 2 if itype.kind == Kind.DENSE and itype.dim > 1 else 1
            if c.ndim < need:
                return False
            lead.add(c.shape[0])
        return len(lead) == 1

    def feed(self, batch: Sequence) -> Dict[str, Value]:
        feeds = {}
        if self._is_prebatched(batch):
            # pre-batched column arrays (the native batch-assembly path,
            # runtime/loader.dense_batch_reader): one ndarray per slot,
            # consistent leading batch dim, dense columns carrying an
            # explicit batch axis — skip per-sample assembly entirely.
            # (A tuple of per-sample arrays fails the slot-count or ndim
            # checks and falls through to the per-sample path.)
            for name, itype in self.data_types.items():
                col = batch[self.feeding[name]]
                enforce.enforce(
                    itype.kind in (Kind.DENSE, Kind.INDEX)
                    and itype.seq == SeqLevel.NO_SEQUENCE,
                    f"pre-batched feed supports dense/index slots only "
                    f"(slot {name!r})")
                if itype.kind == Kind.INDEX:
                    arr = np.ascontiguousarray(col, dtype=np.int32).reshape(-1)
                    self._check_index_range(arr, itype.dim, name)
                else:
                    arr = np.ascontiguousarray(col, dtype=np.float32)
                feeds[name] = Value(jnp.asarray(arr))
            return feeds
        for name, itype in self.data_types.items():
            col = [sample[self.feeding[name]] for sample in batch]
            feeds[name] = self._convert(col, itype, name)
        return feeds

    @staticmethod
    def _check_index_range(arr: np.ndarray, dim: int, name: str):
        """Out-of-range ids reach the device as clamped gathers / zero
        one-hots and surface as silent NaNs many layers later (the
        reference's DataProviderConverter validates at the boundary,
        py_paddle/dataprovider_converter.py index scanner) — fail here
        with the slot named instead."""
        if not arr.size:
            return
        mn, mx = int(arr.min()), int(arr.max())
        if mn < 0 or mx >= dim:
            raise ValueError(
                f"input '{name}': index {mn if mn < 0 else mx} out of "
                f"range for dimension {dim}")

    def _convert(self, col: List, itype: InputType, name: str = "?") -> Value:
        if itype.seq == SeqLevel.NO_SEQUENCE:
            if itype.kind == Kind.DENSE:
                return Value(jnp.asarray(np.asarray(col, np.float32)))
            if itype.kind == Kind.INDEX:
                arr = np.asarray(col, np.int32)
                self._check_index_range(arr, itype.dim, name)
                return Value(jnp.asarray(arr))
            return self._sparse(col, itype, name)
        if itype.seq == SeqLevel.SUB_SEQUENCE:
            if itype.kind in (Kind.SPARSE_BINARY, Kind.SPARSE_FLOAT):
                # flatten sub-sequences on the time axis (same layout rule
                # as dense/index level-2) and record the split
                flat = [[ts for sub in subs for ts in sub] for subs in col]
                subl = sub_lengths_matrix(col)
                return self._sparse_seq(flat, itype, name,
                                        sub_lengths=jnp.asarray(subl))
            if itype.kind == Kind.INDEX:
                nested = [[np.asarray(s, np.int32) for s in subs]
                          for subs in col]
                for subs in nested:
                    for a in subs:
                        self._check_index_range(a, itype.dim, name)
                sb = SequenceBatch.from_nested_list(nested, self.buckets)
            else:
                sb = SequenceBatch.from_nested_list(
                    [[np.asarray(s, np.float32) for s in subs] for subs in col],
                    self.buckets)
            return Value(sb.data, sb.lengths, sb.sub_lengths)
        # SEQUENCE
        if itype.kind == Kind.INDEX:
            seqs = [np.asarray(s, np.int32) for s in col]
            for a in seqs:
                self._check_index_range(a, itype.dim, name)
            sb = SequenceBatch.from_list(seqs, self.buckets)
        elif itype.kind == Kind.DENSE:
            sb = SequenceBatch.from_list([np.asarray(s, np.float32) for s in col],
                                         self.buckets)
        else:
            return self._sparse_seq(col, itype, name)
        return Value(sb.data, sb.lengths)

    def _sparse_seq(self, col, itype, name: str = "?",
                    sub_lengths=None) -> Value:
        """Per-timestep sparse rows: each sample is a list over timesteps,
        each timestep a list of indices (binary) or (index, value) pairs.
        Both the time axis and the per-timestep nonzero count are bucketed
        so batch shapes stay in a small compiled set."""
        T = bucket_length(max((len(s) for s in col), default=1),
                          self.buckets)
        K = bucket_length(
            max((len(ts) for s in col for ts in s), default=1),
            self.buckets)
        ids = np.zeros((len(col), T, K), np.int32)
        w = np.zeros((len(col), T, K), np.float32)
        lengths = np.zeros((len(col),), np.int32)
        for i, s in enumerate(col):
            lengths[i] = len(s)
            for t, ts in enumerate(s):
                if itype.kind == Kind.SPARSE_BINARY:
                    idx = list(ts)
                    vals = [1.0] * len(idx)
                else:
                    idx = [p[0] for p in ts]
                    vals = [p[1] for p in ts]
                ids[i, t, : len(idx)] = idx
                w[i, t, : len(vals)] = vals
        self._check_index_range(ids, itype.dim, name)
        return Value(jnp.asarray(ids), jnp.asarray(lengths), sub_lengths,
                     weights=jnp.asarray(w))

    def _sparse(self, col, itype, name: str = "?") -> Value:
        """sparse_binary_vector: sample is a list of indices;
        sparse_float_vector: list of (index, value)."""
        k = bucket_length(max((len(s) for s in col), default=1), self.buckets)
        ids = np.zeros((len(col), k), np.int32)
        w = np.zeros((len(col), k), np.float32)
        for i, s in enumerate(col):
            if itype.kind == Kind.SPARSE_BINARY:
                idx = list(s)
                vals = [1.0] * len(idx)
            else:
                idx = [p[0] for p in s]
                vals = [p[1] for p in s]
            ids[i, : len(idx)] = idx
            w[i, : len(vals)] = vals
        self._check_index_range(ids, itype.dim, name)
        return Value(jnp.asarray(ids), weights=jnp.asarray(w))
