"""Layer graph → pure traced function.

This is the heart of the rebuild: the reference compiled a declarative layer
config into a protobuf (python/paddle/trainer/config_parser.py → ModelConfig,
python/paddle/v2/topology.py:27) executed layer-by-layer by a C++
GradientMachine (gserver/gradientmachines/NeuralNetwork.cpp:245,295). Here the
layer graph compiles into **one pure Python function over parameter/state
pytrees**, which jax.jit traces and XLA compiles whole — layer-boundary
scheduling, fusion, and backward construction (framework/backward.cc) all
fall out of the compiler.

Runtime values flow as ``Value`` — an array plus optional sequence metadata —
mirroring the reference's ``Argument`` (value + sequenceStartPositions,
paddle/parameter/Argument.h:26,84).
"""

import dataclasses
import itertools
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.param import ParamSpec
from paddle_tpu.utils import enforce


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class Value:
    """Runtime value of a layer: array + optional sequence metadata
    (the Argument equivalent). For sparse inputs (sparse_binary_vector /
    sparse_float_vector), ``array`` holds padded nonzero indices [b, k] and
    ``weights`` the matching values (0-weight entries are padding) — the
    TPU-native SelectedRows-style representation."""
    array: jax.Array
    lengths: Optional[jax.Array] = None          # [batch] for sequence data
    sub_lengths: Optional[jax.Array] = None      # level-2 LoD
    weights: Optional[jax.Array] = None          # sparse nonzero values
    pre_act: Optional[jax.Array] = None          # logits before the activation
    aux: Optional[dict] = None                   # recipe side-channel (e.g.
                                                 # q8 stash + batch stats)

    def tree_flatten(self):
        return (self.array, self.lengths, self.sub_lengths, self.weights,
                self.pre_act, self.aux), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def is_sequence(self):
        return self.lengths is not None

    @property
    def is_sparse(self):
        return self.weights is not None

    def with_array(self, array, pre_act=None) -> "Value":
        # aux is deliberately NOT carried: it describes the q8 stash of
        # THIS array; any transformed array no longer matches the stash,
        # and consumers must re-enter the pipeline via layer.q8_entry
        return Value(array, self.lengths, self.sub_lengths, self.weights,
                     pre_act)


@dataclasses.dataclass
class ForwardContext:
    """Per-invocation context threaded to every layer forward."""
    is_training: bool = False
    dropout_key: Optional[jax.Array] = None      # folded per layer name
    state_in: Dict[str, jax.Array] = dataclasses.field(default_factory=dict)
    state_out: Dict[str, jax.Array] = dataclasses.field(default_factory=dict)

    def layer_key(self, name: str) -> Optional[jax.Array]:
        if self.dropout_key is None:
            return None
        import zlib
        return jax.random.fold_in(self.dropout_key,
                                  zlib.crc32(name.encode()) & 0x7FFFFFFF)


# recurrent-group builds install a (thread-local) hook to capture every
# LayerOutput created while tracing a step function (memory links resolve by
# name even when the linked layer is not an ancestor of the step outputs —
# e.g. an LSTM cell state carried but never emitted)
_hook_local = threading.local()


def set_layer_creation_hook(fn):
    prev = getattr(_hook_local, "fn", None)
    _hook_local.fn = fn
    return prev

_name_lock = threading.Lock()
_name_counters: Dict[str, "itertools.count"] = {}


def auto_name(layer_type: str) -> str:
    """Unique default layer names (reference: config_parser.py assigned
    __fc_layer_0__ style names)."""
    with _name_lock:
        c = _name_counters.setdefault(layer_type, itertools.count())
        return f"__{layer_type}_{next(c)}__"


class LayerOutput:
    """A node in the layer graph (reference: v2 layer.py LayerOutput /
    config_parser LayerConfig). Holds parents, parameter specs, and a forward
    callable ``fn(params, parent_values, ctx) -> Value``."""

    def __init__(self, name: str, layer_type: str, parents: Sequence["LayerOutput"],
                 fn: Callable, param_specs: Sequence[ParamSpec] = (),
                 size: Optional[int] = None, activation: Optional[str] = None,
                 state_specs: Sequence[ParamSpec] = (), is_data: bool = False,
                 data_spec=None):
        self.name = name
        self.layer_type = layer_type
        self.parents = list(parents)
        self.fn = fn
        self.param_specs = list(param_specs)
        self.state_specs = list(state_specs)   # non-trainable (BN stats)
        self.size = size
        self.activation = activation
        self.is_data = is_data
        self.data_spec = data_spec
        # creation record (api + encoded kwargs) attached by the recorder
        # installed over the public layer API (paddle_tpu.record) — the
        # program save format's rebuild handle
        self.config = None
        hook = getattr(_hook_local, "fn", None)
        if hook is not None:
            hook(self)

    def __repr__(self):
        return f"<{self.layer_type} {self.name} size={self.size}>"


def topo_order(outputs: Sequence[LayerOutput]) -> List[LayerOutput]:
    """Deterministic post-order DFS over the layer DAG."""
    seen, order = set(), []

    def visit(node):
        if id(node) in seen:
            return
        seen.add(id(node))
        for p in node.parents:
            visit(p)
        order.append(node)

    for o in outputs:
        visit(o)
    return order


class Topology:
    """The compiled-model handle (reference: python/paddle/v2/topology.py:27 —
    Topology(cost) extracted the ModelConfig proto; here it extracts param
    specs and builds the traced forward)."""

    def __init__(self, outputs):
        if isinstance(outputs, LayerOutput):
            outputs = [outputs]
        self.outputs: List[LayerOutput] = list(outputs)
        self.layers = topo_order(self.outputs)
        names = [l.name for l in self.layers]
        enforce.enforce(len(names) == len(set(names)),
                        "duplicate layer names: %s" % names)
        self.data_layers = [l for l in self.layers if l.is_data]
        # q8 producers defer their BN affine + activation to the consumer;
        # a q8-unaware consumer would silently train on the raw pre-BN
        # carrier while eval applies the full BN+act — catch at build time
        _q8_aware = {"img_conv_bn_q8", "addto_q8", "q8_exit"}
        for l in self.layers:
            if getattr(l, "_q8", None) is None:
                continue
            for o in self.outputs:
                enforce.enforce(
                    o is not l,
                    f"q8 layer {l.name!r} cannot be a graph output — its "
                    f"BN/activation are deferred; insert layer.q8_exit")
        for l in self.layers:
            for p in l.parents:
                enforce.enforce(
                    getattr(p, "_q8", None) is None
                    or l.layer_type in _q8_aware,
                    f"layer {l.name!r} ({l.layer_type}) consumes q8 "
                    f"producer {p.name!r} but is not q8-aware — insert "
                    f"layer.q8_exit between them")

    # -- specs -------------------------------------------------------------
    def param_specs(self) -> List[ParamSpec]:
        out, seen = [], set()
        for l in self.layers:
            for s in l.param_specs:
                if s.name not in seen:
                    seen.add(s.name)
                    out.append(s)
        return out

    def state_specs(self) -> List[ParamSpec]:
        out, seen = [], set()
        for l in self.layers:
            for s in l.state_specs:
                if s.name not in seen:
                    seen.add(s.name)
                    out.append(s)
        return out

    def data_names(self) -> List[str]:
        return [l.name for l in self.data_layers]

    def find(self, name: str) -> LayerOutput:
        """Address any layer's output by name (the get_output capability:
        reference gserver GetOutputLayer / classify.py --job=extract —
        pass the result as an inference output_layer to extract features
        at that point in the program)."""
        for l in self.layers:
            if l.name == name:
                return l
        raise KeyError(f"no layer named {name!r}; have "
                       f"{[l.name for l in self.layers]}")

    # -- compile -----------------------------------------------------------
    def compile(self, extra_outputs: Sequence[LayerOutput] = ()):
        """Build forward(params, state, feeds, *, is_training, dropout_key)
        -> (outputs: dict name->Value, new_state: dict).

        feeds: dict data-layer-name -> Value (or array). The returned callable
        is pure — jit it, grad it, shard it.
        """
        wanted = list(self.outputs) + list(extra_outputs)
        layers = topo_order(wanted)

        def forward(params: Dict, state: Dict, feeds: Dict, *,
                    is_training: bool = False, dropout_key=None):
            ctx = ForwardContext(is_training=is_training,
                                 dropout_key=dropout_key, state_in=dict(state))
            values: Dict[str, Value] = {}
            for layer in layers:
                with enforce.layer_scope(layer.name):
                    if layer.is_data:
                        v = feeds[layer.name]
                        if not isinstance(v, Value):
                            v = Value(jnp.asarray(v))
                        values[layer.name] = v
                    else:
                        parent_vals = [values[p.name] for p in layer.parents]
                        values[layer.name] = layer.fn(params, parent_vals, ctx)
            # strip pre_act from returned outputs: jit can't DCE returned
            # values, and the logits kept for cost fusion are dead weight
            # once a softmax layer is itself an output
            outs = {o.name: values[o.name].with_array(values[o.name].array)
                    for o in wanted}
            return outs, ctx.state_out

        return forward

    # -- serialization (program save format) --------------------------------
    def to_dict(self):
        """Structural + rebuildable description for merged-model artifacts
        (replaces the ModelConfig proto written next to checkpoints,
        proto/ModelConfig.proto:652). Each layer carries its creation
        record (api + encoded kwargs) when the public API recorded one;
        ``from_dict`` replays those records."""
        return {
            "format_version": 1,
            "outputs": [o.name for o in self.outputs],
            "layers": [
                {
                    "name": l.name, "type": l.layer_type, "size": l.size,
                    "parents": [p.name for p in l.parents],
                    "params": [s.name for s in l.param_specs],
                    "activation": l.activation,
                    "config": l.config,
                } for l in self.layers
            ],
        }

    def is_rebuildable(self):
        """True if every non-data layer carries a creation record."""
        return all(l.config is not None for l in self.layers)

    @classmethod
    def from_dict(cls, d) -> "Topology":
        """Rebuild the layer graph by replaying recorded API calls — the
        merged-model loader's half of the program save format (reference
        slot: config_parser.parse_config re-creating a GradientMachine
        from a saved ModelConfig, paddle/capi/gradient_machine.h:52)."""
        from paddle_tpu import record

        nodes: Dict[str, LayerOutput] = {}
        calls: Dict[int, List[LayerOutput]] = {}
        for entry in d["layers"]:
            cfg = entry.get("config")
            if cfg is None:
                raise ValueError(
                    f"layer {entry['name']!r} ({entry['type']}) has no "
                    f"creation record — this graph cannot be rebuilt from "
                    f"its dict; serve it via the AOT StableHLO export "
                    f"(paddle_tpu.io.merged.save_inference_model(..., "
                    f"export_shapes=...)) instead")
            cid = cfg["call"]
            if cid not in calls:
                fn = record.resolve_api(cfg["api"])
                kwargs = {k: record.decode_value(v, nodes)
                          for k, v in cfg["kwargs"].items()}
                # pin the recorded name so parameters keyed by layer name
                # resolve identically in the rebuilding process (auto_name
                # counters differ between processes)
                import inspect
                if ("name" in inspect.signature(fn).parameters
                        and not kwargs.get("name")
                        and len(cfg["out_names"]) == 1):
                    kwargs["name"] = entry["name"]
                result = fn(**kwargs)
                outs = result if isinstance(result, (list, tuple)) \
                    else [result]
                outs = [o for o in outs if isinstance(o, LayerOutput)]
                calls[cid] = outs
            node = calls[cid][cfg["out_index"]]
            enforce.enforce(
                node.name == entry["name"],
                "rebuilt layer name %r != recorded %r (api %s)"
                % (node.name, entry["name"], cfg["api"]))
            nodes[entry["name"]] = node
        return cls([nodes[n] for n in d["outputs"]])
