"""Projections and operators for mixed_layer.

Reference: paddle/gserver/layers/Projection.h (Projection sub-units summed
into a MixedLayer), Operator.h; config DSL full_matrix_projection,
trans_full_matrix_projection, identity_projection, table_projection,
dotmul_projection, scaling_projection, slice_projection, context_projection,
dotmul_operator (trainer_config_helpers/layers.py).

TPU design: a projection is (input, param specs, apply fn) — mixed_layer sums
the applied arrays in one fused XLA graph; there is no separate Projection
runtime object.
"""

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core.param import ParamAttr, ParamSpec
from paddle_tpu.ops import sequence as ops_seq
from paddle_tpu.topology import auto_name
from paddle_tpu.utils import enforce


@dataclasses.dataclass
class Projection:
    inputs: List                      # LayerOutput parents
    size: int                         # output width
    param_specs: List[ParamSpec]
    apply: Callable                   # (params, parent_values, ctx) -> array


def _attr(param_attr, default_name) -> ParamAttr:
    a = param_attr if isinstance(param_attr, ParamAttr) else ParamAttr()
    if a.name is None:
        a = type(a)(**{**a.__dict__, "name": default_name})
    return a


def full_matrix_projection(input, size: int, param_attr=None) -> Projection:
    """W·x (reference: FullMatrixProjection.cpp)."""
    a = _attr(param_attr, f"{auto_name('fm_proj')}.w")
    spec = ParamSpec(a.name, (input.size, size), attr=a, fan_in=input.size)

    def apply(params, parents, ctx):
        return jnp.matmul(parents[0].array, params[spec.name].astype(
            parents[0].array.dtype))

    return Projection([input], size, [spec], apply)


def trans_full_matrix_projection(input, size: int,
                                 param_attr=None) -> Projection:
    """Wᵀ·x — shares a (size, in) matrix transposed (reference:
    TransposedFullMatrixProjection.cpp; used for tied embeddings)."""
    a = _attr(param_attr, f"{auto_name('tfm_proj')}.w")
    spec = ParamSpec(a.name, (size, input.size), attr=a, fan_in=input.size)

    def apply(params, parents, ctx):
        return jnp.matmul(parents[0].array,
                          params[spec.name].T.astype(parents[0].array.dtype))

    return Projection([input], size, [spec], apply)


def identity_projection(input, offset: Optional[int] = None,
                        size: Optional[int] = None) -> Projection:
    """x, or x[offset:offset+size] (reference: IdentityProjection /
    IdentityOffsetProjection)."""
    out_size = size or (input.size - (offset or 0) if offset is not None
                        else input.size)

    def apply(params, parents, ctx):
        x = parents[0].array
        if offset is not None:
            return x[..., offset:offset + out_size]
        return x

    return Projection([input], out_size, [], apply)


def slice_projection(input, slices: Sequence[Tuple[int, int]]) -> Projection:
    """Concat of [begin, end) column slices (reference: SliceProjection)."""
    out_size = sum(e - b for b, e in slices)

    def apply(params, parents, ctx):
        x = parents[0].array
        return jnp.concatenate([x[..., b:e] for b, e in slices], axis=-1)

    return Projection([input], out_size, [], apply)


def table_projection(input, size: int, param_attr=None) -> Projection:
    """Embedding-table row lookup for integer inputs (reference:
    TableProjection.cpp)."""
    a = _attr(param_attr, f"{auto_name('table_proj')}.w")
    spec = ParamSpec(a.name, (input.size, size), attr=a, fan_in=size)

    def apply(params, parents, ctx):
        ids = parents[0].array.astype(jnp.int32)
        if ids.ndim > 1 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        return jnp.take(params[spec.name], ids, axis=0)

    return Projection([input], size, [spec], apply)


def dotmul_projection(input, param_attr=None) -> Projection:
    """x ⊙ w with a learnable vector (reference: DotMulProjection.cpp)."""
    a = _attr(param_attr, f"{auto_name('dotmul_proj')}.w")
    spec = ParamSpec(a.name, (input.size,), attr=a, fan_in=input.size)

    def apply(params, parents, ctx):
        return parents[0].array * params[spec.name].astype(
            parents[0].array.dtype)

    return Projection([input], input.size, [spec], apply)


def scaling_projection(input, param_attr=None) -> Projection:
    """w·x with a learnable scalar (reference: ScalingProjection.cpp)."""
    a = _attr(param_attr, f"{auto_name('scaling_proj')}.w")
    spec = ParamSpec(a.name, (1,), attr=a)

    def apply(params, parents, ctx):
        return parents[0].array * params[spec.name].astype(
            parents[0].array.dtype)

    return Projection([input], input.size, [spec], apply)


def context_projection(input, context_len: int, context_start=None,
                       padding_attr=False) -> Projection:
    """Sliding context-window concat over a sequence (reference:
    ContextProjection.cpp; paddle/function/ContextProjectionOp.cpp).
    Trainable padding is not supported — zero padding only."""
    enforce.enforce(padding_attr is False or padding_attr is None,
                    "trainable context padding is not supported")
    start = -(context_len // 2) if context_start is None else context_start
    out_size = input.size * context_len

    def apply(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "context_projection needs sequences")
        return ops_seq.context_projection(pv.array, pv.lengths, context_len,
                                          start)

    return Projection([input], out_size, [], apply)


def dotmul_operator(a, b, scale: float = 1.0) -> Projection:
    """scale·(a ⊙ b) (reference: DotMulOperator — a mixed_layer Operator,
    no parameters)."""
    enforce.enforce(a.size == b.size, "dotmul operands must match")

    def apply(params, parents, ctx):
        return scale * parents[0].array * parents[1].array

    return Projection([a, b], a.size, [], apply)
