"""Training-curve plotting (reference: python/paddle/v2/plot/plot.py —
Ploter collecting (step, value) series, matplotlib when available,
DISABLE_PLOT env to run headless)."""

import os

__all__ = ["Ploter", "PlotData"]


class PlotData:
    def __init__(self):
        self.step = []
        self.value = []

    def append(self, step, value):
        self.step.append(step)
        self.value.append(value)

    def reset(self):
        self.step = []
        self.value = []


class Ploter:
    """Collects named cost curves; ``plot()`` renders with matplotlib when
    importable and not disabled, else prints the latest values (headless
    CI behaviour — the reference crashed scripts lacking matplotlib, hence
    its DISABLE_PLOT switch)."""

    def __init__(self, *titles):
        self.__args__ = titles
        self.__plot_data__ = {t: PlotData() for t in titles}
        self._disabled = os.environ.get("DISABLE_PLOT") == "True"
        self._plt = None
        if not self._disabled:
            try:
                import matplotlib
                matplotlib.use("Agg")
                import matplotlib.pyplot as plt
                self._plt = plt
            except Exception:
                self._plt = None

    def append(self, title, step, value):
        self.__plot_data__[title].append(step, value)

    def plot(self, path=None):
        if self._disabled or self._plt is None:
            for t, d in self.__plot_data__.items():
                if d.step:
                    print(f"{t}: step {d.step[-1]} value {d.value[-1]}")
            return
        self._plt.figure()
        for t in self.__args__:
            d = self.__plot_data__[t]
            self._plt.plot(d.step, d.value, label=t)
        self._plt.legend()
        if path:
            self._plt.savefig(path)
        self._plt.close()

    def reset(self):
        for d in self.__plot_data__.values():
            d.reset()
