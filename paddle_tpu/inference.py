"""Inference API (reference: python/paddle/v2/inference.py:10,111 —
Inference prunes the topology to the output layer and runs
forward-only; paddle.infer is the one-call surface)."""

from typing import Dict, List, Optional

import jax
import numpy as np

from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.parameters import Parameters
from paddle_tpu.topology import LayerOutput, Topology


class Inference:
    def __init__(self, output_layer, parameters: Parameters):
        outputs = output_layer if isinstance(output_layer, (list, tuple)) \
            else [output_layer]
        self.topology = Topology(list(outputs))
        self._forward = jax.jit(
            lambda params, state, feeds: self.topology.compile()(
                params, state, feeds, is_training=False)[0])
        self.parameters = parameters

    def iter_infer(self, input, feeding=None, batch_size=None):
        dtypes = {l.name: l.data_spec for l in self.topology.data_layers}
        feeder = DataFeeder(dtypes, feeding)
        batch_size = batch_size or len(input)
        for i in range(0, len(input), batch_size):
            feeds = feeder.feed(input[i:i + batch_size])
            outs = self._forward(self.parameters.values,
                                 self.parameters.state, feeds)
            yield [np.asarray(outs[o.name].array)
                   for o in self.topology.outputs]

    def infer(self, input, field="value", feeding=None, batch_size=None):
        chunks = list(self.iter_infer(input, feeding, batch_size))
        n_out = len(self.topology.outputs)
        results = [np.concatenate([c[i] for c in chunks], axis=0)
                   for i in range(n_out)]
        return results[0] if n_out == 1 else results


def infer(output_layer, parameters, input, feeding=None, field="value",
          batch_size=None):
    """paddle.infer (reference: inference.py:111)."""
    return Inference(output_layer, parameters).infer(
        input, field=field, feeding=feeding, batch_size=batch_size)
