"""Multi-host runtime initialisation — the cluster-training control plane.

Reference: the trainer/pserver process topology was assembled by gflags
(--trainer_id/--num_gradient_servers/--pservers, utils/Flags.cpp:58-81)
and launcher scripts (paddle/scripts/cluster_train/paddle.py SSH fan-out,
submit_local.sh.in); the Go master + etcd coordinated elasticity.

TPU-native: one JAX process per host joins the cluster through
``jax.distributed.initialize`` (coordinator + process id); after that,
``jax.devices()`` is the *global* device set, meshes span hosts, and every
collective rides ICI within a slice and DCN across slices — there is no
trainer/pserver asymmetry to configure. This module wraps that runtime:

- ``init()``         — join the cluster (env-var or explicit args)
- ``hybrid_mesh()``  — ICI x DCN mesh for multi-slice jobs
- the local N-process simulation used by tests/launcher lives in
  paddle_tpu.runtime.launch

Env contract (set by paddle_tpu.runtime.launch or your scheduler):
  PADDLE_COORDINATOR   host:port of process 0
  PADDLE_NUM_PROCESSES total process count
  PADDLE_PROCESS_ID    this process's rank
  PADDLE_LOCAL_CPU_DEVICES  (simulation) CPU device count per process
On real TPU pods all three are discovered from the TPU metadata by JAX and
``init()`` degenerates to ``jax.distributed.initialize()``.
"""

import os
import time
from typing import Optional, Sequence

from paddle_tpu.observe import metrics as _metrics
from paddle_tpu.utils.logger import get_logger

log = get_logger("distributed")

_initialized = False

_m_init_s = _metrics.gauge(
    "distributed_init_seconds", "wall time of jax.distributed.initialize")
_m_procs = _metrics.gauge("distributed_process_count",
                          "processes in the cluster")
_m_devices = _metrics.gauge("distributed_global_devices",
                            "global device count")
_m_barriers = _metrics.counter("distributed_barriers_total",
                               "cross-process barriers entered")
_m_barrier_s = _metrics.histogram(
    "distributed_barrier_seconds",
    "barrier wait time — the straggler detector (BarrierStat slot)")


def is_initialized() -> bool:
    return _initialized


def init(coordinator_address: Optional[str] = None,
         num_processes: Optional[int] = None,
         process_id: Optional[int] = None,
         platform: Optional[str] = None,
         local_cpu_devices: Optional[int] = None) -> None:
    """Join (or create) the multi-host JAX cluster.

    With no arguments, reads the PADDLE_* env contract; with nothing set,
    falls back to JAX auto-detection (TPU pod metadata). Safe to call on a
    single host with no env — it then does nothing, keeping single-process
    semantics.
    """
    global _initialized
    if _initialized:
        log.warning("distributed.init() called twice; ignoring")
        return
    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "PADDLE_COORDINATOR")
    if num_processes is None and os.environ.get("PADDLE_NUM_PROCESSES"):
        num_processes = int(os.environ["PADDLE_NUM_PROCESSES"])
    if process_id is None and os.environ.get("PADDLE_PROCESS_ID"):
        process_id = int(os.environ["PADDLE_PROCESS_ID"])
    platform = platform or os.environ.get("PADDLE_PLATFORM")
    if local_cpu_devices is None and os.environ.get(
            "PADDLE_LOCAL_CPU_DEVICES"):
        local_cpu_devices = int(os.environ["PADDLE_LOCAL_CPU_DEVICES"])

    # simulation mode: force the CPU platform with k virtual devices per
    # process (the JAX_PLATFORMS env var may be overridden by site hooks,
    # so use the config API — same technique as tests/conftest.py)
    if platform:
        jax.config.update("jax_platforms", platform)
    if local_cpu_devices:
        from paddle_tpu.utils.flags import set_xla_host_device_count
        set_xla_host_device_count(local_cpu_devices)
        try:
            jax.config.update("jax_num_cpu_devices", local_cpu_devices)
        except AttributeError:
            pass  # older JAX reads XLA_FLAGS at backend init instead

    t0 = time.perf_counter()
    if coordinator_address is None and num_processes is None:
        # single-host (or TPU-pod auto-detect) path
        try:
            jax.distributed.initialize()
            _initialized = True
            _m_init_s.set(time.perf_counter() - t0)
            _m_procs.set(jax.process_count())
            _m_devices.set(len(jax.devices()))
            log.info("distributed: auto-initialized, %d processes, "
                     "%d global devices", jax.process_count(),
                     len(jax.devices()))
        except Exception as e:  # noqa: BLE001 — single-process fallback
            log.info("distributed: single-process mode (%s)", e)
        return

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id)
    _initialized = True
    _m_init_s.set(time.perf_counter() - t0)
    _m_procs.set(jax.process_count())
    _m_devices.set(len(jax.devices()))
    log.info("distributed: joined as process %d/%d, %d global devices "
             "(%d local)", jax.process_index(), jax.process_count(),
             len(jax.devices()), len(jax.local_devices()))


def shutdown():
    global _initialized
    if _initialized:
        import jax
        jax.distributed.shutdown()
        _initialized = False


_barrier_win = None


def barrier_window(create: bool = True):
    """The raw barrier-wait window this process exports to the gang
    supervisor (heartbeat telemetry): the histogram above has already
    binned the per-rank distribution away, and pooled gang quantiles /
    straggler attribution both need raw samples. Lazy — a process that
    never barriers exports nothing. ``create=False`` peeks."""
    global _barrier_win
    if _barrier_win is None and create:
        from paddle_tpu.observe.window import WindowedQuantiles
        _barrier_win = WindowedQuantiles(window_s=120.0,
                                         max_samples=1024)
    return _barrier_win


def barrier(name: str = "barrier") -> float:
    """Block until every process reaches this point; returns (and
    records) this process's wait in seconds. The per-name histogram is
    the straggler detector the reference built BarrierStat for
    (paddle/utils/Stat.h BarrierStat): a process whose wait is
    consistently near-zero while peers wait long IS the straggler.
    Single-process: returns 0.0 immediately (still counted)."""
    import jax

    wall0 = time.time()
    t0 = time.perf_counter()
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)
    dt = time.perf_counter() - t0
    _m_barriers.inc(name=name)
    _m_barrier_s.observe(dt, name=name)
    barrier_window().observe(dt)
    # barrier waits in the Chrome trace: with pid = process index, the
    # merged multi-host timeline shows exactly which host straggled
    from paddle_tpu.observe import chrome_trace
    chrome_trace.record_span(f"barrier/{name}", wall0, dt)
    # every rank exits a barrier at the same true instant: the first
    # exit per name is this process's clock-alignment mark for the
    # offline gang-trace merge (chrome_trace.merge_traces)
    chrome_trace.note_alignment(f"barrier/{name}", wall0 + dt)
    return dt


def process_index() -> int:
    import jax
    return jax.process_index()


def process_count() -> int:
    import jax
    return jax.process_count()


def hybrid_mesh(ici_shape: Sequence[int], axis_names: Sequence[str],
                dcn_axis: str = "dcn",
                num_slices: Optional[int] = None):
    """ICI x DCN mesh for multi-slice / multi-host jobs.

    ici_shape/axis_names lay out the devices *within* a slice; the leading
    ``dcn_axis`` spans slices (usually the pure-DP axis — gradients cross
    DCN once per step, everything else stays on ICI). Single-slice jobs
    (num_slices==1) get a plain mesh without the DCN axis.

    Replaces: the trainer↔pserver split (sync grads crossed the datacenter
    network via ParameterClient2, pserver/ParameterClient2.h:216); here the
    cross-slice all-reduce is one XLA collective on the dcn axis.
    """
    import jax
    import numpy as np
    from jax.sharding import Mesh

    devices = jax.devices()
    is_cpu_sim = devices[0].platform == "cpu"
    if num_slices is None:
        # slice count from device attributes when present (TPU pods); the
        # CPU backend reports slice_index=0 for every device regardless of
        # process, so in simulation use processes-as-slices instead
        if hasattr(devices[0], "slice_index") and not is_cpu_sim:
            num_slices = len({d.slice_index for d in devices})
        elif jax.process_count() > 1:
            num_slices = jax.process_count()
        else:
            num_slices = 1
    per_slice = int(np.prod(ici_shape))
    if per_slice * num_slices != len(devices):
        raise ValueError(
            f"ici {tuple(ici_shape)} x {num_slices} slices needs "
            f"{per_slice * num_slices} devices, have {len(devices)}")
    if num_slices == 1:
        arr = np.asarray(devices).reshape(tuple(ici_shape))
        return Mesh(arr, tuple(axis_names))
    if hasattr(devices[0], "slice_index") and not is_cpu_sim:
        from jax.experimental import mesh_utils
        arr = mesh_utils.create_hybrid_device_mesh(
            tuple(ici_shape), (num_slices,), devices=devices,
            allow_split_physical_axes=True)
        # create_hybrid_device_mesh puts DCN axes last; move it first
        arr = np.moveaxis(arr, -1, 0)
    else:
        # simulation: group devices by process = slice
        order = sorted(range(len(devices)),
                       key=lambda i: (devices[i].process_index, i))
        arr = np.asarray([devices[i] for i in order]).reshape(
            (num_slices,) + tuple(ici_shape))
    return Mesh(arr, (dcn_axis,) + tuple(axis_names))
