"""The training loop.

Reference: python/paddle/v2/trainer.py:124 SGD.train — per-pass/per-batch loop
driving GradientMachine.forwardBackward + ParameterUpdater over SWIG, firing
user events; plus the C++ Trainer/TrainerInternal
(paddle/trainer/TrainerInternal.cpp:66 trainOneBatch).

TPU-native: the whole batch step — forward, backward, optimizer update,
metric accumulables — is ONE jitted function with donated pytrees, so
parameters never leave device and XLA overlaps everything it can. The Python
loop only feeds data and reads back scalars (the reference crossed the SWIG
boundary per layer call; here the boundary is once per step).
"""

import math
import time
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp

from paddle_tpu import event as events
from paddle_tpu import observe
from paddle_tpu.data_feeder import DataFeeder
from paddle_tpu.evaluator import EvaluatorSet
from paddle_tpu.optimizer import Optimizer
from paddle_tpu.parameters import Parameters
from paddle_tpu.runtime import chaos as _chaos
from paddle_tpu.topology import LayerOutput, Topology, Value
from paddle_tpu.utils import logger
from paddle_tpu.utils.flags import GLOBAL_FLAGS
from paddle_tpu.utils.rng import global_key_source


class _StepMonitor:
    """Per-step observability: wall time, examples/sec, loss, recompile
    tagging, MFU, and memory gauges — fanned out through
    ``observe.report()`` (JSONL sink + handlers), the default metrics
    registry, and the flight recorder's last-K ring. All host work is
    O(1) dict/float ops so instrumentation overhead stays in the noise
    (<5% on the smallnet bench, tested by tests/test_observe.py).

    Recompile accounting is two-sided: the exact jit-cache-miss count
    from the compile tracker (arg-shape signatures; ``compile_count``
    in every record) plus the wall-time outlier heuristic (a step over
    ``outlier_factor`` × the running median of the last ``window``
    steps is tagged ``recompile`` — it also catches slowdowns the
    signature tracker cannot see, e.g. backend-side recompiles)."""

    def __init__(self, window: int = 64, outlier_factor: float = 4.0,
                 opt_state_bytes: int = 0, grad_bytes: int = 0,
                 param_bytes: int = 0):
        self._times = []                     # ring buffer of recent steps
        self._window = window
        self._factor = outlier_factor
        self._idx = 0
        self._opt_bytes = int(opt_state_bytes)
        self._grad_bytes = int(grad_bytes)
        self._param_bytes = int(param_bytes)
        reg = observe.default_registry()
        self.steps = reg.counter(
            "train_steps_total", "optimizer steps taken")
        self.examples = reg.counter(
            "train_examples_total", "training examples consumed")
        self.recompiles = reg.counter(
            "train_recompiles_total",
            "steps tagged as XLA recompiles (step-time outliers)")
        self.step_time = reg.histogram(
            "train_step_seconds", "per-step wall time (dispatch+sync)")
        self.loss_gauge = reg.gauge("train_loss", "last step's mean loss")
        self.mfu_gauge = reg.gauge(
            "train_mfu", "model-FLOPs utilisation of the last step "
            "(lowered-HLO flops / wall / declared peak; 0 until the "
            "step cost is known)")
        self.hbm_gauge = reg.gauge(
            "device_bytes_in_use", "device HBM in use (0 when the backend "
            "hides memory stats, e.g. CPU)")
        self.host_gauge = reg.gauge(
            "host_rss_bytes", "host process resident set size")
        self.opt_bytes_gauge = reg.gauge(
            "opt_state_bytes_per_device",
            "optimizer-state bytes resident on ONE device — under "
            "ZeRO (DistConfig zero_stage>=1) this is ~1/data-axis of "
            "the replicated figure")
        self.grad_bytes_gauge = reg.gauge(
            "grad_bytes_per_device",
            "bytes of the longest-lived gradient object on ONE device "
            "(the accum-scan carry, or the transient grad at the "
            "update point) — ~1/data-axis under ZeRO stage>=2")
        self.param_bytes_gauge = reg.gauge(
            "param_bytes_per_device",
            "parameter bytes resident on ONE device between steps — "
            "~1/data-axis under ZeRO stage 3 (params stored sharded, "
            "all-gathered on use)")
        self.bottleneck_frac = reg.gauge(
            "train_bottleneck_fraction",
            "last step's time split by component (label component = "
            "input|compute|sync; observe/bottleneck.py semantics)")
        self.bottleneck_steps = reg.counter(
            "train_steps_bottleneck_total",
            "steps by bottleneck classification (label bottleneck = "
            "input_bound|compute_bound|sync_bound)")
        # set unconditionally: a stateless-optimizer run must overwrite
        # a previous run's value on the shared registry, not expose it
        self.opt_bytes_gauge.set(self._opt_bytes)
        self.grad_bytes_gauge.set(self._grad_bytes)
        self.param_bytes_gauge.set(self._param_bytes)
        # peak FLOP/s is constant for the process: resolve once, not per
        # step (env read + device lookup + table scan on the hot path)
        self._peak_flops = observe.costs.device_peak_flops()
        # raw step walls for the gang plane: the supervisor pools these
        # ACROSS ranks (never averaging per-rank quantiles), and the
        # straggler detector needs the per-rank distribution, which the
        # histogram above has already binned away
        self.step_window = observe.WindowedQuantiles(window_s=120.0,
                                                     max_samples=512)

    def median(self):
        """Running median step wall over the ring (None before the
        first step) — the goodput accountant's useful-vs-recompile
        split point."""
        if not self._times:
            return None
        return sorted(self._times)[len(self._times) // 2]

    def tag_recompile(self, dt: float) -> bool:
        """Record one step time; True when it is a compile-shaped outlier."""
        times = self._times
        first = not times
        if len(times) < self._window:
            times.append(dt)
        else:
            times[self._idx] = dt
            self._idx = (self._idx + 1) % self._window
        if first:
            return True
        med = sorted(times)[len(times) // 2]
        return dt > self._factor * med and dt > med + 0.01

    def update_memory_gauges(self):
        """Refresh host/device memory gauges (called every log_period —
        device_memory_stats can poke the backend, so not per-step)."""
        from paddle_tpu.utils import memory as mem
        dev = mem.device_memory_stats()
        if dev.get("bytes_in_use"):
            self.hbm_gauge.set(dev["bytes_in_use"])
        host = mem.host_memory_stats()
        if host.get("rss_bytes"):
            self.host_gauge.set(host["rss_bytes"])

    def step(self, *, step, pass_id, batch_id, cost, batch_size, dt,
             flops=None, compile_count=0, feed_s=0.0, dispatch_s=0.0,
             sync_s=0.0):
        """One trained batch: update registry, ring the flight recorder,
        and emit the JSONL record. ``flops`` is the lowered-HLO step
        cost when known (None → MFU reports 0). ``feed_s`` /
        ``dispatch_s`` / ``sync_s`` are the step's span components;
        together with the modeled compute time (flops / peak) they
        classify the step input|compute|sync-bound
        (observe/bottleneck.py)."""
        recompile = self.tag_recompile(dt)
        self.steps.inc()
        self.examples.inc(batch_size)
        self.step_time.observe(dt)
        self.step_window.observe(dt)
        self.loss_gauge.set(cost)
        if recompile:
            self.recompiles.inc()
        eps = batch_size / dt if dt > 0 else 0.0
        mfu = (observe.costs.mfu(flops, dt, self._peak_flops)
               if self._peak_flops else None)
        if mfu is not None:
            self.mfu_gauge.set(mfu)
        est_compute = (flops / self._peak_flops
                       if flops and self._peak_flops else None)
        label, frac = observe.attribute_step(feed_s, dispatch_s, sync_s,
                                             est_compute)
        for comp, f in frac.items():
            self.bottleneck_frac.set(round(f, 6), component=comp)
        if label != "unknown":
            self.bottleneck_steps.inc(bottleneck=label)
        rec = dict(kind="step", step=step, pass_id=pass_id,
                   batch_id=batch_id, loss=round(cost, 6),
                   wall_time_s=round(dt, 6),
                   examples_per_sec=round(eps, 2),
                   mfu=round(mfu, 6) if mfu is not None else 0.0,
                   compile_count=int(compile_count),
                   opt_state_bytes=self._opt_bytes,
                   grad_bytes=self._grad_bytes,
                   param_bytes=self._param_bytes,
                   recompile=recompile,
                   bottleneck=label,
                   frac_input=round(frac["input"], 4),
                   frac_compute=round(frac["compute"], 4),
                   frac_sync=round(frac["sync"], 4))
        # the flight ring ALWAYS sees the step — a post-mortem must not
        # depend on a metrics sink having been configured
        observe.default_flight_recorder().record(rec)
        if observe.has_consumers():
            observe.report(rec)
        return recompile, eps


class SGD:
    """paddle.trainer.SGD (reference: python/paddle/v2/trainer.py:48)."""

    def __init__(self, cost: LayerOutput, parameters: Parameters,
                 update_equation: Optimizer,
                 extra_layers: Optional[List[LayerOutput]] = None,
                 is_local: bool = True, parallel=None,
                 grad_accum_steps: int = 1):
        """parallel: an optional paddle_tpu.parallel.DistConfig — shards
        parameters per its rules and the batch across the data axis; XLA
        inserts the gradient all-reduce (replacing the pserver round-trip,
        reference: trainer/RemoteParameterUpdater.cpp).

        grad_accum_steps: split every batch into this many microbatches
        inside the jitted step (a ``lax.scan``): activations live for one
        microbatch at a time (≈N× less activation memory) while gradients
        accumulate and the optimizer sees the full-batch mean gradient.
        For BN-free, dropout-free models the trajectory matches
        grad_accum_steps=1 up to summation order; batch norm normalizes
        per MICROBATCH (ghost-BN statistics) and dropout draws one mask
        per microbatch, so models using either train on slightly
        different (equally valid) noise. Ragged final batches
        (drop_last=False) fall back to the unaccumulated step."""
        if grad_accum_steps < 1:
            raise ValueError(f"grad_accum_steps must be >= 1, "
                             f"got {grad_accum_steps}")
        self.grad_accum_steps = int(grad_accum_steps)
        self.cost = cost
        self.parameters = parameters
        self.optimizer = update_equation
        self.extra_layers = list(extra_layers or [])
        self.topology = Topology([cost] + self.extra_layers)
        self.optimizer.bind(self.topology.param_specs())
        self._forward = self.topology.compile()
        self._feeder_cache: Dict = {}
        self.opt_state = self.optimizer.init_state(parameters.values)
        self._step = 0
        self.parallel = parallel
        if parallel is not None:
            pv = parameters.values
            parameters.values = parallel.shard_params(pv)
            # zero_stage>=1: state_shardings lays the opt-state leaves of
            # replicated params over the data axis (ZeRO-1) — the same
            # call places them replicated under zero=0
            self.opt_state = jax.device_put(
                self.opt_state, parallel.state_shardings(self.opt_state))
            if parameters.state:
                parameters.state = jax.device_put(
                    parameters.state,
                    jax.tree.map(lambda _: parallel.replicated(),
                                 parameters.state))
            if getattr(parallel, "zero_stage", 0) >= 1:
                rep = parallel.zero_report(parameters.values)
                logger.debug(
                    "zero=%d over %s=%d: %d param states sharded, "
                    "%d replicated (%s)", rep["zero_stage"], rep["axis"],
                    rep["axis_size"], len(rep["sharded"]),
                    len(rep["replicated"]),
                    ", ".join(f"{k}: {v}"
                              for k, v in rep["replicated"].items())
                    or "none")
                # stages 2/3 add grad / stored-param layout decisions —
                # same per-leaf reasons, logged per object class
                for section in ("grads", "params"):
                    view = rep[section]
                    if view["sharded"]:
                        logger.debug(
                            "zero=%d %s: %d sharded, %d replicated (%s)",
                            rep["zero_stage"], section,
                            len(view["sharded"]),
                            len(view["replicated"]),
                            ", ".join(f"{k}: {v}" for k, v in
                                      view["replicated"].items())
                            or "none")
        self._plain_train_step = self._build_train_step()
        self._accum_train_step = (self._build_accum_train_step()
                                  if self.grad_accum_steps > 1 else None)
        self._train_step = self._accum_train_step or self._plain_train_step
        self._eval_step = self._build_eval_step()
        # (fn id, feed signature) -> lowered-HLO flops (or None when the
        # cost model punted); filled lazily, once per signature
        self._step_flops: Dict = {}
        self._last_step_wall = None          # healthz progress probes
        self._last_cost = None
        self.evaluators = EvaluatorSet(self.topology.layers)
        if self.grad_accum_steps > 1 and any(
                getattr(l, "layer_type", "") == "pnpair"
                for l in self.topology.layers):
            logger.warning(
                "grad_accum_steps>1 with a positive_negative_pair "
                "evaluator: pairs spanning microbatch boundaries are not "
                "counted — the metric differs from unaccumulated training")

    # -- compiled steps ----------------------------------------------------
    def _zero_shardings(self):
        """(update, keep, state, compute) sharding dicts for the ZeRO
        constraint points, computed ONCE at step-build time (None under
        zero=0 / local training — the steps then call opt.update
        directly). ``keep`` is the STORED layout updated params return
        to: the serving layout below stage 3, the 1/N shard at stage 3.
        ``compute`` is non-None only at stage 3 — the full/TP layout the
        forward constrains stored shards to (the on-use all-gather)."""
        par = self.parallel
        if par is None or getattr(par, "zero_stage", 0) < 1:
            return None
        values = self.parameters.values
        return (par.zero_update_shardings(values),
                par.store_shardings(values),
                par.state_shardings(self.opt_state),
                par.param_shardings(values) if par.zero_stage >= 3
                else None)

    def _build_train_step(self):
        fwd = self._forward
        opt = self.optimizer
        cost_name = self.cost.name
        par = self.parallel
        zero = self._zero_shardings()

        def train_step(params, opt_state, state, feeds, step, dropout_key):
            def loss_fn(p):
                if zero is not None and zero[3] is not None:
                    # ZeRO-3 gather-on-use: stored 1/N shards constrained
                    # to the compute layout — XLA inserts one all-gather
                    # per leaf at its first use (prefetchable under
                    # earlier layers' compute) and the gather's backward
                    # transpose IS the grad reduce-scatter
                    p = jax.lax.with_sharding_constraint(p, zero[3])
                outs, new_state = fwd(p, state, feeds, is_training=True,
                                      dropout_key=dropout_key)
                per_example = outs[cost_name].array
                return jnp.mean(per_example.astype(jnp.float32)), \
                    (outs, new_state)

            (loss, (outs, new_state)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            if zero is not None:
                # ZeRO: grad reduce-scatters, the update runs on 1/N
                # shards against the sharded opt state, updated params
                # return to the stored layout (all-gather below stage 3,
                # still sharded at stage 3 — parallel/spmd.py)
                from paddle_tpu.parallel import spmd
                new_params, new_opt = spmd.zero_constrained_update(
                    par, opt, step, grads, params, opt_state,
                    update_shardings=zero[0], keep_shardings=zero[1],
                    state_shardings=zero[2])
            else:
                new_params, new_opt = opt.update(step, grads, params,
                                                 opt_state)
            return loss, new_params, new_opt, new_state, outs

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _build_accum_train_step(self):
        """Microbatched step: lax.scan over grad_accum_steps slices of the
        batch; gradients sum in the carry, model state (BN running stats)
        threads sequentially, per-microbatch metric accumulables sum (they
        are additive by contract, evaluator.MetricAccumulator — except
        the batch-local pnpair counts, warned about in __init__)."""
        fwd = self._forward
        opt = self.optimizer
        cost_name = self.cost.name
        n = self.grad_accum_steps
        par = self.parallel
        zero = self._zero_shardings()
        metric_names = [l.name for l in self.topology.layers
                        if hasattr(l, "metric_finalize")]

        def train_step(params, opt_state, state, feeds, step, dropout_key):
            def split(a):
                # indivisible batches never reach this step: the train
                # loop routes them to the plain step (_pick_train_step)
                return a.reshape((n, a.shape[0] // n) + a.shape[1:])

            mfeeds = jax.tree_util.tree_map(split, feeds)
            keys = jax.random.split(dropout_key, n)

            def micro(carry, xs):
                st, acc = carry
                fd, mkey = xs

                def loss_fn(p):
                    if zero is not None and zero[3] is not None:
                        # ZeRO-3: gather stored shards on use, per
                        # microbatch (the gather's transpose reduce-
                        # scatters this microbatch's grad into the
                        # sharded accumulator below)
                        p = jax.lax.with_sharding_constraint(p, zero[3])
                    outs, st2 = fwd(p, st, fd, is_training=True,
                                    dropout_key=mkey)
                    per_example = outs[cost_name].array
                    return jnp.mean(per_example.astype(jnp.float32)), \
                        (outs, st2)

                (loss, (outs, st2)), g = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
                acc = jax.tree_util.tree_map(
                    lambda a, b: a + b.astype(jnp.float32), acc, g)
                if zero is not None:
                    # keep the accumulator ZeRO-sharded through the scan:
                    # each microbatch's grad reduce-scatters into the
                    # shard instead of all-reducing a full copy
                    acc = jax.lax.with_sharding_constraint(acc, zero[0])
                mets = {m: outs[m].array.astype(jnp.float32)
                        for m in metric_names if m in outs}
                return (st2, acc), (loss, mets)

            zeros = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            if zero is not None:
                zeros = jax.lax.with_sharding_constraint(zeros, zero[0])
            (new_state, acc), (losses, mets) = jax.lax.scan(
                micro, (state, zeros), (mfeeds, keys))
            grads = jax.tree_util.tree_map(
                lambda a, p: (a / n).astype(p.dtype), acc, params)
            if zero is not None:
                from paddle_tpu.parallel import spmd
                new_params, new_opt = spmd.zero_constrained_update(
                    par, opt, step, grads, params, opt_state,
                    update_shardings=zero[0], keep_shardings=zero[1],
                    state_shardings=zero[2])
            else:
                new_params, new_opt = opt.update(step, grads, params,
                                                 opt_state)
            outs = {m: Value(v.sum(axis=0)) for m, v in mets.items()}
            return (jnp.mean(losses), new_params, new_opt, new_state, outs)

        return jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _build_eval_step(self):
        fwd = self._forward
        cost_name = self.cost.name

        def eval_step(params, state, feeds):
            outs, _ = fwd(params, state, feeds, is_training=False)
            return jnp.mean(outs[cost_name].array.astype(jnp.float32)), outs

        return jax.jit(eval_step)

    def _pick_train_step(self, feeds):
        """Accumulated step when the batch divides by grad_accum_steps;
        otherwise (ragged drop_last=False tail) the plain step — crashing
        at the end of a pass over a remainder batch is not acceptable."""
        if self._accum_train_step is None:
            return self._plain_train_step
        leaves = jax.tree_util.tree_leaves(feeds)
        # every leaf must share the batch dim AND divide evenly; a future
        # non-batched auxiliary input must fall back to the plain step, not
        # die in the accumulated step's reshape with an XLA shape error
        if (leaves
                and all(l.ndim >= 1 and l.shape[0] == leaves[0].shape[0]
                        for l in leaves)
                and leaves[0].shape[0] % self.grad_accum_steps == 0):
            return self._accum_train_step
        return self._plain_train_step

    def _zero_meta(self):
        """The opt-state layout this trainer runs under, for checkpoint
        manifests (None for local / zero=0 training — older checkpoints
        without the key compare equal)."""
        par = self.parallel
        if par is None or getattr(par, "zero_stage", 0) < 1:
            return None
        return {"zero_stage": int(par.zero_stage),
                "axis": par.batch_axis,
                "axis_size": par.zero_axis_size()}

    def _ckpt_meta(self):
        z = self._zero_meta()
        return {"zero": z} if z is not None else None

    @staticmethod
    def _leaf_shard_bytes(leaf, sharding=None, itemsize=None) -> int:
        """Per-device bytes of one leaf: its shard shape under
        ``sharding`` (the leaf's own by default), times itemsize."""
        shape = tuple(jnp.shape(leaf))
        sharding = sharding if sharding is not None else getattr(
            leaf, "sharding", None)
        if sharding is not None and hasattr(sharding, "shard_shape"):
            shape = sharding.shard_shape(shape)
        if itemsize is None:
            itemsize = getattr(getattr(leaf, "dtype", None),
                               "itemsize", 4)
        n = 1
        for s in shape:
            n *= int(s)
        return n * itemsize

    def opt_state_bytes_per_device(self) -> int:
        """Optimizer-state bytes resident on ONE device: each leaf
        contributes its per-device shard (``sharding.shard_shape``), so
        replicated state counts in full while ZeRO-sharded state counts
        at ~1/axis-size — the number the ``opt_state_bytes_per_device``
        gauge and the zero on/off A/B (benchmarks/zero_bench.py) report."""
        return sum(self._leaf_shard_bytes(leaf) for leaf in
                   jax.tree_util.tree_leaves(self.opt_state))

    def param_bytes_per_device(self) -> int:
        """Parameter bytes resident on ONE device between steps (per-leaf
        ``sharding.shard_shape``): the full replicated figure for pure DP
        / ZeRO<=2, ~1/axis-size under ZeRO-3 where params are stored
        sharded and all-gathered on use — the ``param_bytes_per_device``
        gauge and the per-stage A/B in ``benchmarks/zero_bench.py``."""
        return sum(self._leaf_shard_bytes(leaf) for leaf in
                   jax.tree_util.tree_leaves(self.parameters.values))

    def grad_bytes_per_device(self) -> int:
        """Per-device bytes of the longest-lived gradient object, from
        the sharding plan's LAYOUT COMMITMENT (gradients are
        step-transients in the jitted design — there is no persistent
        grad buffer to measure): under grad accumulation this is the
        fp32 scan-carry accumulator, which rides ZeRO-sharded from
        stage 1 on; without accumulation it is the gradient at the
        update boundary — committed to 1/N by the stage>=2 contract
        (``DistConfig.grad_spec``), the param layout otherwise. XLA may
        transiently materialize a full-shape partial-sum before the
        reduce at any stage; this gauge reports what the plan requires
        to stay live, which is what bounds the accumulator and the
        update's working set."""
        par = self.parallel
        accum = self.grad_accum_steps > 1
        total = 0
        for k, v in self.parameters.values.items():
            sh = None
            if par is not None:
                sh = jax.sharding.NamedSharding(
                    par.mesh,
                    par.grad_spec(k, tuple(jnp.shape(v)), accum=accum))
            total += self._leaf_shard_bytes(
                v, sharding=sh, itemsize=4 if accum else None)
        return total

    def _feeder(self, feeding):
        key = tuple(sorted(feeding.items())) if feeding else None
        if key not in self._feeder_cache:
            dtypes = {l.name: l.data_spec for l in self.topology.data_layers}
            self._feeder_cache[key] = DataFeeder(dtypes, feeding)
        return self._feeder_cache[key]

    def _flops_for(self, step_fn, sig, step_args):
        """Lowered-HLO flops of this step signature (the MFU numerator),
        computed once per signature — one extra trace, no XLA compile —
        and only when an observability consumer exists (metrics sink or
        handler): tracing a big model costs real wall time and nobody
        would read the number."""
        if sig in self._step_flops:
            return self._step_flops[sig]
        if not observe.has_consumers():
            return None
        ca = observe.costs.lowered_cost(step_fn, *step_args)
        flops = ca["flops"] if ca else None
        self._step_flops[sig] = flops
        return flops

    def attach_observability(self, host: str = "127.0.0.1",
                             port: int = 0):
        """Serve ``/metrics`` (default registry, Prometheus text) and
        ``/healthz`` (step progress: step count, last loss, seconds
        since the last finished step, compile count) for this trainer.
        Returns the started ``observe.HealthServer`` — callers own its
        ``close()``. ``port=0`` binds an ephemeral port."""

        def health():
            since = (round(time.perf_counter() - self._last_step_wall, 3)
                     if self._last_step_wall is not None else None)
            return {
                "step": self._step,
                "last_loss": self._last_cost,
                "seconds_since_step": since,
                "compile_count":
                    observe.default_compile_tracker().count("train_step"),
            }

        return observe.HealthServer(health_fn=health, host=host, port=port)

    def _telemetry_doc(self) -> dict:
        """The per-beat gang telemetry payload (supervisor scrape
        transport — ``Heartbeat.set_telemetry``): this rank's registry
        snapshot (counters + gauges; histograms don't aggregate), its
        raw step/barrier windows for the pooled gang quantiles and the
        straggler join, and the goodput accountant's buckets. Runs on
        the beat thread at the heartbeat cadence; all O(registry)
        dict work, no device sync."""
        snap = {name: doc for name, doc in
                observe.default_registry().snapshot().items()
                if doc.get("kind") in ("counter", "gauge")}
        window = {}
        mon = getattr(self, "_monitor", None)
        if mon is not None:
            window["step_time_samples"] = \
                mon.step_window.export_samples()
        from paddle_tpu import distributed as _dist
        bw = _dist.barrier_window(create=False)
        if bw is not None:
            window["barrier_wait_samples"] = bw.export_samples()
        doc = {"snapshot": snap, "window": window}
        acct = getattr(self, "_acct", None)
        if acct is not None:
            gp = acct.snapshot()
            doc["goodput"] = {"buckets": gp["buckets"],
                              "t_start_wall": gp["t_start_wall"]}
        return doc

    # -- public API --------------------------------------------------------
    def train(self, reader, num_passes=1,
              event_handler: Optional[Callable] = None,
              feeding: Optional[Dict[str, int]] = None,
              checkpoint_dir: Optional[str] = None,
              prefetch: int = 0):
        """checkpoint_dir: when set, checkpoints (params + optimizer state +
        model state) are written asynchronously every ``checkpoint_period``
        batches (flag; 0 = once per pass) and training resumes from the
        latest checkpoint found there (reference: ParamUtil per-pass dirs +
        --init_model_path/--start_pass, trainer/ParamUtil.cpp).

        prefetch: >0 feeds through the async input pipeline
        (``paddle_tpu.pipeline``) with a staging ring of that many
        batches — conversion and host→device transfer run on pipeline
        threads so step N+1's feeds are on device while step N executes.
        ``reader`` may also BE a ``pipeline.Pipeline`` (prefetch implied),
        which additionally makes resume exact: the pipeline's stream
        position rides inside every checkpoint and a restore continues
        mid-epoch on the exact next batch. 0 keeps the synchronous
        one-batch-lookahead path.

        Elastic contract: under a supervisor (PADDLE_ELASTIC_DIR set by
        ``runtime/supervisor.py``) this entry is crash-re-enterable —
        it resumes from the latest INTACT checkpoint (torn saves are
        skipped), heartbeats step progress to the supervisor every
        batch, and fences every checkpoint commit on the stamped
        coordination epoch so a zombie from a superseded gang can never
        publish state. The chaos knob (PADDLE_TPU_CHAOS, site ``step``)
        is honored at the top of every batch."""
        event_handler = event_handler or (lambda e: None)
        feeder = self._feeder(feeding)
        from paddle_tpu.pipeline import Pipeline
        pipe, own_pipe = None, False
        if isinstance(reader, Pipeline):
            pipe = reader
        elif prefetch and int(prefetch) > 0:
            # without a checkpoint dir the wrapped pipeline's state can
            # never be consumed — skip the per-batch snapshot entirely
            pipe = Pipeline(reader, prefetch=int(prefetch),
                            track_state=checkpoint_dir is not None)
            own_pipe = True
        if pipe is not None:
            transfer = None
            if self.parallel is not None:
                par = self.parallel

                def transfer(feeds):
                    return jax.device_put(feeds,
                                          par.feed_shardings(feeds))

            pipe.attach(convert=feeder.feed, transfer=transfer)
        ks = global_key_source()
        log_period = GLOBAL_FLAGS.get("log_period", 100)
        # flag-driven JSONL metrics sink (PADDLE_TPU_METRICS_PATH or
        # paddle.init(metrics_path=...)); an explicitly observe.configure()d
        # sink wins, but the flag — which paddle.init may have (re)set to a
        # DIFFERENT path — beats the env-autoconfigured sink and an
        # earlier value of itself
        mpath = GLOBAL_FLAGS.get("metrics_path")
        if mpath and not observe.explicitly_disabled() and (
                observe.sink() is None
                or (observe.sink_source() in ("env", "flag")
                    and observe.sink().path != mpath)):
            observe.configure(mpath, _source="flag")
        self._check_finite = (GLOBAL_FLAGS.get("debug_nans") or
                              GLOBAL_FLAGS.get("debug_infs"))
        # elastic supervision (runtime/supervisor.py env contract):
        # heartbeat step progress + fence checkpoint commits on the
        # stamped coordination epoch; both None outside a supervisor
        hb, fence = None, None
        import os as _os
        if _os.environ.get("PADDLE_ELASTIC_DIR"):
            from paddle_tpu.runtime import supervisor as _sup
            hb = _sup.Heartbeat.from_env()
            fence = _sup.fence_from_env()
        # goodput accounting for this incarnation: the accountant's
        # birth is the "startup ends here" mark the supervisor joins
        # with its launch timestamp, and its buckets ride the heartbeat
        # telemetry into the run-lifetime ledger
        self._acct = observe.StepAccountant()
        if hb is not None and _os.environ.get(
                "PADDLE_GANG_TELEMETRY", "1") != "0":
            hb.set_telemetry(self._telemetry_doc)
        ckpt = None
        if checkpoint_dir is not None:
            from paddle_tpu.io import checkpoint as ckpt_io
            t_restore0 = time.perf_counter()
            latest = ckpt_io.latest_checkpoint(checkpoint_dir)
            if latest:
                (self._step, self.parameters.values, self.opt_state,
                 self.parameters.state) = ckpt_io.load_checkpoint(
                    latest, self.parameters.values, self.opt_state,
                    self.parameters.state)
                if pipe is not None and pipe.track_state:
                    ps = ckpt_io.load_pipeline_state(latest)
                    if ps is not None:
                        # continue the data stream mid-epoch on the
                        # exact next batch (shuffle RNG, shard cursor,
                        # in-flight samples all restored)
                        pipe.load_state_dict(ps)
                if self.parallel is not None:
                    # loaded host arrays must go back to the mesh layout
                    # __init__ applied to the fresh init values; the
                    # checkpoint holds FULL arrays (shards are merged at
                    # load), so this device_put IS the resharding restore
                    # when the mesh or zero layout changed since the save
                    saved = (ckpt_io.checkpoint_meta(latest) or {}
                             ).get("zero")
                    cur = self._zero_meta()
                    if saved != cur:
                        logger.info(
                            "checkpoint opt-state layout %s -> restoring "
                            "into %s (resharding)", saved, cur)
                    self.parameters.values = self.parallel.shard_params(
                        self.parameters.values)
                    self.opt_state = jax.device_put(
                        self.opt_state,
                        self.parallel.state_shardings(self.opt_state))
                    if self.parameters.state:
                        self.parameters.state = jax.device_put(
                            self.parameters.state,
                            jax.tree.map(lambda _: self.parallel.replicated(),
                                         self.parameters.state))
                logger.info("resumed from %s (step %d)", latest, self._step)
                self._acct.add("restore",
                               time.perf_counter() - t_restore0)
            ckpt = ckpt_io.AsyncCheckpointer(checkpoint_dir, fence=fence)

        recorder = observe.default_flight_recorder()
        dumps_before = len(recorder.dumped_paths)
        trained_ok = False
        try:
            self._train_passes(reader, num_passes, event_handler, feeder,
                               ks, log_period, ckpt,
                               GLOBAL_FLAGS.get("checkpoint_period", 0),
                               pipe=pipe, hb=hb)
            trained_ok = True
        except Exception as e:
            # post-mortem for any crash escaping the loop — but only
            # when a flight dir is explicitly configured (a default-on
            # dump would litter artifacts through every failing test and
            # notebook), and not when the NaN tripwire already dumped
            from paddle_tpu.observe import flight as _flight
            if (_flight.configured()
                    and len(recorder.dumped_paths) == dumps_before):
                recorder.dump(reason="exception in training loop", exc=e)
            raise
        finally:
            if hb is not None:
                # only a CLEAN exit is marked done (exempt from the
                # supervisor's staleness judgments); on a crash the
                # beacon just stops, so a process that lingers after a
                # swallowed exception still reads heartbeat_lost
                hb.done() if trained_ok else hb.stop()
            if ckpt is not None:
                ckpt.close()
            if own_pipe:
                pipe.close()   # user-passed pipelines stay open: their
                               # state_dict/resume lifecycle is theirs

    def _prefetch_feeds(self, reader, feeder):
        """One-batch-lookahead feed pipeline: batch N+1 is fed and its
        (asynchronous) host→device transfer dispatched BEFORE batch N is
        yielded, so the transfer rides under batch N's step instead of
        serializing after the step's host sync (the reference's data
        providers double-buffer into the trainer the same way —
        PyDataProvider2.cpp:195 async pool). jax.device_put returns
        immediately with the copy in flight; the step that consumes the
        buffer joins it on-device."""
        prev = None
        it = iter(reader())
        while True:
            try:
                data_batch = next(it)
                # feed() already dispatches the H2D copies (jnp.asarray
                # is asynchronous); the sharded put is likewise async
                with observe.trace_scope("feed"):
                    with observe.trace_scope("convert"):
                        feeds = feeder.feed(data_batch)
                    if self.parallel is not None:
                        with observe.trace_scope("transfer"):
                            feeds = jax.device_put(
                                feeds,
                                self.parallel.feed_shardings(feeds))
            except StopIteration:
                break
            except Exception:
                # batch N is already fed; train it before surfacing
                # batch N+1's failure, or the crash would both lose N
                # and point at the wrong batch index
                if prev is not None:
                    yield prev
                    prev = None
                raise
            if prev is not None:
                yield prev
            prev = feeds
        if prev is not None:
            yield prev

    def _train_passes(self, reader, num_passes, event_handler, feeder, ks,
                      log_period, ckpt, period, pipe=None, hb=None):
        monitor = _StepMonitor(
            opt_state_bytes=self.opt_state_bytes_per_device(),
            grad_bytes=self.grad_bytes_per_device(),
            param_bytes=self.param_bytes_per_device())
        # published so the heartbeat telemetry thread can export the
        # raw step window (gang pooling + straggler attribution)
        self._monitor = monitor
        acct = getattr(self, "_acct", None)
        if acct is None:
            acct = self._acct = observe.StepAccountant()
        for pass_id in range(num_passes):
            event_handler(events.BeginPass(pass_id))
            self.evaluators.reset()
            pass_t0 = time.perf_counter()
            pass_examples = 0
            # pipelined mode: one iter() == one epoch, resuming mid-epoch
            # after a restore; feeds arrive converted + device-resident
            feed_iter = (iter(pipe) if pipe is not None
                         else self._prefetch_feeds(reader, feeder))
            batch_id = -1
            while True:
                # feed wait timed explicitly: the input component of the
                # step's bottleneck attribution (sync path: convert+H2D
                # of the NEXT batch; pipelined: the staging-ring get)
                feed_t0 = time.perf_counter()
                try:
                    feeds = next(feed_iter)
                except StopIteration:
                    break
                feed_s = time.perf_counter() - feed_t0
                batch_id += 1
                # chaos site 'step': kill/hang/crash BEFORE the step
                # executes, so "kill at step k" means exactly k steps
                # are committed (runtime/chaos.py; no-op without the
                # PADDLE_TPU_CHAOS env knob)
                _chaos.maybe_trigger("step", step=self._step)
                event_handler(events.BeginIteration(pass_id, batch_id))
                step_fn = self._pick_train_step(feeds)
                # feed-shape signature: params/opt/state shapes are fixed
                # per run, so the feeds (plus which step fn) fully key the
                # jit cache entry — an unseen signature IS a compile
                sig = (id(step_fn),) + observe.arg_signature(feeds)
                dropout_key = ks.step("dropout", self._step)
                step_args = (self.parameters.values, self.opt_state,
                             self.parameters.state, feeds,
                             jnp.asarray(self._step, jnp.int32),
                             dropout_key)
                # the one-time cost retrace stays OUTSIDE the timed
                # window: a seconds-long trace of a big model must not
                # masquerade as step wall time in the metrics
                flops = self._flops_for(step_fn, sig, step_args)
                step_t0 = time.perf_counter()
                with observe.step_scope(self._step, "train_step"):
                    with observe.trace_scope("dispatch"):
                        (loss, self.parameters.values, self.opt_state,
                         self.parameters.state, outs) = step_fn(*step_args)
                dispatch_s = time.perf_counter() - step_t0
                self._step += 1
                self.evaluators.add_batch(outs)
                # float(loss) is the host sync — per-step wall time must
                # include it or async dispatch hides the real step time
                sync_t0 = time.perf_counter()
                with observe.trace_scope("host_sync"):
                    cost = float(loss)
                sync_s = time.perf_counter() - sync_t0
                step_dt = time.perf_counter() - step_t0
                tracker = observe.default_compile_tracker()
                n0 = tracker.count("train_step")
                tracker.record("train_step", sig, step_dt)
                # goodput split: an unseen signature IS a compile — the
                # steady median stays useful, the excess is recompile
                acct.step(step_dt, feed_s=feed_s,
                          compile_miss=tracker.count("train_step") > n0,
                          median_s=monitor.median())
                self._last_step_wall = time.perf_counter()
                self._last_cost = cost
                if hb is not None:
                    # step-progress lease for the elastic supervisor: a
                    # wedged worker keeps the liveness thread beating
                    # but this step counter stalls (wedge_window)
                    hb.beat(self._step)
                bs = int(next(iter(feeds.values())).array.shape[0])
                pass_examples += bs
                _, eps = monitor.step(
                    step=self._step - 1, pass_id=pass_id, batch_id=batch_id,
                    cost=cost, batch_size=bs, dt=step_dt, flops=flops,
                    compile_count=tracker.count("train_step"),
                    feed_s=feed_s, dispatch_s=dispatch_s, sync_s=sync_s)
                if self._check_finite and not math.isfinite(cost):
                    from paddle_tpu.utils import enforce
                    try:
                        enforce.check_numerics(self.parameters.values,
                                               "param")
                        raise enforce.EnforceError(
                            f"non-finite cost {cost} at pass {pass_id} "
                            f"batch {batch_id} (params are finite — check "
                            f"inputs/loss)")
                    except enforce.EnforceError as e:
                        # the NaN tripwire is a flight-recorder trigger:
                        # leave the post-mortem before the raise unwinds
                        observe.default_flight_recorder().dump(
                            reason=f"non-finite cost {cost} (debug_nans "
                                   f"tripwire)", exc=e)
                        raise
                if log_period and batch_id % log_period == 0:
                    monitor.update_memory_gauges()
                    logger.info("pass %d batch %d cost %.5f %s "
                                "(%.1f ex/s)", pass_id, batch_id, cost,
                                self.evaluators.result(), eps)
                event_handler(events.EndIteration(
                    pass_id, batch_id, cost, self.evaluators,
                    wall_time_s=step_dt, examples_per_sec=eps))
                if ckpt is not None and period and self._step % period == 0:
                    # only the synchronous part (device->host snapshot
                    # + enqueue) is checkpoint overhead — the async
                    # write overlaps the next steps
                    save_t0 = time.perf_counter()
                    ckpt.save(self._step, self.parameters.values,
                              self.opt_state, self.parameters.state,
                              pipeline_state=(
                                  pipe.state_dict() if pipe is not None
                                  and pipe.track_state else None),
                              meta=self._ckpt_meta())
                    acct.add("checkpoint_save",
                             time.perf_counter() - save_t0)
            if ckpt is not None and not period:
                save_t0 = time.perf_counter()
                ckpt.save(self._step, self.parameters.values,
                          self.opt_state, self.parameters.state,
                          pipeline_state=(
                              pipe.state_dict() if pipe is not None
                              and pipe.track_state else None),
                          meta=self._ckpt_meta())
                acct.add("checkpoint_save",
                         time.perf_counter() - save_t0)
            monitor.update_memory_gauges()
            pass_dt = time.perf_counter() - pass_t0
            if observe.has_consumers():
                mets = {}
                for k, v in (self.evaluators.result() or {}).items():
                    try:
                        mets[k] = float(v)
                    except (TypeError, ValueError):
                        pass
                observe.report(
                    kind="pass", pass_id=pass_id, step=self._step,
                    wall_time_s=round(pass_dt, 6), examples=pass_examples,
                    examples_per_sec=round(
                        pass_examples / pass_dt if pass_dt > 0 else 0.0, 2),
                    recompiles=int(monitor.recompiles.value()),
                    metrics=mets)
                s = observe.sink()
                if s is not None:
                    s.flush()      # a finished pass must be tail-able
            event_handler(events.EndPass(pass_id, self.evaluators))

    def test(self, reader, feeding: Optional[Dict[str, int]] = None):
        """One evaluation sweep (reference: trainer.py:204 SGD.test)."""
        feeder = self._feeder(feeding)
        self.evaluators.reset()
        total, n = 0.0, 0
        for data_batch in reader():
            feeds = feeder.feed(data_batch)
            if self.parallel is not None:
                feeds = jax.device_put(feeds,
                                       self.parallel.feed_shardings(feeds))
            loss, outs = self._eval_step(self.parameters.values,
                                         self.parameters.state, feeds)
            self.evaluators.add_batch(outs)
            # record count: pre-batched column tuples carry it in the
            # leading axis; sample lists in their length
            if isinstance(data_batch, tuple):
                bs = int(next(iter(feeds.values())).array.shape[0])
            else:
                bs = len(data_batch)
            total += float(loss) * bs
            n += bs
        return events.TestResult(self.evaluators,
                                 cost=total / max(n, 1))

    def save_parameter_to_tar(self, f):
        self.parameters.to_tar(f)
