"""Dynamic RNN DSL: recurrent_group / memory / beam_search.

The RecurrentGradientMachine equivalent. Reference:
- recurrent_group + memory + beam_search config DSL:
  python/paddle/trainer_config_helpers/layers.py (recurrent_group, memory,
  beam_search, StaticInput, GeneratedInput)
- engine: paddle/gserver/gradientmachines/RecurrentGradientMachine.h:32
  (per-step layer-subgraph execution with memory links, generation +
  beamSearch at .h:307-309), operators/recurrent_op.cc (StepScopes).

TPU design: the step function defines a layer *sub-graph* once; it is traced
and run under ``lax.scan`` over the time axis (training/inference over given
sequences) or under the fixed-width ``ops.beam.beam_search`` while_loop
(generation). Memories are scan carries gathered per beam — not per-step
Scopes. Variable lengths are handled by masking the carry, so one compiled
program serves every batch of sequences.
"""

import dataclasses
import threading
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp

from paddle_tpu.core.param import ParamAttr, ParamSpec
from paddle_tpu.ops import beam as ops_beam
from paddle_tpu.ops import sequence as ops_seq
from paddle_tpu.topology import LayerOutput, Value, auto_name, topo_order
from paddle_tpu.utils import enforce

_build_ctx = threading.local()


@dataclasses.dataclass
class _Memory:
    node: LayerOutput            # placeholder node used inside the step graph
    link_name: str               # layer whose output feeds the next step
    size: int
    boot: Optional[LayerOutput]  # evaluated outside the group
    boot_const: Optional[float]


class StaticInput:
    """Non-sequence input broadcast to every step (reference: StaticInput,
    trainer_config_helpers/layers.py)."""

    def __init__(self, input: LayerOutput, is_seq: bool = False):
        self.input = input
        self.is_seq = is_seq  # a whole sequence visible at every step


class GeneratedInput:
    """Generation-mode input: at each step, the embedding of the previously
    generated token (reference: GeneratedInput — embedding_name/size)."""

    def __init__(self, size: int, embedding_name: str, embedding_size: int):
        self.size = size              # vocab size
        self.embedding_name = embedding_name
        self.embedding_size = embedding_size


def _placeholder(name: str, size: int) -> LayerOutput:
    return LayerOutput(name, "step_input", [], fn=None, size=size,
                       is_data=True)


def memory(name: str, size: int, boot_layer: Optional[LayerOutput] = None,
           boot_with_const_value: Optional[float] = None,
           is_seq: bool = False) -> LayerOutput:
    """Inside a step function: the value of layer ``name`` at the previous
    step (boot value at t=0). Reference: memory() in
    trainer_config_helpers/layers.py; RecurrentGradientMachine memory links.
    """
    ctx = getattr(_build_ctx, "group", None)
    enforce.enforce(ctx is not None,
                    "memory() must be called inside a recurrent_group/"
                    "beam_search step function")
    node = _placeholder(auto_name(f"memory_{name}"), size)
    ctx.append(_Memory(node, name, size, boot_layer, boot_with_const_value))
    return node


def _build_step_graph(step: Callable, placeholders: Sequence[LayerOutput]):
    """Run the user step fn collecting memories; returns (outputs, memories,
    step_layers in topo order)."""
    import paddle_tpu.topology as topo_mod
    prev_group = getattr(_build_ctx, "group", None)
    _build_ctx.group = []
    created: List[LayerOutput] = []
    prev_hook = topo_mod.set_layer_creation_hook(created.append)
    try:
        outs = step(*placeholders)
    finally:
        topo_mod.set_layer_creation_hook(prev_hook)
        memories: List[_Memory] = _build_ctx.group
        _build_ctx.group = prev_group
    outs = [outs] if isinstance(outs, LayerOutput) else list(outs)
    # roots: step outputs + memory-linked layers (a carried state like an
    # LSTM cell may not be an ancestor of the emitted output)
    link_names = {m.link_name for m in memories}
    roots = list(outs) + [l for l in created if l.name in link_names]
    layers = topo_order(roots)
    by_name = {l.name: l for l in layers}
    for m in memories:
        enforce.enforce(m.link_name in by_name,
                        f"memory links to layer '{m.link_name}' which is not "
                        f"produced by the step function")
    return outs, memories, layers


def _run_step_layers(layers, params, feed_values: Dict[str, Value], ctx):
    """Execute the step sub-graph once given placeholder feed values."""
    values = dict(feed_values)
    for layer in layers:
        if layer.name in values:
            continue
        if layer.is_data:
            raise enforce.EnforceError(
                f"step sub-graph data layer '{layer.name}' was not fed — "
                f"pass it through recurrent_group(input=...) instead of "
                f"closing over it")
        parent_vals = [values[p.name] for p in layer.parents]
        values[layer.name] = layer.fn(params, parent_vals, ctx)
    return values


def _collect_params(layers) -> List[ParamSpec]:
    out, seen = [], set()
    for l in layers:
        for s in l.param_specs:
            if s.name not in seen:
                seen.add(s.name)
                out.append(s)
    return out


def recurrent_group(step: Callable, input, reverse: bool = False,
                    name: Optional[str] = None) -> LayerOutput:
    """Run a step sub-graph over a sequence with memory links.

    ``input``: sequence layer(s) and/or StaticInput wrappers. The step
    function receives one placeholder per input (the t-th token of sequence
    inputs; the whole value of static inputs) and may call ``memory()``.
    Returns the sequence of (first) step outputs.
    """
    from paddle_tpu import layer as layer_mod  # noqa: F401 (API surface)
    name = name or auto_name("recurrent_group")
    raw_inputs = input if isinstance(input, (list, tuple)) else [input]
    seq_inputs: List[LayerOutput] = []
    static_inputs: List[StaticInput] = []
    placeholders = []
    for i, ri in enumerate(raw_inputs):
        if isinstance(ri, StaticInput):
            static_inputs.append(ri)
            placeholders.append(_placeholder(f"{name}@static{i}", ri.input.size))
        else:
            seq_inputs.append(ri)
            placeholders.append(_placeholder(f"{name}@in{i}", ri.size))
    enforce.enforce(seq_inputs, "recurrent_group needs >=1 sequence input")

    outs, memories, step_layers = _build_step_graph(step, placeholders)
    out0 = outs[0]
    specs = _collect_params(step_layers)
    boot_parents = [m.boot for m in memories if m.boot is not None]
    parents = seq_inputs + [s.input for s in static_inputs] + boot_parents

    # placeholder name mapping for fn-time feeds
    seq_ph = [p for p, ri in zip(placeholders, raw_inputs)
              if not isinstance(ri, StaticInput)]
    static_ph = [p for p, ri in zip(placeholders, raw_inputs)
                 if isinstance(ri, StaticInput)]

    def fwd(params, parent_vals, ctx):
        n_seq = len(seq_inputs)
        n_static = len(static_inputs)
        seq_vals = parent_vals[:n_seq]
        static_vals = parent_vals[n_seq:n_seq + n_static]
        boot_vals = parent_vals[n_seq + n_static:]
        lengths = seq_vals[0].lengths
        enforce.enforce(lengths is not None,
                        "recurrent_group input must be a sequence")
        B, T = seq_vals[0].array.shape[:2]

        xs = [sv.array if not reverse
              else ops_seq.seq_reverse(sv.array, lengths)
              for sv in seq_vals]

        # initial memories
        boot_iter = iter(boot_vals)
        init_mem = []
        for m in memories:
            if m.boot is not None:
                init_mem.append(next(boot_iter).array)
            else:
                fill = m.boot_const or 0.0
                dt = (xs[0].dtype if jnp.issubdtype(xs[0].dtype, jnp.floating)
                      else jnp.float32)
                init_mem.append(jnp.full((B, m.size), fill, dt))
            enforce.enforce(init_mem[-1].shape[-1] == m.size,
                            f"memory '{m.link_name}' boot size mismatch")

        def scan_step(carry, inp):
            mems, t = carry, inp[-1]
            x_ts = inp[:-1]
            feeds = {}
            for ph, x_t in zip(seq_ph, x_ts):
                feeds[ph.name] = Value(x_t)
            for ph, sv in zip(static_ph, static_vals):
                feeds[ph.name] = sv
            for m, mv in zip(memories, mems):
                feeds[m.node.name] = Value(mv)
            values = _run_step_layers(step_layers, params, feeds, ctx)
            alive = (t < lengths)[:, None]
            new_mems = tuple(
                jnp.where(alive, values[m.link_name].array, mv)
                for m, mv in zip(memories, mems))
            return new_mems, tuple(values[o.name].array for o in outs)

        ts = jnp.arange(T)
        xs_t = tuple(jnp.swapaxes(x, 0, 1) for x in xs) + (ts,)
        _, ys = jax.lax.scan(scan_step, tuple(init_mem), xs_t)
        y = jnp.swapaxes(ys[0], 0, 1)          # [B, T, F]
        if reverse:
            y = ops_seq.seq_reverse(y, lengths)
        return Value(y, lengths)

    return LayerOutput(name, "recurrent_group", parents, fwd, specs,
                       size=out0.size, activation=out0.activation)


def beam_search(step: Callable, input, bos_id: int, eos_id: int,
                beam_size: int = 5, max_length: int = 100,
                name: Optional[str] = None,
                length_penalty: float = 0.0) -> LayerOutput:
    """Generation with fixed-width beam search.

    ``input``: exactly one GeneratedInput plus any StaticInput wrappers.
    The step function receives (per GeneratedInput) the embedding of the
    previous token and must return a softmax (or logit) layer over the
    vocabulary. Output Value: tokens [batch, beam, max_length] with
    per-beam lengths in ``sub_lengths`` and scores stored in ``weights``.
    Reference: beam_search DSL (trainer_config_helpers/layers.py),
    RecurrentGradientMachine::beamSearch, beam_search_op.cc.
    """
    name = name or auto_name("beam_search")
    raw_inputs = input if isinstance(input, (list, tuple)) else [input]
    gen: Optional[GeneratedInput] = None
    static_inputs: List[StaticInput] = []
    placeholders = []
    for i, ri in enumerate(raw_inputs):
        if isinstance(ri, GeneratedInput):
            enforce.enforce(gen is None, "only one GeneratedInput allowed")
            gen = ri
            placeholders.append(_placeholder(f"{name}@gen", ri.embedding_size))
        elif isinstance(ri, StaticInput):
            static_inputs.append(ri)
            placeholders.append(_placeholder(f"{name}@static{i}", ri.input.size))
        else:
            raise enforce.EnforceError(
                "beam_search inputs must be GeneratedInput/StaticInput")
    enforce.enforce(gen is not None, "beam_search needs a GeneratedInput")

    outs, memories, step_layers = _build_step_graph(step, placeholders)
    out0 = outs[0]
    enforce.enforce(out0.size == gen.size,
                    f"step output size {out0.size} != vocab {gen.size}")
    specs = _collect_params(step_layers)
    emb_spec = ParamSpec(gen.embedding_name, (gen.size, gen.embedding_size),
                         attr=ParamAttr(name=gen.embedding_name),
                         fan_in=gen.embedding_size)
    if gen.embedding_name not in {s.name for s in specs}:
        specs = specs + [emb_spec]
    boot_parents = [m.boot for m in memories if m.boot is not None]
    parents = [s.input for s in static_inputs] + boot_parents

    gen_ph = placeholders[[isinstance(r, GeneratedInput)
                           for r in raw_inputs].index(True)]
    static_ph = [p for p, ri in zip(placeholders, raw_inputs)
                 if isinstance(ri, StaticInput)]
    V, K = gen.size, beam_size

    def fwd(params, parent_vals, ctx):
        n_static = len(static_inputs)
        static_vals = parent_vals[:n_static]
        boot_vals = parent_vals[n_static:]
        B = (static_vals[0].array.shape[0] if static_vals
             else boot_vals[0].array.shape[0])

        def tile_beam(x):
            return jnp.broadcast_to(x[:, None], (B, K) + x.shape[1:])

        boot_iter = iter(boot_vals)
        mem0 = {}
        for m in memories:
            if m.boot is not None:
                mem0[m.link_name] = tile_beam(next(boot_iter).array)
            else:
                mem0[m.link_name] = jnp.full((B, K, m.size),
                                             m.boot_const or 0.0, jnp.float32)

        def step_fn(last_tok, mems):
            flat_tok = last_tok.reshape(B * K)
            emb = jnp.take(params[gen.embedding_name], flat_tok, axis=0)
            feeds = {gen_ph.name: Value(emb)}
            for ph, sv in zip(static_ph, static_vals):
                arr = sv.array
                flat = jnp.broadcast_to(arr[:, None], (B, K) + arr.shape[1:])
                flat = flat.reshape((B * K,) + arr.shape[1:])
                lens = (jnp.repeat(sv.lengths, K) if sv.is_sequence
                        else None)
                feeds[ph.name] = Value(flat, lens)
            for m in memories:
                feeds[m.node.name] = Value(
                    mems[m.link_name].reshape((B * K, -1)))
            values = _run_step_layers(step_layers, params, feeds, ctx)
            ov = values[out0.name]
            if ov.pre_act is not None:
                logp = jax.nn.log_softmax(ov.pre_act.astype(jnp.float32), -1)
            elif out0.activation == "softmax":
                logp = jnp.log(jnp.maximum(ov.array.astype(jnp.float32),
                                           1e-30))
            else:
                logp = jax.nn.log_softmax(ov.array.astype(jnp.float32), -1)
            new_mems = {m.link_name:
                        values[m.link_name].array.reshape(B, K, -1)
                        for m in memories}
            return logp.reshape(B, K, V), new_mems

        tokens, lengths, scores = ops_beam.beam_search(
            step_fn, mem0, B, K, V, bos_id, eos_id, max_length,
            length_penalty=length_penalty)
        return Value(tokens, lengths=None, sub_lengths=lengths,
                     weights=scores)

    return LayerOutput(name, "beam_search", parents, fwd, specs,
                       size=max_length)
