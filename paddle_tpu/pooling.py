"""Sequence-pooling type declarations (reference: python/paddle/
trainer_config_helpers/poolings.py — MaxPooling, AvgPooling, SumPooling,
SqrtAvgPooling; runtime impls in paddle_tpu.ops.sequence)."""


class BasePoolingType:
    name = None


class Max(BasePoolingType):
    name = "max"


class Avg(BasePoolingType):
    name = "avg"


class Sum(BasePoolingType):
    name = "sum"


class SqrtN(BasePoolingType):
    name = "sqrt"


MaxPooling = Max
AvgPooling = Avg
SumPooling = Sum
SqrtAvgPooling = SqrtN


def resolve(p) -> str:
    if p is None:
        return "max"
    if isinstance(p, str):
        return p
    return p.name
