"""paddle.batch (reference: python/paddle/v2/minibatch.py)."""


def batch(reader_fn, batch_size, drop_last=True):
    """Group samples into lists of batch_size. drop_last defaults True on TPU:
    a ragged final batch would trigger an extra XLA compilation for one step
    (the reference kept it; static shapes argue otherwise)."""
    def reader():
        b = []
        for sample in reader_fn():
            b.append(sample)
            if len(b) == batch_size:
                yield b
                b = []
        if b and not drop_last:
            yield b
    return reader
