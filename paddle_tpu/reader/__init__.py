"""Reader framework (reference: python/paddle/v2/reader/ — a reader is a
zero-arg callable returning an iterable of samples; decorators compose them)."""

from paddle_tpu.reader import creator
from paddle_tpu.reader import minibatch
from paddle_tpu.reader.decorator import (
    buffered, cache, chain, compose, firstn, map_readers, shuffle,
    xmap_readers,
)
