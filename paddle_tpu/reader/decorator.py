"""Reader decorators (reference: python/paddle/v2/reader/decorator.py:26-292
— map_readers, shuffle, chain, compose, buffered, firstn, xmap_readers)."""

import itertools
import queue
import random
import threading

from paddle_tpu.utils.threadq import put_stoppable as _put_stoppable


def map_readers(func, *readers):
    def reader():
        for items in zip(*[r() for r in readers]):
            yield func(*items)
    return reader


_shuffle_ids = itertools.count()


def shuffle(reader_fn, buf_size, seed=None):
    """Pool-shuffle within a bounded buffer (reference: decorator.py:68).

    Seeding: an explicit ``seed`` wins; else the framework seed flag (set by
    ``paddle.init(seed=...)``) makes seeded runs reproducible end-to-end; else
    the global ``random`` module is used, preserving the reference's
    ``random.seed()``-before-building-readers idiom. Each shuffle() call and
    each pass derive distinct orders (decoration id + pass count folded in)."""
    dec_id = next(_shuffle_ids)
    calls = itertools.count()

    def reader():
        n = next(calls)
        base = seed
        if base is None:
            from paddle_tpu.utils.flags import GLOBAL_FLAGS
            s = GLOBAL_FLAGS.get("seed", 0)
            base = s if s else None
        if base is None:
            rng = random  # reference behavior: the global random module
        else:
            rng = random.Random((base * 1000003 + dec_id) * 1000003 + n)
        buf = []
        for e in reader_fn():
            buf.append(e)
            if len(buf) >= buf_size:
                rng.shuffle(buf)
                yield from buf
                buf = []
        rng.shuffle(buf)
        yield from buf
    return reader


def chain(*readers):
    def reader():
        return itertools.chain(*[r() for r in readers])
    return reader


def compose(*readers, check_alignment=True):
    """Zip readers into tuple samples (reference: decorator.py:125)."""
    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        for items in zip(*rs):
            yield sum((make_tuple(i) for i in items), ())
    return reader


def _close_workers(queues, threads, stop):
    """Generator-close path: join the worker threads (waking any blocked
    put by draining), warning instead of hanging when one is stuck
    inside user code — close() must always return."""
    from paddle_tpu.utils.threadq import drain_join
    leaked = drain_join(queues, threads, stop)
    if leaked:
        from paddle_tpu.utils.logger import get_logger
        get_logger("reader").warning(
            "reader close: %d worker thread(s) still blocked in user "
            "code after 10s (%s) — abandoning them as daemons",
            len(leaked), ", ".join(t.name for t in leaked))


def buffered(reader_fn, size):
    """Thread-prefetch up to `size` samples (reference: decorator.py:180).
    Source exceptions propagate to the consumer rather than silently
    truncating the stream; closing the generator mid-iteration (break,
    GC) joins the fill thread instead of leaking it blocked on a full
    queue."""
    end = object()

    def reader():
        q = queue.Queue(maxsize=size)
        stop = threading.Event()

        def fill():
            try:
                for e in reader_fn():
                    if not _put_stoppable(q, e, stop):
                        return
                _put_stoppable(q, end, stop)
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                _put_stoppable(q, (end, exc), stop)

        t = threading.Thread(target=fill, name="reader-buffered",
                             daemon=True)
        t.start()
        try:
            while True:
                e = q.get()
                if e is end:
                    break
                if isinstance(e, tuple) and len(e) == 2 and e[0] is end:
                    raise e[1]
                yield e
        finally:
            _close_workers([q], [t], stop)
    return reader


def firstn(reader_fn, n):
    def reader():
        return itertools.islice(reader_fn(), n)
    return reader


def cache(reader_fn):
    """Materialise once, replay from memory."""
    data = []
    filled = []

    def reader():
        if not filled:
            data.extend(reader_fn())
            filled.append(True)
        return iter(data)
    return reader


def xmap_readers(mapper, reader_fn, process_num, buffer_size, order=False):
    """Parallel map over samples with worker threads (reference:
    decorator.py:229 XmapEndSignal machinery).

    Failure semantics: a SOURCE exception (the feed thread) poisons the
    workers and re-raises at the consumer — previously the feed thread
    died silently, the workers blocked on an empty in-queue forever, and
    the consumer hung. Worker (mapper) exceptions re-raise at the
    consumer as before. Closing the generator early joins every thread
    (no daemon-thread leak after partial iteration)."""
    end = object()

    def reader():
        in_q = queue.Queue(buffer_size)
        out_q = queue.Queue(buffer_size)
        stop = threading.Event()

        def feed():
            try:
                for i, s in enumerate(reader_fn()):
                    if not _put_stoppable(in_q, (i, s), stop):
                        return
                for _ in range(process_num):
                    if not _put_stoppable(in_q, end, stop):
                        return
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                # the consumer must see the source failure (not hang),
                # and the workers must still be released
                _put_stoppable(out_q, (end, exc), stop)
                for _ in range(process_num):
                    if not _put_stoppable(in_q, end, stop):
                        return

        def work():
            try:
                while True:
                    try:
                        item = in_q.get(timeout=0.1)
                    except queue.Empty:
                        if stop.is_set():
                            return
                        continue
                    if item is end:
                        _put_stoppable(out_q, end, stop)
                        break
                    i, s = item
                    if not _put_stoppable(out_q, (i, mapper(s)), stop):
                        return
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                _put_stoppable(out_q, (end, exc), stop)

        threads = [threading.Thread(target=feed, name="reader-xmap-feed",
                                    daemon=True)]
        threads += [threading.Thread(target=work,
                                     name="reader-xmap-worker",
                                     daemon=True)
                    for _ in range(process_num)]
        for t in threads:
            t.start()

        def classify(item):
            """Returns 'end' or 'data'; raises propagated errors."""
            if item is end:
                return "end"
            if isinstance(item, tuple) and len(item) == 2 and item[0] is end:
                raise item[1]
            return "data"

        try:
            finished = 0
            if not order:
                while finished < process_num:
                    item = out_q.get()
                    if classify(item) == "end":
                        finished += 1
                    else:
                        yield item[1]
            else:
                pending, want = {}, 0
                while finished < process_num or pending:
                    if want in pending:
                        yield pending.pop(want)
                        want += 1
                        continue
                    if finished >= process_num:
                        break  # workers done, a gap remains (dropped index)
                    item = out_q.get()
                    if classify(item) == "end":
                        finished += 1
                    else:
                        pending[item[0]] = item[1]
        finally:
            _close_workers([in_q, out_q], threads, stop)
    return reader
