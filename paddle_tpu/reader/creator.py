"""Reader creators (reference: python/paddle/v2/reader/creator.py —
np_array, text_file, recordio)."""

import numpy as np


def np_array(x):
    def reader():
        yield from np.asarray(x)
    return reader


def text_file(path):
    def reader():
        with open(path) as f:
            for line in f:
                yield line.rstrip("\n")
    return reader


def recordio(paths):
    """Read chunked record files written by paddle_tpu.runtime.recordio
    (replaces the Go recordio reader used for cloud datasets)."""
    from paddle_tpu.runtime import recordio as rio
    if isinstance(paths, str):
        paths = [paths]

    def reader():
        for p in paths:
            yield from rio.read_records(p)
    return reader
