"""LM serving artifact — the functional-transformer counterpart of
io/merged.py (reference slot: paddle/capi + MergeModel's one-file
deployment, and the SWIG SequenceGenerator serving surface,
paddle/api/PaddleAPI.h:1025).

One tar holds the parameter pytree, the TransformerConfig, and TWO AOT
StableHLO modules (jax.export):
- ``prefill``: [B, Tp] prompt → (last-position logits, KV cache)
- ``decode``:  one incremental token step against the cache
A loading process needs paddle_tpu for the tar/np plumbing only — no
model code, no tracing, no recompilation on the same platform; greedy
or temperature sampling happens host-side between compiled calls.
"""

import dataclasses
import io as _io
import json
import tarfile
from typing import Optional, Sequence

import numpy as np

from paddle_tpu.io.checkpoint import _flatten          # shared pytree walk
from paddle_tpu.io.merged import _add_member as _add   # shared tar append

FORMAT_VERSION = 1


def _unflatten(flat):
    """Rebuild the nested pytree from checkpoint-style '/'-joined paths
    WITHOUT a template (the loader has no model code): dict nodes whose
    keys are all '__i' were list/tuple nodes in _flatten's encoding."""
    tree = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v

    def fix(node):
        if isinstance(node, dict):
            node = {k: fix(v) for k, v in node.items()}
            if node and all(k.startswith("__") for k in node):
                return [node[f"__{i}"] for i in range(len(node))]
        return node

    return fix(tree)


def _cfg_to_dict(cfg):
    import jax.numpy as jnp
    d = dataclasses.asdict(cfg)
    d["dtype"] = jnp.dtype(cfg.dtype).name
    return d


def _cfg_from_dict(d):
    import jax.numpy as jnp
    from paddle_tpu.models.transformer import TransformerConfig
    d = dict(d)
    d["dtype"] = jnp.dtype(d["dtype"])
    return TransformerConfig(**d)


def save_lm_artifact(path: str, params, cfg, *, batch: int,
                     prompt_len: int, cache_len: int,
                     platforms: Optional[Sequence[str]] = None) -> None:
    """Export the serving pair at fixed shapes and pack the artifact.

    batch/prompt_len/cache_len fix the exported shapes (AOT modules are
    shape-specialized; export several artifacts for several shapes).
    ``platforms`` e.g. ["tpu", "cpu"] widens where the module may run.
    """
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import transformer

    if cache_len > cfg.max_len:
        raise ValueError(f"cache_len {cache_len} exceeds cfg.max_len "
                         f"{cfg.max_len}")

    def prefill_fn(p, tokens):
        return transformer.prefill(p, tokens, cfg, cache_len)

    def decode_fn(p, cache, tokens, pos):
        return transformer.decode_step(p, cache, tokens, pos, cfg)

    kw = {"platforms": list(platforms)} if platforms else {}
    p_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(
            np.shape(a),
            a.dtype if hasattr(a, "dtype") else np.asarray(a).dtype),
        params)
    toks = jax.ShapeDtypeStruct((batch, prompt_len), jnp.int32)
    exp_prefill = jax.export.export(jax.jit(prefill_fn), **kw)(
        p_shapes, toks)
    cache_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        transformer.init_cache(cfg, batch, cache_len))
    exp_decode = jax.export.export(jax.jit(decode_fn), **kw)(
        p_shapes, cache_shapes,
        jax.ShapeDtypeStruct((batch,), jnp.int32),
        jax.ShapeDtypeStruct((), jnp.int32))

    meta = {"format_version": FORMAT_VERSION, "batch": batch,
            "prompt_len": prompt_len, "cache_len": cache_len,
            "config": _cfg_to_dict(cfg)}
    flat = _flatten(params)
    buf = _io.BytesIO()
    np.savez(buf, **flat)
    with tarfile.open(path, "w") as tar:
        _add(tar, "meta.json", json.dumps(meta).encode())
        _add(tar, "params.npz", buf.getvalue())
        _add(tar, "prefill.bin", exp_prefill.serialize())
        _add(tar, "decode.bin", exp_decode.serialize())


class LMServer:
    """Loaded artifact: compiled prefill + decode, host-side sampling.

    ``generate(prompt, max_new)`` mirrors models/transformer.generate
    greedy/temperature semantics but never traces or imports the model.
    """

    def __init__(self, meta, params, prefill_bin, decode_bin):
        import jax
        self.meta = meta
        self.cfg = _cfg_from_dict(meta["config"])
        self.params = params
        self._prefill = jax.export.deserialize(prefill_bin)
        self._decode = jax.export.deserialize(decode_bin)

    def generate(self, prompt: np.ndarray, max_new: int,
                 temperature: float = 0.0,
                 seed: Optional[int] = None) -> np.ndarray:
        import jax.numpy as jnp
        if max_new < 1:
            raise ValueError(f"generate: max_new must be >= 1, "
                             f"got {max_new}")
        b, tp = prompt.shape
        if b != self.meta["batch"] or tp != self.meta["prompt_len"]:
            raise ValueError(
                f"artifact exported for batch={self.meta['batch']} "
                f"prompt_len={self.meta['prompt_len']}, got {prompt.shape}")
        if tp + max_new > self.meta["cache_len"]:
            raise ValueError(f"{tp + max_new} positions exceed the "
                             f"exported cache_len {self.meta['cache_len']}")
        rng = np.random.RandomState(0 if seed is None else seed)

        def sample(logits):
            if temperature <= 0:
                return logits.argmax(-1).astype(np.int32)
            z = np.asarray(logits, np.float64) / temperature
            z = z - z.max(-1, keepdims=True)
            p = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
            return np.asarray([rng.choice(p.shape[-1], p=row)
                               for row in p], np.int32)

        logits, cache = self._prefill.call(
            self.params, jnp.asarray(prompt, jnp.int32))
        toks = [sample(np.asarray(logits))]
        for i in range(max_new - 1):
            logits, cache = self._decode.call(
                self.params, cache, jnp.asarray(toks[-1], jnp.int32),
                jnp.asarray(tp + i, jnp.int32))
            toks.append(sample(np.asarray(logits)))
        return np.concatenate([prompt,
                               np.stack(toks, axis=1)], axis=1)


def load_lm_artifact(path: str) -> LMServer:
    with tarfile.open(path, "r") as tar:
        members = {m.name: tar.extractfile(m).read()
                   for m in tar.getmembers()}
    meta = json.loads(members["meta.json"])
    if meta["format_version"] > FORMAT_VERSION:
        raise ValueError(f"artifact format {meta['format_version']} newer "
                         f"than this loader ({FORMAT_VERSION})")
    with np.load(_io.BytesIO(members["params.npz"]),
                 allow_pickle=False) as z:
        params = _unflatten({k: z[k] for k in z.files})
    return LMServer(meta, params, members["prefill.bin"],
                    members["decode.bin"])
