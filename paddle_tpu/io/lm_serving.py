"""LM serving artifact — the functional-transformer counterpart of
io/merged.py (reference slot: paddle/capi + MergeModel's one-file
deployment, and the SWIG SequenceGenerator serving surface,
paddle/api/PaddleAPI.h:1025).

One tar holds the parameter pytree, the TransformerConfig, and TWO AOT
StableHLO modules (jax.export):
- ``prefill``: [B, Tp] prompt → (last-position logits, KV cache)
- ``decode``:  one incremental token step against the cache
A loading process needs paddle_tpu for the tar/np plumbing only — no
model code, no tracing, no recompilation on the same platform; greedy
or temperature sampling happens host-side between compiled calls.
"""

import dataclasses
import io as _io
import json
import tarfile
import time
from typing import Optional, Sequence

import numpy as np

from paddle_tpu.io.checkpoint import _flatten          # shared pytree walk
from paddle_tpu.io.merged import _add_member as _add   # shared tar append
from paddle_tpu.observe import costs as _costs
from paddle_tpu.observe import metrics as _metrics

FORMAT_VERSION = 5   # max supported; plain artifacts still save as v1,
#                      int8-weight ones as v2; v3 adds the continuous-
#                      batching engine modules (slot prefill per bucket +
#                      vector-position decode with on-device sampling);
#                      v4 replaces them with the PAGED engine modules
#                      (chunked block-pool prefill per chunk bucket +
#                      page-table decode — prefix caching and chunked
#                      prefill are host-side scheduling over them);
#                      v5 additionally stamps a DRAFT model for
#                      speculative decoding (draft params + its chunk
#                      prefill / fused k-step propose / batched verify
#                      modules — LMServer.engine() then schedules a
#                      SpecDecodeEngine over the shared block table)


def _unflatten(flat):
    """Rebuild the nested pytree from checkpoint-style '/'-joined paths
    WITHOUT a template (the loader has no model code): dict nodes whose
    keys are all '__i' were list/tuple nodes in _flatten's encoding."""
    tree = {}
    for key, v in flat.items():
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v

    def fix(node):
        if isinstance(node, dict):
            node = {k: fix(v) for k, v in node.items()}
            if node and all(k.startswith("__") for k in node):
                return [node[f"__{i}"] for i in range(len(node))]
        return node

    return fix(tree)


def _cfg_to_dict(cfg):
    import jax.numpy as jnp
    d = dataclasses.asdict(cfg)
    d["dtype"] = jnp.dtype(cfg.dtype).name
    return d


def _cfg_from_dict(d):
    import jax.numpy as jnp
    from paddle_tpu.models.transformer import TransformerConfig
    d = dict(d)
    d["dtype"] = jnp.dtype(d["dtype"])
    return TransformerConfig(**d)


# the big matmul weights of the transformer pytree, with the axis the
# consuming einsum CONTRACTS over (the quantization-scale reduce axis):
# blocks.* are [L, in, out] (contract axis -2); embed [V, D] doubles as
# the logits projection contracting over D (axis -1), which also makes
# embedding-row gathers dequantize per row
_W8_LEAVES = {("blocks", "qkv"): -2, ("blocks", "attn_out"): -2,
              ("blocks", "mlp_in"): -2, ("blocks", "mlp_out"): -2,
              ("embed",): -1}


def quantize_lm_params(params):
    """Per-output-channel int8 for the big matmul weights (ops/q8
    helpers); layer norms, biases, and position tables stay fp32.
    Returns a pytree whose quantized leaves are {"q8","scale"} nodes —
    HBM (and artifact) weight bytes halve, and every weight read in the
    decode step becomes 1 byte/elt with the dequant multiply fused into
    the matmul operand read (decode is weight-read-bound, so this is the
    serving-throughput lever)."""
    from paddle_tpu.ops import q8 as ops_q8

    out = {k: (dict(v) if isinstance(v, dict) else v)
           for k, v in params.items()}
    for path, axis in _W8_LEAVES.items():
        node = out
        for p in path[:-1]:
            node = node[p]
        node[path[-1]] = ops_q8.quantize_weight(node[path[-1]], axis)
    return out


def save_lm_artifact(path: str, params, cfg, *, batch: int,
                     prompt_len: int, cache_len: int,
                     platforms: Optional[Sequence[str]] = None,
                     weights_int8: bool = False,
                     engine_buckets: Optional[Sequence[int]] = None,
                     engine_paged: bool = False,
                     engine_block_size: int = 16,
                     engine_num_blocks: Optional[int] = None,
                     engine_kv_dtype: Optional[str] = None,
                     engine_draft_params=None,
                     engine_draft_config=None,
                     engine_spec_k: int = 4
                     ) -> None:
    """Export the serving pair at fixed shapes and pack the artifact.

    batch/prompt_len/cache_len fix the exported shapes (AOT modules are
    shape-specialized; export several artifacts for several shapes).
    ``platforms`` e.g. ["tpu", "cpu"] widens where the module may run.
    ``weights_int8`` stores the big matmul weights as per-output-channel
    int8 (see quantize_lm_params) — the exported modules dequantize
    inline, so the loader and LMServer are unchanged.
    ``engine_buckets`` additionally exports the continuous-batching
    engine programs (format v3): one slot-prefill module per prompt
    bucket plus one per-slot-position decode module with on-device
    greedy/temperature/top-k sampling; ``LMServer.engine()`` schedules
    over them. ``batch`` doubles as the KV-arena slot count. v1/v2
    artifacts keep loading into the legacy lockstep path unchanged.
    ``engine_paged=True`` exports the PAGED engine instead (format v4):
    ``engine_buckets`` become CHUNK buckets, one
    ``engine_prefill_paged_<C>_<P>.bin`` chunk-prefill module per
    (chunk bucket C, page-vector length P) pair on the fixed chunk grid
    (``max(engine_buckets)`` tokens — the context span a chunk attends
    over is encoded in its page-vector SHAPE), plus
    one ``engine_decode_paged.bin`` page-table decode; the KV pool is
    ``engine_num_blocks`` (default ``batch * cache_len/block_size``,
    HBM parity with the v3 arena) blocks of ``engine_block_size``
    tokens. ``LMServer.engine()`` then schedules a
    ``serving.PagedDecodeEngine`` (chunked prefill + prefix cache)
    over them; v3 artifacts keep loading into the legacy slot engine.
    ``engine_kv_dtype`` ("int8"/"int4", paged only) exports the engine
    modules over a QUANTIZED pool (``transformer.init_block_pool``
    kv_dtype semantics: int8 / nibble-packed values + per-(position,
    head) fp32 scale tables): the stamp lands in
    ``meta.engine_paged.kv_dtype`` so the loader rebuilds the exact
    pool layout with no model code, and the compiled modules carry the
    write-time quantization + fused-dequant reads.
    """
    import jax
    import jax.export  # noqa: F401 — jax.export needs an explicit import
    import jax.numpy as jnp
    from paddle_tpu.models import transformer
    from paddle_tpu.ops import q8 as ops_q8

    if cache_len > cfg.max_len:
        raise ValueError(f"cache_len {cache_len} exceeds cfg.max_len "
                         f"{cfg.max_len}")
    if engine_kv_dtype and not engine_paged:
        # checked up front, NOT inside the engine-export branch: an
        # export that silently dropped the requested quantized pool
        # would only be discovered at serve time
        raise ValueError("engine_kv_dtype needs engine_paged=True "
                         "(the quantized pool is a paged-engine "
                         "layout)")
    if (engine_draft_params is None) != (engine_draft_config is None):
        raise ValueError("engine_draft_params and engine_draft_config "
                         "come together (the draft model for "
                         "speculative decoding)")
    if engine_draft_params is not None and not engine_paged:
        raise ValueError("engine_draft_params needs engine_paged=True "
                         "(speculative decoding rides the paged block "
                         "table)")
    if engine_draft_config is not None \
            and engine_draft_config.vocab != cfg.vocab:
        raise ValueError(f"draft vocab {engine_draft_config.vocab} != "
                         f"target vocab {cfg.vocab}")

    if weights_int8:
        params = quantize_lm_params(params)

        def _p(p):
            return ops_q8.dequantize_tree(p)
    else:
        def _p(p):
            return p

    def prefill_fn(p, tokens):
        return transformer.prefill(_p(p), tokens, cfg, cache_len)

    def decode_fn(p, cache, tokens, pos):
        return transformer.decode_step(_p(p), cache, tokens, pos, cfg)

    kw = {"platforms": list(platforms)} if platforms else {}
    p_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(
            np.shape(a),
            a.dtype if hasattr(a, "dtype") else np.asarray(a).dtype),
        params)
    toks = jax.ShapeDtypeStruct((batch, prompt_len), jnp.int32)
    jit_prefill, jit_decode = jax.jit(prefill_fn), jax.jit(decode_fn)
    exp_prefill = jax.export.export(jit_prefill, **kw)(
        p_shapes, toks)
    cache_shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
        transformer.init_cache(cfg, batch, cache_len))
    decode_args = (p_shapes, cache_shapes,
                   jax.ShapeDtypeStruct((batch,), jnp.int32),
                   jax.ShapeDtypeStruct((), jnp.int32))
    exp_decode = jax.export.export(jit_decode, **kw)(*decode_args)

    # format-v3 engine programs: slot prefill per bucket + one vector-
    # position decode step with the sampler fused in (token ids are the
    # only host-bound output); format v4 swaps them for the PAGED pair
    # (chunk prefill per chunk bucket + page-table decode)
    engine_members = {}
    engine_paged_meta = None
    if engine_paged and not engine_buckets:
        raise ValueError("engine_paged=True needs engine_buckets= "
                         "(the chunk buckets to export)")
    if engine_buckets:
        from paddle_tpu.ops.pallas import policy as _pallas_policy
        from paddle_tpu.serving import sampling as _sampling
        # stamp which attention/sampling path the engine modules were
        # compiled with (the resolved PADDLE_TPU_PALLAS policy at
        # export time) — a loader cannot re-derive it from the .bin
        engine_pallas = _pallas_policy.pallas_mode(None)
        buckets = sorted({int(b) for b in engine_buckets})
        bad = [b for b in buckets if b < 1 or b > cache_len]
        if bad:
            raise ValueError(f"engine_buckets {bad} outside "
                             f"[1, cache_len={cache_len}]")
        dequant = ops_q8.dequantize_tree if weights_int8 else None
        i32 = jax.ShapeDtypeStruct((), jnp.int32)
        f32 = jax.ShapeDtypeStruct((), jnp.float32)

        def _vec(dt):
            return jax.ShapeDtypeStruct((batch,), dt)

        def _eng_decode_args(kv_shapes, *extra):
            # shared decode signature (tokens, pos, active, [pages,]
            # temperature, top_k, seed) — one spot to extend for both
            # the slot and paged exports
            return (p_shapes, kv_shapes, _vec(jnp.int32),
                    _vec(jnp.int32), _vec(jnp.bool_), *extra,
                    _vec(jnp.float32), _vec(jnp.int32), i32)
        if engine_paged:
            bs = int(engine_block_size)
            if bs < 1 or cache_len % bs:
                raise ValueError(f"cache_len {cache_len} must be a "
                                 f"positive multiple of "
                                 f"engine_block_size {bs}")
            pages = cache_len // bs
            nb = int(engine_num_blocks if engine_num_blocks is not None
                     else batch * pages)
            chunk = max(buckets)        # the engine's prefill chunk grid
            if chunk % bs or cache_len % chunk:
                raise ValueError(
                    f"paged export needs block_size {bs} | chunk "
                    f"{chunk} | cache_len {cache_len} (each dividing "
                    f"the next): the chunk grid anchors the exported "
                    f"context spans")
            engine_paged_meta = {"block_size": bs, "num_blocks": nb,
                                 "pages_per_slot": pages,
                                 "chunk_tokens": chunk,
                                 "pallas": engine_pallas,
                                 "kv_dtype": engine_kv_dtype or "none",
                                 # the pool array layout the modules
                                 # were shaped against — the loader
                                 # refuses to schedule programs from a
                                 # different layout generation (the
                                 # pre-relayout slot-major pool)
                                 "pool_layout":
                                     transformer.POOL_LAYOUT}
            eng_prefill, eng_decode = _sampling.paged_step_fns(
                cfg, bs, dequant=dequant)
            pool_shapes = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                transformer.init_block_pool(
                    cfg, nb, bs, kv_dtype=engine_kv_dtype))
            # one chunk-prefill module per (bucket, context span) the
            # fixed chunk grid can reach: a chunk's context length is
            # encoded in its page-vector SHAPE (span specialization —
            # cold chunks attend over C tokens, not cache_len), so each
            # (C, P) pair is its own AOT program
            for ctx in range(0, cache_len, chunk):
                for b in buckets:
                    pv = ctx // bs + -(-b // bs)
                    ep = jax.export.export(jax.jit(eng_prefill), **kw)(
                        p_shapes, pool_shapes,
                        jax.ShapeDtypeStruct((1, b), jnp.int32), i32,
                        jax.ShapeDtypeStruct((pv,), jnp.int32),
                        f32, i32, i32)
                    engine_members[
                        f"engine_prefill_paged_{b}_{pv}.bin"] = \
                        ep.serialize()
            eng_decode_args = _eng_decode_args(
                pool_shapes,
                jax.ShapeDtypeStruct((batch, pages), jnp.int32))
            eng_decode_member = "engine_decode_paged.bin"
            if engine_draft_params is not None:
                # v5: the draft's program set — chunk prefill mirroring
                # the target grid, fused k-step propose, the target's
                # batched verify, and the draft-side forced-window
                # write the preempt-resume replay needs
                dcfg = engine_draft_config
                k = int(engine_spec_k)
                W = k + 1
                spec = _sampling.paged_spec_fns(cfg, dcfg, bs, k,
                                                dequant=dequant)
                dp_shapes = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(
                        np.shape(a),
                        a.dtype if hasattr(a, "dtype")
                        else np.asarray(a).dtype), engine_draft_params)
                dpool_shapes = jax.tree_util.tree_map(
                    lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype),
                    transformer.init_block_pool(dcfg, nb, bs))
                for ctx in range(0, cache_len, chunk):
                    for b in buckets:
                        pv = ctx // bs + -(-b // bs)
                        ep = jax.export.export(
                            jax.jit(spec["draft_prefill"]), **kw)(
                            dp_shapes, dpool_shapes,
                            jax.ShapeDtypeStruct((1, b), jnp.int32),
                            i32,
                            jax.ShapeDtypeStruct((pv,), jnp.int32))
                        engine_members[
                            f"engine_draft_prefill_{b}_{pv}.bin"] = \
                            ep.serialize()
                pages_s = jax.ShapeDtypeStruct((batch, pages),
                                               jnp.int32)
                win_s = jax.ShapeDtypeStruct((batch, W), jnp.int32)
                engine_members["engine_propose.bin"] = \
                    jax.export.export(jax.jit(spec["propose"]), **kw)(
                        dp_shapes, dpool_shapes, _vec(jnp.int32),
                        _vec(jnp.int32), _vec(jnp.bool_),
                        _vec(jnp.int32), pages_s).serialize()
                jit_verify = jax.jit(spec["verify"])
                verify_args = (p_shapes, pool_shapes, win_s,
                               _vec(jnp.int32), _vec(jnp.int32),
                               _vec(jnp.bool_), pages_s,
                               _vec(jnp.float32), _vec(jnp.int32), i32)
                engine_members["engine_verify.bin"] = \
                    jax.export.export(jit_verify, **kw)(
                        *verify_args).serialize()
                engine_members["engine_draft_verify.bin"] = \
                    jax.export.export(
                        jax.jit(spec["draft_verify"]), **kw)(
                        dp_shapes, dpool_shapes, win_s,
                        _vec(jnp.int32), _vec(jnp.int32),
                        _vec(jnp.bool_), pages_s).serialize()
        else:
            eng_prefill, eng_decode = _sampling.engine_step_fns(
                cfg, dequant=dequant)
            for b in buckets:
                ep = jax.export.export(jax.jit(eng_prefill), **kw)(
                    p_shapes, cache_shapes,
                    jax.ShapeDtypeStruct((1, b), jnp.int32),
                    i32, i32, f32, i32, i32)
                engine_members[f"engine_prefill_{b}.bin"] = ep.serialize()
            eng_decode_args = _eng_decode_args(cache_shapes)
            eng_decode_member = "engine_decode.bin"
        jit_eng_decode = jax.jit(eng_decode)
        engine_members[eng_decode_member] = jax.export.export(
            jit_eng_decode, **kw)(*eng_decode_args).serialize()

    # per-phase cost accounting, stamped into the artifact at export
    # time (the loader has no model code to re-derive it from): the MFU
    # denominator's numerator for any host that serves this file
    cost_analysis = {}
    phases = [("prefill", jit_prefill, (p_shapes, toks)),
              ("decode", jit_decode, decode_args)]
    if engine_buckets:
        phases.append(("engine_decode", jit_eng_decode, eng_decode_args))
    if engine_draft_params is not None:
        # the spec engine dispatches VERIFY rounds, not decode steps —
        # its MFU numerator is the verify program's model FLOPs
        phases.append(("engine_verify", jit_verify, verify_args))
    for phase, fn, args in phases:
        ca = _costs.lowered_cost(fn, *args)
        if ca:
            cost_analysis[phase] = ca

    meta = {
        # quantized artifacts carry nested {"q8","scale"} params — a v2
        # encoding; plain artifacts stay v1 for older loaders; engine
        # modules (whose member names older loaders would not recognise)
        # bump to v3; paged engine modules to v4; a stamped draft to v5
        "format_version": (5 if engine_draft_params is not None
                           else 4 if engine_paged else 3)
        if engine_buckets else (2 if weights_int8 else 1),
        "batch": batch, "prompt_len": prompt_len, "cache_len": cache_len,
        "weights_int8": weights_int8, "config": _cfg_to_dict(cfg),
        "cost_analysis": cost_analysis}
    if engine_buckets:
        meta["engine_buckets"] = buckets
        meta["engine_pallas"] = engine_pallas
    if engine_paged_meta:
        meta["engine_paged"] = engine_paged_meta
    draft_blob = None
    if engine_draft_params is not None:
        meta["engine_spec"] = {
            "k": int(engine_spec_k),
            "draft_config": _cfg_to_dict(engine_draft_config)}
        dbuf = _io.BytesIO()
        np.savez(dbuf, **_flatten(engine_draft_params))
        draft_blob = dbuf.getvalue()
    flat = _flatten(params)
    buf = _io.BytesIO()
    np.savez(buf, **flat)
    with tarfile.open(path, "w") as tar:
        _add(tar, "meta.json", json.dumps(meta).encode())
        _add(tar, "params.npz", buf.getvalue())
        if draft_blob is not None:
            _add(tar, "draft_params.npz", draft_blob)
        _add(tar, "prefill.bin", exp_prefill.serialize())
        _add(tar, "decode.bin", exp_decode.serialize())
        for name, blob in engine_members.items():
            _add(tar, name, blob)


# decode steps run single-digit ms; prefill tens-to-hundreds — buckets
# must resolve both (default Prometheus buckets start too coarse at 1 ms)
_LATENCY_BUCKETS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                    0.1, 0.25, 0.5, 1.0, 2.5, 5.0)


class LMServer:
    """Loaded artifact: compiled prefill + decode, host-side sampling.

    ``generate(prompt, max_new)`` mirrors models/transformer.generate
    greedy/temperature semantics but never traces or imports the model.

    Each server carries its own metrics ``Registry`` (serving several
    artifacts in one process must not cross-pollute counters):
    prefill/decode call counts, generated-token count, and per-phase
    latency histograms; ``metrics_text()`` renders the Prometheus text
    snapshot a scrape endpoint serves verbatim.
    """

    def __init__(self, meta, params, prefill_bin, decode_bin,
                 engine_bins=None, draft_params=None):
        import jax
        import jax.export  # noqa: F401 — needs an explicit import
        self.meta = meta
        self.cfg = _cfg_from_dict(meta["config"])
        self.params = params
        # v5: the stamped speculative-decoding draft (None below v5)
        self.draft_params = draft_params
        self._prefill = jax.export.deserialize(prefill_bin)
        self._decode = jax.export.deserialize(decode_bin)
        # format-v3 continuous-batching modules (absent on v1/v2):
        # deserialized lazily by engine() — lockstep-only consumers of a
        # v3 artifact pay nothing for them
        self._engine_bins = dict(engine_bins or {})
        self.engine_buckets = tuple(meta.get("engine_buckets", ()))
        reg = self.metrics = _metrics.Registry()
        self._m_prefill = reg.counter(
            "lm_prefill_calls_total", "prefill (prompt) passes served")
        self._m_decode = reg.counter(
            "lm_decode_calls_total", "incremental decode steps served")
        self._m_tokens = reg.counter(
            "lm_tokens_generated_total", "tokens sampled across all calls")
        self._m_requests = reg.counter(
            "lm_generate_requests_total", "generate() calls",)
        self._m_prefill_s = reg.histogram(
            "lm_prefill_seconds", "prefill latency (device call + sample)",
            buckets=_LATENCY_BUCKETS)
        self._m_decode_s = reg.histogram(
            "lm_decode_seconds", "per-token decode latency "
            "(device call + sample)", buckets=_LATENCY_BUCKETS)
        # cost accounting stamped at export time (older artifacts: {})
        self.cost_analysis = meta.get("cost_analysis", {})
        self._m_mfu = reg.gauge(
            "lm_decode_mfu", "model-FLOPs utilisation of the last decode "
            "step (0 until the artifact carries cost_analysis)")
        # constant for the process — resolved once, not per decoded token
        self._peak_flops = _costs.device_peak_flops()
        self._last_generate = None

    def metrics_text(self) -> str:
        """Prometheus text exposition snapshot of this server's metrics."""
        return self.metrics.render_prometheus()

    def health(self) -> dict:
        """/healthz document: request/token progress of this server."""
        since = (round(time.perf_counter() - self._last_generate, 3)
                 if self._last_generate is not None else None)
        return {"requests": int(self._m_requests.value()),
                "tokens_generated": int(self._m_tokens.value()),
                "decode_steps": int(self._m_decode.value()),
                "seconds_since_request": since,
                "batch": self.meta["batch"],
                "cache_len": self.meta["cache_len"]}

    def serve(self, host: str = "127.0.0.1", port: int = 0):
        """Start an ``observe.HealthServer`` over THIS server's registry
        (``/metrics``) and ``health()`` (``/healthz``). Returns the
        server; callers own its ``close()``."""
        from paddle_tpu.observe.health import HealthServer
        return HealthServer(registry=self.metrics, health_fn=self.health,
                            host=host, port=port)

    def engine(self, *, seed: Optional[int] = None, registry=None,
               tracker=None, chunk_tokens: Optional[int] = None,
               tiers=None):
        """Continuous-batching engine over this artifact's modules:
        a ``serving.PagedDecodeEngine`` for format-v4 artifacts (paged
        block pool + chunked prefill + prefix cache; the chunk grid is
        the artifact's — ``chunk_tokens`` may only restate it, the
        prefill modules are span-specialized), the legacy
        ``serving.DecodeEngine`` for format-v3 (whole-row arena).
        Raises on v1/v2 artifacts — re-export with
        ``engine_buckets=`` to serve continuously; ``generate()`` stays
        the lockstep fallback either way."""
        import jax.export
        import jax.numpy as jnp
        from paddle_tpu.serving.engine import (DecodeEngine,
                                               PagedDecodeEngine)
        if not self._engine_bins:
            raise ValueError(
                f"artifact (format v{self.meta['format_version']}) has "
                f"no engine modules — re-export with "
                f"save_lm_artifact(..., engine_buckets=(...)) for "
                f"continuous batching")
        cfg = self.cfg
        paged = self.meta.get("engine_paged")
        if paged:
            # layout fencing: the exported modules bake the pool array
            # shapes, so a legacy slot-major artifact (pre-head-major
            # relayout; no pool_layout stamp) cannot be scheduled over
            # the pool this build constructs — the failure would
            # otherwise surface as an opaque shape mismatch at the
            # first prefill call
            from paddle_tpu.models import transformer
            stamped = paged.get("pool_layout", "slot_major")
            if stamped != transformer.POOL_LAYOUT:
                raise ValueError(
                    f"artifact's paged-engine modules were exported "
                    f"against a {stamped!r} KV pool but this build "
                    f"uses {transformer.POOL_LAYOUT!r} — re-export "
                    f"with save_lm_artifact(..., engine_paged=True) "
                    f"to serve it")
            meta_chunk = int(paged.get("chunk_tokens",
                                       max(self.engine_buckets)))
            if chunk_tokens is not None and int(chunk_tokens) != \
                    meta_chunk:
                raise ValueError(
                    f"artifact exported on a chunk grid of "
                    f"{meta_chunk} tokens (its prefill modules are "
                    f"(bucket, context-span)-specialized); "
                    f"chunk_tokens={chunk_tokens} has no programs — "
                    f"re-export to change the grid")
            prefills = {}
            for name, blob in self._engine_bins.items():
                if not name.startswith("engine_prefill_paged_"):
                    continue
                b, pv = name[len("engine_prefill_paged_"):
                             -len(".bin")].split("_")
                prefills[(int(b), int(pv))] = \
                    jax.export.deserialize(blob).call
            decode = jax.export.deserialize(
                self._engine_bins["engine_decode_paged.bin"]).call

            def prefill(params, pool, tokens, length, pagevec, *rest):
                key = (tokens.shape[1], pagevec.shape[0])
                return prefills[key](params, pool, tokens, length,
                                     pagevec, *rest)

            # zero-filled block pool from the meta geometry + kv_dtype
            # stamp, built by the SAME constructor the export shaped
            # the modules against (one source of truth for the pool
            # layout — the loader already imports the transformer
            # module for TransformerConfig, so this adds no dependency)
            from paddle_tpu.models import transformer
            kvd = paged.get("kv_dtype", "none")
            if kvd == "none":
                kvd = None
            pool = transformer.init_block_pool(
                cfg, paged["num_blocks"], paged["block_size"],
                kv_dtype=kvd)
            eng_kw = dict(
                batch=self.meta["batch"],
                cache_len=self.meta["cache_len"],
                block_size=paged["block_size"],
                num_blocks=paged["num_blocks"],
                chunk_tokens=meta_chunk,
                chunk_buckets=self.engine_buckets, seed=seed,
                registry=registry, tracker=tracker,
                decode_flops=self.cost_analysis.get(
                    "engine_decode", {}).get("flops"),
                pallas_mode=self.meta.get("engine_pallas"),
                kv_dtype=kvd, tiers=tiers)
            spec = self.meta.get("engine_spec")
            if spec:
                # v5: schedule the SpecDecodeEngine over the stamped
                # draft — its pool rebuilt from the draft config at
                # the SAME block geometry (one page table, two pools)
                from paddle_tpu.serving.engine import SpecDecodeEngine
                dcfg = _cfg_from_dict(spec["draft_config"])
                draft_pool = transformer.init_block_pool(
                    dcfg, paged["num_blocks"], paged["block_size"])
                dprefills = {}
                for name, blob in self._engine_bins.items():
                    if not name.startswith("engine_draft_prefill_"):
                        continue
                    b, pv = name[len("engine_draft_prefill_"):
                                 -len(".bin")].split("_")
                    dprefills[(int(b), int(pv))] = \
                        jax.export.deserialize(blob).call

                def draft_prefill(dp, dpool, tokens, length, pagevec):
                    key = (tokens.shape[1], pagevec.shape[0])
                    return dprefills[key](dp, dpool, tokens, length,
                                          pagevec)

                eng_kw["decode_flops"] = self.cost_analysis.get(
                    "engine_verify", {}).get(
                    "flops", eng_kw["decode_flops"])
                return SpecDecodeEngine(
                    prefill, decode, self.params, pool,
                    draft_params=self.draft_params,
                    draft_cache=draft_pool,
                    draft_prefill=draft_prefill,
                    propose=jax.export.deserialize(
                        self._engine_bins["engine_propose.bin"]).call,
                    verify=jax.export.deserialize(
                        self._engine_bins["engine_verify.bin"]).call,
                    draft_verify=jax.export.deserialize(
                        self._engine_bins[
                            "engine_draft_verify.bin"]).call,
                    spec_k=spec["k"], **eng_kw)
            return PagedDecodeEngine(
                prefill, decode, self.params, pool, **eng_kw)
        if chunk_tokens is not None:
            raise ValueError(
                f"chunk_tokens={chunk_tokens}: this artifact (format "
                f"v{self.meta['format_version']}) has no paged engine "
                f"modules, so prefill cannot be chunked — re-export "
                f"with save_lm_artifact(..., engine_paged=True)")
        if tiers is not None:
            raise ValueError(
                "tiered spill (tiers=) needs a paged-engine artifact "
                "— the row arena has no block pool to demote from")
        prefills = {b: jax.export.deserialize(
            self._engine_bins[f"engine_prefill_{b}.bin"]).call
            for b in self.engine_buckets}
        decode = jax.export.deserialize(
            self._engine_bins["engine_decode.bin"]).call

        def prefill(params, cache, tokens, *rest):
            return prefills[tokens.shape[1]](params, cache, tokens,
                                             *rest)

        # zero-filled KV arena straight from the meta (no model code —
        # the shape is determined by the config alone)
        shape = (cfg.n_layers, self.meta["batch"], self.meta["cache_len"],
                 cfg.kv_heads, cfg.head_dim)
        cache = {"k": jnp.zeros(shape, cfg.dtype),
                 "v": jnp.zeros(shape, cfg.dtype)}
        return DecodeEngine(
            prefill, decode, self.params, cache,
            batch=self.meta["batch"], cache_len=self.meta["cache_len"],
            buckets=self.engine_buckets, seed=seed, registry=registry,
            tracker=tracker,
            decode_flops=self.cost_analysis.get(
                "engine_decode", {}).get("flops"),
            pallas_mode=self.meta.get("engine_pallas"))

    def generate(self, prompt: np.ndarray, max_new: int,
                 temperature: float = 0.0,
                 seed: Optional[int] = None,
                 eos_id: Optional[int] = None) -> np.ndarray:
        """Lockstep batch generation (every row decodes in unison).

        ``seed=None`` draws fresh OS entropy — two unseeded sampling
        calls differ; pass an int for reproducibility. ``eos_id`` stops
        the decode loop early once EVERY row has emitted it (rows that
        finish first keep emitting ``eos_id`` as padding), so the result
        is ``[B, prompt_len + n]`` with ``n <= max_new``."""
        import jax.numpy as jnp
        if max_new < 1:
            raise ValueError(f"generate: max_new must be >= 1, "
                             f"got {max_new}")
        b, tp = prompt.shape
        if b != self.meta["batch"] or tp != self.meta["prompt_len"]:
            raise ValueError(
                f"artifact exported for batch={self.meta['batch']} "
                f"prompt_len={self.meta['prompt_len']}, got {prompt.shape}")
        if tp + max_new > self.meta["cache_len"]:
            raise ValueError(f"{tp + max_new} positions exceed the "
                             f"exported cache_len {self.meta['cache_len']}")
        # seed=None must NOT collapse to RandomState(0): that made every
        # "unseeded" sampling call deterministically identical. None lets
        # RandomState pull fresh OS entropy.
        rng = np.random.RandomState(seed)

        def sample(logits):
            if temperature <= 0:
                return logits.argmax(-1).astype(np.int32)
            z = np.asarray(logits, np.float64) / temperature
            z = z - z.max(-1, keepdims=True)
            p = np.exp(z) / np.exp(z).sum(-1, keepdims=True)
            return np.asarray([rng.choice(p.shape[-1], p=row)
                               for row in p], np.int32)

        self._m_requests.inc()
        self._last_generate = time.perf_counter()
        decode_flops = self.cost_analysis.get("decode", {}).get("flops")
        t0 = time.perf_counter()
        logits, cache = self._prefill.call(
            self.params, jnp.asarray(prompt, jnp.int32))
        # np.asarray inside sample() is the host sync — latency measured
        # after it is the latency a caller actually observes
        toks = [sample(np.asarray(logits))]
        self._m_prefill.inc()
        self._m_prefill_s.observe(time.perf_counter() - t0)
        self._m_tokens.inc(b)
        done = (toks[0] == eos_id) if eos_id is not None else None
        # device-side position carry: pos advances with an on-device add
        # instead of re-uploading a fresh host scalar every token
        pos = jnp.asarray(tp, jnp.int32)
        for i in range(max_new - 1):
            if eos_id is not None and done.all():
                break          # every row terminated: drop the wasted
            t0 = time.perf_counter()   # lockstep tail steps
            logits, cache = self._decode.call(
                self.params, cache, jnp.asarray(toks[-1], jnp.int32),
                pos)
            pos = pos + 1
            tok = sample(np.asarray(logits))
            if eos_id is not None:
                # rows already finished pad with eos_id from here on
                tok = np.where(done, eos_id, tok).astype(np.int32)
                done = done | (tok == eos_id)
            toks.append(tok)
            dt = time.perf_counter() - t0
            self._m_decode.inc()
            self._m_decode_s.observe(dt)
            self._m_tokens.inc(b)
            if self._peak_flops:
                mfu = _costs.mfu(decode_flops, dt, self._peak_flops)
                if mfu is not None:
                    self._m_mfu.set(mfu)
        return np.concatenate([prompt,
                               np.stack(toks, axis=1)], axis=1)


def load_lm_artifact(path: str) -> LMServer:
    with tarfile.open(path, "r") as tar:
        members = {m.name: tar.extractfile(m).read()
                   for m in tar.getmembers()}
    meta = json.loads(members["meta.json"])
    if meta["format_version"] > FORMAT_VERSION:
        raise ValueError(f"artifact format {meta['format_version']} newer "
                         f"than this loader ({FORMAT_VERSION})")
    with np.load(_io.BytesIO(members["params.npz"]),
                 allow_pickle=False) as z:
        params = _unflatten({k: z[k] for k in z.files})
    draft_params = None
    if "draft_params.npz" in members:
        with np.load(_io.BytesIO(members["draft_params.npz"]),
                     allow_pickle=False) as z:
            draft_params = _unflatten({k: z[k] for k in z.files})
    engine_bins = {k: v for k, v in members.items()
                   if k.startswith("engine_")}
    return LMServer(meta, params, members["prefill.bin"],
                    members["decode.bin"], engine_bins=engine_bins,
                    draft_params=draft_params)
