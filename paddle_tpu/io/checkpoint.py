"""Checkpoint save/resume.

Reference: paddle/trainer/ParamUtil.cpp (per-pass dirs save_dir/pass-%05d,
--init_model_path/--start_pass resume) + Go pserver disk checkpoints with
checksum + etcd meta (go/pserver/service.go:119-174).

TPU-native: one directory per checkpoint holding a numpy .npz per pytree
(params / optimizer state / model state) + a JSON manifest with step counter
and a content checksum (the Go pserver's integrity scheme), written
atomically via tempdir+rename. Checksums are computed in bounded-memory
chunks. Two scaling paths:

- ``AsyncCheckpointer`` — snapshots to host synchronously (bounded by one
  device→host copy) and does serialization/checksum/IO/pruning on a worker
  thread, so training never waits on disk (the orbax-style async slot; the
  reference's pserver checkpoints were also written off the serving path,
  go/pserver/service.go:119).
- ``save_checkpoint(..., process_index/process_count)`` — multi-host layout:
  each process writes only its addressable shards to its own npz
  (``params.p{K}.npz``); load merges every process file present. Shard
  overlap is fine (replicated arrays): last writer wins on identical data.

ZeRO resharding (``meta.zero`` manifest path): single-host saves hold
FULL host arrays — ``np.asarray`` on a ZeRO-sharded leaf (stage>=1 opt
state, stage 3 params) gathers its shards — so a restore under a
DIFFERENT zero stage or mesh size IS the reshard: the trainer
device_puts the loaded full arrays into the current config's layout and
logs the layout change it read from ``meta.zero``. Multi-host saves keep
per-shard entries; ``_load_group`` reassembles the full array before the
same re-layout. Proven save@zero=3/data=4 → restore@zero∈{0,1,2} and
data=2 in tests/test_zero.py::TestZeroCheckpointResharding.
"""

import hashlib
import json
import os
import pickle
import queue
import tempfile
import threading
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np


def _flatten(tree, prefix=""):
    """Flatten nested dict/tuple pytrees of arrays into {path: array}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}__{i}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten_into(flat: Dict[str, np.ndarray], tree):
    """Rebuild values in the structure of `tree` from flat paths."""
    def build(subtree, prefix):
        if isinstance(subtree, dict):
            return {k: build(v, f"{prefix}{k}/") for k, v in subtree.items()}
        if isinstance(subtree, (list, tuple)):
            vals = [build(v, f"{prefix}__{i}/") for i, v in enumerate(subtree)]
            return type(subtree)(vals)
        return jnp.asarray(flat[prefix.rstrip("/")])
    return build(tree, "")


def _file_md5(path):
    """Chunked digest — npz writing seeks (zip headers), so a write-through
    hash cannot work; a 1MB-chunk re-read keeps memory bounded (the old
    path read whole files into memory)."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _local_shards(arr):
    """[(index_tuple_of_slices, np_shard)] for this process's addressable
    shards; a single [(None, full_array)] for unsharded/numpy values."""
    shards = getattr(arr, "addressable_shards", None)
    fully_local = getattr(arr, "is_fully_addressable", True)
    if shards is None or (fully_local and len(shards) <= 1):
        return [(None, np.asarray(arr))]
    seen, out = set(), []
    for s in shards:
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        if key in seen:
            continue            # replicated across devices: write once
        seen.add(key)
        out.append((s.index, np.asarray(s.data)))
    return out


def _write_tree(tmp, fname, tree, manifest, sharded, host_trees=None):
    flat = host_trees[fname] if host_trees else _flatten(tree)
    path = os.path.join(tmp, fname + ".npz")
    entries, index_meta = {}, {}
    for key, arr in flat.items():
        if not sharded:
            entries[key] = np.asarray(arr)
            continue
        for i, (idx, shard) in enumerate(_local_shards(arr)):
            if idx is None:
                entries[key] = np.asarray(shard)
            else:
                entries[f"{key}@@{i}"] = shard
                index_meta.setdefault(key, {})[str(i)] = [
                    [sl.start, sl.stop] for sl in idx]
    with open(path, "wb") as raw:
        np.savez(raw, **entries)
    manifest["files"][fname] = _file_md5(path)
    if index_meta:
        manifest.setdefault("shards", {})[fname] = {
            "full_shapes": {k: list(np.shape(v)) for k, v in flat.items()},
            "index": index_meta}


def _prune_old(save_dir, keep):
    import shutil
    kept = sorted(d for d in os.listdir(save_dir) if d.startswith("ckpt-"))
    for d in kept[:-keep]:
        shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)


def _write_single(save_dir, step, trees, keep, host_trees=None,
                  sharded=False, process_index=0, process_count=1,
                  blobs=None, meta=None):
    """Shared atomic-write core for save_checkpoint and AsyncCheckpointer.
    ``trees``: {fname: pytree} (ignored per-entry when host_trees carries
    the pre-flattened host copy). ``blobs``: {name: bytes} opaque
    payloads (the pipeline's pickled stream position) written verbatim
    as ``<name><suffix>.pkl`` with their checksum in the manifest.
    ``meta``: JSON-able layout metadata (e.g. the ZeRO sharding layout
    the state was trained under) stored in the manifest — restores onto
    a different mesh read it to know a reshard is happening."""
    name = f"ckpt-{step:08d}"
    final = os.path.join(save_dir, name)
    os.makedirs(save_dir, exist_ok=True)
    suffix = f".p{process_index}" if process_count > 1 else ""
    tmp = tempfile.mkdtemp(dir=save_dir, prefix=".tmp-" + name + suffix)
    manifest = {"step": int(step), "files": {},
                "process_index": process_index,
                "process_count": process_count}
    if meta is not None:
        manifest["meta"] = meta
    for base, tree in trees.items():
        if tree is None and not (host_trees and base in host_trees):
            continue
        _write_tree(tmp, base + suffix, tree, manifest, sharded,
                    host_trees={base + suffix: host_trees[base]}
                    if host_trees else None)
    for bname, data in (blobs or {}).items():
        bpath = os.path.join(tmp, bname + suffix + ".pkl")
        with open(bpath, "wb") as f:
            f.write(data)
        manifest.setdefault("blobs", {})[bname + suffix] = _file_md5(bpath)
    with open(os.path.join(tmp, f"manifest{suffix}.json"), "w") as f:
        json.dump(manifest, f)
    if process_count > 1:
        # multi-host: move our files into the shared dir; process 0 owns
        # directory lifecycle, others only add their piece. The manifest
        # moves LAST — its presence is this process's commit point, so a
        # reader that sees all manifests sees all data files too.
        os.makedirs(final, exist_ok=True)
        manifest_fn = f"manifest{suffix}.json"
        for fn in sorted(os.listdir(tmp),
                         key=lambda n: n == manifest_fn):
            os.replace(os.path.join(tmp, fn), os.path.join(final, fn))
        os.rmdir(tmp)
    else:
        import shutil
        if os.path.exists(final):
            shutil.rmtree(final)
        os.rename(tmp, final)
    if process_index == 0:
        _prune_old(save_dir, keep)
    return final


def save_checkpoint(save_dir: str, step: int, params: Dict,
                    opt_state=None, model_state=None, keep: int = 3,
                    process_index: int = 0, process_count: int = 1,
                    sharded: bool = False, pipeline_state=None,
                    meta=None):
    """Write checkpoint 'pass-%05d' style dir; prunes old ones.

    With ``sharded=True`` (or process_count>1) each array entry stores this
    process's addressable shards plus their index metadata — the multi-host
    layout where every host writes only what it owns.

    ``pipeline_state``: the input pipeline's ``state_dict()`` (source
    cursor, shuffle RNG + buffer, batch counter) — persisted next to the
    model so a restore continues the data stream mid-epoch on the exact
    next batch (``load_pipeline_state``)."""
    blobs = None
    if pipeline_state is not None:
        blobs = {"pipeline": pickle.dumps(pipeline_state, protocol=4)}
    return _write_single(
        save_dir, step,
        {"params": params, "opt_state": opt_state,
         "model_state": model_state},
        keep, sharded=sharded or process_count > 1,
        process_index=process_index, process_count=process_count,
        blobs=blobs, meta=meta)


def checkpoint_meta(path: str) -> Optional[dict]:
    """The layout metadata stored with a checkpoint (``meta=`` at save
    time; e.g. the ZeRO optimizer-state layout) — None for checkpoints
    written without any, so every older checkpoint stays loadable."""
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return None
    for fn in names:
        if fn.startswith("manifest") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                manifest = json.load(f)
            if manifest.get("meta") is not None:
                return manifest["meta"]
    return None


def latest_checkpoint(save_dir: str) -> Optional[str]:
    if not os.path.isdir(save_dir):
        return None
    cks = sorted(d for d in os.listdir(save_dir) if d.startswith("ckpt-"))
    return os.path.join(save_dir, cks[-1]) if cks else None


def _verify_file(fpath, want):
    if _file_md5(fpath) != want:
        raise IOError(f"checkpoint checksum mismatch: {fpath}")


def _load_group(path, base, manifests, verify):
    """Merge a logical tree ('params') across every process file present,
    reassembling sharded entries from their index metadata."""
    flat, pending = {}, {}
    for manifest in manifests:
        suffix = (f".p{manifest['process_index']}"
                  if manifest.get("process_count", 1) > 1 else "")
        fname = base + suffix
        if fname not in manifest["files"]:
            return None
        fpath = os.path.join(path, fname + ".npz")
        if verify:
            _verify_file(fpath, manifest["files"][fname])
        data = dict(np.load(fpath))
        shard_meta = manifest.get("shards", {}).get(fname, {})
        index = shard_meta.get("index", {})
        shapes = shard_meta.get("full_shapes", {})
        for key, arr in data.items():
            if "@@" not in key:
                flat[key] = arr
                continue
            base_key, i = key.rsplit("@@", 1)
            buf = pending.get(base_key)
            if buf is None:
                buf = pending[base_key] = np.zeros(
                    shapes[base_key], arr.dtype)
            slices = tuple(slice(a, b) for a, b in index[base_key][i])
            buf[slices] = arr
    flat.update(pending)
    return flat


def load_checkpoint(path: str, params: Dict, opt_state=None, model_state=None,
                    verify: bool = True):
    """Load into the *structure* of the given pytrees; returns
    (step, params, opt_state, model_state). Handles both single-process
    checkpoints and the multi-host per-process shard layout (merges every
    manifest*.json present)."""
    manifests = []
    for fn in sorted(os.listdir(path)):
        if fn.startswith("manifest") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                manifests.append(json.load(f))
    if not manifests:
        raise IOError(f"no manifest in checkpoint dir {path}")
    # a partial multi-host checkpoint (a host died mid-save) must not load:
    # _load_group would silently zero-fill the missing hosts' shards
    want = max(m.get("process_count", 1) for m in manifests)
    have = sorted(m.get("process_index", 0) for m in manifests)
    if have != list(range(want)):
        raise IOError(
            f"incomplete checkpoint {path}: have manifests for processes "
            f"{have} of {want} — a host's save did not finish")
    out = []
    for base, tree in (("params", params), ("opt_state", opt_state),
                       ("model_state", model_state)):
        if tree is None:
            out.append(tree)
            continue
        flat = _load_group(path, base, manifests, verify)
        out.append(_unflatten_into(flat, tree) if flat is not None else tree)
    return (manifests[0]["step"], *out)


def load_pipeline_state(path: str, process_index: int = 0,
                        verify: bool = True) -> Optional[dict]:
    """Read the input-pipeline stream position saved with this
    checkpoint (or None for checkpoints written without one — every
    pre-pipeline checkpoint stays loadable)."""
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return None
    for fn in names:
        if not (fn.startswith("manifest") and fn.endswith(".json")):
            continue
        with open(os.path.join(path, fn)) as f:
            manifest = json.load(f)
        if manifest.get("process_index", 0) != process_index:
            continue
        for bname, digest in manifest.get("blobs", {}).items():
            if bname == "pipeline" or bname.startswith("pipeline.p"):
                bpath = os.path.join(path, bname + ".pkl")
                if verify:
                    _verify_file(bpath, digest)
                with open(bpath, "rb") as f:
                    return pickle.load(f)
    return None


class AsyncCheckpointer:
    """Asynchronous checkpoint writer.

    ``save()`` snapshots the pytrees to host (one blocking device→host
    copy — unavoidable with donated buffers: the next step reuses the
    device memory) and enqueues serialization + checksum + disk IO +
    pruning on a worker thread. Training resumes immediately; call
    ``wait()`` before reading the directory or exiting."""

    def __init__(self, save_dir: str, keep: int = 3, max_pending: int = 2):
        self.save_dir = save_dir
        self.keep = keep
        self._q = queue.Queue(maxsize=max_pending)
        self._err = None
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            step, host_trees, blobs, meta = item
            try:
                self._write(step, host_trees, blobs, meta)
            except Exception as e:  # surfaced on next save()/wait()
                self._err = e
            finally:
                self._q.task_done()

    def _write(self, step, host_trees, blobs=None, meta=None):
        _write_single(self.save_dir, step,
                      {base: None for base in host_trees}, self.keep,
                      host_trees=host_trees, blobs=blobs, meta=meta)

    def save(self, step: int, params: Dict, opt_state=None,
             model_state=None, pipeline_state=None, meta=None):
        """``pipeline_state`` is pickled HERE, on the caller's thread —
        the pipeline keeps mutating as training continues, so the worker
        must serialize a frozen snapshot, not a live reference.
        ``meta``: JSON-able layout metadata for the manifest (see
        ``save_checkpoint``)."""
        if self._err is not None:
            err, self._err = self._err, None
            raise err
        host_trees = {}
        for fname, tree in (("params", params), ("opt_state", opt_state),
                            ("model_state", model_state)):
            if tree is not None:
                host_trees[fname] = {k: np.asarray(v)
                                     for k, v in _flatten(tree).items()}
        blobs = None
        if pipeline_state is not None:
            blobs = {"pipeline": pickle.dumps(pipeline_state, protocol=4)}
        self._q.put((int(step), host_trees, blobs, meta))

    def wait(self):
        self._q.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def close(self):
        try:
            self.wait()
        finally:
            # shut the worker down even when wait() surfaces a write error
            self._q.put(None)
            self._worker.join(timeout=10)
