"""Checkpoint save/resume.

Reference: paddle/trainer/ParamUtil.cpp (per-pass dirs save_dir/pass-%05d,
--init_model_path/--start_pass resume) + Go pserver disk checkpoints with
checksum + etcd meta (go/pserver/service.go:119-174).

TPU-native: one directory per checkpoint holding a numpy .npz per pytree
(params / optimizer state / model state) + a JSON manifest with step counter
and a content checksum (the Go pserver's integrity scheme), written
atomically via tempdir+rename. Checksums are computed in bounded-memory
chunks. Two scaling paths:

- ``AsyncCheckpointer`` — snapshots to host synchronously (bounded by one
  device→host copy) and does serialization/checksum/IO/pruning on a worker
  thread, so training never waits on disk (the orbax-style async slot; the
  reference's pserver checkpoints were also written off the serving path,
  go/pserver/service.go:119).
- ``save_checkpoint(..., process_index/process_count)`` — multi-host layout:
  each process writes only its addressable shards to its own npz
  (``params.p{K}.npz``); load merges every process file present. Shard
  overlap is fine (replicated arrays): last writer wins on identical data.

Crash-consistent commit protocol (the elastic-training contract): every
file is written to a hidden tempdir, fsync'd, and published atomically —
single-host by one ``os.rename`` of the whole dir (re-saving an
existing step renames the old dir aside first — the exposure is one
rename syscall, after which the previous period's checkpoint is the
fallback), multi-host by per-file ``os.replace`` with the manifest
moved LAST (the manifest's presence is the commit point), then the
parent directory is fsync'd. A save killed at ANY instant therefore
leaves an intact restorable checkpoint behind; ``latest_checkpoint`` additionally
validates completeness (every process's manifest present) so a torn
multi-host dir is skipped in favour of the previous intact step.
``fence=`` (a callable) gates the commit: when it returns False at
publish time — e.g. a zombie worker from a superseded elastic epoch —
the save aborts with ``CheckpointFencedError`` and nothing is
published. Chaos hooks (``runtime/chaos.py`` site ``checkpoint``,
phases pre_write/pre_manifest/pre_commit/mid_commit) let tests
interrupt each window.

ZeRO resharding (``meta.zero`` manifest path): single-host saves hold
FULL host arrays — ``np.asarray`` on a ZeRO-sharded leaf (stage>=1 opt
state, stage 3 params) gathers its shards — so a restore under a
DIFFERENT zero stage or mesh size IS the reshard: the trainer
device_puts the loaded full arrays into the current config's layout and
logs the layout change it read from ``meta.zero``. Multi-host saves keep
per-shard entries; ``_load_group`` reassembles the full array before the
same re-layout. Proven save@zero=3/data=4 → restore@zero∈{0,1,2} and
data=2 in tests/test_zero.py::TestZeroCheckpointResharding.
"""

import hashlib
import json
import os
import pickle
import queue
import tempfile
import threading
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np


class CheckpointFencedError(RuntimeError):
    """A save's commit fence rejected the publish — the writer belongs
    to a superseded coordination epoch and must not commit."""


def _chaos(phase: str, step: int):
    """Checkpoint-site chaos hook (no-op unless PADDLE_TPU_CHAOS set)."""
    if os.environ.get("PADDLE_TPU_CHAOS"):
        from paddle_tpu.runtime import chaos
        chaos.maybe_trigger("checkpoint", phase=phase, step=step)


def _fsync_path(path):
    """fsync one file or directory; directory fsync makes the rename
    itself durable. Best-effort: some filesystems refuse dir fsync."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _flatten(tree, prefix=""):
    """Flatten nested dict/tuple pytrees of arrays into {path: array}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}__{i}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten_into(flat: Dict[str, np.ndarray], tree):
    """Rebuild values in the structure of `tree` from flat paths."""
    def build(subtree, prefix):
        if isinstance(subtree, dict):
            return {k: build(v, f"{prefix}{k}/") for k, v in subtree.items()}
        if isinstance(subtree, (list, tuple)):
            vals = [build(v, f"{prefix}__{i}/") for i, v in enumerate(subtree)]
            return type(subtree)(vals)
        return jnp.asarray(flat[prefix.rstrip("/")])
    return build(tree, "")


def _file_md5(path):
    """Chunked digest — npz writing seeks (zip headers), so a write-through
    hash cannot work; a 1MB-chunk re-read keeps memory bounded (the old
    path read whole files into memory)."""
    h = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def _local_shards(arr):
    """[(index_tuple_of_slices, np_shard)] for this process's addressable
    shards; a single [(None, full_array)] for unsharded/numpy values."""
    shards = getattr(arr, "addressable_shards", None)
    fully_local = getattr(arr, "is_fully_addressable", True)
    if shards is None or (fully_local and len(shards) <= 1):
        return [(None, np.asarray(arr))]
    seen, out = set(), []
    for s in shards:
        key = tuple((sl.start, sl.stop, sl.step) for sl in s.index)
        if key in seen:
            continue            # replicated across devices: write once
        seen.add(key)
        out.append((s.index, np.asarray(s.data)))
    return out


def _write_tree(tmp, fname, tree, manifest, sharded, host_trees=None):
    flat = host_trees[fname] if host_trees else _flatten(tree)
    path = os.path.join(tmp, fname + ".npz")
    entries, index_meta = {}, {}
    for key, arr in flat.items():
        if not sharded:
            entries[key] = np.asarray(arr)
            continue
        for i, (idx, shard) in enumerate(_local_shards(arr)):
            if idx is None:
                entries[key] = np.asarray(shard)
            else:
                entries[f"{key}@@{i}"] = shard
                index_meta.setdefault(key, {})[str(i)] = [
                    [sl.start, sl.stop] for sl in idx]
    with open(path, "wb") as raw:
        np.savez(raw, **entries)
    manifest["files"][fname] = _file_md5(path)
    if index_meta:
        manifest.setdefault("shards", {})[fname] = {
            "full_shapes": {k: list(np.shape(v)) for k, v in flat.items()},
            "index": index_meta}


#: incomplete dirs younger than this are presumed to be a peer host's
#: still-publishing save, not a corpse, and are never pruned
_TORN_PRUNE_GRACE_S = 900.0


def _prune_old(save_dir, keep):
    import shutil
    import time as _time
    names = sorted(d for d in os.listdir(save_dir) if d.startswith("ckpt-"))
    if not names:
        return
    # the keep budget counts COMPLETE checkpoints only: torn dirs (a
    # host died mid-publish) must not evict restorable state — else a
    # run of torn saves would leave nothing to restore. Torn dirs are
    # collected only once their mtime is stale past the grace (a slower
    # peer may still be publishing into a recent one — its os.replace
    # must not race a rmtree), and the newest entry is always spared.
    now = _time.time()
    complete, stale_torn = [], []
    for d in names:
        p = os.path.join(save_dir, d)
        if is_complete(p):
            complete.append(d)
        else:
            try:
                age = now - os.path.getmtime(p)
            except OSError:
                continue
            if age > _TORN_PRUNE_GRACE_S:
                stale_torn.append(d)
    keep_set = set(complete[-keep:])
    keep_set.add(names[-1])
    for d in names:
        if d in keep_set:
            continue
        if d in complete or d in stale_torn:
            shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)


def _write_single(save_dir, step, trees, keep, host_trees=None,
                  sharded=False, process_index=0, process_count=1,
                  blobs=None, meta=None, fence=None):
    """Shared atomic-write core for save_checkpoint and AsyncCheckpointer.
    ``trees``: {fname: pytree} (ignored per-entry when host_trees carries
    the pre-flattened host copy). ``blobs``: {name: bytes} opaque
    payloads (the pipeline's pickled stream position) written verbatim
    as ``<name><suffix>.pkl`` with their checksum in the manifest.
    ``meta``: JSON-able layout metadata (e.g. the ZeRO sharding layout
    the state was trained under) stored in the manifest — restores onto
    a different mesh read it to know a reshard is happening.
    ``fence``: callable checked immediately before the publish (and
    again at the multi-host manifest move, the per-process commit
    point); False aborts with ``CheckpointFencedError``. A deposition
    landing INSIDE the final rename syscall can still commit — the
    window is one rename wide, the same bounded guarantee as the
    master's snapshot fencing (runtime/supervisor.py epoch fencing)."""
    name = f"ckpt-{step:08d}"
    final = os.path.join(save_dir, name)
    os.makedirs(save_dir, exist_ok=True)
    suffix = f".p{process_index}" if process_count > 1 else ""
    tmp = tempfile.mkdtemp(dir=save_dir, prefix=".tmp-" + name + suffix)
    try:
        _chaos("pre_write", step)
        manifest = {"step": int(step), "files": {},
                    "process_index": process_index,
                    "process_count": process_count}
        # stamp the gang incarnation (elastic env contract) so a dir
        # holding pieces from TWO save attempts — torn, restarted,
        # re-torn at the same step — is judged incomplete instead of
        # silently merging shards across incarnations
        if os.environ.get("PADDLE_ELASTIC_EPOCH"):
            try:
                manifest["save_epoch"] = int(
                    os.environ["PADDLE_ELASTIC_EPOCH"])
            except ValueError:
                pass
        if meta is not None:
            manifest["meta"] = meta
        for base, tree in trees.items():
            if tree is None and not (host_trees and base in host_trees):
                continue
            _write_tree(tmp, base + suffix, tree, manifest, sharded,
                        host_trees={base + suffix: host_trees[base]}
                        if host_trees else None)
        for bname, data in (blobs or {}).items():
            bpath = os.path.join(tmp, bname + suffix + ".pkl")
            with open(bpath, "wb") as f:
                f.write(data)
            manifest.setdefault("blobs", {})[bname + suffix] = \
                _file_md5(bpath)
        _chaos("pre_manifest", step)
        with open(os.path.join(tmp, f"manifest{suffix}.json"), "w") as f:
            json.dump(manifest, f)
        # durability before visibility: every byte reaches disk while the
        # checkpoint is still invisible to readers, so the publish below
        # can never expose data the kernel might lose in a host crash
        for fn in os.listdir(tmp):
            _fsync_path(os.path.join(tmp, fn))
        _fsync_path(tmp)
        _chaos("pre_commit", step)
        if fence is not None and not fence():
            raise CheckpointFencedError(
                f"checkpoint step {step} not committed: fence rejected "
                f"the publish (superseded coordination epoch?)")
        if process_count > 1:
            # multi-host: move our files into the shared dir; process 0
            # owns directory lifecycle, others only add their piece. The
            # manifest moves LAST — its presence is this process's commit
            # point, so a reader that sees all manifests sees all data
            # files too (and latest_checkpoint skips dirs missing any
            # process's manifest).
            os.makedirs(final, exist_ok=True)
            if process_index == 0:
                # re-saving into a dir a LARGER previous gang tore
                # mid-publish (elastic shrink): stale .pK pieces with
                # K >= the new process_count have no writer anymore and
                # would make completeness unsatisfiable forever — drop
                # them so the dir converges to the new cohort
                import re
                for fn in os.listdir(final):
                    m = re.search(r"\.p(\d+)\.", fn)
                    if m and int(m.group(1)) >= process_count:
                        try:
                            os.unlink(os.path.join(final, fn))
                        except OSError:
                            pass
            manifest_fn = f"manifest{suffix}.json"
            for fn in sorted(os.listdir(tmp),
                             key=lambda n: n == manifest_fn):
                if fn == manifest_fn:
                    _chaos("mid_commit", step)
                    # re-check the fence AT the commit point: the
                    # manifest move is what makes this piece visible,
                    # so a deposition during the data-file moves still
                    # aborts (the residual window is one rename wide —
                    # the same bounded guarantee as the master's
                    # snapshot fencing)
                    if fence is not None and not fence():
                        raise CheckpointFencedError(
                            f"checkpoint step {step} not committed: "
                            f"fence rejected the manifest publish")
                os.replace(os.path.join(tmp, fn), os.path.join(final, fn))
            # the renames INTO final are directory metadata of final
            # itself — without this fsync the manifest entry can vanish
            # in a host crash after the 'commit'
            _fsync_path(final)
            os.rmdir(tmp)
        else:
            import shutil
            aside = None
            if os.path.exists(final):
                # re-saving an existing step (restore + re-executed
                # window): move the old dir ASIDE by rename — the
                # exposure is one rename syscall, not an rmtree's
                # seconds — publish, then collect the corpse
                aside = f"{tmp}.old"
                os.rename(final, aside)
            os.rename(tmp, final)
            if aside is not None:
                shutil.rmtree(aside, ignore_errors=True)
        _fsync_path(save_dir)
    except BaseException:
        # an aborted save must not strand its tempdir as save_dir litter
        # (the chaos kill/hang paths never reach here — their leftover
        # .tmp-* dirs are invisible to latest_checkpoint by prefix)
        import shutil
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    if process_index == 0:
        _prune_old(save_dir, keep)
    return final


def save_checkpoint(save_dir: str, step: int, params: Dict,
                    opt_state=None, model_state=None, keep: int = 3,
                    process_index: int = 0, process_count: int = 1,
                    sharded: bool = False, pipeline_state=None,
                    meta=None, fence=None):
    """Write checkpoint 'pass-%05d' style dir; prunes old ones.

    With ``sharded=True`` (or process_count>1) each array entry stores this
    process's addressable shards plus their index metadata — the multi-host
    layout where every host writes only what it owns.

    ``pipeline_state``: the input pipeline's ``state_dict()`` (source
    cursor, shuffle RNG + buffer, batch counter) — persisted next to the
    model so a restore continues the data stream mid-epoch on the exact
    next batch (``load_pipeline_state``)."""
    blobs = None
    if pipeline_state is not None:
        blobs = {"pipeline": pickle.dumps(pipeline_state, protocol=4)}
    return _write_single(
        save_dir, step,
        {"params": params, "opt_state": opt_state,
         "model_state": model_state},
        keep, sharded=sharded or process_count > 1,
        process_index=process_index, process_count=process_count,
        blobs=blobs, meta=meta, fence=fence)


def checkpoint_meta(path: str) -> Optional[dict]:
    """The layout metadata stored with a checkpoint (``meta=`` at save
    time; e.g. the ZeRO optimizer-state layout) — None for checkpoints
    written without any, so every older checkpoint stays loadable."""
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return None
    for fn in names:
        if fn.startswith("manifest") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                manifest = json.load(f)
            if manifest.get("meta") is not None:
                return manifest["meta"]
    return None


def _read_manifests(path):
    """Every manifest*.json in a checkpoint dir, parsed (may be [])."""
    manifests = []
    for fn in sorted(os.listdir(path)):
        if fn.startswith("manifest") and fn.endswith(".json"):
            with open(os.path.join(path, fn)) as f:
                manifests.append(json.load(f))
    return manifests


def _check_complete(manifests, path):
    """Raise IOError unless every participating process's manifest is
    present — a partial multi-host checkpoint (a host died mid-save)
    must not load: _load_group would silently zero-fill the missing
    hosts' shards."""
    if not manifests:
        raise IOError(f"no manifest in checkpoint dir {path}")
    want = max(m.get("process_count", 1) for m in manifests)
    have = sorted(m.get("process_index", 0) for m in manifests)
    if have != list(range(want)):
        raise IOError(
            f"incomplete checkpoint {path}: have manifests for processes "
            f"{have} of {want} — a host's save did not finish")
    # all pieces must come from ONE save incarnation: a torn dir
    # re-written by a restarted gang can transiently hold old-epoch and
    # new-epoch manifests that happen to cover every index. An
    # UNSTAMPED manifest (no elastic env) is a wildcard — a host that
    # lost the env var must not brick an otherwise consistent save.
    epochs = {m.get("save_epoch") for m in manifests} - {None}
    if len(epochs) > 1:
        raise IOError(
            f"incomplete checkpoint {path}: manifests from mixed save "
            f"incarnations {sorted(epochs)}")


def is_complete(path: str) -> bool:
    """True when the checkpoint dir is a committed, loadable unit (all
    manifests present and parseable). Cheap: reads only the manifests."""
    try:
        _check_complete(_read_manifests(path), path)
        return True
    except (OSError, ValueError, KeyError):
        return False


def latest_checkpoint(save_dir: str,
                      complete_only: bool = True) -> Optional[str]:
    """Newest COMMITTED checkpoint dir (or None). A save interrupted
    mid-publish (multi-host manifest-last window) leaves a torn
    ``ckpt-*`` dir; with ``complete_only`` (the default) such dirs are
    skipped so a restore falls back to the previous intact step instead
    of dying on the torn one — the crash-consistency contract the
    elastic supervisor restarts depend on."""
    if not os.path.isdir(save_dir):
        return None
    for d in sorted((d for d in os.listdir(save_dir)
                     if d.startswith("ckpt-")), reverse=True):
        path = os.path.join(save_dir, d)
        if not complete_only or is_complete(path):
            return path
    return None


def _verify_file(fpath, want):
    if _file_md5(fpath) != want:
        raise IOError(f"checkpoint checksum mismatch: {fpath}")


def _load_group(path, base, manifests, verify):
    """Merge a logical tree ('params') across every process file present,
    reassembling sharded entries from their index metadata."""
    flat, pending = {}, {}
    for manifest in manifests:
        suffix = (f".p{manifest['process_index']}"
                  if manifest.get("process_count", 1) > 1 else "")
        fname = base + suffix
        if fname not in manifest["files"]:
            return None
        fpath = os.path.join(path, fname + ".npz")
        if verify:
            _verify_file(fpath, manifest["files"][fname])
        data = dict(np.load(fpath))
        shard_meta = manifest.get("shards", {}).get(fname, {})
        index = shard_meta.get("index", {})
        shapes = shard_meta.get("full_shapes", {})
        for key, arr in data.items():
            if "@@" not in key:
                flat[key] = arr
                continue
            base_key, i = key.rsplit("@@", 1)
            buf = pending.get(base_key)
            if buf is None:
                buf = pending[base_key] = np.zeros(
                    shapes[base_key], arr.dtype)
            slices = tuple(slice(a, b) for a, b in index[base_key][i])
            buf[slices] = arr
    flat.update(pending)
    return flat


def load_checkpoint(path: str, params: Dict, opt_state=None, model_state=None,
                    verify: bool = True):
    """Load into the *structure* of the given pytrees; returns
    (step, params, opt_state, model_state). Handles both single-process
    checkpoints and the multi-host per-process shard layout (merges every
    manifest*.json present)."""
    manifests = _read_manifests(path)
    _check_complete(manifests, path)
    out = []
    for base, tree in (("params", params), ("opt_state", opt_state),
                       ("model_state", model_state)):
        if tree is None:
            out.append(tree)
            continue
        flat = _load_group(path, base, manifests, verify)
        out.append(_unflatten_into(flat, tree) if flat is not None else tree)
    return (manifests[0]["step"], *out)


def load_pipeline_state(path: str, process_index: int = 0,
                        verify: bool = True) -> Optional[dict]:
    """Read the input-pipeline stream position saved with this
    checkpoint (or None for checkpoints written without one — every
    pre-pipeline checkpoint stays loadable)."""
    try:
        names = sorted(os.listdir(path))
    except OSError:
        return None
    for fn in names:
        if not (fn.startswith("manifest") and fn.endswith(".json")):
            continue
        with open(os.path.join(path, fn)) as f:
            manifest = json.load(f)
        if manifest.get("process_index", 0) != process_index:
            continue
        for bname, digest in manifest.get("blobs", {}).items():
            if bname == "pipeline" or bname.startswith("pipeline.p"):
                bpath = os.path.join(path, bname + ".pkl")
                if verify:
                    _verify_file(bpath, digest)
                with open(bpath, "rb") as f:
                    return pickle.load(f)
    return None


class AsyncCheckpointer:
    """Asynchronous checkpoint writer.

    ``save()`` snapshots the pytrees to host (one blocking device→host
    copy — unavoidable with donated buffers: the next step reuses the
    device memory) and enqueues serialization + checksum + disk IO +
    pruning on a worker thread. Training resumes immediately; call
    ``wait()`` before reading the directory or exiting."""

    def __init__(self, save_dir: str, keep: int = 3, max_pending: int = 2,
                 fence=None):
        """``fence``: commit gate checked by the worker thread right
        before each publish (see ``_write_single``) — a fenced save
        surfaces as ``CheckpointFencedError`` on the next save()/wait()."""
        self.save_dir = save_dir
        self.keep = keep
        self.fence = fence
        self._q = queue.Queue(maxsize=max_pending)
        self._err = None
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def _run(self):
        while True:
            item = self._q.get()
            if item is None:
                return
            step, host_trees, blobs, meta = item
            try:
                self._write(step, host_trees, blobs, meta)
            except Exception as e:  # surfaced on next save()/wait()
                self._err = e
            finally:
                self._q.task_done()

    def _write(self, step, host_trees, blobs=None, meta=None):
        _write_single(self.save_dir, step,
                      {base: None for base in host_trees}, self.keep,
                      host_trees=host_trees, blobs=blobs, meta=meta,
                      fence=self.fence)

    def save(self, step: int, params: Dict, opt_state=None,
             model_state=None, pipeline_state=None, meta=None):
        """``pipeline_state`` is pickled HERE, on the caller's thread —
        the pipeline keeps mutating as training continues, so the worker
        must serialize a frozen snapshot, not a live reference.
        ``meta``: JSON-able layout metadata for the manifest (see
        ``save_checkpoint``)."""
        if self._err is not None:
            err, self._err = self._err, None
            raise err
        host_trees = {}
        for fname, tree in (("params", params), ("opt_state", opt_state),
                            ("model_state", model_state)):
            if tree is not None:
                host_trees[fname] = {k: np.asarray(v)
                                     for k, v in _flatten(tree).items()}
        blobs = None
        if pipeline_state is not None:
            blobs = {"pipeline": pickle.dumps(pipeline_state, protocol=4)}
        self._q.put((int(step), host_trees, blobs, meta))

    def wait(self):
        self._q.join()
        if self._err is not None:
            err, self._err = self._err, None
            raise err

    def close(self):
        try:
            self.wait()
        finally:
            # shut the worker down even when wait() surfaces a write error
            self._q.put(None)
            self._worker.join(timeout=10)
