"""Checkpoint save/resume.

Reference: paddle/trainer/ParamUtil.cpp (per-pass dirs save_dir/pass-%05d,
--init_model_path/--start_pass resume) + Go pserver disk checkpoints with
checksum + etcd meta (go/pserver/service.go:119-174).

TPU-native: one directory per checkpoint holding a numpy .npz per pytree
(params / optimizer state / model state) + a JSON manifest with step counter
and a content checksum (the Go pserver's integrity scheme). Async-friendly:
arrays are pulled to host once, written atomically via tempfile+rename.
"""

import hashlib
import json
import os
import tempfile
from typing import Dict, Optional

import jax.numpy as jnp
import numpy as np


def _flatten(tree, prefix=""):
    """Flatten nested dict/tuple pytrees of arrays into {path: array}."""
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            out.update(_flatten(v, f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten(v, f"{prefix}__{i}/"))
    else:
        out[prefix.rstrip("/")] = np.asarray(tree)
    return out


def _unflatten_into(flat: Dict[str, np.ndarray], tree):
    """Rebuild values in the structure of `tree` from flat paths."""
    def build(subtree, prefix):
        if isinstance(subtree, dict):
            return {k: build(v, f"{prefix}{k}/") for k, v in subtree.items()}
        if isinstance(subtree, (list, tuple)):
            vals = [build(v, f"{prefix}__{i}/") for i, v in enumerate(subtree)]
            return type(subtree)(vals)
        return jnp.asarray(flat[prefix.rstrip("/")])
    return build(tree, "")


def save_checkpoint(save_dir: str, step: int, params: Dict,
                    opt_state=None, model_state=None, keep: int = 3):
    """Write checkpoint 'pass-%05d' style dir; prunes old ones."""
    name = f"ckpt-{step:08d}"
    final = os.path.join(save_dir, name)
    os.makedirs(save_dir, exist_ok=True)
    tmp = tempfile.mkdtemp(dir=save_dir, prefix=".tmp-" + name)
    manifest = {"step": int(step), "files": {}}
    for fname, tree in (("params", params), ("opt_state", opt_state),
                        ("model_state", model_state)):
        if tree is None:
            continue
        flat = _flatten(tree)
        path = os.path.join(tmp, fname + ".npz")
        np.savez(path, **flat)
        with open(path, "rb") as f:
            manifest["files"][fname] = hashlib.md5(f.read()).hexdigest()
    with open(os.path.join(tmp, "manifest.json"), "w") as f:
        json.dump(manifest, f)
    if os.path.exists(final):
        import shutil
        shutil.rmtree(final)
    os.rename(tmp, final)
    # prune
    kept = sorted(d for d in os.listdir(save_dir) if d.startswith("ckpt-"))
    for d in kept[:-keep]:
        import shutil
        shutil.rmtree(os.path.join(save_dir, d), ignore_errors=True)
    return final


def latest_checkpoint(save_dir: str) -> Optional[str]:
    if not os.path.isdir(save_dir):
        return None
    cks = sorted(d for d in os.listdir(save_dir) if d.startswith("ckpt-"))
    return os.path.join(save_dir, cks[-1]) if cks else None


def load_checkpoint(path: str, params: Dict, opt_state=None, model_state=None,
                    verify: bool = True):
    """Load into the *structure* of the given pytrees; returns
    (step, params, opt_state, model_state)."""
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    out = []
    for fname, tree in (("params", params), ("opt_state", opt_state),
                        ("model_state", model_state)):
        if tree is None or fname not in manifest["files"]:
            out.append(tree)
            continue
        fpath = os.path.join(path, fname + ".npz")
        if verify:
            with open(fpath, "rb") as f:
                if hashlib.md5(f.read()).hexdigest() != manifest["files"][fname]:
                    raise IOError(f"checkpoint checksum mismatch: {fpath}")
        flat = dict(np.load(fpath))
        out.append(_unflatten_into(flat, tree))
    return (manifest["step"], *out)
