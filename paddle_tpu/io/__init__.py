"""Checkpointing and model artifacts (reference: trainer/ParamUtil.cpp
per-pass save dirs, v2 parameters.to_tar, operators/save_op.cc/load_op.cc,
trainer/MergeModel.cpp)."""

from paddle_tpu.io.checkpoint import (load_checkpoint, save_checkpoint,
                                      latest_checkpoint)
from paddle_tpu.io.merged import (save_inference_model, load_inference_model,
                                  MergedModel)
from paddle_tpu.io.lm_serving import (save_lm_artifact, load_lm_artifact,
                                      LMServer)
