"""Merged-model serving artifacts — one file holding topology + weights.

Reference: paddle/trainer/MergeModel.cpp packed the ModelConfig proto and
the parameter files into a single binary consumed by the C inference API
(paddle/capi/gradient_machine.h:36-88, create_for_inference_with_parameters);
multi-thread serving cloned the machine sharing parameters (:88).

TPU-native equivalents, both in one tar:

- **replayable topology** (``topology.json``): Topology.to_dict records of
  the public layer-API calls; the loader replays them (Topology.from_dict)
  and jit-compiles forward — works for any batch size, needs the
  paddle_tpu package but NOT the user's model-building code.
- **AOT StableHLO export** (``exported.bin``): jax.export serialization of
  the jitted forward at fixed example shapes — runs with zero model code,
  the capi-style deployment surface; compile happens at save time
  (jit().lower() under the hood), load is compile-free on the same
  platform.
"""

import io as _io
import json
import tarfile
import time
from typing import Dict, Optional, Sequence

import numpy as np

FORMAT_VERSION = 2   # max supported; plain artifacts still save as v1


def _npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = _io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    return buf.getvalue()


def _npz_load(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(_io.BytesIO(data), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def _add_member(tar, name, data: bytes):
    info = tarfile.TarInfo(name)
    info.size = len(data)
    info.mtime = int(time.time())
    tar.addfile(info, _io.BytesIO(data))


def _serve_fn(topology):
    """forward(params, state, feeds-of-arrays) -> {output: array}; plain
    containers only, so jax.export can serialize the calling convention.
    Sequence inputs pass their lengths as a sibling '<name>.lengths' key.
    Quantized weight entries ({"q8","scale"} nodes, weights_int8
    artifacts) dequantize at entry — per call on the exported path."""
    from paddle_tpu.ops import q8 as ops_q8
    from paddle_tpu.topology import Value

    fwd = topology.compile()

    def serve(params, state, feeds):
        params = ops_q8.dequantize_tree(params)
        vals = {k: Value(v, lengths=feeds.get(f"{k}.lengths"))
                for k, v in feeds.items() if not k.endswith(".lengths")}
        outs, _ = fwd(params, state, vals, is_training=False)
        return {k: v.array for k, v in outs.items()}

    return serve


# npz holds a FLAT name->array dict; quantized entries ride two suffixed
# keys and are reassembled into {"q8","scale"} nodes at load
_Q8_KEY, _Q8_SCALE_KEY = "@q8", "@q8scale"


def quantize_v2_params(values, min_size: int = 4096):
    """Per-output-channel int8 for the v2 parameter dict's big matmul/conv
    weights (name '*.w', ndim >= 2, float, >= min_size elements): the
    contraction axes are everything but the trailing output-channel axis
    (fc [in, out]; conv HWIO; embeddings get per-column scales). Biases,
    BN affines, and small tensors stay fp32."""
    import numpy as _np
    from paddle_tpu.ops import q8 as ops_q8

    out = {}
    for k, v in values.items():
        a = _np.asarray(v)
        if (k.endswith(".w") and a.ndim >= 2 and a.size >= min_size
                and _np.issubdtype(a.dtype, _np.floating)):
            out[k] = ops_q8.quantize_weight(a, tuple(range(a.ndim - 1)))
        else:
            out[k] = v
    return out


def _split_quantized(values):
    """{name: array-or-node} -> flat npz dict with suffixed q8 keys."""
    from paddle_tpu.ops import q8 as ops_q8

    flat = {}
    for k, v in values.items():
        if ops_q8.is_quantized_weight(v):
            flat[k + _Q8_KEY] = np.asarray(v["q8"])
            flat[k + _Q8_SCALE_KEY] = np.asarray(v["scale"])
        else:
            flat[k] = np.asarray(v)
    return flat


def _join_quantized(flat):
    """Inverse of _split_quantized."""
    values = {}
    for k, v in flat.items():
        if k.endswith(_Q8_SCALE_KEY):
            continue
        if k.endswith(_Q8_KEY):
            name = k[: -len(_Q8_KEY)]
            values[name] = {"q8": v, "scale": flat[name + _Q8_SCALE_KEY]}
        else:
            values[k] = v
    return values


def example_feeds(topology, batch_size: int) -> Dict[str, np.ndarray]:
    """Zero-filled feed arrays matching the topology's data specs."""
    from paddle_tpu.data_type import Kind, SeqLevel

    feeds = {}
    for l in topology.data_layers:
        spec = l.data_spec
        if spec is None:
            raise ValueError(f"data layer {l.name!r} has no data spec")
        if spec.kind == Kind.INDEX:
            shape = (batch_size,) if spec.seq == SeqLevel.NO_SEQUENCE \
                else (batch_size, 16)
            feeds[l.name] = np.zeros(shape, np.int32)
        else:
            shape = (batch_size, spec.dim) if spec.seq == SeqLevel.NO_SEQUENCE \
                else (batch_size, 16, spec.dim)
            feeds[l.name] = np.zeros(shape, np.float32)
        if spec.seq != SeqLevel.NO_SEQUENCE:
            feeds[f"{l.name}.lengths"] = np.full((batch_size,), 16, np.int32)
    return feeds


def save_inference_model(path: str, output_layer, parameters,
                         export_batch_sizes: Sequence[int] = (),
                         platforms: Optional[Sequence[str]] = None,
                         weights_int8: bool = False) -> None:
    """Write the one-file serving artifact.

    output_layer: LayerOutput or list; parameters: paddle.parameters
    Parameters (or any object with .values/.state dicts).
    export_batch_sizes: also AOT-export the forward at these fixed batch
    sizes (jax.export) for the zero-model-code deployment path.
    weights_int8: store the big '*.w' weights per-output-channel int8
    (quantize_v2_params); the serve path dequantizes at entry, so both
    the replayed topology and the AOT exports consume the quantized
    artifact unchanged.
    """
    import jax
    from paddle_tpu.topology import Topology

    outputs = output_layer if isinstance(output_layer, (list, tuple)) \
        else [output_layer]
    topo = Topology(list(outputs))
    rebuildable = topo.is_rebuildable()
    if not rebuildable and not export_batch_sizes:
        raise ValueError(
            "topology has unrecordable layers and no export_batch_sizes "
            "were given — the artifact would not be servable; pass "
            "export_batch_sizes=[...] to AOT-export instead")

    values = parameters.values
    if weights_int8:
        values = quantize_v2_params(values)

    meta = {
        # quantized artifacts use the v2 params encoding (@q8 suffixed
        # npz keys); plain artifacts stay v1 so older loaders keep working
        "format_version": 2 if weights_int8 else 1,
        "weights_int8": weights_int8,
        "outputs": [o.name for o in topo.outputs],
        "data_layers": topo.data_names(),
        "data_specs": {l.name: [l.data_spec.dim, l.data_spec.kind.value,
                                l.data_spec.seq.value]
                       for l in topo.data_layers if l.data_spec is not None},
        "rebuildable": rebuildable,
        "export_batch_sizes": list(export_batch_sizes),
        # per-exported-batch-size FLOPs/bytes from the lowered-HLO cost
        # model (observe.costs): MFU accounting for whatever host serves
        # this artifact, stamped at export time
        "cost_analysis": {},
    }

    with tarfile.open(path, "w") as tar:
        if rebuildable:
            _add_member(tar, "topology.json",
                        json.dumps(topo.to_dict()).encode())
        _add_member(tar, "params.npz",
                    _npz_bytes(_split_quantized(values)))
        _add_member(tar, "state.npz", _npz_bytes(parameters.state))
        if export_batch_sizes:
            import jax.export  # noqa: F401 — needs an explicit import
            from paddle_tpu.observe import costs as _costs
            serve = jax.jit(_serve_fn(topo))
            for bs in export_batch_sizes:
                feeds = example_feeds(topo, bs)
                kw = {}
                if platforms:
                    kw["platforms"] = list(platforms)
                abstract = (
                    jax.tree_util.tree_map(
                        lambda v: jax.ShapeDtypeStruct(
                            np.shape(v),
                            v.dtype if hasattr(v, "dtype")
                            else np.asarray(v).dtype), values),
                    {k: jax.ShapeDtypeStruct(np.shape(v),
                                             np.asarray(v).dtype)
                     for k, v in parameters.state.items()},
                    {k: jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for k, v in feeds.items()})
                exp = jax.export.export(serve, **kw)(*abstract)
                ca = _costs.lowered_cost(serve, *abstract)
                if ca:
                    meta["cost_analysis"][str(bs)] = ca
                _add_member(tar, f"exported_bs{bs}.bin", exp.serialize())
        _add_member(tar, "meta.json", json.dumps(meta).encode())


class MergedModel:
    """Loaded serving artifact (the create_for_inference_with_parameters
    equivalent). ``infer`` uses the replayed topology (any batch size);
    ``call_exported`` uses the AOT module (fixed shapes, no tracing)."""

    def __init__(self, meta, topology, params, state, exported):
        self.meta = meta
        self.topology = topology
        self.params = params
        self.state = state
        self._exported = exported          # {batch_size: Exported|bytes}
        self._jit_forward = None

    @property
    def outputs(self):
        return self.meta["outputs"]

    @property
    def cost_analysis(self):
        """{batch_size: {"flops", "bytes_accessed"}} stamped at export
        time (empty for pre-cost-accounting artifacts)."""
        return {int(k): v for k, v in
                self.meta.get("cost_analysis", {}).items()}

    def _forward(self):
        import jax
        if self._jit_forward is None:
            if self.topology is None:
                raise ValueError("artifact has no replayable topology; "
                                 "use call_exported()")
            self._jit_forward = jax.jit(_serve_fn(self.topology))
        return self._jit_forward

    def infer(self, feeds: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        outs = self._forward()(self.params, self.state, feeds)
        return {k: np.asarray(v) for k, v in outs.items()}

    def aot_compile(self, batch_size: int):
        """Ahead-of-time compile the forward at a fixed batch size
        (jit().lower().compile()); returns the compiled executable."""
        import jax
        if self.topology is None:
            raise ValueError("no replayable topology to compile")
        feeds = example_feeds(self.topology, batch_size)
        return self._forward().lower(self.params, self.state,
                                     feeds).compile()

    def call_exported(self, feeds: Dict[str, np.ndarray],
                      batch_size: Optional[int] = None):
        """Run the AOT StableHLO module — no model code, no tracing."""
        import jax
        bs = batch_size or next(iter(feeds.values())).shape[0]
        if bs not in self._exported:
            raise KeyError(f"no export for batch size {bs}; "
                           f"available: {sorted(self._exported)}")
        exp = self._exported[bs]
        if isinstance(exp, (bytes, bytearray)):
            import jax.export  # noqa: F401 — needs an explicit import
            exp = self._exported[bs] = jax.export.deserialize(bytes(exp))
        outs = exp.call(self.params, self.state, feeds)
        return {k: np.asarray(v) for k, v in outs.items()}


def load_inference_model(path: str) -> MergedModel:
    """Load the artifact in a process that never built the model."""
    from paddle_tpu.topology import Topology

    with tarfile.open(path, "r") as tar:
        members = {m.name: tar.extractfile(m).read()
                   for m in tar.getmembers()}
    meta = json.loads(members["meta.json"])
    if meta["format_version"] > FORMAT_VERSION:
        raise ValueError(f"artifact format {meta['format_version']} is "
                         f"newer than this loader ({FORMAT_VERSION})")
    topo = None
    if "topology.json" in members:
        topo = Topology.from_dict(json.loads(members["topology.json"]))
    params = _join_quantized(_npz_load(members["params.npz"]))
    state = _npz_load(members["state.npz"])
    exported = {}
    for name, data in members.items():
        if name.startswith("exported_bs"):
            exported[int(name[len("exported_bs"):-len(".bin")])] = data
    return MergedModel(meta, topo, params, state, exported)
