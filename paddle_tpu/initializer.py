"""Initializer objects for the fluid-style API surface (reference:
python/paddle/v2/framework/initializer.py — Constant/Uniform/Normal/Xavier/
MSRA initializers). The layer API consumes these through ParamAttr."""

from paddle_tpu.core.param import ParamAttr


def Constant(value=0.0):
    return ParamAttr(initializer="constant", initial_value=value)


def Normal(mean=0.0, std=1.0):
    return ParamAttr(initializer="normal", initial_mean=mean, initial_std=std)


def Uniform(limit=None):
    return ParamAttr(initializer="uniform", initial_std=limit)


def Xavier():
    return ParamAttr(initializer="xavier")


def MSRA():
    return ParamAttr(initializer="msra")
