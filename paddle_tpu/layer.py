"""The declarative layer API — the v2 capability surface.

Reference: python/paddle/trainer_config_helpers/layers.py (7,144 LoC of config
functions) + python/paddle/v2/layer.py (auto-wrapping into v2). Each function
here returns a LayerOutput node holding parameter specs and a pure forward
callable; paddle_tpu.topology.Topology compiles the graph into one traced
function (no protobuf, no config parser — the Python call graph IS the
config).

Image tensors follow the reference's flat-CHW convention at the data boundary
(config_parser stored images as channel-major flat vectors) but flow as NHWC
internally — TPU-native layout.
"""

import math
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu import activation as act_mod
from paddle_tpu import pooling as pooling_mod
from paddle_tpu.core.param import ParamAttr, ParamSpec
from paddle_tpu.ops import activations as ops_act
from paddle_tpu.ops import conv as ops_conv
from paddle_tpu.ops import loss as ops_loss
from paddle_tpu.ops import norm as ops_norm
from paddle_tpu.ops import pool as ops_pool
from paddle_tpu.ops import rnn as ops_rnn
from paddle_tpu.ops import sequence as ops_seq
from paddle_tpu.ops import sparse as ops_sparse
from paddle_tpu.ops import topk as ops_topk
from paddle_tpu.ops.math import linear as ops_linear, matmul
from paddle_tpu.topology import LayerOutput, Value, auto_name
from paddle_tpu.utils import enforce

# the dynamic-RNN DSL lives in paddle_tpu.recurrent; re-exported here to
# mirror the reference surface (trainer_config_helpers/layers.py had
# recurrent_group/memory/beam_search in the same namespace as fc/lstmemory)
from paddle_tpu.recurrent import (recurrent_group, memory, beam_search,
                                  StaticInput, GeneratedInput)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _param_attr(attr, default_name) -> ParamAttr:
    attr = attr or ParamAttr()
    if attr.name is None:
        attr = type(attr)(**{**attr.__dict__, "name": default_name})
    return attr


def _bias_spec(name, size, bias_attr) -> Optional[ParamSpec]:
    """bias_attr False disables bias (reference convention)."""
    if bias_attr is False:
        return None
    attr = bias_attr if isinstance(bias_attr, ParamAttr) else ParamAttr(
        initializer="constant", initial_value=0.0)
    attr = _param_attr(attr, f"{name}.b")
    return ParamSpec(attr.name, (size,), attr=attr)


def _apply_act(value: Value, act_name: str) -> Value:
    """Keeps the pre-activation on the Value so downstream cost layers can
    fuse with the activation in log-space (the reason the reference had a
    fused softmax_with_cross_entropy op)."""
    if act_name == "sequence_softmax":
        enforce.enforce(value.is_sequence, "sequence_softmax needs sequence input")
        return value.with_array(ops_seq.seq_softmax(value.array, value.lengths))
    # only softmax keeps its logits: classification_cost fuses with them,
    # and an unconsumed pre_act would cost a full extra output buffer at
    # jit boundaries for every other activation
    pre = value.array if act_name == "softmax" else None
    return value.with_array(ops_act.get(act_name)(value.array), pre_act=pre)


def _flatten_if_image(x: jax.Array) -> jax.Array:
    """FC over a conv output: flatten NHWC back to CHW-flat so parameter
    layouts match the reference's channel-major convention."""
    if x.ndim == 4:
        n = x.shape[0]
        return jnp.transpose(x, (0, 3, 1, 2)).reshape(n, -1)
    return x


def _feat_size(x: jax.Array) -> int:
    if x.ndim == 4:
        return int(x.shape[1] * x.shape[2] * x.shape[3])
    return int(x.shape[-1])


# ---------------------------------------------------------------------------
# data
# ---------------------------------------------------------------------------

def data(name: str, type):
    """Input declaration (reference: v2 layer.data / DataConfig)."""
    return LayerOutput(name, "data", [], fn=None, size=type.dim, is_data=True,
                       data_spec=type)


# ---------------------------------------------------------------------------
# fc / embedding / mixed-style projections
# ---------------------------------------------------------------------------

def fc(input, size: int, act=None, name: Optional[str] = None,
       param_attr=None, bias_attr=None):
    """Fully-connected over one or more inputs (summed), mirroring
    fc_layer's multi-input form (reference: trainer_config_helpers/layers.py
    fc_layer; gserver/layers/FullyConnectedLayer.cpp)."""
    name = name or auto_name("fc")
    inputs = _as_list(input)
    act_name = act_mod.resolve(act)
    attrs = _as_list(param_attr) if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(inputs)
    specs = []
    for i, (inp, attr) in enumerate(zip(inputs, attrs)):
        suffix = f".w{i}" if len(inputs) > 1 else ".w"
        a = _param_attr(attr if isinstance(attr, ParamAttr) else ParamAttr(),
                        f"{name}{suffix}")
        in_size = inp.size
        specs.append(ParamSpec(a.name, (in_size, size), attr=a, fan_in=in_size))
    bias = _bias_spec(name, size, bias_attr)
    if bias:
        specs.append(bias)

    def fwd(params, parents, ctx):
        total = None
        for spec, pv in zip(specs, parents):
            if pv.is_sparse:
                # sparse input: gather rows of W by nonzero index and
                # weight-sum — sparse matmul without materialising the
                # multi-hot vector (reference: MulOp sparse path,
                # paddle/function/MulOp.cpp)
                rows = jnp.take(params[spec.name], pv.array.astype(jnp.int32),
                                axis=0)                      # [b, k, size]
                out = jnp.sum(rows * pv.weights[..., None].astype(rows.dtype),
                              axis=-2)
            else:
                x = _flatten_if_image(pv.array)
                out = matmul(x, params[spec.name])
            total = out if total is None else total + out
        if bias:
            total = total + params[bias.name].astype(total.dtype)
        v = Value(total, parents[0].lengths, parents[0].sub_lengths)
        return _apply_act(v, act_name)

    return LayerOutput(name, "fc", inputs, fwd, specs, size=size,
                       activation=act_name)


def embedding(input, size: int, name: Optional[str] = None, param_attr=None,
              padding_idx: Optional[int] = None):
    """Embedding lookup (reference: v2 layer.embedding / TableProjection /
    operators/lookup_table_op.cc)."""
    name = name or auto_name("embedding")
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    vocab = input.size
    spec = ParamSpec(a.name, (vocab, size), attr=a, fan_in=size)

    def fwd(params, parents, ctx):
        pv = parents[0]
        out = ops_sparse.embedding_lookup(params[spec.name], pv.array,
                                          padding_idx)
        return Value(out, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "embedding", [input], fwd, [spec], size=size)


# ---------------------------------------------------------------------------
# images
# ---------------------------------------------------------------------------

def _to_nhwc(x: jax.Array, channels: int, img_h: Optional[int],
             img_w: Optional[int]) -> jax.Array:
    if x.ndim == 4:
        return x
    n, flat = x.shape
    if img_h is None:
        side = int(math.isqrt(flat // channels))
        img_h = img_w = side
    x = x.reshape(n, channels, img_h, img_w)      # reference flat layout: CHW
    return jnp.transpose(x, (0, 2, 3, 1))


def _infer_img_shape(input, cin, img_size):
    """Static (H, W) of a layer's image input — the config_parser equivalent
    (reference: python/paddle/trainer/config_parser.py ConvConfig/ImgSize
    computation; it tracked img dims through every conv/pool)."""
    if img_size is not None:
        return (img_size, img_size) if isinstance(img_size, int) \
            else tuple(img_size)
    shp = getattr(input, "_img_shape", None)
    if shp is not None:
        return shp
    if input.size and cin:
        side = int(math.isqrt(input.size // cin))
        if side * side * cin == input.size:
            return (side, side)
    return (None, None)


def _conv_out_dim(in_dim, k, s, pad, dilation=1):
    """Output spatial size, floor mode (matches explicit-pad reduce_window
    and lax conv arithmetic)."""
    if in_dim is None:
        return None
    eff_k = (k - 1) * dilation + 1
    if pad == "SAME":
        return -(-in_dim // s)
    if pad == "VALID":
        p0 = p1 = 0
    elif isinstance(pad, int):
        p0 = p1 = pad
    else:
        p0, p1 = pad
    return (in_dim + p0 + p1 - eff_k) // s + 1


def img_conv(input, filter_size, num_filters: int, num_channels: Optional[int] = None,
             stride=1, padding=None, groups=1, act=None, name: Optional[str] = None,
             param_attr=None, bias_attr=None, img_size=None, dilation=1,
             trans: bool = False):
    """2-D conv layer (reference: img_conv_layer in
    trainer_config_helpers/layers.py; gserver/layers/ExpandConvLayer.cpp;
    operators/conv_op.cc). Accepts flat-CHW or NHWC input; emits NHWC."""
    name = name or auto_name("img_conv")
    act_name = act_mod.resolve(act)
    k = (filter_size, filter_size) if isinstance(filter_size, int) else tuple(filter_size)
    user_padding = padding
    if padding is None:
        padding = ((k[0] - 1) // 2, (k[1] - 1) // 2)  # reference default: same-ish
    a = _param_attr(param_attr or ParamAttr(initializer="msra"), f"{name}.w")
    cin = num_channels
    if cin is None:
        # infer from parent conv layers; flat data needs explicit channels
        cin = getattr(input, "_out_channels", None)
        enforce.enforce(cin is not None,
                        f"img_conv {name}: num_channels required for flat input")
    if trans:
        enforce.enforce(groups == 1 and dilation == 1,
                        "img_conv trans=True supports groups=1, dilation=1")
    # HWIO for both directions: lax.conv_transpose takes the same
    # (kh, kw, cin, cout) filter layout as the forward conv
    wshape = (k[0], k[1], cin // groups, num_filters)
    spec = ParamSpec(a.name, wshape, attr=a, fan_in=k[0] * k[1] * (cin // groups))
    bias = _bias_spec(name, num_filters, bias_attr)
    specs = [spec] + ([bias] if bias else [])
    ih, iw = _infer_img_shape(input, cin, img_size)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if trans:
        if user_padding in (None, "SAME"):
            oh = ih * s[0] if ih else None
            ow = iw * s[1] if iw else None
        else:
            oh = ow = None  # non-SAME transposed shapes resolved at runtime
    else:
        pads = padding if isinstance(padding, str) else (
            (padding, padding) if isinstance(padding, int) else tuple(padding))
        ph = pads if isinstance(pads, str) else pads[0]
        pw = pads if isinstance(pads, str) else pads[1]
        oh = _conv_out_dim(ih, k[0], s[0], ph, dilation)
        ow = _conv_out_dim(iw, k[1], s[1], pw, dilation)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        if trans:
            if user_padding is None:
                tpad = "SAME"
            elif isinstance(user_padding, str):
                tpad = user_padding
            elif isinstance(user_padding, int):
                tpad = ((user_padding, user_padding),) * 2
            else:
                p = tuple(user_padding)
                tpad = ((p[0], p[0]), (p[1], p[1])) if isinstance(p[0], int) \
                    else p
            out = ops_conv.conv2d_transpose(x, params[spec.name], stride=stride,
                                            padding=tpad)
        else:
            out = ops_conv.conv2d(x, params[spec.name], stride=stride,
                                  padding=padding, dilation=dilation,
                                  groups=groups)
        if bias:
            out = out + params[bias.name].astype(out.dtype)
        return _apply_act(Value(out), act_name)

    lo = LayerOutput(name, "img_conv", [input], fwd, specs,
                     size=oh * ow * num_filters if oh and ow else None,
                     activation=act_name)
    lo._out_channels = num_filters
    lo._img_shape = (oh, ow)
    return lo


def img_pool(input, pool_size, stride=None, padding=0, pool_type=None,
             num_channels=None, name: Optional[str] = None, img_size=None):
    """Image pooling (reference: img_pool_layer; gserver PoolLayer.cpp)."""
    name = name or auto_name("img_pool")
    ptype = pooling_mod.resolve(pool_type)
    cin = num_channels or getattr(input, "_out_channels", None)
    ih, iw = _infer_img_shape(input, cin, img_size)
    k = (pool_size, pool_size) if isinstance(pool_size, int) else tuple(pool_size)
    st = stride if stride is not None else pool_size
    st = (st, st) if isinstance(st, int) else tuple(st)
    pad = padding
    oh = _conv_out_dim(ih, k[0], st[0],
                       pad if isinstance(pad, (str, int)) else pad[0])
    ow = _conv_out_dim(iw, k[1], st[1],
                       pad if isinstance(pad, (str, int)) else pad[1])

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        if ptype == "max":
            out = ops_pool.max_pool2d(x, pool_size, stride=stride, padding=padding)
        else:
            out = ops_pool.avg_pool2d(x, pool_size, stride=stride, padding=padding)
        return Value(out)

    lo = LayerOutput(name, "img_pool", [input], fwd, [],
                     size=oh * ow * cin if oh and ow and cin else None)
    lo._out_channels = cin
    lo._img_shape = (oh, ow)
    return lo


def spp(input, pyramid_height: int, num_channels=None, pool_type=None,
        name: Optional[str] = None):
    """Spatial pyramid pooling layer (reference: spp_layer)."""
    name = name or auto_name("spp")
    ptype = pooling_mod.resolve(pool_type)
    cin = num_channels or getattr(input, "_out_channels", None)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, None, None)
        return Value(ops_pool.spp(x, pyramid_height, ptype))

    bins = sum(4 ** l for l in range(pyramid_height))
    return LayerOutput(name, "spp", [input], fwd, [],
                       size=bins * cin if cin else None)


def img_cmrnorm(input, size=5, scale=0.0001, power=0.75, name=None,
                num_channels=None):
    """Cross-map response normalisation — AlexNet LRN (reference:
    img_cmrnorm_layer in trainer_config_helpers/layers.py; runtime
    paddle/function/CrossMapNormalOp.cpp)."""
    name = name or auto_name("cmrnorm")
    cin = num_channels or getattr(input, "_out_channels", None)
    ih, iw = _infer_img_shape(input, cin, None)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        return Value(ops_norm.lrn(x, size=size, alpha=scale, beta=power))

    lo = LayerOutput(name, "cmrnorm", [input], fwd, [], size=input.size)
    lo._out_channels = cin
    lo._img_shape = getattr(input, "_img_shape", (ih, iw))
    return lo


def batch_norm(input, act=None, name: Optional[str] = None, num_channels=None,
               param_attr=None, bias_attr=None, moving_average_fraction=0.9,
               epsilon=1e-5):
    """Batch normalisation with functional running stats (reference:
    batch_norm_layer; gserver/layers/BatchNormalizationLayer.cpp;
    operators/batch_norm_op.cc). Stats live in the state pytree keyed
    '<name>.mean' / '<name>.var'."""
    name = name or auto_name("batch_norm")
    act_name = act_mod.resolve(act)
    cin = num_channels or getattr(input, "_out_channels", None) or input.size
    ga = _param_attr(param_attr if isinstance(param_attr, ParamAttr) else
                     ParamAttr(initializer="constant", initial_value=1.0),
                     f"{name}.gamma")
    ba = _param_attr(bias_attr if isinstance(bias_attr, ParamAttr) else
                     ParamAttr(initializer="constant", initial_value=0.0),
                     f"{name}.beta")
    gamma = ParamSpec(ga.name, (cin,), attr=ga)
    beta = ParamSpec(ba.name, (cin,), attr=ba)
    mean_s = ParamSpec(f"{name}.mean", (cin,),
                       attr=ParamAttr(initializer="constant", initial_value=0.0))
    var_s = ParamSpec(f"{name}.var", (cin,),
                      attr=ParamAttr(initializer="constant", initial_value=1.0))

    def fwd(params, parents, ctx):
        x = parents[0].array
        if x.ndim == 2 and x.shape[-1] != cin:
            # flat CHW image: reshape so stats are per channel
            x = _to_nhwc(x, cin, None, None)
        rm = ctx.state_in[mean_s.name]
        rv = ctx.state_in[var_s.name]
        if ctx.is_training:
            y, nm, nv = ops_norm.batch_norm_train(
                x, params[gamma.name], params[beta.name], rm, rv,
                momentum=moving_average_fraction, eps=epsilon)
            ctx.state_out[mean_s.name] = nm
            ctx.state_out[var_s.name] = nv
        else:
            y = ops_norm.batch_norm_infer(x, params[gamma.name],
                                          params[beta.name], rm, rv, eps=epsilon)
            ctx.state_out[mean_s.name] = rm
            ctx.state_out[var_s.name] = rv
        return _apply_act(Value(y, parents[0].lengths), act_name)

    lo = LayerOutput(name, "batch_norm", [input], fwd, [gamma, beta],
                     size=input.size, activation=act_name,
                     state_specs=[mean_s, var_s])
    lo._out_channels = getattr(input, "_out_channels", None)
    lo._img_shape = getattr(input, "_img_shape", None)
    return lo


# ---------------------------------------------------------------------------
# regularisation / elementwise composition
# ---------------------------------------------------------------------------

def dropout(input, dropout_rate: float, name: Optional[str] = None):
    """Inverted dropout (reference: dropout_layer / ExtraAttr.drop_rate)."""
    name = name or auto_name("dropout")

    def fwd(params, parents, ctx):
        pv = parents[0]
        if not ctx.is_training or dropout_rate <= 0.0:
            return pv
        key = ctx.layer_key(name)
        enforce.enforce(key is not None,
                        "dropout in training mode needs a dropout_key")
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(key, keep, pv.array.shape)
        return pv.with_array(jnp.where(mask, pv.array / keep, 0.0))

    return LayerOutput(name, "dropout", [input], fwd, [], size=input.size)


def concat(input: Sequence[LayerOutput], name: Optional[str] = None, act=None):
    """Feature-axis concat (reference: concat_layer). When every input is an
    image layer with the same spatial shape, concatenates on the channel
    axis and stays an image (the reference concat semantics for conv
    branches, e.g. inception blocks); otherwise flattens and concats."""
    name = name or auto_name("concat")
    act_name = act_mod.resolve(act)
    inputs = _as_list(input)
    shapes = [getattr(i, "_img_shape", None) for i in inputs]
    chans = [getattr(i, "_out_channels", None) for i in inputs]
    image_mode = (all(c for c in chans) and all(shapes) and
                  len({s for s in shapes}) == 1 and None not in shapes[0])

    def fwd(params, parents, ctx):
        if image_mode:
            arrs = [_to_nhwc(p.array, c, s[0], s[1])
                    for p, c, s in zip(parents, chans, shapes)]
            return _apply_act(Value(jnp.concatenate(arrs, axis=-1)), act_name)
        arrs = [_flatten_if_image(p.array) if p.array.ndim == 4 else p.array
                for p in parents]
        return _apply_act(Value(jnp.concatenate(arrs, axis=-1),
                                parents[0].lengths), act_name)

    lo = LayerOutput(name, "concat", inputs, fwd, [],
                     size=sum(i.size for i in inputs if i.size),
                     activation=act_name)
    if image_mode:
        lo._out_channels = sum(chans)
        lo._img_shape = shapes[0]
    return lo


def addto(input: Sequence[LayerOutput], act=None, name: Optional[str] = None,
          bias_attr=False):
    """Elementwise sum (reference: addto_layer; gserver AddtoLayer.cpp)."""
    name = name or auto_name("addto")
    act_name = act_mod.resolve(act)
    inputs = _as_list(input)
    bias = _bias_spec(name, inputs[0].size, bias_attr) if inputs[0].size else None

    def fwd(params, parents, ctx):
        total = parents[0].array
        for p in parents[1:]:
            total = total + p.array
        if bias:
            total = total + params[bias.name].astype(total.dtype)
        return _apply_act(Value(total, parents[0].lengths), act_name)

    lo = LayerOutput(name, "addto", inputs, fwd, [bias] if bias else [],
                     size=inputs[0].size, activation=act_name)
    lo._out_channels = getattr(inputs[0], "_out_channels", None)
    lo._img_shape = getattr(inputs[0], "_img_shape", None)
    return lo


def scaling(input, weight, name: Optional[str] = None):
    """Row-wise scale by a scalar per example (reference: scaling_layer)."""
    name = name or auto_name("scaling")

    def fwd(params, parents, ctx):
        w, x = parents[0].array, parents[1].array
        return Value(x * w.reshape(w.shape[0], *([1] * (x.ndim - 1))),
                     parents[1].lengths)

    return LayerOutput(name, "scaling", [weight, input], fwd, [],
                       size=input.size)


def slope_intercept(input, slope=1.0, intercept=0.0, name: Optional[str] = None):
    """y = slope*x + intercept (reference: slope_intercept_layer)."""
    name = name or auto_name("slope_intercept")

    def fwd(params, parents, ctx):
        return parents[0].with_array(parents[0].array * slope + intercept)

    return LayerOutput(name, "slope_intercept", [input], fwd, [],
                       size=input.size)


def cos_sim(a, b, scale=1.0, name: Optional[str] = None):
    """Cosine similarity rows of a vs b (reference: cos_sim layer;
    gserver CosSimLayer.cpp). Output [b, 1]."""
    name = name or auto_name("cos_sim")

    def fwd(params, parents, ctx):
        x, y = parents[0].array, parents[1].array
        xf, yf = x.astype(jnp.float32), y.astype(jnp.float32)
        num = jnp.sum(xf * yf, axis=-1, keepdims=True)
        den = jnp.linalg.norm(xf, axis=-1, keepdims=True) * \
            jnp.linalg.norm(yf, axis=-1, keepdims=True)
        return Value(scale * num / jnp.maximum(den, 1e-12))

    return LayerOutput(name, "cos_sim", [a, b], fwd, [], size=1)


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------

def lstmemory(input, size: Optional[int] = None, reverse: bool = False,
              act=None, gate_act=None, name: Optional[str] = None,
              param_attr=None, bias_attr=None):
    """LSTM over a pre-projected sequence: input.size must be 4*size — the
    x@W projection is supplied by the preceding fc/mixed layer, the layer owns
    only recurrent weights, exactly the reference contract
    (reference: lstmemory in trainer_config_helpers/layers.py:3321,
    gserver/layers/LstmLayer.cpp)."""
    name = name or auto_name("lstmemory")
    enforce.enforce(input.size % 4 == 0, "lstmemory input size must be 4*size")
    size = size or input.size // 4
    enforce.enforce(input.size == 4 * size, "lstmemory input size != 4*size")
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    w_hh = ParamSpec(a.name, (size, 4 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 4 * size, bias_attr)
    specs = [w_hh] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "lstmemory needs sequence input")
        xp = pv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        bsz, tmax, _ = xp.shape
        mask = (jnp.arange(tmax)[None, :] < pv.lengths[:, None])
        h = jnp.zeros((bsz, size), xp.dtype)
        c = jnp.zeros((bsz, size), xp.dtype)
        xs, ms = jnp.moveaxis(xp, 1, 0), jnp.moveaxis(mask, 1, 0)
        if reverse:
            xs, ms = xs[::-1], ms[::-1]

        def step(state, inp):
            xt, mt = inp
            nxt = ops_rnn.lstm_cell(xt, state, params[w_hh.name])
            h_ = jnp.where(mt[:, None], nxt.h, state.h)
            c_ = jnp.where(mt[:, None], nxt.c, state.c)
            return ops_rnn.LSTMState(h_, c_), h_

        _, outs = jax.lax.scan(step, ops_rnn.LSTMState(h, c), (xs, ms))
        if reverse:
            outs = outs[::-1]
        outs = jnp.moveaxis(outs, 0, 1) * mask[..., None].astype(xp.dtype)
        return Value(outs, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "lstmemory", [input], fwd, specs, size=size)


def grumemory(input, size: Optional[int] = None, reverse: bool = False,
              act=None, name: Optional[str] = None, param_attr=None,
              bias_attr=None):
    """GRU over a pre-projected sequence (input.size == 3*size)
    (reference: grumemory; gserver/layers/GatedRecurrentLayer.cpp)."""
    name = name or auto_name("grumemory")
    enforce.enforce(input.size % 3 == 0, "grumemory input size must be 3*size")
    size = size or input.size // 3
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    w_hh = ParamSpec(a.name, (size, 3 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 3 * size, bias_attr)
    specs = [w_hh] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "grumemory needs sequence input")
        xp = pv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        bsz, tmax, _ = xp.shape
        mask = (jnp.arange(tmax)[None, :] < pv.lengths[:, None])
        h = jnp.zeros((bsz, size), xp.dtype)
        xs, ms = jnp.moveaxis(xp, 1, 0), jnp.moveaxis(mask, 1, 0)
        if reverse:
            xs, ms = xs[::-1], ms[::-1]

        def step(state, inp):
            xt, mt = inp
            nh = ops_rnn.gru_cell(xt, state, params[w_hh.name])
            nh = jnp.where(mt[:, None], nh, state)
            return nh, nh

        _, outs = jax.lax.scan(step, h, (xs, ms))
        if reverse:
            outs = outs[::-1]
        outs = jnp.moveaxis(outs, 0, 1) * mask[..., None].astype(xp.dtype)
        return Value(outs, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "grumemory", [input], fwd, specs, size=size)


def recurrent(input, act=None, reverse: bool = False, name: Optional[str] = None,
              param_attr=None, bias_attr=False):
    """Simple full-matrix recurrent layer over a pre-projected sequence
    (reference: gserver/layers/RecurrentLayer.cpp)."""
    name = name or auto_name("recurrent")
    size = input.size
    act_name = act_mod.resolve(act or "tanh")
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    w_hh = ParamSpec(a.name, (size, size), attr=a, fan_in=size)
    bias = _bias_spec(name, size, bias_attr)
    specs = [w_hh] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        pv = parents[0]
        xp = pv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        outs, _ = ops_rnn.simple_rnn(
            xp, pv.lengths, None,  # input already projected by contract
            params[w_hh.name], act=ops_act.get(act_name), reverse=reverse)
        return Value(outs, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "recurrent", [input], fwd, specs, size=size,
                       activation=act_name)


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------

def pool(input, pooling_type=None, name: Optional[str] = None):
    """Sequence pooling (reference: pooling_layer; SequencePoolLayer.cpp)."""
    name = name or auto_name("seq_pool")
    ptype = pooling_mod.resolve(pooling_type)

    def fwd(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "pooling_layer needs sequence input")
        fn = {"max": ops_seq.seq_max, "avg": ops_seq.seq_avg,
              "sum": ops_seq.seq_sum, "sqrt": ops_seq.seq_sqrt}[ptype]
        return Value(fn(pv.array, pv.lengths))

    return LayerOutput(name, "seq_pool", [input], fwd, [], size=input.size)


pooling_layer = pool


def last_seq(input, name: Optional[str] = None):
    """(reference: last_seq / SequenceLastInstanceLayer.cpp)"""
    name = name or auto_name("last_seq")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_seq.seq_last(pv.array, pv.lengths))

    return LayerOutput(name, "last_seq", [input], fwd, [], size=input.size)


def first_seq(input, name: Optional[str] = None):
    name = name or auto_name("first_seq")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_seq.seq_first(pv.array, pv.lengths))

    return LayerOutput(name, "first_seq", [input], fwd, [], size=input.size)


def expand(input, expand_as, name: Optional[str] = None):
    """Broadcast per-sequence vectors over timesteps (reference: expand_layer)."""
    name = name or auto_name("expand")

    def fwd(params, parents, ctx):
        v, ref = parents
        out = ops_seq.seq_expand(v.array, ref.lengths, ref.array.shape[1])
        return Value(out, ref.lengths, ref.sub_lengths)

    return LayerOutput(name, "expand", [input, expand_as], fwd, [],
                       size=input.size)


def seq_reverse(input, name: Optional[str] = None):
    name = name or auto_name("seq_reverse")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_seq.seq_reverse(pv.array, pv.lengths), pv.lengths,
                     pv.sub_lengths)

    return LayerOutput(name, "seq_reverse", [input], fwd, [], size=input.size)


def seq_concat(a, b, name: Optional[str] = None):
    """Per-sequence time concat (reference: seq_concat_layer)."""
    name = name or auto_name("seq_concat")

    def fwd(params, parents, ctx):
        x, y = parents
        out, lens = ops_seq.seq_concat(x.array, x.lengths, y.array, y.lengths)
        return Value(out, lens)

    return LayerOutput(name, "seq_concat", [a, b], fwd, [], size=a.size)


def context_projection(input, context_len: int, context_start: Optional[int] = None,
                       name: Optional[str] = None):
    """Context-window concat as a standalone layer (reference:
    context_projection inside mixed_layer; function/ContextProjectionOp.cpp)."""
    name = name or auto_name("context_projection")
    start = context_start if context_start is not None else -(context_len // 2)

    def fwd(params, parents, ctx):
        pv = parents[0]
        out = ops_seq.context_projection(pv.array, pv.lengths, context_len, start)
        return Value(out, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "context_projection", [input], fwd, [],
                       size=input.size * context_len)


# ---------------------------------------------------------------------------
# outputs / decisions
# ---------------------------------------------------------------------------

def max_id(input, name: Optional[str] = None):
    """Argmax layer (reference: maxid_layer / MaxIdLayer.cpp)."""
    name = name or auto_name("max_id")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_topk.max_id(pv.array), pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "max_id", [input], fwd, [], size=1)


# ---------------------------------------------------------------------------
# costs
# ---------------------------------------------------------------------------

def _seq_token_cost(per_token: jax.Array, lengths) -> jax.Array:
    """Sum per-token losses over valid steps → per-sequence cost."""
    tmax = per_token.shape[1]
    mask = (jnp.arange(tmax)[None, :] < lengths[:, None]).astype(per_token.dtype)
    return jnp.sum(per_token * mask, axis=1)


def _cost_layer(name, layer_type, inputs, per_example_fn, size=1):
    def fwd(params, parents, ctx):
        return Value(per_example_fn(params, parents, ctx))
    return LayerOutput(name, layer_type, inputs, fwd, [], size=size)


def classification_cost(input, label, name: Optional[str] = None):
    """Softmax classification cost (reference: classification_cost in v2;
    MultiClassCrossEntropy CostLayer). Softmax-activated inputs (the v1
    convention) are fused: CE is computed as log_softmax on the kept logits,
    never as -log(p) on the probabilities (the reference's fused
    softmax_with_cross_entropy rationale — -log(p+eps) spikes to 1/eps-scale
    gradients when saturated). CE on probabilities remains only as a fallback
    for inputs whose logits were not preserved. Sequence inputs produce
    per-token CE summed over each sequence."""
    name = name or auto_name("classification_cost")
    on_probs = input.activation == "softmax" or input.activation == "sequence_softmax"

    def per_example(params, parents, ctx):
        pv, lv = parents
        pred, lab = pv.array, lv.array
        # Fused path: if the input layer applied softmax and kept its logits,
        # compute CE in log-space on the logits. -log(p+eps) on saturated
        # probabilities produces 1/eps-scale gradient spikes that kill
        # training (dead ReLUs); log_softmax on logits is exact and stable.
        logits = pv.pre_act if input.activation == "softmax" else None
        if pv.is_sequence:
            lab3 = lab if lab.ndim == 2 else lab.reshape(lab.shape[0], -1)
            if logits is not None:
                tok = ops_loss.softmax_cross_entropy(logits, lab3)
            elif on_probs:
                tok = ops_loss.cross_entropy_with_probs(pred, lab3)
            else:
                tok = ops_loss.softmax_cross_entropy(pred, lab3)
            return _seq_token_cost(tok, pv.lengths)
        lab1 = lab.reshape(-1)
        if logits is not None:
            return ops_loss.softmax_cross_entropy(logits, lab1)
        if on_probs:
            return ops_loss.cross_entropy_with_probs(pred, lab1)
        return ops_loss.softmax_cross_entropy(pred, lab1)

    return _cost_layer(name, "classification_cost", [input, label], per_example)


def cross_entropy_cost(input, label, name: Optional[str] = None):
    name = name or auto_name("cross_entropy")
    return classification_cost(input, label, name=name)


def square_error_cost(input, label, name: Optional[str] = None):
    """(reference: square_error_cost / SumOfSquaresCostLayer)"""
    name = name or auto_name("square_error")

    def per_example(params, parents, ctx):
        return ops_loss.square_error(parents[0].array, parents[1].array)

    return _cost_layer(name, "square_error", [input, label], per_example)


regression_cost = square_error_cost
mse_cost = square_error_cost


def multi_binary_label_cross_entropy_cost(input, label, name=None):
    name = name or auto_name("multi_binary_ce")

    def per_example(params, parents, ctx):
        return ops_loss.multi_binary_cross_entropy(parents[0].array,
                                                   parents[1].array)

    return _cost_layer(name, "multi_binary_ce", [input, label], per_example)


def rank_cost(left, right, label, name: Optional[str] = None):
    """(reference: rank_cost / RankingCost)"""
    name = name or auto_name("rank_cost")

    def per_example(params, parents, ctx):
        return ops_loss.rank_cost(parents[0].array, parents[1].array,
                                  parents[2].array.reshape(-1))

    return _cost_layer(name, "rank_cost", [left, right, label], per_example)


def huber_classification_cost(input, label, name: Optional[str] = None):
    name = name or auto_name("huber_cost")

    def per_example(params, parents, ctx):
        return ops_loss.huber_classification(parents[0].array,
                                             parents[1].array.reshape(-1))

    return _cost_layer(name, "huber_cost", [input, label], per_example)


def hinge_cost(input, label, name: Optional[str] = None):
    name = name or auto_name("hinge_cost")

    def per_example(params, parents, ctx):
        return ops_loss.hinge(parents[0].array, parents[1].array.reshape(-1))

    return _cost_layer(name, "hinge_cost", [input, label], per_example)


def crf_layer(input, label, size: Optional[int] = None,
              name: Optional[str] = None, param_attr=None):
    """Linear-chain CRF cost over a sequence of emissions.

    ``input`` is a sequence layer with per-token tag scores (size = #tags),
    ``label`` an integer tag sequence. Produces the per-sequence negative
    log-likelihood. Reference: crf_layer (trainer_config_helpers/layers.py),
    gserver/layers/CRFLayer.cpp, operators/linear_chain_crf_op.cc — same
    (#tags+2, #tags) transition parameterization (start/end rows first).
    """
    from paddle_tpu.ops import crf as ops_crf
    name = name or auto_name("crf")
    enforce.enforce(size is None or size == input.size,
                    f"crf_layer size {size} != input size {input.size}")
    n_tags = size or input.size
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    spec = ParamSpec(a.name, (n_tags + 2, n_tags), attr=a, fan_in=n_tags)

    def fwd(params, parents, ctx):
        ev, lv = parents
        enforce.enforce(ev.is_sequence, "crf_layer input must be a sequence")
        emis = ev.pre_act if ev.pre_act is not None else ev.array
        tags = lv.array.astype(jnp.int32)
        if tags.ndim == 3:
            tags = tags[..., 0]
        nll = -ops_crf.crf_log_likelihood(emis, tags, ev.lengths,
                                          params[spec.name])
        return Value(nll)

    return LayerOutput(name, "crf", [input, label], fwd, [spec], size=1)


def crf_decoding_layer(input, size: Optional[int] = None, label=None,
                       name: Optional[str] = None, param_attr=None):
    """Viterbi decode with a (shared) CRF transition parameter.

    Without ``label``: outputs the best tag sequence [B, T]. With ``label``:
    outputs a per-token 0/1 mismatch mask (the reference's evaluation mode,
    operators/crf_decoding_op.cc:24-35, gserver CRFDecodingLayer).
    Share transitions with the training crf_layer via
    ``param_attr=ParamAttr(name=...)``.
    """
    from paddle_tpu.ops import crf as ops_crf
    name = name or auto_name("crf_decoding")
    enforce.enforce(size is None or size == input.size,
                    f"crf_decoding size {size} != input size {input.size}")
    n_tags = size or input.size
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    spec = ParamSpec(a.name, (n_tags + 2, n_tags), attr=a, fan_in=n_tags)
    inputs = [input] + ([label] if label is not None else [])

    def fwd(params, parents, ctx):
        ev = parents[0]
        enforce.enforce(ev.is_sequence,
                        "crf_decoding_layer input must be a sequence")
        emis = ev.pre_act if ev.pre_act is not None else ev.array
        tags, _ = ops_crf.crf_decode(emis, ev.lengths, params[spec.name])
        if label is not None:
            lab = parents[1].array.astype(jnp.int32)
            if lab.ndim == 3:
                lab = lab[..., 0]
            mask = (jnp.arange(tags.shape[1])[None, :] <
                    ev.lengths[:, None])
            err = jnp.where(mask, (tags != lab).astype(jnp.float32), 0.0)
            return Value(err, ev.lengths)
        return Value(tags, ev.lengths)

    return LayerOutput(name, "crf_decoding", inputs, fwd, [spec], size=1)


def ctc_layer(input, label, size: Optional[int] = None,
              blank: Optional[int] = None, norm_by_times: bool = False,
              name: Optional[str] = None):
    """CTC cost. ``input``: sequence layer of per-frame class scores
    (size = #labels + 1 incl. blank); ``label``: target label sequence.
    Default blank is the LAST class index, matching the v1 ctc_layer
    (gserver/layers/CTCLayer.cpp, LinearChainCTC.cpp uses numClasses-1);
    warp_ctc_layer defaults to blank=0 (WarpCTCLayer.cpp).
    Reference: ctc_layer / warp_ctc_layer (trainer_config_helpers/layers.py).
    """
    from paddle_tpu.ops import ctc as ops_ctc
    name = name or auto_name("ctc")
    enforce.enforce(size is None or size == input.size,
                    f"ctc_layer size {size} != input size {input.size}")
    n_classes = size or input.size
    blank_idx = n_classes - 1 if blank is None else blank

    def fwd(params, parents, ctx):
        ev, lv = parents
        if ev.pre_act is not None:
            logp = jax.nn.log_softmax(ev.pre_act.astype(jnp.float32), axis=-1)
        elif input.activation == "softmax":
            logp = jnp.log(jnp.maximum(ev.array.astype(jnp.float32), 1e-30))
        else:
            logp = jax.nn.log_softmax(ev.array.astype(jnp.float32), axis=-1)
        lab = lv.array.astype(jnp.int32)
        if lab.ndim == 3:
            lab = lab[..., 0]
        enforce.enforce(ev.is_sequence and lv.is_sequence,
                        "ctc_layer input and label must be sequences")
        nll = ops_ctc.ctc_loss(logp, lab, ev.lengths, lv.lengths,
                               blank=blank_idx)
        if norm_by_times:
            nll = nll / jnp.maximum(ev.lengths.astype(jnp.float32), 1.0)
        return Value(nll)

    return LayerOutput(name, "ctc", [input, label], fwd, [], size=1)


def warp_ctc_layer(input, label, size: Optional[int] = None, blank: int = 0,
                   norm_by_times: bool = False, name: Optional[str] = None):
    """warp-ctc flavor: blank defaults to 0 (reference: WarpCTCLayer.cpp,
    hl_warpctc_wrap.cc)."""
    return ctc_layer(input, label, size=size, blank=blank,
                     norm_by_times=norm_by_times,
                     name=name or auto_name("warp_ctc"))


def gru_step(input, state, size: Optional[int] = None,
             name: Optional[str] = None, param_attr=None, bias_attr=None):
    """One GRU step for use inside recurrent_group (reference:
    gru_step_layer, trainer_config_helpers/layers.py; GruStepLayer.cpp).
    ``input``: the projected step input [B, 3H] (W·x, as in the reference —
    compute it with an fc of size 3*size); ``state``: an H-wide memory."""
    name = name or auto_name("gru_step")
    size = size or input.size // 3
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(a.name, (size, 3 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 3 * size, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        xv, sv = parents
        xp = xv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        h = ops_rnn.gru_cell(xp, sv.array, params[w_spec.name])
        return Value(h, xv.lengths, xv.sub_lengths)

    return LayerOutput(name, "gru_step", [input, state], fwd, specs,
                       size=size)


def lstm_step(input, state, cell_state, size: Optional[int] = None,
              name: Optional[str] = None, param_attr=None, bias_attr=None,
              forget_bias: float = 0.0):
    """One LSTM step for recurrent_group (reference: lstm_step_layer).
    ``input``: projected step input [B, 4H]; ``state``/``cell_state``:
    H-wide memories for h and c. Returns (h_layer, c_layer) — link the h
    memory to the first and the c memory to the second."""
    name = name or auto_name("lstm_step")
    size = size or input.size // 4
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(a.name, (size, 4 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 4 * size, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])

    def fwd_h(params, parents, ctx):
        xv, hv, cv = parents
        xp = xv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        st = ops_rnn.lstm_cell(xp, ops_rnn.LSTMState(hv.array, cv.array),
                               params[w_spec.name], forget_bias)
        return Value(st.h, xv.lengths, xv.sub_lengths)

    h_layer = LayerOutput(name, "lstm_step", [input, state, cell_state],
                          fwd_h, specs, size=size)

    def fwd_c(params, parents, ctx):
        xv, hv, cv = parents
        xp = xv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        st = ops_rnn.lstm_cell(xp, ops_rnn.LSTMState(hv.array, cv.array),
                               params[w_spec.name], forget_bias)
        return Value(st.c, xv.lengths, xv.sub_lengths)

    c_layer = LayerOutput(f"{name}@cell", "lstm_step_cell",
                          [input, state, cell_state], fwd_c, specs, size=size)
    return h_layer, c_layer
