"""The declarative layer API — the v2 capability surface.

Reference: python/paddle/trainer_config_helpers/layers.py (7,144 LoC of config
functions) + python/paddle/v2/layer.py (auto-wrapping into v2). Each function
here returns a LayerOutput node holding parameter specs and a pure forward
callable; paddle_tpu.topology.Topology compiles the graph into one traced
function (no protobuf, no config parser — the Python call graph IS the
config).

Image tensors follow the reference's flat-CHW convention at the data boundary
(config_parser stored images as channel-major flat vectors) but flow as NHWC
internally — TPU-native layout.
"""

import math
from typing import List, Optional, Sequence, Union

import jax
import jax.numpy as jnp

from paddle_tpu import activation as act_mod
from paddle_tpu import pooling as pooling_mod
from paddle_tpu.core.param import ParamAttr, ParamSpec
from paddle_tpu.ops import activations as ops_act
from paddle_tpu.ops import beam as ops_beam
from paddle_tpu.ops import conv as ops_conv
from paddle_tpu.ops import loss as ops_loss
from paddle_tpu.ops import norm as ops_norm
from paddle_tpu.ops import pool as ops_pool
from paddle_tpu.ops import rnn as ops_rnn
from paddle_tpu.ops import sequence as ops_seq
from paddle_tpu.ops import sparse as ops_sparse
from paddle_tpu.ops import topk as ops_topk
from paddle_tpu.ops.math import linear as ops_linear, matmul
from paddle_tpu.topology import LayerOutput, Value, auto_name
from paddle_tpu.utils import enforce

# the dynamic-RNN DSL lives in paddle_tpu.recurrent; re-exported here to
# mirror the reference surface (trainer_config_helpers/layers.py had
# recurrent_group/memory/beam_search in the same namespace as fc/lstmemory)
from paddle_tpu.recurrent import (recurrent_group, memory, beam_search,
                                  StaticInput, GeneratedInput)


def _as_list(x):
    if x is None:
        return []
    return list(x) if isinstance(x, (list, tuple)) else [x]


def _param_attr(attr, default_name) -> ParamAttr:
    attr = attr or ParamAttr()
    if attr.name is None:
        attr = type(attr)(**{**attr.__dict__, "name": default_name})
    return attr


def _bias_spec(name, size, bias_attr) -> Optional[ParamSpec]:
    """bias_attr False disables bias (reference convention)."""
    if bias_attr is False:
        return None
    attr = bias_attr if isinstance(bias_attr, ParamAttr) else ParamAttr(
        initializer="constant", initial_value=0.0)
    attr = _param_attr(attr, f"{name}.b")
    return ParamSpec(attr.name, (size,), attr=attr)


def _apply_act(value: Value, act_name: str) -> Value:
    """Keeps the pre-activation on the Value so downstream cost layers can
    fuse with the activation in log-space (the reason the reference had a
    fused softmax_with_cross_entropy op)."""
    if act_name == "sequence_softmax":
        enforce.enforce(value.is_sequence, "sequence_softmax needs sequence input")
        return value.with_array(ops_seq.seq_softmax(value.array, value.lengths))
    # only softmax keeps its logits: classification_cost fuses with them,
    # and an unconsumed pre_act would cost a full extra output buffer at
    # jit boundaries for every other activation
    pre = value.array if act_name == "softmax" else None
    return value.with_array(ops_act.get(act_name)(value.array), pre_act=pre)


def _flatten_if_image(x: jax.Array) -> jax.Array:
    """FC over a conv output: flatten NHWC back to CHW-flat so parameter
    layouts match the reference's channel-major convention."""
    if x.ndim == 4:
        n = x.shape[0]
        return jnp.transpose(x, (0, 3, 1, 2)).reshape(n, -1)
    return x


def _feat_size(x: jax.Array) -> int:
    if x.ndim == 4:
        return int(x.shape[1] * x.shape[2] * x.shape[3])
    return int(x.shape[-1])


# ---------------------------------------------------------------------------
# data
# ---------------------------------------------------------------------------

def data(name: str, type):
    """Input declaration (reference: v2 layer.data / DataConfig)."""
    return LayerOutput(name, "data", [], fn=None, size=type.dim, is_data=True,
                       data_spec=type)


# ---------------------------------------------------------------------------
# fc / embedding / mixed-style projections
# ---------------------------------------------------------------------------

def fc(input, size: int, act=None, name: Optional[str] = None,
       param_attr=None, bias_attr=None):
    """Fully-connected over one or more inputs (summed), mirroring
    fc_layer's multi-input form (reference: trainer_config_helpers/layers.py
    fc_layer; gserver/layers/FullyConnectedLayer.cpp)."""
    name = name or auto_name("fc")
    inputs = _as_list(input)
    act_name = act_mod.resolve(act)
    attrs = _as_list(param_attr) if isinstance(param_attr, (list, tuple)) \
        else [param_attr] * len(inputs)
    specs = []
    for i, (inp, attr) in enumerate(zip(inputs, attrs)):
        suffix = f".w{i}" if len(inputs) > 1 else ".w"
        a = _param_attr(attr if isinstance(attr, ParamAttr) else ParamAttr(),
                        f"{name}{suffix}")
        in_size = inp.size
        specs.append(ParamSpec(a.name, (in_size, size), attr=a, fan_in=in_size))
    bias = _bias_spec(name, size, bias_attr)
    if bias:
        specs.append(bias)

    def fwd(params, parents, ctx):
        total = None
        for spec, pv in zip(specs, parents):
            if pv.is_sparse:
                # sparse input: gather rows of W by nonzero index and
                # weight-sum — sparse matmul without materialising the
                # multi-hot vector (reference: MulOp sparse path,
                # paddle/function/MulOp.cpp)
                rows = jnp.take(params[spec.name], pv.array.astype(jnp.int32),
                                axis=0)                      # [b, k, size]
                out = jnp.sum(rows * pv.weights[..., None].astype(rows.dtype),
                              axis=-2)
            else:
                x = _flatten_if_image(pv.array)
                out = matmul(x, params[spec.name])
            total = out if total is None else total + out
        if bias:
            total = total + params[bias.name].astype(total.dtype)
        v = Value(total, parents[0].lengths, parents[0].sub_lengths)
        return _apply_act(v, act_name)

    return LayerOutput(name, "fc", inputs, fwd, specs, size=size,
                       activation=act_name)


def embedding(input, size: int, name: Optional[str] = None, param_attr=None,
              padding_idx: Optional[int] = None):
    """Embedding lookup (reference: v2 layer.embedding / TableProjection /
    operators/lookup_table_op.cc)."""
    name = name or auto_name("embedding")
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    vocab = input.size
    spec = ParamSpec(a.name, (vocab, size), attr=a, fan_in=size)

    def fwd(params, parents, ctx):
        pv = parents[0]
        out = ops_sparse.embedding_lookup(params[spec.name], pv.array,
                                          padding_idx)
        return Value(out, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "embedding", [input], fwd, [spec], size=size)


# ---------------------------------------------------------------------------
# images
# ---------------------------------------------------------------------------

def _to_nhwc(x: jax.Array, channels: int, img_h: Optional[int],
             img_w: Optional[int]) -> jax.Array:
    if x.ndim == 4:
        return x
    n, flat = x.shape
    if img_h is None:
        side = int(math.isqrt(flat // channels))
        img_h = img_w = side
    x = x.reshape(n, channels, img_h, img_w)      # reference flat layout: CHW
    return jnp.transpose(x, (0, 2, 3, 1))


def _infer_img_shape(input, cin, img_size):
    """Static (H, W) of a layer's image input — the config_parser equivalent
    (reference: python/paddle/trainer/config_parser.py ConvConfig/ImgSize
    computation; it tracked img dims through every conv/pool)."""
    if img_size is not None:
        return (img_size, img_size) if isinstance(img_size, int) \
            else tuple(img_size)
    shp = getattr(input, "_img_shape", None)
    if shp is not None:
        return shp
    if input.size and cin:
        side = int(math.isqrt(input.size // cin))
        if side * side * cin == input.size:
            return (side, side)
    return (None, None)


def _conv_out_dim(in_dim, k, s, pad, dilation=1):
    """Output spatial size, floor mode (matches explicit-pad reduce_window
    and lax conv arithmetic)."""
    if in_dim is None:
        return None
    eff_k = (k - 1) * dilation + 1
    if pad == "SAME":
        return -(-in_dim // s)
    if pad == "VALID":
        p0 = p1 = 0
    elif isinstance(pad, int):
        p0 = p1 = pad
    else:
        p0, p1 = pad
    return (in_dim + p0 + p1 - eff_k) // s + 1


def img_conv(input, filter_size, num_filters: int, num_channels: Optional[int] = None,
             stride=1, padding=None, groups=1, act=None, name: Optional[str] = None,
             param_attr=None, bias_attr=None, img_size=None, dilation=1,
             trans: bool = False):
    """2-D conv layer (reference: img_conv_layer in
    trainer_config_helpers/layers.py; gserver/layers/ExpandConvLayer.cpp;
    operators/conv_op.cc). Accepts flat-CHW or NHWC input; emits NHWC."""
    name = name or auto_name("img_conv")
    act_name = act_mod.resolve(act)
    k = (filter_size, filter_size) if isinstance(filter_size, int) else tuple(filter_size)
    user_padding = padding
    if padding is None:
        padding = ((k[0] - 1) // 2, (k[1] - 1) // 2)  # reference default: same-ish
    a = _param_attr(param_attr or ParamAttr(initializer="msra"), f"{name}.w")
    cin = num_channels
    if cin is None:
        # infer from parent conv layers; flat data needs explicit channels
        cin = getattr(input, "_out_channels", None)
        enforce.enforce(cin is not None,
                        f"img_conv {name}: num_channels required for flat input")
    if trans:
        enforce.enforce(groups == 1 and dilation == 1,
                        "img_conv trans=True supports groups=1, dilation=1")
    # HWIO for both directions: lax.conv_transpose takes the same
    # (kh, kw, cin, cout) filter layout as the forward conv
    wshape = (k[0], k[1], cin // groups, num_filters)
    spec = ParamSpec(a.name, wshape, attr=a, fan_in=k[0] * k[1] * (cin // groups))
    bias = _bias_spec(name, num_filters, bias_attr)
    specs = [spec] + ([bias] if bias else [])
    ih, iw = _infer_img_shape(input, cin, img_size)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    if trans:
        if user_padding in (None, "SAME"):
            oh = ih * s[0] if ih else None
            ow = iw * s[1] if iw else None
        else:
            oh = ow = None  # non-SAME transposed shapes resolved at runtime
    else:
        pads = padding if isinstance(padding, str) else (
            (padding, padding) if isinstance(padding, int) else tuple(padding))
        ph = pads if isinstance(pads, str) else pads[0]
        pw = pads if isinstance(pads, str) else pads[1]
        oh = _conv_out_dim(ih, k[0], s[0], ph, dilation)
        ow = _conv_out_dim(iw, k[1], s[1], pw, dilation)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        if trans:
            if user_padding is None:
                tpad = "SAME"
            elif isinstance(user_padding, str):
                tpad = user_padding
            elif isinstance(user_padding, int):
                tpad = ((user_padding, user_padding),) * 2
            else:
                p = tuple(user_padding)
                tpad = ((p[0], p[0]), (p[1], p[1])) if isinstance(p[0], int) \
                    else p
            out = ops_conv.conv2d_transpose(x, params[spec.name], stride=stride,
                                            padding=tpad)
        else:
            out = ops_conv.conv2d(x, params[spec.name], stride=stride,
                                  padding=padding, dilation=dilation,
                                  groups=groups)
        if bias:
            out = out + params[bias.name].astype(out.dtype)
        return _apply_act(Value(out), act_name)

    lo = LayerOutput(name, "img_conv", [input], fwd, specs,
                     size=oh * ow * num_filters if oh and ow else None,
                     activation=act_name)
    lo._out_channels = num_filters
    lo._img_shape = (oh, ow)
    return lo


def img_pool(input, pool_size, stride=None, padding=0, pool_type=None,
             num_channels=None, name: Optional[str] = None, img_size=None):
    """Image pooling (reference: img_pool_layer; gserver PoolLayer.cpp)."""
    name = name or auto_name("img_pool")
    ptype = pooling_mod.resolve(pool_type)
    cin = num_channels or getattr(input, "_out_channels", None)
    ih, iw = _infer_img_shape(input, cin, img_size)
    k = (pool_size, pool_size) if isinstance(pool_size, int) else tuple(pool_size)
    st = stride if stride is not None else pool_size
    st = (st, st) if isinstance(st, int) else tuple(st)
    pad = padding
    oh = _conv_out_dim(ih, k[0], st[0],
                       pad if isinstance(pad, (str, int)) else pad[0])
    ow = _conv_out_dim(iw, k[1], st[1],
                       pad if isinstance(pad, (str, int)) else pad[1])

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        if ptype == "max":
            out = ops_pool.max_pool2d(x, pool_size, stride=stride, padding=padding)
        else:
            out = ops_pool.avg_pool2d(x, pool_size, stride=stride, padding=padding)
        return Value(out)

    lo = LayerOutput(name, "img_pool", [input], fwd, [],
                     size=oh * ow * cin if oh and ow and cin else None)
    lo._out_channels = cin
    lo._img_shape = (oh, ow)
    return lo


def spp(input, pyramid_height: int, num_channels=None, pool_type=None,
        name: Optional[str] = None):
    """Spatial pyramid pooling layer (reference: spp_layer)."""
    name = name or auto_name("spp")
    ptype = pooling_mod.resolve(pool_type)
    cin = num_channels or getattr(input, "_out_channels", None)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, None, None)
        return Value(ops_pool.spp(x, pyramid_height, ptype))

    bins = sum(4 ** l for l in range(pyramid_height))
    return LayerOutput(name, "spp", [input], fwd, [],
                       size=bins * cin if cin else None)


def img_cmrnorm(input, size=5, scale=0.0001, power=0.75, name=None,
                num_channels=None):
    """Cross-map response normalisation — AlexNet LRN (reference:
    img_cmrnorm_layer in trainer_config_helpers/layers.py; runtime
    paddle/function/CrossMapNormalOp.cpp)."""
    name = name or auto_name("cmrnorm")
    cin = num_channels or getattr(input, "_out_channels", None)
    ih, iw = _infer_img_shape(input, cin, None)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        return Value(ops_norm.lrn(x, size=size, alpha=scale, beta=power))

    lo = LayerOutput(name, "cmrnorm", [input], fwd, [], size=input.size)
    lo._out_channels = cin
    lo._img_shape = getattr(input, "_img_shape", (ih, iw))
    return lo


def batch_norm(input, act=None, name: Optional[str] = None, num_channels=None,
               param_attr=None, bias_attr=None, moving_average_fraction=0.9,
               epsilon=1e-5):
    """Batch normalisation with functional running stats (reference:
    batch_norm_layer; gserver/layers/BatchNormalizationLayer.cpp;
    operators/batch_norm_op.cc). Stats live in the state pytree keyed
    '<name>.mean' / '<name>.var'."""
    name = name or auto_name("batch_norm")
    act_name = act_mod.resolve(act)
    cin = num_channels or getattr(input, "_out_channels", None) or input.size
    ga = _param_attr(param_attr if isinstance(param_attr, ParamAttr) else
                     ParamAttr(initializer="constant", initial_value=1.0),
                     f"{name}.gamma")
    ba = _param_attr(bias_attr if isinstance(bias_attr, ParamAttr) else
                     ParamAttr(initializer="constant", initial_value=0.0),
                     f"{name}.beta")
    gamma = ParamSpec(ga.name, (cin,), attr=ga)
    beta = ParamSpec(ba.name, (cin,), attr=ba)
    mean_s = ParamSpec(f"{name}.mean", (cin,),
                       attr=ParamAttr(initializer="constant", initial_value=0.0))
    var_s = ParamSpec(f"{name}.var", (cin,),
                      attr=ParamAttr(initializer="constant", initial_value=1.0))

    def fwd(params, parents, ctx):
        x = parents[0].array
        if x.ndim == 2 and x.shape[-1] != cin:
            # flat CHW image: reshape so stats are per channel
            x = _to_nhwc(x, cin, None, None)
        rm = ctx.state_in[mean_s.name]
        rv = ctx.state_in[var_s.name]
        if ctx.is_training:
            y, nm, nv = ops_norm.batch_norm_train(
                x, params[gamma.name], params[beta.name], rm, rv,
                momentum=moving_average_fraction, eps=epsilon)
            ctx.state_out[mean_s.name] = nm
            ctx.state_out[var_s.name] = nv
        else:
            y = ops_norm.batch_norm_infer(x, params[gamma.name],
                                          params[beta.name], rm, rv, eps=epsilon)
            ctx.state_out[mean_s.name] = rm
            ctx.state_out[var_s.name] = rv
        return _apply_act(Value(y, parents[0].lengths), act_name)

    lo = LayerOutput(name, "batch_norm", [input], fwd, [gamma, beta],
                     size=input.size, activation=act_name,
                     state_specs=[mean_s, var_s])
    lo._out_channels = getattr(input, "_out_channels", None)
    lo._img_shape = getattr(input, "_img_shape", None)
    return lo


def img_conv_bn(input, filter_size, num_filters: int,
                num_channels: Optional[int] = None, stride=1,
                padding="SAME", act=None, name: Optional[str] = None,
                param_attr=None, bn_param_attr=None, bn_bias_attr=None,
                moving_average_fraction=0.9, epsilon=1e-5, img_size=None,
                conv_name: Optional[str] = None,
                bn_name: Optional[str] = None, save8: bool = False):
    """Fused conv→batch-norm block (ops/conv_bn.py: the stats reductions
    ride the conv's fusion group, normalize is a per-channel affine, and
    the backward is the closed-form two-pass BN VJP — the capability
    slot of the reference's CudnnBatchNormLayer fused with
    ExpandConvLayer). ``save8`` stashes the backward's saved activations
    as per-channel int8. No conv bias (BN's beta subsumes it — the
    reference's conv_bn_layer does the same,
    benchmark/paddle/image/resnet.py:13)."""
    from paddle_tpu.ops import conv_bn as ops_fused

    name = name or auto_name("img_conv_bn")
    # conv_name / bn_name control PARAMETER naming so a fused layer can
    # share checkpoints with an img_conv + batch_norm pair
    conv_name = conv_name or name
    bn_name = bn_name or name
    act_name = act_mod.resolve(act)
    k = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    cin = num_channels or getattr(input, "_out_channels", None)
    enforce.enforce(cin is not None,
                    f"img_conv_bn {name}: num_channels required")
    a = _param_attr(param_attr or ParamAttr(initializer="msra"),
                    f"{conv_name}.w")
    wspec = ParamSpec(a.name, (k[0], k[1], cin, num_filters), attr=a,
                      fan_in=k[0] * k[1] * cin)
    ga = _param_attr(bn_param_attr if isinstance(bn_param_attr, ParamAttr)
                     else ParamAttr(initializer="constant",
                                    initial_value=1.0), f"{bn_name}.gamma")
    ba = _param_attr(bn_bias_attr if isinstance(bn_bias_attr, ParamAttr)
                     else ParamAttr(initializer="constant",
                                    initial_value=0.0), f"{bn_name}.beta")
    gamma = ParamSpec(ga.name, (num_filters,), attr=ga)
    beta = ParamSpec(ba.name, (num_filters,), attr=ba)
    mean_s = ParamSpec(f"{bn_name}.mean", (num_filters,),
                       attr=ParamAttr(initializer="constant",
                                      initial_value=0.0))
    var_s = ParamSpec(f"{bn_name}.var", (num_filters,),
                      attr=ParamAttr(initializer="constant",
                                     initial_value=1.0))
    ih, iw = _infer_img_shape(input, cin, img_size)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    pad_for_dim = "SAME" if padding == "SAME" else padding
    oh = _conv_out_dim(ih, k[0], s[0], pad_for_dim)
    ow = _conv_out_dim(iw, k[1], s[1], pad_for_dim)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        rm = ctx.state_in[mean_s.name]
        rv = ctx.state_in[var_s.name]
        if ctx.is_training:
            y, nm, nv = ops_fused.conv_bn_train(
                x, params[wspec.name], params[gamma.name],
                params[beta.name], rm, rv, stride=stride, padding=padding,
                momentum=moving_average_fraction, eps=epsilon,
                save8=save8)
            ctx.state_out[mean_s.name] = nm
            ctx.state_out[var_s.name] = nv
        else:
            y = ops_fused.conv_bn_infer(
                x, params[wspec.name], params[gamma.name],
                params[beta.name], rm, rv, stride=stride, padding=padding,
                eps=epsilon)
            ctx.state_out[mean_s.name] = rm
            ctx.state_out[var_s.name] = rv
        return _apply_act(Value(y), act_name)

    lo = LayerOutput(name, "img_conv_bn", [input], fwd,
                     [wspec, gamma, beta],
                     size=oh * ow * num_filters if oh and ow else None,
                     activation=act_name, state_specs=[mean_s, var_s])
    lo._out_channels = num_filters
    lo._img_shape = (oh, ow)
    return lo


# ---------------------------------------------------------------------------
# q8 training pipeline layers (ops/q8.py) — activations stored int8 in HBM
# ---------------------------------------------------------------------------

def _q8_state_specs(name, ch):
    """Delayed-scaling state for one stash site: previous step's
    per-channel center and scale."""
    mean_s = ParamSpec(f"{name}.q_mean", (ch,),
                       attr=ParamAttr(initializer="constant",
                                      initial_value=0.0))
    scale_s = ParamSpec(f"{name}.q_scale", (ch,),
                        attr=ParamAttr(initializer="constant",
                                       initial_value=1.0))
    return mean_s, scale_s


def _q8_parent_fold(parent_info, params, aux, q8_mod):
    """(M, B, relu_in) for a consumer's prologue from the producer's
    deferred BN/activation (build-time info + this step's batch stats)."""
    bn_name, act_name, eps = parent_info
    enforce.enforce(act_name in (None, "linear", "relu"),
                    f"q8 pipeline supports relu/None deferred activations, "
                    f"got {act_name!r}")
    relu_in = act_name == "relu"
    if bn_name is None:
        M, B = q8_mod.fold_identity(aux["mu"])
        return M, B, relu_in
    M, B = q8_mod.fold_bn_affine(aux["mu"], aux["var"],
                                 params[f"{bn_name}.gamma"],
                                 params[f"{bn_name}.beta"], eps=eps)
    return M, B, relu_in


def _q8_key(ctx, name: str, stochastic: bool):
    """Trailing key tuple for the stochastic-rounding block variants.
    Typed PRNG keys are unwrapped to raw uint32 so the custom_vjp sees a
    plain integer array (float0 cotangent)."""
    if not stochastic:
        return ()
    key = ctx.layer_key(name)
    enforce.enforce(
        key is not None,
        f"q8 layer {name!r}: stochastic rounding needs the per-step "
        f"dropout_key threaded into forward (trainer.SGD provides it)")
    if jnp.issubdtype(key.dtype, jax.dtypes.prng_key):
        key = jax.random.key_data(key)
    return (key,)


def _q8_info(lo: LayerOutput):
    info = getattr(lo, "_q8", None)
    enforce.enforce(info is not None,
                    f"layer {lo.name!r} is not a q8 producer — q8 layers "
                    f"can only consume q8_entry / img_conv_bn_q8 / "
                    f"addto_q8 outputs")
    return info


def q8_entry(input, name: Optional[str] = None, num_channels=None,
             stash: str = "int8", stochastic: bool = False):
    """Quantize a dense activation into the q8 pipeline (ops/q8.py): from
    here until q8_exit, activations exist in HBM only as centered int8
    under delayed scaling (stash="bf16" keeps the same deferral/remat
    machinery with a near-lossless bf16 stash — the "defer" recipe).
    Training-mode only; in eval the pipeline runs the exact dense
    math."""
    from paddle_tpu.ops import q8 as ops_q8

    name = name or auto_name("q8_entry")
    cin = num_channels or getattr(input, "_out_channels", None)
    enforce.enforce(cin is not None, f"q8_entry {name}: unknown channels")
    mean_s, scale_s = _q8_state_specs(name, cin)

    def fwd(params, parents, ctx):
        v = parents[0]
        if not ctx.is_training:
            ctx.state_out[mean_s.name] = ctx.state_in[mean_s.name]
            ctx.state_out[scale_s.name] = ctx.state_in[scale_s.name]
            return v
        yhat, q, mu, amax = ops_q8.make_entry(stash, stochastic)(
            v.array, ctx.state_in[mean_s.name], ctx.state_in[scale_s.name],
            *_q8_key(ctx, name, stochastic))
        ctx.state_out[mean_s.name] = mu
        ctx.state_out[scale_s.name] = ops_q8.scale_from_amax(amax)
        return Value(yhat, aux={"q": q, "mu": mu})

    lo = LayerOutput(name, "q8_entry", [input], fwd, [],
                     size=input.size, state_specs=[mean_s, scale_s])
    lo._out_channels = cin
    lo._img_shape = getattr(input, "_img_shape", None)
    lo._q8 = (None, None, 1e-5)   # (deferred bn name, deferred act, eps)
    return lo


def img_conv_bn_q8(input, filter_size, num_filters: int,
                   num_channels: Optional[int] = None, stride: int = 1,
                   padding: int = 0, act=None, name: Optional[str] = None,
                   param_attr=None, bn_param_attr=None, bn_bias_attr=None,
                   moving_average_fraction=0.9, epsilon=1e-5,
                   conv_name: Optional[str] = None,
                   bn_name: Optional[str] = None, stash: str = "int8",
                   stochastic: bool = False):
    """Conv→BN block on the q8 pipeline (ops/q8.py): reads the producer's
    int8 stash (dequant + producer-BN affine + producer activation fused
    into this conv's input fusion), writes its own int8 stash (center +
    quantize fused into the conv's output fusion). This layer's OWN
    batch-norm affine and activation are *deferred* — applied by whichever
    q8 layer consumes it. Parameter/state names match the dense
    img_conv + batch_norm pair, so checkpoints interchange.

    The capability endpoint of the reference's fused
    CudnnBatchNormLayer (paddle/gserver/layers/CudnnBatchNormLayer.cpp:21)
    on TPU: see BENCHMARKS.md "Path to 4000"."""
    from paddle_tpu.ops import q8 as ops_q8

    name = name or auto_name("img_conv_bn_q8")
    conv_name = conv_name or name
    bn_name = bn_name or name
    act_name = act_mod.resolve(act)
    k = (filter_size, filter_size) if isinstance(filter_size, int) \
        else tuple(filter_size)
    enforce.enforce(k[0] == k[1], "img_conv_bn_q8: square kernels only")
    cin = num_channels or getattr(input, "_out_channels", None)
    enforce.enforce(cin is not None, f"img_conv_bn_q8 {name}: channels?")
    a = _param_attr(param_attr or ParamAttr(initializer="msra"),
                    f"{conv_name}.w")
    wspec = ParamSpec(a.name, (k[0], k[1], cin, num_filters), attr=a,
                      fan_in=k[0] * k[1] * cin)
    ga = _param_attr(bn_param_attr if isinstance(bn_param_attr, ParamAttr)
                     else ParamAttr(initializer="constant",
                                    initial_value=1.0), f"{bn_name}.gamma")
    ba = _param_attr(bn_bias_attr if isinstance(bn_bias_attr, ParamAttr)
                     else ParamAttr(initializer="constant",
                                    initial_value=0.0), f"{bn_name}.beta")
    gamma = ParamSpec(ga.name, (num_filters,), attr=ga)
    beta = ParamSpec(ba.name, (num_filters,), attr=ba)
    rmean_s = ParamSpec(f"{bn_name}.mean", (num_filters,),
                        attr=ParamAttr(initializer="constant",
                                       initial_value=0.0))
    rvar_s = ParamSpec(f"{bn_name}.var", (num_filters,),
                       attr=ParamAttr(initializer="constant",
                                      initial_value=1.0))
    qmean_s, qscale_s = _q8_state_specs(name, num_filters)
    parent_name = input.name
    parent_info = _q8_info(input)
    ih, iw = _infer_img_shape(input, cin, None)
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    oh = _conv_out_dim(ih, k[0], s[0], padding)
    ow = _conv_out_dim(iw, k[1], s[1], padding)

    def fwd(params, parents, ctx):
        v = parents[0]
        mom = moving_average_fraction
        if not ctx.is_training:
            # exact dense eval: conv -> BN(running stats) -> own act
            y = ops_conv.conv2d(v.array, params[wspec.name], stride=stride,
                                padding=padding)
            y = ops_norm.batch_norm_infer(
                y, params[gamma.name], params[beta.name],
                ctx.state_in[rmean_s.name], ctx.state_in[rvar_s.name],
                eps=epsilon)
            for spec in (rmean_s, rvar_s, qmean_s, qscale_s):
                ctx.state_out[spec.name] = ctx.state_in[spec.name]
            return _apply_act(Value(y), act_name)
        M, B, relu_in = _q8_parent_fold(parent_info, params, v.aux, ops_q8)
        blk = ops_q8.make_conv_q8(stride, padding, relu_in, stash,
                                  stochastic)
        yhat, q, mu, var, amax = blk(
            v.array, v.aux["q"], params[wspec.name], M, B,
            ctx.state_in[f"{parent_name}.q_mean"],
            ctx.state_in[f"{parent_name}.q_scale"],
            ctx.state_in[qmean_s.name], ctx.state_in[qscale_s.name],
            *_q8_key(ctx, name, stochastic))
        ctx.state_out[qmean_s.name] = mu
        ctx.state_out[qscale_s.name] = ops_q8.scale_from_amax(amax)
        ctx.state_out[rmean_s.name] = (
            mom * ctx.state_in[rmean_s.name] + (1 - mom) * mu)
        ctx.state_out[rvar_s.name] = (
            mom * ctx.state_in[rvar_s.name] + (1 - mom) * var)
        return Value(yhat, aux={"q": q, "mu": mu, "var": var})

    lo = LayerOutput(name, "img_conv_bn_q8", [input], fwd,
                     [wspec, gamma, beta],
                     size=oh * ow * num_filters if oh and ow else None,
                     activation=act_name,
                     state_specs=[rmean_s, rvar_s, qmean_s, qscale_s])
    lo._out_channels = num_filters
    lo._img_shape = (oh, ow)
    lo._q8 = (bn_name, act_name, epsilon)
    return lo


def addto_q8(input: Sequence[LayerOutput], act=None,
             name: Optional[str] = None, stash: str = "int8",
             stochastic: bool = False):
    """Residual add on the q8 pipeline: applies both producers' deferred
    BN affines/activations, adds, and stashes the sum centered PRE-act;
    this layer's own activation is deferred to its consumers."""
    from paddle_tpu.ops import q8 as ops_q8

    name = name or auto_name("addto_q8")
    act_name = act_mod.resolve(act)
    inputs = list(input)
    enforce.enforce(len(inputs) == 2, "addto_q8 takes exactly two inputs")
    cin = getattr(inputs[0], "_out_channels", None)
    enforce.enforce(cin is not None, f"addto_q8 {name}: unknown channels")
    p_names = [p.name for p in inputs]
    p_infos = [_q8_info(p) for p in inputs]
    qmean_s, qscale_s = _q8_state_specs(name, cin)

    def fwd(params, parents, ctx):
        va, vb = parents
        if not ctx.is_training:
            ctx.state_out[qmean_s.name] = ctx.state_in[qmean_s.name]
            ctx.state_out[qscale_s.name] = ctx.state_in[qscale_s.name]
            return _apply_act(Value(va.array + vb.array), act_name)
        Ma, Ba, relu_a = _q8_parent_fold(p_infos[0], params, va.aux, ops_q8)
        Mb, Bb, relu_b = _q8_parent_fold(p_infos[1], params, vb.aux, ops_q8)
        blk = ops_q8.make_add_q8(relu_a, relu_b, stash, stochastic)
        yhat, q, mu, amax = blk(
            va.array, va.aux["q"], Ma, Ba,
            ctx.state_in[f"{p_names[0]}.q_mean"],
            ctx.state_in[f"{p_names[0]}.q_scale"],
            vb.array, vb.aux["q"], Mb, Bb,
            ctx.state_in[f"{p_names[1]}.q_mean"],
            ctx.state_in[f"{p_names[1]}.q_scale"],
            ctx.state_in[qmean_s.name], ctx.state_in[qscale_s.name],
            *_q8_key(ctx, name, stochastic))
        ctx.state_out[qmean_s.name] = mu
        ctx.state_out[qscale_s.name] = ops_q8.scale_from_amax(amax)
        return Value(yhat, aux={"q": q, "mu": mu})

    lo = LayerOutput(name, "addto_q8", inputs, fwd, [],
                     size=inputs[0].size, activation=act_name,
                     state_specs=[qmean_s, qscale_s])
    lo._out_channels = cin
    lo._img_shape = getattr(inputs[0], "_img_shape", None)
    lo._q8 = (None, act_name, 1e-5)
    return lo


def q8_exit(input, name: Optional[str] = None):
    """Leave the q8 pipeline: dequantize the producer's stash, apply its
    deferred BN affine + activation, return a dense bf16 Value."""
    from paddle_tpu.ops import q8 as ops_q8

    name = name or auto_name("q8_exit")
    parent_name = input.name
    parent_info = _q8_info(input)

    def fwd(params, parents, ctx):
        v = parents[0]
        if not ctx.is_training:
            return v
        M, B, relu_in = _q8_parent_fold(parent_info, params, v.aux, ops_q8)
        out = ops_q8.make_exit(relu_in)(
            v.array, v.aux["q"], M, B,
            ctx.state_in[f"{parent_name}.q_mean"],
            ctx.state_in[f"{parent_name}.q_scale"])
        return Value(out)

    lo = LayerOutput(name, "q8_exit", [input], fwd, [], size=input.size)
    lo._out_channels = getattr(input, "_out_channels", None)
    lo._img_shape = getattr(input, "_img_shape", None)
    return lo


# ---------------------------------------------------------------------------
# regularisation / elementwise composition
# ---------------------------------------------------------------------------

def dropout(input, dropout_rate: float, name: Optional[str] = None):
    """Inverted dropout (reference: dropout_layer / ExtraAttr.drop_rate)."""
    name = name or auto_name("dropout")

    def fwd(params, parents, ctx):
        pv = parents[0]
        if not ctx.is_training or dropout_rate <= 0.0:
            return pv
        key = ctx.layer_key(name)
        enforce.enforce(key is not None,
                        "dropout in training mode needs a dropout_key")
        keep = 1.0 - dropout_rate
        mask = jax.random.bernoulli(key, keep, pv.array.shape)
        return pv.with_array(jnp.where(mask, pv.array / keep, 0.0))

    lo = LayerOutput(name, "dropout", [input], fwd, [], size=input.size)
    # elementwise: image-shape hints pass through (conv chains with
    # BN+dropout between convs must keep inferring channels)
    lo._out_channels = getattr(input, "_out_channels", None)
    lo._img_shape = getattr(input, "_img_shape", None)
    return lo


def concat(input: Sequence[LayerOutput], name: Optional[str] = None, act=None):
    """Feature-axis concat (reference: concat_layer). When every input is an
    image layer with the same spatial shape, concatenates on the channel
    axis and stays an image (the reference concat semantics for conv
    branches, e.g. inception blocks); otherwise flattens and concats."""
    name = name or auto_name("concat")
    act_name = act_mod.resolve(act)
    inputs = _as_list(input)
    shapes = [getattr(i, "_img_shape", None) for i in inputs]
    chans = [getattr(i, "_out_channels", None) for i in inputs]
    image_mode = (all(c for c in chans) and all(shapes) and
                  len({s for s in shapes}) == 1 and None not in shapes[0])

    def fwd(params, parents, ctx):
        if image_mode:
            arrs = [_to_nhwc(p.array, c, s[0], s[1])
                    for p, c, s in zip(parents, chans, shapes)]
            return _apply_act(Value(jnp.concatenate(arrs, axis=-1)), act_name)
        arrs = [_flatten_if_image(p.array) if p.array.ndim == 4 else p.array
                for p in parents]
        return _apply_act(Value(jnp.concatenate(arrs, axis=-1),
                                parents[0].lengths), act_name)

    lo = LayerOutput(name, "concat", inputs, fwd, [],
                     size=sum(i.size for i in inputs if i.size),
                     activation=act_name)
    if image_mode:
        lo._out_channels = sum(chans)
        lo._img_shape = shapes[0]
    return lo


def addto(input: Sequence[LayerOutput], act=None, name: Optional[str] = None,
          bias_attr=False):
    """Elementwise sum (reference: addto_layer; gserver AddtoLayer.cpp)."""
    name = name or auto_name("addto")
    act_name = act_mod.resolve(act)
    inputs = _as_list(input)
    bias = _bias_spec(name, inputs[0].size, bias_attr) if inputs[0].size else None

    def fwd(params, parents, ctx):
        total = parents[0].array
        for p in parents[1:]:
            total = total + p.array
        if bias:
            total = total + params[bias.name].astype(total.dtype)
        return _apply_act(Value(total, parents[0].lengths), act_name)

    lo = LayerOutput(name, "addto", inputs, fwd, [bias] if bias else [],
                     size=inputs[0].size, activation=act_name)
    lo._out_channels = getattr(inputs[0], "_out_channels", None)
    lo._img_shape = getattr(inputs[0], "_img_shape", None)
    return lo


def scaling(input, weight, name: Optional[str] = None):
    """Row-wise scale by a scalar per example (reference: scaling_layer)."""
    name = name or auto_name("scaling")

    def fwd(params, parents, ctx):
        w, x = parents[0].array, parents[1].array
        return Value(x * w.reshape(w.shape[0], *([1] * (x.ndim - 1))),
                     parents[1].lengths)

    return LayerOutput(name, "scaling", [weight, input], fwd, [],
                       size=input.size)


def slope_intercept(input, slope=1.0, intercept=0.0, name: Optional[str] = None):
    """y = slope*x + intercept (reference: slope_intercept_layer)."""
    name = name or auto_name("slope_intercept")

    def fwd(params, parents, ctx):
        return parents[0].with_array(parents[0].array * slope + intercept)

    return LayerOutput(name, "slope_intercept", [input], fwd, [],
                       size=input.size)


def cos_sim(a, b, scale=1.0, name: Optional[str] = None):
    """Cosine similarity rows of a vs b (reference: cos_sim layer;
    gserver CosSimLayer.cpp). Output [b, 1]."""
    name = name or auto_name("cos_sim")

    def fwd(params, parents, ctx):
        x, y = parents[0].array, parents[1].array
        xf, yf = x.astype(jnp.float32), y.astype(jnp.float32)
        num = jnp.sum(xf * yf, axis=-1, keepdims=True)
        den = jnp.linalg.norm(xf, axis=-1, keepdims=True) * \
            jnp.linalg.norm(yf, axis=-1, keepdims=True)
        return Value(scale * num / jnp.maximum(den, 1e-12))

    return LayerOutput(name, "cos_sim", [a, b], fwd, [], size=1)


# ---------------------------------------------------------------------------
# recurrent layers
# ---------------------------------------------------------------------------

def lstmemory(input, size: Optional[int] = None, reverse: bool = False,
              act=None, gate_act=None, name: Optional[str] = None,
              param_attr=None, bias_attr=None):
    """LSTM over a pre-projected sequence: input.size must be 4*size — the
    x@W projection is supplied by the preceding fc/mixed layer, the layer owns
    only recurrent weights, exactly the reference contract
    (reference: lstmemory in trainer_config_helpers/layers.py:3321,
    gserver/layers/LstmLayer.cpp)."""
    name = name or auto_name("lstmemory")
    enforce.enforce(input.size % 4 == 0, "lstmemory input size must be 4*size")
    size = size or input.size // 4
    enforce.enforce(input.size == 4 * size, "lstmemory input size != 4*size")
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    w_hh = ParamSpec(a.name, (size, 4 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 4 * size, bias_attr)
    specs = [w_hh] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "lstmemory needs sequence input")
        xp = pv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        bsz, tmax, _ = xp.shape
        mask = (jnp.arange(tmax)[None, :] < pv.lengths[:, None])
        h = jnp.zeros((bsz, size), xp.dtype)
        c = jnp.zeros((bsz, size), xp.dtype)
        xs, ms = jnp.moveaxis(xp, 1, 0), jnp.moveaxis(mask, 1, 0)
        if reverse:
            xs, ms = xs[::-1], ms[::-1]

        def step(state, inp):
            xt, mt = inp
            nxt = ops_rnn.lstm_cell(xt, state, params[w_hh.name])
            h_ = jnp.where(mt[:, None], nxt.h, state.h)
            c_ = jnp.where(mt[:, None], nxt.c, state.c)
            return ops_rnn.LSTMState(h_, c_), h_

        _, outs = jax.lax.scan(step, ops_rnn.LSTMState(h, c), (xs, ms))
        if reverse:
            outs = outs[::-1]
        outs = jnp.moveaxis(outs, 0, 1) * mask[..., None].astype(xp.dtype)
        return Value(outs, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "lstmemory", [input], fwd, specs, size=size)


def grumemory(input, size: Optional[int] = None, reverse: bool = False,
              act=None, name: Optional[str] = None, param_attr=None,
              bias_attr=None):
    """GRU over a pre-projected sequence (input.size == 3*size)
    (reference: grumemory; gserver/layers/GatedRecurrentLayer.cpp)."""
    name = name or auto_name("grumemory")
    enforce.enforce(input.size % 3 == 0, "grumemory input size must be 3*size")
    size = size or input.size // 3
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    w_hh = ParamSpec(a.name, (size, 3 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 3 * size, bias_attr)
    specs = [w_hh] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "grumemory needs sequence input")
        xp = pv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        bsz, tmax, _ = xp.shape
        mask = (jnp.arange(tmax)[None, :] < pv.lengths[:, None])
        h = jnp.zeros((bsz, size), xp.dtype)
        xs, ms = jnp.moveaxis(xp, 1, 0), jnp.moveaxis(mask, 1, 0)
        if reverse:
            xs, ms = xs[::-1], ms[::-1]

        def step(state, inp):
            xt, mt = inp
            nh = ops_rnn.gru_cell(xt, state, params[w_hh.name])
            nh = jnp.where(mt[:, None], nh, state)
            return nh, nh

        _, outs = jax.lax.scan(step, h, (xs, ms))
        if reverse:
            outs = outs[::-1]
        outs = jnp.moveaxis(outs, 0, 1) * mask[..., None].astype(xp.dtype)
        return Value(outs, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "grumemory", [input], fwd, specs, size=size)


def recurrent(input, act=None, reverse: bool = False, name: Optional[str] = None,
              param_attr=None, bias_attr=False):
    """Simple full-matrix recurrent layer over a pre-projected sequence
    (reference: gserver/layers/RecurrentLayer.cpp)."""
    name = name or auto_name("recurrent")
    size = input.size
    act_name = act_mod.resolve(act or "tanh")
    a = _param_attr(param_attr or ParamAttr(), f"{name}.w")
    w_hh = ParamSpec(a.name, (size, size), attr=a, fan_in=size)
    bias = _bias_spec(name, size, bias_attr)
    specs = [w_hh] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        pv = parents[0]
        xp = pv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        outs, _ = ops_rnn.simple_rnn(
            xp, pv.lengths, None,  # input already projected by contract
            params[w_hh.name], act=ops_act.get(act_name), reverse=reverse)
        return Value(outs, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "recurrent", [input], fwd, specs, size=size,
                       activation=act_name)


# ---------------------------------------------------------------------------
# sequence layers
# ---------------------------------------------------------------------------

def pool(input, pooling_type=None, name: Optional[str] = None):
    """Sequence pooling (reference: pooling_layer; SequencePoolLayer.cpp)."""
    name = name or auto_name("seq_pool")
    ptype = pooling_mod.resolve(pooling_type)

    def fwd(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "pooling_layer needs sequence input")
        fn = {"max": ops_seq.seq_max, "avg": ops_seq.seq_avg,
              "sum": ops_seq.seq_sum, "sqrt": ops_seq.seq_sqrt}[ptype]
        return Value(fn(pv.array, pv.lengths))

    return LayerOutput(name, "seq_pool", [input], fwd, [], size=input.size)


pooling_layer = pool


def last_seq(input, name: Optional[str] = None):
    """(reference: last_seq / SequenceLastInstanceLayer.cpp)"""
    name = name or auto_name("last_seq")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_seq.seq_last(pv.array, pv.lengths))

    return LayerOutput(name, "last_seq", [input], fwd, [], size=input.size)


def first_seq(input, name: Optional[str] = None):
    name = name or auto_name("first_seq")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_seq.seq_first(pv.array, pv.lengths))

    return LayerOutput(name, "first_seq", [input], fwd, [], size=input.size)


def expand(input, expand_as, name: Optional[str] = None):
    """Broadcast per-sequence vectors over timesteps (reference: expand_layer)."""
    name = name or auto_name("expand")

    def fwd(params, parents, ctx):
        v, ref = parents
        out = ops_seq.seq_expand(v.array, ref.lengths, ref.array.shape[1])
        return Value(out, ref.lengths, ref.sub_lengths)

    return LayerOutput(name, "expand", [input, expand_as], fwd, [],
                       size=input.size)


def seq_reverse(input, name: Optional[str] = None):
    name = name or auto_name("seq_reverse")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_seq.seq_reverse(pv.array, pv.lengths), pv.lengths,
                     pv.sub_lengths)

    return LayerOutput(name, "seq_reverse", [input], fwd, [], size=input.size)


def seq_concat(a, b, name: Optional[str] = None):
    """Per-sequence time concat (reference: seq_concat_layer)."""
    name = name or auto_name("seq_concat")

    def fwd(params, parents, ctx):
        x, y = parents
        out, lens = ops_seq.seq_concat(x.array, x.lengths, y.array, y.lengths)
        return Value(out, lens)

    return LayerOutput(name, "seq_concat", [a, b], fwd, [], size=a.size)


def context_projection(input, context_len: int, context_start: Optional[int] = None,
                       name: Optional[str] = None):
    """Context-window concat as a standalone layer (reference:
    context_projection inside mixed_layer; function/ContextProjectionOp.cpp)."""
    name = name or auto_name("context_projection")
    start = context_start if context_start is not None else -(context_len // 2)

    def fwd(params, parents, ctx):
        pv = parents[0]
        out = ops_seq.context_projection(pv.array, pv.lengths, context_len, start)
        return Value(out, pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "context_projection", [input], fwd, [],
                       size=input.size * context_len)


# ---------------------------------------------------------------------------
# outputs / decisions
# ---------------------------------------------------------------------------

def max_id(input, name: Optional[str] = None):
    """Argmax layer (reference: maxid_layer / MaxIdLayer.cpp)."""
    name = name or auto_name("max_id")

    def fwd(params, parents, ctx):
        pv = parents[0]
        return Value(ops_topk.max_id(pv.array), pv.lengths, pv.sub_lengths)

    return LayerOutput(name, "max_id", [input], fwd, [], size=1)


# ---------------------------------------------------------------------------
# costs
# ---------------------------------------------------------------------------

def _seq_token_cost(per_token: jax.Array, lengths) -> jax.Array:
    """Sum per-token losses over valid steps → per-sequence cost."""
    tmax = per_token.shape[1]
    mask = (jnp.arange(tmax)[None, :] < lengths[:, None]).astype(per_token.dtype)
    return jnp.sum(per_token * mask, axis=1)


def _cost_layer(name, layer_type, inputs, per_example_fn, size=1):
    def fwd(params, parents, ctx):
        return Value(per_example_fn(params, parents, ctx))
    return LayerOutput(name, layer_type, inputs, fwd, [], size=size)


def classification_cost(input, label, name: Optional[str] = None):
    """Softmax classification cost (reference: classification_cost in v2;
    MultiClassCrossEntropy CostLayer). Softmax-activated inputs (the v1
    convention) are fused: CE is computed as log_softmax on the kept logits,
    never as -log(p) on the probabilities (the reference's fused
    softmax_with_cross_entropy rationale — -log(p+eps) spikes to 1/eps-scale
    gradients when saturated). CE on probabilities remains only as a fallback
    for inputs whose logits were not preserved. Sequence inputs produce
    per-token CE summed over each sequence."""
    name = name or auto_name("classification_cost")
    on_probs = input.activation == "softmax" or input.activation == "sequence_softmax"

    def per_example(params, parents, ctx):
        pv, lv = parents
        pred, lab = pv.array, lv.array
        # Fused path: if the input layer applied softmax and kept its logits,
        # compute CE in log-space on the logits. -log(p+eps) on saturated
        # probabilities produces 1/eps-scale gradient spikes that kill
        # training (dead ReLUs); log_softmax on logits is exact and stable.
        logits = pv.pre_act if input.activation == "softmax" else None
        if pv.is_sequence:
            lab3 = lab if lab.ndim == 2 else lab.reshape(lab.shape[0], -1)
            if logits is not None:
                tok = ops_loss.softmax_cross_entropy(logits, lab3)
            elif on_probs:
                tok = ops_loss.cross_entropy_with_probs(pred, lab3)
            else:
                tok = ops_loss.softmax_cross_entropy(pred, lab3)
            return _seq_token_cost(tok, pv.lengths)
        lab1 = lab.reshape(-1)
        if logits is not None:
            return ops_loss.softmax_cross_entropy(logits, lab1)
        if on_probs:
            return ops_loss.cross_entropy_with_probs(pred, lab1)
        return ops_loss.softmax_cross_entropy(pred, lab1)

    return _cost_layer(name, "classification_cost", [input, label], per_example)


def cross_entropy_cost(input, label, name: Optional[str] = None):
    name = name or auto_name("cross_entropy")
    return classification_cost(input, label, name=name)


def cross_entropy_over_beam(step_scores, parents, gold_scores, gold_slot,
                            valid_mask=None, name: Optional[str] = None):
    """Globally-normalized beam-training objective (reference:
    cross_entropy_over_beam / CrossEntropyOverBeam.cpp — softmax over all
    expanded beam paths with the gold path as an extra slot when it fell
    off the beam, loss = −log p(gold)).

    Fixed-width surface over the [B, S, K] beam lattice produced by
    ops/beam.py-style search (the reference's dynamic BeamInput triples
    collapse to dense tensors + masks on TPU):
    ``step_scores`` [B, S·K] or [B, S, K] candidate scores,
    ``parents`` same shape (int), ``gold_scores`` [B, S],
    ``gold_slot`` [B] (−1 when the gold path left the beam),
    ``valid_mask`` optional [B, K]. Emits the per-sequence loss."""
    name = name or auto_name("cross_entropy_over_beam")
    inputs = [step_scores, parents, gold_scores, gold_slot]
    if valid_mask is not None:
        inputs.append(valid_mask)

    def per_example(params, parents_v, ctx):
        sc, par, gsc, gslot = (v.array for v in parents_v[:4])
        vm = parents_v[4].array.astype(bool) if valid_mask is not None \
            else None
        if sc.ndim == 2:                   # flat [B, S*K] feed layout
            S = gsc.shape[1]
            sc = sc.reshape(sc.shape[0], S, -1)
            par = par.reshape(par.shape[0], S, -1)
        return ops_beam.cross_entropy_over_beam(
            sc, par.astype(jnp.int32), gsc,
            gslot.reshape(gslot.shape[0]).astype(jnp.int32), vm)

    return _cost_layer(name, "cross_entropy_over_beam", inputs, per_example)


def square_error_cost(input, label, name: Optional[str] = None):
    """(reference: square_error_cost / SumOfSquaresCostLayer)"""
    name = name or auto_name("square_error")

    def per_example(params, parents, ctx):
        return ops_loss.square_error(parents[0].array, parents[1].array)

    return _cost_layer(name, "square_error", [input, label], per_example)


regression_cost = square_error_cost
mse_cost = square_error_cost


def multi_binary_label_cross_entropy_cost(input, label, name=None):
    name = name or auto_name("multi_binary_ce")

    def per_example(params, parents, ctx):
        return ops_loss.multi_binary_cross_entropy(parents[0].array,
                                                   parents[1].array)

    return _cost_layer(name, "multi_binary_ce", [input, label], per_example)


def rank_cost(left, right, label, name: Optional[str] = None):
    """(reference: rank_cost / RankingCost)"""
    name = name or auto_name("rank_cost")

    def per_example(params, parents, ctx):
        return ops_loss.rank_cost(parents[0].array, parents[1].array,
                                  parents[2].array.reshape(-1))

    return _cost_layer(name, "rank_cost", [left, right, label], per_example)


def huber_classification_cost(input, label, name: Optional[str] = None):
    name = name or auto_name("huber_cost")

    def per_example(params, parents, ctx):
        return ops_loss.huber_classification(parents[0].array,
                                             parents[1].array.reshape(-1))

    return _cost_layer(name, "huber_cost", [input, label], per_example)


def hinge_cost(input, label, name: Optional[str] = None):
    name = name or auto_name("hinge_cost")

    def per_example(params, parents, ctx):
        return ops_loss.hinge(parents[0].array, parents[1].array.reshape(-1))

    return _cost_layer(name, "hinge_cost", [input, label], per_example)


def crf_layer(input, label, size: Optional[int] = None,
              name: Optional[str] = None, param_attr=None):
    """Linear-chain CRF cost over a sequence of emissions.

    ``input`` is a sequence layer with per-token tag scores (size = #tags),
    ``label`` an integer tag sequence. Produces the per-sequence negative
    log-likelihood. Reference: crf_layer (trainer_config_helpers/layers.py),
    gserver/layers/CRFLayer.cpp, operators/linear_chain_crf_op.cc — same
    (#tags+2, #tags) transition parameterization (start/end rows first).
    """
    from paddle_tpu.ops import crf as ops_crf
    name = name or auto_name("crf")
    enforce.enforce(size is None or size == input.size,
                    f"crf_layer size {size} != input size {input.size}")
    n_tags = size or input.size
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    spec = ParamSpec(a.name, (n_tags + 2, n_tags), attr=a, fan_in=n_tags)

    def fwd(params, parents, ctx):
        ev, lv = parents
        enforce.enforce(ev.is_sequence, "crf_layer input must be a sequence")
        emis = ev.pre_act if ev.pre_act is not None else ev.array
        tags = lv.array.astype(jnp.int32)
        if tags.ndim == 3:
            tags = tags[..., 0]
        nll = -ops_crf.crf_log_likelihood(emis, tags, ev.lengths,
                                          params[spec.name])
        return Value(nll)

    return LayerOutput(name, "crf", [input, label], fwd, [spec], size=1)


def crf_decoding_layer(input, size: Optional[int] = None, label=None,
                       name: Optional[str] = None, param_attr=None):
    """Viterbi decode with a (shared) CRF transition parameter.

    Without ``label``: outputs the best tag sequence [B, T]. With ``label``:
    outputs a per-token 0/1 mismatch mask (the reference's evaluation mode,
    operators/crf_decoding_op.cc:24-35, gserver CRFDecodingLayer).
    Share transitions with the training crf_layer via
    ``param_attr=ParamAttr(name=...)``.
    """
    from paddle_tpu.ops import crf as ops_crf
    name = name or auto_name("crf_decoding")
    enforce.enforce(size is None or size == input.size,
                    f"crf_decoding size {size} != input size {input.size}")
    n_tags = size or input.size
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    spec = ParamSpec(a.name, (n_tags + 2, n_tags), attr=a, fan_in=n_tags)
    inputs = [input] + ([label] if label is not None else [])

    def fwd(params, parents, ctx):
        ev = parents[0]
        enforce.enforce(ev.is_sequence,
                        "crf_decoding_layer input must be a sequence")
        emis = ev.pre_act if ev.pre_act is not None else ev.array
        tags, _ = ops_crf.crf_decode(emis, ev.lengths, params[spec.name])
        if label is not None:
            lab = parents[1].array.astype(jnp.int32)
            if lab.ndim == 3:
                lab = lab[..., 0]
            mask = (jnp.arange(tags.shape[1])[None, :] <
                    ev.lengths[:, None])
            err = jnp.where(mask, (tags != lab).astype(jnp.float32), 0.0)
            return Value(err, ev.lengths)
        return Value(tags, ev.lengths)

    return LayerOutput(name, "crf_decoding", inputs, fwd, [spec], size=1)


def ctc_layer(input, label, size: Optional[int] = None,
              blank: Optional[int] = None, norm_by_times: bool = False,
              name: Optional[str] = None):
    """CTC cost. ``input``: sequence layer of per-frame class scores
    (size = #labels + 1 incl. blank); ``label``: target label sequence.
    Default blank is the LAST class index, matching the v1 ctc_layer
    (gserver/layers/CTCLayer.cpp, LinearChainCTC.cpp uses numClasses-1);
    warp_ctc_layer defaults to blank=0 (WarpCTCLayer.cpp).
    Reference: ctc_layer / warp_ctc_layer (trainer_config_helpers/layers.py).
    """
    from paddle_tpu.ops import ctc as ops_ctc
    name = name or auto_name("ctc")
    enforce.enforce(size is None or size == input.size,
                    f"ctc_layer size {size} != input size {input.size}")
    n_classes = size or input.size
    blank_idx = n_classes - 1 if blank is None else blank

    def fwd(params, parents, ctx):
        ev, lv = parents
        if ev.pre_act is not None:
            logp = jax.nn.log_softmax(ev.pre_act.astype(jnp.float32), axis=-1)
        elif input.activation == "softmax":
            logp = jnp.log(jnp.maximum(ev.array.astype(jnp.float32), 1e-30))
        else:
            logp = jax.nn.log_softmax(ev.array.astype(jnp.float32), axis=-1)
        lab = lv.array.astype(jnp.int32)
        if lab.ndim == 3:
            lab = lab[..., 0]
        enforce.enforce(ev.is_sequence and lv.is_sequence,
                        "ctc_layer input and label must be sequences")
        nll = ops_ctc.ctc_loss(logp, lab, ev.lengths, lv.lengths,
                               blank=blank_idx)
        if norm_by_times:
            nll = nll / jnp.maximum(ev.lengths.astype(jnp.float32), 1.0)
        return Value(nll)

    return LayerOutput(name, "ctc", [input, label], fwd, [], size=1)


def warp_ctc_layer(input, label, size: Optional[int] = None, blank: int = 0,
                   norm_by_times: bool = False, name: Optional[str] = None):
    """warp-ctc flavor: blank defaults to 0 (reference: WarpCTCLayer.cpp,
    hl_warpctc_wrap.cc)."""
    return ctc_layer(input, label, size=size, blank=blank,
                     norm_by_times=norm_by_times,
                     name=name or auto_name("warp_ctc"))


def gru_step(input, state, size: Optional[int] = None,
             name: Optional[str] = None, param_attr=None, bias_attr=None):
    """One GRU step for use inside recurrent_group (reference:
    gru_step_layer, trainer_config_helpers/layers.py; GruStepLayer.cpp).
    ``input``: the projected step input [B, 3H] (W·x, as in the reference —
    compute it with an fc of size 3*size); ``state``: an H-wide memory."""
    name = name or auto_name("gru_step")
    size = size or input.size // 3
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(a.name, (size, 3 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 3 * size, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        xv, sv = parents
        xp = xv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        h = ops_rnn.gru_cell(xp, sv.array, params[w_spec.name])
        return Value(h, xv.lengths, xv.sub_lengths)

    return LayerOutput(name, "gru_step", [input, state], fwd, specs,
                       size=size)


def lstm_step(input, state, cell_state, size: Optional[int] = None,
              name: Optional[str] = None, param_attr=None, bias_attr=None,
              forget_bias: float = 0.0):
    """One LSTM step for recurrent_group (reference: lstm_step_layer).
    ``input``: projected step input [B, 4H]; ``state``/``cell_state``:
    H-wide memories for h and c. Returns (h_layer, c_layer) — link the h
    memory to the first and the c memory to the second."""
    name = name or auto_name("lstm_step")
    size = size or input.size // 4
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(a.name, (size, 4 * size), attr=a, fan_in=size)
    bias = _bias_spec(name, 4 * size, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])

    def fwd_h(params, parents, ctx):
        xv, hv, cv = parents
        xp = xv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        st = ops_rnn.lstm_cell(xp, ops_rnn.LSTMState(hv.array, cv.array),
                               params[w_spec.name], forget_bias)
        return Value(st.h, xv.lengths, xv.sub_lengths)

    h_layer = LayerOutput(name, "lstm_step", [input, state, cell_state],
                          fwd_h, specs, size=size)

    def fwd_c(params, parents, ctx):
        xv, hv, cv = parents
        xp = xv.array
        if bias:
            xp = xp + params[bias.name].astype(xp.dtype)
        st = ops_rnn.lstm_cell(xp, ops_rnn.LSTMState(hv.array, cv.array),
                               params[w_spec.name], forget_bias)
        return Value(st.c, xv.lengths, xv.sub_lengths)

    c_layer = LayerOutput(f"{name}@cell", "lstm_step_cell",
                          [input, state, cell_state], fwd_c, specs, size=size)
    return h_layer, c_layer


# ---------------------------------------------------------------------------
# elementwise / structural layers (reference: trainer_config_helpers/layers.py
# interpolation_layer, power_layer, sum_to_one_norm_layer, clip_layer,
# resize_layer, trans_layer, rotate_layer, repeat_layer, maxout_layer,
# multiplex_layer, out_prod_layer, tensor_layer, linear_comb_layer,
# conv_shift_layer, scale_shift_layer, prelu_layer, row_l2_norm_layer,
# gated_unit_layer, eos_layer, sampling_id_layer and their gserver/*.cpp
# implementations)
# ---------------------------------------------------------------------------

def _simple_layer(name, ltype, inputs, fn, size, activation=None, specs=(),
                  meta_from=0):
    """Stateless layer from an array function over parent Values.
    ``meta_from``: index of the parent whose sequence metadata carries over
    (None drops it — for layers that change the row structure)."""
    def fwd(params, parents, ctx):
        arr = fn(params, parents, ctx)
        if meta_from is None:
            return Value(arr)
        p0 = parents[meta_from]
        return Value(arr, p0.lengths, p0.sub_lengths)
    return LayerOutput(name, ltype, inputs, fwd, list(specs), size=size,
                       activation=activation)


def interpolation(input, weight, name: Optional[str] = None):
    """out = w*x + (1-w)*y, per-sample scalar w (reference:
    interpolation_layer; InterpolationLayer.cpp)."""
    name = name or auto_name("interpolation")
    x, y = input
    enforce.enforce(x.size == y.size, "interpolation inputs must match")

    def fn(params, parents, ctx):
        x = parents[1].array
        w = parents[0].array.reshape((-1,) + (1,) * (x.ndim - 1))
        return w * x + (1.0 - w) * parents[2].array

    return _simple_layer(name, "interpolation", [weight, x, y], fn,
                         x.size, meta_from=1)


def power(input, weight, name: Optional[str] = None):
    """out = x ** w, per-sample scalar w (reference: power_layer)."""
    name = name or auto_name("power")

    def fn(params, parents, ctx):
        x = parents[1].array
        w = parents[0].array.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.power(x, w)

    return _simple_layer(name, "power", [weight, input], fn, input.size,
                         meta_from=1)


def sum_to_one_norm(input, name: Optional[str] = None):
    """x / sum(x) per row (reference: sum_to_one_norm_layer)."""
    name = name or auto_name("sum_to_one_norm")

    def fn(params, parents, ctx):
        x = parents[0].array
        return x / jnp.sum(x, axis=-1, keepdims=True)

    return _simple_layer(name, "sum_to_one_norm", [input], fn, input.size)


def row_l2_norm(input, name: Optional[str] = None, eps: float = 1e-12):
    """x / ||x||_2 per row (reference: row_l2_norm_layer)."""
    name = name or auto_name("row_l2_norm")

    def fn(params, parents, ctx):
        x = parents[0].array
        n = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + eps)
        return x / n

    return _simple_layer(name, "row_l2_norm", [input], fn, input.size)


def clip(input, min: float, max: float, name: Optional[str] = None):
    """elementwise clip (reference: clip_layer / clip_op.cc)."""
    name = name or auto_name("clip")
    lo, hi = min, max

    def fn(params, parents, ctx):
        return jnp.clip(parents[0].array, lo, hi)

    return _simple_layer(name, "clip", [input], fn, input.size)


def resize(input, size: int, name: Optional[str] = None):
    """Reshape the whole batch matrix to rows of ``size`` (reference:
    resize_layer — ResizeLayer.cpp reinterprets [B, D] as [B*D/size, size])."""
    name = name or auto_name("resize")

    def fn(params, parents, ctx):
        return parents[0].array.reshape(-1, size)

    return _simple_layer(name, "resize", [input], fn, size,
                         meta_from=None)


def trans(input, name: Optional[str] = None):
    """Transpose the [B, D] batch matrix (reference: trans_layer,
    TransLayer.cpp — used for tied-weight tricks)."""
    name = name or auto_name("trans")

    def fn(params, parents, ctx):
        return parents[0].array.T

    return _simple_layer(name, "trans", [input], fn, input.size,
                         meta_from=None)


def repeat(input, num_repeats: int, as_row_vector: bool = True,
           act=None, name: Optional[str] = None):
    """Tile each row n times (reference: repeat_layer, FeatureMapExpand).
    as_row_vector: [a b c] -> [a b c a b c]; else [a a b b c c]."""
    name = name or auto_name("repeat")
    act_name = act_mod.resolve(act)

    def fn(params, parents, ctx):
        x = parents[0].array
        if as_row_vector:
            out = jnp.tile(x, (1,) * (x.ndim - 1) + (num_repeats,))
        else:
            out = jnp.repeat(x, num_repeats, axis=-1)
        return ops_act.get(act_name)(out)

    return _simple_layer(name, "repeat", [input], fn,
                         input.size * num_repeats, activation=act_name)


def maxout(input, groups: int, num_channels: Optional[int] = None,
           name: Optional[str] = None):
    """Max over ``groups`` consecutive channels (reference: maxout_layer,
    MaxOutLayer.cpp; new stack maxout_op.cc)."""
    name = name or auto_name("maxout")

    def fn(params, parents, ctx):
        x = parents[0].array
        if x.ndim == 4:                        # NHWC
            n, h, w, c = x.shape
            return jnp.max(x.reshape(n, h, w, c // groups, groups), axis=-1)
        n, d = x.shape
        return jnp.max(x.reshape(n, d // groups, groups), axis=-1)

    lo = _simple_layer(name, "maxout", [input], fn, input.size // groups)
    cin = getattr(input, "_out_channels", None)
    if cin:
        lo._out_channels = cin // groups
        lo._img_shape = getattr(input, "_img_shape", None)
    return lo


def multiplex(input, name: Optional[str] = None):
    """Row-wise select among inputs by an index layer (reference:
    multiplex_layer, MultiplexLayer.cpp; multiplex_op.cc). input[0] is the
    integer selector; input[1:] the candidates."""
    name = name or auto_name("multiplex")
    sel, cands = input[0], list(input[1:])

    def fn(params, parents, ctx):
        idx = parents[0].array.reshape(-1).astype(jnp.int32)
        stack = jnp.stack([p.array for p in parents[1:]], axis=0)  # [K, B, F]
        return jnp.take_along_axis(
            stack, idx[None, :, None].astype(jnp.int32), axis=0)[0]

    return _simple_layer(name, "multiplex", [sel] + cands, fn,
                         cands[0].size, meta_from=1)


def out_prod(a, b, name: Optional[str] = None):
    """Flattened outer product per sample (reference: out_prod_layer,
    OuterProdLayer.cpp)."""
    name = name or auto_name("out_prod")

    def fn(params, parents, ctx):
        x, y = parents[0].array, parents[1].array
        return jnp.einsum("bi,bj->bij", x, y).reshape(x.shape[0], -1)

    return _simple_layer(name, "out_prod", [a, b], fn, a.size * b.size)


def tensor(a, b, size: int, act=None, name: Optional[str] = None,
           param_attr=None, bias_attr=None):
    """Bilinear tensor product out_k = a^T W_k b (reference: tensor_layer,
    TensorLayer.cpp)."""
    name = name or auto_name("tensor")
    act_name = act_mod.resolve(act)
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(attr.name, (size, a.size, b.size), attr=attr,
                       fan_in=a.size * b.size)
    bias = _bias_spec(name, size, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        x, y = parents[0].array, parents[1].array
        out = jnp.einsum("bi,kij,bj->bk", x, params[w_spec.name], y)
        if bias:
            out = out + params[bias.name].astype(out.dtype)
        v = Value(out, parents[0].lengths, parents[0].sub_lengths)
        return _apply_act(v, act_name)

    return LayerOutput(name, "tensor", [a, b], fwd, specs, size=size,
                       activation=act_name)


def linear_comb(weights, vectors, size: int, name: Optional[str] = None):
    """out = sum_i w_i * v_i with vectors viewed as [M, size] per sample
    (reference: linear_comb_layer, ConvexCombinationLayer.cpp)."""
    name = name or auto_name("linear_comb")

    def fn(params, parents, ctx):
        w = parents[0].array                       # [B, M]
        v = parents[1].array.reshape(w.shape[0], w.shape[1], size)
        return jnp.einsum("bm,bms->bs", w, v)

    return _simple_layer(name, "linear_comb", [weights, vectors], fn,
                         size, meta_from=1)


def conv_shift(a, b, name: Optional[str] = None):
    """Circular 1-D convolution of each row of ``a`` by the (odd-sized)
    kernel row of ``b`` (reference: conv_shift_layer, ConvShiftLayer.cpp)."""
    name = name or auto_name("conv_shift")
    enforce.enforce(b.size % 2 == 1,
                    f"conv_shift kernel size must be odd, got {b.size}")

    def fn(params, parents, ctx):
        x, k = parents[0].array, parents[1].array
        m = k.shape[-1]
        half = (m - 1) // 2
        idx = (jnp.arange(x.shape[-1])[:, None] +
               jnp.arange(-half, half + 1)[None, :]) % x.shape[-1]
        windows = x[:, idx]                        # [B, D, M]
        return jnp.einsum("bdm,bm->bd", windows, k)

    return _simple_layer(name, "conv_shift", [a, b], fn, a.size)


def scale_shift(input, name: Optional[str] = None, param_attr=None,
                bias_attr=None):
    """w*x + b with scalar learnable w (and b) (reference:
    scale_shift_layer, ScaleShiftLayer.cpp)."""
    name = name or auto_name("scale_shift")
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(initializer="constant",
                                      initial_value=1.0), f"{name}.w")
    w_spec = ParamSpec(attr.name, (1,), attr=attr)
    bias = _bias_spec(name, 1, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])

    def fn(params, parents, ctx):
        out = parents[0].array * params[w_spec.name].astype(
            parents[0].array.dtype)
        if bias:
            out = out + params[bias.name].astype(out.dtype)
        return out

    return _simple_layer(name, "scale_shift", [input], fn, input.size,
                         specs=specs)


def prelu(input, name: Optional[str] = None, param_attr=None,
          channel_shared: bool = False):
    """Parametric ReLU (reference: prelu_layer, ParameterReluLayer.cpp;
    new stack prelu_op). Slope is per-channel unless channel_shared."""
    name = name or auto_name("prelu")
    channels = getattr(input, "_out_channels", None)
    nslopes = 1 if channel_shared else (channels or input.size)
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(initializer="constant",
                                      initial_value=0.25), f"{name}.w")
    w_spec = ParamSpec(attr.name, (nslopes,), attr=attr)

    def fn(params, parents, ctx):
        x = parents[0].array
        a = params[w_spec.name].astype(x.dtype)
        if x.ndim == 4 and not channel_shared:
            a = a.reshape(1, 1, 1, -1)
        return jnp.where(x > 0, x, a * x)

    return _simple_layer(name, "prelu", [input], fn, input.size,
                         specs=[w_spec])


def gated_unit(input, size: int, act=None, name: Optional[str] = None,
               gate_attr=None, inproj_attr=None):
    """act(fc(x)) * sigmoid(fc_gate(x)) (reference: gated_unit_layer,
    GatedRecurrentLayer-adjacent GLU, layers.py:6458)."""
    name = name or auto_name("gated_unit")
    proj = fc(input, size=size, act=act, name=f"{name}_input",
              param_attr=inproj_attr)
    gate = fc(input, size=size, act="sigmoid", name=f"{name}_gate",
              param_attr=gate_attr)

    def fn(params, parents, ctx):
        return parents[0].array * parents[1].array

    return _simple_layer(name, "gated_unit", [proj, gate], fn, size)


def eos(input, eos_id: int, name: Optional[str] = None):
    """1.0 where the integer input equals eos_id (reference: eos_layer,
    EosIdCheckLayer.cpp)."""
    name = name or auto_name("eos")

    def fn(params, parents, ctx):
        return (parents[0].array == eos_id).astype(jnp.float32)

    return _simple_layer(name, "eos", [input], fn, 1)


def sampling_id(input, name: Optional[str] = None):
    """Sample an id per row from the input distribution (reference:
    sampling_id_layer, SamplingIdLayer.cpp). Uses the per-layer RNG key in
    training; argmax fallback when no key is present (deterministic eval)."""
    name = name or auto_name("sampling_id")

    def fwd(params, parents, ctx):
        p = parents[0].array
        key = ctx.layer_key(name)
        if key is None:
            ids = jnp.argmax(p, axis=-1)
        else:
            ids = jax.random.categorical(
                key, jnp.log(jnp.maximum(p.astype(jnp.float32), 1e-30)))
        return Value(ids.astype(jnp.int32), parents[0].lengths)

    return LayerOutput(name, "sampling_id", [input], fwd, [], size=1)


# ---------------------------------------------------------------------------
# image geometry / 3D layers (reference: pad_layer PadLayer.cpp, crop_layer
# CropLayer.cpp, bilinear_interp_layer BilinearInterpLayer.cpp, rotate_layer
# RotateLayer.cpp, cross_channel_norm_layer CrossChannelNormLayer (detection),
# block_expand_layer BlockExpandLayer.cpp, img_conv3d/img_pool3d)
# ---------------------------------------------------------------------------

def _img_layer(name, ltype, input, fn, out_c, out_h, out_w, extra_specs=()):
    c_in, h_in, w_in = _img_in_shape(input)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, c_in, h_in, w_in)
        return Value(fn(params, x, ctx))
    lo = LayerOutput(name, ltype, [input], fwd, list(extra_specs),
                     size=out_c * out_h * out_w)
    lo._out_channels = out_c
    lo._img_shape = (out_h, out_w)
    return lo


def _img_in_shape(input):
    """(channels, H, W) of a layer's image output, via the conv-layer shape
    hints (_out_channels/_img_shape, the config_parser ImgSize equivalent)."""
    c = getattr(input, "_out_channels", None) or 1
    h, w = _infer_img_shape(input, c, None)
    return c, h, w


def pad(input, pad_c=(0, 0), pad_h=(0, 0), pad_w=(0, 0),
        name: Optional[str] = None):
    """Zero-pad channels/height/width (reference: pad_layer, PadLayer.cpp)."""
    name = name or auto_name("pad")
    c, h, w = _img_in_shape(input)
    oc, oh, ow = c + sum(pad_c), h + sum(pad_h), w + sum(pad_w)

    def fn(params, x, ctx):
        return jnp.pad(x, ((0, 0), tuple(pad_h), tuple(pad_w), tuple(pad_c)))

    return _img_layer(name, "pad", input, fn, oc, oh, ow)


def crop(input, offset, shape, name: Optional[str] = None):
    """Static crop of CHW dims: offset/shape are (c, h, w) triples
    (reference: crop_layer, CropLayer.cpp / crop_op.cc)."""
    name = name or auto_name("crop")
    oc, oh, ow = shape

    def fn(params, x, ctx):
        co, ho, wo = offset
        return x[:, ho:ho + oh, wo:wo + ow, co:co + oc]

    return _img_layer(name, "crop", input, fn, oc, oh, ow)


def bilinear_interp(input, out_size_x: int, out_size_y: int,
                    name: Optional[str] = None):
    """Bilinear resize (reference: bilinear_interp_layer,
    BilinearInterpLayer.cpp; bilinear_interp_op.cc)."""
    name = name or auto_name("bilinear_interp")
    c, h, w = _img_in_shape(input)

    def fn(params, x, ctx):
        return jax.image.resize(x, (x.shape[0], out_size_y, out_size_x,
                                    x.shape[3]), method="bilinear")

    return _img_layer(name, "bilinear_interp", input, fn, c, out_size_y,
                      out_size_x)


def rotate(input, height: Optional[int] = None, width: Optional[int] = None,
           name: Optional[str] = None):
    """Rotate each feature map 90° counter-clockwise (reference:
    rotate_layer, RotateLayer.cpp)."""
    name = name or auto_name("rotate")
    c, h0, w0 = _img_in_shape(input)
    h, w = height or h0, width or w0

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, c, h, w)
        return Value(jnp.rot90(x, k=1, axes=(1, 2)))

    lo = LayerOutput(name, "rotate", [input], fwd, [], size=c * h * w)
    lo._out_channels = c
    lo._img_shape = (w, h)
    return lo


def switch_order(input, reshape_order=None, name: Optional[str] = None):
    """NCHW <-> NHWC reorder of the flat representation (reference:
    switch_order_layer, SwitchOrderLayer.cpp). Internally tensors are NHWC;
    this re-lays the *flat* output so downstream fc sees HWC-major."""
    name = name or auto_name("switch_order")
    c, h, w = _img_in_shape(input)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, c, h, w)
        return Value(x.reshape(x.shape[0], -1))     # HWC-major flat

    return LayerOutput(name, "switch_order", [input], fwd, [],
                       size=c * h * w)


def cross_channel_norm(input, name: Optional[str] = None, param_attr=None):
    """L2-normalize across channels at each spatial position, with a
    learned per-channel scale (reference: cross_channel_norm_layer,
    CrossChannelNormLayer.cpp — the SSD detection normalizer)."""
    name = name or auto_name("cross_channel_norm")
    c, h, w = _img_in_shape(input)
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(initializer="constant",
                                      initial_value=1.0), f"{name}.w")
    w_spec = ParamSpec(attr.name, (c,), attr=attr)

    def fn(params, x, ctx):
        norm = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + 1e-10)
        return x / norm * params[w_spec.name].astype(x.dtype)

    return _img_layer(name, "cross_channel_norm", input, fn, c, h, w,
                      extra_specs=[w_spec])


def scale_sub_region(input, indices, value: float,
                     name: Optional[str] = None):
    """Scale a per-sample CHW sub-region by ``value``; indices rows are
    1-based [c1, c2, h1, h2, w1, w2] (reference: scale_sub_region_layer,
    ScaleSubRegionLayer.cpp)."""
    name = name or auto_name("scale_sub_region")
    c, h, w = _img_in_shape(input)

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, c, h, w)
        idx = parents[1].array.astype(jnp.int32)    # [B, 6]
        ci = jnp.arange(c)[None, None, None, :]
        hi = jnp.arange(h)[None, :, None, None]
        wi = jnp.arange(w)[None, None, :, None]
        def rng(k):
            return idx[:, k][:, None, None, None] - 1
        mask = ((ci >= rng(0)) & (ci <= rng(1)) &
                (hi >= rng(2)) & (hi <= rng(3)) &
                (wi >= rng(4)) & (wi <= rng(5)))
        return Value(jnp.where(mask, x * value, x))

    lo = LayerOutput(name, "scale_sub_region", [input, indices], fwd, [],
                     size=c * h * w)
    lo._out_channels = c
    lo._img_shape = (h, w)
    return lo


def block_expand(input, block_x: int, block_y: int, stride_x: int = 1,
                 stride_y: int = 1, padding_x: int = 0, padding_y: int = 0,
                 num_channels: Optional[int] = None,
                 name: Optional[str] = None):
    """im2col as a sequence: each sliding block becomes one timestep
    (reference: block_expand_layer, BlockExpandLayer.cpp — feeds OCR CTC
    pipelines)."""
    name = name or auto_name("block_expand")
    if num_channels is not None:
        c = num_channels
        h, w = _infer_img_shape(input, c, None)
    else:
        c, h, w = _img_in_shape(input)
    oh = (h + 2 * padding_y - block_y) // stride_y + 1
    ow = (w + 2 * padding_x - block_x) // stride_x + 1

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, c, h, w)
        x = jnp.transpose(x, (0, 3, 1, 2))          # NCHW for patch order
        patches = jax.lax.conv_general_dilated_patches(
            x, (block_y, block_x), (stride_y, stride_x),
            padding=((padding_y, padding_y), (padding_x, padding_x)))
        # [B, C*by*bx, oh, ow] -> [B, oh*ow, C*by*bx]
        B = x.shape[0]
        seq = jnp.transpose(patches.reshape(B, -1, oh * ow), (0, 2, 1))
        lengths = jnp.full((B,), oh * ow, jnp.int32)
        return Value(seq, lengths)

    return LayerOutput(name, "block_expand", [input], fwd, [],
                       size=c * block_x * block_y)


def img_conv3d(input, filter_size, num_filters: int, shape,
               num_channels: Optional[int] = None, stride=1, padding=0,
               act=None, name: Optional[str] = None, param_attr=None,
               bias_attr=None):
    """3-D convolution over DHW volumes; ``shape``=(C, D, H, W) of the input
    (reference: img_conv3d_layer; conv3d_op.cc)."""
    name = name or auto_name("conv3d")
    act_name = act_mod.resolve(act)
    cin, d, h, w = shape
    cin = num_channels or cin
    k = (filter_size,) * 3 if isinstance(filter_size, int) else tuple(filter_size)
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(attr.name, k + (cin, num_filters), attr=attr,
                       fan_in=cin * k[0] * k[1] * k[2])
    bias = _bias_spec(name, num_filters, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])
    od = (d + 2 * p[0] - k[0]) // s[0] + 1
    oh = (h + 2 * p[1] - k[1]) // s[1] + 1
    ow = (w + 2 * p[2] - k[2]) // s[2] + 1

    def fwd(params, parents, ctx):
        x = parents[0].array
        if x.ndim == 2:
            x = x.reshape(x.shape[0], cin, d, h, w)
            x = jnp.transpose(x, (0, 2, 3, 4, 1))   # NDHWC
        out = jax.lax.conv_general_dilated(
            x, params[w_spec.name].astype(x.dtype), window_strides=s,
            padding=[(p[0], p[0]), (p[1], p[1]), (p[2], p[2])],
            dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if bias:
            out = out + params[bias.name].astype(out.dtype)
        # flatten channel-major (C, D, H, W) so chained 3-D layers can
        # re-interpret the flat vector consistently
        out = jnp.transpose(out, (0, 4, 1, 2, 3)).reshape(out.shape[0], -1)
        return _apply_act(Value(out), act_name)

    lo = LayerOutput(name, "conv3d", [input], fwd, specs,
                     size=num_filters * od * oh * ow, activation=act_name)
    lo.shape3d = (num_filters, od, oh, ow)
    return lo


def img_pool3d(input, pool_size, shape, stride=None, padding=0,
               pool_type=None, name: Optional[str] = None):
    """3-D max/avg pooling; ``shape``=(C, D, H, W) (reference:
    img_pool3d_layer; pool3d_op.cc)."""
    name = name or auto_name("pool3d")
    c, d, h, w = shape
    k = (pool_size,) * 3 if isinstance(pool_size, int) else tuple(pool_size)
    s = k if stride is None else ((stride,) * 3 if isinstance(stride, int)
                                  else tuple(stride))
    p = (padding,) * 3 if isinstance(padding, int) else tuple(padding)
    is_avg = pooling_mod.resolve(pool_type) == "avg"
    od = (d + 2 * p[0] - k[0]) // s[0] + 1
    oh = (h + 2 * p[1] - k[1]) // s[1] + 1
    ow = (w + 2 * p[2] - k[2]) // s[2] + 1

    def fwd(params, parents, ctx):
        x = parents[0].array
        if x.ndim == 2:
            x = x.reshape(x.shape[0], c, d, h, w)
            x = jnp.transpose(x, (0, 2, 3, 4, 1))
        pads = ((0, 0),) + tuple((pi, pi) for pi in p) + ((0, 0),)
        if is_avg:
            out = jax.lax.reduce_window(
                x, 0.0, jax.lax.add, (1,) + k + (1,), (1,) + s + (1,), pads)
            out = out / float(k[0] * k[1] * k[2])
        else:
            out = jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1,) + k + (1,), (1,) + s + (1,),
                pads)
        out = jnp.transpose(out, (0, 4, 1, 2, 3)).reshape(out.shape[0], -1)
        return Value(out)

    lo = LayerOutput(name, "pool3d", [input], fwd, [],
                     size=c * od * oh * ow)
    lo.shape3d = (c, od, oh, ow)
    return lo


# ---------------------------------------------------------------------------
# sequence slicing (reference: seq_reshape_layer SequenceReshapeLayer.cpp,
# seq_slice_layer SeqSliceLayer.cpp, sub_seq_layer SubSequenceLayer.cpp,
# kmax_seq_score_layer KmaxSeqScoreLayer.cpp)
# ---------------------------------------------------------------------------

def seq_reshape(input, reshape_size: int, name: Optional[str] = None):
    """Re-tokenize a sequence: total per-sequence features regrouped into
    tokens of ``reshape_size`` (reference: seq_reshape_layer)."""
    name = name or auto_name("seq_reshape")
    enforce.enforce(input.size % reshape_size == 0 or
                    reshape_size % input.size == 0,
                    "seq_reshape sizes must divide")

    def fwd(params, parents, ctx):
        pv = parents[0]
        x = pv.array                               # [B, T, F]
        B, T, F = x.shape
        new_total = T * F // reshape_size
        out = x.reshape(B, new_total, reshape_size)
        lengths = (pv.lengths * F) // reshape_size
        return Value(out, lengths)

    return LayerOutput(name, "seq_reshape", [input], fwd, [],
                       size=reshape_size)


def seq_slice(input, starts=None, ends=None, name: Optional[str] = None):
    """Slice each sequence to [start, end) given per-sample scalar layers
    (reference: seq_slice_layer)."""
    name = name or auto_name("seq_slice")
    parents = [input] + [l for l in (starts, ends) if l is not None]

    def fwd(params, parent_vals, ctx):
        pv = parent_vals[0]
        x, lens = pv.array, pv.lengths
        B, T = x.shape[:2]
        i = 1
        if starts is not None:
            s = parent_vals[i].array.reshape(-1).astype(jnp.int32)
            i += 1
        else:
            s = jnp.zeros((B,), jnp.int32)
        if ends is not None:
            e = parent_vals[i].array.reshape(-1).astype(jnp.int32)
        else:
            e = lens
        e = jnp.minimum(e, lens)
        idx = jnp.arange(T)[None, :] + s[:, None]      # shifted gather
        idx = jnp.minimum(idx, T - 1)
        out = jnp.take_along_axis(
            x, idx[..., None].astype(jnp.int32), axis=1)
        return Value(out, jnp.maximum(e - s, 0))

    return LayerOutput(name, "seq_slice", parents, fwd, [], size=input.size)


def sub_seq(input, offsets, sizes, name: Optional[str] = None):
    """Per-sample subsequence by (offset, size) layers (reference:
    sub_seq_layer, SubSequenceLayer.cpp)."""
    name = name or auto_name("sub_seq")

    def fwd(params, parent_vals, ctx):
        pv = parent_vals[0]
        x, lens = pv.array, pv.lengths
        B, T = x.shape[:2]
        off = parent_vals[1].array.reshape(-1).astype(jnp.int32)
        sz = parent_vals[2].array.reshape(-1).astype(jnp.int32)
        idx = jnp.minimum(jnp.arange(T)[None, :] + off[:, None], T - 1)
        out = jnp.take_along_axis(x, idx[..., None].astype(jnp.int32), axis=1)
        new_len = jnp.clip(sz, 0, jnp.maximum(lens - off, 0))
        return Value(out, new_len)

    return LayerOutput(name, "sub_seq", [input, offsets, sizes], fwd, [],
                       size=input.size)


def sub_nested_seq(input, selection, name: Optional[str] = None):
    """Select sub-sequences from a nested sequence (reference:
    sub_nested_seq_layer, SubNestedSequenceLayer.cpp). ``input`` must be a
    2-level LoD sequence (sub_lengths set); ``selection`` is an integer
    sequence whose per-sample values are the indices of the sub-sequences
    to keep (its own lengths give how many are selected per sample) —
    the sequence-native form of the reference's -1-padded index matrix.
    The output is again a nested sequence in selection order."""
    name = name or auto_name("sub_nested_seq")

    def fwd(params, parent_vals, ctx):
        pv, sel = parent_vals
        if pv.sub_lengths is None:
            raise ValueError(
                f"sub_nested_seq {name}: input has no sub-sequence "
                f"structure (sub_lengths is None)")
        if sel.lengths is None:
            raise ValueError(
                f"sub_nested_seq {name}: selection must be a sequence "
                f"input (its lengths give how many are selected)")
        out, new_len, new_sub = ops_seq.sub_nested_seq(
            pv.array, pv.sub_lengths, sel.array.astype(jnp.int32),
            sel.lengths.astype(jnp.int32))
        return Value(out, new_len, new_sub)

    return LayerOutput(name, "sub_nested_seq", [input, selection], fwd, [],
                       size=input.size)


def kmax_seq_score(input, beam_size: int = 1, name: Optional[str] = None):
    """Indices of the k largest per-token scores in each sequence
    (reference: kmax_seq_score_layer, KmaxSeqScoreLayer.cpp)."""
    name = name or auto_name("kmax_seq_score")

    def fwd(params, parents, ctx):
        pv = parents[0]
        scores = pv.array
        if scores.ndim == 3:
            scores = scores[..., 0]
        idx = ops_seq.kmax_score_indices(scores, pv.lengths, beam_size)
        return Value(idx)

    return LayerOutput(name, "kmax_seq_score", [input], fwd, [],
                       size=beam_size)


def printer(input, name: Optional[str] = None, format: str = "{}"):
    """Debug-print a layer's value at run time (reference: printer_layer,
    PrintLayer.cpp — glog; here jax.debug.print inside the traced fn)."""
    name = name or auto_name("printer")

    def fwd(params, parents, ctx):
        jax.debug.print(name + ": " + format, parents[0].array)
        return parents[0]

    return LayerOutput(name, "printer", [input], fwd, [], size=input.size)


# ---------------------------------------------------------------------------
# mixed layer + sampled-output layers
# ---------------------------------------------------------------------------

def mixed(size: Optional[int] = None, input=None, act=None,
          bias_attr=False, name: Optional[str] = None):
    """Sum of projections/operators (reference: mixed_layer,
    MixedLayer.cpp — the composite of Projection/Operator sub-units)."""
    from paddle_tpu import projection as proj_mod
    name = name or auto_name("mixed")
    projs = _as_list(input)
    for pr in projs:
        enforce.enforce(isinstance(pr, proj_mod.Projection),
                        "mixed() inputs must be projections/operators")
    out_size = size or projs[0].size
    for pr in projs:
        enforce.enforce(pr.size == out_size,
                        f"projection size {pr.size} != mixed size {out_size}")
    act_name = act_mod.resolve(act)
    specs = []
    seen = set()
    for pr in projs:
        for sp in pr.param_specs:
            if sp.name not in seen:
                seen.add(sp.name)
                specs.append(sp)
    bias = _bias_spec(name, out_size, bias_attr)
    if bias:
        specs.append(bias)
    parents = []
    slices = []
    for pr in projs:
        lo = len(parents)
        parents.extend(pr.inputs)
        slices.append((pr, lo, len(parents)))

    def fwd(params, parent_vals, ctx):
        total = None
        for pr, lo, hi in slices:
            out = pr.apply(params, parent_vals[lo:hi], ctx)
            total = out if total is None else total + out
        if bias:
            total = total + params[bias.name].astype(total.dtype)
        p0 = parent_vals[0]
        return _apply_act(Value(total, p0.lengths, p0.sub_lengths), act_name)

    return LayerOutput(name, "mixed", parents, fwd, specs, size=out_size,
                       activation=act_name)


mixed_layer = mixed


def selective_fc(input, select, size: int, act=None,
                 name: Optional[str] = None, param_attr=None,
                 bias_attr=None):
    """FC evaluated only on selected output columns (reference:
    selective_fc_layer, SelectiveFullyConnectedLayer.cpp — computes just the
    rows named by ``select``). ``select``: integer ids [B, K]; output [B, K]
    scores aligned with the ids. The TPU form is a gather of W columns +
    batched dot — the SelectedRows idea applied to outputs."""
    name = name or auto_name("selective_fc")
    act_name = act_mod.resolve(act)
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(a.name, (input.size, size), attr=a, fan_in=input.size)
    bias = _bias_spec(name, size, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        x = parents[0].array                       # [B, D]
        sel = parents[1].array.astype(jnp.int32)   # [B, K]
        w_cols = jnp.take(params[w_spec.name].T, sel, axis=0)  # [B, K, D]
        out = jnp.einsum("bkd,bd->bk", w_cols.astype(x.dtype), x)
        if bias:
            out = out + jnp.take(params[bias.name], sel).astype(out.dtype)
        return _apply_act(Value(out), act_name)

    return LayerOutput(name, "selective_fc", [input, select], fwd, specs,
                       size=size, activation=act_name)


def nce(input, label, num_classes: int, num_neg_samples: int = 10,
        name: Optional[str] = None, param_attr=None, bias_attr=None):
    """Noise-contrastive estimation cost over a big softmax (reference:
    nce_layer, NCELayer.cpp — binary logistic on the true class plus sampled
    noise classes; uniform noise distribution).

    Negatives are drawn per batch from the per-layer RNG key (training);
    without a key a fixed fold of the seed is used. Returns per-example cost.
    """
    name = name or auto_name("nce")
    inputs = _as_list(input)
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    specs = []
    w_specs = []
    for i, inp in enumerate(inputs):
        nm = a.name if len(inputs) == 1 else f"{a.name}{i}"
        sp = ParamSpec(nm, (num_classes, inp.size), attr=type(a)(
            **{**a.__dict__, "name": nm}), fan_in=inp.size)
        w_specs.append(sp)
        specs.append(sp)
    bias = _bias_spec(name, num_classes, bias_attr)
    if bias:
        specs.append(bias)

    def fwd(params, parents, ctx):
        xs = parents[:-1]
        lab = parents[-1].array.reshape(-1).astype(jnp.int32)
        B = lab.shape[0]
        key = ctx.layer_key(name)
        if key is None:
            key = jax.random.PRNGKey(0)
        negs = jax.random.randint(key, (B, num_neg_samples), 0, num_classes)
        ids = jnp.concatenate([lab[:, None], negs], axis=1)  # [B, 1+S]

        def scores(ids_):
            total = None
            for sp, xv in zip(w_specs, xs):
                w_rows = jnp.take(params[sp.name], ids_, axis=0)  # [B,S,D]
                o = jnp.einsum("bsd,bd->bs", w_rows.astype(jnp.float32),
                               xv.array.astype(jnp.float32))
                total = o if total is None else total + o
            if bias:
                total = total + jnp.take(params[bias.name], ids_)
            return total

        s = scores(ids)
        pos_loss = jax.nn.softplus(-s[:, 0])
        neg_loss = jnp.sum(jax.nn.softplus(s[:, 1:]), axis=1)
        return Value(pos_loss + neg_loss)

    return LayerOutput(name, "nce", inputs + [label], fwd, specs, size=1)


def hsigmoid(input, label, num_classes: int, name: Optional[str] = None,
             param_attr=None, bias_attr=None):
    """Hierarchical sigmoid cost: binary logistic along the complete-binary-
    tree path of the label class (reference: hsigmoid,
    HierarchicalSigmoidLayer.cpp — leaves numbered c+num_classes, internal
    nodes are the label's ancestors).

    Σ_c p(c) = 1 by construction; cost is -log p(label).
    """
    name = name or auto_name("hsigmoid")
    inputs = _as_list(input)
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    specs, w_specs = [], []
    for i, inp in enumerate(inputs):
        nm = a.name if len(inputs) == 1 else f"{a.name}{i}"
        sp = ParamSpec(nm, (num_classes - 1, inp.size), attr=type(a)(
            **{**a.__dict__, "name": nm}), fan_in=inp.size)
        w_specs.append(sp)
        specs.append(sp)
    bias = _bias_spec(name, num_classes - 1, bias_attr)
    if bias:
        specs.append(bias)
    depth = max(1, math.ceil(math.log2(num_classes)))

    def fwd(params, parents, ctx):
        xs = parents[:-1]
        lab = parents[-1].array.reshape(-1).astype(jnp.int32)
        leaf = lab + num_classes
        # ancestors leaf>>1 .. 1; child bit at each
        ks = jnp.arange(1, depth + 1)
        anc = leaf[:, None] >> ks[None, :]            # [B, depth]
        bit = (leaf[:, None] >> (ks[None, :] - 1)) & 1
        valid = anc >= 1
        node = jnp.maximum(anc - 1, 0)                # weight row index

        total = None
        for sp, xv in zip(w_specs, xs):
            w_rows = jnp.take(params[sp.name], node, axis=0)   # [B,depth,D]
            o = jnp.einsum("bkd,bd->bk", w_rows.astype(jnp.float32),
                           xv.array.astype(jnp.float32))
            total = o if total is None else total + o
        if bias:
            total = total + jnp.take(params[bias.name], node)
        # p(child=right)=sigmoid(s): step cost = softplus(-s) if bit==1
        # (going right) else softplus(s)
        step_cost = jnp.where(bit == 1, jax.nn.softplus(-total),
                              jax.nn.softplus(total))
        return Value(jnp.sum(jnp.where(valid, step_cost, 0.0), axis=1))

    return LayerOutput(name, "hsigmoid", inputs + [label], fwd, specs,
                       size=1)


# ---------------------------------------------------------------------------
# detection suite (reference: priorbox_layer, multibox_loss_layer,
# detection_output_layer, roi_pool_layer — gserver/layers/PriorBox.cpp,
# MultiBoxLossLayer.cpp, DetectionOutputLayer.cpp, ROIPoolLayer.cpp,
# DetectionUtil.cpp). Ground-truth boxes feed as a padded Value
# [B, G, 5] = (class, x1, y1, x2, y2) with lengths = #boxes per image.
# ---------------------------------------------------------------------------

def priorbox(input, image_size, min_size, max_size=None,
             aspect_ratio=(2.0,), variance=(0.1, 0.1, 0.2, 0.2),
             name: Optional[str] = None):
    """SSD prior boxes for one feature map → [P, 4] plus variances kept as
    a layer attribute (reference: priorbox_layer / PriorBox.cpp)."""
    from paddle_tpu.ops import detection as ops_det
    name = name or auto_name("priorbox")
    c, fh, fw = _img_in_shape(input)
    ih, iw = ((image_size, image_size) if isinstance(image_size, int)
              else tuple(image_size))
    boxes = ops_det.prior_boxes(fh, fw, ih, iw, min_size, max_size,
                                aspect_ratios=tuple(aspect_ratio))
    nprior = boxes.shape[0]

    def fwd(params, parents, ctx):
        return Value(boxes)

    lo = LayerOutput(name, "priorbox", [input], fwd, [], size=nprior * 4)
    lo.num_priors = nprior
    lo.variances = tuple(variance)
    return lo


def multibox_loss(input_loc, input_conf, priorbox, label,
                  num_classes: int, overlap_threshold: float = 0.5,
                  neg_pos_ratio: float = 3.0, background_id: int = 0,
                  name: Optional[str] = None):
    """SSD training loss: matched-prior smooth-L1 localization + softmax
    confidence with hard negative mining (reference: multibox_loss_layer,
    MultiBoxLossLayer.cpp).

    input_loc/input_conf: layer(s) of per-prior predictions, concatenated to
    [B, P*4] and [B, P*num_classes]; priorbox: priorbox layer(s).
    """
    from paddle_tpu.ops import detection as ops_det
    name = name or auto_name("multibox_loss")
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    pbs = _as_list(priorbox)
    variances = pbs[0].variances

    def fwd(params, parents, ctx):
        nl, nc, npb = len(locs), len(confs), len(pbs)
        loc_v = parents[:nl]
        conf_v = parents[nl:nl + nc]
        pb_v = parents[nl + nc:nl + nc + npb]
        lab_v = parents[-1]
        priors = jnp.concatenate([p.array for p in pb_v], axis=0)  # [P,4]
        P = priors.shape[0]
        B = loc_v[0].array.shape[0]
        loc = jnp.concatenate(
            [v.array.reshape(B, -1) for v in loc_v], axis=1).reshape(B, P, 4)
        conf = jnp.concatenate(
            [v.array.reshape(B, -1) for v in conf_v],
            axis=1).reshape(B, P, num_classes)
        gt = lab_v.array                                   # [B, G, 5]
        gt_valid = (jnp.arange(gt.shape[1])[None, :] <
                    lab_v.lengths[:, None])                # [B, G]

        def one(loc_b, conf_b, gt_b, valid_b):
            match, _ = ops_det.match_priors(priors, gt_b[:, 1:5], valid_b,
                                            overlap_threshold)
            pos = match >= 0
            npos = jnp.sum(pos)
            safe_match = jnp.maximum(match, 0)
            gt_box = jnp.take(gt_b[:, 1:5], safe_match, axis=0)
            gt_cls = jnp.take(gt_b[:, 0], safe_match).astype(jnp.int32)
            target = ops_det.encode_boxes(gt_box, priors, variances)
            d = (loc_b - target).astype(jnp.float32)
            ad = jnp.abs(d)
            sl1 = jnp.where(ad < 1.0, 0.5 * ad * ad, ad - 0.5).sum(-1)
            loc_loss = jnp.sum(jnp.where(pos, sl1, 0.0))
            # conf loss per prior, target = matched class or background
            tgt_cls = jnp.where(pos, gt_cls, background_id)
            logp = jax.nn.log_softmax(conf_b.astype(jnp.float32), -1)
            ce = -jnp.take_along_axis(logp, tgt_cls[:, None], axis=1)[:, 0]
            # hard negative mining: top (ratio*npos) background priors by ce
            nneg = jnp.minimum((neg_pos_ratio * npos).astype(jnp.int32),
                               P - npos)
            neg_ce = jnp.where(pos, -jnp.inf, ce)
            order = jnp.argsort(-neg_ce)
            rank = jnp.zeros(P, jnp.int32).at[order].set(jnp.arange(P))
            neg = (~pos) & (rank < nneg)
            conf_loss = jnp.sum(jnp.where(pos | neg, ce, 0.0))
            denom = jnp.maximum(npos.astype(jnp.float32), 1.0)
            return (loc_loss + conf_loss) / denom

        losses = jax.vmap(one)(loc, conf, gt, gt_valid)
        return Value(losses)

    return LayerOutput(name, "multibox_loss",
                       locs + confs + pbs + [label], fwd, [], size=1)


def detection_output(input_loc, input_conf, priorbox, num_classes: int,
                     nms_threshold: float = 0.45, nms_top_k: int = 400,
                     keep_top_k: int = 200,
                     confidence_threshold: float = 0.01,
                     background_id: int = 0, name: Optional[str] = None):
    """Decode + per-class NMS → [B, keep_top_k, 6] rows of
    (label, score, x1, y1, x2, y2), label −1 padding (reference:
    detection_output_layer / DetectionOutputLayer.cpp)."""
    from paddle_tpu.ops import detection as ops_det
    name = name or auto_name("detection_output")
    locs = _as_list(input_loc)
    confs = _as_list(input_conf)
    pbs = _as_list(priorbox)
    variances = pbs[0].variances

    def fwd(params, parents, ctx):
        nl, nc = len(locs), len(confs)
        loc_v = parents[:nl]
        conf_v = parents[nl:nl + nc]
        pb_v = parents[nl + nc:]
        priors = jnp.concatenate([p.array for p in pb_v], axis=0)
        P = priors.shape[0]
        B = loc_v[0].array.shape[0]
        loc = jnp.concatenate(
            [v.array.reshape(B, -1) for v in loc_v], axis=1).reshape(B, P, 4)
        conf = jnp.concatenate(
            [v.array.reshape(B, -1) for v in conf_v],
            axis=1).reshape(B, P, num_classes)
        probs = jax.nn.softmax(conf.astype(jnp.float32), -1)

        def one(loc_b, probs_b):
            boxes = ops_det.decode_boxes(loc_b, priors, variances)
            per_k = max(1, min(nms_top_k, P))
            rows = []
            for cls in range(num_classes):
                if cls == background_id:
                    continue
                sel, sc = ops_det.nms(boxes, probs_b[:, cls], per_k,
                                      nms_threshold, confidence_threshold)
                bx = jnp.take(boxes, jnp.maximum(sel, 0), axis=0)
                valid = sel >= 0
                row = jnp.concatenate([
                    jnp.where(valid, cls, -1)[:, None].astype(jnp.float32),
                    sc[:, None], bx], axis=1)              # [per_k, 6]
                rows.append(row)
            allr = jnp.concatenate(rows, axis=0)           # [(C-1)*per_k, 6]
            if allr.shape[0] < keep_top_k:                 # honor size contract
                pad = jnp.full((keep_top_k - allr.shape[0], 6), -1.0)
                allr = jnp.concatenate([allr, pad.at[:, 1:].set(0.0)], axis=0)
            order = jnp.argsort(-jnp.where(allr[:, 0] >= 0, allr[:, 1],
                                           -jnp.inf))
            return jnp.take(allr, order[:keep_top_k], axis=0)

        return Value(jax.vmap(one)(loc, probs))

    return LayerOutput(name, "detection_output", locs + confs + pbs, fwd,
                       [], size=keep_top_k * 6)


def roi_pool(input, rois, pooled_width: int, pooled_height: int,
             spatial_scale: float = 1.0, num_channels: Optional[int] = None,
             name: Optional[str] = None):
    """ROI max pooling (reference: roi_pool_layer / ROIPoolLayer.cpp).
    ``rois``: Value [B, R, 4] with lengths = #rois; output
    [B, R, pooled_h, pooled_w, C] (invalid rois are zero)."""
    from paddle_tpu.ops import detection as ops_det
    name = name or auto_name("roi_pool")
    c, h, w = _img_in_shape(input)
    c = num_channels or c

    def fwd(params, parents, ctx):
        xv, rv = parents
        x = _to_nhwc(xv.array, c, h, w)
        rois_b = rv.array                                  # [B, R, 4]
        out = jax.vmap(lambda f, r: ops_det.roi_pool(
            f, r, pooled_height, pooled_width, spatial_scale))(x, rois_b)
        if rv.lengths is not None:
            valid = (jnp.arange(rois_b.shape[1])[None, :] <
                     rv.lengths[:, None])
            out = jnp.where(valid[..., None, None, None], out, 0.0)
        return Value(out, rv.lengths)

    return LayerOutput(name, "roi_pool", [input, rois], fwd, [],
                       size=pooled_width * pooled_height * c)


# ---------------------------------------------------------------------------
# parity tail: lookahead/row conv, data norm, featmap expand, MDLSTM,
# remaining cost layers (reference: RowConvLayer.cpp, DataNormLayer.cpp,
# FeatureMapExpandLayer.cpp, MDLstmLayer.cpp, CostLayer.cpp)
# ---------------------------------------------------------------------------

def row_conv(input, context_len: int, act=None, name: Optional[str] = None,
             param_attr=None):
    """Lookahead (row) convolution over a sequence — DeepSpeech2's future
    context without full bidirectionality (reference: row_conv_layer,
    gserver/layers/RowConvLayer.cpp, paddle/function/RowConvOp.cpp):
    out[t] = sum_k x[t+k] * w[k], per-feature weights [context_len, d]."""
    name = name or auto_name("row_conv")
    act_name = act_mod.resolve(act)
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(attr.name, (context_len, input.size), attr=attr,
                       fan_in=context_len)

    def fwd(params, parents, ctx):
        pv = parents[0]
        enforce.enforce(pv.is_sequence, "row_conv needs sequence input")
        out = ops_seq.row_conv(pv.array, pv.lengths, params[w_spec.name])
        return _apply_act(Value(out, pv.lengths, pv.sub_lengths), act_name)

    return LayerOutput(name, "row_conv", [input], fwd, [w_spec],
                       size=input.size, activation=act_name)


row_conv_layer = row_conv


def data_norm(input, strategy: str = "z-score", name: Optional[str] = None):
    """Normalise dense input by dataset statistics (reference:
    data_norm_layer, gserver/layers/DataNormLayer.cpp — strategies z-score
    (x-mean)/std, min-max (x-min)/(max-min), decimal-scaling x/10^j). The
    statistics live in non-learned parameters '<name>.mean/.std/.min/.max/
    .decimal' — set them from your data via parameters.set()."""
    name = name or auto_name("data_norm")
    enforce.enforce(strategy in ("z-score", "min-max", "decimal-scaling"),
                    f"unknown data_norm strategy {strategy!r}")
    d = input.size

    def const(suffix, value):
        return ParamSpec(
            f"{name}.{suffix}", (d,),
            attr=ParamAttr(initializer="constant", initial_value=value,
                           is_static=True))

    mean_s, std_s = const("mean", 0.0), const("std", 1.0)
    min_s, max_s = const("min", 0.0), const("max", 1.0)
    dec_s = const("decimal", 1.0)

    def fn(params, parents, ctx):
        x = parents[0].array
        xf = x.astype(jnp.float32)
        if strategy == "z-score":
            out = (xf - params[mean_s.name]) / jnp.maximum(
                params[std_s.name], 1e-8)
        elif strategy == "min-max":
            out = (xf - params[min_s.name]) / jnp.maximum(
                params[max_s.name] - params[min_s.name], 1e-8)
        else:
            out = xf / jnp.maximum(params[dec_s.name], 1e-8)
        return out.astype(x.dtype)

    return _simple_layer(name, "data_norm", [input], fn, d,
                         specs=[mean_s, std_s, min_s, max_s, dec_s])


def featmap_expand(input, num_filters: int, as_row_vector: bool = True,
                   name: Optional[str] = None):
    """Replicate each sample's feature row into num_filters channels
    (reference: featmap_expand, FeatureMapExpandLayer.cpp:22-37 —
    y.row[i] = x tiled num_filters times; as_col_vec repeats elementwise)."""
    name = name or auto_name("featmap_expand")

    def fn(params, parents, ctx):
        x = parents[0].array
        flat = x.reshape(x.shape[0], -1)
        if as_row_vector:
            return jnp.tile(flat, (1, num_filters))
        return jnp.repeat(flat, num_filters, axis=-1)

    lo = _simple_layer(name, "featmap_expand", [input], fn,
                       input.size * num_filters)
    lo._out_channels = num_filters
    return lo


def mdlstmemory(input, size: int, shape=None, name: Optional[str] = None,
                reverse_x: bool = False, reverse_y: bool = False,
                param_attr=None, bias_attr=True):
    """Multi-dimensional (2-D) LSTM over a feature map (reference:
    mdlstmemory, gserver/layers/MDLstmLayer.cpp — Graves MDLSTM; five gates
    with one forget gate per spatial dimension). ``shape``=(C, H, W) of the
    input when it cannot be inferred; output keeps the (size, H, W) map
    flattened channel-major like the conv layers."""
    name = name or auto_name("mdlstm")
    if shape is not None:
        cin, ih, iw = shape
    else:
        cin, ih, iw = _img_in_shape(input)
    a = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                    else ParamAttr(), f"{name}.w")
    w_ih = ParamSpec(f"{name}.w_ih", (cin, 5 * size), attr=a, fan_in=cin)
    w_hx = ParamSpec(f"{name}.w_hx", (size, 5 * size),
                     attr=_param_attr(ParamAttr(), f"{name}.w_hx"),
                     fan_in=size)
    w_hy = ParamSpec(f"{name}.w_hy", (size, 5 * size),
                     attr=_param_attr(ParamAttr(), f"{name}.w_hy"),
                     fan_in=size)
    bias = _bias_spec(name, 5 * size, bias_attr)
    specs = [w_ih, w_hx, w_hy] + ([bias] if bias else [])

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        out = ops_rnn.mdlstm(
            x, params[w_ih.name], params[w_hx.name], params[w_hy.name],
            params[bias.name] if bias else None,
            reverse_x=reverse_x, reverse_y=reverse_y)
        return Value(out)

    lo = LayerOutput(name, "mdlstm", [input], fwd, specs,
                     size=size * ih * iw)
    lo._out_channels = size
    lo._img_shape = (ih, iw)
    return lo


def img_conv3d_transpose(input, filter_size, num_filters: int, shape,
                         num_channels: Optional[int] = None, stride=1,
                         act=None, name: Optional[str] = None,
                         param_attr=None, bias_attr=None):
    """3-D transposed convolution over DHW volumes; ``shape``=(C, D, H, W)
    of the input (reference: deconv3d, gserver/layers/Conv3DLayer.cpp
    DeConv3DLayer; conv3d_transpose via conv_transpose_op.cc). SAME
    padding: output spatial dims = input dims * stride."""
    name = name or auto_name("deconv3d")
    act_name = act_mod.resolve(act)
    cin, d, h, w = shape
    cin = num_channels or cin
    k = (filter_size,) * 3 if isinstance(filter_size, int) \
        else tuple(filter_size)
    s = (stride,) * 3 if isinstance(stride, int) else tuple(stride)
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(), f"{name}.w")
    w_spec = ParamSpec(attr.name, k + (cin, num_filters), attr=attr,
                       fan_in=cin * k[0] * k[1] * k[2])
    bias = _bias_spec(name, num_filters, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])
    od, oh, ow = d * s[0], h * s[1], w * s[2]

    def fwd(params, parents, ctx):
        x = parents[0].array
        if x.ndim == 2:
            x = x.reshape(x.shape[0], cin, d, h, w)
            x = jnp.transpose(x, (0, 2, 3, 4, 1))     # NDHWC
        out = jax.lax.conv_transpose(
            x, params[w_spec.name].astype(x.dtype), strides=s,
            padding="SAME", dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))
        if bias:
            out = out + params[bias.name].astype(out.dtype)
        out = jnp.transpose(out, (0, 4, 1, 2, 3)).reshape(out.shape[0], -1)
        return _apply_act(Value(out), act_name)

    lo = LayerOutput(name, "deconv3d", [input], fwd, specs,
                     size=num_filters * od * oh * ow, activation=act_name)
    lo.shape3d = (num_filters, od, oh, ow)
    return lo


def space_to_depth_conv(input, filter_size: int, num_filters: int,
                        num_channels: Optional[int] = None, act=None,
                        name: Optional[str] = None, param_attr=None,
                        bias_attr=False, block: int = 2, img_size=None):
    """Stride-``block`` conv computed as a stride-1 conv over
    space-to-depth input — numerically identical to
    img_conv(stride=block, padding=k//2) but with ``block²``× the input
    lanes and no strided window (the MLPerf ResNet-stem trick; the C=3
    stem wastes 125/128 lanes otherwise). Weights are stored in the
    canonical [k, k, Cin, Cout] layout (same msra init as img_conv) so
    checkpoints interchange with the plain conv; the transform runs per
    step (negligible: the kernel is KB-sized, derivation + companion
    padding in ops/conv.space_to_depth_conv_transform)."""
    name = name or auto_name("s2d_conv")
    act_name = act_mod.resolve(act)
    cin = num_channels or getattr(input, "_out_channels", None)
    enforce.enforce(cin is not None,
                    f"s2d_conv {name}: num_channels required")
    ih, iw = _infer_img_shape(input, cin, img_size)
    enforce.enforce(ih is not None and ih % block == 0 and
                    iw % block == 0,
                    f"s2d_conv {name}: image size {ih}x{iw} must be known "
                    f"and divisible by block={block} (pass img_size=)")
    k = filter_size
    attr = _param_attr(param_attr if isinstance(param_attr, ParamAttr)
                       else ParamAttr(initializer="msra"), f"{name}.w")
    w_spec = ParamSpec(attr.name, (k, k, cin, num_filters), attr=attr,
                       fan_in=cin * k * k)
    bias = _bias_spec(name, num_filters, bias_attr)
    specs = [w_spec] + ([bias] if bias else [])
    oh, ow = ih // block, iw // block

    def fwd(params, parents, ctx):
        x = _to_nhwc(parents[0].array, cin, ih, iw)
        xs = ops_conv.space_to_depth(x, block)
        ws, pads = ops_conv.space_to_depth_conv_transform(
            params[w_spec.name], block)
        out = ops_conv.conv2d(xs, ws, stride=1, padding=pads)
        if bias:
            out = out + params[bias.name].astype(out.dtype)
        return _apply_act(Value(out), act_name)

    lo = LayerOutput(name, "s2d_conv", [input], fwd, specs,
                     size=oh * ow * num_filters, activation=act_name)
    lo._out_channels = num_filters
    lo._img_shape = (oh, ow)
    return lo


def huber_regression_cost(input, label, delta: float = 1.0,
                          name: Optional[str] = None):
    """(reference: huber_regression_cost, HuberRegressionLoss)"""
    name = name or auto_name("huber_regression")

    def per_example(params, parents, ctx):
        return ops_loss.huber_regression(parents[0].array,
                                         parents[1].array, delta)

    return _cost_layer(name, "huber_regression", [input, label], per_example)


def smooth_l1_cost(input, label, name: Optional[str] = None):
    """(reference: smooth_l1_cost, SmoothL1CostLayer; smooth_l1_loss_op.cc)"""
    name = name or auto_name("smooth_l1")

    def per_example(params, parents, ctx):
        return ops_loss.smooth_l1(parents[0].array, parents[1].array)

    return _cost_layer(name, "smooth_l1", [input, label], per_example)


def soft_binary_class_cross_entropy(input, label, name: Optional[str] = None):
    """Per-dim binary CE with soft (probability) labels (reference:
    soft_binary_class_cross_entropy, SoftBinaryClassCrossEntropy)."""
    name = name or auto_name("soft_binary_ce")

    def per_example(params, parents, ctx):
        return ops_loss.multi_binary_cross_entropy(parents[0].array,
                                                   parents[1].array)

    return _cost_layer(name, "soft_binary_ce", [input, label], per_example)


def cross_entropy_with_selfnorm(input, label, alpha: float = 0.1,
                                name: Optional[str] = None):
    """CE plus alpha*log(Z)^2 self-normalisation (reference:
    cross_entropy_with_selfnorm, CostLayer.cpp:105 — trains the softmax
    partition function toward 1 so inference can skip normalisation).
    Needs the input layer's logits (softmax activation keeps them)."""
    name = name or auto_name("ce_selfnorm")

    def per_example(params, parents, ctx):
        pv, lv = parents
        logits = pv.pre_act if pv.pre_act is not None else pv.array
        return ops_loss.cross_entropy_with_selfnorm(
            logits, lv.array.reshape(-1), alpha)

    return _cost_layer(name, "ce_selfnorm", [input, label], per_example)


def sum_cost_layer(input, name: Optional[str] = None):
    """Cost = sum of the input row (reference: sum_cost, SumCostLayer)."""
    name = name or auto_name("sum_cost")

    def per_example(params, parents, ctx):
        return jnp.sum(parents[0].array.astype(jnp.float32), axis=-1)

    return _cost_layer(name, "sum_cost", [input], per_example)


def lambda_cost(input, score, ndcg_num: int = 5,
                name: Optional[str] = None):
    """LambdaRank NDCG cost over each query sequence (reference:
    lambda_cost, gserver CostLayer.h:252 LambdaCost). ``input`` is the
    model score sequence, ``score`` the relevance sequence."""
    name = name or auto_name("lambda_cost")

    def per_example(params, parents, ctx):
        pv, rv = parents
        enforce.enforce(pv.is_sequence, "lambda_cost needs sequence input")
        s = pv.array[..., 0] if pv.array.ndim == 3 else pv.array
        r = rv.array[..., 0] if rv.array.ndim == 3 else rv.array
        return ops_loss.lambda_rank(s, r, pv.lengths, ndcg_num)

    return _cost_layer(name, "lambda_cost", [input, score], per_example)


# install call recording over this module's public API so built graphs are
# serializable (Topology.to_dict/from_dict — the program save format)
def _install_recording():
    import sys
    from paddle_tpu import record
    record.install(sys.modules[__name__])


_install_recording()
