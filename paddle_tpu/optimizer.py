"""Optimizers as pure pytree update transforms.

Replaces three reference implementations at once:
- paddle/parameter/FirstOrderOptimizer.h (Sgd/Momentum/Adagrad/AdaDelta/
  RMSProp/Adam/Adamax + sparse variants) applied per-parameter on the trainer
- paddle/optimizer/ (the standalone C library the Go pserver drives via cgo)
- paddle/operators/{sgd,momentum,adam,...}_op.cc (the new-stack update ops)

plus LearningRateScheduler.cpp (poly/exp/discexp/linear schedules),
Regularizer.cpp (L1/L2 decay) and error clipping. One implementation serves
local and distributed training because distributed updates are just the same
pure function applied to psum-reduced gradients — there is no separate
"remote" optimizer path on TPU.

The v2 surface is kept: ``paddle.optimizer.Momentum(momentum=0.9,
learning_rate=0.1, regularization=L2Regularization(1e-4))``
(reference: python/paddle/v2/optimizer.py).
"""

import dataclasses
from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from paddle_tpu.core.param import ParamSpec


# ---------------------------------------------------------------------------
# learning-rate schedules (reference: parameter/LearningRateScheduler.cpp)
# ---------------------------------------------------------------------------

def constant_schedule(base_lr):
    return lambda step: jnp.asarray(base_lr, jnp.float32)


def poly_schedule(base_lr, a, b):
    """lr = base * (1 + a*step)^(-b)"""
    return lambda step: base_lr * jnp.power(1.0 + a * step, -b)


def exp_schedule(base_lr, a, b):
    """lr = base * a^(step/b)"""
    return lambda step: base_lr * jnp.power(a, step / b)


def discexp_schedule(base_lr, a, b):
    """lr = base * a^floor(step/b) (reference: discrete exponential)"""
    return lambda step: base_lr * jnp.power(a, jnp.floor(step / b))


def linear_schedule(base_lr, a, b):
    """lr = max(base - a*step, b)"""
    return lambda step: jnp.maximum(base_lr - a * step, b)


def warmup_cosine_schedule(base_lr, warmup_steps, total_steps, min_lr=0.0):
    """TPU-native addition for large-batch training."""
    def fn(step):
        step = jnp.asarray(step, jnp.float32)
        warm = base_lr * step / jnp.maximum(warmup_steps, 1)
        prog = jnp.clip((step - warmup_steps) /
                        jnp.maximum(total_steps - warmup_steps, 1), 0.0, 1.0)
        cos = min_lr + 0.5 * (base_lr - min_lr) * (1 + jnp.cos(jnp.pi * prog))
        return jnp.where(step < warmup_steps, warm, cos)
    return fn


def make_schedule(learning_rate, learning_rate_schedule=None,
                  learning_rate_args="", **kw):
    """Parse the reference's string-typed schedule config
    (TrainerConfig.proto learning_rate_schedule)."""
    if callable(learning_rate_schedule):
        return learning_rate_schedule
    name = learning_rate_schedule or "constant"
    args = [float(x) for x in str(learning_rate_args).split(",") if x != ""]
    if name == "constant":
        return constant_schedule(learning_rate)
    if name == "poly":
        return poly_schedule(learning_rate, *args)
    if name == "exp":
        return exp_schedule(learning_rate, *args)
    if name == "discexp":
        return discexp_schedule(learning_rate, *args)
    if name == "linear":
        return linear_schedule(learning_rate, *args)
    raise ValueError(f"unknown lr schedule {name!r}")


# ---------------------------------------------------------------------------
# regularization (reference: parameter/Regularizer.cpp)
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class L2Regularization:
    rate: float


@dataclasses.dataclass
class L1Regularization:
    rate: float


# ---------------------------------------------------------------------------
# optimizer base
# ---------------------------------------------------------------------------

class Optimizer:
    """Stateful-spec, pure-update optimizer.

    init_state(params) -> state pytree;
    update(step, grads, params, state) -> (new_params, new_state).
    Both are pure and jit/shard-safe.
    """

    def __init__(self, learning_rate=0.01, regularization=None,
                 gradient_clipping_threshold=None,
                 learning_rate_schedule=None, learning_rate_args="", **kw):
        self.schedule = make_schedule(learning_rate, learning_rate_schedule,
                                      learning_rate_args)
        self.regularization = regularization
        self.clip_threshold = gradient_clipping_threshold
        self.specs: Dict[str, ParamSpec] = {}

    def bind(self, specs):
        """Attach per-parameter attrs (lr scale, per-param decay, static)."""
        self.specs = {s.name: s for s in specs}
        return self

    # -- per-array rules, overridden by subclasses -------------------------
    def _init_one(self, p):
        return ()

    def _update_one(self, g, p, s, lr):
        raise NotImplementedError

    # -- pytree plumbing ---------------------------------------------------
    def init_state(self, params: Dict) -> Dict:
        return {k: self._init_one(v) for k, v in params.items()}

    def _decay(self, name, g, p):
        """Apply global + per-param regularization as gradient decay
        (reference: OptimizerWithRegularizer / Regularizer.cpp)."""
        spec = self.specs.get(name)
        l1 = getattr(spec.attr, "l1_rate", None) if spec else None
        l2 = getattr(spec.attr, "l2_rate", None) if spec else None
        if l2 is None and isinstance(self.regularization, L2Regularization):
            l2 = self.regularization.rate
        if l1 is None and isinstance(self.regularization, L1Regularization):
            l1 = self.regularization.rate
        gf = g.astype(jnp.float32)
        if l2:
            gf = gf + l2 * p.astype(jnp.float32)
        if l1:
            gf = gf + l1 * jnp.sign(p.astype(jnp.float32))
        return gf

    def _clip(self, grads: Dict) -> Dict:
        """Global-norm clipping (reference: error_clipping / the v2
        gradient_clipping_threshold optimizer arg)."""
        if not self.clip_threshold:
            return grads
        gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                             for g in grads.values()))
        scale = jnp.minimum(1.0, self.clip_threshold / (gnorm + 1e-12))
        return {k: g * scale.astype(g.dtype) for k, g in grads.items()}

    # -- arbitrary-pytree models (functional models: transformer, GAN…) ---
    @staticmethod
    def _flatten_tree(tree):
        from jax.tree_util import keystr, tree_flatten_with_path
        flat, treedef = tree_flatten_with_path(tree)
        names = [keystr(path) for path, _ in flat]
        return dict(zip(names, (v for _, v in flat))), names, treedef

    def tree_init_state(self, params):
        """init_state for ANY parameter pytree (not just the layer DSL's
        flat name→array dict). Leaves are keyed by their jax keystr tree
        path (e.g. ``"['blocks']['qkv']"``) — per-parameter attrs bound
        via ``bind()`` apply only when spec names use that same path
        format; ``tree_update`` warns if bound specs match no leaf."""
        flat, _, _ = self._flatten_tree(params)
        return self.init_state(flat)

    def tree_update(self, step, grads, params, state):
        """update() for ANY parameter pytree; returns (new_params with
        the input tree structure, new_state)."""
        from jax.tree_util import tree_unflatten
        pd, names, treedef = self._flatten_tree(params)
        unmatched = set(self.specs) - set(names)
        if unmatched and not getattr(self, "_warned_spec_mismatch", False):
            self._warned_spec_mismatch = True
            from paddle_tpu.utils.logger import get_logger
            get_logger().warning(
                "optimizer: bound parameter specs %s match no pytree leaf "
                "path (leaves look like %s) — their per-parameter rules "
                "are NOT being applied", sorted(unmatched)[:5], names[:3])
        gd, _, _ = self._flatten_tree(grads)
        new_p, new_s = self.update(step, gd, pd, state)
        return tree_unflatten(treedef, [new_p[n] for n in names]), new_s

    def update(self, step, grads: Dict, params: Dict, state: Dict):
        lr_t = self.schedule(step)
        grads = self._clip(grads)
        new_p, new_s = {}, {}
        for name, p in params.items():
            spec = self.specs.get(name)
            if spec is not None and spec.attr.is_static:
                new_p[name], new_s[name] = p, state[name]
                continue
            g = grads[name]
            gf = self._decay(name, g, p)
            lr = lr_t * (spec.attr.learning_rate if spec else 1.0)
            np_, ns_ = self._update_one(gf, p.astype(jnp.float32),
                                        state[name], lr)
            new_p[name] = np_.astype(p.dtype)
            new_s[name] = ns_
        return new_p, new_s


class SGD(Optimizer):
    """Plain SGD (reference: SgdOptimizer, FirstOrderOptimizer.h:24)."""

    def _update_one(self, g, p, s, lr):
        return p - lr * g, s


class Momentum(Optimizer):
    """Heavy-ball momentum; use_nesterov for NAG (reference:
    MomentumOptimizer; operators/momentum_op.cc)."""

    def __init__(self, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(**kw)
        self.mu = momentum
        self.nesterov = use_nesterov

    def _init_one(self, p):
        return jnp.zeros_like(p, jnp.float32)

    def _update_one(self, g, p, v, lr):
        nv = self.mu * v + g
        if self.nesterov:
            return p - lr * (g + self.mu * nv), nv
        return p - lr * nv, nv


class AdaGrad(Optimizer):
    """(reference: AdagradParameterOptimizer, FirstOrderOptimizer.h:111)"""

    def __init__(self, epsilon=1e-6, **kw):
        super().__init__(**kw)
        self.eps = epsilon

    def _init_one(self, p):
        return jnp.zeros_like(p, jnp.float32)

    def _update_one(self, g, p, acc, lr):
        nacc = acc + g * g
        return p - lr * g / (jnp.sqrt(nacc) + self.eps), nacc


class AdaDelta(Optimizer):
    """(reference: AdaDeltaParameterOptimizer; rho/epsilon semantics)"""

    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.eps = rho, epsilon

    def _init_one(self, p):
        return (jnp.zeros_like(p, jnp.float32), jnp.zeros_like(p, jnp.float32))

    def _update_one(self, g, p, s, lr):
        acc_g, acc_dx = s
        acc_g = self.rho * acc_g + (1 - self.rho) * g * g
        dx = jnp.sqrt((acc_dx + self.eps) / (acc_g + self.eps)) * g
        acc_dx = self.rho * acc_dx + (1 - self.rho) * dx * dx
        return p - lr * dx, (acc_g, acc_dx)


class RMSProp(Optimizer):
    """(reference: RMSPropParameterOptimizer, FirstOrderOptimizer.h:255)"""

    def __init__(self, rho=0.95, epsilon=1e-6, momentum=0.0, **kw):
        super().__init__(**kw)
        self.rho, self.eps, self.mu = rho, epsilon, momentum

    def _init_one(self, p):
        return (jnp.zeros_like(p, jnp.float32), jnp.zeros_like(p, jnp.float32))

    def _update_one(self, g, p, s, lr):
        acc, mom = s
        acc = self.rho * acc + (1 - self.rho) * g * g
        step = lr * g / jnp.sqrt(acc + self.eps)
        mom = self.mu * mom + step
        return p - mom, (acc, mom)


class Adam(Optimizer):
    """(reference: AdamParameterOptimizer, FirstOrderOptimizer.h:290;
    operators/adam_op.cc — with bias correction)"""

    def __init__(self, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(**kw)
        self.b1, self.b2, self.eps = beta1, beta2, epsilon

    def _init_one(self, p):
        return (jnp.zeros_like(p, jnp.float32), jnp.zeros_like(p, jnp.float32))

    def update(self, step, grads, params, state):
        self._t = jnp.asarray(step, jnp.float32) + 1.0
        return super().update(step, grads, params, state)

    def _update_one(self, g, p, s, lr):
        m, v = s
        m = self.b1 * m + (1 - self.b1) * g
        v = self.b2 * v + (1 - self.b2) * g * g
        mhat = m / (1 - jnp.power(self.b1, self._t))
        vhat = v / (1 - jnp.power(self.b2, self._t))
        return p - lr * mhat / (jnp.sqrt(vhat) + self.eps), (m, v)


class AdamW(Adam):
    """Adam with DECOUPLED weight decay (the modern transformer default):
    decay is applied directly to the parameter, not folded into the
    gradient like L2Regularization — the two differ under adaptive
    per-coordinate scaling. No reference counterpart (2017 predates it);
    included because the TPU build's functional models expect it.

    ``decay_mask`` selects which parameters decay:
    - ``"all"`` (default): every leaf, unconditionally — note this decays
      LayerNorm gains/biases too, unlike the common transformer recipe;
    - ``"no_1d"``: skip leaves with ndim <= 1 (norm gains, biases) — the
      conventional recipe;
    - a callable ``(name, param) -> bool``: True means decay. ``name`` is
      the flat dict key or the jax keystr tree path under ``tree_update``.
    """

    def __init__(self, weight_decay=0.01, decay_mask="all", **kw):
        if kw.get("regularization") is not None:
            raise ValueError(
                "AdamW applies decoupled weight_decay; combining it with "
                "regularization= would decay parameters twice. Use plain "
                "Adam for gradient-coupled L1/L2.")
        super().__init__(**kw)
        self.weight_decay = weight_decay
        if not (decay_mask in ("all", "no_1d") or callable(decay_mask)):
            raise ValueError(f"decay_mask must be 'all', 'no_1d' or a "
                             f"callable, got {decay_mask!r}")
        self.decay_mask = decay_mask

    def _decays(self, name, p):
        if self.decay_mask == "all":
            return True
        if self.decay_mask == "no_1d":
            return p.ndim > 1
        return bool(self.decay_mask(name, p))

    def update(self, step, grads, params, state):
        new_p, new_s = super().update(step, grads, params, state)
        lr_t = self.schedule(step)
        for name, p in params.items():
            spec = self.specs.get(name)
            if spec is not None and spec.attr.is_static:
                continue
            if not self._decays(name, p):
                continue
            lr = lr_t * (spec.attr.learning_rate if spec else 1.0)
            new_p[name] = (new_p[name].astype(jnp.float32)
                           - lr * self.weight_decay * p.astype(jnp.float32)
                           ).astype(p.dtype)
        return new_p, new_s


class AdaMax(Optimizer):
    """(reference: AdamaxParameterOptimizer; operators/adamax_op.cc)"""

    def __init__(self, beta1=0.9, beta2=0.999, **kw):
        super().__init__(**kw)
        self.b1, self.b2 = beta1, beta2

    def _init_one(self, p):
        return (jnp.zeros_like(p, jnp.float32), jnp.zeros_like(p, jnp.float32))

    def update(self, step, grads, params, state):
        self._t = jnp.asarray(step, jnp.float32) + 1.0
        return super().update(step, grads, params, state)

    def _update_one(self, g, p, s, lr):
        m, u = s
        m = self.b1 * m + (1 - self.b1) * g
        u = jnp.maximum(self.b2 * u, jnp.abs(g))
        return p - lr / (1 - jnp.power(self.b1, self._t)) * m / (u + 1e-12), (m, u)


class ModelAverage:
    """Bounded-window parameter averaging (reference: AverageOptimizer,
    parameter/AverageOptimizer.cpp — window = min(steps * average_window,
    max_average_window)). Implemented as a running mean that transitions to
    an EMA with decay 1-1/window once the window fills — cumulative average
    early, bounded-memory thereafter. Functional: accumulate alongside
    training, swap in averaged() for eval."""

    def __init__(self, average_window=0.0, max_average_window=10000):
        self.window = float(max_average_window or 10000)

    def init_state(self, params):
        return {"avg": jax.tree.map(lambda p: p.astype(jnp.float32), params),
                "count": jnp.zeros((), jnp.float32)}

    def accumulate(self, params, state):
        c = state["count"] + 1.0
        decay = jnp.minimum((c - 1.0) / c, 1.0 - 1.0 / self.window)
        return {"avg": jax.tree.map(
            lambda a, p: decay * a + (1.0 - decay) * p.astype(jnp.float32),
            state["avg"], params),
            "count": c}

    def averaged(self, params, state):
        return jax.tree.map(lambda a, p: a.astype(p.dtype),
                            state["avg"], params)


class DecayedAdagrad(Optimizer):
    """Adagrad with a decayed accumulator (reference:
    DecayedAdagradParameterOptimizer, parameter/FirstOrderOptimizer.h;
    operators/decayed_adagrad_op.cc)."""

    def __init__(self, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(**kw)
        self.rho, self.eps = rho, epsilon

    def _init_one(self, p):
        return jnp.zeros_like(p, jnp.float32)

    def _update_one(self, g, p, acc, lr):
        nacc = self.rho * acc + (1 - self.rho) * g * g
        return p - lr * g / (jnp.sqrt(nacc) + self.eps), nacc


class ProximalGD(Optimizer):
    """Proximal gradient descent with L1/L2 proximal steps (reference:
    operators/proximal_gd_op.cc): prox = sign(w')*max(|w'|-lr*l1, 0) /
    (1+lr*l2) after the plain step w' = w - lr*g."""

    def __init__(self, l1=0.0, l2=0.0, **kw):
        super().__init__(**kw)
        self.l1, self.l2 = l1, l2

    def _update_one(self, g, p, s, lr):
        w = p - lr * g
        if self.l1:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - lr * self.l1, 0.0)
        if self.l2:
            w = w / (1.0 + lr * self.l2)
        return w, s


class ProximalAdagrad(Optimizer):
    """Adagrad step with the same proximal projection (reference:
    operators/proximal_adagrad_op.cc)."""

    def __init__(self, l1=0.0, l2=0.0, epsilon=1e-6, **kw):
        super().__init__(**kw)
        self.l1, self.l2, self.eps = l1, l2, epsilon

    def _init_one(self, p):
        return jnp.zeros_like(p, jnp.float32)

    def _update_one(self, g, p, acc, lr):
        nacc = acc + g * g
        alr = lr / (jnp.sqrt(nacc) + self.eps)
        w = p - alr * g
        if self.l1:
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - alr * self.l1, 0.0)
        if self.l2:
            w = w / (1.0 + alr * self.l2)
        return w, nacc


class StaticPruning:
    """Magnitude pruning mask applied to a trained/initial parameter set and
    every subsequent update (reference: StaticPruningHook,
    parameter/ParameterUpdaterHook.cpp:39 — zeroes the smallest
    ``sparsity_ratio`` fraction of each hooked parameter by |w| and keeps
    them zero through training).

    Usage: masks = StaticPruning(ratio).make_masks(params, names);
    wrap the optimizer with .apply(optimizer) so updates re-mask."""

    def __init__(self, sparsity_ratio: float):
        assert 0.0 <= sparsity_ratio < 1.0
        self.ratio = sparsity_ratio
        self.masks = {}

    def make_masks(self, params, names=None):
        """Build {name: 0/1 mask} from current magnitudes (the hook ran at
        init / after load, ParameterUpdaterHook.cpp init path). Exactly the
        k smallest-|w| entries are pruned (rank-based, so magnitude ties —
        e.g. zero-initialised tensors — never over-prune)."""
        import numpy as _np
        self.masks.clear()
        for name, p in params.items():
            if names is not None and name not in names:
                continue
            mag = _np.abs(_np.asarray(p, _np.float32)).reshape(-1)
            k = int(self.ratio * mag.size)
            mask = _np.ones(mag.size, _np.float32)
            if k > 0:
                mask[_np.argpartition(mag, k - 1)[:k]] = 0.0
            self.masks[name] = jnp.asarray(mask.reshape(_np.shape(p)))
        return self.masks

    def prune(self, params):
        return {k: (p * self.masks[k].astype(p.dtype)
                    if k in self.masks else p) for k, p in params.items()}

    def apply(self, optimizer: Optimizer) -> Optimizer:
        """Wrap optimizer.update so every step re-applies the masks.

        Call make_masks() FIRST: under jit the mask dict is baked in at
        trace time, so an empty dict would silently disable pruning —
        apply() refuses it. Re-wrapping the same optimizer also raises
        (double-masking)."""
        if not self.masks:
            raise ValueError(
                "StaticPruning.apply() before make_masks(): the masks are "
                "trace-time constants under jit — build them first")
        if getattr(optimizer, "_pruning_wrapped", False):
            raise ValueError("optimizer already wrapped by StaticPruning")
        inner = optimizer.update
        hook = self

        def update(step, grads, params, state):
            masks = hook.masks
            grads = {k: (g * masks[k].astype(g.dtype) if k in masks else g)
                     for k, g in grads.items()}
            new_p, new_s = inner(step, grads, params, state)
            return hook.prune(new_p), new_s

        optimizer.update = update
        optimizer._pruning_wrapped = True
        return optimizer
