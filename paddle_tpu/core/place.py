"""Device & mesh abstraction.

Reference: paddle/platform/place.h:24 (CPUPlace/GPUPlace variant) and
device_context.h:38 (per-device contexts holding cublas/cudnn handles).

TPU-native: JAX owns streams/handles; the useful abstraction is *which devices*
and *what mesh shape*. A ``Place`` is a jax.Device; a mesh is
``jax.sharding.Mesh`` over the local (or global) device set. Axis naming
follows the scaling-book convention: ``data`` (DP), ``model`` (TP),
``seq`` (SP/CP), ``expert`` (EP), ``stage`` (PP).
"""

import functools
import os
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical mesh-axis names used across the framework.
AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_STAGE = "stage"

# Declared per-chip peak dense-matmul FLOP/s (bf16 with fp32 accumulation
# — the MXU number every published TPU spec quotes), keyed by substrings
# of ``device.device_kind``. Matched longest-pattern-first so "v5 lite"
# wins over "v5". The MFU accounting in ``observe.costs`` divides by
# this; ``PADDLE_TPU_PEAK_TFLOPS`` overrides (also how a future chip gets
# a number before the table learns it). The "cpu" entry is a NOMINAL
# placeholder (0.1 TFLOP/s) so the MFU plumbing stays exercised in CPU
# tests — absolute CPU MFU values are meaningless and documented as such.
PEAK_FLOPS_TABLE = (
    ("v6 lite", 918e12),      # Trillium / v6e
    ("v6e", 918e12),
    ("v5 lite", 197e12),      # v5e (device_kind: "TPU v5 lite" / "v5e")
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v5", 459e12),
    ("v4 lite", 137e12),      # v4i
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
    ("cpu", 0.1e12),          # nominal — see note above
)


def peak_flops(device=None) -> Optional[float]:
    """Declared peak FLOP/s of ``device`` (default: the default device).

    Resolution order: ``PADDLE_TPU_PEAK_TFLOPS`` (in TFLOP/s) →
    longest-matching ``PEAK_FLOPS_TABLE`` entry against the device kind
    → None (unknown hardware; MFU reporting then stays silent rather
    than inventing a denominator)."""
    env = os.environ.get("PADDLE_TPU_PEAK_TFLOPS")
    if env:
        try:
            return float(env) * 1e12
        except ValueError:
            pass
    device = device or default_device()
    kind = (getattr(device, "device_kind", "") or device.platform).lower()
    best = None
    for pat, flops in PEAK_FLOPS_TABLE:
        if pat in kind and (best is None or len(pat) > len(best[0])):
            best = (pat, flops)
    return best[1] if best else None


def local_devices(platform: Optional[str] = None):
    return jax.devices(platform) if platform else jax.devices()


def default_device():
    return local_devices()[0]


def is_tpu() -> bool:
    return default_device().platform == "tpu"


@functools.lru_cache(maxsize=None)
def _cached_mesh(shape: Tuple[int, ...], axes: Tuple[str, ...], ndev: int) -> Mesh:
    devices = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devices, axes)


def make_mesh(shape: Sequence[int], axes: Sequence[str]) -> Mesh:
    """Build a mesh over local devices; validates the device count."""
    n = int(np.prod(shape))
    avail = len(jax.devices())
    if n > avail:
        raise ValueError(f"mesh {tuple(shape)} needs {n} devices, have {avail}")
    return _cached_mesh(tuple(shape), tuple(axes), avail)


def default_mesh(data_parallel: Optional[int] = None) -> Mesh:
    """1-D data-parallel mesh over all local devices (the
    MultiGradientMachine replacement's default shape,
    reference: gserver/gradientmachines/MultiGradientMachine.h:44)."""
    n = data_parallel or len(jax.devices())
    return make_mesh((n,), (AXIS_DATA,))


def device_count() -> int:
    return len(jax.devices())
