"""Device & mesh abstraction.

Reference: paddle/platform/place.h:24 (CPUPlace/GPUPlace variant) and
device_context.h:38 (per-device contexts holding cublas/cudnn handles).

TPU-native: JAX owns streams/handles; the useful abstraction is *which devices*
and *what mesh shape*. A ``Place`` is a jax.Device; a mesh is
``jax.sharding.Mesh`` over the local (or global) device set. Axis naming
follows the scaling-book convention: ``data`` (DP), ``model`` (TP),
``seq`` (SP/CP), ``expert`` (EP), ``stage`` (PP).
"""

import functools
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical mesh-axis names used across the framework.
AXIS_DATA = "data"
AXIS_MODEL = "model"
AXIS_SEQ = "seq"
AXIS_EXPERT = "expert"
AXIS_STAGE = "stage"


def local_devices(platform: Optional[str] = None):
    return jax.devices(platform) if platform else jax.devices()


def default_device():
    return local_devices()[0]


def is_tpu() -> bool:
    return default_device().platform == "tpu"


@functools.lru_cache(maxsize=None)
def _cached_mesh(shape: Tuple[int, ...], axes: Tuple[str, ...], ndev: int) -> Mesh:
    devices = np.asarray(jax.devices()[:int(np.prod(shape))]).reshape(shape)
    return Mesh(devices, axes)


def make_mesh(shape: Sequence[int], axes: Sequence[str]) -> Mesh:
    """Build a mesh over local devices; validates the device count."""
    n = int(np.prod(shape))
    avail = len(jax.devices())
    if n > avail:
        raise ValueError(f"mesh {tuple(shape)} needs {n} devices, have {avail}")
    return _cached_mesh(tuple(shape), tuple(axes), avail)


def default_mesh(data_parallel: Optional[int] = None) -> Mesh:
    """1-D data-parallel mesh over all local devices (the
    MultiGradientMachine replacement's default shape,
    reference: gserver/gradientmachines/MultiGradientMachine.h:44)."""
    n = data_parallel or len(jax.devices())
    return make_mesh((n,), (AXIS_DATA,))


def device_count() -> int:
    return len(jax.devices())
