"""Parameter specs and the parameter pytree.

Reference: paddle/parameter/Parameter.h:60 — a Parameter owns a set of typed
buffers (PARAMETER_VALUE, PARAMETER_GRADIENT, PARAMETER_MOMENTUM, ...) plus a
ParameterConfig proto (dims, initial_mean/std, sparsity, learning-rate scale,
decay). TPU-native: parameters are entries of a flat dict pytree
``{name: jax.Array}``; optimizer state is a parallel pytree owned by the
optimizer (not the parameter); metadata lives in ``ParamSpec``.
"""

import dataclasses
import math
from typing import Callable, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from paddle_tpu.core import dtypes


@dataclasses.dataclass(frozen=True)
class ParamAttr:
    """Per-parameter attributes (reference: trainer_config_helpers/attrs.py
    ParameterAttribute — name, initial_std/mean, learning_rate, l1/l2 decay,
    sparse flags)."""
    name: Optional[str] = None
    initializer: Optional[str] = None      # normal | uniform | xavier | msra | constant
    initial_mean: float = 0.0
    initial_std: Optional[float] = None    # None => 1/sqrt(fan_in) like reference
    initial_value: Optional[float] = None  # for constant init
    learning_rate: float = 1.0             # per-param lr scale
    l1_rate: Optional[float] = None
    l2_rate: Optional[float] = None
    is_static: bool = False                # frozen parameter
    sparse_update: bool = False            # row-sparse gradient path


@dataclasses.dataclass(frozen=True)
class ParamSpec:
    """Shape/dtype/init spec for one named parameter."""
    name: str
    shape: Tuple[int, ...]
    dtype: object = None
    attr: ParamAttr = dataclasses.field(default_factory=ParamAttr)
    # axis interpretation for default init: fan_in is prod(shape[:-1]) unless set
    fan_in: Optional[int] = None
    # mesh-axis sharding hint, parallel layer fills this (e.g. (None,'model'))
    sharding: Optional[Tuple] = None

    def resolved_dtype(self):
        return self.dtype or dtypes.param_dtype()

    def initialize(self, key: jax.Array) -> jax.Array:
        """Materialise the initial value (reference: Parameter::randomize,
        paddle/parameter/Parameter.cpp — default N(0, 1/sqrt(fan_in)))."""
        a = self.attr
        dtype = self.resolved_dtype()
        shape = self.shape
        if a.initial_value is not None or a.initializer == "constant":
            return jnp.full(shape, a.initial_value or 0.0, dtype)
        fan_in = self.fan_in
        if fan_in is None:
            fan_in = int(math.prod(shape[:-1])) if len(shape) > 1 else int(shape[0])
        init = a.initializer or "normal"
        if init == "normal":
            std = a.initial_std if a.initial_std is not None else 1.0 / math.sqrt(max(1, fan_in))
            return a.initial_mean + std * jax.random.normal(key, shape, dtype)
        if init == "uniform":
            lim = a.initial_std if a.initial_std is not None else 1.0 / math.sqrt(max(1, fan_in))
            return jax.random.uniform(key, shape, dtype, -lim, lim)
        if init == "xavier":
            fan_out = int(shape[-1]) if len(shape) > 1 else int(shape[0])
            lim = math.sqrt(6.0 / (fan_in + fan_out))
            return jax.random.uniform(key, shape, dtype, -lim, lim)
        if init == "msra":
            std = math.sqrt(2.0 / max(1, fan_in))
            return std * jax.random.normal(key, shape, dtype)
        raise ValueError(f"unknown initializer {init!r}")


def init_params(specs: Sequence[ParamSpec], key_source=None) -> dict:
    """Initialise a full parameter pytree from specs, name-keyed subkeys."""
    from paddle_tpu.utils import rng
    ks = key_source or rng.global_key_source()
    return {s.name: s.initialize(ks.named(s.name)) for s in specs}
