"""Core semantics (reference: paddle/platform/ + paddle/framework/).

- place: device & mesh abstraction (replaces Place/DeviceContext,
  paddle/platform/place.h:24, device_context.h:38)
- dtypes: dtype table (replaces paddle/framework/data_type.h)
- param: parameter specs + pytree registry (replaces Parameter buffers +
  Scope/Variable, paddle/parameter/Parameter.h:60, paddle/framework/scope.h:38)
- ragged: variable-length sequence batches (replaces LoDTensor /
  Argument.sequenceStartPositions, paddle/framework/lod_tensor.h:82)
"""

from paddle_tpu.core import place
from paddle_tpu.core import dtypes
from paddle_tpu.core import param
from paddle_tpu.core import ragged

from paddle_tpu.core.place import default_device, default_mesh, local_devices
from paddle_tpu.core.param import ParamSpec
from paddle_tpu.core.ragged import SequenceBatch
