"""Variable-length sequence batches under XLA static shapes.

Reference machinery being replaced:
- ``Argument.sequenceStartPositions`` / ``subSequenceStartPositions``
  (paddle/parameter/Argument.h:84-90) — zero-padding-free nested sequences.
- ``LoDTensor`` level-of-detail tensor (paddle/framework/lod_tensor.h:82).
- sequence→batch reordering for RNNs (operators/math/sequence2batch.h).

TPU-native design: XLA wants static shapes, so sequences are **padded to a
bucketed max length with an explicit mask**, and sequence-level ops use
segment-ids. Bucketing bounds recompilation (one compiled program per bucket);
masking keeps math exact (masked softmax/pool/loss). The sequence2batch GEMM
trick is unnecessary — a padded ``lax.scan`` already runs each timestep as one
dense GEMM over the whole batch on the MXU, and the mask zeroes state updates
of finished rows.

Two sequence levels are supported, mirroring SEQUENCE / SUB_SEQUENCE input
types (python/paddle/trainer/PyDataProvider2.py:25,186-250): an outer batch of
sequences, each optionally composed of sub-sequences.
"""

import dataclasses
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

DEFAULT_BUCKETS = (16, 32, 64, 128, 256, 512, 1024)


def sub_lengths_matrix(nested: List[List]) -> np.ndarray:
    """[batch, max_subseqs] int32 lengths of each sample's sub-sequences
    (level-2 LoD split record) — shared by every level-2 ingestion path."""
    max_subs = max((len(subs) for subs in nested), default=1)
    subl = np.zeros((len(nested), max_subs), np.int32)
    for i, subs in enumerate(nested):
        for j, s in enumerate(subs):
            subl[i, j] = len(s)
    return subl


def bucket_length(n: int, buckets: Sequence[int] = DEFAULT_BUCKETS) -> int:
    """Smallest bucket >= n; beyond the last bucket, round up to a multiple of
    it, so recompilation stays bounded for any length distribution."""
    for b in buckets:
        if n <= b:
            return int(b)
    last = int(buckets[-1])
    return ((int(n) + last - 1) // last) * last


@jax.tree_util.register_pytree_node_class
@dataclasses.dataclass
class SequenceBatch:
    """A batch of variable-length sequences, padded + masked.

    data:    [batch, time, ...] padded values
    lengths: [batch] int32 true lengths
    sub_lengths: optional [batch, max_subseqs] int32 — lengths of the
        sub-sequences making up each sequence (level-2 LoD); sum over valid
        entries equals ``lengths``.
    """
    data: jax.Array
    lengths: jax.Array
    sub_lengths: Optional[jax.Array] = None

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        return (self.data, self.lengths, self.sub_lengths), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    # -- construction ------------------------------------------------------
    @classmethod
    def from_list(cls, seqs: List[np.ndarray], buckets=DEFAULT_BUCKETS,
                  dtype=None, pad_value=0):
        """Build from a python list of per-sequence arrays ([len, ...] each)."""
        seqs = [np.asarray(s) for s in seqs]
        max_len = bucket_length(max((len(s) for s in seqs), default=1), buckets)
        feat = seqs[0].shape[1:] if seqs else ()
        dtype = dtype or (seqs[0].dtype if seqs else np.float32)
        data = np.full((len(seqs), max_len) + feat, pad_value, dtype=dtype)
        lengths = np.zeros((len(seqs),), np.int32)
        for i, s in enumerate(seqs):
            data[i, : len(s)] = s
            lengths[i] = len(s)
        return cls(jnp.asarray(data), jnp.asarray(lengths))

    @classmethod
    def from_nested_list(cls, nested: List[List[np.ndarray]], buckets=DEFAULT_BUCKETS,
                         dtype=None, pad_value=0):
        """Level-2: each element is a list of sub-sequences; they are
        concatenated on the time axis and sub_lengths records the split."""
        # infer feat/dtype from real data so empty entries don't poison them
        proto = next((np.asarray(s) for subs in nested for s in subs), None)
        empty = (np.zeros((0,) + proto.shape[1:], proto.dtype) if proto is not None
                 else np.zeros((0,), np.float32))
        flat = [np.concatenate([np.asarray(s) for s in subs], axis=0) if subs
                else empty for subs in nested]
        out = cls.from_list(flat, buckets, dtype, pad_value)
        return cls(out.data, out.lengths,
                   jnp.asarray(sub_lengths_matrix(nested)))

    # -- views -------------------------------------------------------------
    @property
    def batch_size(self) -> int:
        return self.data.shape[0]

    @property
    def max_len(self) -> int:
        return self.data.shape[1]

    def mask(self, dtype=jnp.float32) -> jax.Array:
        """[batch, time] validity mask."""
        t = jnp.arange(self.max_len, dtype=jnp.int32)
        return (t[None, :] < self.lengths[:, None]).astype(dtype)

    def segment_ids(self) -> jax.Array:
        """Flattened [batch*time] segment ids; padding slots get id=batch
        (one-past-last) so segment_sum with num_segments=batch drops them."""
        valid = self.mask(jnp.bool_)
        ids = jnp.broadcast_to(
            jnp.arange(self.batch_size, dtype=jnp.int32)[:, None],
            (self.batch_size, self.max_len))
        ids = jnp.where(valid, ids, self.batch_size)
        return ids.reshape(-1)

    def flat_data(self) -> jax.Array:
        """[batch*time, ...] flattened values (padding rows included)."""
        return self.data.reshape((-1,) + self.data.shape[2:])

    def with_data(self, data: jax.Array) -> "SequenceBatch":
        return SequenceBatch(data, self.lengths, self.sub_lengths)

    def sub_segment_mask(self) -> jax.Array:
        """[batch, time] int32 sub-sequence index of each timestep (level-2);
        requires sub_lengths. Padding gets the one-past-last sub index."""
        if self.sub_lengths is None:
            raise ValueError("no sub_lengths on this SequenceBatch")
        # cum over sub lengths gives boundaries; timestep t belongs to the
        # first sub whose cumulative end exceeds t.
        ends = jnp.cumsum(self.sub_lengths, axis=1)          # [b, S]
        t = jnp.arange(self.max_len, dtype=jnp.int32)        # [T]
        # sub_idx[b, t] = #{s : ends[b, s] <= t}
        return jnp.sum(t[None, :, None] >= ends[:, None, :], axis=-1).astype(jnp.int32)
